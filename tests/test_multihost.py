"""Multi-host bootstrap tests.

Single-process behavior of ``initialize_distributed`` (the no-op path any
one-chip script hits), the DCN-hybrid mesh fallback, and — where the
installed jax supports cross-process CPU collectives — a REAL two-process
run: each subprocess owns 4 virtual CPU devices, joins a localhost
coordinator, builds the global 8-device dp mesh, and psums across hosts.
≙ the spirit of the reference's two-process NCCL tests
(tests/distributed/DDP), with processes instead of GPUs.
"""

import os
import socket
import subprocess
import sys
import textwrap

import jax
import pytest

from apex_tpu import parallel_state as ps
from apex_tpu.parallel import (
    distributed_is_initialized,
    initialize_distributed,
)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def test_single_process_noop():
    # no cluster env in this harness: the guard must be queryable BEFORE
    # init (it must not touch the backend) and report False...
    assert not distributed_is_initialized()
    idx, count = initialize_distributed()
    assert (idx, count) == (0, 1)
    # ...and the no-op path must leave it False (nothing was joined)
    assert not distributed_is_initialized()


def test_warns_when_cluster_env_present_but_join_fails(monkeypatch):
    """The 'pod member silently degrading to single-process' path must at
    least shout: hints set + failed join -> RuntimeWarning naming them."""
    from apex_tpu.parallel import multihost

    def failing_initialize(*a, **k):
        raise RuntimeError("coordinator unreachable")

    monkeypatch.setenv("JAX_COORDINATOR_ADDRESS", "10.0.0.1:8476")
    monkeypatch.setattr(jax.distributed, "initialize", failing_initialize)
    assert multihost.cluster_env_hints() == ("JAX_COORDINATOR_ADDRESS",)
    with pytest.warns(RuntimeWarning, match="JAX_COORDINATOR_ADDRESS"):
        idx, count = initialize_distributed()
    assert (idx, count) == (0, 1)
    assert not distributed_is_initialized()  # degraded, and knows it


def test_strict_raises_when_cluster_env_present_but_join_fails(monkeypatch):
    from apex_tpu.parallel import multihost

    monkeypatch.setenv("JAX_COORDINATOR_ADDRESS", "10.0.0.1:8476")
    monkeypatch.setattr(
        jax.distributed,
        "initialize",
        lambda *a, **k: (_ for _ in ()).throw(RuntimeError("unreachable")),
    )
    with pytest.raises(RuntimeError, match="cluster environment detected"):
        multihost.initialize_distributed(strict=True)
    assert not distributed_is_initialized()


def test_no_hints_no_warning(monkeypatch, recwarn):
    """Without cluster env hints a failed autodetect is the benign
    single-process path: silent, strict or not."""
    from apex_tpu.parallel import multihost

    for k in multihost._CLUSTER_ENV_HINTS:
        monkeypatch.delenv(k, raising=False)
    monkeypatch.setattr(
        jax.distributed,
        "initialize",
        lambda *a, **k: (_ for _ in ()).throw(RuntimeError("no cluster")),
    )
    assert initialize_distributed() == (0, 1)
    assert multihost.initialize_distributed(strict=True) == (0, 1)
    assert not any(
        issubclass(w.category, RuntimeWarning) for w in recwarn.list
    )


def test_finalize_resets_state_when_shutdown_raises(monkeypatch):
    """A teardown error (coordinator already gone) must not wedge the
    module: warn, reset, stay idempotent."""
    from apex_tpu.parallel import multihost

    monkeypatch.setattr(
        jax.distributed,
        "shutdown",
        lambda: (_ for _ in ()).throw(RuntimeError("socket closed")),
    )
    monkeypatch.setattr(multihost, "_INITIALIZED", True)
    with pytest.warns(RuntimeWarning, match="mid-teardown"):
        multihost.finalize_distributed()
    assert multihost._INITIALIZED is False
    multihost.finalize_distributed()  # second call: clean no-op


def test_dcn_mesh_falls_back_on_single_granule():
    """dcn_data_parallel on a 1-process backend warns and still yields a
    working mesh (the single-granule ICI layout)."""
    with pytest.warns(RuntimeWarning, match="hybrid"):
        mesh = ps.initialize_model_parallel(
            tensor_model_parallel_size=2, dcn_data_parallel=True
        )
    assert mesh.devices.size == len(jax.devices())
    ps.destroy_model_parallel()


_WORKER = textwrap.dedent(
    """
    import os, sys
    sys.path.insert(0, {repo!r})
    os.environ["XLA_FLAGS"] = (
        os.environ.get("XLA_FLAGS", "")
        + " --xla_force_host_platform_device_count=4"
    )
    import jax
    jax.config.update("jax_platforms", "cpu")
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P
    from apex_tpu.parallel import initialize_distributed
    from apex_tpu import parallel_state as ps

    pid = int(sys.argv[1]); port = sys.argv[2]
    idx, count = initialize_distributed(
        coordinator_address="127.0.0.1:" + port,
        num_processes=2, process_id=pid,
    )
    assert count == 2, count
    assert len(jax.devices()) == 8, len(jax.devices())
    mesh = ps.initialize_model_parallel()  # dp = 8 across both processes
    out = jax.jit(
        jax.shard_map(
            lambda x: jax.lax.psum(x, ps.DATA_PARALLEL_AXIS),
            mesh=mesh, in_specs=P("dp"), out_specs=P(),
            check_vma=False,
        )
    )(jnp.arange(8.0))
    total = float(jax.device_get(out)[0])
    assert total == 28.0, total
    print("MULTIHOST_OK", idx, total, flush=True)
    """
)


@pytest.mark.slow
def test_two_process_cpu_psum():
    """Two OS processes x 4 CPU devices -> one 8-device dp world."""
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = str(s.getsockname()[1])
    env = {
        k: v for k, v in os.environ.items() if k not in ("XLA_FLAGS",)
    }
    procs = [
        subprocess.Popen(
            [sys.executable, "-c", _WORKER.format(repo=REPO), str(i), port],
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
            env=env, cwd=REPO,
        )
        for i in range(2)
    ]
    outs = []
    try:
        for p in procs:
            out, _ = p.communicate(timeout=300)
            outs.append((p.returncode, out))
    except subprocess.TimeoutExpired:
        for p in procs:
            p.kill()
        pytest.skip("two-process CPU rendezvous timed out in this sandbox")
    for rc, out in outs:
        if rc != 0 and (
            "UNIMPLEMENTED" in out
            or "not supported" in out
            or "cross-host" in out
        ):
            pytest.skip(
                "installed jax lacks cross-process CPU collectives: "
                + out[-300:]
            )
    for rc, out in outs:
        assert rc == 0, out[-2000:]
        assert "MULTIHOST_OK" in out
