"""Context parallelism (ring + Ulysses) vs full-attention golden.

No reference analog (the reference has no CP) — golden is
:func:`apex_tpu.ops.attention.mha_reference` on the gathered sequence.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from apex_tpu import parallel_state as ps
from apex_tpu.ops.attention import mha_reference
from apex_tpu.transformer.context_parallel import (
    ring_attention,
    ulysses_attention,
)

B, H, S, D = 2, 4, 64, 16  # S is the GLOBAL sequence length


def _qkv(key):
    kq, kk, kv = jax.random.split(key, 3)
    q = jax.random.normal(kq, (B, H, S, D))
    k = jax.random.normal(kk, (B, H, S, D))
    v = jax.random.normal(kv, (B, H, S, D))
    return q, k, v


def _run_cp(fn, q, k, v, cp):
    """Run fn inside shard_map with the seq dim sharded over cp."""
    mesh = ps.initialize_model_parallel(context_parallel_size=cp)
    out = jax.jit(
        jax.shard_map(
            fn,
            mesh=mesh,
            in_specs=(P(None, None, "cp"), P(None, None, "cp"),
                      P(None, None, "cp")),
            out_specs=P(None, None, "cp"),
            check_vma=False,
        )
    )(q, k, v)
    ps.destroy_model_parallel()
    return out


@pytest.mark.parametrize("causal", [False, True])
@pytest.mark.parametrize("cp", [2, 4, 8])
def test_ring_matches_full(eight_devices, causal, cp):
    q, k, v = _qkv(jax.random.PRNGKey(0))
    out = _run_cp(
        lambda q, k, v: ring_attention(q, k, v, causal=causal), q, k, v, cp
    )
    ref = mha_reference(q, k, v, causal=causal)
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(ref), atol=2e-5, rtol=2e-5
    )


@pytest.mark.parametrize("causal", [False, True])
def test_ring_grads_match_full(eight_devices, causal):
    q, k, v = _qkv(jax.random.PRNGKey(1))

    def ring_loss(q, k, v):
        return jnp.sum(ring_attention(q, k, v, causal=causal) ** 2)

    def full_loss(q, k, v):
        return jnp.sum(mha_reference(q, k, v, causal=causal) ** 2)

    mesh = ps.initialize_model_parallel(context_parallel_size=4)

    def f(q, k, v):
        # each rank sums only its own q rows, so psum over cp rebuilds the
        # full loss; /4 then matches the unsharded scale after the psum
        # transpose duplicates the cotangent onto every rank
        gq, gk, gv = jax.grad(
            lambda args: jax.lax.psum(ring_loss(*args), "cp") / 4
        )((q, k, v))
        return gq, gk, gv

    gq, gk, gv = jax.jit(
        jax.shard_map(
            f,
            mesh=mesh,
            in_specs=(P(None, None, "cp"),) * 3,
            out_specs=(P(None, None, "cp"),) * 3,
            check_vma=False,
        )
    )(q, k, v)
    ps.destroy_model_parallel()

    rq, rk, rv = jax.grad(lambda args: full_loss(*args))((q, k, v))
    np.testing.assert_allclose(np.asarray(gq), np.asarray(rq), atol=5e-4, rtol=1e-3)
    np.testing.assert_allclose(np.asarray(gk), np.asarray(rk), atol=5e-4, rtol=1e-3)
    np.testing.assert_allclose(np.asarray(gv), np.asarray(rv), atol=5e-4, rtol=1e-3)


def _ring_dropout_golden_keep(rng, cp, p, causal):
    """The full-(S, S) keep mask ring dropout induces on the jnp dispatch
    path: block (r, src) draws bernoulli from fold_in(rng, r*cp + src) —
    exactly ring_attention's per-hop folding.  Future causal blocks carry
    no probability mass, so their (undrawn) mask entries are irrelevant."""
    s_local = S // cp
    keep = np.ones((B, H, S, S), bool)
    for r in range(cp):
        for src in range(cp):
            if causal and src > r:
                continue
            m = jax.random.bernoulli(
                jax.random.fold_in(rng, r * cp + src), 1.0 - p,
                (B, H, s_local, s_local),
            )
            keep[
                :, :, r * s_local:(r + 1) * s_local,
                src * s_local:(src + 1) * s_local,
            ] = np.asarray(m)
    return jnp.asarray(keep)


@pytest.mark.parametrize("causal", [False, True])
def test_ring_dropout_matches_blockmask_golden(eight_devices, causal):
    """Ring attention with fused dropout == full attention under the
    block-assembled keep mask (values AND grads): the merge weights each
    block by its TRUE softmax mass while the block's PV contribution is
    masked + rescaled, so the composition is exact, not just in
    expectation."""
    from apex_tpu.ops.attention import _scores

    cp, p = 4, 0.25
    q, k, v = _qkv(jax.random.PRNGKey(5))
    rng = jax.random.PRNGKey(77)
    scale = 1.0 / (D ** 0.5)
    keep = _ring_dropout_golden_keep(rng, cp, p, causal)

    def ring_fn(q, k, v):
        return ring_attention(
            q, k, v, causal=causal, dropout_p=p, dropout_rng=rng
        )

    out = _run_cp(ring_fn, q, k, v, cp)

    def golden(q, k, v):
        s = _scores(q, k, None, causal, scale)
        probs = jax.nn.softmax(s, axis=-1)
        pd = jnp.where(keep, probs / (1.0 - p), 0.0)
        return jnp.einsum("bhqk,bhkd->bhqd", pd.astype(q.dtype), v)

    ref = golden(q, k, v)
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(ref), atol=2e-5, rtol=2e-5
    )

    # determinism: identical rng → identical output; fresh rng → different
    out2 = _run_cp(ring_fn, q, k, v, cp)
    np.testing.assert_array_equal(np.asarray(out), np.asarray(out2))
    out3 = _run_cp(
        lambda q, k, v: ring_attention(
            q, k, v, causal=causal, dropout_p=p,
            dropout_rng=jax.random.PRNGKey(78),
        ),
        q, k, v, cp,
    )
    assert not np.array_equal(np.asarray(out), np.asarray(out3))

    # grads through the dropped ring == grads of the masked golden
    mesh = ps.initialize_model_parallel(context_parallel_size=cp)

    def f(q, k, v):
        gq, gk, gv = jax.grad(
            lambda args: jax.lax.psum(
                jnp.sum(ring_fn(*args) ** 2), "cp"
            ) / cp
        )((q, k, v))
        return gq, gk, gv

    gq, gk, gv = jax.jit(
        jax.shard_map(
            f, mesh=mesh, in_specs=(P(None, None, "cp"),) * 3,
            out_specs=(P(None, None, "cp"),) * 3, check_vma=False,
        )
    )(q, k, v)
    ps.destroy_model_parallel()
    rq, rk, rv = jax.grad(
        lambda args: jnp.sum(golden(*args) ** 2)
    )((q, k, v))
    for g, r in ((gq, rq), (gk, rk), (gv, rv)):
        np.testing.assert_allclose(
            np.asarray(g), np.asarray(r), atol=5e-4, rtol=1e-3
        )


def _run_cp_zigzag(fn, q, k, v, cp):
    """Run fn under shard_map with zigzag-stacked locals; returns the
    global-order output."""
    from apex_tpu.transformer.context_parallel import (
        zigzag_merge,
        zigzag_split,
    )

    mesh = ps.initialize_model_parallel(context_parallel_size=cp)
    qs, ks, vs = (zigzag_split(x, cp) for x in (q, k, v))

    def wrapped(q, k, v):
        return fn(q[0], k[0], v[0])[None]

    out = jax.jit(
        jax.shard_map(
            wrapped, mesh=mesh, in_specs=(P("cp"),) * 3,
            out_specs=P("cp"), check_vma=False,
        )
    )(qs, ks, vs)
    ps.destroy_model_parallel()
    return zigzag_merge(out, cp)


@pytest.mark.parametrize("cp", [2, 4, 8])
def test_ring_zigzag_matches_full(eight_devices, cp):
    """Zigzag (load-balanced) causal ring == full causal attention."""
    q, k, v = _qkv(jax.random.PRNGKey(8))
    out = _run_cp_zigzag(
        lambda q, k, v: ring_attention(
            q, k, v, causal=True, layout="zigzag"
        ),
        q, k, v, cp,
    )
    ref = mha_reference(q, k, v, causal=True)
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(ref), atol=2e-5, rtol=2e-5
    )


def test_ring_zigzag_grads_match_full(eight_devices):
    """cp=2 keeps this in the quick tier while covering every hop branch
    (self/past/skip appear for both halves across the two ranks)."""
    from apex_tpu.transformer.context_parallel import (
        zigzag_merge,
        zigzag_split,
    )

    cp = 2
    q, k, v = _qkv(jax.random.PRNGKey(9))
    mesh = ps.initialize_model_parallel(context_parallel_size=cp)
    qs, ks, vs = (zigzag_split(x, cp) for x in (q, k, v))

    def f(q, k, v):
        gq, gk, gv = jax.grad(
            lambda args: jax.lax.psum(
                jnp.sum(
                    ring_attention(
                        args[0][0], args[1][0], args[2][0],
                        causal=True, layout="zigzag",
                    ) ** 2
                ),
                "cp",
            ) / cp
        )((q, k, v))
        return gq, gk, gv

    gq, gk, gv = jax.jit(
        jax.shard_map(
            f, mesh=mesh, in_specs=(P("cp"),) * 3,
            out_specs=(P("cp"),) * 3, check_vma=False,
        )
    )(qs, ks, vs)
    ps.destroy_model_parallel()
    rq, rk, rv = jax.grad(
        lambda args: jnp.sum(mha_reference(*args, causal=True) ** 2)
    )((q, k, v))
    for g, r in ((gq, rq), (gk, rk), (gv, rv)):
        np.testing.assert_allclose(
            zigzag_merge(g, cp), np.asarray(r), atol=5e-4, rtol=1e-3
        )


def test_ring_zigzag_dropout_matches_blockmask_golden(eight_devices):
    """Zigzag ring dropout == full causal attention under the
    pair-assembled keep mask (fold index (r·cp+src)·4 + pair)."""
    from apex_tpu.ops.attention import _scores

    cp, p = 4, 0.2
    s_chunk = S // (2 * cp)
    q, k, v = _qkv(jax.random.PRNGKey(10))
    rng = jax.random.PRNGKey(88)
    scale = 1.0 / (D ** 0.5)

    def blk(idx):  # global row/col range of chunk idx
        return slice(idx * s_chunk, (idx + 1) * s_chunk)

    keep = np.ones((B, H, S, S), bool)
    for r in range(cp):
        hi_r = 2 * cp - 1 - r
        for src in range(cp):
            hi_s = 2 * cp - 1 - src
            base = (r * cp + src) * 4
            draws = []
            if src <= r:  # pair 0: lo vs lo'
                draws.append((r, src, base + 0))
            draws.append((hi_r, src, base + 1))  # pair 1: hi vs lo'
            if src >= r:  # pair 2: hi vs hi'
                draws.append((hi_r, hi_s, base + 2))
            for row_c, col_c, fold in draws:
                m = jax.random.bernoulli(
                    jax.random.fold_in(rng, fold), 1.0 - p,
                    (B, H, s_chunk, s_chunk),
                )
                keep[:, :, blk(row_c), blk(col_c)] = np.asarray(m)
    keep = jnp.asarray(keep)

    out = _run_cp_zigzag(
        lambda q, k, v: ring_attention(
            q, k, v, causal=True, layout="zigzag",
            dropout_p=p, dropout_rng=rng,
        ),
        q, k, v, cp,
    )

    def golden(q, k, v):
        s_ = _scores(q, k, None, True, scale)
        probs = jax.nn.softmax(s_, axis=-1)
        pd = jnp.where(keep, probs / (1.0 - p), 0.0)
        return jnp.einsum("bhqk,bhkd->bhqd", pd.astype(q.dtype), v)

    ref = golden(q, k, v)
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(ref), atol=2e-5, rtol=2e-5
    )

    # grads: the checkpointed hop must regenerate the SAME per-pair
    # masks in backward — a fold mismatch there passes forward-only
    from apex_tpu.transformer.context_parallel import (
        zigzag_merge,
        zigzag_split,
    )

    mesh = ps.initialize_model_parallel(context_parallel_size=cp)
    qs, ks, vs = (zigzag_split(x, cp) for x in (q, k, v))

    def f(q, k, v):
        gq, gk, gv = jax.grad(
            lambda args: jax.lax.psum(
                jnp.sum(
                    ring_attention(
                        args[0][0], args[1][0], args[2][0],
                        causal=True, layout="zigzag",
                        dropout_p=p, dropout_rng=rng,
                    ) ** 2
                ),
                "cp",
            ) / cp
        )((q, k, v))
        return gq, gk, gv

    gq, gk, gv = jax.jit(
        jax.shard_map(
            f, mesh=mesh, in_specs=(P("cp"),) * 3,
            out_specs=(P("cp"),) * 3, check_vma=False,
        )
    )(qs, ks, vs)
    ps.destroy_model_parallel()
    rq, rk, rv = jax.grad(
        lambda args: jnp.sum(golden(*args) ** 2)
    )((q, k, v))
    for g, r in ((gq, rq), (gk, rk), (gv, rv)):
        np.testing.assert_allclose(
            zigzag_merge(g, cp), np.asarray(r), atol=5e-4, rtol=1e-3
        )


def test_ring_zigzag_layout_probes(eight_devices):
    q, k, v = _qkv(jax.random.PRNGKey(11))
    with pytest.raises(ValueError, match="zigzag"):
        _run_cp(
            lambda q, k, v: ring_attention(q, k, v, layout="zigzag"),
            q, k, v, 2,
        )
    # the raise aborts _run_cp before its own cleanup runs
    ps.destroy_model_parallel()
    with pytest.raises(ValueError, match="layout"):
        _run_cp(
            lambda q, k, v: ring_attention(
                q, k, v, causal=True, layout="striped"
            ),
            q, k, v, 2,
        )


def test_zigzag_split_merge_roundtrip():
    from apex_tpu.transformer.context_parallel import (
        zigzag_merge,
        zigzag_split,
    )

    x = jnp.arange(2 * 3 * 16 * 4).reshape(2, 3, 16, 4).astype(jnp.float32)
    for cp in (2, 4):
        np.testing.assert_array_equal(
            np.asarray(zigzag_merge(zigzag_split(x, cp), cp)),
            np.asarray(x),
        )
        # rank r's local really is [chunk r; chunk 2cp-1-r]
        sc = 16 // (2 * cp)
        lo = np.asarray(zigzag_split(x, cp))[0, :, :, :sc]
        np.testing.assert_array_equal(lo, np.asarray(x[:, :, :sc]))


def _padding_bias(key, p_keep=0.75):
    """Random (B, 1, 1, S) key-padding mask; global key 0 always kept so
    no row is fully masked."""
    from apex_tpu.ops.pallas.flash_attention import MASK_VALUE

    keep = jax.random.bernoulli(
        key, p_keep, (B, 1, 1, S)
    ).at[..., 0].set(True)
    return jnp.where(keep, 0.0, MASK_VALUE)


@pytest.mark.parametrize("causal", [False, True])
def test_ring_key_padding_bias_matches_full(eight_devices, causal):
    """A per-rank (B, 1, 1, S_local) key-padding bias rotates around the
    ring with kv: result == full attention under the GLOBAL mask
    (values and grads) — variable-length long-document batches."""
    cp = 4
    q, k, v = _qkv(jax.random.PRNGKey(13))
    bias = _padding_bias(jax.random.PRNGKey(14))

    mesh = ps.initialize_model_parallel(context_parallel_size=cp)

    def f(q, k, v, bias):
        rank = jax.lax.axis_index(ps.CONTEXT_PARALLEL_AXIS)
        s_local = S // cp
        bias_local = jax.lax.dynamic_slice_in_dim(
            bias, rank * s_local, s_local, 3
        )

        def ring_loss(args):
            q, k, v = args
            o = ring_attention(q, k, v, bias_local, causal=causal)
            return jax.lax.psum(jnp.sum(o.astype(jnp.float32) ** 2), "cp") / cp, o

        (_, o), (gq, gk, gv) = jax.value_and_grad(
            ring_loss, has_aux=True
        )((q, k, v))
        return o, gq, gk, gv

    o, gq, gk, gv = jax.jit(
        jax.shard_map(
            f, mesh=mesh,
            in_specs=(P(None, None, "cp"),) * 3 + (P(),),
            out_specs=(P(None, None, "cp"),) * 4, check_vma=False,
        )
    )(q, k, v, bias)
    ps.destroy_model_parallel()

    def golden(args):
        q, k, v = args
        o = mha_reference(q, k, v, bias, causal=causal)
        return jnp.sum(o.astype(jnp.float32) ** 2), o

    (_, ow), (rq, rk, rv) = jax.value_and_grad(golden, has_aux=True)(
        (q, k, v)
    )
    np.testing.assert_allclose(
        np.asarray(o), np.asarray(ow), atol=2e-5, rtol=2e-5
    )
    for g, r in ((gq, rq), (gk, rk), (gv, rv)):
        np.testing.assert_allclose(
            np.asarray(g), np.asarray(r), atol=5e-4, rtol=1e-3
        )


def test_ring_zigzag_key_padding_bias_matches_full(eight_devices):
    """Key-padding bias under the zigzag layout: the per-rank mask's
    halves ride the kv halves around the ring == full causal attention
    under the global mask — values AND grads (the bias halves ride the
    checkpointed hop and the ppermute scan carry in backward).  Also
    pins the broadcast (..., 1) mask branch."""
    from apex_tpu.transformer.context_parallel import (
        zigzag_merge,
        zigzag_shard,
        zigzag_split,
    )

    cp = 4
    q, k, v = _qkv(jax.random.PRNGKey(16))
    bias = _padding_bias(jax.random.PRNGKey(17))

    mesh = ps.initialize_model_parallel(context_parallel_size=cp)
    qs, ks, vs = (zigzag_split(x, cp) for x in (q, k, v))

    def f(q, k, v, bias):
        rank = jax.lax.axis_index(ps.CONTEXT_PARALLEL_AXIS)
        bias_local = zigzag_shard(bias, rank, cp, axis=3)

        def ring_loss(args):
            o = ring_attention(
                args[0], args[1], args[2], bias_local,
                causal=True, layout="zigzag",
            )
            return jax.lax.psum(
                jnp.sum(o.astype(jnp.float32) ** 2), "cp"
            ) / cp, o

        (_, o), (gq, gk, gv) = jax.value_and_grad(
            ring_loss, has_aux=True
        )((q[0], k[0], v[0]))
        # broadcast (..., 1) mask branch: a zero bias must be a no-op
        o_b1 = ring_attention(
            q[0], k[0], v[0], jnp.zeros((B, 1, 1, 1)),
            causal=True, layout="zigzag",
        )
        o_nb = ring_attention(
            q[0], k[0], v[0], causal=True, layout="zigzag"
        )
        return o[None], gq[None], gk[None], gv[None], o_b1[None], o_nb[None]

    o, gq, gk, gv, o_b1, o_nb = jax.jit(
        jax.shard_map(
            f, mesh=mesh, in_specs=(P("cp"),) * 3 + (P(),),
            out_specs=(P("cp"),) * 6, check_vma=False,
        )
    )(qs, ks, vs, bias)
    ps.destroy_model_parallel()

    def golden(args):
        o = mha_reference(*args, bias, causal=True)
        return jnp.sum(o.astype(jnp.float32) ** 2), o

    (_, ow), (rq, rk, rv) = jax.value_and_grad(golden, has_aux=True)(
        (q, k, v)
    )
    np.testing.assert_allclose(
        np.asarray(zigzag_merge(o, cp)), np.asarray(ow),
        atol=2e-5, rtol=2e-5,
    )
    for g, r in ((gq, rq), (gk, rk), (gv, rv)):
        np.testing.assert_allclose(
            zigzag_merge(g, cp), np.asarray(r), atol=5e-4, rtol=1e-3
        )
    np.testing.assert_allclose(
        np.asarray(o_b1), np.asarray(o_nb), atol=1e-6, rtol=1e-6
    )


def test_ring_bias_rejects_query_dependent_shape(eight_devices):
    q, k, v = _qkv(jax.random.PRNGKey(15))
    bad = jnp.zeros((B, 1, S // 2, S // 2))
    with pytest.raises(ValueError, match="key-padding"):
        _run_cp(
            lambda q, k, v: ring_attention(q, k, v, bad[:, :, : S // 2]),
            q, k, v, 2,
        )


def test_ring_dropout_requires_rng(eight_devices):
    q, k, v = _qkv(jax.random.PRNGKey(6))
    with pytest.raises(ValueError, match="dropout_rng"):
        _run_cp(
            lambda q, k, v: ring_attention(q, k, v, dropout_p=0.3),
            q, k, v, 2,
        )


@pytest.mark.parametrize("causal", [False, True])
@pytest.mark.parametrize("cp", [2, 4])
def test_ulysses_matches_full(eight_devices, causal, cp):
    q, k, v = _qkv(jax.random.PRNGKey(2))
    out = _run_cp(
        lambda q, k, v: ulysses_attention(q, k, v, causal=causal), q, k, v, cp
    )
    ref = mha_reference(q, k, v, causal=causal)
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(ref), atol=2e-5, rtol=2e-5
    )


def test_ulysses_head_divisibility(eight_devices):
    mesh = ps.initialize_model_parallel(context_parallel_size=8)
    q = jnp.ones((1, 4, 8, 16))  # 4 heads, cp=8 -> error

    def f(q):
        return ulysses_attention(q, q, q)

    with pytest.raises(ValueError, match="divisible"):
        jax.jit(
            jax.shard_map(
                f, mesh=mesh, in_specs=(P(None, None, "cp"),),
                out_specs=P(None, None, "cp"), check_vma=False,
            )
        )(q)


def test_cp_axis_in_registry(eight_devices):
    mesh = ps.initialize_model_parallel(
        tensor_model_parallel_size=2, context_parallel_size=2,
    )
    assert ps.get_context_parallel_world_size() == 2
    assert mesh.shape == {"dp": 2, "pp": 1, "cp": 2, "tp": 2}


def test_ulysses_key_padding_bias(eight_devices):
    """Local (B,1,1,S_local) bias is gathered to the global key axis."""
    q, k, v = _qkv(jax.random.PRNGKey(3))
    bias_global = np.zeros((B, 1, 1, S), np.float32)
    bias_global[:, :, :, S // 2:] = -1e9
    bias_global = jnp.asarray(bias_global)
    mesh = ps.initialize_model_parallel(context_parallel_size=4)

    def f(q, k, v, bias_local):
        return ulysses_attention(q, k, v, bias_local)

    out = jax.jit(
        jax.shard_map(
            f,
            mesh=mesh,
            in_specs=(P(None, None, "cp"),) * 3 + (P(None, None, None, "cp"),),
            out_specs=P(None, None, "cp"),
            check_vma=False,
        )
    )(q, k, v, bias_global)
    ps.destroy_model_parallel()
    ref = mha_reference(q, k, v, bias_global)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5, rtol=2e-5)


def test_ulysses_rejects_full_bias(eight_devices):
    mesh = ps.initialize_model_parallel(context_parallel_size=4)
    q = jnp.ones((1, 4, 16, 16))
    bias = jnp.zeros((1, 4, 16, 16))

    def f(q, bias):
        return ulysses_attention(q, q, q, bias)

    with pytest.raises(ValueError, match="key-padding"):
        jax.jit(
            jax.shard_map(
                f, mesh=mesh,
                in_specs=(P(None, None, "cp"), P(None, None, "cp")),
                out_specs=P(None, None, "cp"), check_vma=False,
            )
        )(q, bias)


@pytest.mark.parametrize("causal", [False, True])
def test_ring_kernel_path_matches_full(eight_devices, causal):
    """Ring attention with the Pallas flash hop forced (interpret on CPU):
    the kernel-backed (o, lse) block + dlse backward inside shard_map."""
    from apex_tpu.ops import _dispatch

    q, k, v = _qkv(jax.random.PRNGKey(7))

    def loss_cp(q, k, v):
        def f(q, k, v):
            return ring_attention(q, k, v, causal=causal)

        out = _run_cp(f, q, k, v, cp=2)
        return jnp.sum(out.astype(jnp.float32) ** 2)

    _dispatch.set_use_pallas(True)
    try:
        got_val, got_grads = jax.value_and_grad(loss_cp, argnums=(0, 1, 2))(
            q, k, v
        )
    finally:
        _dispatch.set_use_pallas(None)

    def loss_ref(q, k, v):
        out = mha_reference(q, k, v, causal=causal)
        return jnp.sum(out.astype(jnp.float32) ** 2)

    want_val, want_grads = jax.value_and_grad(loss_ref, argnums=(0, 1, 2))(
        q, k, v
    )
    np.testing.assert_allclose(float(got_val), float(want_val), rtol=2e-5)
    for g, w in zip(got_grads, want_grads):
        np.testing.assert_allclose(
            np.asarray(g), np.asarray(w), atol=5e-5, rtol=5e-5
        )
