"""Reference public-API surface parity (import-level).

A user migrating from the reference must find the same names in the
mirrored namespaces (SURVEY §2 component tables).  Import-level only —
behavior is covered by the per-module suites; this guards against broken
re-exports (a real one shipped in r3: contrib.clip_grad importing a name
its backing module didn't export) and accidental renames.
"""

import importlib

import pytest

SURFACE = [
    ("apex_tpu.amp", ["initialize", "scale_loss", "master_params",
                      "state_dict", "load_state_dict", "AmpHandle",
                      "DynamicLossScaler", "opt_levels"]),
    ("apex_tpu.parallel", ["DistributedDataParallel", "SyncBatchNorm",
                           "convert_syncbn_model", "LARC", "Reducer"]),
    ("apex_tpu.optimizers", ["FusedAdam", "FusedLAMB", "FusedSGD",
                             "FusedNovoGrad", "FusedAdagrad",
                             "FusedMixedPrecisionLamb", "clip_grad_norm"]),
    ("apex_tpu.normalization", ["FusedLayerNorm", "FusedRMSNorm",
                                "MixedFusedLayerNorm", "MixedFusedRMSNorm"]),
    ("apex_tpu.fp16_utils", ["FP16_Optimizer", "network_to_half",
                             "BN_convert_float", "prep_param_lists",
                             "master_params_to_model_params",
                             "model_grads_to_master_grads", "tofp16"]),
    ("apex_tpu.multi_tensor_apply", ["MultiTensorApply",
                                     "multi_tensor_applier"]),
    ("apex_tpu.transformer.tensor_parallel", [
        "ColumnParallelLinear", "RowParallelLinear",
        "VocabParallelEmbedding", "vocab_parallel_cross_entropy",
        "broadcast_data", "checkpoint", "get_cuda_rng_tracker",
        "model_parallel_cuda_manual_seed"]),
    ("apex_tpu.transformer.functional", [
        "FusedScaleMaskSoftmax", "fused_apply_rotary_pos_emb",
        "fused_apply_rotary_pos_emb_cached"]),
    ("apex_tpu.contrib.multihead_attn", ["SelfMultiheadAttn",
                                         "EncdecMultiheadAttn"]),
    ("apex_tpu.contrib.xentropy", ["SoftmaxCrossEntropyLoss"]),
    ("apex_tpu.contrib.sparsity", ["ASP"]),
    ("apex_tpu.contrib.clip_grad", ["clip_grad_norm_"]),
    ("apex_tpu.contrib.optimizers", ["DistributedFusedAdam",
                                     "DistributedFusedLamb"]),
    ("apex_tpu.contrib.focal_loss", []),
    ("apex_tpu.contrib.transducer", ["TransducerJoint", "TransducerLoss"]),
    ("apex_tpu.contrib.group_norm", ["GroupNorm"]),
    ("apex_tpu.contrib.groupbn", ["BatchNorm2d_NHWC"]),
    ("apex_tpu.contrib.index_mul_2d", []),
    ("apex_tpu.contrib.conv_bias_relu", []),
    ("apex_tpu.contrib.fmha", []),
    ("apex_tpu.contrib.peer_memory", ["PeerMemoryPool",
                                      "PeerHaloExchanger1d"]),
    ("apex_tpu.contrib.bottleneck", ["Bottleneck", "SpatialBottleneck"]),
    ("apex_tpu.parallel_state", [
        "initialize_model_parallel", "destroy_model_parallel",
        "get_tensor_model_parallel_rank",
        "get_tensor_model_parallel_world_size",
        "get_pipeline_model_parallel_rank",
        "get_pipeline_model_parallel_world_size",
        "get_data_parallel_rank", "get_data_parallel_world_size",
        "is_pipeline_first_stage", "is_pipeline_last_stage",
        "set_virtual_pipeline_model_parallel_rank",
        "get_virtual_pipeline_model_parallel_world_size"]),
    ("apex_tpu.transformer.pipeline_parallel", [
        "get_forward_backward_func", "forward_backward_no_pipelining",
        "forward_backward_pipelining_without_interleaving",
        "forward_backward_pipelining_with_interleaving"]),
    ("apex_tpu.transformer.pipeline_parallel.p2p_communication", [
        "recv_forward", "recv_backward", "send_forward", "send_backward",
        "send_forward_recv_backward", "send_backward_recv_forward",
        "send_forward_recv_forward"]),
    ("apex_tpu.transformer.pipeline_parallel.utils", [
        "setup_microbatch_calculator", "get_num_microbatches",
        "listify_model", "get_kth_microbatch"]),
    ("apex_tpu.transformer.tensor_parallel.mappings", [
        "copy_to_tensor_model_parallel_region",
        "reduce_from_tensor_model_parallel_region",
        "scatter_to_tensor_model_parallel_region",
        "gather_from_tensor_model_parallel_region",
        "scatter_to_sequence_parallel_region",
        "gather_from_sequence_parallel_region",
        "reduce_scatter_to_sequence_parallel_region",
        "allreduce_sequence_parallel_gradients"]),
    ("apex_tpu.transformer.tensor_parallel.utils", [
        "VocabUtility", "divide", "split_tensor_along_last_dim"]),
    ("apex_tpu.transformer.amp", ["GradScaler"]),
    ("apex_tpu.transformer.enums", ["ModelType", "AttnType",
                                    "AttnMaskType"]),
    ("apex_tpu.transformer.microbatches", [
        "ConstantNumMicroBatches", "RampupBatchsizeNumMicroBatches"]),
    ("apex_tpu.mlp", ["MLP"]),
    ("apex_tpu.fused_dense", ["FusedDense", "FusedDenseGeluDense",
                              "fused_dense_function"]),
]


@pytest.mark.parametrize("mod,names", SURFACE, ids=[m for m, _ in SURFACE])
def test_reference_surface(mod, names):
    m = importlib.import_module(mod)
    missing = [n for n in names if not hasattr(m, n)]
    assert not missing, f"{mod} missing reference names: {missing}"
