"""Reference public-API surface parity (import-level).

A user migrating from the reference must find the same names in the
mirrored namespaces (SURVEY §2 component tables).  Import-level only —
behavior is covered by the per-module suites; this guards against broken
re-exports (a real one shipped in r3: contrib.clip_grad importing a name
its backing module didn't export) and accidental renames.
"""

import importlib

import pytest

SURFACE = [
    ("apex_tpu.amp", ["initialize", "scale_loss", "master_params",
                      "state_dict", "load_state_dict", "AmpHandle",
                      "DynamicLossScaler", "opt_levels"]),
    ("apex_tpu.parallel", ["DistributedDataParallel", "SyncBatchNorm",
                           "convert_syncbn_model", "LARC", "Reducer"]),
    ("apex_tpu.optimizers", ["FusedAdam", "FusedLAMB", "FusedSGD",
                             "FusedNovoGrad", "FusedAdagrad",
                             "FusedMixedPrecisionLamb", "clip_grad_norm"]),
    ("apex_tpu.normalization", ["FusedLayerNorm", "FusedRMSNorm",
                                "MixedFusedLayerNorm", "MixedFusedRMSNorm"]),
    ("apex_tpu.fp16_utils", ["FP16_Optimizer", "network_to_half",
                             "BN_convert_float", "prep_param_lists",
                             "master_params_to_model_params",
                             "model_grads_to_master_grads", "tofp16"]),
    ("apex_tpu.multi_tensor_apply", ["MultiTensorApply",
                                     "multi_tensor_applier"]),
    ("apex_tpu.transformer.tensor_parallel", [
        "ColumnParallelLinear", "RowParallelLinear",
        "VocabParallelEmbedding", "vocab_parallel_cross_entropy",
        "broadcast_data", "checkpoint", "get_cuda_rng_tracker",
        "model_parallel_cuda_manual_seed"]),
    ("apex_tpu.transformer.functional", [
        "FusedScaleMaskSoftmax", "fused_apply_rotary_pos_emb",
        "fused_apply_rotary_pos_emb_cached"]),
    ("apex_tpu.contrib.multihead_attn", ["SelfMultiheadAttn",
                                         "EncdecMultiheadAttn"]),
    ("apex_tpu.contrib.xentropy", ["SoftmaxCrossEntropyLoss"]),
    ("apex_tpu.contrib.sparsity", ["ASP"]),
    ("apex_tpu.contrib.clip_grad", ["clip_grad_norm_"]),
    ("apex_tpu.contrib.optimizers", ["DistributedFusedAdam",
                                     "DistributedFusedLamb"]),
    ("apex_tpu.contrib.focal_loss", []),
    ("apex_tpu.contrib.transducer", []),
    ("apex_tpu.contrib.group_norm", []),
    ("apex_tpu.contrib.index_mul_2d", []),
    ("apex_tpu.contrib.conv_bias_relu", []),
    ("apex_tpu.contrib.fmha", []),
    ("apex_tpu.contrib.peer_memory", []),
    ("apex_tpu.contrib.bottleneck", []),
]


@pytest.mark.parametrize("mod,names", SURFACE, ids=[m for m, _ in SURFACE])
def test_reference_surface(mod, names):
    m = importlib.import_module(mod)
    missing = [n for n in names if not hasattr(m, n)]
    assert not missing, f"{mod} missing reference names: {missing}"
