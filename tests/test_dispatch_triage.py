"""Trace-time dispatch triage (ADVICE r4 on flash-dropout streams).

The pallas and jnp paths draw DIFFERENT dropout streams by documented
contract, so when a shape or backend change silently flips the
dispatch, reproducibility debugging needs `_dispatch.last_paths()` to
say which implementation the most recent trace actually took.
"""

import jax
import jax.numpy as jnp

from apex_tpu.ops import _dispatch
from apex_tpu.ops.attention import flash_attention
from apex_tpu.ops.layer_norm import fused_layer_norm_affine


def _qkv(s=128, d=64):
    ks = jax.random.split(jax.random.PRNGKey(0), 3)
    return tuple(jax.random.normal(k, (1, 2, s, d), jnp.float32) for k in ks)


def test_records_attention_and_norm_paths():
    q, k, v = _qkv()
    x = jax.random.normal(jax.random.PRNGKey(1), (8, 128), jnp.float32)
    w = jnp.ones((128,), jnp.float32)
    b = jnp.zeros((128,), jnp.float32)

    _dispatch.clear_paths()
    _dispatch.set_use_pallas(False)
    try:
        flash_attention(q, k, v, None)
        fused_layer_norm_affine(x, w, b, (128,))
        assert _dispatch.last_paths()["flash_attention"] == "jnp"
        assert _dispatch.last_paths()["layer_norm"] == "jnp"

        # Forced pallas bypasses the short-sequence auto heuristic, so
        # the same tiny shapes flip paths — exactly the silent flip the
        # triage log exists to expose.
        _dispatch.set_use_pallas(True)
        flash_attention(q, k, v, None)
        fused_layer_norm_affine(x, w, b, (128,))
        assert _dispatch.last_paths()["flash_attention"] == "pallas"
        assert _dispatch.last_paths()["layer_norm"] == "pallas"
    finally:
        _dispatch.set_use_pallas(None)

    # auto mode at a short sequence routes attention back to jnp
    flash_attention(q, k, v, None)
    assert _dispatch.last_paths()["flash_attention"] == "jnp"

    _dispatch.clear_paths()
    assert _dispatch.last_paths() == {}
