"""Input pipeline: memmap dataset, sharded loader, native gather,
device prefetcher, MLM batch stream (≙ the reference's data_prefetcher +
input-side host loops, SURVEY §2.7 example row)."""

import numpy as np
import pytest

import jax

from apex_tpu import _native
from apex_tpu.data import (
    DataLoader,
    DevicePrefetcher,
    TokenFileDataset,
    bert_mlm_batches,
    write_token_file,
)


@pytest.fixture
def token_file(tmp_path):
    toks = np.arange(1000, 1000 + 4096, dtype=np.uint16)
    p = tmp_path / "corpus.bin"
    write_token_file(p, toks)
    return p, toks


class TestDataset:
    def test_windows_and_len(self, token_file):
        p, toks = token_file
        ds = TokenFileDataset(p, seq_len=128)
        assert len(ds) == 4096 // 128
        np.testing.assert_array_equal(ds[0], toks[:128])
        np.testing.assert_array_equal(ds[3], toks[3 * 128 : 4 * 128])
        with pytest.raises(IndexError):
            ds[len(ds)]

    def test_overlapping_stride(self, token_file):
        p, toks = token_file
        ds = TokenFileDataset(p, seq_len=128, stride=64)
        assert len(ds) == (4096 - 128) // 64 + 1
        np.testing.assert_array_equal(ds[1], toks[64 : 64 + 128])

    def test_too_small_raises(self, tmp_path):
        p = tmp_path / "tiny.bin"
        write_token_file(p, np.zeros(16, np.uint16))
        with pytest.raises(ValueError):
            TokenFileDataset(p, seq_len=128)

    def test_zero_stride_raises(self, token_file):
        p, _ = token_file
        with pytest.raises(ValueError):
            TokenFileDataset(p, seq_len=128, stride=0)


class TestNativeGather:
    def test_matches_python_indexing(self):
        base = np.random.default_rng(0).integers(
            0, 60000, size=10_000
        ).astype(np.uint16)
        starts = np.array([0, 128, 9872, 55, 4096], np.int64)
        out = _native.gather_rows(base, starts, 128)
        for i, s in enumerate(starts):
            np.testing.assert_array_equal(out[i], base[s : s + 128])

    def test_bounds_check(self):
        base = np.zeros(100, np.uint16)
        with pytest.raises(IndexError):
            _native.gather_rows(base, np.array([90], np.int64), 64)
        with pytest.raises(IndexError):
            _native.gather_rows(base, np.array([-1], np.int64), 10)


class TestLoader:
    def test_sharding_partitions_epoch(self, token_file):
        p, _ = token_file
        ds = TokenFileDataset(p, seq_len=128)  # 32 samples
        seen = []
        for rank in range(4):
            dl = DataLoader(
                ds, batch_size=2, seed=7, shard=(rank, 4)
            )
            assert dl.batches_per_epoch == 4
            for batch in dl.epoch(0):
                assert batch.shape == (2, 128)
                seen.extend(batch[:, 0].tolist())
        # every sample's first token is unique (windows are disjoint) —
        # the 4 ranks together cover 32 distinct samples exactly once
        assert len(seen) == 32 and len(set(seen)) == 32

    def test_epoch_determinism_and_reshuffle(self, token_file):
        p, _ = token_file
        ds = TokenFileDataset(p, seq_len=128)
        dl = DataLoader(ds, batch_size=4, seed=3)
        a = np.concatenate(list(dl.epoch(0)))
        b = np.concatenate(list(dl.epoch(0)))
        c = np.concatenate(list(dl.epoch(1)))
        np.testing.assert_array_equal(a, b)
        assert not np.array_equal(a, c)

    def test_iter_from_seeks_without_replay(self, token_file):
        """iter_from(N) batch k == plain stream batch N+k (incl. across
        the epoch boundary), with no gathers for the skipped prefix."""
        p, _ = token_file
        ds = TokenFileDataset(p, seq_len=128)
        dl = DataLoader(ds, batch_size=4, seed=9)  # 8 batches/epoch
        import itertools

        plain = list(itertools.islice(iter(dl), 12))
        for start in (3, 8, 10):  # mid-epoch, boundary, next epoch
            seeked = list(
                itertools.islice(dl.iter_from(start), 12 - start)
            )
            for k, b in enumerate(seeked):
                np.testing.assert_array_equal(b, plain[start + k])

    def test_mlm_stream_start_step_matches(self, token_file):
        """bert_mlm_batches(start_step=N) reproduces batch N of the
        uninterrupted stream bit-exactly (loader seek + mask seed)."""
        p, _ = token_file
        ds = TokenFileDataset(p, seq_len=128)

        def stream(start):
            return bert_mlm_batches(
                DataLoader(ds, batch_size=4, seed=2), seed=7,
                vocab_size=6000, start_step=start,
            )

        import itertools

        plain = list(itertools.islice(stream(0), 6))
        resumed = list(itertools.islice(stream(4), 2))
        for k in range(2):
            for key in plain[0]:
                np.testing.assert_array_equal(
                    resumed[k][key], plain[4 + k][key], err_msg=key
                )

    def test_endless_iter_crosses_epochs(self, token_file):
        p, _ = token_file
        ds = TokenFileDataset(p, seq_len=128)
        dl = DataLoader(ds, batch_size=4, shard=(0, 1))
        it = iter(dl)
        batches = [next(it) for _ in range(dl.batches_per_epoch + 2)]
        assert all(b.shape == (4, 128) for b in batches)

    def test_same_seed_identical_across_processes(self, token_file):
        """Same (seed, shard) ⇒ bit-identical batch sequence from a
        freshly constructed loader — the property that makes a resumed
        process's stream equal the dead one's."""
        p, _ = token_file
        import itertools

        def seq(rank, world):
            ds = TokenFileDataset(p, seq_len=128)  # fresh mmap each time
            dl = DataLoader(ds, batch_size=2, seed=11, shard=(rank, world))
            return [b.tobytes() for b in itertools.islice(iter(dl), 6)]

        for shard in ((0, 1), (0, 4), (3, 4)):
            assert seq(*shard) == seq(*shard), shard

    def test_world_sizes_slice_one_global_permutation(self, token_file):
        """Same seed ⇒ every world size derives from the SAME global
        shuffle: epoch 0 at world=W, rank r yields exactly the
        order[r::W] slice of the world=1 sample order (≙ torch
        DistributedSampler semantics) — so scaling the fleet reshards
        the epoch instead of reshuffling it."""
        p, _ = token_file
        ds = TokenFileDataset(p, seq_len=128)  # 32 samples
        global_order = [
            s for b in DataLoader(
                ds, batch_size=1, seed=5, shard=(0, 1)
            ).epoch(0) for s in b[:, 0].tolist()
        ]
        for world in (2, 4):
            for rank in range(world):
                mine = [
                    s for b in DataLoader(
                        ds, batch_size=1, seed=5, shard=(rank, world)
                    ).epoch(0) for s in b[:, 0].tolist()
                ]
                assert mine == global_order[rank::world], (rank, world)

    def test_save_restore_boundary_mid_epoch(self, token_file):
        """The resume contract across a checkpoint boundary, including
        mid-epoch: a fresh loader seeked to batch N continues the
        exact sequence (bit-identical) the first loader would have
        produced — pinned through the goodput stream-state round-trip."""
        from apex_tpu.goodput import stream_state, verify_stream_state

        p, _ = token_file
        import itertools

        def fresh():
            return DataLoader(
                TokenFileDataset(p, seq_len=128), batch_size=4, seed=13
            )  # 8 batches/epoch

        plain = list(itertools.islice(iter(fresh()), 14))
        for boundary in (3, 8, 11):  # mid-epoch, boundary, next epoch
            # "checkpoint" the cursor, "restore" it onto a fresh loader
            saved = stream_state(fresh(), boundary)
            resumed_loader = fresh()
            start = verify_stream_state(resumed_loader, saved)
            resumed = itertools.islice(
                resumed_loader.iter_from(start), 14 - boundary
            )
            for k, b in enumerate(resumed):
                np.testing.assert_array_equal(
                    b, plain[boundary + k], err_msg=f"boundary={boundary}"
                )

    def test_bad_shard_and_small_dataset(self, token_file):
        p, _ = token_file
        ds = TokenFileDataset(p, seq_len=128)
        with pytest.raises(ValueError):
            DataLoader(ds, batch_size=2, shard=(4, 4))
        with pytest.raises(ValueError):
            DataLoader(ds, batch_size=64)  # 32 samples < one batch
        with pytest.raises(NotImplementedError):
            DataLoader(ds, batch_size=2, drop_last=False)


class TestPrefetcher:
    def test_yields_device_arrays_in_order(self, token_file):
        p, _ = token_file
        ds = TokenFileDataset(p, seq_len=128)
        dl = DataLoader(ds, batch_size=4, shuffle=False)
        direct = list(dl.epoch(0))
        with DevicePrefetcher(dl.epoch(0), depth=3) as pf:
            fetched = list(pf)
        assert len(fetched) == len(direct)
        for d, f in zip(direct, fetched):
            assert isinstance(f, jax.Array)
            np.testing.assert_array_equal(d, np.asarray(f))

    def test_propagates_worker_error(self):
        def bad():
            yield np.zeros((2, 2))
            raise RuntimeError("boom")

        with DevicePrefetcher(bad(), depth=1) as pf:
            next(pf)
            with pytest.raises(RuntimeError, match="boom"):
                while True:
                    next(pf)

    def test_close_stops_worker(self, token_file):
        p, _ = token_file
        ds = TokenFileDataset(p, seq_len=128)
        pf = DevicePrefetcher(iter(DataLoader(ds, batch_size=2)), depth=1)
        next(pf)
        pf.close()
        assert not pf._worker.is_alive()

    def test_pytree_batches(self):
        batches = [{"a": np.ones((2,)), "b": np.zeros((3,))}] * 3
        with DevicePrefetcher(iter(batches)) as pf:
            out = list(pf)
        assert len(out) == 3 and isinstance(out[0]["a"], jax.Array)


class TestMlmBatches:
    def test_stream_shapes_and_corruption(self, token_file):
        p, _ = token_file
        ds = TokenFileDataset(p, seq_len=128)
        dl = DataLoader(ds, batch_size=4, seed=1)
        it = bert_mlm_batches(
            dl, seed=5, vocab_size=6000, mask_id=103, special_floor=1000
        )
        b = next(it)
        assert b["input_ids"].shape == (128, 4)  # seq-first
        assert b["mlm_labels"].shape == (128, 4)
        assert b["attention_mask"].shape == (4, 128)
        sel = b["mlm_labels"] >= 0
        assert 0.05 < sel.mean() < 0.30  # ~15% selected
        # at selected positions the label holds the ORIGINAL token
        masked_frac = (b["input_ids"][sel] == 103).mean()
        assert 0.6 < masked_frac < 0.95  # ~80% of selected -> [MASK]
        # consecutive steps draw different masks
        b2 = next(it)
        assert not np.array_equal(b["mlm_labels"], b2["mlm_labels"])

    def test_packed_prediction_triple(self, token_file):
        """max_predictions_per_seq adds the fixed-K positions/ids/weights
        triple consistent with the dense labels (reference input format):
        real rows are a position-sorted uniform subset of the masked set
        (random selection when over budget), ids match the labels, pads
        carry weight 0.  Selection is deterministic in (seed, step)."""
        p, _ = token_file
        ds = TokenFileDataset(p, seq_len=128)
        dl = DataLoader(ds, batch_size=4, seed=1)
        b = next(bert_mlm_batches(
            dl, seed=5, vocab_size=6000, max_predictions_per_seq=24
        ))
        pos, ids, w = b["mlm_positions"], b["mlm_label_ids"], b["mlm_weights"]
        assert pos.shape == ids.shape == w.shape == (24, 4)
        labels = b["mlm_labels"]
        for col in range(4):
            masked = np.nonzero(labels[:, col] >= 0)[0]
            n = int(w[:, col].sum())
            assert n == min(len(masked), 24)
            got = pos[:n, col]
            assert (np.sort(got) == got).all()  # position order
            assert set(got) <= set(masked)  # subset of the masked set
            np.testing.assert_array_equal(ids[:n, col], labels[got, col])
            assert w[:n, col].all() and not w[n:, col].any()
        # deterministic in (seed, step): a fresh stream reproduces the
        # same selection bit-exactly
        b2 = next(bert_mlm_batches(
            DataLoader(ds, batch_size=4, seed=1), seed=5, vocab_size=6000,
            max_predictions_per_seq=24,
        ))
        np.testing.assert_array_equal(b2["mlm_positions"], pos)
        np.testing.assert_array_equal(b2["mlm_weights"], w)
