"""Unit tests for the comm-structure analysis passes — the parsers
behind COMM_STRUCTURE_r{N}.json, which live in the shared analysis core
(``apex_tpu/analysis/hlo.py``) and are consumed by
``tools/comm_structure.py``.

These run on synthetic HLO text / pure arithmetic, so regressions in the
artifact generator fail here rather than silently skewing the recorded
comm fractions.
"""

import os
import sys

import pytest

from apex_tpu.analysis.hlo import (
    collective_summary as collect,
    overlap_collect,
)

# bare `pytest` puts tests/ (not the repo root) on sys.path; tools/ is a
# plain directory, not an installed package.  The balance/traffic models
# (not regex parsers) still live with the artifact generator.
sys.path.insert(
    0, os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
)

from tools.comm_structure import (  # noqa: E402
    cp_ring_balance_model,
    ring_traffic_bytes,
)


# ---------------------------------------------------------------------------
# overlap windows
# ---------------------------------------------------------------------------


SYNC_OVERLAPPED = """
ENTRY %main {
  %p0 = f32[8,128]{1,0} parameter(0)
  %p1 = f32[128,128]{1,0} parameter(1)
  %ar = f32[8,128]{1,0} all-reduce(%p0), replica_groups={{0,1}}
  %dot = f32[128,128]{1,0} dot(%p1, %p1), lhs_contracting_dims={1}, rhs_contracting_dims={0}
  %use = f32[8,128]{1,0} fusion(%ar, %dot), kind=kLoop, calls=%fc
}
"""

SYNC_SERIAL = """
ENTRY %main {
  %p0 = f32[8,128]{1,0} parameter(0)
  %ar = f32[8,128]{1,0} all-reduce(%p0), replica_groups={{0,1}}
  %use = f32[8,128]{1,0} fusion(%ar), kind=kLoop, calls=%fc
  %dot = f32[128,128]{1,0} dot(%use, %use), lhs_contracting_dims={1}, rhs_contracting_dims={0}
}
"""

ASYNC_PAIR = """
ENTRY %main {
  %p0 = f32[8,128]{1,0} parameter(0)
  %ar-start = (f32[8,128]{1,0}, f32[8,128]{1,0}) all-reduce-start(%p0), replica_groups={{0,1}}
  %dot = f32[128,128]{1,0} dot(%p1, %p1), lhs_contracting_dims={1}, rhs_contracting_dims={0}
  %ar-done = f32[8,128]{1,0} all-reduce-done(%ar-start)
  %use = f32[8,128]{1,0} fusion(%ar-done), kind=kLoop, calls=%fc
}
"""

NO_SIGIL = """
ENTRY main {
  p0 = f32[8,128]{1,0} parameter(0)
  p1 = f32[128,128]{1,0} parameter(1)
  ar = f32[8,128]{1,0} all-reduce(p0), replica_groups={{0,1}}
  use = f32[8,128]{1,0} fusion(ar), kind=kLoop, calls=fc
  dot.1 = f32[128,128]{1,0} dot(p1, p1), lhs_contracting_dims={1}, rhs_contracting_dims={0}
}
"""

BYTES_8x128_F32 = 8 * 128 * 4


def test_sync_window_with_independent_compute_is_overlapped():
    ov = overlap_collect(SYNC_OVERLAPPED)
    assert ov["sync_count"] == 1
    assert ov["sync_bytes"] == BYTES_8x128_F32
    assert ov["overlapped_count"] == 1
    assert ov["overlapped_bytes"] == BYTES_8x128_F32


def test_sync_window_closed_at_first_consumer_is_serial():
    """Compute AFTER the first consumer is outside the window — the
    collective blocks its consumer and cannot be hidden behind it."""
    ov = overlap_collect(SYNC_SERIAL)
    assert ov["sync_count"] == 1
    assert ov["overlapped_count"] == 0
    assert ov["overlapped_bytes"] == 0


def test_async_pair_with_compute_in_window():
    ov = overlap_collect(ASYNC_PAIR)
    assert ov["async_pairs"] == 1
    assert ov["async_bytes"] == BYTES_8x128_F32  # result element only
    assert ov["overlapped_count"] == 1


def test_sigil_free_hlo_still_closes_windows():
    """HLO printed without '%' name sigils: the first-consumer search
    must still close the window (the regression the sigil-optional
    consumer regex exists for) — compute after first use stays serial."""
    ov = overlap_collect(NO_SIGIL)
    assert ov["sync_count"] == 1
    assert ov["overlapped_count"] == 0


def test_collect_and_traffic_model_consistent():
    kinds = collect(SYNC_OVERLAPPED)
    assert kinds["all-reduce"]["count"] == 1
    assert kinds["all-reduce"]["bytes"] == BYTES_8x128_F32
    # ring all-reduce moves 2*(w-1)/w of the operand per chip
    t = ring_traffic_bytes(kinds, world=8)
    assert t == pytest.approx(2 * BYTES_8x128_F32 * 7 / 8)


# ---------------------------------------------------------------------------
# zigzag causal balance model
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("cp", [2, 4, 8])
def test_balance_model_invariants(cp):
    m = cp_ring_balance_model(cp)
    cont, zz = m["contiguous"], m["zigzag"]
    # both layouts do the same useful work: the full causal triangle
    # over 2cp chunks = 2cp*(2cp+1)/2 half-tiles... in tile units:
    # cp^2 full tiles + 2cp diagonals*0.5 -> 2cp^2 per the derivation
    assert cont["useful_tiles_total"] == zz["useful_tiles_total"] == 2 * cp * cp
    # zigzag is perfectly balanced: 2 tiles per hop, every hop
    assert zz["per_hop_max_tiles"] == [2.0] * cp
    assert zz["utilization"] == 1.0
    # contiguous: diagonal hop 2, then full-block hops 4
    assert cont["per_hop_max_tiles"] == [2.0] + [4.0] * (cp - 1)
    # the headline: wall ratio = 2 - 1/cp
    assert m["wall_ratio_contiguous_over_zigzag"] == pytest.approx(
        2.0 - 1.0 / cp
    )


def test_balance_model_wall_is_sum_of_hop_maxima():
    m = cp_ring_balance_model(4)
    for layout in ("contiguous", "zigzag"):
        assert m[layout]["lockstep_wall_tiles"] == sum(
            m[layout]["per_hop_max_tiles"]
        )
