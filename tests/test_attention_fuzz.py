"""Seeded shape/variant fuzz of the flash-attention kernel path.

The directed parity tests pin specific corners; this sweeps a seeded
random sample of the whole eligibility envelope — arbitrary (Sq, Sk)
including non-tile multiples, causal × bias-group × trainable-bias ×
dtype — kernel (interpret mode on CPU) vs the jnp reference, values AND
grads.  A divergence prints its draw so the case can be promoted to a
directed test.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from apex_tpu.ops import _dispatch
from apex_tpu.ops.attention import flash_attention, mha_reference

N_DRAWS = 10


def _draw(rng):
    b = int(rng.integers(1, 3))
    h = int(rng.integers(1, 3))
    d = int(rng.choice([32, 64]))
    sq = int(rng.integers(8, 200))
    sk = int(rng.integers(8, 200))
    causal = bool(rng.integers(0, 2))
    # the one documented jnp-only corner (attention._pallas_eligible):
    # bottom-right causal with Sq > Sk and a padding-needing Sk — there
    # the forced-kernel run would silently fall back to jnp and the test
    # would compare jnp to itself.  Align sk to the tile quantum (the
    # _seq_pad rule: 8 below a lane block, 128 above) so the kernel path
    # stays live for causal draws.
    if causal and sk < sq:
        quantum = 8 if sk < 128 else 128
        sk = min(sq, ((sk + quantum - 1) // quantum) * quantum)
    dtype = jnp.bfloat16 if rng.integers(0, 2) else jnp.float32
    bias_kind = int(rng.integers(0, 3))  # 0: none, 1: (1,1,Sk), 2: (B,H,Sq,Sk)
    bias_grad = bool(rng.integers(0, 2)) and bias_kind == 2
    return b, h, d, sq, sk, causal, dtype, bias_kind, bias_grad


@pytest.mark.slow
@pytest.mark.parametrize("seed", range(N_DRAWS))
def test_flash_vs_reference_fuzz(seed):
    rng = np.random.default_rng(1234 + seed)
    b, h, d, sq, sk, causal, dtype, bias_kind, bias_grad = _draw(rng)
    tol = (
        dict(rtol=3e-2, atol=3e-2)
        if dtype == jnp.bfloat16
        else dict(rtol=2e-4, atol=2e-4)
    )
    key = jax.random.PRNGKey(seed)
    kq, kk, kv, kb = jax.random.split(key, 4)
    q = jax.random.normal(kq, (b, h, sq, d), dtype)
    k = jax.random.normal(kk, (b, h, sk, d), dtype)
    v = jax.random.normal(kv, (b, h, sk, d), dtype)
    bias = None
    if bias_kind == 1:
        bias = jax.random.normal(kb, (1, 1, 1, sk), jnp.float32)
    elif bias_kind == 2:
        bias = jax.random.normal(kb, (b, h, sq, sk), jnp.float32)
    desc = (f"b={b} h={h} d={d} sq={sq} sk={sk} causal={causal} "
            f"dtype={dtype.__name__} bias={bias_kind} bgrad={bias_grad}")

    def run(forced):
        # interpret mode is automatic off-TPU (_dispatch.pallas_interpret)
        _dispatch.set_use_pallas(forced)
        try:
            args = (q, k, v) + ((bias,) if bias is not None else ())

            def loss(*args):
                o = flash_attention(
                    *args, causal=causal, bias_grad=bias_grad
                )
                return jnp.sum(o.astype(jnp.float32) ** 2), o

            (l, o), grads = jax.value_and_grad(
                loss, argnums=tuple(range(len(args))), has_aux=True
            )(*args)
            return o, grads
        finally:
            _dispatch.set_use_pallas(None)

    # kernel path eligibility: the public dispatch may still choose jnp
    # for the documented corner — that IS the contract, so both runs just
    # exercise whatever the forced flag selects
    try:
        o_k, g_k = run(True)
    except ValueError as e:
        pytest.skip(f"{desc}: kernel path refused: {e}")
    o_r, g_r = run(False)

    np.testing.assert_allclose(
        np.asarray(o_k, np.float32), np.asarray(o_r, np.float32),
        err_msg=desc, **tol,
    )
    # q/k/v grads always; the bias cotangent only when trainable —
    # bias_grad=False is DOCUMENTED to return zeros on the flash path
    # while the jnp fallback differentiates naturally
    n_cmp = 3 + (1 if (bias is not None and bias_grad) else 0)
    for a, b_ in zip(g_k[:n_cmp], g_r[:n_cmp]):
        np.testing.assert_allclose(
            np.asarray(a, np.float32), np.asarray(b_, np.float32),
            err_msg=desc, **tol,
        )


@pytest.mark.slow
@pytest.mark.parametrize("seed", range(6))
def test_with_lse_fuzz(seed):
    """flash_attention_with_lse (the ring-attention building block) under
    random aligned shapes: (o, lse) and grads — INCLUDING the lse
    cotangent the ring merge differentiates through — kernel vs jnp."""
    from apex_tpu.ops.attention import flash_attention_with_lse

    rng = np.random.default_rng(77 + seed)
    b = int(rng.integers(1, 3))
    h = int(rng.integers(1, 3))
    d = int(rng.choice([32, 64]))
    # aligned shapes only (the lse variant has no pad/bias plumbing):
    # multiples of the sublane/lane quantum
    sq = int(rng.choice([16, 64, 128, 256]))
    sk = int(rng.choice([16, 64, 128, 256]))
    causal = bool(rng.integers(0, 2))
    if causal and sk < sq:
        sk = sq
    dtype = jnp.bfloat16 if rng.integers(0, 2) else jnp.float32
    tol = (
        dict(rtol=3e-2, atol=3e-2)
        if dtype == jnp.bfloat16
        else dict(rtol=3e-4, atol=3e-4)
    )
    key = jax.random.PRNGKey(seed)
    kq, kk, kv, kc = jax.random.split(key, 4)
    q = jax.random.normal(kq, (b, h, sq, d), dtype)
    k = jax.random.normal(kk, (b, h, sk, d), dtype)
    v = jax.random.normal(kv, (b, h, sk, d), dtype)
    # a fixed random cotangent for lse so its backward path is exercised
    dlse_w = jax.random.normal(kc, (b, h, sq), jnp.float32)
    desc = f"b={b} h={h} d={d} sq={sq} sk={sk} causal={causal} {dtype.__name__}"

    def run(forced):
        _dispatch.set_use_pallas(forced)
        try:
            def loss(q, k, v):
                o, lse = flash_attention_with_lse(q, k, v, causal=causal)
                return (
                    jnp.sum(o.astype(jnp.float32) ** 2)
                    + jnp.sum(lse * dlse_w),
                    (o, lse),
                )

            (_, (o, lse)), grads = jax.value_and_grad(
                loss, argnums=(0, 1, 2), has_aux=True
            )(q, k, v)
            return o, lse, grads
        finally:
            _dispatch.set_use_pallas(None)

    o_k, lse_k, g_k = run(True)
    o_r, lse_r, g_r = run(False)
    np.testing.assert_allclose(
        np.asarray(o_k, np.float32), np.asarray(o_r, np.float32),
        err_msg=desc, **tol,
    )
    np.testing.assert_allclose(
        np.asarray(lse_k), np.asarray(lse_r), err_msg=desc,
        rtol=1e-3, atol=1e-3,
    )
    for a, b_ in zip(g_k, g_r):
        np.testing.assert_allclose(
            np.asarray(a, np.float32), np.asarray(b_, np.float32),
            err_msg=desc, **tol,
        )


def test_mha_reference_is_the_golden():
    """The fuzz compares against mha_reference — pin that it matches a
    hand-written softmax composition once, so the golden itself is
    anchored."""
    key = jax.random.PRNGKey(0)
    q, k, v = (
        jax.random.normal(s, (1, 2, 16, 8), jnp.float32)
        for s in jax.random.split(key, 3)
    )
    got = mha_reference(q, k, v, causal=True)
    scale = 8 ** -0.5
    s = jnp.einsum("bhqd,bhkd->bhqk", q, k) * scale
    mask = jnp.tril(jnp.ones((16, 16), bool))
    s = jnp.where(mask, s, -1e30)
    want = jnp.einsum("bhqk,bhkd->bhqd", jax.nn.softmax(s, axis=-1), v)
    np.testing.assert_allclose(
        np.asarray(got), np.asarray(want), rtol=1e-5, atol=1e-5
    )
