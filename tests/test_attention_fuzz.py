"""Seeded shape/variant fuzz of the flash-attention kernel path.

The directed parity tests pin specific corners; this sweeps a seeded
random sample of the whole eligibility envelope — arbitrary (Sq, Sk)
including non-tile multiples, causal × bias-group × trainable-bias ×
dtype — kernel (interpret mode on CPU) vs the jnp reference, values AND
grads.  A divergence prints its draw so the case can be promoted to a
directed test.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from apex_tpu.ops import _dispatch
from apex_tpu.ops.attention import flash_attention, mha_reference

N_DRAWS = 10


def _draw(rng):
    b = int(rng.integers(1, 3))
    h = int(rng.integers(1, 3))
    d = int(rng.choice([32, 64]))
    sq = int(rng.integers(8, 200))
    sk = int(rng.integers(8, 200))
    causal = bool(rng.integers(0, 2))
    # the one documented jnp-only corner (attention._pallas_eligible):
    # bottom-right causal with Sq > Sk and a padding-needing Sk — there
    # the forced-kernel run would silently fall back to jnp and the test
    # would compare jnp to itself.  Align sk to the tile quantum (the
    # _seq_pad rule: 8 below a lane block, 128 above) so the kernel path
    # stays live for causal draws.
    if causal and sk < sq:
        quantum = 8 if sk < 128 else 128
        sk = min(sq, ((sk + quantum - 1) // quantum) * quantum)
    dtype = jnp.bfloat16 if rng.integers(0, 2) else jnp.float32
    bias_kind = int(rng.integers(0, 3))  # 0: none, 1: (1,1,Sk), 2: (B,H,Sq,Sk)
    bias_grad = bool(rng.integers(0, 2)) and bias_kind == 2
    return b, h, d, sq, sk, causal, dtype, bias_kind, bias_grad


@pytest.mark.slow
@pytest.mark.parametrize("seed", range(N_DRAWS))
def test_flash_vs_reference_fuzz(seed):
    rng = np.random.default_rng(1234 + seed)
    b, h, d, sq, sk, causal, dtype, bias_kind, bias_grad = _draw(rng)
    tol = (
        dict(rtol=3e-2, atol=3e-2)
        if dtype == jnp.bfloat16
        else dict(rtol=2e-4, atol=2e-4)
    )
    key = jax.random.PRNGKey(seed)
    kq, kk, kv, kb = jax.random.split(key, 4)
    q = jax.random.normal(kq, (b, h, sq, d), dtype)
    k = jax.random.normal(kk, (b, h, sk, d), dtype)
    v = jax.random.normal(kv, (b, h, sk, d), dtype)
    bias = None
    if bias_kind == 1:
        bias = jax.random.normal(kb, (1, 1, 1, sk), jnp.float32)
    elif bias_kind == 2:
        bias = jax.random.normal(kb, (b, h, sq, sk), jnp.float32)
    desc = (f"b={b} h={h} d={d} sq={sq} sk={sk} causal={causal} "
            f"dtype={dtype.__name__} bias={bias_kind} bgrad={bias_grad}")

    def run(forced):
        # interpret mode is automatic off-TPU (_dispatch.pallas_interpret)
        _dispatch.set_use_pallas(forced)
        try:
            args = (q, k, v) + ((bias,) if bias is not None else ())

            def loss(*args):
                o = flash_attention(
                    *args, causal=causal, bias_grad=bias_grad
                )
                return jnp.sum(o.astype(jnp.float32) ** 2), o

            (l, o), grads = jax.value_and_grad(
                loss, argnums=tuple(range(len(args))), has_aux=True
            )(*args)
            return o, grads
        finally:
            _dispatch.set_use_pallas(None)

    # kernel path eligibility: the public dispatch may still choose jnp
    # for the documented corner — that IS the contract, so both runs just
    # exercise whatever the forced flag selects
    try:
        o_k, g_k = run(True)
    except ValueError as e:
        pytest.skip(f"{desc}: kernel path refused: {e}")
    o_r, g_r = run(False)

    np.testing.assert_allclose(
        np.asarray(o_k, np.float32), np.asarray(o_r, np.float32),
        err_msg=desc, **tol,
    )
    # q/k/v grads always; the bias cotangent only when trainable —
    # bias_grad=False is DOCUMENTED to return zeros on the flash path
    # while the jnp fallback differentiates naturally
    n_cmp = 3 + (1 if (bias is not None and bias_grad) else 0)
    for a, b_ in zip(g_k[:n_cmp], g_r[:n_cmp]):
        np.testing.assert_allclose(
            np.asarray(a, np.float32), np.asarray(b_, np.float32),
            err_msg=desc, **tol,
        )


@pytest.mark.slow
@pytest.mark.parametrize("seed", range(6))
def test_with_lse_fuzz(seed):
    """flash_attention_with_lse (the ring-attention building block) under
    random aligned shapes × optional key-padding bias: (o, lse) and
    grads — INCLUDING the lse cotangent the ring merge differentiates
    through — kernel vs jnp."""
    from apex_tpu.ops.attention import flash_attention_with_lse
    from apex_tpu.ops.pallas.flash_attention import MASK_VALUE

    rng = np.random.default_rng(77 + seed)
    b = int(rng.integers(1, 3))
    h = int(rng.integers(1, 3))
    d = int(rng.choice([32, 64]))
    # aligned shapes only (the lse variant has no pad plumbing):
    # multiples of the sublane/lane quantum
    sq = int(rng.choice([16, 64, 128, 256]))
    sk = int(rng.choice([16, 64, 128, 256]))
    causal = bool(rng.integers(0, 2))
    if causal and sk < sq:
        sk = sq
    dtype = jnp.bfloat16 if rng.integers(0, 2) else jnp.float32
    with_bias = bool(rng.integers(0, 2))
    tol = (
        dict(rtol=3e-2, atol=3e-2)
        if dtype == jnp.bfloat16
        else dict(rtol=3e-4, atol=3e-4)
    )
    key = jax.random.PRNGKey(seed)
    kq, kk, kv, kc, kb = jax.random.split(key, 5)
    q = jax.random.normal(kq, (b, h, sq, d), dtype)
    k = jax.random.normal(kk, (b, h, sk, d), dtype)
    v = jax.random.normal(kv, (b, h, sk, d), dtype)
    bias = None
    if with_bias:
        # key-padding mask; key 0 always kept so no row is fully masked
        keep = jax.random.bernoulli(
            kb, 0.75, (b, 1, 1, sk)
        ).at[..., 0].set(True)
        bias = jnp.where(keep, 0.0, MASK_VALUE)
    # a fixed random cotangent for lse so its backward path is exercised
    dlse_w = jax.random.normal(kc, (b, h, sq), jnp.float32)
    desc = (f"b={b} h={h} d={d} sq={sq} sk={sk} causal={causal} "
            f"{dtype.__name__} bias={with_bias}")

    def run(forced):
        _dispatch.set_use_pallas(forced)
        try:
            def loss(q, k, v):
                o, lse = flash_attention_with_lse(
                    q, k, v, bias, causal=causal
                )
                return (
                    jnp.sum(o.astype(jnp.float32) ** 2)
                    + jnp.sum(lse * dlse_w),
                    (o, lse),
                )

            (_, (o, lse)), grads = jax.value_and_grad(
                loss, argnums=(0, 1, 2), has_aux=True
            )(q, k, v)
            return o, lse, grads
        finally:
            _dispatch.set_use_pallas(None)

    o_k, lse_k, g_k = run(True)
    o_r, lse_r, g_r = run(False)
    np.testing.assert_allclose(
        np.asarray(o_k, np.float32), np.asarray(o_r, np.float32),
        err_msg=desc, **tol,
    )
    np.testing.assert_allclose(
        np.asarray(lse_k), np.asarray(lse_r), err_msg=desc,
        rtol=1e-3, atol=1e-3,
    )
    for a, b_ in zip(g_k, g_r):
        np.testing.assert_allclose(
            np.asarray(a, np.float32), np.asarray(b_, np.float32),
            err_msg=desc, **tol,
        )


def _kernel_keep_mask(seed, b, h, sq, sk, p):
    """The full (B,H,Sq,Sk) keep mask the kernel's counter-based PRNG
    generates: ``_dropout_keep_block`` is a pure function of (seed, bh,
    absolute row, absolute col), so evaluating tile (0, 0) at full size
    reproduces every kernel tile's coordinates exactly."""
    from apex_tpu.ops.pallas.flash_attention import _dropout_keep_block

    masks = [
        _dropout_keep_block(
            seed, jnp.asarray(bh, jnp.int32), 0, 0, sq, sk, p
        )
        for bh in range(b * h)
    ]
    return jnp.stack(masks).reshape(b, h, sq, sk)


def _derive_seed(dropout_rng):
    # exactly the dispatcher's derivation (ops/attention.py)
    return jax.random.randint(
        dropout_rng, (1,), jnp.iinfo(jnp.int32).min,
        jnp.iinfo(jnp.int32).max, dtype=jnp.int32,
    )[0]


@pytest.mark.slow
@pytest.mark.parametrize("seed", range(8))
def test_flash_dropout_fuzz(seed):
    """Fused-dropout fuzz (VERDICT r3 #6): random shape x causal x
    bias_kind x bias_grad x padded-S draws with dropout_p > 0, checking
    the kernel against its OWN keep-mask contract — a jnp golden that
    applies the kernel's regenerated mask to the reference softmax
    (values AND grads) — plus determinism and keep-rate statistics.  The
    jnp fallback's jax.random mask stream differs by documented contract,
    so kernel-vs-jnp comparison is only valid through the shared mask."""
    from apex_tpu.ops.pallas import flash_attention as _pallas
    from apex_tpu.ops.attention import _scores

    rng = np.random.default_rng(5678 + seed)
    b, h, d, sq, sk, causal, dtype, bias_kind, bias_grad = _draw(rng)
    if causal and sk < sq:
        # fully-masked rows (bottom-right causal, Sk < Sq) have
        # uniform-average semantics the masked-softmax golden can't
        # express with dropout; the no-dropout fuzz keeps that corner
        sk = sq
    dropout_p = float(rng.choice([0.1, 0.2, 0.35, 0.5]))
    tol = (
        dict(rtol=3e-2, atol=3e-2)
        if dtype == jnp.bfloat16
        else dict(rtol=2e-4, atol=2e-4)
    )
    key = jax.random.PRNGKey(seed)
    kq, kk, kv, kb, kr = jax.random.split(key, 5)
    q = jax.random.normal(kq, (b, h, sq, d), dtype)
    k = jax.random.normal(kk, (b, h, sk, d), dtype)
    v = jax.random.normal(kv, (b, h, sk, d), dtype)
    bias = None
    if bias_kind == 1:
        bias = jax.random.normal(kb, (1, 1, 1, sk), jnp.float32)
    elif bias_kind == 2:
        bias = jax.random.normal(kb, (b, h, sq, sk), jnp.float32)
    desc = (f"b={b} h={h} d={d} sq={sq} sk={sk} causal={causal} "
            f"dtype={dtype.__name__} bias={bias_kind} bgrad={bias_grad} "
            f"p={dropout_p}")
    args = (q, k, v) + ((bias,) if bias is not None else ())

    def kernel_run(rng_key):
        _dispatch.set_use_pallas(True)
        try:
            def loss(*args):
                o = flash_attention(
                    *args, causal=causal, bias_grad=bias_grad,
                    dropout_p=dropout_p, dropout_rng=rng_key,
                )
                return jnp.sum(o.astype(jnp.float32) ** 2), o

            (l, o), grads = jax.value_and_grad(
                loss, argnums=tuple(range(len(args))), has_aux=True
            )(*args)
            return o, grads
        finally:
            _dispatch.set_use_pallas(None)

    o_k, g_k = kernel_run(kr)

    # determinism: identical rng -> bitwise-identical output
    o_k2, _ = kernel_run(kr)
    np.testing.assert_array_equal(
        np.asarray(o_k), np.asarray(o_k2), err_msg=desc
    )

    # golden: reference softmax with the kernel's regenerated keep mask
    keep = _kernel_keep_mask(_derive_seed(kr), b, h, sq, sk, dropout_p)

    # keep-rate statistics (binomial over b*h*sq*sk draws)
    n = keep.size
    rate = float(jnp.mean(keep))
    bound = 5.0 * float(np.sqrt(dropout_p * (1 - dropout_p) / n)) + 1e-3
    assert abs(rate - (1 - dropout_p)) < bound, (desc, rate)

    scale = 1.0 / (d ** 0.5)

    def golden(*args):
        q, k, v = args[:3]
        bz = args[3] if len(args) > 3 else None
        if bz is not None:
            # the dispatcher clamps the bias to MASK_VALUE on both paths
            bz = jnp.maximum(bz, _pallas.MASK_VALUE)
        s = _scores(q, k, bz, causal, scale)
        probs = jax.nn.softmax(s, axis=-1)
        pd = jnp.where(keep, probs / (1.0 - dropout_p), 0.0)
        o = jnp.einsum("bhqk,bhkd->bhqd", pd.astype(q.dtype), v)
        return jnp.sum(o.astype(jnp.float32) ** 2), o

    (_, o_g), g_g = jax.value_and_grad(
        golden, argnums=tuple(range(len(args))), has_aux=True
    )(*args)

    np.testing.assert_allclose(
        np.asarray(o_k, np.float32), np.asarray(o_g, np.float32),
        err_msg=desc, **tol,
    )
    n_cmp = 3 + (1 if (bias is not None and bias_grad) else 0)
    for a, b_ in zip(g_k[:n_cmp], g_g[:n_cmp]):
        _assert_grad_close(a, b_, dtype, tol, desc)


def _assert_grad_close(a, b_, dtype, tol, desc):
    """Grad comparison scaled to the golden's own magnitude: a bf16 dot
    product's rounding error is proportional to the LARGEST values summed
    into it, not to each output element — so bf16 draws get an atol of
    2% of the golden's max |g| (f32 draws keep the strict tol; they pin
    exactness of the shared mask stream)."""
    a32, b32 = np.asarray(a, np.float32), np.asarray(b_, np.float32)
    eff = dict(tol)
    if dtype == jnp.bfloat16:
        eff["atol"] = max(eff["atol"], 2e-2 * float(np.abs(b32).max()))
    np.testing.assert_allclose(a32, b32, err_msg=desc, **eff)


@pytest.mark.slow
@pytest.mark.parametrize("seed", range(4))
def test_with_lse_dropout_fuzz(seed):
    """Dropout on the with-lse path (ring-attention building block):
    the PV contribution is masked + rescaled while lse stays the full
    undropped row statistic, and the dlse cotangent bypasses the keep
    mask in backward — checked against the keep-mask golden, values
    (o AND lse) and grads with a live lse cotangent."""
    from apex_tpu.ops.attention import _scores, flash_attention_with_lse

    rng = np.random.default_rng(901 + seed)
    b = int(rng.integers(1, 3))
    h = int(rng.integers(1, 3))
    d = int(rng.choice([32, 64]))
    sq = int(rng.choice([16, 64, 128, 256]))
    sk = int(rng.choice([16, 64, 128, 256]))
    causal = bool(rng.integers(0, 2))
    if causal and sk < sq:
        sk = sq
    dropout_p = float(rng.choice([0.1, 0.25, 0.4]))
    dtype = jnp.bfloat16 if rng.integers(0, 2) else jnp.float32
    tol = (
        dict(rtol=3e-2, atol=3e-2)
        if dtype == jnp.bfloat16
        else dict(rtol=3e-4, atol=3e-4)
    )
    key = jax.random.PRNGKey(seed)
    kq, kk, kv, kc, kr = jax.random.split(key, 5)
    q = jax.random.normal(kq, (b, h, sq, d), dtype)
    k = jax.random.normal(kk, (b, h, sk, d), dtype)
    v = jax.random.normal(kv, (b, h, sk, d), dtype)
    dlse_w = jax.random.normal(kc, (b, h, sq), jnp.float32)
    desc = (f"b={b} h={h} d={d} sq={sq} sk={sk} causal={causal} "
            f"{dtype.__name__} p={dropout_p}")

    def kernel_run():
        _dispatch.set_use_pallas(True)
        try:
            def loss(q, k, v):
                o, lse = flash_attention_with_lse(
                    q, k, v, causal=causal, dropout_p=dropout_p,
                    dropout_rng=kr,
                )
                return (
                    jnp.sum(o.astype(jnp.float32) ** 2)
                    + jnp.sum(lse * dlse_w),
                    (o, lse),
                )

            (_, (o, lse)), grads = jax.value_and_grad(
                loss, argnums=(0, 1, 2), has_aux=True
            )(q, k, v)
            return o, lse, grads
        finally:
            _dispatch.set_use_pallas(None)

    o_k, lse_k, g_k = kernel_run()

    keep = _kernel_keep_mask(_derive_seed(kr), b, h, sq, sk, dropout_p)
    scale = 1.0 / (d ** 0.5)

    def golden(q, k, v):
        s = _scores(q, k, None, causal, scale)
        m = jnp.max(s, axis=-1, keepdims=True)
        pexp = jnp.exp(s - m)
        l = jnp.sum(pexp, axis=-1, keepdims=True)
        pd = jnp.where(keep, (pexp / l) / (1.0 - dropout_p), 0.0)
        o = jnp.einsum("bhqk,bhkd->bhqd", pd.astype(q.dtype), v)
        lse = (m + jnp.log(l))[..., 0]
        return (
            jnp.sum(o.astype(jnp.float32) ** 2) + jnp.sum(lse * dlse_w),
            (o, lse),
        )

    (_, (o_g, lse_g)), g_g = jax.value_and_grad(
        golden, argnums=(0, 1, 2), has_aux=True
    )(q, k, v)

    np.testing.assert_allclose(
        np.asarray(o_k, np.float32), np.asarray(o_g, np.float32),
        err_msg=desc, **tol,
    )
    np.testing.assert_allclose(
        np.asarray(lse_k), np.asarray(lse_g), err_msg=desc,
        rtol=1e-3, atol=1e-3,
    )
    for a, b_ in zip(g_k, g_g):
        _assert_grad_close(a, b_, dtype, tol, desc)


def test_mha_reference_is_the_golden():
    """The fuzz compares against mha_reference — pin that it matches a
    hand-written softmax composition once, so the golden itself is
    anchored."""
    key = jax.random.PRNGKey(0)
    q, k, v = (
        jax.random.normal(s, (1, 2, 16, 8), jnp.float32)
        for s in jax.random.split(key, 3)
    )
    got = mha_reference(q, k, v, causal=True)
    scale = 8 ** -0.5
    s = jnp.einsum("bhqd,bhkd->bhqk", q, k) * scale
    mask = jnp.tril(jnp.ones((16, 16), bool))
    s = jnp.where(mask, s, -1e30)
    want = jnp.einsum("bhqk,bhkd->bhqd", jax.nn.softmax(s, axis=-1), v)
    np.testing.assert_allclose(
        np.asarray(got), np.asarray(want), rtol=1e-5, atol=1e-5
    )
