"""Flash attention vs unfused reference — ≙ apex/contrib/test/fmha and
multihead_attn tests (fused kernel vs plain torch attention composition)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from apex_tpu.ops import _dispatch
from apex_tpu.ops.attention import flash_attention, fmha_qkvpacked, mha_reference


@pytest.fixture
def force_pallas():
    _dispatch.set_use_pallas(True)
    yield
    _dispatch.set_use_pallas(None)


def _rand_qkv(key, b=2, h=2, sq=128, sk=128, d=64, dtype=jnp.float32):
    kq, kk, kv = jax.random.split(key, 3)
    q = jax.random.normal(kq, (b, h, sq, d), dtype)
    k = jax.random.normal(kk, (b, h, sk, d), dtype)
    v = jax.random.normal(kv, (b, h, sk, d), dtype)
    return q, k, v


@pytest.mark.parametrize("causal", [False, True])
def test_forward_matches_reference(force_pallas, causal):
    q, k, v = _rand_qkv(jax.random.PRNGKey(0))
    out = flash_attention(q, k, v, causal=causal)
    ref = mha_reference(q, k, v, causal=causal)
    np.testing.assert_allclose(out, ref, atol=2e-5, rtol=2e-5)


def test_forward_bias(force_pallas):
    q, k, v = _rand_qkv(jax.random.PRNGKey(1))
    # key-padding-style additive mask: last 32 keys masked out for batch 1
    bias = np.zeros((2, 1, 1, 128), np.float32)
    bias[1, :, :, 96:] = -1e9
    bias = jnp.asarray(np.broadcast_to(bias, (2, 1, 128, 128)))
    out = flash_attention(q, k, v, bias)
    ref = mha_reference(q, k, v, bias)
    np.testing.assert_allclose(out, ref, atol=2e-5, rtol=2e-5)


def test_forward_shared_bias(force_pallas):
    q, k, v = _rand_qkv(jax.random.PRNGKey(5))
    bias = jax.random.normal(jax.random.PRNGKey(6), (1, 1, 128, 128))
    out = flash_attention(q, k, v, bias)
    ref = mha_reference(q, k, v, bias)
    np.testing.assert_allclose(out, ref, atol=2e-5, rtol=2e-5)


@pytest.mark.parametrize("causal", [False, True])
def test_grads_match_reference(force_pallas, causal):
    q, k, v = _rand_qkv(jax.random.PRNGKey(2), b=1, h=2, sq=128, sk=128, d=64)

    def loss_fused(q, k, v):
        return jnp.sum(flash_attention(q, k, v, causal=causal) ** 2)

    def loss_ref(q, k, v):
        return jnp.sum(mha_reference(q, k, v, causal=causal) ** 2)

    gf = jax.grad(loss_fused, argnums=(0, 1, 2))(q, k, v)
    gr = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(gf, gr):
        np.testing.assert_allclose(a, b, atol=5e-4, rtol=5e-4)


def test_grads_with_bias(force_pallas):
    q, k, v = _rand_qkv(jax.random.PRNGKey(3), b=1, h=1)
    bias = jax.random.normal(jax.random.PRNGKey(4), (1, 1, 128, 128)) * 0.1

    gf = jax.grad(lambda q: jnp.sum(flash_attention(q, k, v, bias)))(q)
    gr = jax.grad(lambda q: jnp.sum(mha_reference(q, k, v, bias)))(q)
    np.testing.assert_allclose(gf, gr, atol=5e-4, rtol=5e-4)


def test_cross_attention_shapes(force_pallas):
    q, k, v = _rand_qkv(jax.random.PRNGKey(7), sq=128, sk=256)
    out = flash_attention(q, k, v)
    ref = mha_reference(q, k, v)
    np.testing.assert_allclose(out, ref, atol=2e-5, rtol=2e-5)


def test_multi_block_long_seq(force_pallas):
    # >1 block in both q and k (blocks are 128): exercises the online-softmax
    # carry across the key grid dimension.
    q, k, v = _rand_qkv(jax.random.PRNGKey(8), b=1, h=1, sq=256, sk=384)
    out = flash_attention(q, k, v, causal=True)
    ref = mha_reference(q, k, v, causal=True)
    np.testing.assert_allclose(out, ref, atol=2e-5, rtol=2e-5)


def test_bf16_io(force_pallas):
    q, k, v = _rand_qkv(jax.random.PRNGKey(9), dtype=jnp.bfloat16)
    out = flash_attention(q, k, v)
    assert out.dtype == jnp.bfloat16
    ref = mha_reference(
        q.astype(jnp.float32), k.astype(jnp.float32), v.astype(jnp.float32)
    )
    np.testing.assert_allclose(
        out.astype(np.float32), ref, atol=3e-2, rtol=3e-2
    )


def test_dropout_falls_back_and_runs():
    q, k, v = _rand_qkv(jax.random.PRNGKey(10))
    rng = jax.random.PRNGKey(11)
    out = flash_attention(q, k, v, dropout_p=0.5, dropout_rng=rng)
    assert out.shape == q.shape
    # dropout is a no-op in expectation direction check: zero-prob path equals ref
    out0 = flash_attention(q, k, v, dropout_p=0.0)
    ref = mha_reference(q, k, v)
    np.testing.assert_allclose(out0, ref, atol=2e-5, rtol=2e-5)


def test_fmha_qkvpacked(force_pallas):
    b, s, h, d = 2, 128, 2, 64
    qkv = jax.random.normal(jax.random.PRNGKey(12), (b, s, 3, h, d))
    out = fmha_qkvpacked(qkv, causal=False)
    assert out.shape == (b, s, h, d)
    q, k, v = (jnp.moveaxis(qkv[:, :, i], 1, 2) for i in range(3))
    ref = jnp.moveaxis(mha_reference(q, k, v), 1, 2)
    np.testing.assert_allclose(out, ref, atol=2e-5, rtol=2e-5)


def test_odd_seq_uses_reference_path():
    # Non-tile-friendly seq length must still work (jnp fallback).
    q, k, v = _rand_qkv(jax.random.PRNGKey(13), sq=37, sk=53)
    out = flash_attention(q, k, v)
    ref = mha_reference(q, k, v)
    np.testing.assert_allclose(out, ref, atol=2e-5, rtol=2e-5)


def test_neg_inf_bias_first_block_fully_masked(force_pallas):
    """-inf additive bias (torch convention) on a whole leading key block.

    Regression: with the first 128-key block fully masked at -inf, the
    online softmax's running max stayed -inf and alpha = exp(-inf - -inf)
    poisoned the row with NaN.  The kernel clamps bias to MASK_VALUE.
    """
    q, k, v = _rand_qkv(jax.random.PRNGKey(7), sq=256, sk=256)
    bias = np.zeros((2, 1, 1, 256), np.float32)
    bias[:, :, :, :128] = -np.inf  # left padding: whole first k-block masked
    bias = jnp.asarray(np.broadcast_to(bias, (2, 1, 256, 256)))
    out = flash_attention(q, k, v, bias)
    assert bool(jnp.all(jnp.isfinite(out)))
    ref = mha_reference(q, k, v, jnp.maximum(bias, -1e9))
    np.testing.assert_allclose(out, ref, atol=2e-5, rtol=2e-5)

    # gradients stay finite too (bwd recompute uses the same clamp)
    g = jax.grad(lambda q_: jnp.sum(flash_attention(q_, k, v, bias) ** 2))(q)
    assert bool(jnp.all(jnp.isfinite(g)))


def test_neg_inf_bias_fallback_path_matches():
    """The jnp fallback (non-tile-friendly S) must share the clamp
    semantics: same -inf mask, S=120 routes to mha_reference internally."""
    q, k, v = _rand_qkv(jax.random.PRNGKey(8), sq=120, sk=120)
    bias = np.zeros((2, 1, 1, 120), np.float32)
    bias[1, :, :, :60] = -np.inf
    bias = jnp.asarray(bias)
    out = flash_attention(q, k, v, bias)
    assert bool(jnp.all(jnp.isfinite(out)))


def test_key_padding_bias_not_materialized(force_pallas):
    """(B, 1, 1, Sk) key-padding bias stays a single row per batch on the
    Pallas path (G=B, RS=1) — and matches the reference numerics."""
    q, k, v = _rand_qkv(jax.random.PRNGKey(9), b=3, h=4, sq=256, sk=256)
    bias = np.zeros((3, 1, 1, 256), np.float32)
    bias[0, :, :, 200:] = -1e9
    bias[2, :, :, 100:] = -1e9
    bias = jnp.asarray(bias)
    out = flash_attention(q, k, v, bias)
    ref = mha_reference(q, k, v, bias)
    np.testing.assert_allclose(out, ref, atol=2e-5, rtol=2e-5)
    gf = jax.grad(lambda q_: jnp.sum(flash_attention(q_, k, v, bias) ** 2))(q)
    gr = jax.grad(lambda q_: jnp.sum(mha_reference(q_, k, v, bias) ** 2))(q)
    np.testing.assert_allclose(gf, gr, atol=5e-4, rtol=1e-3)


def test_per_batch_full_bias_grouped(force_pallas):
    """(B, 1, Sq, Sk) bias uses the grouped index map (G=B) — no H-fold."""
    q, k, v = _rand_qkv(jax.random.PRNGKey(10), b=2, h=3, sq=128, sk=128)
    bias = jax.random.normal(jax.random.PRNGKey(11), (2, 1, 128, 128))
    out = flash_attention(q, k, v, bias)
    ref = mha_reference(q, k, v, bias)
    np.testing.assert_allclose(out, ref, atol=2e-5, rtol=2e-5)


@pytest.mark.parametrize(
    "bias_shape",
    [
        (1, 1, 128, 128),   # G=1,  RS=Sq (shared relative-position bias)
        (2, 1, 128, 128),   # G=B,  RS=Sq
        (2, 2, 128, 128),   # G=BH, RS=Sq (per-head bias)
        (1, 2, 128, 128),   # broadcast B -> G=BH with B-sum unbroadcast
        (1, 1, 1, 128),     # G=1,  RS=1  (shared key bias row)
        (2, 1, 1, 128),     # G=B,  RS=1  (key-padding-style trainable)
        (2, 2, 1, 128),     # G=BH, RS=1
    ],
)
@pytest.mark.parametrize("causal", [False, True])
def test_trainable_bias_grad_matches_reference(
    force_pallas, bias_shape, causal
):
    """dbias through the flash path (dedicated dbias kernel) vs the jnp
    composition, across every (G, RS) bias-group layout (VERDICT r2 #3;
    ≙ the reference's self_attn_bias additive-bias backward)."""
    q, k, v = _rand_qkv(jax.random.PRNGKey(20), b=2, h=2, sq=128, sk=128)
    bias = jax.random.normal(jax.random.PRNGKey(21), bias_shape) * 0.3

    def loss_fused(bias, q):
        return jnp.sum(
            flash_attention(q, k, v, bias, causal=causal, bias_grad=True)
            ** 2
        )

    def loss_ref(bias, q):
        return jnp.sum(mha_reference(q, k, v, bias, causal=causal) ** 2)

    db_f, dq_f = jax.grad(loss_fused, argnums=(0, 1))(bias, q)
    db_r, dq_r = jax.grad(loss_ref, argnums=(0, 1))(bias, q)
    assert db_f.shape == bias.shape
    np.testing.assert_allclose(db_f, db_r, atol=5e-4, rtol=5e-4)
    np.testing.assert_allclose(dq_f, dq_r, atol=5e-4, rtol=5e-4)
    # the cotangent is genuinely nonzero — the parity is not vacuous
    assert float(jnp.max(jnp.abs(db_f))) > 1e-6


def test_trainable_bias_multiblock(force_pallas):
    """dbias with a multi-block grid (Sq=Sk=256, blocks of 128) exercises
    the scratch accumulation across the inner group dim."""
    q, k, v = _rand_qkv(jax.random.PRNGKey(22), b=2, h=2, sq=256, sk=256)
    bias = jax.random.normal(jax.random.PRNGKey(23), (1, 2, 256, 256)) * 0.3

    db_f = jax.grad(
        lambda b_: jnp.sum(
            flash_attention(q, k, v, b_, causal=True, bias_grad=True) ** 2
        )
    )(bias)
    db_r = jax.grad(
        lambda b_: jnp.sum(mha_reference(q, k, v, b_, causal=True) ** 2)
    )(bias)
    np.testing.assert_allclose(db_f, db_r, atol=5e-4, rtol=5e-4)


def test_nontrainable_bias_zero_grad_on_flash_path(force_pallas):
    """Default (bias_grad=False) keeps the documented zero-cotangent
    contract on the flash path."""
    q, k, v = _rand_qkv(jax.random.PRNGKey(24), b=1, h=1)
    bias = jax.random.normal(jax.random.PRNGKey(25), (1, 1, 128, 128))
    db = jax.grad(
        lambda b_: jnp.sum(flash_attention(q, k, v, b_) ** 2)
    )(bias)
    np.testing.assert_allclose(np.asarray(db), 0.0)


@pytest.mark.parametrize("sq,sk", [(100, 100), (1000, 1000), (333, 259)])
@pytest.mark.parametrize("causal", [False, True])
def test_arbitrary_seq_kernel_parity(force_pallas, sq, sk, causal):
    """Arbitrary (non-tile-multiple) S runs the kernel via padding with
    masked keys (VERDICT r2 #4) and matches the unfused reference."""
    q, k, v = _rand_qkv(jax.random.PRNGKey(30), b=1, h=2, sq=sq, sk=sk)
    out = flash_attention(q, k, v, causal=causal)
    ref = mha_reference(q, k, v, causal=causal)
    np.testing.assert_allclose(out, ref, atol=2e-5, rtol=2e-5)


@pytest.mark.parametrize("sq,sk", [(100, 100), (333, 259)])
def test_arbitrary_seq_grads_parity(force_pallas, sq, sk):
    q, k, v = _rand_qkv(jax.random.PRNGKey(31), b=1, h=1, sq=sq, sk=sk)
    gf = jax.grad(
        lambda q_, k_, v_: jnp.sum(flash_attention(q_, k_, v_) ** 2),
        argnums=(0, 1, 2),
    )(q, k, v)
    gr = jax.grad(
        lambda q_, k_, v_: jnp.sum(mha_reference(q_, k_, v_) ** 2),
        argnums=(0, 1, 2),
    )(q, k, v)
    for a, b in zip(gf, gr):
        np.testing.assert_allclose(a, b, atol=5e-4, rtol=5e-4)


def test_arbitrary_seq_with_bias_parity(force_pallas):
    """User bias + padding compose: padded key columns stay masked, bias
    cotangent keeps the user's shape."""
    sq = sk = 100
    q, k, v = _rand_qkv(jax.random.PRNGKey(32), b=2, h=2, sq=sq, sk=sk)
    bias = jax.random.normal(jax.random.PRNGKey(33), (2, 1, sq, sk)) * 0.3
    out = flash_attention(q, k, v, bias)
    ref = mha_reference(q, k, v, bias)
    np.testing.assert_allclose(out, ref, atol=2e-5, rtol=2e-5)
    db_f = jax.grad(
        lambda b_: jnp.sum(
            flash_attention(q, k, v, b_, bias_grad=True) ** 2
        )
    )(bias)
    db_r = jax.grad(lambda b_: jnp.sum(mha_reference(q, k, v, b_) ** 2))(
        bias
    )
    assert db_f.shape == bias.shape
    np.testing.assert_allclose(db_f, db_r, atol=5e-4, rtol=5e-4)


def test_fully_masked_row_with_padded_keys(force_pallas):
    """A batch row whose key-padding bias masks EVERY real key, at an Sk
    that needs tile padding: the output must average V over the REAL keys
    (padded keys sit at PAD_VALUE < MASK_VALUE and underflow out), matching
    the unpadded reference."""
    sq = sk = 100  # pads to 104
    q, k, v = _rand_qkv(jax.random.PRNGKey(35), b=2, h=1, sq=sq, sk=sk)
    bias = np.zeros((2, 1, 1, sk), np.float32)
    bias[1] = -np.inf  # batch 1: all real keys masked
    bias = jnp.asarray(bias)
    out = flash_attention(q, k, v, bias)
    ref = mha_reference(q, k, v, jnp.maximum(bias, -1e9))
    np.testing.assert_allclose(out, ref, atol=2e-5, rtol=2e-5)


def test_causal_short_keys_unaligned_falls_back(force_pallas):
    """The one documented jnp corner: causal, Sq > Sk, Sk needs padding —
    fully-masked rows average V over the REAL Sk."""
    q, k, v = _rand_qkv(jax.random.PRNGKey(34), b=1, h=1, sq=100, sk=50)
    out = flash_attention(q, k, v, causal=True)
    ref = mha_reference(q, k, v, causal=True)
    np.testing.assert_allclose(out, ref, atol=2e-5, rtol=2e-5)


class TestFusedDropout:
    """In-kernel attention dropout (≙ the reference's philox dropout in
    the fused MHA kernels).  The mask is extracted exactly by setting
    V = I, which makes o = D ⊙ softmax(s): each output element IS the
    dropped, rescaled probability."""

    def _qkv_ident(self, key, s=128):
        kq, kk = jax.random.split(key)
        q = jax.random.normal(kq, (1, 1, s, s))
        k = jax.random.normal(kk, (1, 1, s, s))
        v = jnp.eye(s)[None, None]
        return q, k, v

    def test_mask_semantics_and_rate(self, force_pallas):
        p = 0.15
        q, k, v = self._qkv_ident(jax.random.PRNGKey(40))
        rng = jax.random.PRNGKey(41)
        probs = flash_attention(q, k, v)  # = softmax(s), no dropout
        out = flash_attention(q, k, v, dropout_p=p, dropout_rng=rng)
        mask = np.asarray(out) != 0.0
        rate = mask.mean()
        assert abs(rate - (1 - p)) < 0.03, rate  # binomial, 16k draws
        # kept entries are exactly probs/(1-p); dropped are exactly 0
        np.testing.assert_allclose(
            np.asarray(out),
            np.where(mask, np.asarray(probs) / (1 - p), 0.0),
            atol=1e-6, rtol=1e-5,
        )

    def test_deterministic_and_rng_dependent(self, force_pallas):
        q, k, v = self._qkv_ident(jax.random.PRNGKey(42))
        r1, r2 = jax.random.PRNGKey(1), jax.random.PRNGKey(2)
        a = flash_attention(q, k, v, dropout_p=0.3, dropout_rng=r1)
        b = flash_attention(q, k, v, dropout_p=0.3, dropout_rng=r1)
        c = flash_attention(q, k, v, dropout_p=0.3, dropout_rng=r2)
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
        assert not np.array_equal(np.asarray(a), np.asarray(c))

    def test_mask_varies_per_batch_head(self, force_pallas):
        s = 128
        q = jax.random.normal(jax.random.PRNGKey(43), (2, 2, s, s))
        k = jax.random.normal(jax.random.PRNGKey(44), (2, 2, s, s))
        v = jnp.broadcast_to(jnp.eye(s), (2, 2, s, s))
        out = np.asarray(
            flash_attention(
                q, k, v, dropout_p=0.3, dropout_rng=jax.random.PRNGKey(3)
            )
        )
        masks = (out != 0.0).reshape(4, -1)
        for i in range(4):
            for j in range(i + 1, 4):
                assert not np.array_equal(masks[i], masks[j]), (i, j)

    def test_grads_consistent_with_forward(self, force_pallas):
        """The hand-written backward (mask regenerated in dkdv/dq kernels)
        must match numerical differentiation of the actual forward."""
        from jax.test_util import check_grads

        kq, kk, kv = jax.random.split(jax.random.PRNGKey(45), 3)
        q = jax.random.normal(kq, (1, 1, 128, 32))
        k = jax.random.normal(kk, (1, 1, 128, 32))
        v = jax.random.normal(kv, (1, 1, 128, 32))
        rng = jax.random.PRNGKey(7)

        def f(q, k, v):
            return flash_attention(
                q, k, v, dropout_p=0.25, dropout_rng=rng
            ).astype(jnp.float32)

        check_grads(f, (q, k, v), order=1, modes=["rev"],
                    atol=1e-2, rtol=1e-2)

    def test_dropout_with_trainable_bias_grads(self, force_pallas):
        """dropout + bias_grad compose: dbias kernel applies the same
        mask (checked against numerical diff)."""
        from jax.test_util import check_grads

        kq, kk, kv, kb = jax.random.split(jax.random.PRNGKey(46), 4)
        q = jax.random.normal(kq, (1, 2, 128, 32))
        k = jax.random.normal(kk, (1, 2, 128, 32))
        v = jax.random.normal(kv, (1, 2, 128, 32))
        bias = jax.random.normal(kb, (1, 2, 128, 128)) * 0.3
        rng = jax.random.PRNGKey(8)

        def f(bias):
            return flash_attention(
                q, k, v, bias, dropout_p=0.2, dropout_rng=rng,
                bias_grad=True,
            ).astype(jnp.float32)

        check_grads(f, (bias,), order=1, modes=["rev"],
                    atol=1e-2, rtol=1e-2)

    def test_keep_mask_hash_no_long_context_aliasing(self):
        """The keyed pair-hash must not correlate positions at long-
        context coordinates (the linear-counter scheme aliased
        (r, c+65537) with (r+1, c)); also sane keep-rate far from the
        origin."""
        from apex_tpu.ops.pallas.flash_attention import (
            _dropout_keep_block,
        )

        seed = jnp.asarray(1234, jnp.int32)
        bh = jnp.asarray(3, jnp.int32)
        bq = bk = 128
        # two tiles starting beyond the 2^16 boundary in both dims
        i1, j1 = 512, 513  # rows/cols ~65.5k
        m1 = np.asarray(
            _dropout_keep_block(seed, bh, i1, j1, bq, bk, 0.5)
        )
        # the tile one row down, one "aliasing constant" right — under
        # the old scheme shifted copies of the same mask appear
        m2 = np.asarray(
            _dropout_keep_block(seed, bh, i1 + 1, j1, bq, bk, 0.5)
        )
        assert not np.array_equal(m1, m2)
        # no shifted-copy correlation: agreement stays near 50% for a
        # p=0.5 mask (aliasing would give long identical runs)
        agree = (m1[1:, :] == m2[:-1, :]).mean()
        assert 0.4 < agree < 0.6, agree
        # keep-rate far from origin within binomial noise
        rate = m1.mean()
        assert abs(rate - 0.5) < 0.04, rate

    def test_dropout_with_causal_and_padding(self, force_pallas):
        """dropout composes with the causal mask and arbitrary-S padding:
        zero positions stay a superset of the causal zeros, kept entries
        scale by 1/(1-p)."""
        s = 100  # pads to 104
        q, k, v = self._qkv_ident(jax.random.PRNGKey(47), s=s)
        rng = jax.random.PRNGKey(9)
        probs = flash_attention(q, k, v, causal=True)
        out = flash_attention(
            q, k, v, causal=True, dropout_p=0.2, dropout_rng=rng
        )
        mask = np.asarray(out) != 0.0
        np.testing.assert_allclose(
            np.asarray(out),
            np.where(mask, np.asarray(probs) / 0.8, 0.0),
            atol=1e-6, rtol=1e-5,
        )
        # upper triangle (causal-masked) stays all zero
        upper = np.triu(np.ones((s, s), bool), k=1)
        assert not np.asarray(out)[0, 0][upper].any()


class TestFlashAttentionWithLse:
    """flash_attention_with_lse: (o, lse) values AND the dlse backward
    (the ring-attention merge differentiates through lse)."""

    @pytest.mark.parametrize("causal", [False, True])
    def test_values_match_reference(self, force_pallas, causal):
        from apex_tpu.ops.attention import (
            flash_attention_with_lse,
            mha_reference_with_lse,
        )

        q, k, v = _rand_qkv(jax.random.PRNGKey(3))
        o, lse = jax.jit(
            lambda q, k, v: flash_attention_with_lse(q, k, v, causal=causal)
        )(q, k, v)
        _dispatch.set_use_pallas(False)
        ow, lw = mha_reference_with_lse(q, k, v, causal=causal)
        np.testing.assert_allclose(
            np.asarray(o), np.asarray(ow), atol=2e-5, rtol=2e-5
        )
        np.testing.assert_allclose(
            np.asarray(lse), np.asarray(lw), atol=2e-5, rtol=2e-5
        )

    def test_key_padding_bias_matches_reference(self, force_pallas):
        """(B, 1, 1, Sk) key-padding bias on the with-lse path: kernel
        vs jnp composition for (o, lse) AND grads (the bias is the
        additive-mask form — its own cotangent is zero)."""
        from apex_tpu.ops.attention import (
            flash_attention_with_lse,
            mha_reference_with_lse,
        )
        from apex_tpu.ops.pallas.flash_attention import MASK_VALUE

        q, k, v = _rand_qkv(jax.random.PRNGKey(11))
        keep = jax.random.bernoulli(
            jax.random.PRNGKey(12), 0.8, (2, 1, 1, 128)
        ).at[..., 0].set(True)  # every row keeps key 0
        bias = jnp.where(keep, 0.0, MASK_VALUE)

        def loss(fn, q, k, v):
            o, lse = fn(q, k, v, bias)
            return jnp.sum(o.astype(jnp.float32) ** 2) + jnp.sum(lse), (o, lse)

        (_, (o, lse)), g = jax.value_and_grad(
            lambda q, k, v: loss(flash_attention_with_lse, q, k, v),
            argnums=(0, 1, 2), has_aux=True,
        )(q, k, v)
        _dispatch.set_use_pallas(False)
        (_, (ow, lw)), gw = jax.value_and_grad(
            lambda q, k, v: loss(mha_reference_with_lse, q, k, v),
            argnums=(0, 1, 2), has_aux=True,
        )(q, k, v)
        np.testing.assert_allclose(
            np.asarray(o), np.asarray(ow), atol=2e-5, rtol=2e-5
        )
        np.testing.assert_allclose(
            np.asarray(lse), np.asarray(lw), atol=2e-5, rtol=2e-5
        )
        for a, b_ in zip(g, gw):
            np.testing.assert_allclose(
                np.asarray(a), np.asarray(b_), atol=5e-5, rtol=5e-5
            )
        # masked keys contribute nothing: their dk/dv are exactly zero
        dk = np.asarray(g[1])
        masked_cols = ~np.asarray(keep)[:, 0, 0]  # (B, Sk)
        for bi in range(2):
            np.testing.assert_allclose(
                dk[bi][:, masked_cols[bi]], 0.0, atol=1e-6
            )

    @pytest.mark.parametrize("causal", [False, True])
    def test_grads_include_lse_cotangent(self, force_pallas, causal):
        """A loss that consumes BOTH outputs — the lse term exercises the
        delta - dlse folding in flash_bwd."""
        from apex_tpu.ops.attention import (
            flash_attention_with_lse,
            mha_reference_with_lse,
        )

        q, k, v = _rand_qkv(jax.random.PRNGKey(4))

        def loss(fn, q, k, v):
            o, lse = fn(q, k, v, causal=causal)
            return jnp.sum(o.astype(jnp.float32) ** 2) + jnp.sum(
                jnp.sin(lse)
            )

        got = jax.jit(
            jax.grad(
                lambda q, k, v: loss(flash_attention_with_lse, q, k, v),
                argnums=(0, 1, 2),
            )
        )(q, k, v)
        _dispatch.set_use_pallas(False)
        want = jax.grad(
            lambda q, k, v: loss(mha_reference_with_lse, q, k, v),
            argnums=(0, 1, 2),
        )(q, k, v)
        for g, w in zip(got, want):
            np.testing.assert_allclose(
                np.asarray(g), np.asarray(w), atol=5e-5, rtol=5e-5
            )


class TestIndependentDqTiles:
    """flash_bwd's dq pallas_call can take tile sizes independent of the
    dkdv one (block_q_dq/block_k_dq — the tuner's backward lever); the
    results must be bitwise-insensitive to the tile choice."""

    @pytest.mark.parametrize("dropout_p", [0.0, 0.2])
    def test_dq_tiles_do_not_change_grads(self, force_pallas, dropout_p):
        from apex_tpu.ops.pallas import flash_attention as fa

        sq = 256
        q, k, v = _rand_qkv(jax.random.PRNGKey(9), b=1, h=2, sq=sq, sk=sq)
        q, k, v = (x.reshape(2, sq, 64) for x in (q, k, v))
        scale = 64 ** -0.5
        kw = dict(scale=scale, causal=True, dropout_p=dropout_p)
        seed = dict(dropout_seed=7) if dropout_p else {}
        o, lse = fa.flash_fwd(
            q, k, v, None, block_q=128, block_k=128, **kw, **seed
        )
        do = 2.0 * o
        base = fa.flash_bwd(
            q, k, v, o, lse, do, None, block_q=128, block_k=128,
            **kw, **seed,
        )
        for bq_dq, bk_dq in ((256, 128), (128, 256), (256, 256)):
            alt = fa.flash_bwd(
                q, k, v, o, lse, do, None, block_q=128, block_k=128,
                block_q_dq=bq_dq, block_k_dq=bk_dq, **kw, **seed,
            )
            # dq numerics may differ only by f32 accumulation order
            np.testing.assert_allclose(
                np.asarray(alt[0]), np.asarray(base[0]),
                atol=2e-5, rtol=2e-5,
            )
            # dk/dv come from the UNCHANGED dkdv call: bit-identical
            for a, b in zip(alt[1:], base[1:]):
                np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


class TestTunedTileTable:
    """_TUNED_TILES (the attn_tune → kernel landing table, ≙ the
    reference's per-shape kernel-traits tables): entries route tile
    selection away from the _auto_block heuristic without changing
    numerics."""

    def test_table_entries_are_consulted_and_numerics_unchanged(
        self, force_pallas, monkeypatch
    ):
        from apex_tpu.ops.pallas import flash_attention as fa

        sq, d = 256, 64
        q, k, v = _rand_qkv(jax.random.PRNGKey(12), b=1, h=2, sq=sq, sk=sq)
        q, k, v = (x.reshape(2, sq, d) for x in (q, k, v))
        kw = dict(scale=d ** -0.5, causal=True)
        o_ref, lse_ref = fa.flash_fwd(q, k, v, None, **kw)
        base = fa.flash_bwd(q, k, v, o_ref, lse_ref, 2.0 * o_ref, None, **kw)

        monkeypatch.setitem(
            fa._TUNED_TILES, (sq, d, True),
            {"fwd": (128, 128), "bwd": (128, 128), "bwd_dq": (256, 128)},
        )

        def boom(*a, **k):
            raise AssertionError(
                "_auto_block consulted despite a tuned-table entry"
            )

        monkeypatch.setattr(fa, "_auto_block", boom)
        # fresh shapes would hit the jit cache of the un-patched trace;
        # clear so the lookup runs under the patched table — and ALWAYS
        # clear again on exit so a failing assert can't leave
        # tuned-tile traces live for later tests of the same shape
        fa.flash_fwd.clear_cache()
        fa.flash_bwd.clear_cache()
        try:
            o, lse = fa.flash_fwd(q, k, v, None, **kw)
            alt = fa.flash_bwd(q, k, v, o, lse, 2.0 * o, None, **kw)
            np.testing.assert_allclose(
                np.asarray(o), np.asarray(o_ref), atol=2e-5, rtol=2e-5
            )
            for a, b in zip(alt, base):
                np.testing.assert_allclose(
                    np.asarray(a), np.asarray(b), atol=2e-5, rtol=2e-5
                )
        finally:
            fa.flash_fwd.clear_cache()
            fa.flash_bwd.clear_cache()

    def test_cross_attention_nondividing_tuned_tile_falls_back(
        self, force_pallas, monkeypatch
    ):
        """A tuned entry measured on self-attention must not hand a
        non-dividing bk to a cross-attention call's sk (the kernels
        have no partial-tile masking): the per-axis divisibility guard
        drops the tile and numerics stay correct."""
        from apex_tpu.ops.pallas import flash_attention as fa

        sq, sk, d = 256, 384, 64  # sk % 256 != 0
        kq, kk, kv = jax.random.split(jax.random.PRNGKey(13), 3)
        q = jax.random.normal(kq, (2, sq, d))
        k = jax.random.normal(kk, (2, sk, d))
        v = jax.random.normal(kv, (2, sk, d))
        kw = dict(scale=d ** -0.5, causal=False)
        base, _ = fa.flash_fwd(q, k, v, None, block_q=128, block_k=128, **kw)
        monkeypatch.setitem(
            fa._TUNED_TILES, (sq, d, False), {"fwd": (256, 256)}
        )
        fa.flash_fwd.clear_cache()
        try:
            o, _ = fa.flash_fwd(q, k, v, None, **kw)
            np.testing.assert_allclose(
                np.asarray(o), np.asarray(base), atol=2e-5, rtol=2e-5
            )
        finally:
            fa.flash_fwd.clear_cache()


# ---------------------------------------------------------------------------
# Paged single-query decode attention (the serving kernel,
# ops/pallas/decode_attention.py — docs/serving.md)
# ---------------------------------------------------------------------------


class TestPagedDecodeAttention:
    """The decode kernel must agree with its gather-based jnp reference
    AND with plain full-context attention on the equivalent contiguous
    history — paging and online softmax are layout, not math."""

    def _paged_case(self, key, b=3, h=4, d=32, page=8, pool=12, np_=3,
                    lengths=(17, 9, 0)):
        import numpy as np_mod

        rs = np_mod.random.RandomState(int(key))
        k_pages = jnp.asarray(rs.randn(pool, h, page, d), jnp.float32)
        v_pages = jnp.asarray(rs.randn(pool, h, page, d), jnp.float32)
        q = jnp.asarray(rs.randn(b, h, d), jnp.float32)
        # distinct non-null pages per live sequence
        table = jnp.asarray(
            rs.permutation(pool - 1)[: b * np_].reshape(b, np_) + 1,
            jnp.int32,
        )
        return q, k_pages, v_pages, table, jnp.asarray(lengths, jnp.int32)

    def test_kernel_matches_reference(self, force_pallas):
        from apex_tpu.ops.paged_attention import (
            paged_decode_attention,
            paged_decode_attention_reference,
        )

        q, kp, vp, table, lengths = self._paged_case(0)
        out = paged_decode_attention(q, kp, vp, table, lengths)
        ref = paged_decode_attention_reference(q, kp, vp, table, lengths)
        np.testing.assert_allclose(out, ref, atol=2e-6, rtol=2e-6)

    def test_matches_contiguous_attention(self, force_pallas):
        """Sequence 0's paged output == mha_reference over the pages
        gathered back into a contiguous (1, H, S, D) history."""
        from apex_tpu.ops.paged_attention import paged_decode_attention

        q, kp, vp, table, lengths = self._paged_case(1)
        out = paged_decode_attention(q, kp, vp, table, lengths)
        s0 = int(lengths[0])
        page = kp.shape[2]
        kc = jnp.moveaxis(kp[table[0]], 0, 1).reshape(
            kp.shape[1], -1, kp.shape[3]
        )[None, :, :s0]
        vc = jnp.moveaxis(vp[table[0]], 0, 1).reshape(
            vp.shape[1], -1, vp.shape[3]
        )[None, :, :s0]
        ref = mha_reference(
            q[0][None, :, None, :], kc, vc, scale=q.shape[-1] ** -0.5
        )
        np.testing.assert_allclose(
            out[0], ref[0, :, 0], atol=2e-6, rtol=2e-6
        )
        del page

    def test_fused_rope_matches_pre_rotated_query(self, force_pallas):
        """In-kernel q RoPE == rotating q first and attending plain."""
        from apex_tpu.ops.paged_attention import paged_decode_attention
        from apex_tpu.ops.rope import rotate_half

        q, kp, vp, table, lengths = self._paged_case(2)
        rs = np.random.RandomState(9)
        cos = jnp.asarray(rs.randn(q.shape[0], q.shape[2]), jnp.float32)
        sin = jnp.asarray(rs.randn(q.shape[0], q.shape[2]), jnp.float32)
        fused = paged_decode_attention(
            q, kp, vp, table, lengths, rope_cos=cos, rope_sin=sin
        )
        q_rot = q * cos[:, None, :] + rotate_half(q) * sin[:, None, :]
        plain = paged_decode_attention(q_rot, kp, vp, table, lengths)
        np.testing.assert_allclose(fused, plain, atol=2e-6, rtol=2e-6)

    def test_int8_kv_dequant_matches_reference(self, force_pallas):
        """In-kernel int8 dequant == the reference's gather+dequant,
        and both sit near the f32 cache (codec quantization noise
        only)."""
        from apex_tpu.ops.paged_attention import (
            paged_decode_attention,
            paged_decode_attention_reference,
        )
        from apex_tpu.serve.cache import encode_kv

        q, kp, vp, table, lengths = self._paged_case(3)
        kq, ks = encode_kv(kp)
        vq, vs = encode_kv(vp)
        out = paged_decode_attention(
            q, kq, vq, table, lengths, k_scale=ks, v_scale=vs
        )
        ref = paged_decode_attention_reference(
            q, kq, vq, table, lengths, k_scale=ks, v_scale=vs
        )
        np.testing.assert_allclose(out, ref, atol=2e-6, rtol=2e-6)
        f32 = paged_decode_attention(q, kp, vp, table, lengths)
        assert float(jnp.abs(out - f32).max()) < 5e-2

    def test_idle_slot_returns_zeros(self, force_pallas):
        from apex_tpu.ops.paged_attention import paged_decode_attention

        q, kp, vp, table, lengths = self._paged_case(4)
        out = paged_decode_attention(q, kp, vp, table, lengths)
        assert float(jnp.abs(out[2]).max()) == 0.0  # lengths[2] == 0

    def test_jnp_dispatch_default_off_tpu(self):
        """Auto mode off-TPU routes to the gather-based jnp path (the
        kernel runs interpret-mode only when forced or on real TPU)."""
        from apex_tpu.ops import paged_attention as pa

        q, kp, vp, table, lengths = self._paged_case(5)
        pa.paged_decode_attention(q, kp, vp, table, lengths)
        assert _dispatch.last_paths()["paged_decode_attention"] == "jnp"
