"""Checkpoint / resume subsystem (≙ SURVEY §5 checkpoint row).

Covers the reference's four persistence pieces (params, optimizer state,
amp scaler state_dict, RNG tracker states) plus the TPU-native additions:
sharded save/restore over the 8-device mesh and manager retention.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from apex_tpu import checkpoint as ckpt


def _tree_close(a, b):
    la = jax.tree_util.tree_leaves(a)
    lb = jax.tree_util.tree_leaves(b)
    assert len(la) == len(lb)
    for x, y in zip(la, lb):
        np.testing.assert_allclose(np.asarray(x), np.asarray(y), rtol=1e-6)


def test_save_restore_roundtrip(tmp_path):
    state = {
        "w": jnp.arange(12.0).reshape(3, 4),
        "nested": {"b": jnp.ones((5,), jnp.bfloat16), "n": np.int64(7)},
    }
    ckpt.save_checkpoint(tmp_path / "c1", state)
    out = ckpt.restore_checkpoint(tmp_path / "c1")
    _tree_close(state, out)
    assert np.asarray(out["nested"]["b"]).dtype == jnp.bfloat16


def test_sharded_roundtrip_and_reshard(tmp_path, eight_devices):
    mesh = Mesh(np.array(eight_devices).reshape(4, 2), ("dp", "tp"))
    sharding = NamedSharding(mesh, P("dp", "tp"))
    x = jax.device_put(jnp.arange(64.0).reshape(8, 8), sharding)
    ckpt.save_checkpoint(tmp_path / "c", {"x": x})

    # restore with the original sharding
    tmpl = {"x": jax.ShapeDtypeStruct((8, 8), jnp.float32, sharding=sharding)}
    out = ckpt.restore_checkpoint(tmp_path / "c", template=tmpl)
    assert out["x"].sharding == sharding
    _tree_close({"x": x}, out)

    # restore re-sharded onto a different layout (tp-major)
    mesh2 = Mesh(np.array(eight_devices).reshape(2, 4), ("dp", "tp"))
    sh2 = NamedSharding(mesh2, P("tp", None))
    tmpl2 = {"x": jax.ShapeDtypeStruct((8, 8), jnp.float32, sharding=sh2)}
    out2 = ckpt.restore_checkpoint(tmp_path / "c", template=tmpl2)
    assert out2["x"].sharding == sh2
    _tree_close({"x": x}, out2)


def test_manager_retention_and_latest(tmp_path):
    state = {"w": jnp.zeros((2,))}
    with ckpt.CheckpointManager(
        tmp_path, max_to_keep=2, save_interval_steps=2
    ) as mgr:
        for step in range(6):
            saved = mgr.save(step, {"w": state["w"] + step})
            assert saved == (step % 2 == 0)  # interval policy
        mgr.wait_until_finished()
        assert mgr.latest_step() == 4
        assert mgr.all_steps() == [2, 4]  # max_to_keep pruned step 0
        out = mgr.restore(template=state)  # default = latest
        np.testing.assert_allclose(np.asarray(out["w"]), [4.0, 4.0])


def test_latest_step_ignores_uncommitted_debris(tmp_path):
    """Crash consistency: orbax-style temp directories from an interrupted
    save (and other non-step junk) are invisible to step enumeration, and
    restore of the newest COMPLETE step still works."""
    import os

    state = {"w": jnp.zeros((2,))}
    with ckpt.CheckpointManager(tmp_path) as mgr:
        for step in range(3):
            mgr.save(step, {"w": state["w"] + step})
        mgr.wait_until_finished()
    # a host died mid-save of step 3: uncommitted tmp dir + stray file
    debris = tmp_path / f"3.orbax-checkpoint-tmp-{os.getpid()}"
    debris.mkdir()
    (debris / "params").write_text("torn write")
    (tmp_path / "not_a_step").mkdir()
    assert ckpt.all_steps(tmp_path) == [0, 1, 2]
    assert ckpt.latest_step(tmp_path) == 2
    with ckpt.CheckpointManager(tmp_path) as mgr:
        assert mgr.latest_step() == 2
        out = mgr.restore(template=state)
        np.testing.assert_allclose(np.asarray(out["w"]), [2.0, 2.0])


def test_half_written_step_dir_is_invisible(tmp_path):
    """Crash consistency, the harder shape: a crash that got as far as
    CREATING the step directory (non-atomic fs, torn non-orbax write)
    but never committed.  Orbax's own enumeration would report it as a
    valid step; ours must not — resume has to pick the previous
    COMPLETE step, and restoring the planted step must refuse."""
    state = {"w": jnp.zeros((2,))}
    with ckpt.CheckpointManager(tmp_path) as mgr:
        for step in range(3):
            mgr.save(step, {"w": state["w"] + step})
        mgr.wait_until_finished()
    # a half-written step 7: digit-named dir, payload bytes, NO commit
    # marker — newer than every complete step
    partial = tmp_path / "7"
    partial.mkdir()
    (partial / "params").write_text("torn half-written payload")
    assert ckpt.all_steps(tmp_path) == [0, 1, 2]
    assert ckpt.latest_step(tmp_path) == 2
    with pytest.raises(FileNotFoundError, match="incomplete"):
        ckpt.restore_step_dir(tmp_path, 7, template=state)
    with ckpt.CheckpointManager(tmp_path) as mgr:
        assert mgr.latest_step() == 2  # the manager surface agrees
        out = mgr.restore(template=state)
        np.testing.assert_allclose(np.asarray(out["w"]), [2.0, 2.0])


def test_manager_restore_empty_raises(tmp_path):
    with ckpt.CheckpointManager(tmp_path / "empty") as mgr:
        with pytest.raises(FileNotFoundError):
            mgr.restore()


def test_training_state_snapshot_resume(tmp_path):
    """End-to-end resume: params+opt+amp scaler+RNG tracker round-trip."""
    import optax

    from apex_tpu import amp
    from apex_tpu.transformer.tensor_parallel.random import (
        get_tpu_rng_tracker,
        model_parallel_tpu_manual_seed,
    )

    params = {"w": jnp.ones((4, 4)), "b": jnp.zeros((4,))}
    cast_params, handle = amp.initialize(
        params, optax.sgd(0.1), opt_level="O2", loss_scale="dynamic"
    )
    amp_state = handle.init(params)
    model_parallel_tpu_manual_seed(1234, tp_rank=0)
    tracker = get_tpu_rng_tracker()
    k_before = tracker.fork()  # advance the stream past its seed state

    state = ckpt.snapshot_training_state(
        cast_params,
        amp_state.opt_state,
        step=17,
        amp_handle=handle,
        amp_state=amp_state,
        extra={"master": amp_state.master_params},
    )
    ckpt.save_checkpoint(tmp_path / "snap", state)

    # clobber everything, then restore
    tracker.reset()
    restored = ckpt.restore_checkpoint(tmp_path / "snap")
    r_params, r_opt, r_step, r_amp_state, r_extra = (
        ckpt.restore_training_state(
            restored, amp_handle=handle, amp_state=amp_state
        )
    )
    assert r_step == 17
    _tree_close(cast_params, r_params)
    _tree_close(amp_state.opt_state, r_opt)
    _tree_close(amp_state.master_params, r_extra["master"])
    np.testing.assert_allclose(
        np.asarray(r_amp_state.scaler_state.loss_scale),
        np.asarray(amp_state.scaler_state.loss_scale),
    )
    # the tracker resumes mid-stream: next fork matches a non-restored
    # tracker that was advanced the same number of times
    k_after = tracker.fork()
    model_parallel_tpu_manual_seed(1234, tp_rank=0)
    tracker.fork()
    k_ref = tracker.fork()
    np.testing.assert_array_equal(np.asarray(k_after), np.asarray(k_ref))
    assert not np.array_equal(np.asarray(k_before), np.asarray(k_after))
