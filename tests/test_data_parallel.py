"""≙ tests/distributed/DDP + synced_batchnorm + contrib DistributedFusedAdam
tests — DP equivalence on the 8-device CPU mesh."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

from apex_tpu import parallel_state as ps
from apex_tpu.optimizers import fused_adam, fused_lamb
from apex_tpu.parallel import (
    DistributedDataParallel,
    DistributedFusedAdam,
    DistributedFusedLAMB,
    SyncBatchNorm,
    all_reduce_gradients,
)


def toy_loss(params, batch):
    x, y = batch["x"], batch["y"]
    pred = jnp.tanh(x @ params["w1"]) @ params["w2"]
    return jnp.mean((pred - y) ** 2)


def toy_setup(n=64):
    rng = np.random.RandomState(0)
    params = {
        "w1": jnp.asarray(rng.randn(8, 16) * 0.3, jnp.float32),
        "w2": jnp.asarray(rng.randn(16, 4) * 0.3, jnp.float32),
    }
    batch = {
        "x": jnp.asarray(rng.randn(n, 8), jnp.float32),
        "y": jnp.asarray(rng.randn(n, 4), jnp.float32),
    }
    return params, batch


def test_ddp_grads_match_single_device(eight_devices):
    mesh = ps.initialize_model_parallel()  # dp=8
    params, batch = toy_setup()
    ddp = DistributedDataParallel(toy_loss)

    f = jax.jit(
        jax.shard_map(
            ddp.value_and_grad,
            mesh=mesh,
            in_specs=(P(), P("dp")),
            out_specs=(P(), P()),
        )
    )
    loss_dp, grads_dp = f(params, batch)
    loss_ref, grads_ref = jax.value_and_grad(toy_loss)(params, batch)
    np.testing.assert_allclose(float(loss_dp), float(loss_ref), rtol=1e-5)
    for a, r in zip(
        jax.tree_util.tree_leaves(grads_dp), jax.tree_util.tree_leaves(grads_ref)
    ):
        np.testing.assert_allclose(np.asarray(a), np.asarray(r), rtol=1e-5,
                                   atol=1e-6)


def test_ddp_make_step_trains(eight_devices):
    mesh = ps.initialize_model_parallel()
    params, batch = toy_setup()
    tx = fused_adam(5e-2)
    opt_state = tx.init(params)
    ddp = DistributedDataParallel(toy_loss)
    step = ddp.make_step(tx, mesh)
    losses = []
    for _ in range(20):
        params, opt_state, loss = step(params, opt_state, batch)
        losses.append(float(loss))
    assert losses[-1] < 0.5 * losses[0]


def test_predivide_factor(eight_devices):
    mesh = ps.initialize_model_parallel()
    g = {"w": jnp.ones((8, 4))}

    def f(g):
        return all_reduce_gradients(g, gradient_predivide_factor=2.0)

    out = jax.shard_map(
        f, mesh=mesh, in_specs=(P("dp"),), out_specs=P("dp")
    )(g)
    # predivide by 2, psum (x8), postdivide by 8/2=4 -> mean preserved
    np.testing.assert_allclose(np.asarray(out["w"]), 1.0, rtol=1e-6)


def test_delay_allreduce_returns_local_grads(eight_devices):
    mesh = ps.initialize_model_parallel()
    params, batch = toy_setup()
    ddp = DistributedDataParallel(toy_loss, delay_allreduce=True,
                                  gradient_average=False)

    def f(p, b):
        _, g = ddp.value_and_grad(p, b)
        # local grads differ per shard; psum afterwards == full-batch sum
        return jax.tree_util.tree_map(
            lambda x: jax.lax.psum(x, "dp") / 8.0, g
        )

    out = jax.jit(
        jax.shard_map(f, mesh=mesh, in_specs=(P(), P("dp")), out_specs=P())
    )(params, batch)
    _, ref = jax.value_and_grad(toy_loss)(params, batch)
    np.testing.assert_allclose(
        np.asarray(out["w1"]), np.asarray(ref["w1"]), rtol=1e-5, atol=1e-6
    )


# ---------------------------------------------------------------------------
# SyncBatchNorm ≙ tests/distributed/synced_batchnorm
# ---------------------------------------------------------------------------


def test_syncbn_matches_full_batch_bn(eight_devices):
    mesh = ps.initialize_model_parallel()
    rng = np.random.RandomState(1)
    x = jnp.asarray(rng.randn(32, 6) * 2 + 1, jnp.float32)
    bn = SyncBatchNorm(features=6, momentum=0.1)
    variables = bn.init(jax.random.PRNGKey(0), x, use_running_average=False)

    # single-device full batch (plain BN math)
    y_ref, mut_ref = bn.apply(
        variables, x, use_running_average=False, mutable=["batch_stats"]
    )

    # 8-way sharded batch through shard_map: same stats via psum
    def f(v, x):
        y, mut = bn.apply(
            v, x, use_running_average=False, mutable=["batch_stats"]
        )
        return y, mut

    y_dp, mut_dp = jax.jit(
        jax.shard_map(
            f, mesh=mesh, in_specs=(P(), P("dp")), out_specs=(P("dp"), P())
        )
    )(variables, x)
    np.testing.assert_allclose(
        np.asarray(y_dp), np.asarray(y_ref), rtol=1e-4, atol=1e-5
    )
    np.testing.assert_allclose(
        np.asarray(mut_dp["batch_stats"]["mean"]),
        np.asarray(mut_ref["batch_stats"]["mean"]),
        rtol=1e-4, atol=1e-6,
    )
    np.testing.assert_allclose(
        np.asarray(mut_dp["batch_stats"]["var"]),
        np.asarray(mut_ref["batch_stats"]["var"]),
        rtol=1e-4, atol=1e-6,
    )


def test_syncbn_eval_uses_running_stats():
    x = jnp.asarray(np.random.RandomState(2).randn(16, 3), jnp.float32)
    bn = SyncBatchNorm(features=3)
    v = bn.init(jax.random.PRNGKey(0), x, use_running_average=False)
    y = bn.apply(v, x, use_running_average=True)
    # fresh stats: mean 0 var 1 -> identity (affine init is identity too)
    np.testing.assert_allclose(np.asarray(y), np.asarray(x), rtol=1e-4,
                               atol=1e-5)


def test_syncbn_bad_channels_raises():
    bn = SyncBatchNorm(features=5)
    with pytest.raises(ValueError):
        bn.init(jax.random.PRNGKey(0), jnp.zeros((4, 3)),
                use_running_average=False)


# ---------------------------------------------------------------------------
# ZeRO-sharded optimizers ≙ contrib DistributedFusedAdam/LAMB
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("opt_name", ["adam", "lamb"])
def test_distributed_fused_matches_unsharded(eight_devices, opt_name):
    """The sharded update must be numerically identical to the single-device
    fused optimizer (including LAMB trust ratios across shard boundaries)."""
    mesh = ps.initialize_model_parallel()  # dp=8
    params, batch = toy_setup()

    if opt_name == "adam":
        dist = DistributedFusedAdam(lr=1e-2, weight_decay=0.01)
        ref_tx = fused_adam(1e-2, weight_decay=0.01)
    else:
        dist = DistributedFusedLAMB(lr=1e-2, weight_decay=0.01)
        ref_tx = fused_lamb(1e-2, weight_decay=0.01)

    state = dist.init(params, world=8)
    step = dist.make_train_step(toy_loss, mesh)

    # reference: single device, full-batch mean grads
    ref_state = ref_tx.init(params)
    ref_params = params

    @jax.jit
    def ref_step(p, s):
        _, g = jax.value_and_grad(toy_loss)(p, batch)
        u, s = ref_tx.update(g, s, p)
        return jax.tree_util.tree_map(lambda a, b: a + b, p, u), s

    dp_params = params
    for _ in range(4):
        dp_params, state, _ = step(dp_params, state, batch)
        ref_params, ref_state = ref_step(ref_params, ref_state)

    for a, r in zip(
        jax.tree_util.tree_leaves(dp_params),
        jax.tree_util.tree_leaves(ref_params),
    ):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(r), rtol=2e-5, atol=2e-6
        )


def test_distributed_state_is_sharded(eight_devices):
    mesh = ps.initialize_model_parallel()
    params, _ = toy_setup()
    dist = DistributedFusedAdam(lr=1e-3)
    state = dist.init(params, world=8)
    shardings = dist.state_sharding(mesh)
    m = jax.device_put(state.m, shardings.m)
    assert m.sharding.spec == P("dp")
    # each device holds 1/8 of the padded flat buffer
    assert state.m.size == dist.spec.padded_size
    assert dist.spec.shard_size * 8 == dist.spec.padded_size
