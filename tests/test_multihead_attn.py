"""SelfMultiheadAttn / EncdecMultiheadAttn — ≙ apex/contrib/test/multihead_attn
(fused module vs plain attention composition, norm_add and masking variants)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from apex_tpu.contrib.fmha import fmha
from apex_tpu.contrib.multihead_attn import EncdecMultiheadAttn, SelfMultiheadAttn
from apex_tpu.ops.attention import mha_reference

S, B, E, H = 128, 2, 256, 4


def _ref_self_attn(params, x, key_padding_mask=None, causal=False):
    w = params["params"]["qkv_proj"]["kernel"]
    wo = params["params"]["out_proj"]["kernel"]
    qkv = x @ w
    qkv = qkv.reshape(S, B, 3, H, E // H)
    q, k, v = (jnp.transpose(qkv[:, :, i], (1, 2, 0, 3)) for i in range(3))
    bias = None
    if key_padding_mask is not None:
        bias = jnp.where(key_padding_mask, -1e9, 0.0)[:, None, None, :]
    o = mha_reference(q, k, v, bias, causal=causal, scale=(E // H) ** -0.5)
    return jnp.transpose(o, (2, 0, 1, 3)).reshape(S, B, E) @ wo


def test_self_attn_matches_reference():
    mod = SelfMultiheadAttn(embed_dim=E, num_heads=H)
    x = jax.random.normal(jax.random.PRNGKey(0), (S, B, E))
    params = mod.init(jax.random.PRNGKey(1), x)
    out = mod.apply(params, x)
    ref = _ref_self_attn(params, x)
    np.testing.assert_allclose(out, ref, atol=1e-4, rtol=1e-4)


def test_self_attn_key_padding_mask():
    mod = SelfMultiheadAttn(embed_dim=E, num_heads=H)
    x = jax.random.normal(jax.random.PRNGKey(2), (S, B, E))
    params = mod.init(jax.random.PRNGKey(3), x)
    kpm = np.zeros((B, S), bool)
    kpm[1, 100:] = True  # mask out tail keys of batch 1
    kpm = jnp.asarray(kpm)
    out = mod.apply(params, x, kpm)
    ref = _ref_self_attn(params, x, key_padding_mask=kpm)
    np.testing.assert_allclose(out, ref, atol=1e-4, rtol=1e-4)


def test_self_attn_causal():
    mod = SelfMultiheadAttn(embed_dim=E, num_heads=H)
    x = jax.random.normal(jax.random.PRNGKey(4), (S, B, E))
    params = mod.init(jax.random.PRNGKey(5), x)
    out = mod.apply(params, x, causal=True)
    ref = _ref_self_attn(params, x, causal=True)
    np.testing.assert_allclose(out, ref, atol=1e-4, rtol=1e-4)


def test_self_attn_norm_add_residual():
    mod = SelfMultiheadAttn(embed_dim=E, num_heads=H, include_norm_add=True)
    x = jax.random.normal(jax.random.PRNGKey(6), (S, B, E))
    params = mod.init(jax.random.PRNGKey(7), x)
    out = mod.apply(params, x)
    # zeroing the attention path must leave exactly the residual:
    # out = attn(LN(x)) + x
    assert out.shape == x.shape
    ln = params["params"]
    assert "lyr_nrm_gamma_weights" in ln
    # check residual add: subtracting x gives the attn branch on LN(x)
    mod_plain = SelfMultiheadAttn(embed_dim=E, num_heads=H)
    import flax

    plain_params = flax.core.freeze(
        {"params": {k: v for k, v in params["params"].items()
                    if k in ("qkv_proj", "out_proj")}}
    )
    from apex_tpu.ops.layer_norm import fused_layer_norm_affine

    lnx = fused_layer_norm_affine(
        x, ln["lyr_nrm_gamma_weights"], ln["lyr_nrm_beta_weights"], (E,)
    )
    expect = mod_plain.apply(plain_params, lnx) + x
    np.testing.assert_allclose(out, expect, atol=1e-4, rtol=1e-4)


def test_self_attn_dropout_stochastic():
    mod = SelfMultiheadAttn(embed_dim=E, num_heads=H, dropout=0.5)
    x = jax.random.normal(jax.random.PRNGKey(8), (S, B, E))
    params = mod.init(jax.random.PRNGKey(9), x)
    o1 = mod.apply(params, x, deterministic=False,
                   rngs={"dropout": jax.random.PRNGKey(10)})
    o2 = mod.apply(params, x, deterministic=False,
                   rngs={"dropout": jax.random.PRNGKey(11)})
    assert not np.allclose(o1, o2)
    # deterministic mode ignores dropout
    od = mod.apply(params, x)
    ref = _ref_self_attn(params, x)
    np.testing.assert_allclose(od, ref, atol=1e-4, rtol=1e-4)


def test_encdec_attn():
    mod = EncdecMultiheadAttn(embed_dim=E, num_heads=H)
    q = jax.random.normal(jax.random.PRNGKey(12), (S, B, E))
    kv = jax.random.normal(jax.random.PRNGKey(13), (S // 2, B, E))
    params = mod.init(jax.random.PRNGKey(14), q, kv)
    out = mod.apply(params, q, kv)
    assert out.shape == (S, B, E)

    wq = params["params"]["q_proj"]["kernel"]
    wkv = params["params"]["kv_proj"]["kernel"]
    wo = params["params"]["out_proj"]["kernel"]
    d = E // H
    qp = jnp.transpose((q @ wq).reshape(S, B, H, d), (1, 2, 0, 3))
    kvp = (kv @ wkv).reshape(S // 2, B, 2, H, d)
    kp, vp = (jnp.transpose(kvp[:, :, i], (1, 2, 0, 3)) for i in range(2))
    ref = mha_reference(qp, kp, vp, scale=d ** -0.5)
    ref = jnp.transpose(ref, (2, 0, 1, 3)).reshape(S, B, E) @ wo
    np.testing.assert_allclose(out, ref, atol=1e-4, rtol=1e-4)


def test_fmha_varlen_masking():
    b, s, h, d = 2, 128, 2, 64
    qkv = jax.random.normal(jax.random.PRNGKey(15), (b, s, 3, h, d))
    seqlens = jnp.array([128, 80])
    out = fmha(qkv, seqlens)
    # batch 0 (full length) must equal the unmasked computation
    full = fmha(qkv)
    np.testing.assert_allclose(out[0], full[0], atol=1e-5, rtol=1e-5)
    # batch 1 rows < 80 must be independent of key positions >= 80
    qkv_mut = qkv.at[1, 80:].set(123.0)
    out_mut = fmha(qkv_mut, seqlens)
    np.testing.assert_allclose(out[1, :80], out_mut[1, :80], atol=1e-5, rtol=1e-5)


def test_grads_flow():
    mod = SelfMultiheadAttn(embed_dim=E, num_heads=H, bias=True)
    x = jax.random.normal(jax.random.PRNGKey(16), (S, B, E))
    params = mod.init(jax.random.PRNGKey(17), x)

    def loss(p):
        return jnp.sum(mod.apply(p, x) ** 2)

    g = jax.grad(loss)(params)
    gnorm = jax.tree_util.tree_reduce(
        lambda a, l: a + jnp.sum(l ** 2), g, 0.0
    )
    assert np.isfinite(gnorm) and gnorm > 0
