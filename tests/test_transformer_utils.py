"""≙ test_random.py, test_data.py, test_transformer_utils.py + fused softmax
wrapper + model-parallel GradScaler."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from apex_tpu import parallel_state as ps
from apex_tpu.transformer import AttnMaskType, get_transformer_logger
from apex_tpu.transformer.amp import GradScaler
from apex_tpu.transformer.functional import FusedScaleMaskSoftmax
from apex_tpu.transformer.tensor_parallel import (
    broadcast_data,
    checkpoint,
    get_tpu_rng_tracker,
    model_parallel_tpu_manual_seed,
    to_per_rank_key,
)


# -- random -----------------------------------------------------------------


def test_rng_tracker_streams_differ_and_replay():
    tracker = model_parallel_tpu_manual_seed(1234)
    k1 = tracker.fork()
    k2 = tracker.fork()
    assert not np.array_equal(np.asarray(k1), np.asarray(k2))
    # replay: restoring states reproduces the same forks
    tracker2 = model_parallel_tpu_manual_seed(1234)
    r1 = tracker2.fork()
    np.testing.assert_array_equal(np.asarray(k1), np.asarray(r1))
    with pytest.raises(RuntimeError):
        tracker.add("default-rng", 0)  # duplicate
    with pytest.raises(RuntimeError):
        tracker.fork("nonexistent")


def test_per_rank_keys_differ(eight_devices):
    mesh = ps.initialize_model_parallel(tensor_model_parallel_size=8)

    def f(key):
        return to_per_rank_key(key)[None]

    keys = jax.jit(
        jax.shard_map(
            f, mesh=mesh, in_specs=(P(),), out_specs=P("tp"),
            check_vma=False,
        )
    )(jax.random.PRNGKey(0))
    arr = np.asarray(keys)
    assert len({tuple(row) for row in arr}) == 8  # all distinct


def test_checkpoint_matches_uncheckpointed():
    key = jax.random.PRNGKey(0)
    x = jax.random.normal(key, (8, 16))
    w = jax.random.normal(jax.random.PRNGKey(1), (16, 16)) * 0.1

    def block(w, x):
        h = jnp.tanh(x @ w)
        drop_key = jax.random.PRNGKey(42)  # explicit key: replay-identical
        mask = jax.random.bernoulli(drop_key, 0.8, h.shape)
        return jnp.sum((h * mask) ** 2)

    g_plain = jax.grad(block)(w, x)
    g_ckpt = jax.grad(lambda w, x: checkpoint(block, w, x))(w, x)
    np.testing.assert_allclose(
        np.asarray(g_plain), np.asarray(g_ckpt), rtol=1e-6
    )


# -- data -------------------------------------------------------------------


def test_broadcast_data_validates():
    data = {
        "text": jnp.zeros((4, 8), jnp.int32),
        "mask": jnp.zeros((4, 8), jnp.int32),
        "extra": jnp.zeros((1,), jnp.float32),
    }
    out = broadcast_data(["text", "mask"], data, jnp.int32)
    assert set(out) == {"text", "mask"}
    with pytest.raises(TypeError):
        broadcast_data(["extra"], data, jnp.int32)
    with pytest.raises(KeyError):
        broadcast_data(["missing"], data, jnp.int32)


# -- fused softmax wrapper --------------------------------------------------


def test_fused_scale_mask_softmax_causal():
    sm = FusedScaleMaskSoftmax(
        input_in_bf16=True,
        attn_mask_type=AttnMaskType.causal,
        scale=0.5,
    )
    x = jax.random.normal(jax.random.PRNGKey(2), (2, 4, 8, 8), jnp.bfloat16)
    y = sm(x)
    assert y.dtype == jnp.bfloat16
    assert sm.is_kernel_available(None, 2, 4, 8, 8)
    s = jnp.sum(y.astype(jnp.float32), axis=-1)
    np.testing.assert_allclose(np.asarray(s), 1.0, atol=2e-2)
    # strictly-upper-triangular zeros
    assert float(y[0, 0, 0, 1]) < 1e-3


def test_fused_scale_mask_softmax_padding_mask():
    sm = FusedScaleMaskSoftmax(attn_mask_type=AttnMaskType.padding)
    x = jax.random.normal(jax.random.PRNGKey(3), (2, 2, 4, 6))
    mask = jnp.zeros((2, 1, 4, 6), bool).at[:, :, :, -2:].set(True)
    y = sm(x, mask)
    assert float(jnp.max(y[..., -2:])) < 1e-4


def test_fused_softmax_mask_func_is_applied():
    # user-provided mask_func (e.g. additive bias) must actually be called
    def additive(xs, mask):
        return xs + jnp.where(mask, -1e9, 0.0)

    sm = FusedScaleMaskSoftmax(mask_func=additive, scale=1.0)
    x = jnp.zeros((1, 1, 2, 4))
    mask = jnp.asarray([[[[False, False, True, True]]]])
    y = sm(x, mask)
    np.testing.assert_allclose(np.asarray(y[..., :2]), 0.5, atol=1e-6)
    np.testing.assert_allclose(np.asarray(y[..., 2:]), 0.0, atol=1e-6)


def test_checkpoint_accepts_positional_distribute_flag():
    # megatron-style: checkpoint(fn, False, *tensors)
    x = jnp.ones((4,))
    out = checkpoint(lambda t: jnp.sum(t * 2), False, x)
    np.testing.assert_allclose(float(out), 8.0)


def test_tp_layer_unbound_axis_raises(eight_devices):
    from apex_tpu.transformer.tensor_parallel import ColumnParallelLinear

    ps.initialize_model_parallel(tensor_model_parallel_size=8)
    layer = ColumnParallelLinear(8, 16)
    with pytest.raises(RuntimeError):
        layer.init(jax.random.PRNGKey(0), jnp.zeros((2, 8)))  # no shard_map


def test_fused_softmax_flag_validation():
    with pytest.raises(RuntimeError):
        FusedScaleMaskSoftmax(input_in_fp16=True, input_in_bf16=True)
    with pytest.raises(RuntimeError):
        FusedScaleMaskSoftmax(scale=2.0, softmax_in_fp32=False)


# -- model-parallel grad scaler --------------------------------------------


def test_grad_scaler_syncs_found_inf_across_tp(eight_devices):
    mesh = ps.initialize_model_parallel(tensor_model_parallel_size=8)
    scaler = GradScaler(init_scale=8.0)
    state = scaler.init()

    def f(g):
        # only rank 3 sees an inf in its shard
        rank = jax.lax.axis_index("tp")
        g = jnp.where(rank == 3, jnp.inf, g)
        _, found = scaler.unscale({"g": g}, state)
        return found[None]

    found = jax.jit(
        jax.shard_map(
            f, mesh=mesh, in_specs=(P(),), out_specs=P("tp"),
            check_vma=False,
        )
    )(jnp.ones((4,)))
    # every rank agrees: overflow
    np.testing.assert_allclose(np.asarray(found), 1.0)


def test_logger():
    lg = get_transformer_logger("x")
    assert lg.name.startswith("apex_tpu.transformer")
