"""The OpenMetrics exporter (ISSUE 11): name-mangling round-trips,
registry-level key validation, exposition-format conformance (type/unit
lines, escaping, counter ``_total``, histogram bucket ordering), the
HTTP endpoint, and the scrape-under-load overhead pins (the PR 3
<1%-on-the-compiled-cost-model bar: the exporter adds ZERO device ops
and never blocks on a fetch)."""

import math
import threading
import time
import urllib.error
import urllib.request

import pytest

from apex_tpu.observability.metrics import Board, MetricRegistry, board
from apex_tpu.observability.ometrics import (
    CONTENT_TYPE,
    DEFAULT_LATENCY_BUCKETS_MS,
    ExportNamespace,
    Histogram,
    OpsServer,
    metric_name,
    parse_exposition,
    render,
)


@pytest.fixture(autouse=True)
def _clean_board():
    board.clear()
    yield
    board.clear()


# ---------------------------------------------------------------------------
# name mangling + the injectivity guard
# ---------------------------------------------------------------------------


class TestMetricName:
    def test_documented_mapping(self):
        # the exact examples the docs table carries
        assert metric_name("serve/ttft_queue_wait_fraction") == (
            "apex_tpu_serve_ttft_queue_wait_fraction"
        )
        assert metric_name("guard/skipped") == "apex_tpu_guard_skipped"
        assert metric_name("fleet/train/step_time_ms/host0") == (
            "apex_tpu_fleet_train_step_time_ms_host0"
        )
        assert metric_name("memstats/device0/bytes_in_use") == (
            "apex_tpu_memstats_device0_bytes_in_use"
        )

    def test_separators_dashes_dots_spaces(self):
        assert metric_name("a-b.c d:e") == "apex_tpu_a_b_c_d_e"

    def test_case_folds_and_runs_collapse(self):
        assert metric_name("Serve//TTFT__ms") == "apex_tpu_serve_ttft_ms"

    def test_illegal_chars_dropped_not_kept(self):
        assert metric_name("serve/p99!") == "apex_tpu_serve_p99"

    def test_unmappable_key_raises(self):
        with pytest.raises(ValueError, match="cannot be mapped"):
            metric_name("///")
        with pytest.raises(ValueError):
            metric_name("")

    def test_namespace_collision_after_mangling(self):
        ns = ExportNamespace()
        ns.declare("serve/ttft_ms", "gauge")
        # same key re-declared: idempotent
        assert ns.declare("serve/ttft_ms", "gauge") == (
            "apex_tpu_serve_ttft_ms"
        )
        with pytest.raises(ValueError, match="injective"):
            ns.declare("serve.ttft_ms", "gauge")

    def test_counter_total_suffix_reserved(self):
        # a counter `x` emits `x_total`: a gauge named x_total collides
        ns = ExportNamespace()
        ns.declare("serve/shed", "counter")
        with pytest.raises(ValueError, match="collides"):
            ns.declare("serve/shed_total", "gauge")
        # ...and the reverse order too
        ns2 = ExportNamespace()
        ns2.declare("serve/shed_total", "gauge")
        with pytest.raises(ValueError, match="collides"):
            ns2.declare("serve/shed", "counter")

    def test_registry_declare_validates(self):
        reg = MetricRegistry()
        reg.gauge("train/loss")
        with pytest.raises(ValueError):
            reg.gauge("train.loss")  # collides after mangling
        with pytest.raises(ValueError):
            reg.counter("///")  # unmappable
        # legal keys still declare fine after a rejection
        reg.counter("train/skips")

    def test_shipped_vocabulary_round_trips(self):
        """The board/registry vocabulary the stack actually publishes
        must round-trip injectively — the ISSUE 11 audit, pinned so a
        future key addition that can't export fails here."""
        reg = MetricRegistry()
        from apex_tpu.serve.scheduler import declare_serve_metrics

        declare_serve_metrics(reg)  # raises on any illegal serve key
        from apex_tpu.fleetctl.fleet import declare_fleet_metrics

        declare_fleet_metrics(reg)  # raises on any illegal fleet key
        # the resilient example's device metric set
        reg.counter("guard/skipped")
        for key in ("train/loss", "guard/found_inf",
                    "guard/spike", "guard/grad_norm", "guard/norm_ema",
                    "guard/consecutive_skips", "guard/total_skips",
                    "guard/budget_left", "amp/loss_scale",
                    "amp/growth_tracker", "amp/hysteresis"):
            reg.gauge(key)
        # board-only families published across the stack
        seen = set()
        for key in (
            "serve/peak_hbm_bytes", "serve/hbm/decode/peak_hbm_bytes",
            "serve/hbm/prefill_16/peak_hbm_bytes",
            "analysis/peak_hbm_bytes", "analysis/peak_hbm/params",
            "analysis/shard_plan/rows", "analysis/pass_ms/memory",
            "analysis/kernels/flash_fwd/vmem_bytes",
            "attribution/collective_fraction",
            "attribution/host_stall_fraction",
            "health/slo_ttft", "health/memstats_drift",
            "fleet/train/step_time_ms/host0",
            # the canary deploy gate's ledger (ISSUE 20)
            "fleet/deploys_rolled_back", "fleet/canary/probes",
            "fleet/canary/routed", "fleet/canary/verdict_pass",
            "fleet/canary/verdict_fail",
            "fleet/canary/fingerprint_distance",
            "fleet/canary/detect_ticks", "fleet/canary/exposure_frac",
            "memstats/device0/bytes_in_use",
            "memstats/device0/peak_bytes_in_use", "memstats/crosscheck",
            "ops/scrape_ms", "ops/scrapes", "ops/port",
        ):
            name = metric_name(key)
            assert name not in seen, f"{key} collides with another key"
            seen.add(name)


# ---------------------------------------------------------------------------
# Histogram
# ---------------------------------------------------------------------------


class TestHistogram:
    def test_observe_and_cumulative(self):
        h = Histogram("serve/ttft_hist_ms", (1.0, 5.0, 10.0), unit="ms")
        for v in (0.5, 1.0, 3.0, 10.0, 99.0):
            h.observe(v)
        # le is INCLUSIVE: the 1.0 observation lands in the le=1 bucket
        assert h.cumulative() == [
            (1.0, 2), (5.0, 3), (10.0, 4), (math.inf, 5),
        ]
        assert h.count == 5
        assert h.sum == pytest.approx(113.5)

    def test_count_le_truncates_to_lower_bound(self):
        h = Histogram("x", (1.0, 5.0, 10.0))
        for v in (0.5, 3.0, 7.0):
            h.observe(v)
        assert h.count_le(5.0) == 2       # exact bound
        assert h.count_le(7.0) == 2       # truncates down to le=5
        assert h.count_le(0.2) == 0       # under the first bucket
        assert h.count_le(1e9) == 3

    def test_bad_buckets_rejected(self):
        with pytest.raises(ValueError):
            Histogram("x", ())
        with pytest.raises(ValueError):
            Histogram("x", (1.0, 1.0))
        with pytest.raises(ValueError):
            Histogram("x", (1.0, math.inf))
        with pytest.raises(ValueError, match="cannot be mapped"):
            Histogram("///", (1.0,))

    def test_default_latency_buckets_increase(self):
        b = DEFAULT_LATENCY_BUCKETS_MS
        assert all(y > x for x, y in zip(b, b[1:]))

    def test_render_consistent_under_concurrent_observe(self):
        """A scrape racing observe() must never emit an exposition
        whose _count disagrees with the +Inf bucket — strict parsers
        (and the CI OPS gate) reject that as invalid."""
        h = Histogram("lat_ms", (1.0, 10.0), unit="ms")
        stop = threading.Event()

        def hammer():
            i = 0
            while not stop.is_set():
                h.observe(float(i % 20))
                i += 1

        t = threading.Thread(target=hammer, daemon=True)
        t.start()
        try:
            for _ in range(200):
                parse_exposition(render(histograms=[h]))  # raises on skew
        finally:
            stop.set()
            t.join(timeout=5)


# ---------------------------------------------------------------------------
# exposition conformance
# ---------------------------------------------------------------------------


def _sample_registry():
    reg = MetricRegistry(fetch_every=1)
    reg.gauge("serve/ttft_ms", "ms")
    reg.counter("serve/completed")
    reg.minimum("train/loss_min")
    st = reg.init()
    st = reg.update(st, {"serve/ttft_ms": 12.5, "serve/completed": 3,
                         "train/loss_min": 0.25})
    reg.observe(0, st)
    reg.fetch()
    return reg


class TestExposition:
    def test_conformance_round_trip(self):
        reg = _sample_registry()
        h = Histogram("serve/ttft_hist_ms", (1.0, 10.0), unit="ms")
        h.observe(4.0)
        text = render([reg], [h], {"analysis/peak_hbm_bytes": 4096})
        fams = parse_exposition(text)  # raises on any format violation
        assert fams["apex_tpu_serve_completed"]["type"] == "counter"
        assert fams["apex_tpu_serve_completed"]["value"] == 3
        assert fams["apex_tpu_serve_ttft_ms"]["unit"] == "ms"
        assert fams["apex_tpu_serve_ttft_ms"]["value"] == 12.5
        # min/max kinds export as gauges
        assert fams["apex_tpu_train_loss_min"]["type"] == "gauge"
        assert fams["apex_tpu_analysis_peak_hbm_bytes"]["value"] == 4096
        assert text.endswith("# EOF\n")

    def test_counter_sample_is_name_total(self):
        text = render([_sample_registry()])
        assert "apex_tpu_serve_completed_total 3" in text
        # the metadata lines carry the BARE family name
        assert "# TYPE apex_tpu_serve_completed counter" in text

    def test_help_documents_the_original_key(self):
        text = render([_sample_registry()])
        assert (
            "# HELP apex_tpu_serve_ttft_ms board key 'serve/ttft_ms'"
            in text
        )

    def test_help_escaping(self):
        h = Histogram("x_ms", (1.0,), unit="ms",
                      help='line1\nline2 with "quotes" and \\slash')
        text = render(histograms=[h])
        assert '# HELP apex_tpu_x_ms line1\\nline2' in text
        parse_exposition(text)

    def test_histogram_bucket_ordering_and_count(self):
        h = Histogram("lat_ms", (1.0, 5.0, 25.0), unit="ms")
        for v in (0.1, 2.0, 2.0, 100.0):
            h.observe(v)
        text = render(histograms=[h])
        fams = parse_exposition(text)
        buckets = [
            (labels["le"], v)
            for s, labels, v in fams["apex_tpu_lat_ms"]["samples"]
            if s.endswith("_bucket")
        ]
        assert buckets == [("1", 1), ("5", 3), ("25", 3), ("+Inf", 4)]
        assert 'apex_tpu_lat_ms_count 4' in text
        assert 'apex_tpu_lat_ms_sum 104.1' in text

    def test_unit_line_only_when_suffix_matches(self):
        reg = MetricRegistry(fetch_every=1)
        reg.gauge("serve/batch_fill", "fraction of max_batch slots")
        st = reg.update(reg.init(), {"serve/batch_fill": 0.5})
        reg.observe(0, st)
        reg.fetch()
        text = render([reg])
        # a descriptive unit string is NOT a legal unit token suffix —
        # no UNIT line, and the exposition still parses
        assert "# UNIT" not in text
        parse_exposition(text)

    def test_board_strings_skipped(self):
        text = render(board={"serve/kv_wire": "int8", "serve/pages": 64})
        assert "kv_wire" not in text
        assert "apex_tpu_serve_pages 64" in text

    def test_nonfinite_values_encode(self):
        reg = MetricRegistry(fetch_every=1)
        reg.gauge("x")
        reg._values["x"] = float("nan")
        text = render([reg])
        assert "apex_tpu_x NaN" in text
        parse_exposition(text)

    def test_registry_beats_board_echo(self):
        # a board echo of a registry key must not duplicate the family
        reg = _sample_registry()
        text = render([reg], board={"serve/ttft_ms": 999.0})
        assert text.count("# TYPE apex_tpu_serve_ttft_ms") == 1
        assert parse_exposition(text)["apex_tpu_serve_ttft_ms"][
            "value"
        ] == 12.5

    def test_parser_rejects_planted_defects(self):
        with pytest.raises(ValueError, match="# EOF"):
            parse_exposition("apex_tpu_x 1\n")
        with pytest.raises(ValueError, match="before any matching"):
            parse_exposition("apex_tpu_x 1\n# EOF\n")
        with pytest.raises(ValueError, match="_total"):
            parse_exposition(
                "# TYPE apex_tpu_c counter\napex_tpu_c 1\n# EOF\n"
            )
        with pytest.raises(ValueError, match="not increasing"):
            parse_exposition(
                "# TYPE h histogram\n"
                'h_bucket{le="5"} 1\nh_bucket{le="1"} 2\n'
                'h_bucket{le="+Inf"} 2\n# EOF\n'
            )
        with pytest.raises(ValueError, match="decreasing"):
            parse_exposition(
                "# TYPE h histogram\n"
                'h_bucket{le="1"} 3\nh_bucket{le="5"} 2\n'
                'h_bucket{le="+Inf"} 3\n# EOF\n'
            )
        with pytest.raises(ValueError, match=r"\+Inf"):
            parse_exposition(
                "# TYPE h histogram\n"
                'h_bucket{le="1"} 1\n# EOF\n'
            )
        with pytest.raises(ValueError, match="suffix"):
            parse_exposition("# TYPE x gauge\n# UNIT x ms\nx 1\n# EOF\n")


# ---------------------------------------------------------------------------
# the HTTP endpoint
# ---------------------------------------------------------------------------


class TestOpsServer:
    def test_serves_metrics_over_http(self):
        reg = _sample_registry()
        srv = OpsServer(registries=[reg], port=0).start()
        try:
            assert srv.port > 0
            with urllib.request.urlopen(srv.url, timeout=5) as resp:
                assert resp.status == 200
                assert resp.headers["Content-Type"] == CONTENT_TYPE
                fams = parse_exposition(resp.read().decode())
            assert fams["apex_tpu_serve_completed"]["value"] == 3
        finally:
            srv.stop()

    def test_unknown_path_404(self):
        srv = OpsServer(port=0).start()
        try:
            with pytest.raises(urllib.error.HTTPError) as exc:
                urllib.request.urlopen(
                    f"http://127.0.0.1:{srv.port}/bogus", timeout=5
                )
            assert exc.value.code == 404
        finally:
            srv.stop()

    def test_collect_hook_runs_per_scrape(self):
        calls = []
        srv = OpsServer(collect=lambda: calls.append(1))
        srv.scrape()
        srv.scrape()
        assert len(calls) == 2

    def test_from_env(self, monkeypatch):
        monkeypatch.delenv("APEX_TPU_OPS_PORT", raising=False)
        assert OpsServer.from_env() is None
        monkeypatch.setenv("APEX_TPU_OPS_PORT", "")
        assert OpsServer.from_env() is None
        monkeypatch.setenv("APEX_TPU_OPS_PORT", "0")
        srv = OpsServer.from_env()
        assert srv is not None and srv.port == 0

    def test_scrape_publishes_self_observability(self):
        srv = OpsServer()
        srv.scrape()
        assert board.get("ops/scrapes") == 1
        assert board.get("ops/scrape_ms") is not None

    def test_bound_port_none_before_start(self):
        srv = OpsServer(port=0)
        assert srv.bound_port is None
        srv.start()
        try:
            assert srv.bound_port == srv.port > 0
        finally:
            srv.stop()

    def test_port0_fleet_no_collision_and_namespaced_board(self):
        """N replicas in ONE process (the fleet control plane's
        layout): each port-0 server gets its own OS-assigned port, and
        ``name=`` keeps their self-observation board keys from
        overwriting each other."""
        servers = [
            OpsServer(registries=[_sample_registry()], port=0,
                      name=f"r{i}").start()
            for i in range(3)
        ]
        try:
            ports = [s.bound_port for s in servers]
            assert all(p and p > 0 for p in ports)
            assert len(set(ports)) == 3
            for i, srv in enumerate(servers):
                srv.scrape()
                assert board.get(f"ops/r{i}/scrapes") == 1
                assert board.get(f"ops/r{i}/port") == srv.bound_port
        finally:
            for srv in servers:
                srv.stop()


# ---------------------------------------------------------------------------
# overhead: the PR 3 bar, applied to the scrape path
# ---------------------------------------------------------------------------


class TestScrapeOverhead:
    def test_scrape_never_fetches_or_syncs(self):
        """The <1% claim's mechanism: a scrape renders the registry's
        CACHED values — no blocking fetch, no device contact.  100
        scrapes must leave the fetch count at zero and the async
        double-buffer untouched."""
        fetches = []

        class CountingRegistry(MetricRegistry):
            def fetch(self):
                fetches.append(1)
                return super().fetch()

            def _rotate(self):
                fetches.append(1)  # even an async copy start counts
                return super()._rotate()

        reg = CountingRegistry(fetch_every=1000)
        reg.gauge("x")
        st = reg.update(reg.init(), {"x": 1.0})
        reg.observe(1, st)  # off-cadence: stays pending
        srv = OpsServer(registries=[reg])
        for _ in range(100):
            srv.scrape()
        assert not fetches, "scrape touched the device fetch path"
        assert reg._pending is not None  # the stash survived untouched

    def test_device_cost_identical_under_scraping(self):
        """The compiled-cost-model pin (same bar as the PR 3 registry
        test): the step program's flops/bytes are IDENTICAL with a live
        exporter scraping concurrently — the exporter adds zero device
        ops, so its share of the <1% budget is exactly 0."""
        import jax
        import jax.numpy as jnp

        reg = MetricRegistry(fetch_every=32)
        reg.gauge("loss")

        def chunk(w, m):
            def body(carry, _):
                w, m = carry
                w = w @ w * 0.99
                m = reg.update(m, {"loss": jnp.sum(w)})
                return (w, m), ()

            (w, m), _ = jax.lax.scan(body, (w, m), None, length=8)
            return w, m

        w0 = jnp.ones((64, 64), jnp.float32)
        m0 = reg.init()
        fn = jax.jit(chunk)

        def costs():
            c = fn.lower(w0, m0).compile().cost_analysis()
            c = c[0] if isinstance(c, (list, tuple)) else c
            return (float(c.get("flops", 0.0)),
                    float(c.get("bytes accessed", 0.0)))

        bare = costs()
        srv = OpsServer(registries=[reg], port=0).start()
        stop = threading.Event()

        def scraper():
            while not stop.is_set():
                try:
                    urllib.request.urlopen(srv.url, timeout=5).read()
                except Exception:
                    pass

        t = threading.Thread(target=scraper, daemon=True)
        t.start()
        try:
            scraped = costs()
        finally:
            stop.set()
            t.join(timeout=5)
            srv.stop()
        assert bare == scraped, (
            f"exporter perturbed the compiled step: {bare} vs {scraped}"
        )

    def test_host_path_tripwire_under_scraping(self):
        """Wall-clock tripwire (PR 3's 25% discipline, not a precision
        claim on a shared container): the hot observe() loop with a
        thread scraping flat-out must stay within 1.25x of the bare
        loop on its best-of-9 ratio."""
        reg = MetricRegistry(fetch_every=10_000)
        reg.gauge("x")
        st = reg.update(reg.init(), {"x": 1.0})

        def observe_loop(n=2000):
            t0 = time.perf_counter()
            for i in range(n):
                reg.observe(i + 1, st)
            return time.perf_counter() - t0

        observe_loop()  # warmup
        srv = OpsServer(registries=[reg])
        stop = threading.Event()

        def scraper():
            while not stop.is_set():
                srv.scrape()

        ratios = []
        for _ in range(9):
            tb = observe_loop()
            t = threading.Thread(target=scraper, daemon=True)
            stop.clear()
            t.start()
            ti = observe_loop()
            stop.set()
            t.join(timeout=5)
            ratios.append(ti / tb)
        assert min(ratios) < 1.25, (
            f"scrape-under-load tripwire: best ratio {min(ratios):.3f} "
            f"(all: {[round(r, 3) for r in ratios]})"
        )


def test_board_class_unaffected():
    # the Board stays a plain dict surface (no validation — ad-hoc keys
    # are skipped at render time instead)
    b = Board()
    b.set("weird key!!", 1)
    text = render(board=b.snapshot())
    parse_exposition(text)
