"""int8-wire gradient all-reduce (parallel.quantized) vs the exact psum.

Beyond the reference (pattern: EQuARX, arxiv 2506.17615) — golden is
:func:`parallel.all_reduce_gradients` on the same shards.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from apex_tpu import parallel_state as ps
from apex_tpu.parallel import (
    all_reduce_gradients,
    quantized_all_reduce_gradients,
)

DP = 8


def _run(fn, tree):
    """tree leaves have a leading (DP,) axis of per-rank values."""
    mesh = ps.initialize_model_parallel(devices=jax.devices()[:DP])

    def f(tree):
        local = jax.tree_util.tree_map(lambda x: x[0], tree)
        out = fn(local)
        return jax.tree_util.tree_map(lambda x: x[None], out)

    out = jax.jit(
        jax.shard_map(
            f, mesh=mesh, in_specs=(P("dp"),), out_specs=P("dp"),
            check_vma=False,
        )
    )(tree)
    ps.destroy_model_parallel()
    return out


def _per_rank_grads(key, shape):
    return jax.random.normal(key, (DP,) + shape, jnp.float32)


def test_error_bounded_vs_exact(eight_devices):
    """Averaged sync: the reduce-scatter stage's world half-ulp errors
    average back down, plus one re-quantize half-ulp — so element error
    is bounded by ~1/127 of the PRE-reduction input max (a world-robust
    bound, unlike one phrased against the post-mean result)."""
    g = {
        "w": _per_rank_grads(jax.random.PRNGKey(0), (64, 96)),
        "b": _per_rank_grads(jax.random.PRNGKey(1), (4096,)),
    }
    got = _run(quantized_all_reduce_gradients, g)
    want = _run(all_reduce_gradients, g)
    for k in g:
        a, b = np.asarray(got[k][0]), np.asarray(want[k][0])
        # replicated output: every rank row identical
        for r in range(1, DP):
            np.testing.assert_array_equal(np.asarray(got[k][r]), a)
        gmax = np.abs(np.asarray(g[k])).max()  # pre-reduction magnitude
        bound = 2.0 / 127.0 * gmax
        assert np.abs(a - b).max() <= bound, (k, np.abs(a - b).max(), bound)
        # and the quantized result is genuinely close in aggregate
        rel = np.abs(a - b).mean() / (np.abs(b).mean() + 1e-12)
        assert rel < 0.02, (k, rel)


def test_small_leaves_are_exact(eight_devices):
    """Leaves under min_size ride the exact psum — bit-identical."""
    g = {"tiny": _per_rank_grads(jax.random.PRNGKey(2), (37,))}
    got = _run(quantized_all_reduce_gradients, g)
    want = _run(all_reduce_gradients, g)
    np.testing.assert_array_equal(
        np.asarray(got["tiny"]), np.asarray(want["tiny"])
    )


def test_sum_semantics_and_odd_sizes(eight_devices):
    """gradient_average=False sums; non-world-divisible leaf sizes pad
    and unpad correctly (no wraparound into real elements)."""
    shape = (1023,)  # not divisible by DP=8
    g = {"x": _per_rank_grads(jax.random.PRNGKey(3), shape)}
    got = _run(
        lambda t: quantized_all_reduce_gradients(t, gradient_average=False),
        g,
    )
    want = _run(
        lambda t: all_reduce_gradients(t, gradient_average=False), g
    )
    a, b = np.asarray(got["x"][0]), np.asarray(want["x"][0])
    assert a.shape == shape
    # SUM semantics: each rank contributes its own half-ulp, so the
    # absolute bound scales with world (as the sum itself does)
    gmax = np.abs(np.asarray(g["x"])).max()
    bound = (0.5 * (DP + 1) + 0.5) / 127.0 * gmax
    assert np.abs(a - b).max() <= bound


def test_predivide_factor_matches_exact_semantics(eight_devices):
    """gradient_predivide_factor is honored identically to
    all_reduce_gradients (pre-divide, psum, post-divide world/factor) —
    and is a numerical no-op inside the quantized path (constant scaling
    commutes with max/127 quantization), so results equal the
    no-predivide call bit-for-bit."""
    g = {"w": _per_rank_grads(jax.random.PRNGKey(7), (2048,))}
    base = _run(quantized_all_reduce_gradients, g)
    pre = _run(
        lambda t: quantized_all_reduce_gradients(
            t, gradient_predivide_factor=4.0
        ),
        g,
    )
    np.testing.assert_allclose(
        np.asarray(pre["w"]), np.asarray(base["w"]), rtol=1e-6, atol=1e-7
    )
    want = _run(
        lambda t: all_reduce_gradients(t, gradient_predivide_factor=4.0),
        g,
    )
    bound = 2.0 / 127.0 * np.abs(np.asarray(g["w"])).max()
    assert np.abs(np.asarray(pre["w"]) - np.asarray(want["w"])).max() <= bound


def test_single_bucket_two_collectives(eight_devices):
    """The whole tree's eligible leaves share ONE bucket: compiled HLO
    contains exactly one all-to-all and one all-gather regardless of
    leaf count (the DCN-latency property the module promises)."""
    mesh = ps.initialize_model_parallel(devices=jax.devices()[:DP])
    tree = {
        f"p{i}": jnp.ones((137 + 61 * i, 33)) for i in range(5)
    }  # 5 eligible leaves, deliberately awkward sizes

    def f(t):
        return quantized_all_reduce_gradients(t, min_size=1)

    hlo = (
        jax.jit(
            jax.shard_map(
                f, mesh=mesh, in_specs=(P(),), out_specs=P(),
                check_vma=False,
            )
        )
        .lower(tree)
        .compile()
        .as_text()
    )
    ps.destroy_model_parallel()
    import re

    n_a2a = len(re.findall(r"\ball-to-all(?:-start)?\(", hlo))
    n_ag = len(re.findall(r"\ball-gather(?:-start)?\(", hlo))
    assert n_a2a == 1, n_a2a
    assert n_ag == 1, n_ag


@pytest.mark.parametrize("block", [256, 4096])
def test_ddp_training_converges_with_quantized_sync(eight_devices, block):
    """A dp=8 MLP trained with int8-wire sync reaches (approximately)
    the loss of exact-sync training from the same init, across the
    block-size envelope (VERDICT r4 #7): 256 (many scales per leaf)
    and 4096 (the whole bucket padded into one block — the coarsest,
    most error-prone point; see tools/int8wire_sensitivity.py for the
    full block x model-scale table)."""
    from apex_tpu.optimizers import fused_sgd

    d, h, n_steps = 16, 64, 30
    tx = fused_sgd(learning_rate=0.1, momentum=0.9)
    xs = jax.random.normal(jax.random.PRNGKey(5), (DP, 32, d))
    w_true = jax.random.normal(jax.random.PRNGKey(6), (d, 1)) * 0.5
    ys = jnp.einsum("rbd,do->rbo", xs, w_true)
    k1, k2 = jax.random.split(jax.random.PRNGKey(7))
    init = {
        # a hidden layer so the bucket spans multiple 256-blocks and
        # mixes magnitudes — at d=1-layer scale every block size is
        # trivially identical
        "w1": jax.random.normal(k1, (d, h)) / np.sqrt(d),
        "b1": jnp.zeros((h,)),
        "w2": jax.random.normal(k2, (h, 1)) / np.sqrt(h),
        "b2": jnp.zeros((1,)),
    }

    def train(sync):
        def f(x, y):
            x, y = x[0], y[0]
            params = init
            opt = tx.init(params)

            def model(p, x):
                return jnp.tanh(x @ p["w1"] + p["b1"]) @ p["w2"] + p["b2"]

            def step(carry, _):
                params, opt = carry
                loss, grads = jax.value_and_grad(
                    lambda p: jnp.mean((model(p, x) - y) ** 2)
                )(params)
                grads = sync(grads)
                upd, opt = tx.update(grads, opt, params)
                params = jax.tree_util.tree_map(jnp.add, params, upd)
                return (params, opt), loss

            _, hist = jax.lax.scan(step, (params, opt), None, length=n_steps)
            return jax.lax.pmean(hist, ps.DATA_PARALLEL_AXIS)[None]

        mesh = ps.initialize_model_parallel(devices=jax.devices()[:DP])
        hist = jax.jit(
            jax.shard_map(
                f, mesh=mesh, in_specs=(P("dp"), P("dp")),
                out_specs=P("dp"), check_vma=False,
            )
        )(xs, ys)
        ps.destroy_model_parallel()
        return np.asarray(hist)[0]

    h_exact = train(all_reduce_gradients)
    h_quant = train(
        lambda g: quantized_all_reduce_gradients(
            g, min_size=1, block=block
        )
    )
    assert h_exact[-1] < h_exact[0] * 0.1
    assert h_quant[-1] < h_quant[0] * 0.15, (h_quant[0], h_quant[-1])
    # trajectories track each other to a few percent
    assert abs(h_quant[-1] - h_exact[-1]) < 0.1 * h_exact[0]


# ---------------------------------------------------------------------------
# codec edge cases (ISSUE 2 satellite): zero/empty blocks, tail blocks
# ---------------------------------------------------------------------------


def test_all_zero_and_empty_leaves_stay_finite_and_exact(eight_devices):
    """All-zero blocks must not mint NaN/Inf scales (max==0 ->
    scale=max/127=0 was the trap), and zero-size leaves must pass
    through untouched."""
    g = {
        "zeros": jnp.zeros((DP, 4096), jnp.float32),
        "empty": jnp.zeros((DP, 0), jnp.float32),
        "w": _per_rank_grads(jax.random.PRNGKey(11), (2048,)),
    }
    got = _run(lambda t: quantized_all_reduce_gradients(t, min_size=1), g)
    z = np.asarray(got["zeros"])
    assert np.all(np.isfinite(z))
    np.testing.assert_array_equal(z, 0.0)
    assert got["empty"].shape == (DP, 0)
    assert np.all(np.isfinite(np.asarray(got["w"])))


def test_tail_block_roundtrip(eight_devices):
    """flat_size % block != 0: the tail block must quantize on its own
    scale (no wraparound into pad), and — because dequantized values sit
    exactly on the int8 grid — a second quantize/dequantize pass must be
    bit-identical (the fixed-point property)."""
    from apex_tpu.parallel import comm

    n, block = 300, 256  # 44-element tail block
    x = jax.random.normal(jax.random.PRNGKey(12), (n,), jnp.float32)
    q, s = comm.quantize_blocks(x, block)
    assert q.shape == (512,) and s.shape == (2,)
    # pad region encodes to zero codes
    np.testing.assert_array_equal(np.asarray(q[n:]), 0)
    y = comm.dequantize_blocks(q, s, block, n)
    assert y.shape == (n,)
    # per-block error bound: half an ulp of that block's own max
    for lo, hi in ((0, 256), (256, n)):
        blk = np.asarray(x[lo:hi])
        err = np.abs(np.asarray(y[lo:hi]) - blk).max()
        assert err <= 0.5 * np.abs(blk).max() / 127.0 + 1e-7, (lo, err)
    # fixed point: re-quantizing the dequantized values is exact
    q2, s2 = comm.quantize_blocks(y, block)
    y2 = comm.dequantize_blocks(q2, s2, block, n)
    np.testing.assert_array_equal(np.asarray(y), np.asarray(y2))
    # and through the full sync: a tail-carrying tree matches the exact
    # psum within the usual bound
    g = {"x": _per_rank_grads(jax.random.PRNGKey(13), (1021 * 3,))}
    got = _run(lambda t: quantized_all_reduce_gradients(t, min_size=1), g)
    want = _run(all_reduce_gradients, g)
    gmax = np.abs(np.asarray(g["x"])).max()
    assert (
        np.abs(np.asarray(got["x"][0]) - np.asarray(want["x"][0])).max()
        <= 2.0 / 127.0 * gmax
    )


def test_all_zero_block_scale_is_one_not_tiny():
    """Unit pin on the scale rule: max==0 -> scale exactly 1.0 (a
    subnormal scale risks x/tiny overflow on later encodes of the same
    grid)."""
    from apex_tpu.parallel import comm

    q, s = comm.quantize_blocks(jnp.zeros((512,), jnp.float32), 256)
    np.testing.assert_array_equal(np.asarray(s), 1.0)
    np.testing.assert_array_equal(np.asarray(q), 0)
