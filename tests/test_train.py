"""`apex_tpu.train` — the composable 3D-parallel trainer (ISSUE 12).

Covers the satellite test matrix:

- the update-sharding heuristic: tiny trees stay replicated, large
  trees shard on dp, the explicit override always wins, dp=1 never
  shards, custom optimizers never shard;
- rule tables: a leaf no rule covers fails the build LOUDLY naming the
  unmatched path (never silent replication);
- the dp=2 x tp=2 live check: the compiled step's collectives equal
  the trainer's declared plan for BOTH the f32 and int8 wires (the
  build's own `analysis.check` run must come back with zero findings);
- numerics: the zero (update-sharded) and ddp (replicated) modes train
  to the same losses; tp=2 matches tp=1;
- the guarded two-phase build keeps the resilient example's contract;
- `fit` runs the composed loop (run_resilient + goodput) end to end.
"""

import json
import os

import jax
import jax.numpy as jnp
import pytest
from jax.sharding import PartitionSpec as P

from apex_tpu.train import (
    TrainBuildError,
    TrainConfig,
    Trainer,
    build_demo,
    decide_update_sharding,
)
from apex_tpu.train.demo import demo_batch, demo_loss, demo_params

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _cfg(**kw):
    base = dict(
        mesh={"dp": 2},
        rules=[(r".*", P())],
        optimizer="adam",
    )
    base.update(kw)
    return TrainConfig(**base)


def _params(n_elems: int):
    return {"w": jnp.zeros((n_elems,), jnp.float32)}


# ---------------------------------------------------------------------------
# the update-sharding heuristic (pure host logic — no mesh needed)
# ---------------------------------------------------------------------------


class TestUpdateShardingHeuristic:
    def test_small_tree_stays_replicated(self):
        d = decide_update_sharding(_params(1024), _cfg())
        assert not d.shard and d.mode == "ddp"
        assert "floor" in d.reason

    def test_large_tree_shards_on_dp(self):
        cfg = _cfg(zero_min_bytes=1 << 10)
        d = decide_update_sharding(_params(1 << 16), cfg)
        assert d.shard and d.mode == "zero"
        assert d.state_bytes_saved > 0
        # the decision narrates itself: bytes, both wire plans, savings
        text = d.render()
        assert "zero" in text and "MiB" in text

    def test_dp1_never_shards(self):
        cfg = _cfg(mesh={"dp": 1}, zero_min_bytes=0)
        d = decide_update_sharding(_params(1 << 20), cfg)
        assert not d.shard and "dp=1" in d.reason

    def test_explicit_override_wins_both_ways(self):
        forced_on = decide_update_sharding(
            _params(16), _cfg(update_sharding="shard", zero_min_bytes=1 << 40)
        )
        assert forced_on.shard and forced_on.reason == "explicit override"
        forced_off = decide_update_sharding(
            _params(1 << 20),
            _cfg(update_sharding="replicate", zero_min_bytes=0),
        )
        assert not forced_off.shard

    def test_custom_optimizer_never_shards(self):
        from apex_tpu.optimizers import fused_adam

        cfg = _cfg(optimizer=fused_adam(1e-3), zero_min_bytes=0)
        d = decide_update_sharding(_params(1 << 20), cfg)
        assert not d.shard and "twin" in d.reason
        with pytest.raises(ValueError, match="twin"):
            decide_update_sharding(
                _params(16),
                _cfg(optimizer=fused_adam(1e-3), update_sharding="shard"),
            )

    def test_explicit_shard_on_dp1_is_an_error(self):
        with pytest.raises(ValueError, match="dp axis"):
            decide_update_sharding(
                _params(16), _cfg(mesh={"dp": 1}, update_sharding="shard")
            )


# ---------------------------------------------------------------------------
# config validation
# ---------------------------------------------------------------------------


class TestConfig:
    def test_pp_is_reserved(self):
        with pytest.raises(NotImplementedError, match="reserved"):
            TrainConfig(mesh={"dp": 2, "pp": 2}, rules=[(r".*", P())])

    def test_unknown_axis_and_bad_knobs(self):
        with pytest.raises(ValueError, match="unknown mesh axis"):
            TrainConfig(mesh={"xx": 2}, rules=[])
        with pytest.raises(ValueError):
            _cfg(wire="f16")
        with pytest.raises(ValueError):
            _cfg(update_sharding="maybe")
        with pytest.raises(ValueError):
            _cfg(verify="loudly")

    def test_optimizer_registry_is_loud(self):
        from apex_tpu import optimizers

        assert optimizers.by_name("adam") is optimizers.fused_adam
        with pytest.raises(ValueError, match="unknown optimizer"):
            optimizers.by_name("adamw2")


# ---------------------------------------------------------------------------
# rule tables: misses are loud
# ---------------------------------------------------------------------------


class TestRuleTables:
    def test_uncovered_param_fails_the_build_naming_the_path(self):
        cfg = TrainConfig(mesh={"dp": 2}, rules=[(r"^w$", P())])
        params = {"w": jnp.zeros((64,)), "mlp": {"kernel": jnp.zeros((8,))}}
        with pytest.raises(TrainBuildError, match=r"mlp/kernel"):
            Trainer(cfg).build(
                lambda p, b: jnp.sum(p["w"]), params,
                (jnp.zeros((4, 2)),),
            )

    def test_mesh_larger_than_devices_is_loud(self, eight_devices):
        cfg = TrainConfig(mesh={"dp": 16}, rules=[(r".*", P())])
        with pytest.raises(TrainBuildError, match="devices"):
            Trainer(cfg).build(
                lambda p, b: jnp.sum(p["w"]),
                {"w": jnp.zeros((8,))}, (jnp.zeros((4, 2)),),
            )


# ---------------------------------------------------------------------------
# live builds on the 8-device mesh
# ---------------------------------------------------------------------------


class TestLiveBuilds:
    def test_dp2tp2_compiled_collectives_equal_declared_plan(
        self, eight_devices
    ):
        """The ISSUE 12 acceptance check: for f32 AND int8 wires the
        dp=2 x tp=2 build's self-verification (sharding conformance +
        reshard plan + memory budget, `analysis.check`) must come back
        with ZERO findings — i.e. the compiled step contains exactly
        the collectives the trainer declared, at the declared wire
        dtypes."""
        for wire in ("f32", "int8"):
            step = build_demo(2, 2, wire=wire, verify="error",
                              hbm_budget=64 << 20)
            assert step.mode == "zero"
            assert step.report is not None
            assert step.report.findings == [], (
                wire, step.report.render()
            )
            for rule in ("sharding", "reshard", "memory"):
                assert rule in step.report.rules_run
            if wire == "int8":
                kinds = {
                    e["kind"] for e in step.expect_plan["collectives"]
                }
                # quantized grads ride all-to-all payloads; the tp
                # activation reduction stays a planned f32 all-reduce
                assert "all-to-all" in kinds and "all-reduce" in kinds

    def test_zero_and_ddp_modes_train_identically(self, eight_devices):
        """The framework's sharding choice must be a LAYOUT decision,
        not a numerics one: forced-replicate and forced-shard builds
        follow the same loss trajectory in f32."""
        losses = {}
        for mode in ("replicate", "shard"):
            step = build_demo(2, 1, update_sharding=mode, verify="off")
            st = step.state
            out = []
            for _ in range(5):
                st, aux = step(st, step.example_batch)
                out.append(float(aux["loss"]))
            losses[mode] = out
        assert losses["replicate"] == pytest.approx(
            losses["shard"], rel=1e-5
        )

    def test_tp2_matches_tp1_numerics(self, eight_devices):
        ref = build_demo(1, 1, verify="off")
        tp2 = build_demo(1, 2, verify="off")
        st_r, st_t = ref.state, tp2.state
        for _ in range(3):
            st_r, aux_r = ref(st_r, ref.example_batch)
            st_t, aux_t = tp2(st_t, tp2.example_batch)
        assert float(aux_t["loss"]) == pytest.approx(
            float(aux_r["loss"]), rel=1e-4
        )

    def test_planted_bogus_plan_fails_the_build(self, eight_devices):
        """A trainer whose declared plan cannot match the compiled step
        must refuse to hand the step out (the self-verification
        contract): planting an undeclarable collective expectation
        raises TrainBuildError naming the reshard rule."""
        from apex_tpu.train.demo import demo_config

        cfg = demo_config(2, 1)
        bogus = dict(
            mesh=cfg.mesh, rules=cfg.rules, optimizer=cfg.optimizer,
            learning_rate=cfg.learning_rate,
            zero_min_bytes=cfg.zero_min_bytes, verify="error",
            model_collectives=[{
                "kind": "all-to-all", "axis": "tp", "count": 7,
                "dtypes": ["s8"],
            }],
        )
        with pytest.raises(TrainBuildError, match="reshard-plan"):
            Trainer(TrainConfig(**bogus)).build(
                demo_loss, demo_params(), demo_batch()
            )

    def test_metrics_fold_rides_aux_and_registry_observes(
        self, eight_devices
    ):
        step = build_demo(2, 1, verify="off")
        assert step.registry is not None
        st, aux = step(step.state, step.example_batch)
        assert "metrics" in aux
        step.registry.observe(1, aux["metrics"])
        step.registry.fetch()
        vals = step.registry.values()
        assert vals["train/loss"] == pytest.approx(
            float(aux["loss"]), rel=1e-6
        )

    def test_optimizer_kwargs_survive_a_mode_flip(self, eight_devices):
        """ONE optimizer_kwargs vocabulary must stay valid whichever
        mode the heuristic picks: beta1/beta2 (the optax spelling) and
        betas (the distributed spelling) both build in BOTH modes —
        the mode is a size heuristic, so growing the model must never
        invalidate the config (code-review regression)."""
        import dataclasses

        from apex_tpu.train.demo import (
            demo_batch, demo_config, demo_loss, demo_params,
        )

        for kwargs in ({"beta1": 0.95, "beta2": 0.98},
                       {"betas": (0.95, 0.98)}):
            for mode in ("replicate", "shard"):
                cfg = dataclasses.replace(
                    demo_config(2, 1, update_sharding=mode,
                                verify="off"),
                    optimizer_kwargs=kwargs,
                )
                step = Trainer(cfg).build(
                    demo_loss, demo_params(), demo_batch()
                )
                st, aux = step(step.state, step.example_batch)
                assert float(aux["loss"]) > 0

    def test_zero_twins_single_source(self):
        from apex_tpu.train import sharding as tsh
        from apex_tpu.train import trainer as ttr

        assert ttr.ZERO_TWINS is tsh.ZERO_TWINS

    def test_track_grad_norm_is_honest_in_zero_mode(self, eight_devices):
        """The gauge must carry the real norm in the update-sharded
        mode too (code-review regression: it silently read 0.0), the
        two layouts must agree on the measured value, and the
        unsupported zero+tp>1 combination must refuse the build instead
        of exporting an overcounted metric."""
        import dataclasses

        from apex_tpu.train.demo import (
            demo_batch, demo_config, demo_loss, demo_params,
        )

        norms = {}
        for mode in ("replicate", "shard"):
            cfg = dataclasses.replace(
                demo_config(2, 1, update_sharding=mode, verify="off"),
                track_grad_norm=True,
            )
            step = Trainer(cfg).build(demo_loss, demo_params(),
                                      demo_batch())
            st, aux = step(step.state, step.example_batch)
            norms[mode] = float(aux["grad_norm"])
            assert norms[mode] > 0, (mode, aux)
            assert float(
                aux["metrics"]["train/grad_norm"]
            ) == pytest.approx(norms[mode])
        # same averaged gradient, two layouts: one norm
        assert norms["shard"] == pytest.approx(
            norms["replicate"], rel=1e-5
        )
        cfg = dataclasses.replace(
            demo_config(2, 2, update_sharding="shard", verify="off"),
            track_grad_norm=True,
        )
        with pytest.raises(TrainBuildError, match="track_grad_norm"):
            Trainer(cfg).build(demo_loss, demo_params(), demo_batch())

    def test_collective_plan_surface(self, eight_devices):
        step = build_demo(2, 2, verify="off")
        plan = step.collective_plan()
        assert plan["mesh"] == {"dp": 2, "tp": 2}
        axes = {e.get("axis") for e in plan["collectives"]}
        assert "dp" in axes and "tp" in axes


# ---------------------------------------------------------------------------
# guarded two-phase build (the resilient example's shape)
# ---------------------------------------------------------------------------


class TestGuarded:
    def _build(self, dp=1, wire="f32", accum=1, verify="off", batch=None):
        from apex_tpu import amp
        from apex_tpu.optimizers import fused_adam
        from apex_tpu.resilience import GradGuard

        trainer = Trainer(TrainConfig(
            mesh={"dp": dp}, rules=[(r".*", P())], wire=wire,
            update_sharding="replicate",
        ))
        params = {"w": jnp.zeros((8, 4), jnp.float32)}
        return trainer.build_guarded(
            lambda p, b: jnp.mean((b[0] @ p["w"] - b[1]) ** 2),
            params,
            tx=fused_adam(1e-2),
            scaler=amp.DynamicLossScaler(init_scale=2.0**10),
            guard=GradGuard(warmup_steps=2),
            accum=accum,
            verify=verify,
            example_batch=batch,
        )

    def _batch(self, accum=1, rows=16):
        x = jnp.ones((accum, rows, 8), jnp.float32)
        return (x, jnp.ones((accum, rows, 4), jnp.float32))

    def test_two_phase_step_runs_and_updates(self):
        g = self._build()
        batch = self._batch()
        loss, scaled = g.compute_grads(
            g.state["params"], g.state["scaler"], batch
        )
        new_state, verdict = g.apply_update(scaled, g.state, loss)
        assert not bool(verdict.skipped)
        assert float(jnp.sum(jnp.abs(new_state["params"]["w"]))) > 0

    def test_guarded_declares_the_example_contract(self):
        g = self._build()
        assert g.expect_sharding["mesh"] == {"dp": 1}
        assert any("params" in r for r, _ in g.shard_rules)
        assert "collectives" in g.expect_plan

    def test_guarded_verify_checks_compute_grads(self, eight_devices):
        g = self._build(dp=8, verify="error", batch=self._batch())
        assert g.dp == 8  # built AND passed its own analysis.check

    def test_guarded_rejects_tp_and_forced_sharding(self):
        trainer = Trainer(TrainConfig(
            mesh={"dp": 2, "tp": 2}, rules=[(r".*", P())],
        ))
        with pytest.raises(TrainBuildError, match="tp"):
            trainer.build_guarded(
                lambda p, b: 0.0, {}, tx=None, scaler=None, guard=None
            )


# ---------------------------------------------------------------------------
# the composed fit loop
# ---------------------------------------------------------------------------


class TestFit:
    def test_fit_runs_resilient_loop_with_goodput(
        self, eight_devices, tmp_path
    ):
        step = build_demo(2, 1, verify="off")
        batch = step.example_batch

        result = step.fit(
            lambda i: batch, 6, directory=str(tmp_path / "ckpt"),
            save_interval_steps=2,
        )
        assert result.last_step == 5
        assert result.steps_run == 6
        snap = step.goodput.snapshot()
        assert snap["accepted"] == 6
        assert snap["goodput"] == 1.0
        # the run checkpointed: a fresh fit resumes from the last
        # interval save (step 4) and replays only the tail
        step2 = build_demo(2, 1, verify="off")
        result2 = step2.fit(
            lambda i: batch, 6, directory=str(tmp_path / "ckpt"),
        )
        assert result2.resumed_from == 4
        assert result2.steps_run == 1
        assert result2.last_step == 5
