"""Serving subsystem — paged cache, AOT engine, continuous batching.

Covers the ISSUE 7 acceptance surface: page-pool alloc/free/exhaustion
+ shedding, continuous-batching admission order and mid-stream
admission (batch fill above the single-request baseline), prefill and
decode numerics against the UNPAGED ``GptModel.apply`` reference at f32
and int8-KV, the ``analysis.check`` zero-ERROR pin on both AOT step
programs, and the serving watchdog rules.  The decode-attention kernel
parity tests live beside the flash-attention tests
(``tests/test_attention.py::TestPagedDecodeAttention``).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from apex_tpu.models.gpt import GptConfig, GptModel, _tied_vocab_logits
from apex_tpu.serve import (
    ContinuousBatchingScheduler,
    InferenceEngine,
    NULL_PAGE,
    PagePool,
    Request,
    ServeConfig,
)
from apex_tpu.serve import cache as cache_lib
from apex_tpu.serve import model as serve_model

#: pinned serving-numerics envelopes on last-position logits vs the
#: unpaged f32 reference (tools/serve_bench.py pins the same numbers)
TOL_F32 = 2e-4
TOL_INT8_KV = 5e-2


def tiny_cfg(**kw):
    base = dict(
        vocab_size=64, hidden_size=32, num_layers=2, num_heads=2,
        intermediate_size=64, max_seq_len=128, dtype=jnp.float32,
    )
    base.update(kw)
    return GptConfig(**base)


@pytest.fixture(scope="module")
def gpt():
    cfg = tiny_cfg()
    model = GptModel(cfg)
    params = model.init(
        jax.random.PRNGKey(0), jnp.zeros((8, 1), jnp.int32)
    )
    return cfg, model, params


def make_engine(gpt, **serve_kw):
    cfg, _, params = gpt
    kw = dict(
        page_size=8, num_pages=32, max_batch=2, max_pages_per_seq=8,
        verify=False,
    )
    kw.update(serve_kw)
    return InferenceEngine(cfg, params, ServeConfig(**kw))


def ref_logits(model, params, token_ids):
    """Unpaged reference: full forward, all positions' logits."""
    ids = jnp.asarray(np.asarray(token_ids, np.int32)[:, None])
    h = model.apply(params, ids)
    return np.asarray(
        _tied_vocab_logits(params, model, h, sp_gathered=False)[:, 0]
    )


# ---------------------------------------------------------------------------
# page pool
# ---------------------------------------------------------------------------


class TestPagePool:
    def test_alloc_free_roundtrip(self):
        pool = PagePool(num_pages=8, page_size=4)
        assert pool.usable == 7 and pool.available == 7
        got = pool.alloc(3)
        assert len(got) == 3 and NULL_PAGE not in got
        assert pool.in_use == 3
        pool.free(got)
        assert pool.available == 7 and pool.occupancy() == 0.0

    def test_alloc_is_all_or_nothing(self):
        pool = PagePool(num_pages=4, page_size=4)
        assert pool.alloc(5) is None
        # the failed alloc must not leak pages
        assert pool.available == 3
        assert len(pool.alloc(3)) == 3
        assert pool.alloc(1) is None

    def test_double_free_and_bad_ids_raise(self):
        pool = PagePool(num_pages=8, page_size=4)
        got = pool.alloc(2)
        pool.free(got)
        with pytest.raises(ValueError, match="double free"):
            pool.free([got[0]])
        with pytest.raises(ValueError):
            pool.free([NULL_PAGE])

    def test_pages_for(self):
        pool = PagePool(num_pages=8, page_size=4)
        assert pool.pages_for(0) == 0
        assert pool.pages_for(1) == 1
        assert pool.pages_for(4) == 1
        assert pool.pages_for(5) == 2


# ---------------------------------------------------------------------------
# cache device helpers
# ---------------------------------------------------------------------------


class TestCacheWrites:
    def test_prompt_pages_roundtrip(self):
        rs = np.random.RandomState(0)
        s, h, d, page = 16, 2, 8, 4
        kv = jnp.asarray(rs.randn(1, s, h, d), jnp.float32)  # one layer
        pages = jnp.zeros((1, 10, h, page, d), jnp.float32)
        ids = jnp.asarray([3, 5, 2, 7], jnp.int32)
        blocks = jax.vmap(
            lambda t: cache_lib.pack_prompt_pages(t, page)
        )(kv)
        out = cache_lib.write_prompt_pages(pages, blocks, ids)
        # gather back in table order and compare to the original rows
        got = jnp.moveaxis(out[0][ids], 1, 0).reshape(h, s, d)
        np.testing.assert_array_equal(
            np.asarray(got), np.asarray(jnp.transpose(kv[0], (1, 0, 2)))
        )

    def test_append_token_roundtrip(self):
        rs = np.random.RandomState(1)
        h, d, page = 2, 8, 4
        pages = jnp.zeros((6, h, page, d), jnp.float32)
        rows = jnp.asarray(rs.randn(3, h, d), jnp.float32)
        pids = jnp.asarray([1, 4, 2], jnp.int32)
        slots = jnp.asarray([0, 3, 1], jnp.int32)
        out = cache_lib.append_token_kv(pages, rows, pids, slots)
        for b in range(3):
            np.testing.assert_array_equal(
                np.asarray(out[pids[b], :, slots[b]]),
                np.asarray(rows[b]),
            )

    def test_int8_encode_roundtrip(self):
        rs = np.random.RandomState(2)
        x = jnp.asarray(rs.randn(4, 2, 8) * 3.0, jnp.float32)
        codes, scale = cache_lib.encode_kv(x)
        assert codes.dtype == jnp.int8 and scale.shape == (4, 2)
        back = codes.astype(jnp.float32) * scale[..., None]
        assert float(jnp.abs(back - x).max()) <= float(
            jnp.abs(x).max()
        ) / 127.0 + 1e-6


# ---------------------------------------------------------------------------
# engine numerics vs the unpaged reference
# ---------------------------------------------------------------------------


class TestEngineNumerics:
    def test_prefill_matches_unpaged_reference(self, gpt):
        cfg, model, params = gpt
        eng = make_engine(gpt)
        rs = np.random.RandomState(3)
        prompt = [int(t) for t in rs.randint(0, cfg.vocab_size, size=21)]
        pages = eng.pool.alloc(eng.pool.pages_for(len(prompt)))
        logits, tok = eng.prefill(prompt, pages)
        ref = ref_logits(model, params, prompt)[-1]
        assert np.abs(logits - ref).max() <= 1e-5
        assert tok == int(np.argmax(ref))

    @pytest.mark.parametrize("kv_wire,tol", [
        ("f32", TOL_F32), ("int8", TOL_INT8_KV),
    ])
    def test_decode_matches_unpaged_reference(self, gpt, kv_wire, tol):
        """Greedy continuation through the paged decode step stays
        within the pinned envelope of the growing full forward — and
        at f32 the generated TOKENS are identical."""
        cfg, model, params = gpt
        eng = make_engine(gpt, kv_wire=kv_wire)
        rs = np.random.RandomState(4)
        prompt = [int(t) for t in rs.randint(0, cfg.vocab_size, size=13)]
        pages = eng.pool.alloc(eng.pool.pages_for(len(prompt)))
        _, tok = eng.prefill(prompt, pages)
        cur = list(prompt)
        ctx = len(prompt)
        table = np.zeros((2, 8), np.int32)
        for _ in range(5):
            if ctx // 8 >= len(pages):
                pages += eng.pool.alloc(1)
            table[0, : len(pages)] = pages
            logits, nxt = eng.decode(
                np.array([tok, 0]), np.array([ctx + 1, 0]), table
            )
            cur.append(tok)
            ref = ref_logits(model, params, cur)[-1]
            assert np.abs(logits[0] - ref).max() <= tol, kv_wire
            if kv_wire == "f32":
                assert int(nxt[0]) == int(np.argmax(ref))
            ctx += 1
            tok = int(nxt[0])

    def test_weight_wire_int8_stays_close(self, gpt):
        cfg, model, params = gpt
        eng = make_engine(gpt, weight_wire="int8")
        rs = np.random.RandomState(5)
        prompt = [int(t) for t in rs.randint(0, cfg.vocab_size, size=9)]
        pages = eng.pool.alloc(eng.pool.pages_for(len(prompt)))
        logits, _ = eng.prefill(prompt, pages)
        ref = ref_logits(model, params, prompt)[-1]
        # int8 weights: codec noise only, scaled by logit magnitude
        assert np.abs(logits - ref).max() <= 0.15 * max(
            1.0, np.abs(ref).max()
        )

    def test_packed_weight_roundtrip(self, gpt):
        _, _, params = gpt
        q = serve_model.quantize_params(params)
        back = serve_model.dequantize_params(q)
        for a, b in zip(
            jax.tree_util.tree_leaves(params),
            jax.tree_util.tree_leaves(back),
        ):
            assert a.shape == b.shape and a.dtype == b.dtype
            scale = max(1e-6, float(jnp.abs(a).max()))
            # blockwise int8: worst-case error is one quantization step
            assert float(jnp.abs(a - b).max()) <= scale / 127.0 + 1e-6


# ---------------------------------------------------------------------------
# AOT + analysis pins
# ---------------------------------------------------------------------------


class TestEngineBuild:
    def test_analysis_zero_errors_on_both_steps(self, gpt):
        """The ISSUE 7 acceptance pin: analysis.check runs over the
        AOT prefill AND decode programs at build and reports zero
        ERRORs (transfer-free + donation-aliased), for both KV
        wires."""
        for wire in ("f32", "int8"):
            eng = make_engine(gpt, kv_wire=wire, verify=True)
            eng.build(buckets=(16,))
            assert set(eng.reports) == {"prefill_16", "decode"}
            for name, report in eng.reports.items():
                assert report.errors() == [], (wire, name, report.render())
                assert "transfer" in report.rules_run
                assert "donation" in report.rules_run

    def test_lint_surface_is_clean(self, gpt):
        report = make_engine(gpt).lint()
        assert report.errors() == [], report.render()
        assert report.target == "serve"
        # the ISSUE 9 artifact sections ride the serve lint too
        blob = report.to_json()
        assert blob["peak_hbm_bytes"] > 0
        assert set(blob["peak_hbm_by_program"]) == {
            "serve/prefill_8", "serve/decode"}

    def test_hbm_budget_gate_fails_build(self, gpt):
        """The ISSUE 9 serve satellite: a pool that never fit is a
        BUILD error (memory-budget), not a step-0 OOM; a generous
        budget builds and publishes the peak gauge."""
        from apex_tpu.observability.metrics import board

        eng = make_engine(gpt, verify=True, hbm_budget_bytes=1 << 10)
        with pytest.raises(RuntimeError, match="memory-budget"):
            eng.build(buckets=(16,))

        board.clear()
        ok = make_engine(gpt, verify=True, hbm_budget_bytes=64 << 20)
        ok.build(buckets=(16,))
        peak = board.get("serve/peak_hbm_bytes")
        assert peak and 0 < peak <= 64 << 20
        # the KV page pool (static shape) is part of the budgeted peak
        pool_bytes = sum(
            np.asarray(leaf).nbytes
            for leaf in jax.tree_util.tree_leaves(ok.cache)
        )
        assert peak >= pool_bytes
        board.clear()

    def test_aot_compiles_once_no_retrace(self, gpt):
        """Steady-state serving never recompiles: many prefill/decode
        calls leave exactly one compile per program and zero sentinel
        retraces."""
        eng = make_engine(gpt)
        rs = np.random.RandomState(6)
        table = np.zeros((2, 8), np.int32)
        for i in range(4):
            prompt = [int(t) for t in rs.randint(0, 64, size=5 + i)]
            pages = eng.pool.alloc(1)
            _, tok = eng.prefill(prompt, pages)
            table[0, :1] = pages
            eng.decode(
                np.array([tok, 0]),
                np.array([len(prompt) + 1, 0]), table,
            )
            eng.pool.free(pages)
        assert eng.compile_counts == {"prefill_8": 1, "decode": 1}
        assert eng.retraces == 0

    def test_config_validation(self, gpt):
        cfg, _, params = gpt
        with pytest.raises(ValueError, match="max_seq_len"):
            InferenceEngine(
                cfg, params,
                ServeConfig(page_size=8, num_pages=128,
                            max_pages_per_seq=64),
            )
        with pytest.raises(ValueError, match="cannot hold even one"):
            ServeConfig(page_size=8, num_pages=4, max_pages_per_seq=8)
        with pytest.raises(ValueError, match="sequence_parallel"):
            serve_model.validate_config(
                tiny_cfg(sequence_parallel=True)
            )
        with pytest.raises(ValueError, match="kv_wire"):
            ServeConfig(kv_wire="fp8")

    def test_prompt_over_max_context_rejected(self, gpt):
        eng = make_engine(gpt, max_pages_per_seq=2)
        with pytest.raises(ValueError, match="exceeds the max context"):
            eng.bucket_for(17)


# ---------------------------------------------------------------------------
# continuous batching scheduler
# ---------------------------------------------------------------------------


class FakeClock:
    def __init__(self):
        self.t = 0.0

    def __call__(self):
        self.t += 1e-4  # every read advances a hair (monotonic)
        return self.t

    def advance(self, dt):
        self.t += dt


class TestScheduler:
    def _prompt(self, rs, n):
        return [int(t) for t in rs.randint(0, 64, size=n)]

    def test_fifo_admission_and_drain(self, gpt):
        eng = make_engine(gpt)
        sched = ContinuousBatchingScheduler(eng)
        rs = np.random.RandomState(7)
        reqs = [
            sched.submit(Request(prompt=self._prompt(rs, 6),
                                 max_new_tokens=3))
            for _ in range(4)
        ]
        sched.run()
        assert [r.rid for r in sched.completed] == [r.rid for r in reqs]
        assert all(len(r.tokens) == 3 for r in sched.completed)
        assert all(r.ttft_ms is not None for r in sched.completed)
        assert eng.pool.in_use == 0  # every page returned

    def test_mid_stream_admission_raises_batch_fill(self, gpt):
        """A request submitted while another is mid-decode joins the
        RUNNING batch (continuous batching), pushing batch fill above
        the single-request baseline."""
        eng = make_engine(gpt)
        sched = ContinuousBatchingScheduler(eng)
        rs = np.random.RandomState(8)
        first = sched.submit(Request(prompt=self._prompt(rs, 6),
                                     max_new_tokens=12))
        fills = []
        sched.step()  # admit + first decode of request 1, alone
        baseline = sched.batch_fill()
        assert baseline == 0.5  # 1 of 2 slots
        second = sched.submit(Request(prompt=self._prompt(rs, 6),
                                      max_new_tokens=4))
        while sched.pending:
            sched.step()
            fills.append(sched.batch_fill())
        assert max(fills) == 1.0  # both ran TOGETHER mid-stream
        assert second.status == "done" and first.status == "done"
        # the short request finished while the long one kept running
        assert second.done_at < first.done_at

    def test_pool_exhaustion_sheds_past_deadline(self, gpt):
        """Admission backpressure: with the pool pinned by a running
        request, a queued request waits — and is shed (not silently
        starved) once its TTFT SLO deadline passes."""
        eng = make_engine(gpt, num_pages=3, max_pages_per_seq=2)
        clock = FakeClock()
        sched = ContinuousBatchingScheduler(eng, clock=clock)
        rs = np.random.RandomState(9)
        # the hog still holds the whole pool when the starved request's
        # deadline is judged (it keeps decoding past step 1)
        hog = sched.submit(Request(prompt=self._prompt(rs, 14),
                                   max_new_tokens=4))
        starved = sched.submit(Request(prompt=self._prompt(rs, 14),
                                       max_new_tokens=2,
                                       slo_ttft_ms=500.0))
        sched.step()  # hog admitted (2 pages = whole pool), starved waits
        assert hog.status in ("running", "done")
        assert starved.status == "queued"
        clock.advance(1.0)  # blow the 500ms deadline
        sched.run()
        assert starved.status == "shed"
        assert starved.shed_reason == "deadline"  # the split-counter pin
        assert hog.status == "done"
        assert eng.pool.in_use == 0

    def test_growth_page_exhaustion_sheds_youngest(self, gpt):
        """Mid-decode pool exhaustion sheds the YOUNGEST running
        request so older ones keep making progress."""
        eng = make_engine(gpt, num_pages=4, max_pages_per_seq=3)
        clock = FakeClock()
        sched = ContinuousBatchingScheduler(eng, clock=clock)
        rs = np.random.RandomState(10)
        # both need a growth page mid-generation: 8-token prompts fill
        # one page exactly, decode crosses into a second page
        old = sched.submit(Request(prompt=self._prompt(rs, 8),
                                   max_new_tokens=10))
        young = sched.submit(Request(prompt=self._prompt(rs, 8),
                                     max_new_tokens=10))
        # a third hogs the remaining page so growth must fail
        hog = sched.submit(Request(prompt=self._prompt(rs, 8),
                                   max_new_tokens=1))
        sched.run()
        assert old.status == "done" and len(old.tokens) == 10
        assert young.status == "shed"
        assert young.shed_reason == "growth_victim"
        assert hog.status == "done"
        assert eng.pool.in_use == 0

    def test_oversize_prompt_is_shed(self, gpt):
        eng = make_engine(gpt, max_pages_per_seq=2)  # 16-token context
        sched = ContinuousBatchingScheduler(eng)
        rs = np.random.RandomState(11)
        too_big = sched.submit(Request(prompt=self._prompt(rs, 20)))
        ok = sched.submit(Request(prompt=self._prompt(rs, 6),
                                  max_new_tokens=2))
        sched.run()
        assert too_big.status == "shed"
        assert too_big.shed_reason == "oversize"
        assert ok.status == "done"

    def test_metrics_flow_through_registry(self, gpt):
        from apex_tpu.observability import MetricRegistry

        eng = make_engine(gpt)
        reg = MetricRegistry(fetch_every=1)
        sched = ContinuousBatchingScheduler(eng, registry=reg)
        rs = np.random.RandomState(12)
        for _ in range(3):
            sched.submit(Request(prompt=self._prompt(rs, 6),
                                 max_new_tokens=2))
        sched.run()
        reg.fetch()
        vals = reg.values()
        assert vals["serve/completed"] == 3.0
        assert vals["serve/admitted"] == 3.0
        assert vals["serve/shed"] == 0.0
        assert vals["serve/tokens_out"] == 6.0
        assert vals["serve/ttft_ms"] > 0.0
        assert vals["serve/tokens_per_s"] >= 0.0
        # the shed breakdown sums to the total (here: all zero)
        from apex_tpu.serve import SHED_REASONS, TTFT_COMPONENTS

        assert vals["serve/shed"] == sum(
            vals[f"serve/shed_{r}"] for r in SHED_REASONS
        )
        # TTFT attribution percentiles ride the same registry, and the
        # components sum to the TTFT gauge on every completed request
        for comp in TTFT_COMPONENTS:
            for tag in ("p50", "p95", "p99"):
                assert f"serve/ttft_{comp}_ms_{tag}" in vals
        assert vals["serve/ttft_prefill_ms_p50"] > 0.0
        for r in sched.completed:
            c = r.ttft_components()
            assert (
                c["queue_wait_ms"] + c["prefill_ms"] + c["contention_ms"]
            ) == pytest.approx(c["ttft_ms"], abs=1e-6)


# ---------------------------------------------------------------------------
# serving watchdog rules
# ---------------------------------------------------------------------------


class TestServeHealthRules:
    def _registry(self, **values):
        from apex_tpu.observability import MetricRegistry
        from apex_tpu.serve import declare_serve_metrics

        reg = MetricRegistry(fetch_every=1)
        declare_serve_metrics(reg)
        state = reg.update(reg.init(), values)
        reg.observe(0, state)
        reg.observe(1, state)
        reg.fetch()
        return reg

    def test_ttft_rule_fires_and_escalates(self):
        from apex_tpu.observability import TTFTRule, Watchdog, serve_rules

        reg = self._registry(**{"serve/ttft_ms": 2500.0})
        wd = Watchdog(
            serve_rules(ttft={"deadline_ms": 1000.0}),
            registry=reg, check_every=1,
        )
        wd.on_step(1)
        events = [e for e in wd.events if e.rule == "ttft"]
        assert len(events) == 1
        assert events[0].severity == "critical"  # > 2x deadline
        # under the deadline: silent
        rule = TTFTRule(deadline_ms=5000.0)
        reg2 = self._registry(**{"serve/ttft_ms": 100.0})
        wd2 = Watchdog([rule], registry=reg2, check_every=1)
        wd2.on_step(1)
        assert wd2.events == []

    def test_queue_depth_rule(self):
        from apex_tpu.observability import Watchdog, serve_rules

        reg = self._registry(**{"serve/queue_depth": 40.0})
        wd = Watchdog(
            serve_rules(queue_depth={"max_depth": 16}),
            registry=reg, check_every=1,
        )
        wd.on_step(1)
        events = [e for e in wd.events if e.rule == "queue_depth"]
        assert len(events) == 1 and events[0].severity == "warn"

    def test_serve_rules_rejects_unknown(self):
        from apex_tpu.observability import serve_rules

        with pytest.raises(ValueError, match="unknown serve health"):
            serve_rules(mfu_floor={})


class TestPagePoolLeakCheck:
    def test_exact_ownership_passes(self):
        pool = PagePool(num_pages=8, page_size=4)
        a = pool.alloc(2)
        b = pool.alloc(3)
        pool.leak_check([a, b])
        pool.free(b)
        pool.leak_check([a])
        pool.free(a)
        pool.leak_check([])

    def test_leaked_page_named(self):
        pool = PagePool(num_pages=8, page_size=4)
        a = pool.alloc(2)
        with pytest.raises(ValueError, match=rf"leaked.*{a[1]}"):
            pool.leak_check([[a[0]]])

    def test_foreign_page_named(self):
        pool = PagePool(num_pages=8, page_size=4)
        a = pool.alloc(1)
        free_page = 7 if a[0] != 7 else 6
        with pytest.raises(ValueError, match="foreign"):
            pool.leak_check([a, [free_page]])

    def test_double_owned_page_named(self):
        pool = PagePool(num_pages=8, page_size=4)
        a = pool.alloc(2)
        with pytest.raises(ValueError, match="more than one request"):
            pool.leak_check([a, [a[0]]])


# ---------------------------------------------------------------------------
# serving resilience: retries, quarantine, timeouts, ladder, drain
# (docs/serving.md "Failure semantics & degradation ladder")
# ---------------------------------------------------------------------------


def _registry():
    from apex_tpu.observability import MetricRegistry

    return MetricRegistry(fetch_every=1)


def _vals(reg):
    reg.fetch()
    return reg.values()


class TestServeResilience:
    def _prompt(self, rs, n):
        return [int(t) for t in rs.randint(0, 64, size=n)]

    def test_decode_fault_retries_preserve_prefix(self, gpt):
        """A crashed decode iteration sends every rider through
        bounded re-admission with pages and prefix retained — the
        resumed f32 token stream is BIT-IDENTICAL to an unfaulted
        run's (the scheduler-level half of the rebuild-determinism
        satellite)."""
        from apex_tpu.resilience import chaos

        rs = np.random.RandomState(20)
        prompts = [self._prompt(rs, 6) for _ in range(2)]

        def run(faults):
            eng = make_engine(gpt)
            sched = ContinuousBatchingScheduler(eng)
            with chaos.inject(*faults):
                reqs = [
                    sched.submit(Request(prompt=list(p), max_new_tokens=6))
                    for p in prompts
                ]
                sched.run()
            return eng, sched, [r.tokens for r in reqs]

        _, _, clean = run(())
        eng, sched, faulted = run(
            (chaos.Fault(chaos.SERVE_DECODE, steps=(2,), mode="raise",
                         max_hits=1),)
        )
        assert faulted == clean  # prefix preserved, resume exact
        assert eng.rebuilds == 1  # deferred rebuild flushed at idle
        assert all(r.status == "done" for r in sched.completed)
        assert sched.pool.in_use == 0
        assert sched.leak_checks_run > 0

    def test_persistent_decode_fault_exhausts_rebuild_limit(self, gpt):
        from apex_tpu.resilience import chaos

        eng = make_engine(gpt)
        sched = ContinuousBatchingScheduler(eng, rebuild_limit=1)
        rs = np.random.RandomState(21)
        with chaos.inject(chaos.Fault(
            chaos.SERVE_DECODE, steps=tuple(range(64)), mode="raise",
        )):
            sched.submit(Request(prompt=self._prompt(rs, 6),
                                 max_new_tokens=4))
            with pytest.raises(RuntimeError, match="rebuild_limit"):
                sched.run()

    def test_prefill_fault_retried_then_shed_when_persistent(self, gpt):
        from apex_tpu.resilience import chaos

        rs = np.random.RandomState(22)
        # transient: one fault, heals on retry
        eng = make_engine(gpt)
        sched = ContinuousBatchingScheduler(eng)
        with chaos.inject(chaos.Fault(
            chaos.SERVE_PREFILL, steps=(0,), mode="raise", max_hits=1,
        )):
            req = sched.submit(Request(prompt=self._prompt(rs, 6),
                                       max_new_tokens=2))
            sched.run()
        assert req.status == "done" and req.retries == 1
        assert len(req.tokens) == 2
        # persistent: the re-admission budget bounds the loop
        eng2 = make_engine(gpt)
        reg = _registry()
        sched2 = ContinuousBatchingScheduler(
            eng2, registry=reg, max_retries=2,
        )
        with chaos.inject(chaos.Fault(
            chaos.SERVE_PREFILL, steps=tuple(range(16)), mode="raise",
        )):
            req2 = sched2.submit(Request(prompt=self._prompt(rs, 6)))
            sched2.run()
        assert req2.status == "shed"
        assert req2.shed_reason == "retries_exhausted"
        assert req2.retries == 2
        assert sched2.pool.in_use == 0  # retained pages freed at shed
        vals = _vals(reg)
        assert vals["serve/shed_retries_exhausted"] == 1.0
        assert vals["serve/retries"] == 2.0
        assert vals["serve/engine_faults"] == 3.0  # initial + 2 retries

    def test_poisoned_decode_evicts_only_offending_slot(self, gpt):
        """Non-finite logits quarantine THE slot, never the batch: the
        co-resident request keeps the tokens of that very iteration."""
        from apex_tpu.resilience import chaos

        eng = make_engine(gpt)
        reg = _registry()
        sched = ContinuousBatchingScheduler(eng, registry=reg)
        rs = np.random.RandomState(23)
        victim = sched.submit(Request(prompt=self._prompt(rs, 6),
                                      max_new_tokens=8))
        bystander = sched.submit(Request(prompt=self._prompt(rs, 6),
                                         max_new_tokens=8))
        with chaos.inject(chaos.Fault(
            chaos.SERVE_DECODE, steps=(1,), mode="nan", max_hits=1,
        )):
            sched.run()
        assert victim.status == "shed"
        assert victim.shed_reason == "poisoned"
        assert bystander.status == "done"
        assert len(bystander.tokens) == 8
        assert sched.pool.in_use == 0
        vals = _vals(reg)
        assert vals["serve/shed_poisoned"] == 1.0
        assert vals["serve/shed"] == 1.0

    def test_poisoned_prefill_quarantined_at_first_token(self, gpt):
        from apex_tpu.resilience import chaos

        eng = make_engine(gpt)
        sched = ContinuousBatchingScheduler(eng)
        rs = np.random.RandomState(24)
        with chaos.inject(chaos.Fault(
            chaos.SERVE_PREFILL, steps=(0,), mode="nan", max_hits=1,
        )):
            req = sched.submit(Request(prompt=self._prompt(rs, 6)))
            sched.run()
        assert req.status == "shed" and req.shed_reason == "poisoned"
        assert req.tokens == []  # the poisoned first token is not kept
        assert sched.pool.in_use == 0

    def test_decode_timeout_is_per_request(self, gpt):
        """A chaos stall makes one iteration slow; ONLY the request
        carrying a decode timeout discards that iteration and goes
        through retry — its co-rider keeps the token."""
        from apex_tpu.resilience import chaos

        eng = make_engine(gpt)
        reg = _registry()
        sched = ContinuousBatchingScheduler(eng, registry=reg,
                                            max_retries=8)
        rs = np.random.RandomState(25)
        timed = sched.submit(Request(prompt=self._prompt(rs, 6),
                                     max_new_tokens=4,
                                     decode_timeout_ms=20.0))
        free = sched.submit(Request(prompt=self._prompt(rs, 6),
                                    max_new_tokens=4))
        with chaos.inject(chaos.Fault(
            chaos.SERVE_DECODE, steps=(1,), mode="stall", max_hits=1,
        )):
            sched.run()
        assert timed.status == "done" and timed.retries >= 1
        assert free.status == "done" and free.retries == 0
        assert len(timed.tokens) == 4 and len(free.tokens) == 4
        assert _vals(reg)["serve/decode_timeouts"] >= 1.0

    def test_admission_fault_is_transient(self, gpt):
        from apex_tpu.resilience import chaos

        eng = make_engine(gpt)
        reg = _registry()
        sched = ContinuousBatchingScheduler(eng, registry=reg)
        rs = np.random.RandomState(26)
        with chaos.inject(chaos.Fault(
            chaos.SERVE_ADMISSION, steps=(0, 1), mode="raise",
        )):
            req = sched.submit(Request(prompt=self._prompt(rs, 6),
                                       max_new_tokens=2))
            sched.run()
        assert req.status == "done"
        assert _vals(reg)["serve/admission_faults"] == 2.0

    def test_kv_alloc_fault_degrades_gracefully(self, gpt):
        from apex_tpu.resilience import chaos

        eng = make_engine(gpt)
        reg = _registry()
        sched = ContinuousBatchingScheduler(eng, registry=reg)
        rs = np.random.RandomState(27)
        with chaos.inject(chaos.Fault(
            chaos.SERVE_KV_ALLOC, steps=(0,), mode="fail", max_hits=1,
        )):
            req = sched.submit(Request(prompt=self._prompt(rs, 6),
                                       max_new_tokens=2))
            sched.run()
        assert req.status == "done"  # waited one iteration, then ran
        assert _vals(reg)["serve/kv_alloc_faults"] == 1.0

    def test_queue_cap_fast_rejects_excess(self, gpt):
        eng = make_engine(gpt)
        reg = _registry()
        sched = ContinuousBatchingScheduler(eng, registry=reg,
                                            max_queue_depth=2)
        rs = np.random.RandomState(28)
        reqs = [
            sched.submit(Request(prompt=self._prompt(rs, 6),
                                 max_new_tokens=2))
            for _ in range(5)
        ]
        rejected = [r for r in reqs if r.shed_reason == "queue_full"]
        assert len(rejected) == 3  # exactly the over-cap excess
        assert all(r.done_at is not None for r in rejected)
        sched.run()
        assert [r.status for r in reqs[:2]] == ["done", "done"]
        vals = _vals(reg)
        assert vals["serve/shed_queue_full"] == 3.0
        assert vals["serve/shed"] == 3.0

    def test_clamp_rung_bounds_token_budget(self, gpt):
        eng = make_engine(gpt, num_pages=9, max_pages_per_seq=4)
        reg = _registry()
        sched = ContinuousBatchingScheduler(
            eng, registry=reg,
            clamp_max_new_tokens=2, clamp_occupancy=0.25,
        )
        rs = np.random.RandomState(29)
        first = sched.submit(Request(prompt=self._prompt(rs, 16),
                                     max_new_tokens=10))
        second = sched.submit(Request(prompt=self._prompt(rs, 16),
                                      max_new_tokens=10))
        sched.run()
        # occupancy crossed the threshold once the first was resident
        assert first.status == "done" and second.status == "done"
        assert second.clamped_from == 10
        assert second.max_new_tokens == 2 and len(second.tokens) == 2
        assert _vals(reg)["serve/clamped"] >= 1.0

    def test_drain_finishes_running_and_sheds_queued(self, gpt):
        eng = make_engine(gpt)  # max_batch=2
        reg = _registry()
        sched = ContinuousBatchingScheduler(eng, registry=reg)
        rs = np.random.RandomState(30)
        reqs = [
            sched.submit(Request(prompt=self._prompt(rs, 6),
                                 max_new_tokens=6))
            for _ in range(4)
        ]
        sched.step()  # two admitted, two still queued
        report = sched.drain()
        assert report["drained"] and report["pool_in_use"] == 0
        assert [r.status for r in reqs[:2]] == ["done", "done"]
        assert all(r.shed_reason == "draining" for r in reqs[2:])
        vals = _vals(reg)
        assert vals["serve/drains"] == 1.0
        assert vals["serve/shed_draining"] == 2.0
        # a drained scheduler rejects new work loudly
        late = sched.submit(Request(prompt=self._prompt(rs, 6)))
        assert late.status == "shed" and late.shed_reason == "draining"

    def test_step_loop_flushes_deferred_rebuild_at_idle(self, gpt):
        """A caller-driven step() loop (the documented drive pattern)
        must still execute the deferred rebuild once the scheduler
        goes idle — not only run()/drain()."""
        from apex_tpu.resilience import chaos

        eng = make_engine(gpt)
        sched = ContinuousBatchingScheduler(eng)
        rs = np.random.RandomState(34)
        with chaos.inject(chaos.Fault(
            chaos.SERVE_DECODE, steps=(1,), mode="raise", max_hits=1,
        )):
            req = sched.submit(Request(prompt=self._prompt(rs, 6),
                                       max_new_tokens=4))
            while sched.pending:
                sched.step()
        assert req.status == "done"
        assert eng.rebuilds == 1  # flushed by step(), off the traffic path

    def test_resume_clears_drained_state_and_gauge(self, gpt):
        eng = make_engine(gpt)
        reg = _registry()
        sched = ContinuousBatchingScheduler(eng, registry=reg)
        rs = np.random.RandomState(35)
        sched.drain()
        rejected = sched.submit(Request(prompt=self._prompt(rs, 6)))
        assert rejected.shed_reason == "draining"
        sched.resume()
        accepted = sched.submit(Request(prompt=self._prompt(rs, 6),
                                        max_new_tokens=2))
        sched.run()
        assert accepted.status == "done"
        assert _vals(reg)["serve/draining"] == 0.0

    def test_drain_handoff_reroutes_instead_of_shedding(self, gpt):
        """The fleet hook (docs/serving.md "Fleet operations"): with a
        ``handoff``, drain hands never-admitted work out instead of
        shedding it — ledgered as the DISTINCT ``rerouted`` reason
        (still summing into ``serve/shed``), but NOT terminal: no shed
        span, no ``sched.shed`` entry, the request continues
        elsewhere."""
        eng = make_engine(gpt)  # max_batch=2
        reg = _registry()
        sched = ContinuousBatchingScheduler(eng, registry=reg)
        rs = np.random.RandomState(36)
        reqs = [
            sched.submit(Request(prompt=self._prompt(rs, 6),
                                 max_new_tokens=6))
            for _ in range(4)
        ]
        sched.step()  # two admitted, two still queued
        handed = []

        def handoff(r):
            handed.append(r)
            return True

        report = sched.drain(handoff=handoff)
        assert report["drained"] and report["pool_in_use"] == 0
        assert report["rerouted"] == 2
        assert [r.status for r in reqs[:2]] == ["done", "done"]
        assert handed == reqs[2:]
        # re-routed requests are NOT terminal on this replica
        assert all(r.status == "queued" for r in handed)
        assert all(r.shed_reason is None for r in handed)
        assert all(not r.pages for r in handed)  # pages replica-local
        assert sched.shed == []
        vals = _vals(reg)
        assert vals["serve/shed_rerouted"] == 2.0
        assert vals["serve/shed"] == 2.0  # breakdown still sums
        assert vals["serve/shed_draining"] == 0.0

    def test_incremental_drain_start_finish_split(self, gpt):
        """A fleet control plane drains a replica INCREMENTALLY:
        ``start_drain`` now, caller-driven ``step`` ticks, then
        ``finish_drain`` seals with the pool re-proven empty."""
        eng = make_engine(gpt)
        sched = ContinuousBatchingScheduler(eng)
        rs = np.random.RandomState(37)
        reqs = [
            sched.submit(Request(prompt=self._prompt(rs, 6),
                                 max_new_tokens=6))
            for _ in range(2)
        ]
        sched.step()
        rerouted = sched.start_drain(handoff=lambda r: True)
        assert sched.draining and rerouted == 0  # both were admitted
        steps = 0
        while sched.pending:
            sched.step()
            steps += 1
        assert steps > 0  # the drain really spanned ticks
        report = sched.finish_drain()
        assert report["drained"] and report["pool_in_use"] == 0
        assert all(r.status == "done" for r in reqs)

    def test_shed_breakdown_still_sums_with_new_reasons(self, gpt):
        from apex_tpu.observability.ometrics import metric_name
        from apex_tpu.serve import SHED_REASONS

        assert {"poisoned", "queue_full", "retries_exhausted",
                "draining", "rerouted"} < set(SHED_REASONS)
        # the per-reason ledger counters must stay injective on the
        # OpenMetrics export: two reasons mapping to one exposition
        # family would silently merge on every fleet aggregation
        exported = [metric_name(f"serve/shed_{r}") for r in SHED_REASONS]
        assert len(set(exported)) == len(SHED_REASONS)


class TestEngineRecovery:
    def test_rebuild_decode_is_bit_identical(self, gpt):
        """Satellite: a restored engine's decode over RETAINED KV
        pages is bit-identical to the uninterrupted run — same pin
        style as goodput's resume-loss-drift check (drift must be 0.0,
        not small)."""
        cfg, _, _ = gpt
        rs = np.random.RandomState(31)
        prompt = [int(t) for t in rs.randint(0, cfg.vocab_size, size=9)]

        def decode_stream(rebuild_at):
            eng = make_engine(gpt)
            pages = eng.pool.alloc(eng.pool.pages_for(len(prompt)))
            _, tok = eng.prefill(prompt, pages)
            ctx = len(prompt)
            table = np.zeros((2, 8), np.int32)
            out_tokens, out_logits = [], []
            for step in range(6):
                if step == rebuild_at:
                    eng.rebuild()
                if ctx // 8 >= len(pages):
                    pages += eng.pool.alloc(1)
                table[0, : len(pages)] = pages
                logits, nxt = eng.decode(
                    np.array([tok, 0]), np.array([ctx + 1, 0]), table
                )
                out_tokens.append(int(nxt[0]))
                out_logits.append(np.asarray(logits[0]))
                ctx += 1
                tok = int(nxt[0])
            return eng, out_tokens, out_logits

        _, clean_toks, clean_logits = decode_stream(rebuild_at=None)
        eng, toks, logits = decode_stream(rebuild_at=3)
        assert eng.rebuilds == 1
        assert eng.compile_counts["decode"] == 2  # honest recompile
        assert toks == clean_toks
        for a, b in zip(logits, clean_logits):
            np.testing.assert_array_equal(a, b)  # bit-identical

    def test_full_rebuild_drops_prefill_buckets_lazily(self, gpt):
        eng = make_engine(gpt).build(buckets=(8,))
        assert eng.compile_counts == {"prefill_8": 1, "decode": 1}
        eng.rebuild(full=True)
        assert eng.compile_counts["decode"] == 2
        # prefill recompiles lazily on next use
        rs = np.random.RandomState(32)
        pages = eng.pool.alloc(1)
        eng.prefill([int(t) for t in rs.randint(0, 64, size=5)], pages)
        assert eng.compile_counts["prefill_8"] == 2
        eng.pool.free(pages)

    def test_finite_screens_default_clean(self, gpt):
        eng = make_engine(gpt)
        rs = np.random.RandomState(33)
        pages = eng.pool.alloc(1)
        eng.prefill([int(t) for t in rs.randint(0, 64, size=5)], pages)
        assert eng.last_prefill_finite is True
        table = np.zeros((2, 8), np.int32)
        table[0, :1] = pages
        eng.decode(np.array([1, 0]), np.array([6, 0]), table)
        assert eng.last_decode_finite is not None
        assert bool(eng.last_decode_finite.all())
        eng.pool.free(pages)


class TestBf16Serving:
    def test_bf16_engine_runs_and_is_sane(self):
        """The default training dtype (bf16) serves: greedy decode
        runs, logits stay finite, and the argmax token agrees with the
        bf16 reference forward most of the time (exact-match is not
        guaranteed at bf16 — the paged path rounds at different
        points)."""
        cfg = tiny_cfg(dtype=jnp.bfloat16)
        model = GptModel(cfg)
        params = model.init(
            jax.random.PRNGKey(0), jnp.zeros((8, 1), jnp.int32)
        )
        eng = InferenceEngine(
            cfg, params,
            ServeConfig(page_size=8, num_pages=16, max_batch=2,
                        max_pages_per_seq=4, verify=False),
        )
        rs = np.random.RandomState(13)
        prompt = [int(t) for t in rs.randint(0, cfg.vocab_size, size=9)]
        pages = eng.pool.alloc(2)
        logits, tok = eng.prefill(prompt, pages)
        assert np.isfinite(logits).all()
        table = np.zeros((2, 4), np.int32)
        table[0, :2] = pages
        lg, nxt = eng.decode(
            np.array([tok, 0]), np.array([len(prompt) + 1, 0]), table
        )
        assert np.isfinite(lg[0]).all()
        assert 0 <= int(nxt[0]) < cfg.vocab_size


# ---------------------------------------------------------------------------
# prefix cache: refcounted pool, content-addressed runs, COW, chunked prefill
# (docs/serving.md "Prefix caching & chunked prefill")
# ---------------------------------------------------------------------------


class TestPagePoolRefcounts:
    def test_share_free_roundtrip(self):
        pool = PagePool(num_pages=8, page_size=4)
        got = pool.alloc(2)
        pool.share(got)
        assert pool.refcount(got[0]) == 2
        pool.free(got)  # one reference down: pages stay allocated
        assert pool.in_use == 2
        assert pool.refcount(got[0]) == 1
        pool.free(got)  # last holder lets go: back on the free list
        assert pool.in_use == 0
        assert pool.refcount(got[0]) == 0

    def test_share_unallocated_raises(self):
        pool = PagePool(num_pages=8, page_size=4)
        with pytest.raises(ValueError, match="unallocated"):
            pool.share([3])
        with pytest.raises(ValueError):
            pool.share([NULL_PAGE])

    def test_double_free_still_loud_after_shares(self):
        pool = PagePool(num_pages=8, page_size=4)
        got = pool.alloc(1)
        pool.share(got)
        pool.free(got)
        pool.free(got)
        with pytest.raises(ValueError, match="double free"):
            pool.free(got)

    def test_leak_check_cached_arm(self):
        pool = PagePool(num_pages=8, page_size=4)
        mine = pool.alloc(2)
        cached = pool.alloc(1)
        pool.share(cached)  # the cache's own hold on a borrowed run
        pool.leak_check([mine, cached], cached=cached)
        pool.free(cached)  # the borrower retires
        pool.leak_check([mine], cached=cached)
        # the cache's reference unaccounted -> leaked, loudly
        with pytest.raises(ValueError, match="leaked"):
            pool.leak_check([mine])
        # a claim above the reference count is still double-ownership
        with pytest.raises(ValueError, match="more than one request"):
            pool.leak_check([mine, cached, cached], cached=cached)


class TestPrefixCache:
    def test_prefix_keys_chain_and_tail_commitment(self):
        a = cache_lib.prefix_keys([1, 2, 3, 4, 5, 6], 4)
        b = cache_lib.prefix_keys([1, 2, 3, 4, 9, 9], 4)
        assert [end for _, end in a] == [4, 6]
        assert a[0][0] == b[0][0]  # shared first page, same key
        assert a[1][0] != b[1][0]  # diverging tail
        # a partial-tail key embeds the WHOLE prompt: extending the
        # prompt changes the second key even with the same 6 tokens
        c = cache_lib.prefix_keys([1, 2, 3, 4, 5, 6, 7, 8], 4)
        assert c[0][0] == a[0][0]
        assert c[1][0] != a[1][0]

    def test_commit_match_borrow(self):
        pool = PagePool(num_pages=16, page_size=4)
        cache = cache_lib.PrefixCache(pool)
        prompt = list(range(10))  # 2 full pages + a partial tail
        pages = pool.alloc(3)
        assert cache.commit(prompt, pages) == 3
        assert cache.commits == 1
        # full hit: every page, INCLUDING the partial tail
        hit, tokens = cache.match(prompt)
        assert hit == pages and tokens == 10
        cache.borrow(hit)
        assert pool.refcount(pages[0]) == 3  # owner + cache + borrower
        # shared-prefix hit: full pages only — the foreign partial
        # tail's key embeds tokens this prompt does not have
        hit2, tok2 = cache.match(list(range(8)) + [63, 62, 61])
        assert hit2 == pages[:2] and tok2 == 8
        assert cache.hits == 2 and cache.misses == 0
        assert cache.hit_tokens == 18

    def test_match_miss_and_nontouching_peek(self):
        pool = PagePool(num_pages=16, page_size=4)
        cache = cache_lib.PrefixCache(pool)
        assert cache.match([1, 2, 3]) == ([], 0)
        assert cache.misses == 1
        pages = pool.alloc(1)
        cache.commit([1, 2, 3, 4], pages)
        tick = cache._tick
        assert cache.peek_tokens([1, 2, 3, 4]) == 4
        assert cache.peek_tokens([9, 9]) == 0
        assert cache._tick == tick  # the router probe never touches LRU

    def test_commit_existing_key_keeps_incumbent(self):
        pool = PagePool(num_pages=16, page_size=4)
        cache = cache_lib.PrefixCache(pool)
        prompt = [1, 2, 3, 4, 5, 6, 7, 8]
        a = pool.alloc(2)
        assert cache.commit(prompt, a) == 2
        b = pool.alloc(2)
        # a racing cold prefill of the same prompt: incumbent wins,
        # nothing double-publishes, the loser's pages stay the loser's
        assert cache.commit(prompt, b) == 0
        hit, tokens = cache.match(prompt)
        assert hit == a and tokens == 8
        pool.free(a)
        pool.free(b)
        cache.flush()
        assert pool.in_use == 0

    def test_evict_lru_leaf_first_and_borrowed_pinned(self):
        pool = PagePool(num_pages=16, page_size=4)
        cache = cache_lib.PrefixCache(pool)
        a = pool.alloc(2)
        cache.commit([1, 2, 3, 4, 5, 6, 7, 8], a)
        pool.free(a)  # only the cache holds run A now
        b = pool.alloc(1)
        cache.commit([9, 9, 9, 9], b)
        pool.free(b)
        # A is LRU; its leaf (tail) page goes first — never the parent
        # out from under a cached child
        assert cache.evict(need=1) == 1
        assert cache.peek_tokens([1, 2, 3, 4, 5, 6, 7, 8]) == 4
        # a borrowed run is NEVER evicted, even by a full sweep
        hit, _ = cache.match([9, 9, 9, 9])
        cache.borrow(hit)
        assert cache.evict() == 1  # only A's remaining page was free
        assert cache.peek_tokens([9, 9, 9, 9]) == 4
        pool.free(hit)
        cache.flush()
        assert pool.in_use == 0

    def test_flush_releases_cache_holds_only(self):
        pool = PagePool(num_pages=16, page_size=4)
        cache = cache_lib.PrefixCache(pool)
        pages = pool.alloc(1)
        cache.commit([1, 2, 3, 4], pages)
        assert cache.flush() == 1
        assert pool.refcount(pages[0]) == 1  # the owner's ref survives
        pool.free(pages)
        assert pool.in_use == 0


class TestFusedSampling:
    def test_greedy_rows_are_argmax(self):
        rng = jax.random.PRNGKey(0)
        logits = jax.random.normal(jax.random.PRNGKey(1), (8, 64))
        out = serve_model.sample_tokens(logits, np.zeros(8), rng)
        assert (np.asarray(out) == np.argmax(logits, axis=-1)).all()

    def test_temperature_draws_differ_and_are_deterministic(self):
        logits = jax.random.normal(jax.random.PRNGKey(1), (32, 64))
        temps = np.full(32, 5.0)
        a = serve_model.sample_tokens(logits, temps, jax.random.PRNGKey(2))
        b = serve_model.sample_tokens(logits, temps, jax.random.PRNGKey(2))
        c = serve_model.sample_tokens(logits, temps, jax.random.PRNGKey(3))
        assert (np.asarray(a) == np.asarray(b)).all()  # same key, same draw
        assert (np.asarray(a) != np.asarray(c)).any()  # new key, new draw
        # hot draws leave the argmax at least somewhere over 32 rows
        assert (np.asarray(a) != np.argmax(logits, axis=-1)).any()

    def test_top_k_bounds_the_support(self):
        logits = jax.random.normal(jax.random.PRNGKey(4), (64, 32))
        temps = np.full(64, 10.0)  # hot enough to wander without a mask
        out = np.asarray(serve_model.sample_tokens(
            logits, temps, jax.random.PRNGKey(5), top_k=4
        ))
        top4 = np.argsort(np.asarray(logits), axis=-1)[:, -4:]
        assert all(out[i] in top4[i] for i in range(64))

    def test_mixed_batch_keeps_greedy_rows_exact(self):
        logits = jax.random.normal(jax.random.PRNGKey(6), (8, 64))
        temps = np.array([0.0, 1.0] * 4)
        out = np.asarray(serve_model.sample_tokens(
            logits, temps, jax.random.PRNGKey(7)
        ))
        greedy = np.argmax(np.asarray(logits), axis=-1)
        assert (out[temps == 0.0] == greedy[temps == 0.0]).all()


class TestPrefixScheduler:
    def _prompt(self, rs, n):
        return [int(t) for t in rs.randint(0, 64, size=n)]

    def test_hit_skips_prefill_and_streams_bit_identical(self, gpt):
        eng = make_engine(gpt)
        reg = _registry()
        sched = ContinuousBatchingScheduler(eng, registry=reg,
                                            prefix_cache=True)
        rs = np.random.RandomState(50)
        prompt = self._prompt(rs, 19)  # 2 full pages + a partial tail
        cold = sched.submit(Request(prompt=list(prompt), max_new_tokens=4))
        sched.run()
        calls_after_cold = eng.prefill_calls
        warm = sched.submit(Request(prompt=list(prompt), max_new_tokens=4))
        sched.run()
        # the full prompt (partial tail included) matched, and the hit
        # paid exactly ONE tail chunk instead of a full prefill
        assert warm.cache_hit_tokens == 19
        assert eng.prefill_calls == calls_after_cold + 1
        assert warm.tokens == cold.tokens  # decode is bit-identical
        vals = _vals(reg)
        assert vals["serve/prefix_hits"] == 1.0
        assert vals["serve/prefix_misses"] == 1.0
        assert vals["serve/prefix_hit_tokens"] == 19.0
        assert vals["serve/prefix_commits"] > 0.0
        # the 4-way TTFT attribution: the hit carries a cached_prefill
        # share and the components still sum exactly
        c = warm.ttft_components()
        assert c["cached_prefill_ms"] > 0.0
        assert (
            c["queue_wait_ms"] + c["cached_prefill_ms"]
            + c["prefill_ms"] + c["contention_ms"]
        ) == pytest.approx(c["ttft_ms"], abs=1e-6)
        report = sched.drain()  # flushes the cache, re-proves the pool
        assert report["pool_in_use"] == 0
        assert sched.leak_checks_run > 0

    def test_cow_fork_diverges_without_corrupting_cache(self, gpt):
        """The committer keeps decoding into its own tail page AFTER
        committing it (refcount 2 -> the append forks); a later hit
        borrows the pristine cached run and must see the prompt's KV,
        not the committer's appended tokens."""
        eng = make_engine(gpt)
        reg = _registry()
        sched = ContinuousBatchingScheduler(eng, registry=reg,
                                            prefix_cache=True)
        rs = np.random.RandomState(51)
        prompt = self._prompt(rs, 12)  # partial tail: 4 of 8 slots live
        cold = sched.submit(Request(prompt=list(prompt), max_new_tokens=6))
        sched.run()
        warm = sched.submit(Request(prompt=list(prompt), max_new_tokens=6))
        sched.run()
        warm2 = sched.submit(Request(prompt=list(prompt), max_new_tokens=6))
        sched.run()
        assert warm.cache_hit_tokens == 12
        assert warm.tokens == cold.tokens
        assert warm2.tokens == cold.tokens  # the cached copy never drifted
        assert _vals(reg)["serve/prefix_forks"] >= 3.0  # one per append
        report = sched.drain()
        assert report["pool_in_use"] == 0

    def test_cow_fork_int8_tail(self, gpt):
        """Same fork-then-diverge pin on the int8 KV wire: the fork
        must copy codes AND scale planes."""
        eng = make_engine(gpt, kv_wire="int8")
        sched = ContinuousBatchingScheduler(eng, prefix_cache=True)
        rs = np.random.RandomState(52)
        prompt = self._prompt(rs, 12)
        cold = sched.submit(Request(prompt=list(prompt), max_new_tokens=6))
        sched.run()
        warm = sched.submit(Request(prompt=list(prompt), max_new_tokens=6))
        sched.run()
        assert warm.cache_hit_tokens == 12
        assert warm.tokens == cold.tokens
        report = sched.drain()
        assert report["pool_in_use"] == 0

    def test_eviction_under_pressure_admits_new_work(self, gpt):
        eng = make_engine(gpt, num_pages=5, max_pages_per_seq=4)
        reg = _registry()
        sched = ContinuousBatchingScheduler(eng, registry=reg,
                                            prefix_cache=True)
        rs = np.random.RandomState(53)
        a = sched.submit(Request(prompt=self._prompt(rs, 16),
                                 max_new_tokens=2))
        sched.run()
        assert a.status == "done"
        # run A retired but its 2 pages stay cached; B's admission +
        # growth need the pool back — idle cached pages are reclaimed
        b = sched.submit(Request(prompt=self._prompt(rs, 16),
                                 max_new_tokens=2))
        sched.run()
        assert b.status == "done"
        assert _vals(reg)["serve/prefix_evictions"] >= 1.0
        report = sched.drain()
        assert report["pool_in_use"] == 0

    def test_prefix_evict_drill_spares_borrowed_pages(self, gpt):
        """The ``serve.prefix_evict`` chaos site: a forced full sweep
        mid-traffic reclaims every idle cached run — but a hit's
        borrowed pages survive (refcount > 1 is never evictable) and
        the ledger stays exact under the in-drill leak check."""
        from apex_tpu.resilience import chaos

        eng = make_engine(gpt)
        reg = _registry()
        sched = ContinuousBatchingScheduler(eng, registry=reg,
                                            prefix_cache=True)
        rs = np.random.RandomState(54)
        prompt = self._prompt(rs, 19)
        cold = sched.submit(Request(prompt=list(prompt), max_new_tokens=4))
        sched.run()
        with chaos.inject(chaos.Fault(
            chaos.SERVE_PREFIX_EVICT, steps=tuple(range(64)),
            mode="force", max_hits=1,
        )):
            warm = sched.submit(Request(prompt=list(prompt),
                                        max_new_tokens=4))
            sched.run()
        assert warm.status == "done"
        assert warm.tokens == cold.tokens  # borrowed pages survived
        vals = _vals(reg)
        assert vals["serve/prefix_evict_faults"] == 1.0
        assert sched.leak_checks_run > 0
        report = sched.drain()
        assert report["pool_in_use"] == 0

    def test_shed_borrower_decrements_never_frees_shared(self, gpt):
        """The shed/retry refcount pin (planted fault): a cache-hit
        request whose prefill faults persistently is shed with
        ``retries_exhausted`` — its page release must DECREMENT the
        shared references, not return cached pages to the free list.
        The cache's run survives intact: a later hit still matches the
        full prompt and decodes bit-identical to the cold run."""
        from apex_tpu.resilience import chaos

        eng = make_engine(gpt)
        reg = _registry()
        sched = ContinuousBatchingScheduler(eng, registry=reg,
                                            prefix_cache=True,
                                            max_retries=1)
        rs = np.random.RandomState(55)
        prompt = self._prompt(rs, 19)
        cold = sched.submit(Request(prompt=list(prompt), max_new_tokens=4))
        sched.run()
        cached_before = sorted(sched.prefix.cached_pages())
        with chaos.inject(chaos.Fault(
            chaos.SERVE_PREFILL, steps=tuple(range(64)), mode="raise",
        )):
            doomed = sched.submit(Request(prompt=list(prompt),
                                          max_new_tokens=4))
            sched.run()
        assert doomed.status == "shed"
        assert doomed.shed_reason == "retries_exhausted"
        # the cached run is untouched by the borrower's demise
        assert sorted(sched.prefix.cached_pages()) == cached_before
        sched.leak_check()  # exact ledger, cache holds included
        warm = sched.submit(Request(prompt=list(prompt), max_new_tokens=4))
        sched.run()
        assert warm.status == "done"
        assert warm.cache_hit_tokens == 19
        assert warm.tokens == cold.tokens
        report = sched.drain()
        assert report["pool_in_use"] == 0

    def test_chunked_prefill_matches_monolithic_numerics(self, gpt):
        """Cache OFF, chunking ON: the chunked first token equals the
        monolithic engine's on the same prompt (greedy, f32)."""
        cfg, model, params = gpt
        rs = np.random.RandomState(56)
        prompt = self._prompt(rs, 22)
        eng_mono = make_engine(gpt)
        mono = ContinuousBatchingScheduler(eng_mono)
        a = mono.submit(Request(prompt=list(prompt), max_new_tokens=5))
        mono.run()
        eng_chunk = make_engine(gpt)
        chunked = ContinuousBatchingScheduler(eng_chunk,
                                              prefill_chunk_tokens=8)
        b = chunked.submit(Request(prompt=list(prompt), max_new_tokens=5))
        chunked.run()
        assert a.status == "done" and b.status == "done"
        assert b.tokens[0] == a.tokens[0]  # argmax agrees at f32 tol
        assert b.tokens == a.tokens
        assert eng_chunk.pool.in_use == 0

    def test_chunk_grain_must_be_page_multiple(self, gpt):
        eng = make_engine(gpt)  # page_size=8
        with pytest.raises(ValueError, match="page"):
            ContinuousBatchingScheduler(eng, prefill_chunk_tokens=12)
        with pytest.raises(ValueError):
            ContinuousBatchingScheduler(eng, prefill_chunk_tokens=0)

    def test_cache_off_components_stay_three_way(self, gpt):
        """Without the cache the new component is EXACTLY 0.0 — the
        pre-existing 3-way attribution contract is unchanged."""
        eng = make_engine(gpt)
        sched = ContinuousBatchingScheduler(eng)
        rs = np.random.RandomState(57)
        req = sched.submit(Request(prompt=self._prompt(rs, 6),
                                   max_new_tokens=2))
        sched.run()
        c = req.ttft_components()
        assert c["cached_prefill_ms"] == 0.0


# ---------------------------------------------------------------------------
# speculative decoding (draft propose, one-step verify, PagePool rollback)
# ---------------------------------------------------------------------------


def make_spec_engine(gpt, k=4, spec_kw=None, **serve_kw):
    """A speculative engine; default self-draft (the target proposes
    for itself — 100% greedy acceptance, the tokens/step upper bound)."""
    from apex_tpu.serve import SpecConfig

    cfg, _, params = gpt
    kw = dict(
        page_size=8, num_pages=32, max_batch=2, max_pages_per_seq=8,
        verify=False,
    )
    kw.update(serve_kw)
    spec = SpecConfig(draft_params=None, k=k, **(spec_kw or {}))
    return InferenceEngine(cfg, params, ServeConfig(**kw), spec=spec)


class TestSpeculativeDecoding:
    def _prompt(self, rs, n):
        return [int(t) for t in rs.randint(0, 64, size=n)]

    def _run(self, sched, prompts, max_new=8, **req_kw):
        reqs = [
            sched.submit(Request(prompt=list(p), max_new_tokens=max_new,
                                 rid=f"r{i}", **req_kw))
            for i, p in enumerate(prompts)
        ]
        sched.run()
        assert all(r.status == "done" for r in reqs), [
            (r.status, r.shed_reason) for r in reqs
        ]
        return reqs

    def test_greedy_spec_bit_identical_f32(self, gpt):
        """The acceptance gate: self-draft greedy spec at k=4 emits the
        EXACT token stream plain decode emits, and accepts everything
        (tokens/decode-step = k+1 >> the 1.5 floor)."""
        rs = np.random.RandomState(60)
        prompts = [self._prompt(rs, 6), self._prompt(rs, 11)]
        plain = ContinuousBatchingScheduler(make_engine(gpt))
        base = self._run(plain, prompts)
        reg = _registry()
        sched = ContinuousBatchingScheduler(make_spec_engine(gpt),
                                            registry=reg)
        spec = self._run(sched, prompts)
        for a, b in zip(base, spec):
            assert b.tokens == a.tokens
        vals = _vals(reg)
        assert vals["serve/spec_drafted"] > 0
        assert vals["serve/spec_accepted"] == vals["serve/spec_drafted"]
        assert vals["serve/spec_accept_rate"] == 1.0
        assert vals["serve/spec_tokens_per_step"] >= 1.5
        # spec rounds ARE decode steps: far fewer than tokens emitted
        assert vals["serve/decode_steps"] < vals["serve/tokens_out"] - 2
        assert sched.engine.pool.in_use == 0
        sched.leak_check()

    def test_greedy_spec_bit_identical_int8_kv(self, gpt):
        """Same gate on the int8 KV wire: draft and verify quantize
        through the same codec as plain decode, so greedy acceptance
        still matches argmax-for-argmax."""
        rs = np.random.RandomState(61)
        prompts = [self._prompt(rs, 9), self._prompt(rs, 14)]
        plain = ContinuousBatchingScheduler(make_engine(gpt, kv_wire="int8"))
        base = self._run(plain, prompts)
        sched = ContinuousBatchingScheduler(
            make_spec_engine(gpt, kv_wire="int8")
        )
        spec = self._run(sched, prompts)
        for a, b in zip(base, spec):
            assert b.tokens == a.tokens
        assert sched.engine.pool.in_use == 0

    def test_spec_bit_identical_under_cow_fork(self, gpt):
        """A spec round may roll back KV on the request's tail page —
        which a prefix-cache hit BORROWS.  The scheduler must COW-fork
        the whole speculative window before the round, so the warm
        stream matches the cold one and the cached copy never drifts."""
        rs = np.random.RandomState(62)
        prompt = self._prompt(rs, 12)  # partial tail: 4 of 8 slots live
        plain = ContinuousBatchingScheduler(make_engine(gpt))
        base = self._run(plain, [prompt], max_new=6)
        reg = _registry()
        sched = ContinuousBatchingScheduler(make_spec_engine(gpt),
                                            registry=reg,
                                            prefix_cache=True)
        cold = self._run(sched, [prompt], max_new=6)
        warm = sched.submit(Request(prompt=list(prompt), max_new_tokens=6,
                                    rid="warm"))
        sched.run()
        assert warm.status == "done"
        assert warm.cache_hit_tokens == 12
        assert cold[0].tokens == base[0].tokens
        assert warm.tokens == base[0].tokens
        assert _vals(reg)["serve/prefix_forks"] >= 2.0  # cold + warm tails
        warm2 = sched.submit(Request(prompt=list(prompt), max_new_tokens=6,
                                     rid="warm2"))
        sched.run()
        assert warm2.tokens == base[0].tokens  # cached copy never drifted
        report = sched.drain()
        assert report["pool_in_use"] == 0

    def test_draft_pages_never_enter_prefix_cache(self, gpt):
        """The namespace screen: leak_check refuses a draft-namespace
        page claimed by the prefix cache, and a spec+cache run never
        trips it (draft pages are scheduler-owned only)."""
        pool = PagePool(num_pages=8, page_size=4)
        draft = pool.alloc(1, ns="draft")
        assert pool.namespace(draft[0]) == "draft"
        with pytest.raises(ValueError, match="draft-namespace"):
            pool.leak_check([], cached=draft)
        pool.free(draft)
        # kv-namespace pages cache fine
        kv = pool.alloc(1)
        pool.leak_check([], cached=kv)

    def test_temperature_rollback_replay_bit_identical(self, gpt):
        """The per-slot rng regression pin: sampled tokens are a pure
        function of (stream, position), so re-decoding a position after
        a planted rollback replays the SAME token — no global counter
        leaks into the stream."""
        eng = make_spec_engine(gpt, k=4)
        prompt = list(np.random.RandomState(63).randint(0, 64, size=9))
        pages = eng.pool.alloc(eng.pool.pages_for(len(prompt)))
        _, first = eng.prefill(prompt, pages)
        table = np.zeros((2, 8), np.int32)
        table[0, : len(pages)] = pages
        args = (
            np.array([first, 0], np.int32),
            np.array([len(prompt) + 1, 0], np.int32),
            table,
            np.array([0.8, 0.0], np.float32),
        )
        kw = dict(streams=np.array([1234, 0], np.uint32),
                  gens=np.array([1, 0], np.int32))
        _, t1 = eng.decode(*args, **kw)
        # plant the rollback: truncate the KV row the decode just wrote
        eng.rollback(np.array([len(prompt), 0], np.int32),
                     np.array([1, 0], np.int32), table)
        _, t2 = eng.decode(*args, **kw)
        assert int(t1[0]) == int(t2[0])
        eng.pool.free(pages)

    def test_temperature_k0_matches_plain_stream(self, gpt):
        """Satellite pin: with k=0 the spec path is plain decode routed
        through the verify program — a temperature stream with an
        explicit stream_seed must be bit-identical to the non-spec
        scheduler's."""
        rs = np.random.RandomState(64)
        prompts = [self._prompt(rs, 7), self._prompt(rs, 10)]
        plain = ContinuousBatchingScheduler(make_engine(gpt))
        base = self._run(plain, prompts, temperature=0.7, stream_seed=99)
        sched = ContinuousBatchingScheduler(make_spec_engine(gpt, k=0))
        spec = self._run(sched, prompts, temperature=0.7, stream_seed=99)
        for a, b in zip(base, spec):
            assert b.tokens == a.tokens
        assert sched.engine.pool.in_use == 0

    def test_rejection_sampling_preserves_target_distribution(self):
        """Chi-square on the rejection sampler: proposals drawn from a
        MISMATCHED draft distribution q, accepted/resampled against the
        target p — the emitted first token must be distributed exactly
        as p.  Seeded, CPU, critical value hardcoded (df=7, a=0.001)."""
        from apex_tpu.serve import spec as spec_lib

        V, N, k = 8, 4096, 1
        rs = np.random.RandomState(0)
        p_logits = (rs.randn(V) * 1.5).astype(np.float32)
        q_logits = (rs.randn(V) * 1.5).astype(np.float32)
        p = np.exp(p_logits - p_logits.max())
        p /= p.sum()
        q = np.exp(q_logits - q_logits.max())
        q /= q.sum()
        # the consistency the theorem needs: d ~ q
        d = rs.choice(V, size=(N, k), p=q).astype(np.int32)
        out, n_acc = spec_lib.speculative_verify(
            jnp.broadcast_to(jnp.asarray(p_logits), (k + 1, N, V)),
            jnp.asarray(d),
            jnp.broadcast_to(jnp.asarray(q, jnp.float32), (k, N, V)),
            jnp.ones((N,), jnp.float32),
            jax.vmap(jax.random.fold_in, (None, 0))(
                jax.random.PRNGKey(42), jnp.arange(N, dtype=jnp.uint32)
            ),
            jnp.zeros((N,), jnp.int32),
        )
        counts = np.bincount(np.asarray(out[:, 0]), minlength=V)
        expected = p * N
        chi2 = float(((counts - expected) ** 2 / expected).sum())
        assert chi2 < 24.322, (chi2, counts.tolist(), expected.tolist())
        # and SOME of both outcomes occurred — the test saw real
        # accepts and real rejections, not a degenerate path
        acc = np.asarray(n_acc)
        assert 0 < acc.sum() < N * k

    def test_draft_fault_storm_stream_intact_and_leak_clean(self, gpt):
        """The serve.draft chaos gate: a raise storm makes every spec
        round fall back to plain decode and a nan storm poisons the
        proposals — in BOTH cases the emitted stream stays bit-identical
        to plain decode and the page ledger stays exact."""
        from apex_tpu.resilience import chaos

        rs = np.random.RandomState(65)
        prompts = [self._prompt(rs, 6), self._prompt(rs, 11)]
        plain = ContinuousBatchingScheduler(make_engine(gpt))
        base = self._run(plain, prompts)
        for mode in ("raise", "nan"):
            reg = _registry()
            sched = ContinuousBatchingScheduler(make_spec_engine(gpt),
                                                registry=reg)
            with chaos.inject(chaos.Fault(
                chaos.SERVE_DRAFT, steps=(0, 1, 2), mode=mode
            )):
                reqs = self._run(sched, prompts)
            for a, b in zip(base, reqs):
                assert b.tokens == a.tokens, mode
            vals = _vals(reg)
            if mode == "raise":
                assert vals["serve/draft_faults"] >= 1.0
            else:
                # poisoned proposals are REJECTED, never emitted
                assert vals["serve/spec_rejected"] >= 1.0
            assert sched.engine.pool.in_use == 0
            sched.leak_check()

    def test_acceptance_collapse_falls_back_to_plain(self, gpt):
        """The degradation ladder: a hopeless draft (acceptance under
        min_accept_rate over the window) trips the sticky fallback —
        later rounds ride plain decode, resume() re-arms."""
        import dataclasses as dc

        from apex_tpu.serve import SpecConfig, draft_from_params

        cfg, _, params = gpt
        spec = SpecConfig(
            draft_params=draft_from_params(params, 1),
            k=4,
            draft_cfg=dc.replace(cfg, num_layers=1),
            min_accept_rate=0.95,
            window=2,
        )
        eng = InferenceEngine(cfg, params, ServeConfig(
            page_size=8, num_pages=32, max_batch=2, max_pages_per_seq=8,
            verify=False,
        ), spec=spec)
        reg = _registry()
        sched = ContinuousBatchingScheduler(eng, registry=reg)
        rs = np.random.RandomState(66)
        prompts = [self._prompt(rs, 8), self._prompt(rs, 8)]
        plain = ContinuousBatchingScheduler(make_engine(gpt))
        base = self._run(plain, prompts, max_new=12)
        reqs = self._run(sched, prompts, max_new=12)
        for a, b in zip(base, reqs):
            assert b.tokens == a.tokens  # fallback or not: same stream
        vals = _vals(reg)
        assert vals["serve/spec_fallbacks"] >= 1.0
        assert sched._spec_fallback
        sched.resume()
        assert not sched._spec_fallback
        assert eng.pool.in_use == 0

    def test_spec_acceptance_watchdog_rule(self, gpt):
        """SpecAcceptanceRule pages when the published acceptance gauge
        sinks under its floor — and stays silent when speculation never
        ran."""
        from apex_tpu.observability import (
            MetricRegistry, SpecAcceptanceRule, Watchdog,
        )
        from apex_tpu.serve import declare_serve_metrics

        reg = MetricRegistry(fetch_every=1)
        declare_serve_metrics(reg)
        state = reg.update(reg.init(), {
            "serve/spec_rounds": 8.0,
            "serve/spec_accept_rate": 0.2,
        })
        reg.observe(0, state)
        reg.observe(1, state)
        reg.fetch()
        wd = Watchdog([SpecAcceptanceRule(min_rate=0.5)], registry=reg,
                      check_every=1)
        wd.on_step(1)
        events = [e for e in wd.events if e.rule == "spec_acceptance"]
        assert len(events) == 1
        # silent when spec never ran (rate gauge 0.0, rounds 0)
        reg2 = MetricRegistry(fetch_every=1)
        declare_serve_metrics(reg2)
        state2 = reg2.update(reg2.init(), {})
        reg2.observe(0, state2)
        reg2.observe(1, state2)
        reg2.fetch()
        wd2 = Watchdog([SpecAcceptanceRule(min_rate=0.5)], registry=reg2,
                       check_every=1)
        wd2.on_step(1)
        assert wd2.events == []
