"""Step-time attribution, roofline, and the bench regression gate.

Covers ISSUE 6: the trace parser on recorded fixtures (clean + a
planted unattributable gap), cost-model attribution of a real jitted
step (matmul dominance, named-scope bucketing), the shared peak/bucket
model in ``observability.meter`` (and the pin that bench.py no longer
carries its own copy), the watchdog fraction rules, and
``tools/bench_diff.py`` — including the committed r03→r05 flash
flatline, the exact miss this layer exists to catch.
"""

import json
import os
import subprocess
import sys

import jax
import jax.numpy as jnp
import pytest

from apex_tpu.observability import attribution as A
from apex_tpu.observability import meter as M
from apex_tpu.observability.metrics import board

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
DATA = os.path.join(os.path.dirname(os.path.abspath(__file__)), "data")

sys.path.insert(0, REPO)

from tools import bench_diff as bd  # noqa: E402


def _load_fixture(name):
    with open(os.path.join(DATA, name)) as f:
        return json.load(f)


# ---------------------------------------------------------------------------
# trace parser on recorded fixtures
# ---------------------------------------------------------------------------


class TestTraceFixtures:
    def test_clean_fixture_buckets_and_sum(self):
        meas = A.attribute_trace(_load_fixture("attribution_trace_clean.json"))
        assert meas.source == "device-ops"
        # wrappers (while.1 / jit_train_step) and host frames excluded:
        # exactly the five op rows, 1400us of busy time
        assert meas.events == 5
        assert meas.busy_ms == pytest.approx(1.4)
        assert meas.bucket_ms["matmul"] == pytest.approx(0.9)
        assert meas.bucket_ms["norm_elementwise"] == pytest.approx(0.3)
        assert meas.bucket_ms["collective"] == pytest.approx(0.2)
        fr = meas.fractions()
        assert sum(fr.values()) == pytest.approx(1.0, abs=1e-9)
        # 50us of dispatch gap over a 1450us span
        assert fr["host_stall"] == pytest.approx(50 / 1450, abs=1e-6)
        assert fr["collective"] == pytest.approx(
            (200 / 1400) * (1400 / 1450), abs=1e-6
        )

    def test_gap_fixture_detects_host_stall(self):
        meas = A.attribute_trace(_load_fixture("attribution_trace_gap.json"))
        fr = meas.fractions()
        assert sum(fr.values()) == pytest.approx(1.0, abs=1e-9)
        # the planted 1000us hole: no op accounts for it -> host stall
        assert fr["host_stall"] == pytest.approx(1050 / 2450, abs=1e-6)
        assert fr["host_stall"] > 0.25
        # busy time unchanged: the gap shifts ops, it does not add work
        assert meas.busy_ms == pytest.approx(1.4)

    def test_hlo_map_overrides_name_heuristic(self):
        meas = A.attribute_trace(
            _load_fixture("attribution_trace_clean.json"),
            hlo_map={"dot.12": "attention"},
        )
        assert meas.bucket_ms["attention"] == pytest.approx(0.5)
        assert meas.bucket_ms["matmul"] == pytest.approx(0.4)

    def test_executor_span_fallback_uses_cost_weights(self):
        trace = {"traceEvents": [
            {"ph": "M", "pid": 1, "name": "process_name",
             "args": {"name": "/host:CPU"}},
            {"ph": "X", "pid": 1, "tid": 1, "ts": 0, "dur": 800,
             "name": "TfrtCpuExecutable::Execute", "args": {}},
            {"ph": "X", "pid": 1, "tid": 1, "ts": 900, "dur": 100,
             "name": "TfrtCpuExecutable::Execute", "args": {}},
        ]}
        meas = A.attribute_trace(
            trace, cost_weights={"matmul": 0.75, "collective": 0.25}
        )
        assert meas.source == "executor-spans"
        fr = meas.fractions()
        assert sum(fr.values()) == pytest.approx(1.0, abs=1e-9)
        assert fr["host_stall"] == pytest.approx(0.1)
        assert meas.bucket_ms["matmul"] == pytest.approx(0.675)

    def test_empty_trace_is_all_zero_not_nan(self):
        meas = A.attribute_trace({"traceEvents": []})
        fr = meas.fractions()
        assert fr == {"compute": 0.0, "collective": 0.0, "host_stall": 0.0}

    def test_trace_step_period_median_rejects_outlier(self):
        # the same op recurring every 1000us, except one 50000us gap
        # (the profiler's first-capture anomaly): the median period is
        # still the honest step time
        evs = [
            {"ph": "X", "pid": 1, "tid": 1, "name": "dot.12",
             "ts": ts, "dur": 10, "args": {}}
            for ts in (0, 50_000, 51_000, 52_000, 53_000)
        ]
        period = A.trace_step_period({"traceEvents": evs})
        assert period == pytest.approx(1000 / 1e6)
        # single occurrence per op -> indeterminate, not a crash
        assert A.trace_step_period(
            _load_fixture("attribution_trace_clean.json")
        ) == 0.0


# ---------------------------------------------------------------------------
# cost-model attribution of a real jitted step
# ---------------------------------------------------------------------------


def _toy_step_hlo(d=512, batch=256):
    def step(params, x, y):
        def loss_fn(p):
            h = jnp.tanh(x @ p["w1"])
            pred = h @ p["w2"]
            return jnp.mean((pred - y) ** 2)

        loss, g = jax.value_and_grad(loss_fn)(params)
        new = jax.tree_util.tree_map(
            lambda p, gg: p - 1e-2 * gg, params, g
        )
        return new, loss

    params = {"w1": jnp.ones((d, d)), "w2": jnp.ones((d, d))}
    x = jnp.ones((batch, d))
    y = jnp.ones((batch, d))
    return jax.jit(step).lower(params, x, y).compile().as_text()


class TestCostModel:
    def test_matmul_bucket_dominates_toy_train_step(self):
        cost = A.attribute_cost_model(_toy_step_hlo())
        total = cost.total_flops
        assert total > 0
        # fwd+bwd of two d x d matmuls: the dots own nearly all FLOPs —
        # the dominance claim the ISSUE pins for the cost model
        assert cost.buckets["matmul"]["flops"] > 0.8 * total
        # est time is bandwidth-ruled at this size, where the update's
        # elementwise bytes legitimately compete — matmul still holds a
        # substantial share
        assert cost.bucket_fractions()["matmul"] > 0.25
        fr = cost.fractions()
        assert sum(fr.values()) == pytest.approx(1.0)
        assert fr["host_stall"] == 0.0  # invisible to the compiled program

    def test_named_scope_buckets_dot_as_attention(self):
        def f(x, w):
            with jax.named_scope("flash_attention_core"):
                s = x @ w
            return jnp.sum(s)

        text = jax.jit(f).lower(
            jnp.ones((64, 64)), jnp.ones((64, 64))
        ).compile().as_text()
        cost = A.attribute_cost_model(text)
        assert cost.buckets["attention"]["flops"] > 0
        assert cost.buckets["matmul"]["flops"] == 0.0

    def test_dot_flops_exact(self):
        text = jax.jit(lambda a, b: a @ b).lower(
            jnp.ones((32, 48)), jnp.ones((48, 16))
        ).compile().as_text()
        cost = A.attribute_cost_model(text)
        assert cost.total_flops == pytest.approx(2 * 32 * 16 * 48)

    def test_multi_program_merge_and_bucket_map(self):
        t1 = _toy_step_hlo(d=32, batch=8)
        t2 = _toy_step_hlo(d=32, batch=8)
        merged = A.attribute_cost_model([t1, t2])
        single = A.attribute_cost_model(t1)
        assert merged.total_flops == pytest.approx(2 * single.total_flops)
        hmap = A.hlo_bucket_map(t1)
        assert hmap  # raw instruction names -> bucket
        assert set(hmap.values()) <= set(M.BUCKETS)

    def test_collective_bucketed_from_psum_hlo(self):
        hlo = """
HloModule m, entry_computation_layout={(f32[1024]{0})->f32[1024]{0}}

%sum (a: f32[], b: f32[]) -> f32[] {
  %a = f32[] parameter(0)
  %b = f32[] parameter(1)
  ROOT %add.1 = f32[] add(f32[] %a, f32[] %b)
}

ENTRY %main (p0: f32[1024]) -> f32[1024] {
  %p0 = f32[1024]{0} parameter(0)
  %mul.1 = f32[1024]{0} multiply(f32[1024]{0} %p0, f32[1024]{0} %p0)
  ROOT %all-reduce.3 = f32[1024]{0} all-reduce(f32[1024]{0} %mul.1), replica_groups={}, to_apply=%sum
}
"""
        cost = A.attribute_cost_model(hlo)
        assert cost.buckets["collective"]["bytes"] == 4096
        assert cost.fractions()["collective"] > 0


# ---------------------------------------------------------------------------
# the shared peak/bucket model (meter.py satellite)
# ---------------------------------------------------------------------------


class TestMeterModel:
    def test_peak_flops_for_table_and_default(self):
        assert M.peak_flops_for("TPU v5e") == 197e12
        assert M.peak_flops_for("TPU v5p something") == 459e12
        assert M.peak_flops_for("cpu") == M.DEFAULT_PEAK_FLOPS
        assert M.peak_hbm_bandwidth_for("TPU v4") == 1228e9
        assert M.peak_ici_bandwidth_for("never heard of it") == \
            M.DEFAULT_ICI_GBPS

    def test_chip_peak_flops_delegates_to_string_helper(self):
        class Dev:
            device_kind = "TPU v6 lite"

        assert M.chip_peak_flops(Dev()) == M.peak_flops_for("TPU v6 lite")

    def test_categorize_op_priorities(self):
        assert M.categorize_op("all-reduce") == "collective"
        assert M.categorize_op("all-gather-start") == "collective"
        # attention scope wins over the dot opcode: the attention
        # bucket owns its matmuls
        assert M.categorize_op(
            "dot", "jit(f)/flash_attention/dot_general"
        ) == "attention"
        assert M.categorize_op("dot", "jit(f)/mlp/dot_general") == "matmul"
        assert M.categorize_op("convolution") == "matmul"
        assert M.categorize_op(
            "fusion", "jit(f)/conv_general_dilated"
        ) == "matmul"
        # dtype casts must NOT ride the "conv" substring into matmul —
        # amp steps are full of them (both call paths: opcode from the
        # cost model, event-name lead token from the trace parser)
        assert M.categorize_op(
            "convert", "jit(f)/convert_element_type"
        ) == "norm_elementwise"
        assert M.categorize_op("convert", "convert_fusion.5") == \
            "norm_elementwise"
        assert M.categorize_op("tanh") == "norm_elementwise"
        assert M.categorize_op(
            "fusion", "jit(f)/layer_norm/reduce"
        ) == "norm_elementwise"
        assert M.categorize_op("copy") == "other"
        assert set((M.categorize_op(o) for o in (
            "dot", "all-reduce", "add", "copy"
        ))) <= set(M.BUCKETS)

    def test_bench_shares_the_meter_peak_model(self):
        """bench.py must not carry its own peak table (the satellite's
        one-denominator pin)."""
        import importlib.util

        spec = importlib.util.spec_from_file_location(
            "bench", os.path.join(REPO, "bench.py")
        )
        bench = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(bench)
        assert bench._chip_peak is M.chip_peak_flops
        assert bench._train_flops is M.transformer_train_flops
        import re

        with open(os.path.join(REPO, "bench.py")) as f:
            src = f.read()
        # no local peak-FLOPs constants (197e12-style literals; the
        # 1e12 TFLOP unit conversion is fine)
        assert not re.search(r"\b\d{2,}(\.\d+)?e12\b", src), (
            "bench.py grew its own peak constant; use "
            "observability.meter.peak_flops_for"
        )


# ---------------------------------------------------------------------------
# roofline
# ---------------------------------------------------------------------------


class TestRoofline:
    def test_rows_verdicts_and_total_mfu(self):
        cost = A.attribute_cost_model(
            _toy_step_hlo(), device_kind="TPU v5e"
        )
        step_time = 1e-3
        rows = A.roofline_report(cost, step_time_s=step_time)
        total = rows[-1]
        assert total.bucket == "total"
        assert total.pct_peak == pytest.approx(
            cost.total_flops / (step_time * M.peak_flops_for("TPU v5e"))
        )
        by_bucket = {r.bucket: r for r in rows}
        # a d=512 matmul at AI ~ 50 FLOP/B sits under the v5e ridge
        # (197e12/819e9 ~ 241): bandwidth-bound verdict
        assert by_bucket["matmul"].bound == "bandwidth"
        for r in rows[:-1]:
            assert r.bound in ("compute", "bandwidth", "comm")
        assert "bucket" in A.render_roofline(rows).splitlines()[0]

    def test_measured_shares_scale_bucket_time(self):
        cost = A.attribute_cost_model(_toy_step_hlo())
        meas = A.attribute_trace(
            _load_fixture("attribution_trace_clean.json")
        )
        rows = A.roofline_report(cost, step_time_s=1.45e-3, measured=meas)
        by_bucket = {r.bucket: r for r in rows}
        # matmul owned 900/1450 of the measured span
        assert by_bucket["matmul"].time_ms == pytest.approx(0.9, rel=1e-6)


# ---------------------------------------------------------------------------
# publication + the watchdog fraction rules
# ---------------------------------------------------------------------------


class TestFractionRules:
    def teardown_method(self):
        board.clear()

    def test_rules_fire_from_attribution_object(self):
        import apex_tpu.observability as obs

        wd = obs.Watchdog(
            rules=[obs.CollectiveFractionRule(max_fraction=0.3),
                   obs.HostStallRule(max_fraction=0.2)],
            attribution={"compute": 0.3, "collective": 0.4,
                         "host_stall": 0.3},
        )
        fired = {e.rule for e in wd.check(0)}
        assert fired == {"collective_fraction", "host_stall"}

    def test_rules_fall_back_to_board_and_stay_silent_without(self):
        import apex_tpu.observability as obs

        wd = obs.Watchdog(rules=[obs.HostStallRule(max_fraction=0.15)])
        assert wd.check(0) == []  # nothing published -> silent
        meas = A.attribute_trace(
            _load_fixture("attribution_trace_gap.json")
        )
        A.publish_attribution(meas)
        events = wd.check(64)
        assert [e.rule for e in events] == ["host_stall"]
        assert events[0].value == pytest.approx(1050 / 2450, abs=1e-6)

    def test_publish_writes_board_and_reporter(self, tmp_path):
        import apex_tpu.observability as obs

        out = tmp_path / "attr.jsonl"
        rep = obs.Reporter([obs.JSONLSink(str(out))])
        meas = A.attribute_trace(
            _load_fixture("attribution_trace_clean.json")
        )
        fr = A.publish_attribution(meas, reporter=rep, step=7)
        rep.close()
        assert board.get("attribution/collective_fraction") == \
            pytest.approx(fr["collective"])
        recs = [json.loads(l) for l in out.read_text().splitlines()]
        names = {r["metric"] for r in recs}
        assert "attribution/host_stall_fraction" in names
        assert "attribution/bucket/matmul" in names
        assert all(list(r)[:4] == ["metric", "value", "unit",
                                   "vs_baseline"] for r in recs)

    def test_default_rules_include_fraction_rules(self):
        import apex_tpu.observability as obs

        rules = obs.default_rules(host_stall={"max_fraction": 0.5})
        names = [r.name for r in rules]
        assert "collective_fraction" in names
        assert "host_stall" in names
        assert [r for r in rules if r.name == "host_stall"][0] \
            .max_fraction == 0.5


# ---------------------------------------------------------------------------
# tools/bench_diff.py — the regression/flatline gate
# ---------------------------------------------------------------------------


def _rec(metric, value, unit="", degenerate=False, **extra):
    rec = {"metric": metric, "value": value, "unit": unit,
           "vs_baseline": None}
    if degenerate:
        rec["degenerate"] = True
    rec.update(extra)
    return rec


def _write_jsonl(path, records):
    with open(path, "w") as f:
        for r in records:
            f.write(json.dumps(r) + "\n")
    return str(path)


class TestBenchDiff:
    def test_regression_direction_higher_and_lower(self):
        cur = bd.collapse([_rec("tflops", 40.0), _rec("step_ms", 12.0)])
        base = bd.collapse([_rec("tflops", 50.0), _rec("step_ms", 10.0)])
        rows = {r["metric"]: r for r in bd.compare(cur, base)}
        assert rows["tflops"]["status"] == "regressed"  # higher-better
        assert rows["step_ms"]["status"] == "regressed"  # lower-better
        rows = {r["metric"]: r for r in bd.compare(base, cur)}
        assert rows["tflops"]["status"] == "improved"
        assert rows["step_ms"]["status"] == "improved"

    def test_median_of_trials(self):
        cur = bd.collapse([_rec("m", v) for v in (10.0, 99.0, 11.0)])
        assert cur["m"]["value"] == 11.0
        assert cur["m"]["trials"] == 3

    def test_degenerate_rows_excluded_from_gating(self):
        cur = bd.collapse([_rec("dp_x", 1.0, "img/s (dp=1)",
                                degenerate=True)])
        base = bd.collapse([_rec("dp_x", 100.0, "img/s (dp=8)")])
        rows = bd.compare(cur, base)
        assert rows[0]["status"] == "degenerate"

    def test_flat_detection_and_tolerance(self):
        base = bd.collapse([_rec("tflops", 43.0)])
        flat = bd.collapse([_rec("tflops", 43.1)])
        moved = bd.collapse([_rec("tflops", 45.0)])
        assert bd.compare(flat, base)[0]["status"] == "flat"
        assert bd.compare(moved, base)[0]["status"] != "flat"

    def test_loader_handles_wrapper_and_jsonl(self, tmp_path):
        w = tmp_path / "wrap.json"
        w.write_text(json.dumps(
            {"n": 5, "rc": 3, "parsed": _rec("m", None, "NOT MEASURED")}
        ))
        recs = bd.load_records(str(w))
        assert len(recs) == 1 and recs[0]["metric"] == "m"
        j = _write_jsonl(tmp_path / "x.jsonl",
                         [_rec("a", 1.0), _rec("b", 2.0)])
        assert len(bd.load_records(j)) == 2

    def test_schema_check_degenerate_honesty(self):
        ok = [_rec("x", 1.0, "ms/step (dp=1, ...)", degenerate=True),
              _rec("y", 2.0, "img/s (dp=8, ...)")]
        assert bd.check_schema(ok) == []
        missing = [_rec("x", 1.0, "ms/step (dp=1, ...)")]
        assert any("not marked degenerate" in p
                   for p in bd.check_schema(missing))
        dishonest = [_rec("y", 2.0, "img/s (dp=8, ...)", degenerate=True)]
        assert any("real multi-device" in p
                   for p in bd.check_schema(dishonest))
        bad_order = [{"value": 1.0, "metric": "z", "unit": "",
                      "vs_baseline": None}]
        assert any("contract" in p for p in bd.check_schema(bad_order))

    def test_committed_rounds_reproduce_the_flatline_catch(self, tmp_path):
        """r03 vs r05: the flash line sat at 43 TFLOP/s and nothing
        failed — the gate must catch exactly that from the committed
        artifacts."""
        r05 = os.path.join(REPO, "BENCH_all_r05.json")
        r03 = os.path.join(REPO, "BENCH_all_r03.json")
        rc_flat = bd.main([
            r05, "--baseline", r03, "--fail-on-flat",
        ])
        assert rc_flat == 1
        rc_reg = bd.main([
            r05, "--baseline", r03, "--fail-on-regression",
        ])
        assert rc_reg == 0
        out = tmp_path / "diff.json"
        bd.main([r05, "--baseline", r03, "--json", str(out)])
        rows = {r["metric"]: r
                for r in json.loads(out.read_text())["rows"]}
        assert rows["long_context_flash_attn_tflops"]["status"] == "flat"
        assert rows["tp_gpt_block_step_ms"]["status"] == "degenerate"

    def test_fail_on_flat_when_metric_missing(self, tmp_path):
        cur = _write_jsonl(tmp_path / "c.jsonl", [_rec("other", 1.0)])
        base = _write_jsonl(tmp_path / "b.jsonl", [_rec("other", 1.0)])
        rc = bd.main([cur, "--baseline", base, "--fail-on-flat",
                      "long_context_flash_attn_tflops"])
        assert rc == 1

    def test_require_same_metrics(self, tmp_path):
        cur = _write_jsonl(tmp_path / "c.jsonl", [_rec("a", 1.0)])
        base = _write_jsonl(tmp_path / "b.jsonl",
                            [_rec("a", 1.0), _rec("b", 2.0)])
        assert bd.main([cur, "--baseline", base,
                        "--require-same-metrics"]) == 1
        assert bd.main([cur, "--baseline", base]) == 0

    def test_golden_cpu_line_passes_schema(self):
        golden = bd.load_records(
            os.path.join(REPO, "tools", "bench_golden_cpu.jsonl")
        )
        assert bd.check_schema(golden) == []
        # smoke + serving + train3d rows — the verify_tier1.sh PERF
        # pass runs all three configs against this file
        assert {r["metric"] for r in golden} == {
            "smoke_mlp_step_ms", "smoke_dp_mlp_step_ms",
            "serve_prefill_tokens_per_s", "serve_decode_tokens_per_s",
            "serve_ttft_ms",
            # the prefix-cache rows: warm-cache hit TTFT through the
            # scheduler + the deterministic analytic prefill-FLOPs
            # saving of a full hit (docs/serving.md "Prefix caching")
            "serve_prefix_hit_ttft_ms", "serve_prefill_flops_saved_pct",
            # the live ops plane rows (ISSUE 11): exporter scrape cost
            # + the deterministic burn-rate drill
            "ops_scrape_ms", "slo_alerts_fired",
            # the serving resilience rows (ISSUE 14): request goodput
            # under the serve chaos storm + p99 TTFT inflation vs the
            # fault-free reference (deterministic virtual-clock drill)
            "serve_chaos_goodput_pct", "serve_chaos_p99_inflation",
            # the speculative-decode rows (ISSUE 18): self-draft k=4
            # greedy acceptance (exact by construction) + emitted
            # tokens per decode step (docs/serving.md "Speculative
            # decoding")
            "serve_spec_accept_rate", "serve_spec_tokens_per_step",
            # the composable trainer's honest multi-device rows
            # (ISSUE 12): dp/tp >= 2 on the mocked 8-device mesh —
            # check_schema refuses degenerate train3d rows
            "train3d_dp2_step_ms", "train3d_tp2_step_ms",
            "train3d_dp2tp2_step_ms", "train3d_lint_errors",
            # the host-side analyzer row (ISSUE 19): lock-discipline +
            # replay-purity ERROR findings over the whole package,
            # pinned at 0 (docs/analysis.md "Concurrency &
            # replay-purity passes")
            "concurrency_lint_errors",
            # the goodput storm-drill rows (ISSUE 13): chaos-storm
            # goodput, zero-stall bound, ckpt enqueue/finalize stall,
            # input-stall fraction, bit-exact-resume drift
            "goodput_storm_pct", "goodput_zero_stall_pct",
            "goodput_ckpt_enqueue_ms", "goodput_ckpt_finalize_ms",
            "goodput_input_stall_frac", "goodput_resume_loss_drift",
            # the fleet control-plane rows (ISSUE 16): request goodput
            # under the crash+preempt+spike+deploy storm, accepted
            # requests lost by rolling deploys (must be 0), p99 TTFT
            # inflation vs the fault-free fixed-size reference
            "fleet_chaos_goodput_pct", "fleet_deploy_lost_requests",
            "fleet_p99_inflation",
            # the canary deploy-gate rows (ISSUE 20): ticks from window
            # open to the planted regression's FAIL verdict + rollback,
            # and FAIL verdicts across clean re-seeded deploys (must
            # stay 0.0 — docs/serving.md "Canary deploys")
            "fleet_canary_detect_ticks", "fleet_canary_false_positive",
        }


# ---------------------------------------------------------------------------
# bench.py degenerate marking (satellite pin)
# ---------------------------------------------------------------------------


class TestBenchEmit:
    def _bench(self):
        import importlib.util

        spec = importlib.util.spec_from_file_location(
            "bench_for_emit", os.path.join(REPO, "bench.py")
        )
        mod = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(mod)
        return mod

    def test_emit_degenerate_key_contract(self, capsys):
        bench = self._bench()
        bench._emit("m1", 1.0, "img/s (dp=1)", None, degenerate=True)
        bench._emit("m2", 2.0, "img/s (dp=8)", None)
        lines = [json.loads(l)
                 for l in capsys.readouterr().out.splitlines()]
        assert lines[0]["degenerate"] is True
        assert "degenerate" not in lines[1]
        # key order is the driver contract
        assert list(lines[0])[:4] == ["metric", "value", "unit",
                                      "vs_baseline"]
        # and --gate sees exactly what was printed
        assert bench._GATE_RECORDS[-2:] == lines

    def test_degenerate_sites_cover_multi_device_configs(self):
        """ddp_syncbn, tp_gpt and zero must keep marking their
        single-device runs: the source carries the degenerate= marking
        at each emit site (the honest-trajectory satellite)."""
        with open(os.path.join(REPO, "bench.py")) as f:
            src = f.read()
        assert src.count("degenerate=dp == 1") >= 3  # ddp, zero, smoke-dp
        assert src.count("degenerate=tp == 1") >= 1  # tp_gpt


# ---------------------------------------------------------------------------
# tools/step_profile.py acceptance (ISSUE 6)
# ---------------------------------------------------------------------------


class TestStepProfile:
    def test_resilient_target_fractions_and_mfu_agreement(self, tmp_path):
        """The acceptance line: fractions sum to 1 +- 0.02 and the
        roofline MFU matches the StepMeter within 5%."""
        out = tmp_path / "profile.json"
        env = dict(os.environ, JAX_PLATFORMS="cpu")
        env.pop("APEX_TPU_TRACE_STEPS", None)
        proc = subprocess.run(
            [sys.executable, os.path.join(REPO, "tools", "step_profile.py"),
             "--target", "resilient", "--steps", "5",
             "--json", str(out)],
            capture_output=True, text=True, env=env, timeout=420,
        )
        assert proc.returncode == 0, proc.stderr[-2000:]
        payload = json.loads(out.read_text())
        assert payload["fraction_sum"] == pytest.approx(1.0, abs=0.02)
        fr = payload["fractions"]
        assert set(fr) == {"compute", "collective", "host_stall"}
        assert all(0.0 <= v <= 1.0 for v in fr.values())
        assert payload["mfu"]["agreement"] <= 0.05
        assert payload["roofline"][-1]["bucket"] == "total"
        assert "step fractions" in proc.stdout
