"""SLO burn-rate alerting (ISSUE 11): hand-checked window math on the
BurnRateTracker, multi-window gating semantics, the SLO sources
(counter ratio + histogram latency), SLORule riding the full Watchdog
emission fan-out (board / flight / spans), and the deterministic drill
bench.py pins into the golden stream."""

import pytest

from apex_tpu.observability.flight import FlightRecorder
from apex_tpu.observability.health import Watchdog
from apex_tpu.observability.metrics import MetricRegistry, board
from apex_tpu.observability.ometrics import Histogram
from apex_tpu.observability.slo import (
    DEFAULT_WINDOWS,
    BurnRateTracker,
    CounterRatioSLO,
    LatencySLO,
    SLORule,
    Window,
    burn_rate_drill,
    serve_slo_rules,
)
from apex_tpu.observability.spans import SpanRecorder


@pytest.fixture(autouse=True)
def _clean_board():
    board.clear()
    yield
    board.clear()


# ---------------------------------------------------------------------------
# the burn-rate math, hand-checked
# ---------------------------------------------------------------------------


class TestBurnRateTracker:
    def test_hand_checked_window(self):
        """objective 0.9 (budget 0.1); 100 events/minute at a 50% error
        rate.  Error rate .5 / budget .1 = burn 5.0 — the windowed
        deltas must reproduce it exactly."""
        tr = BurnRateTracker(0.9, horizon_s=600)
        for minute in range(5):
            tr.observe(good=50.0 * minute, total=100.0 * minute,
                       t=60.0 * minute)
        assert tr.burn_rate(60.0) == pytest.approx(5.0)
        assert tr.burn_rate(240.0) == pytest.approx(5.0)

    def test_windowed_delta_not_lifetime(self):
        """A storm that ENDED: minutes 0-2 were 100% errors, minutes
        3-6 are clean.  The 60s window must read burn 0 (the short
        window is the 'still happening' proof) while a 360s window
        still reads the blended rate 3/6 / 0.1 = 5."""
        tr = BurnRateTracker(0.9, horizon_s=600)
        good = total = 0.0
        for minute in range(7):
            tr.observe(good, total, t=60.0 * minute)
            total += 100.0
            good += 0.0 if minute < 3 else 100.0
        assert tr.burn_rate(60.0) == pytest.approx(0.0)
        assert tr.burn_rate(360.0) == pytest.approx(5.0)

    def test_cold_start_returns_none_until_half_coverage(self):
        tr = BurnRateTracker(0.999, horizon_s=3600)
        tr.observe(0, 0, t=0.0)
        assert tr.burn_rate(300.0) is None  # one sample
        tr.observe(10, 100, t=60.0)
        # 60s of data: covers half of a 60s window... but only 1/5 of
        # a 300s one — extrapolating would manufacture pages
        assert tr.burn_rate(60.0) is not None
        assert tr.burn_rate(300.0) is None
        tr.observe(20, 200, t=150.0)
        assert tr.burn_rate(300.0) == pytest.approx(0.9 / 0.001)

    def test_no_events_in_window_is_none(self):
        tr = BurnRateTracker(0.9, horizon_s=600)
        tr.observe(50, 100, t=0.0)
        tr.observe(50, 100, t=120.0)  # nothing arrived since
        assert tr.burn_rate(60.0) is None

    def test_decimation_bounds_sample_count(self):
        """A per-iteration cadence against a long horizon must not
        hoard samples: arrivals inside min_interval_s REPLACE the
        newest sample, and the burn math still reads the latest
        cumulative counts."""
        tr = BurnRateTracker(0.9, horizon_s=3600, min_interval_s=10.0)
        for i in range(10_000):
            t = 0.01 * i  # 100 Hz for 100 seconds
            tr.observe(good=0.0, total=float(i), t=t)
        assert len(tr.samples) <= 12  # ~100s / 10s + anchors
        # freshness survived decimation: the newest cumulative count
        # is the last observed one
        assert tr.samples[-1][2] == 9999.0
        assert tr.burn_rate(60.0) == pytest.approx(10.0)

    def test_horizon_trim_keeps_anchor(self):
        tr = BurnRateTracker(0.9, horizon_s=120)
        for minute in range(10):
            tr.observe(100.0 * minute, 100.0 * minute, t=60.0 * minute)
        # trimmed to the horizon + one anchor sample at/just before it
        assert len(tr.samples) <= 4
        assert tr.burn_rate(120.0) == pytest.approx(0.0)

    def test_burn_caps_at_total_budget_rate(self):
        tr = BurnRateTracker(0.9, horizon_s=600)
        tr.observe(0, 0, t=0.0)
        tr.observe(0, 100, t=60.0)  # 100% errors
        assert tr.burn_rate(60.0) == pytest.approx(10.0)  # 1.0 / 0.1

    def test_bad_objective_rejected(self):
        with pytest.raises(ValueError):
            BurnRateTracker(1.0, horizon_s=60)
        with pytest.raises(ValueError):
            BurnRateTracker(0.0, horizon_s=60)


# ---------------------------------------------------------------------------
# SLO sources
# ---------------------------------------------------------------------------


class TestSources:
    def test_counter_ratio(self):
        slo = CounterRatioSLO(
            "goodput", 0.95,
            good_keys=("serve/completed",),
            total_keys=("serve/completed", "serve/shed"),
        )
        assert slo.counts({}) is None  # no data = no claim
        assert slo.counts({"serve/completed": 8.0, "serve/shed": 2.0}) \
            == (8.0, 10.0)
        assert slo.error_budget == pytest.approx(0.05)

    def test_latency_histogram(self):
        h = Histogram("serve/ttft_hist_ms", (10.0, 100.0), unit="ms")
        slo = LatencySLO("ttft", 0.9, histogram=h, threshold=10.0)
        assert slo.counts({}) is None
        for v in (5.0, 50.0, 7.0, 500.0):
            h.observe(v)
        assert slo.counts({}) == (2.0, 4.0)

    def test_objective_bounds(self):
        with pytest.raises(ValueError):
            CounterRatioSLO("x", 1.5, good_keys=("a",), total_keys=("a",))


# ---------------------------------------------------------------------------
# SLORule: multi-window gating + the Watchdog fan-out
# ---------------------------------------------------------------------------


def _storm_rule(window=Window(60.0, 240.0, 2.0, "critical"),
                error_rate=0.5, cooldown=64):
    """A rule fed by a synthetic clock + counter source; advance() runs
    one check-minute of ``error_rate`` traffic."""
    state = {"t": 0.0, "good": 0.0, "total": 0.0, "step": 0}
    rule = SLORule(
        CounterRatioSLO("t", 0.9, good_keys=("good",),
                        total_keys=("total",)),
        windows=(window,), cooldown=cooldown,
        values_fn=lambda: {"good": state["good"],
                           "total": state["total"]},
        clock=lambda: state["t"],
    )

    class _Wd:
        registry = None

    def advance():
        state["t"] = 60.0 * state["step"]
        fired = rule.check(_Wd(), state["step"])
        state["step"] += 1
        state["good"] += 100.0 * (1.0 - error_rate)
        state["total"] += 100.0
        return fired

    return rule, advance


class TestSLORule:
    def test_fires_when_both_windows_hot(self):
        rule, advance = _storm_rule()
        fired = []
        for _ in range(3):
            fired += advance()
        # t=0: one sample; t=60: short hot (burn 5) but long under
        # half coverage; t=120: both hot -> exactly one event
        assert len(fired) == 1
        ev = fired[0]
        assert ev.rule == "slo_t" and ev.severity == "critical"
        assert ev.value == pytest.approx(5.0)
        assert ev.threshold == 2.0
        assert "burning 5.0x" in ev.message
        assert "objective 0.9" in ev.message

    def test_quiet_when_under_budget(self):
        rule, advance = _storm_rule(error_rate=0.01)  # burn 0.1
        fired = []
        for _ in range(6):
            fired += advance()
        assert fired == []

    def test_short_blip_does_not_page(self):
        """One bad minute in an otherwise clean run: the long window
        dilutes it under the factor — the multi-window point."""
        state = {"t": 0.0, "good": 0.0, "total": 0.0}
        rule = SLORule(
            CounterRatioSLO("t", 0.9, good_keys=("good",),
                            total_keys=("total",)),
            windows=(Window(60.0, 600.0, 4.0, "critical"),),
            values_fn=lambda: dict(state),
            clock=lambda: state["t"],
        )

        class _Wd:
            registry = None

        fired = []
        for minute in range(11):
            state["t"] = 60.0 * minute
            fired += rule.check(_Wd(), minute)
            bad = 100.0 if minute == 5 else 0.0
            state["good"] += 100.0 - bad
            state["total"] += 100.0
        # short burn hits 10 at minute 6, but the 600s window reads
        # ~1/10 errors / 0.1 budget = burn ~1 < 4: no page
        assert fired == []

    def test_cooldown_heartbeat(self):
        rule, advance = _storm_rule(cooldown=2)
        fired = []
        for _ in range(7):
            fired += advance()
        # fires at minute 2, then on the 2-check heartbeat
        assert len(fired) == 3

    def test_reads_watchdog_registry(self):
        reg = MetricRegistry(fetch_every=1)
        reg.counter("serve/completed")
        reg.counter("serve/shed")
        t = {"now": 0.0}
        rule = SLORule(
            CounterRatioSLO("goodput", 0.9,
                            good_keys=("serve/completed",),
                            total_keys=("serve/completed", "serve/shed")),
            windows=(Window(60.0, 240.0, 2.0, "critical"),),
            clock=lambda: t["now"],
        )
        wd = Watchdog(rules=[rule], registry=reg, check_every=1,
                      clock=lambda: t["now"])
        st = reg.init()
        for step in range(4):
            t["now"] = 60.0 * step
            st = reg.update(st, {"serve/shed": 60.0,
                                 "serve/completed": 40.0})
            reg.observe(step, st)
            reg.fetch()
            wd.on_step(step)
        assert [e.rule for e in wd.events] == ["slo_goodput"]

    def test_event_rides_the_full_fanout(self):
        """The acceptance wiring: a fired SLO alert must land on the
        board, in the flight recorder's event log, AND on the span
        recorder's health track — the same timeline as the requests."""
        flight = FlightRecorder(capacity=8)
        spans = SpanRecorder(capacity=64)
        rule, advance_inner = _storm_rule()
        wd = Watchdog(rules=[rule], flight=flight, spans=spans,
                      check_every=1)
        # drive through the watchdog instead of the bare rule
        state_rule = rule  # reuse the synthetic source/clock
        for step in range(3):
            state_rule.values_fn  # (source already wired)
            advance_fired = advance_inner()
            for ev in advance_fired:
                wd._emit(ev)
        assert board.get("health/slo_t") == pytest.approx(5.0)
        kinds = [e["kind"] for e in flight.events]
        assert "health" in kinds
        health_spans = [
            e for e in spans.snapshot() if e.get("track") == "health"
        ]
        assert len(health_spans) == 1
        assert health_spans[0]["name"] == "health/slo_t"
        assert health_spans[0]["args"]["severity"] == "critical"

    def test_window_validation(self):
        slo = CounterRatioSLO("x", 0.9, good_keys=("a",),
                              total_keys=("a",))
        with pytest.raises(ValueError):
            SLORule(slo, windows=())
        with pytest.raises(ValueError):
            SLORule(slo, windows=(Window(600.0, 60.0, 2.0),))


class TestServeSet:
    def test_serve_slo_rules_composition(self):
        h = Histogram("serve/ttft_hist_ms", (10.0, 100.0), unit="ms")
        rules = serve_slo_rules(ttft_histogram=h, ttft_threshold_ms=10.0)
        assert [r.name for r in rules] == [
            "slo_ttft", "slo_goodput", "slo_deadline_shed",
        ]
        # without a histogram the latency SLO is skipped, not broken
        assert [r.name for r in serve_slo_rules()] == [
            "slo_goodput", "slo_deadline_shed",
        ]

    def test_default_windows_are_the_sre_pair(self):
        assert DEFAULT_WINDOWS[0] == (300.0, 3600.0, 14.4, "critical")
        assert DEFAULT_WINDOWS[1] == (1800.0, 21600.0, 6.0, "warn")

    def test_deadline_shed_distinguishes_reason(self):
        """Growth-victim sheds are a capacity story (goodput); ONLY the
        deadline sheds burn the deadline_shed budget."""
        rules = serve_slo_rules()
        dl = [r for r in rules if r.name == "slo_deadline_shed"][0]
        values = {"serve/completed": 90.0, "serve/shed": 10.0,
                  "serve/shed_growth_victim": 10.0}
        good, total = dl.slo.counts(values)
        assert (good, total) == (100.0, 100.0)  # victims count as good
        values = {"serve/completed": 90.0, "serve/shed": 10.0}
        good, total = dl.slo.counts(values)  # all 10 were deadline
        assert (good, total) == (90.0, 100.0)


def test_burn_rate_drill_is_deterministic():
    """The fixture bench.py emits as ``slo_alerts_fired``: 50% errors
    vs a 90% objective through one (60s, 240s, 2x) window fires
    EXACTLY once — pinned here against the hand math and in the
    bench_diff golden stream."""
    assert burn_rate_drill() == 1
    assert burn_rate_drill() == 1  # stateless across calls
