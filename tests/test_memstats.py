"""Live device-memory telemetry (ISSUE 11): provider degradation on
CPU, watermark gauge publication + bounded history, the static-vs-live
crosscheck (drift in EITHER direction names the governing program), the
watchdog rule, and the OOM-forensics hook draining the watermark
history into the flight recorder."""

import pytest

from apex_tpu.observability.flight import FlightRecorder
from apex_tpu.observability.health import Watchdog
from apex_tpu.observability.memstats import (
    DeviceMemoryProvider,
    FakeMemoryProvider,
    MemStatsMonitor,
    MemStatsRule,
    default_provider,
    oom_forensics,
    static_peaks_from_board,
)
from apex_tpu.observability.metrics import Board, board
from apex_tpu.observability.spans import SpanRecorder

MIB = 1 << 20


@pytest.fixture(autouse=True)
def _clean_board():
    board.clear()
    yield
    board.clear()


class TestProviders:
    def test_cpu_backend_degrades_to_empty(self):
        # the CPU backend reports no memory_stats: the documented
        # degradation is an empty view, not an exception
        assert DeviceMemoryProvider().stats() == {}
        assert default_provider() is None

    def test_fake_tracks_peak(self):
        fake = FakeMemoryProvider(limit_bytes=1024 * MIB)
        fake.set_usage(bytes_in_use=100 * MIB)
        fake.set_usage(bytes_in_use=50 * MIB)
        s = fake.stats()["device0"]
        assert s["bytes_in_use"] == 50 * MIB
        assert s["peak_bytes_in_use"] == 100 * MIB  # high-water holds
        assert s["bytes_limit"] == 1024 * MIB

    def test_fake_from_static_scales(self):
        fake = FakeMemoryProvider.from_static(
            {"decode": 10 * MIB, "prefill_16": 6 * MIB}, scale=2.0
        )
        assert fake.stats()["device0"]["peak_bytes_in_use"] == 20 * MIB
        with pytest.raises(ValueError):
            FakeMemoryProvider.from_static({})

    def test_fake_multi_device(self):
        fake = FakeMemoryProvider(devices=2, limit_bytes=MIB)
        fake.set_usage(device=1, bytes_in_use=MIB // 2)
        assert fake.stats()["device1"]["bytes_in_use"] == MIB // 2
        assert fake.stats()["device0"]["bytes_in_use"] == 0.0


class TestMonitor:
    def test_sample_publishes_watermark_gauges(self):
        fake = FakeMemoryProvider(limit_bytes=100 * MIB)
        fake.set_usage(bytes_in_use=25 * MIB)
        mon = MemStatsMonitor(fake)
        mon.sample(step=3)
        assert board.get("memstats/device0/bytes_in_use") == 25 * MIB
        assert board.get("memstats/device0/peak_bytes_in_use") == 25 * MIB
        assert board.get("memstats/device0/bytes_limit") == 100 * MIB
        assert board.get("memstats/samples") == 1

    def test_history_bounded_and_peaks_survive_trim(self):
        fake = FakeMemoryProvider(limit_bytes=100 * MIB)
        mon = MemStatsMonitor(fake, history=4)
        for i in range(10):
            fake.set_usage(bytes_in_use=(i + 1) * MIB)
            mon.sample(i)
        assert len(mon.watermarks()) == 4
        # the provider's own peak is a high-water mark, so the live
        # peak is not lost to ring eviction
        assert mon.live_peaks()["device0"] == 10 * MIB

    def test_needs_a_provider(self):
        with pytest.raises(ValueError, match="provider"):
            MemStatsMonitor(None)


class TestCrosscheck:
    def _monitor(self, live_bytes):
        fake = FakeMemoryProvider(limit_bytes=1024 * MIB)
        fake.set_usage(bytes_in_use=live_bytes)
        mon = MemStatsMonitor(fake)
        mon.sample(0)
        return mon

    def test_reconciled_within_tolerance(self):
        mon = self._monitor(11 * MIB)
        static = {"decode": 10 * MIB, "prefill_16": 6 * MIB}
        assert mon.crosscheck(static, tolerance=0.25) == []
        assert board.get("memstats/crosscheck") == 0.0

    def test_static_under_prediction_names_the_program(self):
        mon = self._monitor(25 * MIB)
        static = {"decode": 10 * MIB, "prefill_16": 6 * MIB}
        findings = mon.crosscheck(static, tolerance=0.25)
        assert len(findings) == 1
        f = findings[0]
        assert f["rule"] == "memstats-drift"
        assert f["program"] == "decode"  # the governing (max) program
        assert f["direction"] == "static-under-predicts"
        assert f["ratio"] == pytest.approx(2.5)
        assert "decode" in f["message"]
        assert board.get("memstats/crosscheck") == 1.0

    def test_static_over_prediction_is_also_drift(self):
        mon = self._monitor(2 * MIB)
        findings = mon.crosscheck({"decode": 10 * MIB}, tolerance=0.25)
        assert len(findings) == 1
        assert findings[0]["direction"] == "static-over-predicts"

    def test_no_basis_is_distinguishable_from_clean(self):
        mon = self._monitor(5 * MIB)
        assert mon.crosscheck({}, tolerance=0.25) == []
        assert board.get("memstats/crosscheck") == -1.0

    def test_harvests_static_peaks_from_board(self):
        board.set("serve/hbm/decode/peak_hbm_bytes", 10 * MIB)
        board.set("serve/hbm/prefill_16/peak_hbm_bytes", 6 * MIB)
        board.set("serve/hbm/decode/peak_hbm/params", 4 * MIB)  # not a peak
        board.set("analysis/peak_hbm_bytes", 8 * MIB)
        board.set("serve/kv_wire", "int8")  # strings never harvest
        peaks = static_peaks_from_board()
        assert peaks == {
            "decode": 10 * MIB, "prefill_16": 6 * MIB,
            "analysis": 8 * MIB,
        }
        # and crosscheck defaults to the harvested set
        mon = self._monitor(10 * MIB)
        assert mon.crosscheck(tolerance=0.25) == []

    def test_board_isolation(self):
        b = Board()
        b.set("serve/hbm/decode/peak_hbm_bytes", 123.0)
        assert static_peaks_from_board(b) == {"decode": 123.0}


class TestWatchdogRule:
    def test_drift_pages_through_the_watchdog(self):
        fake = FakeMemoryProvider(limit_bytes=1024 * MIB)
        fake.set_usage(bytes_in_use=30 * MIB)
        mon = MemStatsMonitor(fake)
        flight = FlightRecorder(capacity=8)
        spans = SpanRecorder(capacity=64)
        rule = MemStatsRule(mon, static_peaks={"decode": 10 * MIB},
                            tolerance=0.25)
        wd = Watchdog(rules=[rule], flight=flight, spans=spans,
                      check_every=1)
        wd.on_step(0)
        assert len(wd.events) == 1
        ev = wd.events[0]
        assert ev.rule == "memstats_drift"
        assert ev.severity == "critical"  # 3x is past 2*tolerance
        assert "decode" in ev.message
        assert board.get("health/memstats_drift") == pytest.approx(3.0)
        assert any(e["kind"] == "health" for e in flight.events)
        assert [e["name"] for e in spans.snapshot()
                if e.get("track") == "health"] == [
            "health/memstats_drift"
        ]

    def test_warn_inside_double_tolerance(self):
        fake = FakeMemoryProvider(limit_bytes=1024 * MIB)
        fake.set_usage(bytes_in_use=14 * MIB)  # 1.4x at tol 0.25
        rule = MemStatsRule(MemStatsMonitor(fake),
                            static_peaks={"decode": 10 * MIB},
                            tolerance=0.25)
        wd = Watchdog(rules=[rule], check_every=1)
        wd.on_step(0)
        assert [e.severity for e in wd.events] == ["warn"]

    def test_sampling_continues_under_cooldown(self):
        fake = FakeMemoryProvider(limit_bytes=1024 * MIB)
        fake.set_usage(bytes_in_use=30 * MIB)
        mon = MemStatsMonitor(fake)
        rule = MemStatsRule(mon, static_peaks={"decode": 10 * MIB},
                            cooldown=64)
        wd = Watchdog(rules=[rule], check_every=1)
        for step in range(5):
            wd.on_step(step)
        assert len(wd.events) == 1  # cooldown held the repeats
        assert mon.samples == 5  # but the forensic record kept growing


class TestOOMForensics:
    def _armed(self):
        fake = FakeMemoryProvider(limit_bytes=100 * MIB)
        mon = MemStatsMonitor(fake)
        for i in range(3):
            fake.set_usage(bytes_in_use=(30 + 30 * i) * MIB)
            mon.sample(i)
        return fake, mon, FlightRecorder(capacity=8)

    def test_resource_exhausted_drains_into_flight(self):
        fake, mon, flight = self._armed()
        with pytest.raises(RuntimeError, match="RESOURCE_EXHAUSTED"):
            with oom_forensics(mon, flight=flight):
                raise RuntimeError(
                    "RESOURCE_EXHAUSTED: Out of memory while trying to "
                    "allocate 104857600 bytes"
                )
        oom = [e for e in flight.events if e["kind"] == "oom"]
        assert len(oom) == 1
        assert "RESOURCE_EXHAUSTED" in oom[0]["error"]
        # the watermark CLIMB is the forensic payload (3 armed samples
        # + the final sample the hook takes at death)
        assert len(oom[0]["watermarks"]) == 4
        assert oom[0]["live_peaks"]["device0"] == 90 * MIB
        assert board.get("memstats/oom") == 1.0

    def test_memory_error_counts_as_oom(self):
        _fake, mon, flight = self._armed()
        with pytest.raises(MemoryError):
            with oom_forensics(mon, flight=flight):
                raise MemoryError()
        assert any(e["kind"] == "oom" for e in flight.events)

    def test_other_exceptions_pass_through_untouched(self):
        _fake, mon, flight = self._armed()
        with pytest.raises(ValueError):
            with oom_forensics(mon, flight=flight):
                raise ValueError("not an allocation failure")
        assert flight.events == []
        assert board.get("memstats/oom") is None

    def test_spans_get_the_instant_too(self):
        _fake, mon, _flight = self._armed()
        spans = SpanRecorder(capacity=16)
        with pytest.raises(MemoryError):
            with oom_forensics(mon, spans=spans):
                raise MemoryError()
        names = [e["name"] for e in spans.snapshot()]
        assert "health/oom" in names

    def test_hook_survives_a_dying_provider(self):
        class DyingProvider(FakeMemoryProvider):
            def stats(self):
                raise RuntimeError("device gone")

        fake = DyingProvider(limit_bytes=MIB)
        mon = MemStatsMonitor(fake)
        flight = FlightRecorder(capacity=8)
        with pytest.raises(MemoryError):
            with oom_forensics(mon, flight=flight):
                raise MemoryError()
        # the dump still landed (with whatever history existed)
        assert any(e["kind"] == "oom" for e in flight.events)
