"""Fused RoPE vs unfused reference (incl. autodiff-vs-custom_vjp grads)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from apex_tpu import ops


def ref_rope(t, freqs):
    rot_dim = freqs.shape[-1]
    t_rot, t_pass = t[..., :rot_dim], t[..., rot_dim:]
    tf = t_rot.astype(jnp.float32)
    out = tf * jnp.cos(freqs) + ops.rotate_half(tf) * jnp.sin(freqs)
    return jnp.concatenate((out.astype(t.dtype), t_pass), axis=-1)


def make_freqs(seq, rot_dim, duplicated=True):
    inv = 1.0 / (10000 ** (jnp.arange(0, rot_dim, 2) / rot_dim))
    ang = jnp.outer(jnp.arange(seq), inv)  # (seq, rot_dim/2)
    if duplicated:
        emb = jnp.concatenate((ang, ang), axis=-1)
    else:
        # deliberately non-duplicated halves: exercises the exact-transpose bwd
        emb = jnp.concatenate((ang, 2.0 * ang), axis=-1)
    return emb[:, None, None, :]


@pytest.mark.parametrize("dtype", [jnp.float32, jnp.bfloat16])
@pytest.mark.parametrize("rot_frac", [1.0, 0.5])
@pytest.mark.parametrize("duplicated", [True, False])
def test_rope_fwd_bwd(dtype, rot_frac, duplicated):
    seq, b, h, d = 12, 2, 3, 16
    rot_dim = int(d * rot_frac)
    t = jax.random.normal(jax.random.PRNGKey(0), (seq, b, h, d), dtype)
    freqs = make_freqs(seq, rot_dim, duplicated)

    got = ops.fused_apply_rotary_pos_emb(t, freqs)
    ref = ref_rope(t, freqs)
    atol = 2e-2 if dtype == jnp.bfloat16 else 1e-6
    np.testing.assert_allclose(
        np.asarray(got, np.float32), np.asarray(ref, np.float32), atol=atol
    )

    g_got = jax.grad(
        lambda t: jnp.sum(
            ops.fused_apply_rotary_pos_emb(t, freqs).astype(jnp.float32) ** 2
        )
    )(t)
    g_ref = jax.grad(
        lambda t: jnp.sum(ref_rope(t, freqs).astype(jnp.float32) ** 2)
    )(t)
    np.testing.assert_allclose(
        np.asarray(g_got, np.float32), np.asarray(g_ref, np.float32), atol=atol
    )


def test_rope_cached():
    seq, b, h, d = 8, 2, 2, 8
    t = jax.random.normal(jax.random.PRNGKey(1), (seq, b, h, d))
    freqs = make_freqs(seq, d)
    cos_, sin_ = jnp.cos(freqs), jnp.sin(freqs)
    got = ops.fused_apply_rotary_pos_emb_cached(t, cos_, sin_)
    ref = ref_rope(t, freqs)
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref), atol=1e-6)

    g_got = jax.grad(
        lambda t: jnp.sum(ops.fused_apply_rotary_pos_emb_cached(t, cos_, sin_) ** 2)
    )(t)
    g_ref = jax.grad(lambda t: jnp.sum(ref_rope(t, freqs) ** 2))(t)
    np.testing.assert_allclose(np.asarray(g_got), np.asarray(g_ref), atol=1e-5)
