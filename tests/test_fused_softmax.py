"""≙ tests/L0/run_transformer/test_fused_softmax.py — vs unfused composition."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from apex_tpu import ops


def ref_scaled_masked(x, mask, scale):
    xs = x.astype(jnp.float32) * scale
    if mask is not None:
        xs = jnp.where(mask, -10000.0, xs)
    return jax.nn.softmax(xs, axis=-1).astype(x.dtype)


@pytest.mark.parametrize("dtype", [jnp.float32, jnp.bfloat16])
@pytest.mark.parametrize("scale", [1.0, 0.125])
def test_scaled_softmax(dtype, scale):
    x = jax.random.normal(jax.random.PRNGKey(0), (2, 4, 8, 16), dtype)
    got = ops.scaled_softmax(x, scale)
    ref = ref_scaled_masked(x, None, scale)
    np.testing.assert_allclose(
        np.asarray(got, np.float32), np.asarray(ref, np.float32), atol=1e-2
        if dtype == jnp.bfloat16 else 1e-6,
    )
    g_got = jax.grad(lambda x: jnp.sum(ops.scaled_softmax(x, scale) ** 2))(x)
    g_ref = jax.grad(lambda x: jnp.sum(ref_scaled_masked(x, None, scale) ** 2))(x)
    np.testing.assert_allclose(
        np.asarray(g_got, np.float32),
        np.asarray(g_ref, np.float32),
        atol=1e-2 if dtype == jnp.bfloat16 else 1e-5,
    )


def test_scaled_masked_softmax():
    key = jax.random.PRNGKey(1)
    x = jax.random.normal(key, (2, 4, 8, 16))
    mask = jax.random.bernoulli(jax.random.PRNGKey(2), 0.3, (2, 1, 8, 16))
    scale = 0.5
    got = ops.scaled_masked_softmax(x, mask, scale)
    ref = ref_scaled_masked(x, mask, scale)
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref), atol=1e-6)
    # masked positions get (near-)zero probability
    assert float(jnp.max(jnp.where(mask, got, 0.0))) < 1e-4

    g_got = jax.grad(
        lambda x: jnp.sum(ops.scaled_masked_softmax(x, mask, scale) ** 2)
    )(x)
    g_ref = jax.grad(lambda x: jnp.sum(ref_scaled_masked(x, mask, scale) ** 2))(x)
    np.testing.assert_allclose(np.asarray(g_got), np.asarray(g_ref), atol=1e-5)


def test_scaled_upper_triang_masked_softmax():
    x = jax.random.normal(jax.random.PRNGKey(3), (6, 16, 16))
    scale = 0.25
    got = ops.scaled_upper_triang_masked_softmax(x, scale)
    causal = jnp.triu(jnp.ones((16, 16), bool), k=1)[None]
    ref = ref_scaled_masked(x, causal, scale)
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref), atol=1e-6)
    # row 0 attends only to position 0
    np.testing.assert_allclose(np.asarray(got[:, 0, 0]), 1.0, atol=1e-4)

    g_got = jax.grad(
        lambda x: jnp.sum(ops.scaled_upper_triang_masked_softmax(x, scale) ** 2)
    )(x)
    g_ref = jax.grad(lambda x: jnp.sum(ref_scaled_masked(x, causal, scale) ** 2))(x)
    np.testing.assert_allclose(np.asarray(g_got), np.asarray(g_ref), atol=1e-5)


def test_generic_alias():
    x = jax.random.normal(jax.random.PRNGKey(4), (3, 5, 7))
    mask = jax.random.bernoulli(jax.random.PRNGKey(5), 0.2, (3, 5, 7))
    got = ops.generic_scaled_masked_softmax(x, mask, 2.0)
    ref = ref_scaled_masked(x, mask, 2.0)
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref), atol=1e-6)
