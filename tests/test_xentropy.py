"""≙ apex/contrib/test/xentropy — fused CE vs unfused reference w/ smoothing."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from apex_tpu import ops


def ref_loss(logits, labels, smoothing=0.0, ignore_idx=-100):
    lf = logits.astype(jnp.float32)
    logp = jax.nn.log_softmax(lf, axis=-1)
    v = logits.shape[-1]
    one_hot = jax.nn.one_hot(labels, v)
    if smoothing > 0:
        target = (1 - smoothing) * one_hot + smoothing / v
    else:
        target = one_hot
    nll = -jnp.sum(target * logp, axis=-1)
    return jnp.where(labels != ignore_idx, nll, 0.0)


@pytest.mark.parametrize("dtype", [jnp.float32, jnp.bfloat16])
@pytest.mark.parametrize("smoothing", [0.0, 0.1])
def test_xentropy_fwd_bwd(dtype, smoothing):
    n, v = 32, 100
    logits = jax.random.normal(jax.random.PRNGKey(0), (n, v), dtype) * 3
    labels = jax.random.randint(jax.random.PRNGKey(1), (n,), 0, v)

    got = ops.softmax_cross_entropy_loss(logits, labels, smoothing)
    ref = ref_loss(logits, labels, smoothing)
    atol = 3e-2 if dtype == jnp.bfloat16 else 1e-5
    np.testing.assert_allclose(
        np.asarray(got, np.float32), np.asarray(ref, np.float32), atol=atol,
        rtol=1e-3,
    )

    g_got = jax.grad(
        lambda l: jnp.sum(ops.softmax_cross_entropy_loss(l, labels, smoothing))
    )(logits)
    g_ref = jax.grad(lambda l: jnp.sum(ref_loss(l, labels, smoothing)))(logits)
    np.testing.assert_allclose(
        np.asarray(g_got, np.float32), np.asarray(g_ref, np.float32), atol=atol
    )


def test_ignore_index():
    n, v = 8, 10
    logits = jax.random.normal(jax.random.PRNGKey(2), (n, v))
    labels = jnp.array([0, 1, -100, 3, -100, 5, 6, 7])
    loss = ops.softmax_cross_entropy_loss(logits, labels, 0.0)
    assert float(loss[2]) == 0.0 and float(loss[4]) == 0.0
    g = jax.grad(lambda l: jnp.sum(ops.softmax_cross_entropy_loss(l, labels)))(
        logits
    )
    np.testing.assert_allclose(np.asarray(g[2]), 0.0, atol=1e-7)
    np.testing.assert_allclose(np.asarray(g[4]), 0.0, atol=1e-7)


def test_module_shaped_api():
    logits = jax.random.normal(jax.random.PRNGKey(3), (4, 16))
    labels = jnp.array([0, 3, 7, 15])
    # default padding_idx=0 zeroes rows whose label is 0 (reference semantics)
    got = ops.SoftmaxCrossEntropyLoss.apply(logits, labels, 0.1)
    ref = ref_loss(logits, labels, 0.1, ignore_idx=0)
    assert float(got[0]) == 0.0
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref), atol=1e-5)
    # explicit non-colliding padding_idx keeps all rows
    got2 = ops.SoftmaxCrossEntropyLoss.apply(logits, labels, 0.1, padding_idx=-1)
    np.testing.assert_allclose(
        np.asarray(got2), np.asarray(ref_loss(logits, labels, 0.1)), atol=1e-5
    )
