"""Test harness: an 8-device CPU mesh in one process.

The reference's distributed tests spawn one NCCL process per GPU
(apex/transformer/testing/distributed_test_base.py :: DistributedTestBase) and
skip when <2 GPUs are present.  The TPU-native analog is strictly better:
``--xla_force_host_platform_device_count=8`` gives eight XLA CPU devices in a
single process, so every DP/TP/PP/SP test runs in CI with no hardware.

NOTE: this environment registers an `axon` TPU backend at interpreter startup
(sitecustomize) and forces ``jax_platforms``; we override back to CPU before
any backend is initialized.
"""

import os

os.environ["XLA_FLAGS"] = (
    os.environ.get("XLA_FLAGS", "") + " --xla_force_host_platform_device_count=8"
)

import jax

jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_enable_x64", False)

import pytest  # noqa: E402


@pytest.fixture(autouse=True)
def _reset_parallel_state():
    """Each test starts from a clean mesh registry."""
    from apex_tpu import parallel_state

    yield
    parallel_state.destroy_model_parallel()


@pytest.fixture
def eight_devices():
    devs = jax.devices()
    if len(devs) < 8:
        pytest.skip("needs 8 virtual devices")
    return devs


def pytest_configure(config):
    config.addinivalue_line(
        "markers", "slow: multi-process / long-running tests"
    )
    config.addinivalue_line(
        "markers",
        "chaos: fault-injection tests (apex_tpu.resilience.chaos) — "
        "select with `pytest -m chaos`",
    )


# Tiering (VERDICT r2 item 8): everything that measured >= ~10 s on this
# 1-core container (full run: 389 tests / ~38 min, 2026-07-30,
# `pytest --durations=40`) is marked slow centrally here, so the default
# quick signal is `pytest -m "not slow"` (~4-5 min) and CI runs the full
# suite.  Regenerate the list with `pytest --durations=40` after adding
# heavy tests.  test_examples_smoke.py is slow wholesale (end-to-end
# example drives, ~13 min of the total).
_SLOW_FILES = {"test_examples_smoke.py"}
_SLOW_TESTS = {
    "test_gpt_moe_trains_and_matches_ep",
    "test_bert_sp_grads_match_unsharded",
    "test_dryrun_multichip",
    "test_gpt_moe_sp_grads_match_unsharded",
    "test_1f1b_bert_stages_match_sequential",
    "test_gpt_sp_grads_match_unsharded",
    "test_cp_moe_gpt_matches_unsharded",
    "test_syncbn_variant_runs",
    "test_bert_tp_noSP_head_grads_match_unsharded",
    "test_forward_and_grad",
    "test_unsharded_loss_and_grads",
    "test_gpt_tp_noSP_grads_match_unsharded",
    "test_cp_gpt_matches_unsharded",
    "test_reregistration_on_retrace",
    "test_two_process_cpu_psum",
    "test_grads_flow",
    "test_cp_with_tp_loss_matches",
    "test_chunked_mlm_loss_matches_unchunked",
    "test_packed_mlm_matches_dense",
    "test_packed_mlm_tp_sp_matches_unsharded",
    "test_sp_matches_tp",
    "test_unrolled_matches_scanned",
    "test_forward_and_grads_unsharded",
    "test_rope_cached",
    "test_interleaved_matches_sequential_configs",
    "test_training_descends",
    "test_rope_fwd_bwd",
    "test_tp_matches_unsharded",
    "test_arbitrary_seq_with_bias_parity",
    "test_1f1b_carry_chunk_matches_sequential",
    "test_interleaved_carry_chunk_matches_sequential",
    # interpret-mode kernel parametrization sweeps (the quick tier keeps
    # test_trainable_bias_multiblock / test_arbitrary_seq_grads_parity /
    # test_mask_semantics_and_rate as representatives of each family)
    "test_trainable_bias_grad_matches_reference",
    "test_arbitrary_seq_kernel_parity",
    "test_grads_consistent_with_forward",
    "test_dropout_with_trainable_bias_grads",
    "test_dropout_with_causal_and_padding",
    "test_mask_varies_per_batch_head",
    "test_interleaved_matches_sequential",
    "test_imagenet_amp_smoke",
    "test_tp_sp_matches_unsharded",
    "test_causality",
    "test_loss_grad_finite",
    "test_openfold_axial_pair_stack_sharded_matches_unsharded",
    "test_evoformer_pair_block_dap_matches_unsharded",
    "test_evoformer_pair_block_dap_grads_match",
    "test_evoformer_block_dap_matches_unsharded",
    "test_evoformer_block_dap_grads_match",
    # quick tier keeps test_trainable_bias_multiblock as the dbias-kernel
    # representative; this one re-proves it through TriangleAttention
    "test_triangle_attention_bias_is_trainable",
    "test_spatial_matches_full",
    "test_synced_grads_match_global_objective",
    "test_sp_dropout_masks_differ_per_rank",
    "test_scaled_upper_triang_masked_softmax",
    "test_lstm_vs_loop_reference",
    "test_checkpoint_matches_uncheckpointed",
    "test_instance_norm_module_running_stats",
    "test_key_padding_bias_not_materialized",
    "test_loss_vs_brute_force",
    "test_fused_scale_mask_softmax_causal",
    # both parametrizations of the ring-dropout keep-mask golden (~12 s
    # each); quick keeps the zigzag value/grad tests + requires-rng probe
    "test_ring_dropout_matches_blockmask_golden",
    # model-level zigzag regression pin (oversized position table):
    # rides the full tier with the rest of the cp model parity suite
    "test_cp_zigzag_positions_with_oversized_table",
    # int8-wire convergence (r5: parametrized over block sizes, so it
    # moved here from _SLOW_EXACT — every parametrization is slow; the
    # quick tier keeps error-bound/bucketing/exactness coverage)
    "test_ddp_training_converges_with_quantized_sync",
    # r5b margin trim (moved here from _SLOW_EXACT, which is
    # parametrization-only by contract — these four are whole
    # non-parametrized tests; ADVICE r5): channels-first instance norm
    # is a layout transpose over the functional path whose [bfloat16]
    # id stays quick; the with-lse key-padding parity is re-proven
    # through the quick ring test
    # (test_ring_key_padding_bias_matches_full[False]) and the
    # kernel-level bias tests.
    "test_instance_norm_channels_first_parity",
    "test_key_padding_bias_matches_reference",
    # second r5b pass: the sharded-reshard checkpoint case rides full
    # (quick keeps manager retention/raises + the full-training-state
    # resume, the strongest checkpoint signal); the Elman
    # activation-override review pin is a stable regression guard, full
    # tier is where pins live once the fix has soaked.
    "test_sharded_roundtrip_and_reshard",
    "test_elman_activation_override_respected",
}

# Slow PARAMETRIZATIONS of otherwise-quick families: match the exact test
# id so at least one parameter combination of each family stays in the
# quick tier as a representative.
_SLOW_EXACT = {
    # r3 re-tier: one param of each pair carries the quick signal
    "test_remat_policy_preserves_values[full]",
    "test_remat_policy_preserves_values[dots]",
    "test_layer_norm_affine_fwd_bwd[False-bfloat16-shape1]",
    "test_layer_norm_affine_fwd_bwd[False-bfloat16-shape2]",
    "test_xentropy_fwd_bwd[0.0-bfloat16]",
    "test_rms_norm_affine_fwd_bwd[False-bfloat16]",
    "test_scaled_softmax[0.125-float32]",
    "test_triangle_multiplicative_update_dap_matches[incoming]",
    "test_layer_norm_affine_fwd_bwd[False-float32-shape0]",
    "test_layer_norm_affine_fwd_bwd[False-float32-shape1]",
    "test_layer_norm_affine_fwd_bwd[False-float32-shape2]",
    "test_rms_norm_affine_fwd_bwd[False-float32]",
    "test_xentropy_fwd_bwd[0.0-float32]",
    "test_shapes_and_grad[RNNReLU]",
    "test_shapes_and_grad[mLSTM]",
    "test_shapes_and_grad[GRU]",
    "test_conv_bias_relu_value_and_grad[float32]",
    "test_conv_bias_relu_value_and_grad[bfloat16]",
    "test_scaled_softmax[1.0-float32]",
    "test_scaled_softmax[1.0-bfloat16]",
    "test_group_norm_value_and_grad[float32]",
    "test_arbitrary_seq_grads_parity[333-259]",
    "test_ep_matches_unsharded[1]",
    "test_standalone_providers_forward[bert_model_provider]",
    "test_ring_kernel_path_matches_full[True]",
    "test_pallas_kernel_matches_jnp_path[False-False]",
    "test_vocab_parallel_cross_entropy_matches_full[0.0]",
    "test_instance_norm_functional_matches_manual[float32]",
    "test_groupbn_value_and_grad[False-float32]",
    "test_grads_include_lse_cotangent[False]",
    "test_grads_match_reference[False]",
    "test_matches_plain_bn_math",
    "test_ring_grads_match_full[False]",
    "test_ring_grads_match_full[True]",
    "test_wgrad_is_f32_under_bf16_compute[ColumnParallelLinear]",
    "test_ignore_index",
    "test_sequence_parallel_pair_matches_dense",
    "test_focal_loss_ignore_and_grad_finite[float32]",
    "test_fused_scale_mask_softmax_padding_mask",
    "test_self_attn_matches_reference",
    "test_save_restore_roundtrip",
    "test_bn_group_psum",
    "test_sigmoid_focal_loss_value_and_grad[float32]",
    "test_group_norm_module_grad_dtypes[float32]",
    "test_generic_alias",
    "test_gated_attention_matches_manual_composition",
    "test_encdec_attn",
    "test_capacity_bounds_per_expert",
    "test_vs_compose",
    # r4 re-tier (VERDICT r3 #8: quick tier standalone ≤ 240 s on this
    # 1-core container; measured 328 s before, 237 s after, both
    # standalone 2026-07-31).  Families keep a quick representative:
    # LN keeps [True-*-shape0] + the pallas-vs-jnp [True-*] ids,
    # scaled-softmax keeps test_scaled_masked_softmax, xentropy keeps
    # [0.1-bfloat16], rms keeps [True-bfloat16], group_norm keeps
    # module_grad_dtypes[bfloat16], hand-1F1B keeps both pp=4 modes,
    # remat-policy parity rides the full tier + the dryrun's "sums" leg
    # (its class fixture alone cost 13.8 s), packed-MLM and the
    # gpt-provider forward ride the full tier + __graft_entry__ drives.
    "test_remat_policy_preserves_values[sums]",
    "test_layer_norm_affine_fwd_bwd[True-float32-shape1]",
    "test_layer_norm_affine_fwd_bwd[True-float32-shape2]",
    "test_layer_norm_affine_fwd_bwd[False-bfloat16-shape0]",
    "test_scaled_softmax[0.125-bfloat16]",
    "test_xentropy_fwd_bwd[0.1-float32]",
    "test_rms_norm_affine_fwd_bwd[True-float32]",
    "test_group_norm_value_and_grad[bfloat16]",
    "test_pallas_kernel_matches_jnp_path[False-True]",
    "test_hand_1f1b_matches_sequential[8-residuals]",
    "test_hand_1f1b_matches_sequential[8-input]",
    "test_ep_matches_unsharded[2]",
    "test_standalone_providers_forward[gpt_model_provider]",
    "test_packed_mlm_truncates_and_chunks",
    "test_outer_product_mean_math",
    # ring-dropout keep-mask golden (~14 s): the quick tier keeps the
    # cheap zigzag value/grad parity tests + the requires-rng probe
    "test_ring_zigzag_dropout_matches_blockmask_golden",
    # zigzag parity: cp=2 (values AND grads) carries the quick signal
    "test_ring_zigzag_matches_full[4]",
    "test_ring_zigzag_matches_full[8]",
    # r4 second trim for headroom vs the 240 s budget (measurements on
    # this shared core wobble ±10 s): each family keeps a cheaper quick
    # representative (key-padding → kernel-level bias tests,
    # groupbn → module-grad variants, triangle-mult → [incoming] math)
    "test_self_attn_key_padding_mask",
    "test_groupbn_value_and_grad[False-bfloat16]",
    "test_triangle_multiplicative_update_math[outgoing]",
    # ring key-padding: the contiguous non-causal test carries the quick
    # signal; the causal and zigzag variants ride the full tier
    "test_ring_key_padding_bias_matches_full[True]",
    "test_ring_zigzag_key_padding_bias_matches_full",
    # r4 third trim (row additions pushed the measured tier to 287 s;
    # target ≤ 240 s — note this box's wall measurements wobble ±15 s
    # with background load, so the tier is sized ~25 s under target):
    # GPT remat-policy parity rides the full tier (the boundary drive +
    # hand-1F1B policy test keep sums covered); the quick LN set is now
    # [True-bfloat16-shape0] + [False-bfloat16-shape1,2] (memory-
    # efficient=True keeps exactly ONE quick id — do not trim
    # [True-bfloat16-shape0] without adding another back); RNN and
    # xentropy families ride the full tier (stable modules; their other
    # variants were already tiered); groupbn keeps [True-bfloat16];
    # quantized-allreduce keeps error-bound/bucketing/exactness quick
    # with the convergence test in the full tier; focal keeps
    # sigmoid_focal[bfloat16].  test_scaled_masked_softmax stays QUICK:
    # it is the fused-softmax family's only quick id (everything else in
    # test_fused_softmax.py is slow-tiered).
    "test_gpt_remat_policy_preserves_values[dots]",
    "test_gpt_remat_policy_preserves_values[sums]",
    "test_layer_norm_affine_fwd_bwd[True-bfloat16-shape1]",
    "test_layer_norm_affine_fwd_bwd[True-bfloat16-shape2]",
    "test_layer_norm_affine_fwd_bwd[True-float32-shape0]",
    "test_shapes_and_grad[RNNTanh]",
    "test_groupbn_value_and_grad[True-float32]",
    "test_pallas_kernel_matches_jnp_path[True-False]",
    "test_xentropy_fwd_bwd[0.1-bfloat16]",
    "test_vocab_parallel_cross_entropy_matches_full[0.1]",
    "test_focal_loss_ignore_and_grad_finite[bfloat16]",
    # r5 entry-tier (VERDICT r4 #8: tier new tests on entry, not after a
    # breach): hand-INTERLEAVED 1F1B keeps [residuals] + the
    # rejects-indivisible probe quick; the [input] stash variant, the
    # head-lane test (covered by the config fuzz and the plain-1F1B
    # head test), forward_only delegate, and deep-pipe/fuzz cases ride
    # the full tier (deep/fuzz are already @slow in-file).  Measured
    # 2026-08-01 standalone: 319 quick 235.9 s → after the r5 trims and
    # the dq-tile/tuned-table additions, 320 quick 223.6 s (this box
    # wobbles ±15 s vs r4's 217 s baseline).
    "test_hand_interleaved_matches_sequential[input]",
    "test_hand_interleaved_forward_only",
    "test_hand_interleaved_loss_takes_params",
    # independent-dq-tile parity: the no-dropout param carries the quick
    # signal; the dropout variant rides the full tier
    "test_dq_tiles_do_not_change_grads[0.2]",
    # tuned-tile table: the cheaper cross-attention fallback test (which
    # also proves consultation) carries the quick signal; the full
    # heuristic-must-not-be-called probe rides the full tier
    "test_table_entries_are_consulted_and_numerics_unchanged",
    # r5b margin trims (watcher-free standalone 223.6 s vs the 240 s
    # budget; later measurements 251/262/283 s — this shared core's
    # wall clock wobbles ±30 s run-to-run) landed four WHOLE
    # non-parametrized tests here; they moved to _SLOW_TESTS (ADVICE
    # r5) because this set's contract is parametrization-only: every
    # entry must carry a [param] suffix so each family keeps at least
    # one quick representative by construction.
}


def pytest_collection_modifyitems(config, items):
    for item in items:
        name = getattr(item, "originalname", None) or item.name
        if (
            item.fspath.basename in _SLOW_FILES
            or name in _SLOW_TESTS
            or item.name in _SLOW_EXACT
        ):
            item.add_marker(pytest.mark.slow)
