"""Test harness: an 8-device CPU mesh in one process.

The reference's distributed tests spawn one NCCL process per GPU
(apex/transformer/testing/distributed_test_base.py :: DistributedTestBase) and
skip when <2 GPUs are present.  The TPU-native analog is strictly better:
``--xla_force_host_platform_device_count=8`` gives eight XLA CPU devices in a
single process, so every DP/TP/PP/SP test runs in CI with no hardware.

NOTE: this environment registers an `axon` TPU backend at interpreter startup
(sitecustomize) and forces ``jax_platforms``; we override back to CPU before
any backend is initialized.
"""

import os

os.environ["XLA_FLAGS"] = (
    os.environ.get("XLA_FLAGS", "") + " --xla_force_host_platform_device_count=8"
)

import jax

jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_enable_x64", False)

import pytest  # noqa: E402


@pytest.fixture(autouse=True)
def _reset_parallel_state():
    """Each test starts from a clean mesh registry."""
    from apex_tpu import parallel_state

    yield
    parallel_state.destroy_model_parallel()


@pytest.fixture
def eight_devices():
    devs = jax.devices()
    if len(devs) < 8:
        pytest.skip("needs 8 virtual devices")
    return devs


def pytest_configure(config):
    config.addinivalue_line(
        "markers", "slow: multi-process / long-running tests"
    )
