"""gradient_accumulation_fusion honesty tests (VERDICT r1 item 6).

The tensor-parallel layers claim the reference's wgrad-accumulation fusion
(``fused_weight_gradient_mlp_cuda`` :: wgrad GEMM accumulating into an fp32
main_grad) *structurally*: f32 ``param_dtype`` + bf16 compute ``dtype`` ⇒
the backward matmul produces the weight cotangent directly in f32 (MXU
accumulates in f32; ``preferred_element_type`` keeps the output f32 — no
bf16 round-trip of the wgrad).  These tests pin that claim to the jaxpr so
flipping param/compute dtype handling breaks a test, not just a docstring.
"""

import jax
import jax.numpy as jnp
import pytest

from apex_tpu.transformer.tensor_parallel import (
    ColumnParallelLinear,
    RowParallelLinear,
)


def _wgrad_dot_eqns(jaxpr, weight_shape):
    """All dot_general eqns in (possibly nested) jaxprs producing the
    weight-cotangent shape (either orientation)."""
    found = []

    def visit(jx):
        for eqn in jx.eqns:
            if eqn.primitive.name == "dot_general":
                shp = eqn.outvars[0].aval.shape
                if shp in (weight_shape, weight_shape[::-1]):
                    found.append(eqn)
            for p in eqn.params.values():
                if hasattr(p, "jaxpr"):  # ClosedJaxpr
                    visit(p.jaxpr)
                elif hasattr(p, "eqns"):  # Jaxpr
                    visit(p)

    visit(jaxpr.jaxpr)
    return found


@pytest.mark.parametrize("layer_cls", [ColumnParallelLinear, RowParallelLinear])
def test_wgrad_is_f32_under_bf16_compute(layer_cls):
    layer = layer_cls(64, 128, dtype=jnp.bfloat16)
    x = jax.random.normal(jax.random.PRNGKey(0), (8, 64), jnp.bfloat16)
    params = layer.init(jax.random.PRNGKey(1), x)
    w = params["params"]["weight"]
    assert w.dtype == jnp.float32  # param_dtype default

    def loss(p):
        return jnp.sum(layer.apply(p, x).astype(jnp.float32) ** 2)

    grads = jax.grad(loss)(params)
    assert grads["params"]["weight"].dtype == jnp.float32
    assert grads["params"]["bias"].dtype == jnp.float32

    # The jaxpr-level claim: the dot_general that *produces* the weight
    # cotangent emits f32 directly (preferred_element_type=f32), i.e. the
    # wgrad never exists as a bf16 tensor.
    jaxpr = jax.make_jaxpr(jax.grad(loss))(params)
    dots = _wgrad_dot_eqns(jaxpr, w.shape)
    assert dots, "no wgrad dot_general found in the backward jaxpr"
    for eqn in dots:
        assert eqn.outvars[0].aval.dtype == jnp.float32
        assert eqn.params["preferred_element_type"] == jnp.float32


def test_wgrad_dtype_follows_param_dtype():
    """The failing direction: flip param_dtype to bf16 and the f32-wgrad
    property is gone — proving the test above actually guards something."""
    layer = ColumnParallelLinear(
        64, 128, dtype=jnp.bfloat16, param_dtype=jnp.bfloat16
    )
    x = jax.random.normal(jax.random.PRNGKey(0), (8, 64), jnp.bfloat16)
    params = layer.init(jax.random.PRNGKey(1), x)
    assert params["params"]["weight"].dtype == jnp.bfloat16

    def loss(p):
        return jnp.sum(layer.apply(p, x).astype(jnp.float32) ** 2)

    grads = jax.grad(loss)(params)
    assert grads["params"]["weight"].dtype == jnp.bfloat16
