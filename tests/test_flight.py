"""Flight recorder: ring semantics, atomic JSON-safe dumps, arming
inside run_resilient (skip-budget exhaustion, SIGTERM/preemption, env),
rollback replay re-arming, and the postmortem tooling
(tools/flight_view.py, tools/trace_summary.py --flight).
ISSUE 5 acceptance: a dying chaos run always leaves a parseable black
box whose last frames carry the guard state that explains the failure.
"""

import json
import os
import sys

import pytest

import jax.numpy as jnp

from apex_tpu.observability import (
    FlightRecorder,
    GoodputAccountant,
    MetricRegistry,
    parse_flight_spec,
)
from apex_tpu.observability.flight import ENV_FLIGHT, json_safe
from apex_tpu.resilience import ObserverFanout, chaos, run_resilient

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
TOOLS = os.path.join(REPO, "tools")


def _load(path):
    with open(path) as f:
        return json.load(f)


# ---------------------------------------------------------------------------
# unit: spec parsing, ring, JSON safety
# ---------------------------------------------------------------------------


def test_parse_flight_spec_forms():
    assert parse_flight_spec("64") == (64, None)
    assert parse_flight_spec("16:/tmp/fl") == (16, "/tmp/fl")
    assert parse_flight_spec("0") == (0, None)
    with pytest.raises(ValueError):
        parse_flight_spec("banana")


def test_from_env_unset_or_zero_is_unarmed(monkeypatch):
    monkeypatch.delenv(ENV_FLIGHT, raising=False)
    assert FlightRecorder.from_env() is None
    assert FlightRecorder.from_env("0") is None
    armed = FlightRecorder.from_env("8:/tmp/fl_env")
    assert armed.capacity == 8 and armed.directory == "/tmp/fl_env"


def test_ring_keeps_last_capacity_frames_and_marks_replay():
    rec = FlightRecorder(capacity=4, directory="/tmp/unused")
    for step in range(6):
        rec.on_step(step)
    assert [f["step"] for f in rec.frames] == [2, 3, 4, 5]
    # a rollback replay rewinds the counter: recording continues, the
    # first rewound frame carries the replay mark, seq stays monotonic
    rec.on_rollback(5, 2, 3, 0)
    rec.on_step(3)
    rec.on_step(4)
    frames = rec.frames
    assert frames[-2]["step"] == 3 and frames[-2].get("replay") is True
    assert frames[-1]["step"] == 4 and "replay" not in frames[-1]
    seqs = [r["seq"] for r in frames] + [e["seq"] for e in rec.events]
    assert len(set(seqs)) == len(seqs)
    assert rec.events[-1]["kind"] == "rollback"


def test_json_safe_preserves_nonfinite_as_strings():
    enc = json_safe(
        {"a": float("nan"), "b": float("inf"), "c": -float("inf"),
         "d": 1.5, "e": [float("nan")], "f": jnp.float32(2.0)}
    )
    assert enc["a"] == "NaN" and enc["b"] == "Infinity"
    assert enc["c"] == "-Infinity" and enc["d"] == 1.5
    assert enc["e"] == ["NaN"] and enc["f"] == 2.0
    json.dumps(enc, allow_nan=False)  # genuinely valid JSON


def test_dump_is_atomic_and_drains_registry(tmp_path):
    """The dump appends a FINAL frame with force-drained values — the
    guard state at death, not one fetch cadence stale — and leaves no
    tmp debris next to the artifact."""
    reg = MetricRegistry(fetch_every=100)  # never fetches on its own
    reg.gauge("guard/consecutive_skips")
    state = reg.update(reg.init(), {"guard/consecutive_skips": 7.0})
    rec = FlightRecorder(
        capacity=8, directory=str(tmp_path), registry=reg,
        goodput=GoodputAccountant(),
    )
    reg.observe(1, state)  # stashed, NOT fetched (off cadence)
    rec.on_step(1)
    assert rec.frames[-1]["metrics"] == {}  # stale by design pre-dump
    path = rec.dump("unit test")
    data = _load(path)
    assert data["reason"] == "unit test"
    assert data["final"]["metrics"]["guard/consecutive_skips"] == 7.0
    assert data["goodput"]["goodput"] == 1.0
    assert not [p for p in os.listdir(tmp_path) if p.endswith(".tmp")]


# ---------------------------------------------------------------------------
# armed inside run_resilient
# ---------------------------------------------------------------------------


def _nan_job():
    """Grads NaN via the chaos site; skip flag computed like the real
    guard (host-side here for test cheapness)."""

    def step_fn(state, batch):
        grads = {"w": jnp.ones(())}
        grads = chaos.corrupt_tree(grads, int(batch))
        skipped = bool(jnp.isnan(grads["w"]) | jnp.isinf(grads["w"]))
        if not skipped:
            state = {"w": state["w"] + grads["w"]}
        return state, {"skipped": skipped}

    return {"w": jnp.zeros(())}, step_fn, (lambda step: step)


@pytest.mark.chaos
def test_skip_budget_exhaustion_always_dumps(tmp_path):
    """ISSUE 5 acceptance: the max_rollbacks RuntimeError leaves a
    parseable dump whose frames show the fatal skip streak and whose
    event log prices every rollback."""
    init, step_fn, batch_fn = _nan_job()
    acct = GoodputAccountant()
    rec = FlightRecorder(
        capacity=32, directory=str(tmp_path / "fl"), goodput=acct
    )
    with chaos.inject(
        chaos.Fault(chaos.GRADS, steps=(3, 4, 5), mode="nan")  # persistent
    ):
        with pytest.raises(RuntimeError, match="skip budget exhausted"):
            run_resilient(
                step_fn, init, batch_fn,
                directory=tmp_path / "ckpt", num_steps=10,
                save_interval_steps=2, rollback_after=3, max_rollbacks=2,
                observer=acct, flight=rec,
            )
    assert len(rec.dumps) == 1
    data = _load(rec.dumps[0])
    assert "skip budget exhausted" in data["reason"]
    # the last frames ARE the fatal streak
    tail = data["frames"][-3:]
    assert [f["skipped"] for f in tail] == [True, True, True]
    assert [f["step"] for f in tail] == [3, 4, 5]
    rollbacks = [e for e in data["events"] if e["kind"] == "rollback"]
    assert len(rollbacks) == 2
    assert all(r["skips"] == 3 for r in rollbacks)
    # dump ledger == observer ledger == what the JSONL line would carry
    assert data["goodput"]["skipped"] == acct.skipped == 9
    assert data["goodput"]["rollbacks"] == acct.rollbacks == 2
    # replay passes after each rollback are marked
    assert any(f.get("replay") for f in data["frames"])


@pytest.mark.chaos
def test_preemption_dumps_after_final_checkpoint(tmp_path):
    """SIGTERM: the loop exits cleanly (final checkpoint written) AND
    leaves a black box with the preempt event."""
    init, step_fn, batch_fn = _nan_job()
    rec = FlightRecorder(capacity=16, directory=str(tmp_path / "fl"))
    with chaos.inject(chaos.Fault(chaos.PREEMPTION, steps=(4,))):
        res = run_resilient(
            step_fn, init, batch_fn,
            directory=tmp_path / "ckpt", num_steps=10,
            save_interval_steps=2, flight=rec,
        )
    assert res.preempted and res.last_step == 4
    assert len(rec.dumps) == 1
    data = _load(rec.dumps[0])
    assert "preemption" in data["reason"]
    # the event log now also narrates checkpoint I/O (enqueues + the
    # async engine's completed writes — docs/goodput.md); the preempt
    # instant is exactly once, after which nothing but checkpoint
    # drain events may land
    kinds = [e["kind"] for e in data["events"]]
    assert kinds.count("preempt") == 1
    assert set(kinds) == {"checkpoint", "preempt"}
    writes = [e for e in data["events"]
              if e["kind"] == "checkpoint" and e.get("phase") == "write"]
    assert {e["step"] for e in writes} == {0, 2, 4}  # interval + forced
    assert data["frames"][-1]["step"] == 4


def test_env_arms_flight_inside_run_resilient(tmp_path, monkeypatch):
    """APEX_TPU_FLIGHT=N:DIR arms a recorder with no code changes; an
    unhandled step exception dumps and re-raises unchanged."""
    monkeypatch.setenv(ENV_FLIGHT, f"8:{tmp_path / 'envfl'}")

    def step_fn(state, batch):
        if int(batch) == 3:
            raise ValueError("boom at step 3")
        return {"w": state["w"] + 1.0}, None

    with pytest.raises(ValueError, match="boom at step 3"):
        run_resilient(
            step_fn, {"w": jnp.zeros(())}, lambda s: s,
            directory=tmp_path / "ckpt", num_steps=10,
        )
    dumps = sorted((tmp_path / "envfl").glob("flight_*.json"))
    assert len(dumps) == 1
    data = _load(dumps[0])
    assert data["reason"] == "ValueError: boom at step 3"
    assert [f["step"] for f in data["frames"]] == [0, 1, 2]


def test_observer_fanout_forwards_to_implementers_only():
    seen = []

    class StepsOnly:
        def on_step(self, step, skipped, info):
            seen.append(("step", step))

    class RollbacksOnly:
        def on_rollback(self, step, anchor, skips, discarded):
            seen.append(("rollback", step))

    fan = ObserverFanout([StepsOnly(), None, RollbacksOnly()])
    fan.on_step(1, False, None)
    fan.on_rollback(5, 2, 3, 0)
    fan.on_preempt(6)  # nobody implements it: silently fine
    assert seen == [("step", 1), ("rollback", 5)]


# ---------------------------------------------------------------------------
# postmortem tooling
# ---------------------------------------------------------------------------


def _make_dump(tmp_path, steps=(10, 11, 12)):
    rec = FlightRecorder(capacity=16, directory=str(tmp_path))
    for s in steps:
        rec.on_step(s, skipped=(s == steps[-1]))
    rec.on_rollback(steps[-1], steps[0], 1, 0)
    return rec.dump("RuntimeError: unit postmortem")


def test_flight_view_renders_and_summarizes(tmp_path, capsys):
    sys.path.insert(0, TOOLS)
    try:
        import flight_view
    finally:
        sys.path.remove(TOOLS)
    path = _make_dump(tmp_path)

    assert flight_view.main([path]) == 0
    out = capsys.readouterr().out
    assert "unit postmortem" in out and "ROLLBACK" in out

    assert flight_view.main([path, "--json"]) == 0
    summary = json.loads(capsys.readouterr().out)
    assert summary["frames"] == 3 and summary["rollbacks"] == 1
    assert summary["frame_skips"] == 1

    # unparseable input is a hard error, not a pretty empty report
    bad = tmp_path / "not_a_dump.json"
    bad.write_text("{}")
    assert flight_view.main([str(bad)]) == 2


def test_trace_summary_cross_references_flight_windows(tmp_path, capsys):
    sys.path.insert(0, TOOLS)
    try:
        import trace_summary
    finally:
        sys.path.remove(TOOLS)
    from apex_tpu.observability.trace import window_dir

    # windows: one overlapping the incident span (10..12), one outside
    os.makedirs(window_dir(str(tmp_path), 11, 13))
    os.makedirs(window_dir(str(tmp_path), 40, 42))
    dump = _make_dump(tmp_path / "fl")

    assert trace_summary.flight_step_range(dump) == (10, 12)
    hit = trace_summary.cross_reference_flight(str(tmp_path), dump)
    out = capsys.readouterr().out
    assert hit == window_dir(str(tmp_path), 11, 13)
    assert "11..13: OVERLAPS" in out and "40..42: outside" in out

    # no overlap at all -> None (and says so)
    dump_far = _make_dump(tmp_path / "fl2", steps=(90, 91))
    assert trace_summary.cross_reference_flight(str(tmp_path), dump_far) is None
    assert "no trace window overlaps" in capsys.readouterr().out
