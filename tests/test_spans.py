"""Span recorder + unified timeline: lifecycle invariants, TTFT
attribution, the Chrome-trace sink, and the span-accounting tooling.

ISSUE 8 acceptance surface: every admitted request ends in exactly one
terminal span, shed reasons match the scheduler's ledger counters, a
planted out-of-order event is rejected loudly, per-request TTFT
components sum to the measured TTFT by construction, and
``tools/timeline.py`` turns a scheduler run's span dump into a
Perfetto-loadable trace plus a passing accounting summary.
"""

import importlib.util
import json
import os
import sys

import pytest

import jax
import jax.numpy as jnp
import numpy as np

from apex_tpu.observability import (
    MetricRegistry,
    QueueWaitFractionRule,
    SpanRecorder,
    TimelineSink,
    Watchdog,
    bench_record,
    monotonic_to_epoch,
    serve_rules,
    wall_clock_anchor,
)
from apex_tpu.observability.health import HealthEvent
from apex_tpu.observability.spans import (
    REQ_DECODE,
    REQ_DONE,
    REQ_PREFILL,
    REQ_QUEUED,
    REQ_SHED,
    TRACK_ENGINE,
    TRACK_REQUESTS,
)
from apex_tpu.observability.trace import TraceScheduler

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
TOOLS = os.path.join(REPO, "tools")


def _tool(name):
    spec = importlib.util.spec_from_file_location(
        name, os.path.join(TOOLS, f"{name}.py")
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def _names(rec):
    counts = {}
    for e in rec.snapshot():
        counts[e["name"]] = counts.get(e["name"], 0) + 1
    return counts


# ---------------------------------------------------------------------------
# anchor
# ---------------------------------------------------------------------------


class TestAnchor:
    def test_anchor_is_captured_once(self):
        a = wall_clock_anchor()
        b = wall_clock_anchor()
        assert a == b
        assert set(a) >= {"monotonic", "epoch", "pid"}
        assert a["pid"] == os.getpid()

    def test_monotonic_to_epoch_offset(self):
        a = wall_clock_anchor()
        # the anchor's own monotonic timestamp maps to its epoch one
        assert monotonic_to_epoch(a["monotonic"]) == pytest.approx(
            a["epoch"]
        )
        assert monotonic_to_epoch(a["monotonic"] + 2.5) == pytest.approx(
            a["epoch"] + 2.5
        )


# ---------------------------------------------------------------------------
# recorder core
# ---------------------------------------------------------------------------


class TestRecorderCore:
    def test_span_and_instant_record(self):
        rec = SpanRecorder(capacity=16)
        rec.span("a", 1.0, 2.0, track="t", lane=7, foo=1)
        rec.instant("b", 3.0, track="t")
        spans = rec.snapshot()
        assert spans[0]["name"] == "a" and spans[0]["lane"] == 7
        assert spans[0]["args"] == {"foo": 1}
        assert spans[1]["name"] == "b" and spans[1]["t"] == 3.0
        assert [e["seq"] for e in spans] == [0, 1]

    def test_backwards_span_rejected(self):
        rec = SpanRecorder(capacity=16)
        with pytest.raises(ValueError, match="ends before it starts"):
            rec.span("a", 2.0, 1.0)

    def test_ring_drops_oldest_and_counts(self):
        rec = SpanRecorder(capacity=4)
        for i in range(10):
            rec.instant(f"e{i}", float(i))
        assert rec.dropped == 6
        assert [e["name"] for e in rec.snapshot()] == [
            "e6", "e7", "e8", "e9",
        ]

    def test_dump_payload(self, tmp_path):
        rec = SpanRecorder(capacity=8, run={"job": "t"})
        rec.span("a", 1.0, 2.0)
        rec.instant("nan", 1.5, value=float("nan"))
        path = rec.dump(reason="unit", path=str(tmp_path / "s.json"))
        data = json.load(open(path))
        assert data["kind"] == "apex_tpu_spans"
        assert data["version"] == 1
        assert set(data["anchor"]) >= {"monotonic", "epoch"}
        assert data["reason"] == "unit"
        assert data["run"] == {"job": "t"}
        assert data["dropped"] == 0
        assert len(data["spans"]) == 2
        # non-finite forensics survive as strings, strict JSON
        assert data["spans"][1]["args"]["value"] == "NaN"

    def test_from_env(self, monkeypatch, tmp_path):
        from apex_tpu.observability.spans import ENV_SPANS

        monkeypatch.delenv(ENV_SPANS, raising=False)
        assert SpanRecorder.from_env() is None
        monkeypatch.setenv(ENV_SPANS, "0")
        assert SpanRecorder.from_env() is None
        monkeypatch.setenv(ENV_SPANS, f"32:{tmp_path}")
        rec = SpanRecorder.from_env()
        assert rec.capacity == 32 and rec.directory == str(tmp_path)


# ---------------------------------------------------------------------------
# request lifecycle state machine
# ---------------------------------------------------------------------------


class TestRequestLifecycle:
    def test_full_chain_spans(self):
        rec = SpanRecorder(capacity=64)
        rec.request_event(5, REQ_QUEUED, 1.0, prompt_tokens=4)
        rec.request_event(5, REQ_PREFILL, 2.0, bucket=8)
        rec.request_event(5, REQ_DECODE, 3.0, ttft_ms=2000.0)
        rec.request_event(5, REQ_DONE, 4.0, tokens=3)
        names = _names(rec)
        assert names == {
            "req/queued": 1, "req/admitted": 1, "req/prefill": 1,
            "req/decode": 1, "req/done": 1,
        }
        assert rec.open_requests == {}
        spans = {e["name"]: e for e in rec.snapshot()}
        # phase spans cover [open, close] and merge open+close args
        q = spans["req/queued"]
        assert (q["t0"], q["t1"]) == (1.0, 2.0)
        assert q["args"] == {"prompt_tokens": 4, "bucket": 8}
        p = spans["req/prefill"]
        assert (p["t0"], p["t1"]) == (2.0, 3.0)
        assert p["args"]["ttft_ms"] == 2000.0
        assert spans["req/done"]["lane"] == 5

    def test_shed_from_queue(self):
        rec = SpanRecorder(capacity=64)
        rec.request_event(1, REQ_QUEUED, 1.0)
        rec.request_event(1, REQ_SHED, 2.0, reason="deadline")
        names = _names(rec)
        assert names == {"req/queued": 1, "req/shed": 1}
        shed = [e for e in rec.snapshot() if e["name"] == "req/shed"][0]
        assert shed["args"]["reason"] == "deadline"
        assert rec.open_requests == {}

    def test_out_of_order_transition_rejected(self):
        rec = SpanRecorder(capacity=64)
        with pytest.raises(ValueError, match="out-of-order request"):
            rec.request_event(1, REQ_DECODE, 1.0)  # decode before queued
        rec.request_event(1, REQ_QUEUED, 1.0)
        with pytest.raises(ValueError, match="out-of-order request"):
            rec.request_event(1, REQ_DECODE, 2.0)  # skip prefill
        rec.request_event(1, REQ_PREFILL, 2.0)
        rec.request_event(1, REQ_DONE, 3.0)
        with pytest.raises(ValueError, match="out-of-order request"):
            rec.request_event(1, REQ_DONE, 4.0)  # second terminal

    def test_backwards_timestamp_rejected(self):
        rec = SpanRecorder(capacity=64)
        rec.request_event(1, REQ_QUEUED, 5.0)
        with pytest.raises(ValueError, match="out-of-order request timestamp"):
            rec.request_event(1, REQ_PREFILL, 4.0)


class TestRecoveryLifecycle:
    """The fault-recovery vocabulary (docs/serving.md "Failure
    semantics"): ``retrying`` transitions, the ``shed(poisoned)``
    terminal, and the illegal recovery paths the validated state
    machine must reject."""

    def test_decode_retry_roundtrip_chain(self):
        from apex_tpu.observability.spans import REQ_RETRYING

        rec = SpanRecorder(capacity=64)
        rec.request_event(9, REQ_QUEUED, 1.0)
        rec.request_event(9, REQ_PREFILL, 2.0)
        rec.request_event(9, REQ_DECODE, 3.0)
        rec.request_event(9, REQ_RETRYING, 4.0, cause="engine:Boom",
                          attempt=1)
        rec.request_event(9, REQ_DECODE, 5.0, resumed=True)
        rec.request_event(9, REQ_DONE, 6.0, tokens=4)
        names = _names(rec)
        assert names["req/retrying"] == 1
        assert names["req/decode"] == 2
        assert names["req/done"] == 1
        retry = [e for e in rec.snapshot()
                 if e["name"] == "req/retrying"][0]
        # the recovery interval carries its cause AND the resume marker
        assert (retry["t0"], retry["t1"]) == (4.0, 5.0)
        assert retry["args"]["cause"] == "engine:Boom"
        assert retry["args"]["resumed"] is True
        assert rec.open_requests == {}

    def test_prefill_retry_reenters_through_prefill(self):
        from apex_tpu.observability.spans import REQ_RETRYING

        rec = SpanRecorder(capacity=64)
        rec.request_event(3, REQ_QUEUED, 1.0)
        rec.request_event(3, REQ_PREFILL, 2.0)
        rec.request_event(3, REQ_RETRYING, 3.0, cause="prefill:Boom")
        rec.request_event(3, REQ_PREFILL, 4.0, attempt=1)
        rec.request_event(3, REQ_DECODE, 5.0, ttft_ms=4000.0)
        rec.request_event(3, REQ_DONE, 6.0)
        assert _names(rec)["req/prefill"] == 2
        assert rec.open_requests == {}

    def test_shed_poisoned_from_decode(self):
        rec = SpanRecorder(capacity=64)
        rec.request_event(4, REQ_QUEUED, 1.0)
        rec.request_event(4, REQ_PREFILL, 2.0)
        rec.request_event(4, REQ_DECODE, 3.0)
        rec.request_event(4, REQ_SHED, 4.0, reason="poisoned")
        shed = [e for e in rec.snapshot() if e["name"] == "req/shed"][0]
        assert shed["args"]["reason"] == "poisoned"
        assert rec.open_requests == {}

    def test_shed_from_retrying_allowed(self):
        from apex_tpu.observability.spans import REQ_RETRYING

        rec = SpanRecorder(capacity=64)
        rec.request_event(5, REQ_QUEUED, 1.0)
        rec.request_event(5, REQ_PREFILL, 2.0)
        rec.request_event(5, REQ_RETRYING, 3.0)
        rec.request_event(5, REQ_SHED, 4.0, reason="retries_exhausted")
        assert rec.open_requests == {}

    def test_retrying_cannot_complete_directly(self):
        """retrying -> done is illegal: completion must go back
        through a decode (or prefill) that actually produced tokens."""
        from apex_tpu.observability.spans import REQ_RETRYING

        rec = SpanRecorder(capacity=64)
        rec.request_event(6, REQ_QUEUED, 1.0)
        rec.request_event(6, REQ_PREFILL, 2.0)
        rec.request_event(6, REQ_RETRYING, 3.0)
        with pytest.raises(ValueError, match="out-of-order request"):
            rec.request_event(6, REQ_DONE, 4.0)

    def test_shed_cannot_be_readmitted(self):
        """shed -> decode without re-admission is illegal: a terminal
        shed is final — recovery means a NEW request id."""
        from apex_tpu.observability.spans import REQ_RETRYING

        rec = SpanRecorder(capacity=64)
        rec.request_event(7, REQ_QUEUED, 1.0)
        rec.request_event(7, REQ_PREFILL, 2.0)
        rec.request_event(7, REQ_SHED, 3.0, reason="poisoned")
        for state in (REQ_DECODE, REQ_RETRYING, REQ_PREFILL):
            with pytest.raises(ValueError, match="out-of-order request"):
                rec.request_event(7, state, 4.0)

    def test_queued_cannot_jump_to_retrying(self):
        """retrying is a FAULT phase: a request that never reached
        prefill has nothing to retry."""
        from apex_tpu.observability.spans import REQ_RETRYING

        rec = SpanRecorder(capacity=64)
        rec.request_event(8, REQ_QUEUED, 1.0)
        with pytest.raises(ValueError, match="out-of-order request"):
            rec.request_event(8, REQ_RETRYING, 2.0)

    def test_routed_hop_chain(self):
        """The fleet re-route chain: queued -> routed (drain handoff)
        -> queued on the destination, then a normal lifecycle.  The
        routed span carries the destination replica and is closed by
        the target's own queued event."""
        from apex_tpu.observability.spans import REQ_ROUTED

        rec = SpanRecorder(capacity=64)
        rec.request_event(9, REQ_ROUTED, 1.0, replica="r0")  # fresh dispatch
        rec.request_event(9, REQ_QUEUED, 1.0)
        rec.request_event(9, REQ_ROUTED, 2.0, replica="r1")  # drain handoff
        rec.request_event(9, REQ_QUEUED, 2.5)
        rec.request_event(9, REQ_PREFILL, 3.0)
        rec.request_event(9, REQ_DECODE, 4.0)
        rec.request_event(9, REQ_DONE, 5.0)
        assert rec.open_requests == {}
        routed = [e for e in rec.snapshot() if e["name"] == "req/routed"]
        assert [s["args"]["replica"] for s in routed] == ["r0", "r1"]

    def test_routed_from_retrying_after_crash_evacuation(self):
        """A crash evacuation moves RUNNING work through retrying
        (charging the shared budget) before the hop — retrying ->
        routed is the legal crash-migration edge."""
        from apex_tpu.observability.spans import REQ_RETRYING, REQ_ROUTED

        rec = SpanRecorder(capacity=64)
        rec.request_event(10, REQ_QUEUED, 1.0)
        rec.request_event(10, REQ_PREFILL, 2.0)
        rec.request_event(10, REQ_DECODE, 3.0)
        rec.request_event(10, REQ_RETRYING, 4.0, cause="replica_crash")
        rec.request_event(10, REQ_ROUTED, 4.5, replica="r2")
        rec.request_event(10, REQ_QUEUED, 5.0)
        assert rec.open_requests == {10: "queued"}

    def test_inflight_phases_cannot_route_directly(self):
        """prefill/decode -> routed is illegal: a migration of
        in-flight work IS a fault recovery and must pass through
        retrying, where the shared retry budget is charged — a free
        hop would let a flapping replica bounce a request forever."""
        from apex_tpu.observability.spans import REQ_ROUTED

        for last in (REQ_PREFILL, REQ_DECODE):
            rec = SpanRecorder(capacity=64)
            rec.request_event(11, REQ_QUEUED, 1.0)
            rec.request_event(11, REQ_PREFILL, 2.0)
            if last == REQ_DECODE:
                rec.request_event(11, REQ_DECODE, 3.0)
            with pytest.raises(ValueError, match="out-of-order request"):
                rec.request_event(11, REQ_ROUTED, 4.0, replica="r1")

    def test_scheduler_records_retry_chain_end_to_end(self):
        """The scheduler's real fault path produces the validated
        chain: decode fault -> retrying span (with cause) ->
        re-admitted decode -> done, and the clamp rung lands as a
        req/clamped instant."""
        import numpy as np

        from apex_tpu.models.gpt import GptConfig, GptModel
        from apex_tpu.resilience import chaos
        from apex_tpu.serve import (
            ContinuousBatchingScheduler,
            InferenceEngine,
            Request,
            ServeConfig,
        )

        cfg = GptConfig(
            vocab_size=64, hidden_size=32, num_layers=2, num_heads=2,
            intermediate_size=64, max_seq_len=128, dtype=jnp.float32,
        )
        model = GptModel(cfg)
        params = model.init(
            jax.random.PRNGKey(0), jnp.zeros((8, 1), jnp.int32)
        )
        eng = InferenceEngine(
            cfg, params,
            ServeConfig(page_size=8, num_pages=32, max_batch=2,
                        max_pages_per_seq=8, verify=False),
        )
        rec = SpanRecorder(capacity=4096)
        sched = ContinuousBatchingScheduler(
            eng, spans=rec,
            clamp_max_new_tokens=3, clamp_occupancy=0.01,
        )
        rs = np.random.RandomState(40)
        with chaos.inject(chaos.Fault(
            chaos.SERVE_DECODE, steps=(1,), mode="raise", max_hits=1,
        )):
            a = sched.submit(Request(
                prompt=[int(t) for t in rs.randint(0, 64, size=6)],
                max_new_tokens=6,
            ))
            b = sched.submit(Request(
                prompt=[int(t) for t in rs.randint(0, 64, size=6)],
                max_new_tokens=6,
            ))
            sched.run()
        assert a.status == "done" and b.status == "done"
        names = _names(rec)
        assert names.get("req/retrying", 0) >= 1
        assert names.get("req/clamped", 0) >= 1  # occupancy rung fired
        assert rec.open_requests == {}
        retry = [e for e in rec.snapshot()
                 if e["name"] == "req/retrying"][0]
        assert retry["args"]["cause"].startswith("engine:")
        assert retry["args"]["attempt"] == 1


# ---------------------------------------------------------------------------
# run_resilient observer bridge + trace window markers
# ---------------------------------------------------------------------------


class TestObserverBridge:
    def test_step_spans_and_replay_mark(self):
        t = [0.0]

        def clock():
            t[0] += 1.0
            return t[0]

        rec = SpanRecorder(capacity=64, clock=clock)
        rec.on_step(0)          # baseline tick only — no span yet
        rec.on_step(1)
        rec.on_rollback(2, 0, skips=2, discarded=1)
        rec.on_step(1)          # replay: rewound counter
        rec.on_checkpoint(1)
        rec.on_resume(5)
        rec.on_retry("save", 2, RuntimeError("boom"))
        rec.on_preempt(6)
        names = _names(rec)
        assert names["train/step"] == 2
        for k in ("train/rollback", "train/checkpoint", "train/resume",
                  "train/retry", "train/preempt"):
            assert names[k] == 1
        steps = [e for e in rec.snapshot() if e["name"] == "train/step"]
        assert "replay" not in (steps[0]["args"])
        assert steps[1]["args"]["replay"] is True
        retry = [e for e in rec.snapshot()
                 if e["name"] == "train/retry"][0]
        assert "RuntimeError: boom" in retry["args"]["error"]

    def test_health_event_instant(self):
        rec = SpanRecorder(capacity=16)
        rec.note_health(HealthEvent(
            "ttft", "critical", 7, 2500.0, 1000.0, "TTFT blown", None,
        ))
        ev = rec.snapshot()[0]
        assert ev["name"] == "health/ttft"
        assert ev["args"]["severity"] == "critical"
        assert ev["args"]["threshold"] == 1000.0

    def test_trace_scheduler_abort_records_partial_window(self, tmp_path):
        """A watchdog re-arm mid-capture closes the window early; its
        partial artifacts still get a span, marked aborted."""
        rec = SpanRecorder(capacity=16)
        sched = TraceScheduler(
            spec=f"1+4:{tmp_path}", spans=rec,
            _start_fn=lambda d: None, _stop_fn=lambda: None,
        )
        sched.on_step(1)          # capture starts
        assert sched.tracing
        sched.arm(5, 1)           # escalation re-arms mid-capture
        windows = [e for e in rec.snapshot()
                   if e["name"] == "trace/window"]
        assert len(windows) == 1
        assert windows[0]["args"]["aborted"] == "rearm"
        # the re-armed window captures and records cleanly
        for step in range(2, 8):
            sched.on_step(step)
        windows = [e for e in rec.snapshot()
                   if e["name"] == "trace/window"]
        assert len(windows) == 2
        assert "aborted" not in windows[1]["args"]
        assert windows[1]["args"]["start_step"] == 5

    def test_trace_scheduler_window_marker(self, tmp_path):
        calls = []
        rec = SpanRecorder(capacity=16)
        sched = TraceScheduler(
            spec=f"2+2:{tmp_path}", spans=rec,
            _start_fn=lambda d: calls.append(("start", d)),
            _stop_fn=lambda: calls.append(("stop",)),
        )
        for step in range(6):
            sched.on_step(step)
        assert [c[0] for c in calls] == ["start", "stop"]
        windows = [e for e in rec.snapshot()
                   if e["name"] == "trace/window"]
        assert len(windows) == 1
        w = windows[0]
        assert w["args"]["start_step"] == 2
        assert w["args"]["end_step"] == 3
        assert w["args"]["log_dir"] == sched.log_dir
        assert w["t1"] >= w["t0"]


# ---------------------------------------------------------------------------
# scheduler-driven lifecycle (the ISSUE 8 invariants)
# ---------------------------------------------------------------------------


def tiny_engine(**serve_kw):
    from apex_tpu.models.gpt import GptConfig, GptModel
    from apex_tpu.serve import InferenceEngine, ServeConfig

    cfg = GptConfig(
        vocab_size=64, hidden_size=32, num_layers=2, num_heads=2,
        intermediate_size=64, max_seq_len=128, dtype=jnp.float32,
    )
    model = GptModel(cfg)
    params = model.init(
        jax.random.PRNGKey(0), jnp.zeros((8, 1), jnp.int32)
    )
    kw = dict(page_size=8, num_pages=32, max_batch=2,
              max_pages_per_seq=8, verify=False)
    kw.update(serve_kw)
    return InferenceEngine(cfg, params, ServeConfig(**kw))


@pytest.fixture(scope="module")
def engine():
    return tiny_engine()


def _run_load(engine, n=4, spans=None, registry=None, max_new=3):
    from apex_tpu.serve import ContinuousBatchingScheduler, Request

    sched = ContinuousBatchingScheduler(
        engine, registry=registry, spans=spans,
    )
    rs = np.random.RandomState(0)
    for _ in range(n):
        sched.submit(Request(
            prompt=[int(t) for t in rs.randint(0, 64, size=6)],
            max_new_tokens=max_new,
        ))
    sched.run()
    return sched


class TestSchedulerSpans:
    def test_every_admitted_request_has_one_terminal(self, engine):
        rec = SpanRecorder(capacity=1024)
        sched = _run_load(engine, n=4, spans=rec)
        engine.spans = None
        assert rec.open_requests == {}
        terms = {}
        for e in rec.snapshot():
            if e["name"] in ("req/done", "req/shed"):
                terms[e["lane"]] = terms.get(e["lane"], 0) + 1
        assert sorted(terms) == sorted(r.rid for r in sched.completed)
        assert all(v == 1 for v in terms.values())

    def test_ttft_components_sum_and_span_args(self, engine):
        rec = SpanRecorder(capacity=1024)
        sched = _run_load(engine, n=4, spans=rec)
        engine.spans = None
        assert len(sched.completed) == 4
        for r in sched.completed:
            c = r.ttft_components()
            total = (
                c["queue_wait_ms"] + c["prefill_ms"] + c["contention_ms"]
            )
            # by construction: contention is the remainder
            assert total == pytest.approx(c["ttft_ms"], abs=1e-6)
        # the req/prefill span carries the full attribution
        prefills = [e for e in rec.snapshot()
                    if e["name"] == "req/prefill"]
        assert len(prefills) == 4
        for p in prefills:
            args = p["args"]
            assert {"ttft_ms", "queue_wait_ms", "prefill_ms",
                    "contention_ms"} <= set(args)

    def test_decode_iter_correlation(self, engine):
        rec = SpanRecorder(capacity=1024)
        sched = _run_load(engine, n=2, spans=rec, max_new=4)
        engine.spans = None
        iters = {
            e["args"]["iter"] for e in rec.snapshot()
            if e["name"] == "engine/decode"
        }
        assert iters, "engine decode spans missing"
        for r in sched.completed:
            assert r.first_decode_iter in iters
            assert r.last_decode_iter in iters
            assert r.first_decode_iter <= r.last_decode_iter
        # the terminal args carry the correlation window
        dones = [e for e in rec.snapshot() if e["name"] == "req/done"]
        by_rid = {e["lane"]: e["args"] for e in dones}
        for r in sched.completed:
            assert by_rid[r.rid]["first_iter"] == r.first_decode_iter
            assert by_rid[r.rid]["last_iter"] == r.last_decode_iter
            assert by_rid[r.rid]["tokens"] == len(r.tokens)

    def test_shed_reasons_match_ledger_counters(self):
        """Deadline + growth-victim sheds: span reasons == Request
        ledger == the split serve/shed_* registry counters."""
        from apex_tpu.serve import ContinuousBatchingScheduler, Request

        class FakeClock:
            def __init__(self):
                self.t = 0.0

            def __call__(self):
                self.t += 1e-4
                return self.t

            def advance(self, dt):
                self.t += dt

        eng = tiny_engine(num_pages=3, max_pages_per_seq=2)
        rec = SpanRecorder(capacity=1024)
        reg = MetricRegistry(fetch_every=1)
        clock = FakeClock()
        sched = ContinuousBatchingScheduler(
            eng, registry=reg, clock=clock, spans=rec,
        )
        rs = np.random.RandomState(9)
        hog = sched.submit(Request(
            prompt=[int(t) for t in rs.randint(0, 64, size=14)],
            max_new_tokens=4,
        ))
        starved = sched.submit(Request(
            prompt=[int(t) for t in rs.randint(0, 64, size=14)],
            max_new_tokens=2, slo_ttft_ms=500.0,
        ))
        sched.step()
        clock.advance(1.0)
        sched.run()
        eng.spans = None
        assert starved.status == "shed"
        assert starved.shed_reason == "deadline"
        assert hog.status == "done"
        sheds = [e for e in rec.snapshot() if e["name"] == "req/shed"]
        assert len(sheds) == 1
        assert sheds[0]["lane"] == starved.rid
        assert sheds[0]["args"]["reason"] == "deadline"
        reg.fetch()
        vals = reg.values()
        assert vals["serve/shed"] == 1.0
        assert vals["serve/shed_deadline"] == 1.0
        assert vals["serve/shed_growth_victim"] == 0.0
        assert vals["serve/shed_pool_exhausted"] == 0.0
        assert vals["serve/shed_oversize"] == 0.0

    def test_growth_victim_reason(self):
        from apex_tpu.serve import ContinuousBatchingScheduler, Request

        eng = tiny_engine(num_pages=4, max_pages_per_seq=3)
        rec = SpanRecorder(capacity=1024)
        reg = MetricRegistry(fetch_every=1)
        sched = ContinuousBatchingScheduler(eng, registry=reg, spans=rec)
        rs = np.random.RandomState(10)
        old = sched.submit(Request(
            prompt=[int(t) for t in rs.randint(0, 64, size=8)],
            max_new_tokens=10,
        ))
        young = sched.submit(Request(
            prompt=[int(t) for t in rs.randint(0, 64, size=8)],
            max_new_tokens=10,
        ))
        hog = sched.submit(Request(
            prompt=[int(t) for t in rs.randint(0, 64, size=8)],
            max_new_tokens=1,
        ))
        sched.run()
        eng.spans = None
        assert old.status == "done" and hog.status == "done"
        assert young.status == "shed"
        assert young.shed_reason == "growth_victim"
        reg.fetch()
        vals = reg.values()
        assert vals["serve/shed"] == 1.0
        assert vals["serve/shed_growth_victim"] == 1.0
        # ledger counters == span record == per-reason sum
        reasons = [e["args"]["reason"] for e in rec.snapshot()
                   if e["name"] == "req/shed"]
        assert reasons == ["growth_victim"]
        assert vals["serve/shed"] == sum(
            vals[f"serve/shed_{r}"] for r in
            ("deadline", "growth_victim", "pool_exhausted", "oversize")
        )

    def test_second_scheduler_takes_over_engine_recorder(self, engine):
        """A later scheduler's recorder replaces the retired one on the
        shared engine — its dump carries the engine spans its
        correlation ids reference."""
        rec_a = SpanRecorder(capacity=1024)
        _run_load(engine, n=1, spans=rec_a, max_new=2)
        rec_b = SpanRecorder(capacity=1024)
        sched_b = _run_load(engine, n=1, spans=rec_b, max_new=2)
        engine.spans = None
        b_iters = {e["args"]["iter"] for e in rec_b.snapshot()
                   if e["name"] == "engine/decode"}
        assert b_iters, "second recorder got no engine spans"
        for r in sched_b.completed:
            assert r.first_decode_iter in b_iters
        # and nothing from B's run leaked into A's retired record
        a_iters = {e["args"]["iter"] for e in rec_a.snapshot()
                   if e["name"] == "engine/decode"}
        assert not (a_iters & b_iters)

    def test_prefill_calls_counted_without_recorder(self):
        eng = tiny_engine()
        pages = eng.pool.alloc(1)
        eng.prefill([1, 2, 3], pages)  # no recorder attached
        assert eng.prefill_calls == 1
        eng.pool.free(pages)

    def test_custom_clock_shared_with_recorder(self):
        """A non-default scheduler clock becomes the recorder's clock:
        one time basis for request AND engine spans."""
        from apex_tpu.serve import ContinuousBatchingScheduler

        eng = tiny_engine()
        rec = SpanRecorder(capacity=64)
        clock_vals = iter(float(i) for i in range(1000))
        clock = lambda: next(clock_vals)  # noqa: E731
        ContinuousBatchingScheduler(eng, clock=clock, spans=rec)
        assert rec.clock is clock
        eng.spans = None

    def test_attribution_percentiles_on_registry(self, engine):
        reg = MetricRegistry(fetch_every=1)
        _run_load(engine, n=4, spans=None, registry=reg)
        reg.fetch()
        vals = reg.values()
        for comp in ("queue_wait", "prefill", "contention"):
            for tag in ("p50", "p95", "p99"):
                assert f"serve/ttft_{comp}_ms_{tag}" in vals
        # prefill really runs, so its p50 must be positive
        assert vals["serve/ttft_prefill_ms_p50"] > 0.0
        assert 0.0 <= vals["serve/ttft_queue_wait_fraction"] <= 1.0


# ---------------------------------------------------------------------------
# watchdog: queue-wait fraction rule
# ---------------------------------------------------------------------------


class TestQueueWaitFractionRule:
    def _registry(self, **values):
        from apex_tpu.serve import declare_serve_metrics

        reg = MetricRegistry(fetch_every=1)
        declare_serve_metrics(reg)
        state = reg.update(reg.init(), values)
        reg.observe(0, state)
        reg.observe(1, state)
        reg.fetch()
        return reg

    def test_fires_when_admission_starved(self):
        reg = self._registry(**{"serve/ttft_queue_wait_fraction": 0.8})
        wd = Watchdog(
            serve_rules(queue_wait_fraction={"max_fraction": 0.5}),
            registry=reg, check_every=1,
        )
        wd.on_step(1)
        events = [e for e in wd.events
                  if e.rule == "queue_wait_fraction"]
        assert len(events) == 1
        assert "admission starved" in events[0].message

    def test_watchdog_forwards_events_to_span_recorder(self):
        """Watchdog(spans=rec): a firing lands on the health track, so
        the merged timeline shows the alert next to its cause."""
        rec = SpanRecorder(capacity=16)
        reg = self._registry(**{"serve/ttft_queue_wait_fraction": 0.9})
        wd = Watchdog(
            serve_rules(queue_wait_fraction={"max_fraction": 0.5}),
            registry=reg, spans=rec, check_every=1,
        )
        wd.on_step(1)
        health = [e for e in rec.snapshot()
                  if e["name"] == "health/queue_wait_fraction"]
        assert len(health) == 1
        assert health[0]["args"]["severity"] == "warn"

    def test_silent_under_budget_and_in_serve_rules(self):
        reg = self._registry(**{"serve/ttft_queue_wait_fraction": 0.2})
        wd = Watchdog(serve_rules(), registry=reg, check_every=1)
        wd.on_step(1)
        assert [e for e in wd.events
                if e.rule == "queue_wait_fraction"] == []
        assert any(
            isinstance(r, QueueWaitFractionRule)
            for r in serve_rules()
        )


# ---------------------------------------------------------------------------
# TimelineSink (Chrome trace events)
# ---------------------------------------------------------------------------


class TestTimelineSink:
    def test_spans_to_chrome_events(self, tmp_path):
        out = tmp_path / "trace.json"
        anchor = {"monotonic": 100.0, "epoch": 1000.0}
        with TimelineSink(str(out), process_name="test") as sink:
            n = sink.add_spans(
                [
                    {"name": "req/prefill", "track": TRACK_REQUESTS,
                     "lane": 3, "t0": 101.0, "t1": 101.5,
                     "args": {"bucket": 8}},
                    {"name": "req/done", "track": TRACK_REQUESTS,
                     "lane": 3, "t": 102.0},
                    {"name": "engine/decode", "track": TRACK_ENGINE,
                     "t0": 101.5, "t1": 101.6},
                ],
                anchor=anchor,
            )
            assert n == 3
        data = json.load(open(out))
        evs = data["traceEvents"]
        x = [e for e in evs if e["ph"] == "X"]
        i = [e for e in evs if e["ph"] == "i"]
        m = [e for e in evs if e["ph"] == "M"]
        assert len(x) == 2 and len(i) == 1 and m
        prefill = [e for e in x if e["name"] == "req/prefill"][0]
        # monotonic 101.0 -> epoch 1001.0 -> 1.001e9 us
        assert prefill["ts"] == pytest.approx(1001.0 * 1e6)
        assert prefill["dur"] == pytest.approx(0.5 * 1e6)
        assert prefill["args"] == {"bucket": 8}
        # one named thread row per (track, lane)
        names = {e["args"]["name"] for e in m
                 if e["name"] == "thread_name"}
        assert f"{TRACK_REQUESTS} [3]" in names
        assert TRACK_ENGINE in names

    def test_counter_from_bench_record(self, tmp_path):
        out = tmp_path / "trace.json"
        with TimelineSink(str(out)) as sink:
            sink.write(bench_record("serve/ttft_ms", 12.5, "ms"))
            sink.write(bench_record("ignored", "text"))
            sink.write(bench_record("skipped", float("nan")))
        evs = json.load(open(out))["traceEvents"]
        counters = [e for e in evs if e["ph"] == "C"]
        assert len(counters) == 1
        assert counters[0]["name"] == "serve/ttft_ms"
        assert counters[0]["args"]["value"] == 12.5


# ---------------------------------------------------------------------------
# tools/timeline.py accounting (the CI gate)
# ---------------------------------------------------------------------------


class TestTimelineTool:
    def test_clean_run_accounts_and_merges(self, engine, tmp_path):
        timeline = _tool("timeline")
        rec = SpanRecorder(capacity=4096)
        sched = _run_load(engine, n=3, spans=rec)
        engine.spans = None
        spans_path = str(tmp_path / "spans.json")
        rec.dump(reason="test", path=spans_path)
        out = str(tmp_path / "trace.json")
        rc = timeline.main([
            "--spans", spans_path, "--out", out, "--json",
        ])
        assert rc == 0
        trace = json.load(open(out))
        assert trace["traceEvents"], "empty merged trace"
        summary = timeline.account_requests(
            json.load(open(spans_path))["spans"], 0, 1.0
        )
        assert summary["ok"], summary["violations"]
        assert summary["requests"]["total"] == 3
        assert summary["requests"]["admitted"] == 3
        assert summary["requests"]["complete"] == 3
        assert summary["ttft_accounting"]["checked"] == 3
        assert summary["ttft_accounting"]["max_error_ms"] <= 1.0
        assert len(sched.completed) == 3

    def test_incomplete_chain_fails_accounting(self):
        timeline = _tool("timeline")
        # an admitted request with no terminal event
        spans = [
            {"name": "req/queued", "track": "serve/requests", "lane": 1,
             "t0": 0.0, "t1": 1.0},
            {"name": "req/prefill", "track": "serve/requests", "lane": 1,
             "t0": 1.0, "t1": 2.0},
        ]
        summary = timeline.account_requests(spans, 0, 1.0)
        assert not summary["ok"]
        assert any("terminal" in v for v in summary["violations"])

    def test_ttft_sum_mismatch_fails_accounting(self):
        timeline = _tool("timeline")
        spans = [
            {"name": "req/queued", "track": "serve/requests", "lane": 1,
             "t0": 0.0, "t1": 1.0},
            {"name": "req/prefill", "track": "serve/requests", "lane": 1,
             "t0": 1.0, "t1": 2.0,
             "args": {"ttft_ms": 10.0, "queue_wait_ms": 2.0,
                      "prefill_ms": 3.0, "contention_ms": 1.0}},
            {"name": "req/done", "track": "serve/requests", "lane": 1,
             "t": 2.0},
        ]
        summary = timeline.account_requests(spans, 0, 1.0)
        assert not summary["ok"]
        assert any("components sum off" in v
                   for v in summary["violations"])

    def test_dropped_entries_fail_accounting(self):
        timeline = _tool("timeline")
        chain = [
            {"name": "req/queued", "track": "serve/requests", "lane": 1,
             "t0": 0.0, "t1": 1.0},
            {"name": "req/shed", "track": "serve/requests", "lane": 1,
             "t": 1.0, "args": {"reason": "deadline"}},
        ]
        # a wrapped ring invalidates completeness claims about chains...
        summary = timeline.account_requests(chain, 5, 1.0)
        assert not summary["ok"]
        assert any("dropped" in v for v in summary["violations"])
        # ...but a wrapped train-only record claims nothing about
        # chains and stays clean (the long-run steady state)
        assert timeline.account_requests([], 5, 1.0)["ok"]
        # per-source scoping: a wrapped train-only dump (src 0) merged
        # with a complete serve dump (src 1) must not fail src 1's
        # accounting
        merged = [
            {"name": "train/step", "track": "train",
             "t0": 0.0, "t1": 1.0, "_src": 0},
        ] + [dict(e, _src=1) for e in chain]
        summary = timeline.account_requests(merged, {0: 7, 1: 0}, 1.0)
        assert summary["ok"], summary["violations"]
        assert summary["dropped"] == 7
        # the serve dump's OWN wrap still fails it
        summary = timeline.account_requests(merged, {0: 0, 1: 3}, 1.0)
        assert not summary["ok"]
        # a wrapped serve dump whose CHAINS were all evicted (only
        # engine spans survive) is exactly the truncation the gate
        # exists to catch — serve activity + drops = unaccountable
        engine_only = [
            {"name": "engine/decode", "track": "serve/engine",
             "t0": 0.0, "t1": 0.1, "args": {"iter": 1}},
        ]
        summary = timeline.account_requests(engine_only, {0: 500}, 1.0)
        assert not summary["ok"]
        assert any("dropped" in v for v in summary["violations"])

    def test_flight_dump_merges(self, tmp_path):
        timeline = _tool("timeline")
        from apex_tpu.observability import FlightRecorder, MetricRegistry

        reg = MetricRegistry(fetch_every=1)
        reg.gauge("train/loss")
        state = reg.update(reg.init(), {"train/loss": float("nan")})
        reg.observe(0, state)
        reg.observe(1, state)
        reg.fetch()
        rec = FlightRecorder(
            capacity=8, directory=str(tmp_path), registry=reg,
        )
        for s in range(4):
            rec.on_step(s, skipped=(s == 2))
        rec.on_rollback(3, 1, skips=1)
        dump = rec.dump("unit test")
        out = str(tmp_path / "trace.json")
        rc = timeline.main(["--flight", dump, "--out", out])
        assert rc == 0
        evs = json.load(open(out))["traceEvents"]
        steps = [e for e in evs if e.get("name") == "train/step"]
        assert len(steps) == 3  # 4 frames -> 3 intervals
        assert any(e.get("name") == "train/rollback" for e in evs)
        # the NaN loss — the crash evidence — survives as a marker
        # instant (a counter track cannot render non-finites)
        nan_marks = [e for e in evs
                     if e.get("name") == "train/loss = NaN"]
        assert nan_marks and nan_marks[0]["ph"] == "i"
