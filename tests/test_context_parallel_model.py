"""Context parallelism wired into the GPT model family.

Load-bearing invariant: a cp=2-sharded GptModel (ring or Ulysses
attention, global-position RoPE/embeddings, boundary-crossing next-token
loss) must reproduce the unsharded model's loss AND — after the
pmean-over-cp gradient sync (cp is a data axis for gradients) — its
gradients, from the same init key (degree-invariant init)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from apex_tpu import parallel_state as ps
from apex_tpu.models.gpt import (
    GptConfig,
    GptModel,
    gpt_lm_loss,
    gpt_lm_loss_cp,
)

S, B, CP = 16, 2, 2
KW = dict(
    vocab_size=64, hidden_size=32, num_layers=2, num_heads=4,
    intermediate_size=64, max_seq_len=S, dtype=jnp.float32,
)
TOL = dict(rtol=2e-4, atol=1e-5)


def _ids():
    return jax.random.randint(jax.random.PRNGKey(3), (S, B), 0, 64)


def _run_cp(cfg, ids, tp=1):
    """loss + synced grads of the cp-sharded model (ids replicated in,
    sliced per cp rank inside, honoring the configured layout)."""
    m = GptModel(cfg)

    def f(key, ids):
        rank = jax.lax.axis_index(ps.CONTEXT_PARALLEL_AXIS)
        if cfg.context_parallel == "ring_zigzag":
            from apex_tpu.transformer.context_parallel import zigzag_shard

            local = zigzag_shard(ids, rank, CP, axis=0)
        else:
            local = jax.lax.dynamic_slice_in_dim(
                ids, rank * (S // CP), S // CP, 0
            )
        params = m.init(key, local)
        loss, grads = jax.value_and_grad(
            lambda p: gpt_lm_loss_cp(p, m, local)
        )(params)
        grads = jax.tree_util.tree_map(
            lambda g: jax.lax.pmean(g, ps.CONTEXT_PARALLEL_AXIS), grads
        )
        g = grads["params"]
        out = {
            "ln_attn": g["layers"]["block"]["ln_attn"]["scale"],
            "ln_f": g["ln_f"]["scale"],
            "qkv": g["layers"]["block"]["qkv"]["weight"],
            "embed": g["word_embeddings"]["weight"],
        }
        if not cfg.rotary:
            out["pos"] = g["position_embeddings"]
        return loss, out

    mesh = ps.initialize_model_parallel(
        context_parallel_size=CP, tensor_model_parallel_size=tp
    )
    loss, grads = jax.jit(
        jax.shard_map(
            f, mesh=mesh, in_specs=(P(), P()), out_specs=(P(), P()),
            check_vma=False,
        )
    )(jax.random.PRNGKey(0), ids)
    ps.destroy_model_parallel()
    return float(loss), grads


def _run_ref(ids, **kw):
    m = GptModel(GptConfig(**kw))
    params = m.init(jax.random.PRNGKey(0), ids)
    loss, grads = jax.value_and_grad(lambda p: gpt_lm_loss(p, m, ids))(
        params
    )
    g = grads["params"]
    out = {
        "ln_attn": g["layers"]["block"]["ln_attn"]["scale"],
        "ln_f": g["ln_f"]["scale"],
        "qkv": g["layers"]["block"]["qkv"]["weight"],
        "embed": g["word_embeddings"]["weight"],
    }
    if "rotary" in kw and not kw["rotary"]:
        out["pos"] = g["position_embeddings"]
    return float(loss), out


def test_cp_zigzag_positions_with_oversized_table(eight_devices):
    """Learned position embeddings with max_seq_len > S under zigzag:
    the chunk math must run on the global SEQUENCE length, not the
    table length (regression: the table-length variant returned
    wrong-size, wrong-position rows)."""
    kw = dict(KW, max_seq_len=4 * S)
    ids = _ids()
    loss, grads = _run_cp(
        GptConfig(context_parallel="ring_zigzag", rotary=False, **kw),
        ids,
    )
    loss_ref, ref = _run_ref(ids, rotary=False, **kw)
    np.testing.assert_allclose(loss, loss_ref, rtol=1e-5)
    np.testing.assert_allclose(
        np.asarray(grads["pos"]), np.asarray(ref["pos"]), **TOL
    )


@pytest.mark.parametrize("mode", ["ring", "ring_zigzag", "ulysses"])
@pytest.mark.parametrize("rotary", [True, False])
def test_cp_gpt_matches_unsharded(mode, rotary, eight_devices):
    ids = _ids()
    loss, grads = _run_cp(
        GptConfig(context_parallel=mode, rotary=rotary, **KW), ids
    )
    loss_ref, ref = _run_ref(ids, rotary=rotary, **KW)
    np.testing.assert_allclose(loss, loss_ref, rtol=1e-5)
    for name in ref:
        np.testing.assert_allclose(
            np.asarray(grads[name]), np.asarray(ref[name]),
            err_msg=f"{mode}/{name}", **TOL,
        )


def test_cp_with_tp_loss_matches(eight_devices):
    """cp=2 x tp=2 compiles and reproduces the unsharded loss (grads for
    the tp-sharded leaves are per-shard; the cp-only test covers them)."""
    ids = _ids()
    m_cfg = GptConfig(context_parallel="ring", rotary=True, **KW)
    m = GptModel(m_cfg)

    def f(key, ids):
        rank = jax.lax.axis_index(ps.CONTEXT_PARALLEL_AXIS)
        local = jax.lax.dynamic_slice_in_dim(ids, rank * (S // CP), S // CP, 0)
        params = m.init(key, local)
        return gpt_lm_loss_cp(params, m, local)

    mesh = ps.initialize_model_parallel(
        context_parallel_size=2, tensor_model_parallel_size=2
    )
    loss = jax.jit(
        jax.shard_map(
            f, mesh=mesh, in_specs=(P(), P()), out_specs=P(),
            check_vma=False,
        )
    )(jax.random.PRNGKey(0), ids)
    ps.destroy_model_parallel()
    loss_ref, _ = _run_ref(ids, rotary=True, **KW)
    np.testing.assert_allclose(float(loss), loss_ref, rtol=1e-5)


def test_cp_moe_gpt_matches_unsharded(eight_devices):
    """MoE + cp: router/expert grads and loss (incl. cp-pmean'd aux
    stats) match the unsharded model after sync_moe_gradients over dp +
    pmean over cp.  capacity_factor=num_experts ⇒ no drops, so routing
    is exactly equivalent."""
    from apex_tpu.transformer.moe import sync_moe_gradients

    kw = dict(KW, num_experts=8, moe_capacity_factor=8.0)
    m = GptModel(GptConfig(context_parallel="ring", **kw))
    ids = _ids()

    def f(key, ids):
        rank = jax.lax.axis_index(ps.CONTEXT_PARALLEL_AXIS)
        local = jax.lax.dynamic_slice_in_dim(ids, rank * (S // CP), S // CP, 0)
        params = m.init(key, local)
        loss, grads = jax.value_and_grad(
            lambda p: gpt_lm_loss_cp(p, m, local)
        )(params)
        grads = sync_moe_gradients(grads)  # dp (expert-aware)
        grads = jax.tree_util.tree_map(
            lambda g: jax.lax.pmean(g, ps.CONTEXT_PARALLEL_AXIS), grads
        )
        g = grads["params"]["layers"]["block"]
        e1 = jax.lax.all_gather(
            g["moe"]["expert_w1"], ps.DATA_PARALLEL_AXIS, axis=1, tiled=True
        )
        return loss, g["moe"]["router"], e1, g["ln_mlp"]["scale"]

    mesh = ps.initialize_model_parallel(context_parallel_size=CP)
    loss, router, e1, ln = jax.jit(
        jax.shard_map(
            f, mesh=mesh, in_specs=(P(), P()), out_specs=P(),
            check_vma=False,
        )
    )(jax.random.PRNGKey(0), ids)
    ps.destroy_model_parallel()

    m_ref = GptModel(GptConfig(**kw))
    params = m_ref.init(jax.random.PRNGKey(0), ids)
    loss_ref, grads_ref = jax.value_and_grad(
        lambda p: gpt_lm_loss(p, m_ref, ids)
    )(params)
    g = grads_ref["params"]["layers"]["block"]
    np.testing.assert_allclose(float(loss), float(loss_ref), rtol=1e-5)
    np.testing.assert_allclose(
        np.asarray(router), np.asarray(g["moe"]["router"]),
        err_msg="router", **TOL,
    )
    np.testing.assert_allclose(
        np.asarray(e1), np.asarray(g["moe"]["expert_w1"]),
        err_msg="expert_w1", **TOL,
    )
    np.testing.assert_allclose(
        np.asarray(ln), np.asarray(g["ln_mlp"]["scale"]),
        err_msg="ln_mlp", **TOL,
    )


def test_config_validation():
    with pytest.raises(ValueError, match="mutually exclusive"):
        GptConfig(context_parallel="ring", sequence_parallel=True, **KW)
    with pytest.raises(ValueError, match="context_parallel"):
        GptConfig(context_parallel="rings", **KW)


def test_lm_loss_guard(eight_devices):
    """gpt_lm_loss refuses a cp-sharded model inside the mesh (the shift
    would silently skip shard boundaries)."""
    m = GptModel(GptConfig(context_parallel="ring", **KW))

    def f(key, ids):
        rank = jax.lax.axis_index(ps.CONTEXT_PARALLEL_AXIS)
        local = jax.lax.dynamic_slice_in_dim(ids, rank * (S // CP), S // CP, 0)
        params = m.init(key, local)
        return gpt_lm_loss(params, m, local)

    mesh = ps.initialize_model_parallel(context_parallel_size=CP)
    with pytest.raises(ValueError, match="gpt_lm_loss_cp"):
        jax.jit(
            jax.shard_map(
                f, mesh=mesh, in_specs=(P(), P()), out_specs=P(),
                check_vma=False,
            )
        )(jax.random.PRNGKey(0), _ids())
