"""FusedMixedPrecisionLamb + InstanceNorm3d (VERDICT r1 missing item 6)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from apex_tpu.normalization import InstanceNorm3d, InstanceNorm3dNVFuser, instance_norm
from apex_tpu.optimizers import (
    FusedMixedPrecisionLamb,
    fused_lamb,
    fused_mixed_precision_lamb,
)


# ---------------------------------------------------------------------------
# FusedMixedPrecisionLamb
# ---------------------------------------------------------------------------


def _half_params():
    rs = np.random.RandomState(0)
    return {
        "w": jnp.asarray(rs.randn(16, 8), jnp.bfloat16),
        "b": jnp.zeros((8,), jnp.bfloat16),
    }


def _grads_like(params, seed=1):
    rs = np.random.RandomState(seed)
    return jax.tree_util.tree_map(
        lambda p: jnp.asarray(rs.randn(*p.shape), p.dtype), params
    )


def test_mp_lamb_matches_f32_lamb_on_masters():
    """The master trajectory must equal plain f32 LAMB on f32 params."""
    params_half = _half_params()
    params_f32 = jax.tree_util.tree_map(
        lambda p: p.astype(jnp.float32), params_half
    )
    mp = fused_mixed_precision_lamb(learning_rate=1e-2, weight_decay=0.01)
    ref = fused_lamb(learning_rate=1e-2, weight_decay=0.01)
    mp_state = mp.init(params_half)
    ref_state = ref.init(params_f32)

    p_half, p_f32 = params_half, params_f32
    for step in range(5):
        g_half = _grads_like(p_half, seed=step)
        g_f32 = jax.tree_util.tree_map(
            lambda g: g.astype(jnp.float32), g_half
        )
        u_mp, mp_state = mp.update(g_half, mp_state, p_half)
        u_ref, ref_state = ref.update(g_f32, ref_state, p_f32)
        p_half = jax.tree_util.tree_map(jnp.add, p_half, u_mp)
        p_f32 = jax.tree_util.tree_map(jnp.add, p_f32, u_ref)

    # masters follow the f32 trajectory exactly
    jax.tree_util.tree_map(
        lambda m, r: np.testing.assert_allclose(
            np.asarray(m), np.asarray(r), rtol=1e-6, atol=1e-6
        ),
        mp_state.masters, p_f32,
    )
    # model params are exactly the rounded masters (no drift)
    jax.tree_util.tree_map(
        lambda p, m: np.testing.assert_array_equal(
            np.asarray(p, np.float32),
            np.asarray(m.astype(jnp.bfloat16), np.float32),
        ),
        p_half, mp_state.masters,
    )
    # and the half trajectory beats naive half-only accumulation: dtype held
    assert all(
        p.dtype == jnp.bfloat16 for p in jax.tree_util.tree_leaves(p_half)
    )


def test_mp_lamb_stateful_wrapper():
    params = _half_params()
    opt = FusedMixedPrecisionLamb(params, learning_rate=1e-2)
    new = opt.step(_grads_like(params), params)
    assert all(
        p.dtype == jnp.bfloat16 for p in jax.tree_util.tree_leaves(new)
    )
    changed = jax.tree_util.tree_map(
        lambda a, b: bool(jnp.any(a != b)), params, new
    )
    assert all(jax.tree_util.tree_leaves(changed))


def test_mp_lamb_requires_params():
    mp = fused_mixed_precision_lamb()
    state = mp.init(_half_params())
    with pytest.raises(ValueError):
        mp.update(_grads_like(_half_params()), state, None)


# ---------------------------------------------------------------------------
# InstanceNorm3d
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("dtype", [jnp.float32, jnp.bfloat16])
def test_instance_norm_functional_matches_manual(dtype):
    x = jax.random.normal(jax.random.PRNGKey(0), (2, 3, 4, 5, 6), dtype)
    w = jnp.linspace(0.5, 1.5, 6, dtype=jnp.float32)
    b = jnp.linspace(-1.0, 1.0, 6, dtype=jnp.float32)
    y = instance_norm(x, w, b, eps=1e-5)
    assert y.dtype == dtype

    xf = np.asarray(x, np.float32)
    mean = xf.mean(axis=(1, 2, 3), keepdims=True)
    var = xf.var(axis=(1, 2, 3), keepdims=True)
    want = (xf - mean) / np.sqrt(var + 1e-5) * np.asarray(w) + np.asarray(b)
    tol = 1e-5 if dtype == jnp.float32 else 3e-2
    np.testing.assert_allclose(
        np.asarray(y, np.float32), want, atol=tol, rtol=tol
    )


def test_instance_norm_module_running_stats():
    m = InstanceNorm3d(num_features=4, track_running_stats=True, momentum=0.5)
    x = jax.random.normal(jax.random.PRNGKey(0), (2, 3, 3, 3, 4)) * 3 + 1
    variables = m.init(jax.random.PRNGKey(1), x)
    assert variables["batch_stats"]["mean"].shape == (4,)

    y, mutated = m.apply(x=x, variables=variables, mutable=["batch_stats"])
    # train-mode output is normalized per (n, c)
    yf = np.asarray(y, np.float32)
    np.testing.assert_allclose(
        yf.mean(axis=(1, 2, 3)), 0.0, atol=1e-4
    )
    # running stats moved toward the batch stats (torch momentum)
    assert np.all(np.asarray(mutated["batch_stats"]["var"]) != 1.0)

    # eval mode consumes the running stats (different result than train)
    y_eval = m.apply(
        {"params": variables["params"],
         "batch_stats": mutated["batch_stats"]},
        x, use_running_average=True,
    )
    assert not np.allclose(np.asarray(y_eval), yf)


def test_instance_norm_channels_first_parity():
    x_last = jax.random.normal(jax.random.PRNGKey(0), (2, 3, 4, 5, 6))
    x_first = jnp.moveaxis(x_last, -1, 1)
    m_last = InstanceNorm3d(num_features=6)
    m_first = InstanceNorm3dNVFuser(num_features=6, channels_first=True)
    v = m_last.init(jax.random.PRNGKey(1), x_last)
    y_last = m_last.apply(v, x_last)
    y_first = m_first.apply(v, x_first)
    np.testing.assert_allclose(
        np.asarray(jnp.moveaxis(y_first, 1, -1)), np.asarray(y_last),
        rtol=1e-6, atol=1e-6,
    )


def test_instance_norm_channel_mismatch_raises():
    m = InstanceNorm3d(num_features=8)
    x = jnp.ones((1, 2, 2, 2, 4))
    with pytest.raises(ValueError):
        m.init(jax.random.PRNGKey(0), x)
