"""Canary analysis for fleet deploys (ISSUE 20).

Covers: the dependency-free statistics (one-sided Mann–Whitney U,
exact binomial tail), the CanaryAnalyzer honesty floor ("no verdict"
is NOT a pass) and its seeded false-positive pin, golden-probe model
fingerprints (bit-exact across a same-weights rebuild, flipped by a
SINGLE corrupted weight bit), the validated ``canary`` routing-span
annotation, and the canary-gated rolling update end to end: clean
deploy passes, planted NaN regression fails + rolls back bit-exact,
mid-canary spawns keep incumbent weights, and router exposure stays
within the canary fraction.  The full drill (throttled decode,
timeline re-proof, golden rows) lives in ``tools/canary_drill.py``
behind the CANARY CI gate.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from apex_tpu.fleetctl import EngineReplica, Fleet, LIVE
from apex_tpu.models.gpt import GptConfig, GptModel
from apex_tpu.observability import MetricRegistry
from apex_tpu.observability.canary import (
    CanaryAnalyzer,
    CanaryConfig,
    GoldenProbeSet,
    binom_tail,
    fingerprint_distance,
    mann_whitney_p,
    model_fingerprint,
)
from apex_tpu.observability.spans import (
    REQ_QUEUED,
    REQ_ROUTED,
    SpanRecorder,
)
from apex_tpu.serve import InferenceEngine, Request, ServeConfig


class VClock:
    def __init__(self, tick_s=0.005):
        self.t = 0.0
        self.tick_s = tick_s

    def __call__(self):
        return self.t

    def advance(self):
        self.t += self.tick_s


@pytest.fixture(scope="module")
def gpt():
    cfg = GptConfig(
        vocab_size=64, hidden_size=32, num_layers=2, num_heads=2,
        intermediate_size=64, max_seq_len=128, dtype=jnp.float32,
    )
    model = GptModel(cfg)
    params = model.init(
        jax.random.PRNGKey(0), jnp.zeros((8, 1), jnp.int32)
    )
    return cfg, model, params


def make_engine(gpt, params=None):
    cfg, _, base = gpt
    return InferenceEngine(
        cfg, params if params is not None else base,
        ServeConfig(page_size=8, num_pages=32, max_batch=2,
                    max_pages_per_seq=8, verify=False),
        registry=MetricRegistry(fetch_every=1),
    ).build()


PROBES = GoldenProbeSet.generate(
    64, n_probes=2, prompt_len=6, max_new_tokens=4
)


# ---------------------------------------------------------------------------
# statistics
# ---------------------------------------------------------------------------


class TestStats:
    def test_mwu_same_distribution_no_signal(self):
        rs = np.random.RandomState(7)
        a = rs.normal(10.0, 2.0, size=60)
        b = rs.normal(10.0, 2.0, size=200)
        assert mann_whitney_p(a, b, worse="greater") > 1e-3

    def test_mwu_detects_shift(self):
        rs = np.random.RandomState(7)
        a = rs.normal(14.0, 2.0, size=60)      # canary clearly worse
        b = rs.normal(10.0, 2.0, size=200)
        assert mann_whitney_p(a, b, worse="greater") < 1e-9

    def test_mwu_one_sided_direction(self):
        """A canary that is BETTER in the worse direction never
        signals — the held canary serves less load and would
        false-positive under any two-sided test."""
        rs = np.random.RandomState(7)
        a = rs.normal(6.0, 2.0, size=60)       # canary better
        b = rs.normal(10.0, 2.0, size=200)
        assert mann_whitney_p(a, b, worse="greater") > 0.99
        # ...and the same data signals when lower IS worse
        assert mann_whitney_p(a, b, worse="less") < 1e-9

    def test_mwu_all_ties_is_p1(self):
        assert mann_whitney_p([3.0] * 30, [3.0] * 50) == 1.0

    def test_mwu_rejects_bad_direction(self):
        with pytest.raises(ValueError):
            mann_whitney_p([1.0] * 20, [1.0] * 20, worse="sideways")

    def test_binom_tail_matches_direct_sum(self):
        from math import comb

        n, p = 12, 0.3
        for k in range(n + 1):
            direct = sum(
                comb(n, i) * p ** i * (1 - p) ** (n - i)
                for i in range(k, n + 1)
            )
            assert binom_tail(k, n, p) == pytest.approx(
                direct, rel=1e-10
            )

    def test_binom_tail_edges(self):
        assert binom_tail(0, 8, 0.1) == 1.0
        assert binom_tail(9, 8, 0.1) == 0.0
        assert binom_tail(8, 8, 0.5) == pytest.approx(0.5 ** 8)


# ---------------------------------------------------------------------------
# analyzer: honesty floor + false-positive pin
# ---------------------------------------------------------------------------


class TestCanaryAnalyzer:
    def test_empty_is_no_verdict(self):
        v = CanaryAnalyzer().verdict()
        assert v.status == "no_verdict"
        assert v.status != "pass"

    def test_below_floor_is_no_verdict_not_pass(self):
        an = CanaryAnalyzer(min_samples=16, min_event_total=8)
        an.add_samples("canary", "ttft_ms", [1.0] * 15)   # one short
        an.add_samples("incumbent", "ttft_ms", [1.0] * 100)
        an.add_events("canary", "shed_deadline", 0, 7)    # one short
        an.add_events("incumbent", "shed_deadline", 0, 100)
        v = an.verdict()
        assert v.status == "no_verdict"
        assert all(c["verdict"] is None for c in v.checks)

    def test_identical_distributions_pass(self):
        an = CanaryAnalyzer(min_samples=16)
        vals = [float(i % 7) for i in range(40)]
        an.add_samples("canary", "ttft_ms", vals)
        an.add_samples("incumbent", "ttft_ms", vals * 3)
        assert an.verdict().status == "pass"

    def test_planted_sample_drift_fails(self):
        an = CanaryAnalyzer(min_samples=16, alpha=1e-3)
        rs = np.random.RandomState(3)
        an.add_samples("canary", "ttft_ms",
                       rs.normal(20.0, 1.0, size=40))
        an.add_samples("incumbent", "ttft_ms",
                       rs.normal(10.0, 1.0, size=120))
        v = an.verdict()
        assert v.status == "fail"
        assert v.failed[0]["metric"] == "ttft_ms"

    def test_planted_event_drift_fails(self):
        an = CanaryAnalyzer(min_events=4, min_event_total=8)
        an.add_events("canary", "shed_poisoned", 9, 12)
        an.add_events("incumbent", "shed_poisoned", 0, 200)
        assert an.verdict().status == "fail"

    def test_event_fail_needs_min_events(self):
        """p alone cannot fail a channel: one unlucky request out of
        few trials is an anecdote, not a regression."""
        an = CanaryAnalyzer(min_events=4, min_event_total=8,
                            alpha=0.05)
        an.add_events("canary", "shed_deadline", 3, 10)
        an.add_events("incumbent", "shed_deadline", 0, 500)
        v = an.verdict()
        (check,) = v.checks
        assert check["p"] < 0.05 and v.status == "pass"

    def test_events_accumulate(self):
        an = CanaryAnalyzer(min_event_total=8)
        for _ in range(4):
            an.add_events("canary", "shed_deadline", 1, 3)
            an.add_events("incumbent", "shed_deadline", 1, 3)
        (check,) = an.verdict().checks
        assert check["n_canary"] == 12 and check["bad_canary"] == 4

    def test_direction_change_rejected(self):
        an = CanaryAnalyzer()
        an.add_samples("canary", "m", [1.0], worse="greater")
        with pytest.raises(ValueError):
            an.add_samples("canary", "m", [1.0], worse="less")

    def test_bogus_direction_rejected(self):
        # a typo'd direction would silently invert the one-sided test
        with pytest.raises(ValueError, match="greater"):
            CanaryAnalyzer().add_samples("canary", "m", [1.0],
                                         worse="sideways")

    def test_false_positive_pin_20_seeds(self):
        """Identical generating distributions on both sides across 20
        seeds: ZERO fail verdicts — the satellite-3 pin."""
        fails = 0
        for seed in range(20):
            rs = np.random.RandomState(seed)
            an = CanaryAnalyzer(min_samples=16, alpha=1e-3)
            an.add_samples("canary", "ttft_ms",
                           rs.normal(10.0, 3.0, size=48))
            an.add_samples("incumbent", "ttft_ms",
                           rs.normal(10.0, 3.0, size=160))
            an.add_samples("canary", "tokens_per_slot_tick",
                           rs.poisson(3.0, size=48).astype(float),
                           worse="less")
            an.add_samples("incumbent", "tokens_per_slot_tick",
                           rs.poisson(3.0, size=160).astype(float),
                           worse="less")
            bad_c = rs.binomial(40, 0.02)
            bad_i = rs.binomial(160, 0.02)
            an.add_events("canary", "shed_deadline", bad_c, 40)
            an.add_events("incumbent", "shed_deadline", bad_i, 160)
            if an.verdict().status == "fail":
                fails += 1
        assert fails == 0


# ---------------------------------------------------------------------------
# golden-probe fingerprints
# ---------------------------------------------------------------------------


class TestFingerprint:
    def test_rebuild_bit_exact_and_pool_clean(self, gpt):
        engine = make_engine(gpt)
        fp_a = model_fingerprint(engine, PROBES)
        assert engine.pool.in_use == 0
        engine.rebuild(full=True)
        fp_b = model_fingerprint(engine, PROBES)
        assert fp_a["digest"] == fp_b["digest"]
        assert fp_a["finite"] and fp_b["finite"]
        d = fingerprint_distance(fp_a, fp_b)
        assert d["match"] and d["distance"] == 0.0

    def test_single_bit_corruption_flips_digest(self, gpt):
        """Flip ONE bit — the sign of the highest-magnitude weight,
        chosen so the corrupted value provably participates — and the
        digest must change; restoring the weights must restore it."""
        _, _, params = gpt
        engine = make_engine(gpt)
        fp_a = model_fingerprint(engine, PROBES)

        leaves, treedef = jax.tree_util.tree_flatten(params)
        mags = [float(np.abs(np.asarray(x)).max()) for x in leaves]
        i = int(np.argmax(mags))
        flat = np.asarray(leaves[i]).copy()
        j = int(np.abs(flat).argmax())
        flat.view(np.uint32).flat[j] ^= np.uint32(0x80000000)
        corrupt = list(leaves)
        corrupt[i] = jnp.asarray(flat)
        engine.params = jax.tree_util.tree_unflatten(treedef, corrupt)
        engine.rebuild(full=True)
        fp_bit = model_fingerprint(engine, PROBES)
        assert fp_bit["digest"] != fp_a["digest"]
        d = fingerprint_distance(fp_a, fp_bit)
        assert not d["match"] and d["distance"] > 0.0

        engine.params = params
        engine.rebuild(full=True)
        fp_back = model_fingerprint(engine, PROBES)
        assert fp_back["digest"] == fp_a["digest"]

    def test_nan_weights_fingerprint_not_finite(self, gpt):
        _, _, params = gpt
        bad = jax.tree_util.tree_map(
            lambda a: a.at[...].set(jnp.nan) if a.ndim else a, params
        )
        engine = make_engine(gpt, params=bad)
        fp = model_fingerprint(engine, PROBES)
        assert not fp["finite"]

    def test_probe_set_is_deterministic(self):
        a = GoldenProbeSet.generate(64, n_probes=3, prompt_len=5,
                                    max_new_tokens=4, seed=11)
        b = GoldenProbeSet.generate(64, n_probes=3, prompt_len=5,
                                    max_new_tokens=4, seed=11)
        c = GoldenProbeSet.generate(64, n_probes=3, prompt_len=5,
                                    max_new_tokens=4, seed=12)
        assert a.prompts == b.prompts
        assert a.prompts != c.prompts
        assert all(t >= 1 for p in a.prompts for t in p)


# ---------------------------------------------------------------------------
# validated `canary` routing annotation
# ---------------------------------------------------------------------------


class TestCanarySpanAnnotation:
    def test_annotation_requires_open_deploy_window(self):
        clock = VClock()
        rec = SpanRecorder(64, clock=clock)
        with pytest.raises(ValueError, match="deploy window"):
            rec.request_event(1, REQ_ROUTED, canary=True)
        rec.begin_deploy_window(canary="r0", frac=0.25)
        rec.request_event(1, REQ_ROUTED, canary=True)
        rec.request_event(1, REQ_QUEUED, replica="r0")
        rec.end_deploy_window(verdict="pass")
        with pytest.raises(ValueError, match="deploy window"):
            rec.request_event(2, REQ_ROUTED, canary=True)

    def test_annotation_only_on_routed_hops(self):
        clock = VClock()
        rec = SpanRecorder(64, clock=clock)
        rec.begin_deploy_window(canary="r0", frac=0.25)
        with pytest.raises(ValueError, match="routed"):
            rec.request_event(1, REQ_QUEUED, canary=True)

    def test_window_pairing_enforced(self):
        rec = SpanRecorder(64, clock=VClock())
        with pytest.raises(RuntimeError):
            rec.end_deploy_window(verdict="pass")
        rec.begin_deploy_window(canary="r0", frac=0.5)
        assert rec.deploy_window_open
        with pytest.raises(RuntimeError):
            rec.begin_deploy_window(canary="r1", frac=0.5)
        rec.end_deploy_window(verdict="fail")
        assert not rec.deploy_window_open


# ---------------------------------------------------------------------------
# canary-gated rolling update, end to end
# ---------------------------------------------------------------------------


def make_fleet(gpt, clock, *, n=3, spans=None):
    def factory(name):
        cfg, _, params = gpt
        engine = InferenceEngine(
            cfg, params,
            ServeConfig(page_size=8, num_pages=32, max_batch=2,
                        max_pages_per_seq=8, verify=False),
            registry=MetricRegistry(fetch_every=1),
        ).build()
        return EngineReplica(name, engine, clock=clock, spans=spans,
                             max_queue_depth=16)

    return Fleet(factory, replicas=n, clock=clock, spans=spans)


def canary_cfg(**kw):
    kw.setdefault("frac", 0.34)
    kw.setdefault("probes", PROBES)
    kw.setdefault("min_samples", 8)
    kw.setdefault("min_events", 3)
    kw.setdefault("min_event_total", 6)
    kw.setdefault("soak_ticks", 60)
    kw.setdefault("max_window_ticks", 400)
    return CanaryConfig(**kw)


def run_deploy(gpt, deploy_params, *, cfg=None, n_requests=60,
               submit_every=3, deploy_after=25, max_ticks=5000,
               spans=None, mid_canary=None):
    """Drive a seeded load through a canary-gated deploy until every
    request is terminal and the deploy machinery is idle.  Returns
    ``(fleet, reqs)``; ``mid_canary(fleet)`` runs once on the first
    tick the deploy is in its canary phase."""
    clock = VClock()
    fleet = make_fleet(gpt, clock, spans=spans)
    rs = np.random.RandomState(0)
    reqs = []
    deployed = False
    fired = mid_canary is None
    for tick in range(max_ticks):
        if len(reqs) < n_requests and tick % submit_every == 0:
            reqs.append(fleet.submit(Request(
                prompt=list(rs.randint(1, 64, size=8)),
                max_new_tokens=8,
            )))
        if not deployed and tick >= deploy_after:
            fleet.start_rolling_update(
                deploy_params, canary=cfg or canary_cfg()
            )
            deployed = True
        if not fired and fleet.deploy is not None \
                and fleet.deploy.get("phase") == "canary":
            mid_canary(fleet)
            fired = True
        fleet.step()
        clock.advance()
        if deployed and len(reqs) >= n_requests \
                and not fleet.pending and fleet.deploy is None:
            break
    else:
        raise AssertionError(
            f"deploy did not settle in {max_ticks} ticks "
            f"(deploy={fleet.deploy})"
        )
    assert all(r.status in ("done", "shed") for r in reqs)
    return fleet, reqs


class TestCanaryDeploy:
    def test_rejects_non_config_canary(self, gpt):
        clock = VClock()
        fleet = make_fleet(gpt, clock)
        _, _, params = gpt
        with pytest.raises(TypeError):
            fleet.start_rolling_update(params, canary=0.25)

    def test_clean_deploy_passes(self, gpt):
        cfg, _, _ = gpt
        new_params = GptModel(cfg).init(
            jax.random.PRNGKey(9), jnp.zeros((8, 1), jnp.int32)
        )
        fleet, reqs = run_deploy(gpt, new_params)
        d = fleet.deploy_history[-1]
        c = d["canary"]
        assert c["verdict"] == "pass"
        assert not d.get("rolled_back")
        assert d["lost_requests"] == 0
        assert sorted(d["updated"]) == sorted(
            r.name for r in fleet.replicas
        )
        # every live replica really serves the new weights
        assert all(
            r.engine.params is new_params for r in fleet.live
        )
        # exposure honored while the verdict was out
        assert c["canary_routed"] <= 0.34 * c["routed"] + 1
        fr = fleet.registry.fetch()
        assert fr["fleet/deploys_rolled_back"] == 0
        assert fr["fleet/canary/verdict_pass"] == 1
        assert fr["fleet/canary/verdict_fail"] == 0
        # intentional weight change: recorded as a distance, not a
        # failure
        assert c["fingerprint"]["distance"] > 0.0

    def test_nan_regression_fails_and_rolls_back(self, gpt):
        _, _, params = gpt
        bad = jax.tree_util.tree_map(
            lambda a: a.at[...].set(jnp.nan) if a.ndim else a, params
        )
        fleet, reqs = run_deploy(gpt, bad)
        d = fleet.deploy_history[-1]
        c = d["canary"]
        assert d["rolled_back"] and c["verdict"] == "fail"
        assert d["lost_requests"] == 0
        assert c["detect_ticks"] > 0
        assert not c["fingerprint"]["new_finite"]
        # the rollback is bit-exact: post-rollback probe digest equals
        # the pre-deploy incumbent digest
        assert c["rollback_digest"] == c["fingerprint"]["old_digest"]
        # every live replica is back on the incumbent weights
        assert all(r.engine.params is params for r in fleet.live)
        # the bad weights only ever saw the canary slice
        assert c["canary_routed"] <= 0.34 * c["routed"] + 1
        fr = fleet.registry.fetch()
        assert fr["fleet/deploys_rolled_back"] == 1
        assert fr["fleet/canary/verdict_fail"] == 1
        rules = [e.rule for e in fleet.health_events]
        assert "fleet_canary_verdict" in rules
        assert "fleet_deploy_rollback" in rules
        # NaN quarantine sheds are the DETECTION signal and the only
        # casualties — bounded by the canary slice, never silent junk
        # tokens served as answers
        shed = [r for r in reqs if r.status == "shed"]
        assert all(r.shed_reason == "poisoned" for r in shed)
        assert len(shed) <= c["canary_routed"]

    def test_mid_canary_spawn_keeps_incumbent_weights(self, gpt):
        cfg, _, params = gpt
        new_params = GptModel(cfg).init(
            jax.random.PRNGKey(9), jnp.zeros((8, 1), jnp.int32)
        )
        seen = {}

        def spawn(fleet):
            rep = fleet._spawn()
            seen["name"] = rep.name
            # born before the verdict: incumbent weights, queued for
            # the rolling phase
            assert rep.engine.params is params
            assert rep.name in fleet.deploy["remaining"]

        fleet, _ = run_deploy(gpt, new_params, mid_canary=spawn)
        d = fleet.deploy_history[-1]
        assert d["canary"]["verdict"] == "pass"
        # ...and the PASS still rolled the newcomer forward
        assert seen["name"] in d["updated"]
        assert fleet.replica(seen["name"]).engine.params is new_params

    def test_clean_deploy_emits_valid_span_windows(self, gpt):
        cfg, _, _ = gpt
        new_params = GptModel(cfg).init(
            jax.random.PRNGKey(9), jnp.zeros((8, 1), jnp.int32)
        )
        clock = VClock()
        rec = SpanRecorder(65536, clock=clock)
        fleet = make_fleet(gpt, clock, spans=rec)
        rs = np.random.RandomState(1)
        reqs = []
        deployed = False
        for tick in range(5000):
            if len(reqs) < 50 and tick % 3 == 0:
                reqs.append(fleet.submit(Request(
                    prompt=list(rs.randint(1, 64, size=8)),
                    max_new_tokens=8,
                )))
            if not deployed and tick >= 25:
                fleet.start_rolling_update(
                    new_params, canary=canary_cfg()
                )
                deployed = True
            fleet.step()
            clock.advance()
            if deployed and len(reqs) >= 50 and not fleet.pending \
                    and fleet.deploy is None:
                break
        else:
            raise AssertionError("deploy did not settle")
        assert not rec.deploy_window_open
        entries = rec.snapshot()
        names = [e["name"] for e in entries]
        assert names.count("fleet/deploy_window_open") == 1
        assert names.count("fleet/deploy_window_close") == 1
        marked = [
            e for e in entries
            if e["name"] == "req/routed"
            and (e.get("args") or {}).get("canary")
        ]
        canary_name = fleet.deploy_history[-1]["canary"]["name"]
        assert marked, "no canary-annotated routing hops recorded"
        assert all(
            e["args"]["replica"] == canary_name for e in marked
        )
