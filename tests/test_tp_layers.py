"""≙ tests/L0/run_transformer/test_layers.py — TP layers vs dense golden."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from apex_tpu import parallel_state as ps
from apex_tpu.transformer.tensor_parallel import (
    ColumnParallelLinear,
    RowParallelLinear,
    VocabParallelEmbedding,
    vocab_parallel_cross_entropy,
)

TP = 8


def tp_mesh():
    return ps.initialize_model_parallel(tensor_model_parallel_size=TP)


def run_smap(fn, *args, in_specs, out_specs):
    return jax.jit(
        jax.shard_map(
            fn,
            mesh=ps.get_mesh(),
            in_specs=in_specs,
            out_specs=out_specs,
            check_vma=False,
        )
    )(*args)


def test_column_parallel_matches_dense(eight_devices):
    tp_mesh()
    x = jax.random.normal(jax.random.PRNGKey(0), (4, 16))
    layer = ColumnParallelLinear(16, 32, gather_output=True)

    def f(key, x):
        params = layer.init(key, x)
        y = layer.apply(params, x)
        w_full = jax.lax.all_gather(
            params["params"]["weight"], "tp", axis=1, tiled=True
        )
        b_full = jax.lax.all_gather(
            params["params"]["bias"], "tp", axis=0, tiled=True
        )
        return y, w_full, b_full

    y, w, b = run_smap(
        f, jax.random.PRNGKey(1), x, in_specs=(P(), P()), out_specs=P()
    )
    ref = x @ w + b
    np.testing.assert_allclose(np.asarray(y), np.asarray(ref), rtol=1e-5,
                               atol=1e-5)


def test_column_row_pair_matches_dense(eight_devices):
    """The canonical megatron MLP: Column(gather=False) -> Row(parallel in)."""
    tp_mesh()
    x = jax.random.normal(jax.random.PRNGKey(2), (6, 16))
    col = ColumnParallelLinear(16, 64, gather_output=False)
    row = RowParallelLinear(64, 16, input_is_parallel=True)

    def f(key, x):
        k1, k2 = jax.random.split(key)
        pc = col.init(k1, x)
        h = col.apply(pc, x)
        pr = row.init(k2, h)
        y = row.apply(pr, h)
        wc = jax.lax.all_gather(pc["params"]["weight"], "tp", axis=1, tiled=True)
        bc = jax.lax.all_gather(pc["params"]["bias"], "tp", axis=0, tiled=True)
        wr = jax.lax.all_gather(pr["params"]["weight"], "tp", axis=0, tiled=True)
        br = pr["params"]["bias"]
        return y, wc, bc, wr, br

    y, wc, bc, wr, br = run_smap(
        f, jax.random.PRNGKey(3), x, in_specs=(P(), P()), out_specs=P()
    )
    ref = (x @ wc + bc) @ wr + br
    np.testing.assert_allclose(np.asarray(y), np.asarray(ref), rtol=1e-4,
                               atol=1e-4)


def test_sequence_parallel_pair_matches_dense(eight_devices):
    """SP: input sharded along sequence; Column all-gathers, Row
    reduce-scatters; final gather must equal the dense result."""
    tp_mesh()
    seq = 16
    x = jax.random.normal(jax.random.PRNGKey(4), (seq, 8))  # (s, d)
    col = ColumnParallelLinear(8, 32, sequence_parallel_enabled=True)
    row = RowParallelLinear(
        32, 8, input_is_parallel=True, sequence_parallel_enabled=True
    )

    def f(key, x_shard):
        k1, k2 = jax.random.split(key)
        pc = col.init(k1, x_shard)
        h = col.apply(pc, x_shard)       # (s, 32/tp) local
        pr = row.init(k2, h)
        y_shard = row.apply(pr, h)       # (s/tp, 8) seq shard
        y = jax.lax.all_gather(y_shard, "tp", axis=0, tiled=True)
        wc = jax.lax.all_gather(pc["params"]["weight"], "tp", axis=1, tiled=True)
        bc = jax.lax.all_gather(pc["params"]["bias"], "tp", axis=0, tiled=True)
        wr = jax.lax.all_gather(pr["params"]["weight"], "tp", axis=0, tiled=True)
        return y, wc, bc, wr, pr["params"]["bias"]

    y, wc, bc, wr, br = run_smap(
        f, jax.random.PRNGKey(5), x, in_specs=(P(), P("tp")), out_specs=P()
    )
    ref = (x @ wc + bc) @ wr + br
    np.testing.assert_allclose(np.asarray(y), np.asarray(ref), rtol=1e-4,
                               atol=1e-4)


def test_column_parallel_grads_match_dense(eight_devices):
    tp_mesh()
    x = jax.random.normal(jax.random.PRNGKey(6), (4, 16))
    layer = ColumnParallelLinear(16, 32, gather_output=True)

    def f(key, x):
        params = layer.init(key, x)

        def loss(p, x):
            return jnp.sum(layer.apply(p, x) ** 2)

        g = jax.grad(loss)(params, x)
        gw_full = jax.lax.all_gather(
            g["params"]["weight"], "tp", axis=1, tiled=True
        )
        w_full = jax.lax.all_gather(
            params["params"]["weight"], "tp", axis=1, tiled=True
        )
        b_full = jax.lax.all_gather(
            params["params"]["bias"], "tp", axis=0, tiled=True
        )
        return gw_full, w_full, b_full

    gw, w, b = run_smap(
        f, jax.random.PRNGKey(7), x, in_specs=(P(), P()), out_specs=P()
    )
    ref_gw = jax.grad(lambda w: jnp.sum((x @ w + b) ** 2))(w)
    np.testing.assert_allclose(np.asarray(gw), np.asarray(ref_gw), rtol=1e-4,
                               atol=1e-4)


def test_vocab_parallel_embedding_matches_dense(eight_devices):
    tp_mesh()
    vocab, dim = 32, 8
    ids = jnp.asarray([[0, 5, 31], [7, 16, 2]])
    emb = VocabParallelEmbedding(vocab, dim)

    def f(key, ids):
        params = emb.init(key, ids)
        out = emb.apply(params, ids)
        w_full = jax.lax.all_gather(
            params["params"]["weight"], "tp", axis=0, tiled=True
        )
        return out, w_full

    out, w = run_smap(
        f, jax.random.PRNGKey(8), ids, in_specs=(P(), P()), out_specs=P()
    )
    ref = jnp.take(w, ids, axis=0)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=1e-5,
                               atol=1e-6)


def test_column_parallel_rejects_gather_with_sp(eight_devices):
    tp_mesh()
    layer = ColumnParallelLinear(
        8, 16, gather_output=True, sequence_parallel_enabled=True
    )
    with pytest.raises(ValueError):
        run_smap(
            lambda k, x: layer.init(k, x),
            jax.random.PRNGKey(0),
            jnp.zeros((8, 8)),
            in_specs=(P(), P("tp")),
            out_specs=P(),
        )


@pytest.mark.parametrize("smoothing", [0.0, 0.1])
def test_vocab_parallel_cross_entropy_matches_full(eight_devices, smoothing):
    tp_mesh()
    n, vocab = 8, 64
    logits = jax.random.normal(jax.random.PRNGKey(9), (n, vocab)) * 2
    target = jax.random.randint(jax.random.PRNGKey(10), (n,), 0, vocab)

    def f(logits, target):
        loss = vocab_parallel_cross_entropy(logits, target, smoothing)
        grad = jax.grad(
            lambda l: jnp.sum(vocab_parallel_cross_entropy(l, target, smoothing))
        )(logits)
        grad_full = jax.lax.all_gather(grad, "tp", axis=1, tiled=True)
        return loss, grad_full

    loss, grad = run_smap(
        f, logits, target, in_specs=(P(None, "tp"), P()), out_specs=P()
    )

    def ref_loss_fn(l):
        logp = jax.nn.log_softmax(l, axis=-1)
        one_hot = jax.nn.one_hot(target, vocab)
        tgt = (1 - smoothing) * one_hot + smoothing / vocab
        return -jnp.sum(tgt * logp, axis=-1)

    ref = ref_loss_fn(logits)
    np.testing.assert_allclose(np.asarray(loss), np.asarray(ref), rtol=1e-4,
                               atol=1e-5)
    ref_grad = jax.grad(lambda l: jnp.sum(ref_loss_fn(l)))(logits)
    np.testing.assert_allclose(np.asarray(grad), np.asarray(ref_grad),
                               rtol=1e-4, atol=1e-5)
