"""Contrib dtype × grad coverage matrix (VERDICT r1 item 9).

Every targeted contrib feature (group_norm, groupbn, focal_loss,
index_mul_2d, conv_bias_relu) gets ≥2 dtypes and ≥1 gradient check:
values vs an independent composition in f32, grads vs numerical/
composition autodiff, output dtype == input dtype.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from apex_tpu.contrib.conv_bias_relu import (
    ConvBias,
    ConvBiasMaskReLU,
    ConvBiasReLU,
)
from apex_tpu.contrib.focal_loss import focal_loss, sigmoid_focal_loss
from apex_tpu.contrib.group_norm import GroupNorm, group_norm
from apex_tpu.contrib.groupbn import BatchNorm2d_NHWC
from apex_tpu.contrib.index_mul_2d import index_mul_2d

DTYPES = [jnp.float32, jnp.bfloat16]


def _tol(dtype):
    return dict(atol=1e-5, rtol=1e-5) if dtype == jnp.float32 else dict(
        atol=3e-2, rtol=3e-2
    )


# ---------------------------------------------------------------------------
# group_norm
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("dtype", DTYPES)
def test_group_norm_value_and_grad(dtype):
    x = jax.random.normal(jax.random.PRNGKey(0), (2, 4, 4, 8), dtype)
    w = jnp.linspace(0.5, 1.5, 8, dtype=jnp.float32)
    b = jnp.linspace(-0.5, 0.5, 8, dtype=jnp.float32)

    y = group_norm(x, 2, w, b, act="silu")
    assert y.dtype == dtype

    def ref(xf, wf, bf):
        n, h, wd, c = xf.shape
        g = 2
        xr = xf.reshape(n, h * wd, g, c // g)
        mean = xr.mean(axis=(1, 3), keepdims=True)
        var = ((xr - mean) ** 2).mean(axis=(1, 3), keepdims=True)
        yr = ((xr - mean) / jnp.sqrt(var + 1e-5)).reshape(xf.shape)
        yr = yr * wf + bf
        return yr * jax.nn.sigmoid(yr)

    want = ref(x.astype(jnp.float32), w, b)
    np.testing.assert_allclose(
        np.asarray(y, np.float32), np.asarray(want), **_tol(dtype)
    )

    # grads of a scalar reduction agree with the composition's autodiff
    g_fused = jax.grad(
        lambda x, w, b: jnp.sum(
            group_norm(x, 2, w, b, act="silu").astype(jnp.float32) ** 2
        ),
        argnums=(0, 1, 2),
    )(x, w, b)
    g_ref = jax.grad(
        lambda x, w, b: jnp.sum(ref(x.astype(jnp.float32), w, b) ** 2),
        argnums=(0, 1, 2),
    )(x, w, b)
    for a, e in zip(g_fused, g_ref):
        np.testing.assert_allclose(
            np.asarray(a, np.float32), np.asarray(e, np.float32),
            **_tol(dtype),
        )


@pytest.mark.parametrize("dtype", DTYPES)
def test_group_norm_module_grad_dtypes(dtype):
    m = GroupNorm(num_groups=4, num_channels=16, act="silu")
    x = jax.random.normal(jax.random.PRNGKey(0), (2, 3, 3, 16), dtype)
    params = m.init(jax.random.PRNGKey(1), x)

    def loss(p):
        return jnp.sum(m.apply(p, x).astype(jnp.float32) ** 2)

    g = jax.grad(loss)(params)
    leaves = jax.tree_util.tree_leaves(g)
    assert leaves and all(l.dtype == jnp.float32 for l in leaves)
    assert all(bool(jnp.all(jnp.isfinite(l))) for l in leaves)


# ---------------------------------------------------------------------------
# groupbn (BatchNorm2d_NHWC + fused add/relu)
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("dtype", DTYPES)
@pytest.mark.parametrize("fuse_relu", [False, True])
def test_groupbn_value_and_grad(dtype, fuse_relu):
    m = BatchNorm2d_NHWC(8, fuse_relu=fuse_relu)
    x = jax.random.normal(jax.random.PRNGKey(0), (4, 3, 3, 8), dtype)
    z = jax.random.normal(jax.random.PRNGKey(1), (4, 3, 3, 8), dtype)
    variables = m.init(
        jax.random.PRNGKey(2), x, z, use_running_average=False
    )

    y, _ = m.apply(
        variables, x, z, use_running_average=False, mutable=["batch_stats"]
    )
    assert y.dtype == dtype

    xf, zf = x.astype(jnp.float32), z.astype(jnp.float32)
    mean = xf.mean(axis=(0, 1, 2))
    var = xf.var(axis=(0, 1, 2))
    want = (xf - mean) / jnp.sqrt(var + m.eps) + zf
    if fuse_relu:
        want = jax.nn.relu(want)
    np.testing.assert_allclose(
        np.asarray(y, np.float32), np.asarray(want), **_tol(dtype)
    )

    def loss(p):
        out, _ = m.apply(
            {"params": p, "batch_stats": variables["batch_stats"]},
            x, z, use_running_average=False, mutable=["batch_stats"],
        )
        return jnp.sum(out.astype(jnp.float32) ** 2)

    g = jax.grad(loss)(variables["params"])
    leaves = jax.tree_util.tree_leaves(g)
    assert leaves and all(bool(jnp.all(jnp.isfinite(l))) for l in leaves)


# ---------------------------------------------------------------------------
# focal_loss
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("dtype", DTYPES)
def test_sigmoid_focal_loss_value_and_grad(dtype):
    logits = jax.random.normal(jax.random.PRNGKey(0), (16, 4), dtype)
    targets = jax.random.bernoulli(
        jax.random.PRNGKey(1), 0.3, (16, 4)
    ).astype(jnp.float32)

    got = sigmoid_focal_loss(logits, targets, alpha=0.25, gamma=2.0)
    assert got.dtype == jnp.float32  # structurally f32

    lf = logits.astype(jnp.float32)
    p = jax.nn.sigmoid(lf)
    ce = -(targets * jnp.log(p) + (1 - targets) * jnp.log1p(-p))
    p_t = p * targets + (1 - p) * (1 - targets)
    a_t = 0.25 * targets + 0.75 * (1 - targets)
    want = a_t * (1 - p_t) ** 2.0 * ce
    np.testing.assert_allclose(
        np.asarray(got), np.asarray(want), atol=1e-4, rtol=1e-4
    )

    g = jax.grad(lambda l: jnp.sum(sigmoid_focal_loss(l, targets)))(logits)
    g_ref = jax.grad(
        lambda l: jnp.sum(
            0.25 * targets * (1 - jax.nn.sigmoid(l.astype(jnp.float32)))
            ** 2.0
            * -jnp.log(jax.nn.sigmoid(l.astype(jnp.float32)))
            + 0.75 * (1 - targets)
            * jax.nn.sigmoid(l.astype(jnp.float32)) ** 2.0
            * -jnp.log1p(-jax.nn.sigmoid(l.astype(jnp.float32)))
        )
    )(logits)
    np.testing.assert_allclose(
        np.asarray(g, np.float32), np.asarray(g_ref, np.float32),
        **_tol(dtype),
    )


@pytest.mark.parametrize("dtype", DTYPES)
def test_focal_loss_ignore_and_grad_finite(dtype):
    logits = jax.random.normal(jax.random.PRNGKey(0), (8, 5), dtype)
    targets = jnp.asarray([-1, 0, 1, 5, 2, 0, -1, 3])  # -1 ignored

    loss = focal_loss(logits, targets, num_positives_sum=4.0)
    assert bool(jnp.isfinite(loss))

    # ignored anchors contribute no gradient
    g = jax.grad(
        lambda l: focal_loss(l, targets, num_positives_sum=4.0)
    )(logits)
    gn = np.asarray(g, np.float32)
    assert np.all(gn[0] == 0.0) and np.all(gn[6] == 0.0)
    assert np.any(gn[1] != 0.0)
    assert np.all(np.isfinite(gn))


# ---------------------------------------------------------------------------
# index_mul_2d
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("dtype", DTYPES)
def test_index_mul_2d_value_and_scatter_grad(dtype):
    in1 = jax.random.normal(jax.random.PRNGKey(0), (6, 8), dtype)
    in2 = jax.random.normal(jax.random.PRNGKey(1), (5, 8), dtype)
    idx = jnp.asarray([0, 2, 2, 4, 1])  # repeated index 2

    y = index_mul_2d(in1, in2, idx)
    assert y.dtype == dtype
    np.testing.assert_allclose(
        np.asarray(y, np.float32),
        np.asarray(in1, np.float32)[np.asarray(idx)]
        * np.asarray(in2, np.float32),
        **_tol(dtype),
    )

    # scatter-add backward for repeated indices
    d_in1 = jax.grad(
        lambda a: jnp.sum(index_mul_2d(a, in2, idx).astype(jnp.float32))
    )(in1)
    d1 = np.asarray(d_in1, np.float32)
    want_row2 = np.asarray(in2, np.float32)[1] + np.asarray(in2, np.float32)[2]
    np.testing.assert_allclose(d1[2], want_row2, **_tol(dtype))
    np.testing.assert_allclose(d1[3], 0.0, atol=1e-6)  # unused row


# ---------------------------------------------------------------------------
# conv_bias_relu
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("dtype", DTYPES)
def test_conv_bias_relu_value_and_grad(dtype):
    x = jax.random.normal(jax.random.PRNGKey(0), (2, 5, 5, 3), dtype)
    w = jax.random.normal(jax.random.PRNGKey(1), (3, 3, 3, 4), dtype) * 0.2
    b = jnp.linspace(-0.1, 0.1, 4, dtype=dtype)
    mask = jax.random.bernoulli(jax.random.PRNGKey(2), 0.7, (2, 5, 5, 4))

    def ref(x, w, b):
        y = jax.lax.conv_general_dilated(
            x.astype(jnp.float32), w.astype(jnp.float32),
            window_strides=(1, 1), padding=((1, 1), (1, 1)),
            dimension_numbers=("NHWC", "HWIO", "NHWC"),
        )
        return y + b.astype(jnp.float32)

    for fused, reference in [
        (ConvBias(x, w, b), ref(x, w, b)),
        (ConvBiasReLU(x, w, b), jax.nn.relu(ref(x, w, b))),
        (ConvBiasMaskReLU(x, w, b, mask), jax.nn.relu(ref(x, w, b) * mask)),
    ]:
        assert fused.dtype == dtype
        np.testing.assert_allclose(
            np.asarray(fused, np.float32), np.asarray(reference, np.float32),
            **_tol(dtype),
        )

    g = jax.grad(
        lambda x, w, b: jnp.sum(
            ConvBiasReLU(x, w, b).astype(jnp.float32) ** 2
        ),
        argnums=(0, 1, 2),
    )(x, w, b)
    g_ref = jax.grad(
        lambda x, w, b: jnp.sum(jax.nn.relu(ref(x, w, b)) ** 2),
        argnums=(0, 1, 2),
    )(x, w, b)
    for a, e in zip(g, g_ref):
        assert a.dtype == dtype
        np.testing.assert_allclose(
            np.asarray(a, np.float32), np.asarray(e, np.float32),
            **_tol(dtype),
        )
