"""≙ tests/L0/run_transformer/test_p2p_comm.py +
test_pipeline_parallel_fwd_bwd.py + test_microbatches.py.

Golden: the pipelined loss/grads must equal a sequential (non-pipelined)
composition of the same stages on the same microbatches.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from apex_tpu import parallel_state as ps
from apex_tpu.transformer.microbatches import (
    ConstantNumMicroBatches,
    RampupBatchsizeNumMicroBatches,
)
from apex_tpu.transformer.pipeline_parallel import (
    forward_backward_no_pipelining,
    forward_backward_pipelining_with_interleaving,
    forward_backward_pipelining_without_interleaving,
    get_forward_backward_func,
    p2p_communication as p2p,
    split_batch_into_microbatches,
)

D, MB, NM = 8, 4, 6


def stage_fn(params, x):
    return jnp.tanh(x @ params["w"] + params["b"])


def loss_fn(y, t):
    return jnp.mean((y - t) ** 2)


def make_stages(n_stages, seed=0):
    rng = np.random.RandomState(seed)
    return {
        "w": jnp.asarray(
            rng.randn(n_stages, D, D) * 0.5, jnp.float32
        ),
        "b": jnp.asarray(rng.randn(n_stages, D) * 0.1, jnp.float32),
    }


def make_batch(seed=1):
    rng = np.random.RandomState(seed)
    inputs = jnp.asarray(rng.randn(NM, MB, D), jnp.float32)
    targets = jnp.asarray(rng.randn(NM, MB, D), jnp.float32)
    return inputs, targets


def sequential_reference(stacked, inputs, targets, n_stages):
    """Sequential mean loss over microbatches + grads wrt stacked params."""

    def mean_loss(stacked):
        def apply_all(x):
            for s in range(n_stages):
                p_s = jax.tree_util.tree_map(lambda v: v[s], stacked)
                x = stage_fn(p_s, x)
            return x

        losses = jax.vmap(lambda x, t: loss_fn(apply_all(x), t))(
            inputs, targets
        )
        return jnp.mean(losses), losses

    (_, losses), grads = jax.value_and_grad(mean_loss, has_aux=True)(stacked)
    return losses, grads


# ---------------------------------------------------------------------------
# p2p
# ---------------------------------------------------------------------------


def test_p2p_shifts(eight_devices):
    mesh = ps.initialize_model_parallel(1, 8)  # pp=8

    def f(x):
        fwd = p2p.send_forward_recv_forward(x)
        bwd = p2p.send_backward_recv_backward(x)
        return fwd[None], bwd[None]

    x = jnp.arange(8.0)
    fwd, bwd = jax.jit(
        jax.shard_map(
            f, mesh=mesh, in_specs=(P("pp"),), out_specs=P("pp"),
            check_vma=False,
        )
    )(x)
    # forward shift: rank r receives value from r-1; rank 0 gets zeros
    np.testing.assert_allclose(
        np.asarray(fwd).ravel(), [0, 0, 1, 2, 3, 4, 5, 6]
    )
    np.testing.assert_allclose(
        np.asarray(bwd).ravel(), [1, 2, 3, 4, 5, 6, 7, 0]
    )


# ---------------------------------------------------------------------------
# schedules
# ---------------------------------------------------------------------------


def test_no_pipelining_matches_sequential():
    stacked = make_stages(1)
    inputs, targets = make_batch()
    losses, grads = forward_backward_no_pipelining(
        stage_fn,
        loss_fn,
        jax.tree_util.tree_map(lambda v: v[0], stacked),
        (inputs, targets),
        num_microbatches=NM,
    )
    ref_losses, ref_grads = sequential_reference(stacked, inputs, targets, 1)
    np.testing.assert_allclose(
        np.asarray(losses), np.asarray(ref_losses), rtol=1e-5
    )
    np.testing.assert_allclose(
        np.asarray(grads["w"]),
        np.asarray(ref_grads["w"][0]),
        rtol=1e-4,
        atol=1e-6,
    )


@pytest.mark.parametrize("remat", [True, False])
def test_1f1b_matches_sequential(eight_devices, remat):
    pp = 4
    mesh = ps.initialize_model_parallel(1, pp)  # dp=2 unused, pp=4
    stacked = make_stages(pp)
    inputs, targets = make_batch()

    def run(stacked_local, inputs, targets):
        params = jax.tree_util.tree_map(lambda v: v[0], stacked_local)
        losses, grads = forward_backward_pipelining_without_interleaving(
            stage_fn,
            loss_fn,
            params,
            (inputs, targets),
            num_microbatches=NM,
            remat=remat,
        )
        grads = jax.tree_util.tree_map(lambda v: v[None], grads)
        return losses, grads

    losses, grads = jax.jit(
        jax.shard_map(
            run,
            mesh=mesh,
            in_specs=(P("pp"), P(), P()),
            out_specs=(P(), P("pp")),
            check_vma=False,
        )
    )(stacked, inputs, targets)

    ref_losses, ref_grads = sequential_reference(stacked, inputs, targets, pp)
    np.testing.assert_allclose(
        np.asarray(losses), np.asarray(ref_losses), rtol=1e-4, atol=1e-6
    )
    for k in ("w", "b"):
        np.testing.assert_allclose(
            np.asarray(grads[k]),
            np.asarray(ref_grads[k]),
            rtol=1e-4,
            atol=1e-5,
        )


def test_1f1b_forward_only(eight_devices):
    pp = 4
    mesh = ps.initialize_model_parallel(1, pp)
    stacked = make_stages(pp)
    inputs, targets = make_batch()

    def run(stacked_local, inputs, targets):
        params = jax.tree_util.tree_map(lambda v: v[0], stacked_local)
        losses, grads = forward_backward_pipelining_without_interleaving(
            stage_fn, loss_fn, params, (inputs, targets),
            num_microbatches=NM, forward_only=True,
        )
        assert grads is None
        return losses

    losses = jax.jit(
        jax.shard_map(
            run, mesh=mesh, in_specs=(P("pp"), P(), P()), out_specs=P(),
            check_vma=False,
        )
    )(stacked, inputs, targets)
    ref_losses, _ = sequential_reference(stacked, inputs, targets, pp)
    np.testing.assert_allclose(
        np.asarray(losses), np.asarray(ref_losses), rtol=1e-4, atol=1e-6
    )


def test_interleaved_matches_sequential(eight_devices):
    pp, vpp = 2, 2
    n_virtual = pp * vpp
    mesh = ps.initialize_model_parallel(1, pp)
    stacked = make_stages(n_virtual)  # ordered by virtual stage
    inputs, targets = make_batch()
    # rank r holds chunks k at virtual stage k*pp + r:
    # reshape (n_virtual, ...) -> (vpp, pp, ...), shard dim 1 over pp
    regrouped = jax.tree_util.tree_map(
        lambda v: v.reshape(vpp, pp, *v.shape[1:]), stacked
    )

    def run(local, inputs, targets):
        params = jax.tree_util.tree_map(lambda v: v[:, 0], local)  # (vpp,...)
        losses, grads = forward_backward_pipelining_with_interleaving(
            stage_fn,
            loss_fn,
            params,
            (inputs, targets),
            num_microbatches=NM,
            num_model_chunks=vpp,
        )
        grads = jax.tree_util.tree_map(lambda v: v[:, None], grads)
        return losses, grads

    losses, grads = jax.jit(
        jax.shard_map(
            run,
            mesh=mesh,
            in_specs=(P(None, "pp"), P(), P()),
            out_specs=(P(), P(None, "pp")),
            check_vma=False,
        )
    )(regrouped, inputs, targets)

    ref_losses, ref_grads = sequential_reference(
        stacked, inputs, targets, n_virtual
    )
    np.testing.assert_allclose(
        np.asarray(losses), np.asarray(ref_losses), rtol=1e-4, atol=1e-6
    )
    for k in ("w", "b"):
        got = np.asarray(grads[k]).reshape(n_virtual, *stacked[k].shape[1:])
        np.testing.assert_allclose(
            got, np.asarray(ref_grads[k]), rtol=1e-4, atol=1e-5
        )


@pytest.mark.parametrize("carry_chunk", [1, 3, 4, 100])
def test_1f1b_carry_chunk_matches_sequential(eight_devices, carry_chunk):
    """The two-level (checkpointed) tick scan is numerics-identical to the
    flat scan for any chunk size, including non-dividing and oversized."""
    pp = 4
    mesh = ps.initialize_model_parallel(1, pp)
    stacked = make_stages(pp)
    inputs, targets = make_batch()

    def run(stacked_local, inputs, targets):
        params = jax.tree_util.tree_map(lambda v: v[0], stacked_local)
        losses, grads = forward_backward_pipelining_without_interleaving(
            stage_fn, loss_fn, params, (inputs, targets),
            num_microbatches=NM, carry_chunk=carry_chunk,
        )
        grads = jax.tree_util.tree_map(lambda v: v[None], grads)
        return losses, grads

    losses, grads = jax.jit(
        jax.shard_map(
            run, mesh=mesh, in_specs=(P("pp"), P(), P()),
            out_specs=(P(), P("pp")), check_vma=False,
        )
    )(stacked, inputs, targets)
    ref_losses, ref_grads = sequential_reference(stacked, inputs, targets, pp)
    np.testing.assert_allclose(
        np.asarray(losses), np.asarray(ref_losses), rtol=1e-4, atol=1e-6
    )
    for k in ("w", "b"):
        np.testing.assert_allclose(
            np.asarray(grads[k]), np.asarray(ref_grads[k]),
            rtol=1e-4, atol=1e-5,
        )


def test_1f1b_carry_chunk_bounds_memory(eight_devices):
    """At large nm, carry_chunk≈√ticks must cut XLA's temp memory vs the
    flat scan (the O(nm) carry slope measured in docs/pipeline-schedules)."""
    pp, nm, d = 2, 64, 64
    mesh = ps.initialize_model_parallel(1, pp)
    rng = np.random.RandomState(0)
    stacked = {
        "w": jnp.asarray(rng.randn(pp, d, d) * 0.2, jnp.float32),
        "b": jnp.asarray(rng.randn(pp, d) * 0.1, jnp.float32),
    }
    inputs = jnp.asarray(rng.randn(nm, 8, d), jnp.float32)
    targets = jnp.asarray(rng.randn(nm, 8, d), jnp.float32)

    def make(chunk):
        def run(stacked_local, inputs, targets):
            params = jax.tree_util.tree_map(lambda v: v[0], stacked_local)
            losses, grads = forward_backward_pipelining_without_interleaving(
                stage_fn, loss_fn, params, (inputs, targets),
                num_microbatches=nm, carry_chunk=chunk,
            )
            return losses, jax.tree_util.tree_map(lambda v: v[None], grads)

        return jax.jit(
            jax.shard_map(
                run, mesh=mesh, in_specs=(P("pp"), P(), P()),
                out_specs=(P(), P("pp")), check_vma=False,
            )
        )

    def temp_bytes(f):
        m = f.lower(stacked, inputs, targets).compile().memory_analysis()
        return m.temp_size_in_bytes

    flat, chunked = temp_bytes(make(None)), temp_bytes(make(8))
    assert chunked < flat, (flat, chunked)


@pytest.mark.parametrize("pp,vpp,nm", [(2, 3, 4), (4, 2, 8), (2, 2, 2)])
def test_interleaved_matches_sequential_configs(eight_devices, pp, vpp, nm):
    n_virtual = pp * vpp
    mesh = ps.initialize_model_parallel(1, pp)
    stacked = make_stages(n_virtual, seed=pp * 10 + vpp)
    rng = np.random.RandomState(2)
    inputs = jnp.asarray(rng.randn(nm, MB, D), jnp.float32)
    targets = jnp.asarray(rng.randn(nm, MB, D), jnp.float32)
    regrouped = jax.tree_util.tree_map(
        lambda v: v.reshape(vpp, pp, *v.shape[1:]), stacked
    )

    def run(local, inputs, targets):
        params = jax.tree_util.tree_map(lambda v: v[:, 0], local)
        losses, grads = forward_backward_pipelining_with_interleaving(
            stage_fn, loss_fn, params, (inputs, targets),
            num_microbatches=nm, num_model_chunks=vpp,
        )
        grads = jax.tree_util.tree_map(lambda v: v[:, None], grads)
        return losses, grads

    losses, grads = jax.jit(
        jax.shard_map(
            run, mesh=mesh,
            in_specs=(P(None, "pp"), P(), P()),
            out_specs=(P(), P(None, "pp")),
            check_vma=False,
        )
    )(regrouped, inputs, targets)

    ref_losses, ref_grads = sequential_reference(
        stacked, inputs, targets, n_virtual
    )
    np.testing.assert_allclose(
        np.asarray(losses), np.asarray(ref_losses), rtol=1e-4, atol=1e-6
    )
    for k in ("w", "b"):
        got = np.asarray(grads[k]).reshape(n_virtual, *stacked[k].shape[1:])
        np.testing.assert_allclose(
            got, np.asarray(ref_grads[k]), rtol=1e-4, atol=1e-5
        )


def test_1f1b_remat_policy_dots_matches_sequential(eight_devices):
    """remat_policy='dots' (selective recompute) is numerics-identical."""
    pp = 2
    mesh = ps.initialize_model_parallel(1, pp)
    stacked = make_stages(pp)
    inputs, targets = make_batch()

    def run(stacked_local, inputs, targets):
        params = jax.tree_util.tree_map(lambda v: v[0], stacked_local)
        losses, grads = forward_backward_pipelining_without_interleaving(
            stage_fn, loss_fn, params, (inputs, targets),
            num_microbatches=NM, remat=True, remat_policy="dots",
        )
        return losses, jax.tree_util.tree_map(lambda v: v[None], grads)

    losses, grads = jax.jit(
        jax.shard_map(
            run, mesh=mesh, in_specs=(P("pp"), P(), P()),
            out_specs=(P(), P("pp")), check_vma=False,
        )
    )(stacked, inputs, targets)
    ref_losses, ref_grads = sequential_reference(stacked, inputs, targets, pp)
    np.testing.assert_allclose(
        np.asarray(losses), np.asarray(ref_losses), rtol=1e-4, atol=1e-6
    )
    for k in ("w", "b"):
        np.testing.assert_allclose(
            np.asarray(grads[k]), np.asarray(ref_grads[k]),
            rtol=1e-4, atol=1e-5,
        )


def test_1f1b_loss_takes_params_matches_sequential(eight_devices):
    """loss_fn(stage_params, y, t): the LAST stage's params get loss-side
    gradients (Megatron post-process head pattern) — golden = sequential
    composition applying the same head."""
    pp = 4
    mesh = ps.initialize_model_parallel(1, pp)
    stacked = make_stages(pp)
    inputs, targets = make_batch()

    def head_loss(p, y, t):
        return jnp.mean((y + p["b"] - t) ** 2)

    def run(stacked_local, inputs, targets):
        params = jax.tree_util.tree_map(lambda v: v[0], stacked_local)
        losses, grads = forward_backward_pipelining_without_interleaving(
            stage_fn, head_loss, params, (inputs, targets),
            num_microbatches=NM, loss_takes_params=True,
        )
        grads = jax.tree_util.tree_map(lambda v: v[None], grads)
        return losses, grads

    losses, grads = jax.jit(
        jax.shard_map(
            run, mesh=mesh, in_specs=(P("pp"), P(), P()),
            out_specs=(P(), P("pp")), check_vma=False,
        )
    )(stacked, inputs, targets)

    def seq_loss(stacked):
        def one(x, t):
            for s in range(pp):
                p_s = jax.tree_util.tree_map(lambda v: v[s], stacked)
                x = stage_fn(p_s, x)
            p_last = jax.tree_util.tree_map(lambda v: v[pp - 1], stacked)
            return head_loss(p_last, x, t)

        losses = jax.vmap(one)(inputs, targets)
        return jnp.mean(losses), losses

    (_, ref_losses), ref_grads = jax.value_and_grad(
        seq_loss, has_aux=True
    )(stacked)
    np.testing.assert_allclose(
        np.asarray(losses), np.asarray(ref_losses), rtol=1e-4, atol=1e-6
    )
    for k in ("w", "b"):
        np.testing.assert_allclose(
            np.asarray(grads[k]), np.asarray(ref_grads[k]),
            rtol=1e-4, atol=1e-5,
        )
    # the head path is exercised: last stage's b-grad differs from a
    # pure-MSE run (the loss adds b directly)
    assert not np.allclose(np.asarray(grads["b"][-1]), 0.0)


# ---------------------------------------------------------------------------
# hand-scheduled 1F1B (explicit stash ring, manually reversed permutes)
# ---------------------------------------------------------------------------


def _run_hand_1f1b(mesh, stacked, inputs, targets, nm, **kw):
    from apex_tpu.transformer.pipeline_parallel import (
        forward_backward_pipelining_1f1b,
    )

    def run(stacked_local, inputs, targets):
        params = jax.tree_util.tree_map(lambda v: v[0], stacked_local)
        losses, grads = forward_backward_pipelining_1f1b(
            stage_fn, loss_fn, params, (inputs, targets),
            num_microbatches=nm, **kw,
        )
        grads = jax.tree_util.tree_map(lambda v: v[None], grads)
        return losses, grads

    return jax.jit(
        jax.shard_map(
            run, mesh=mesh, in_specs=(P("pp"), P(), P()),
            out_specs=(P(), P("pp")), check_vma=False,
        )
    )(stacked, inputs, targets)


@pytest.mark.parametrize("stash", ["residuals", "input"])
@pytest.mark.parametrize("pp", [4, 8])
def test_hand_1f1b_matches_sequential(eight_devices, stash, pp):
    """The manual schedule (grads computed inside ONE forward scan, no
    autodiff over the tick loop) reproduces the sequential golden for
    both stash modes, at nm > pp and nm < pp."""
    mesh = ps.initialize_model_parallel(1, pp)
    stacked = make_stages(pp)
    inputs, targets = make_batch()
    losses, grads = _run_hand_1f1b(
        mesh, stacked, inputs, targets, NM, stash=stash
    )
    ref_losses, ref_grads = sequential_reference(stacked, inputs, targets, pp)
    np.testing.assert_allclose(
        np.asarray(losses), np.asarray(ref_losses), rtol=1e-4, atol=1e-6
    )
    for k in ("w", "b"):
        np.testing.assert_allclose(
            np.asarray(grads[k]), np.asarray(ref_grads[k]),
            rtol=1e-4, atol=1e-5,
        )


def test_hand_1f1b_residuals_with_remat_policy(eight_devices):
    """stash="residuals" composes with a checkpoint policy: the policy
    bounds what the ring holds (saved names + inputs) and numerics are
    unchanged."""
    pp = 4
    mesh = ps.initialize_model_parallel(1, pp)
    stacked = make_stages(pp)
    inputs, targets = make_batch()
    losses, grads = _run_hand_1f1b(
        mesh, stacked, inputs, targets, NM,
        stash="residuals", remat=True, remat_policy="dots",
    )
    ref_losses, ref_grads = sequential_reference(stacked, inputs, targets, pp)
    np.testing.assert_allclose(
        np.asarray(losses), np.asarray(ref_losses), rtol=1e-4, atol=1e-6
    )
    for k in ("w", "b"):
        np.testing.assert_allclose(
            np.asarray(grads[k]), np.asarray(ref_grads[k]),
            rtol=1e-4, atol=1e-5,
        )


def test_hand_1f1b_loss_takes_params(eight_devices):
    """Megatron post-process head pattern through the manual loss lane:
    the last stage's params receive loss-side gradients."""
    from apex_tpu.transformer.pipeline_parallel import (
        forward_backward_pipelining_1f1b,
    )

    pp = 4
    mesh = ps.initialize_model_parallel(1, pp)
    stacked = make_stages(pp)
    inputs, targets = make_batch()

    def head_loss(p, y, t):
        return jnp.mean((y + p["b"] - t) ** 2)

    def run(stacked_local, inputs, targets):
        params = jax.tree_util.tree_map(lambda v: v[0], stacked_local)
        losses, grads = forward_backward_pipelining_1f1b(
            stage_fn, head_loss, params, (inputs, targets),
            num_microbatches=NM, loss_takes_params=True,
        )
        grads = jax.tree_util.tree_map(lambda v: v[None], grads)
        return losses, grads

    losses, grads = jax.jit(
        jax.shard_map(
            run, mesh=mesh, in_specs=(P("pp"), P(), P()),
            out_specs=(P(), P("pp")), check_vma=False,
        )
    )(stacked, inputs, targets)

    def seq_loss(stacked):
        def one(x, t):
            for s in range(pp):
                p_s = jax.tree_util.tree_map(lambda v: v[s], stacked)
                x = stage_fn(p_s, x)
            p_last = jax.tree_util.tree_map(lambda v: v[pp - 1], stacked)
            return head_loss(p_last, x, t)

        losses = jax.vmap(one)(inputs, targets)
        return jnp.mean(losses), losses

    (_, ref_losses), ref_grads = jax.value_and_grad(
        seq_loss, has_aux=True
    )(stacked)
    np.testing.assert_allclose(
        np.asarray(losses), np.asarray(ref_losses), rtol=1e-4, atol=1e-6
    )
    for k in ("w", "b"):
        np.testing.assert_allclose(
            np.asarray(grads[k]), np.asarray(ref_grads[k]),
            rtol=1e-4, atol=1e-5,
        )
    assert not np.allclose(np.asarray(grads["b"][-1]), 0.0)


@pytest.mark.slow
@pytest.mark.parametrize("seed", range(6))
def test_hand_1f1b_config_fuzz(eight_devices, seed):
    """Seeded (pp, nm, stash, remat, head) draws — including nm=1 (pure
    warmup/cooldown) and nm < pp — hand schedule vs the lockstep golden
    on identical params/inputs (losses AND grads)."""
    from apex_tpu.transformer.pipeline_parallel import (
        forward_backward_pipelining_1f1b,
    )

    rng = np.random.RandomState(4321 + seed)
    pp = int(rng.choice([2, 4, 8]))
    # seed 0 pins nm=1 (pure warmup/cooldown), seed 1 pins nm < pp;
    # the rest draw freely
    if seed == 0:
        nm = 1
    elif seed == 1:
        pp, nm = 8, int(rng.randint(2, 8))
    else:
        nm = int(rng.randint(1, 9))
    stash = str(rng.choice(["residuals", "input"]))
    remat = bool(rng.randint(0, 2)) and stash == "residuals"
    takes_params = bool(rng.randint(0, 2))
    desc = f"pp={pp} nm={nm} stash={stash} remat={remat} head={takes_params}"

    mesh = ps.initialize_model_parallel(1, pp)
    stacked = make_stages(pp, seed=seed)
    inputs = jnp.asarray(rng.randn(nm, MB, D), jnp.float32)
    targets = jnp.asarray(rng.randn(nm, MB, D), jnp.float32)

    if takes_params:
        def lfn(p, y, t):
            return jnp.mean((y + p["b"] - t) ** 2)
    else:
        lfn = loss_fn

    def run(schedule, **kw):
        def body(stacked_local, inputs, targets):
            params = jax.tree_util.tree_map(lambda v: v[0], stacked_local)
            losses, grads = schedule(
                stage_fn, lfn, params, (inputs, targets),
                num_microbatches=nm, loss_takes_params=takes_params, **kw
            )
            return losses, jax.tree_util.tree_map(lambda v: v[None], grads)

        return jax.jit(
            jax.shard_map(
                body, mesh=mesh, in_specs=(P("pp"), P(), P()),
                out_specs=(P(), P("pp")), check_vma=False,
            )
        )(stacked, inputs, targets)

    losses, grads = run(
        forward_backward_pipelining_1f1b, stash=stash,
        remat=remat, remat_policy="dots" if remat else None,
    )
    ref_losses, ref_grads = run(
        forward_backward_pipelining_without_interleaving, remat=False
    )
    np.testing.assert_allclose(
        np.asarray(losses), np.asarray(ref_losses),
        rtol=1e-5, atol=1e-7, err_msg=desc,
    )
    for k in ("w", "b"):
        np.testing.assert_allclose(
            np.asarray(grads[k]), np.asarray(ref_grads[k]),
            rtol=1e-4, atol=1e-6, err_msg=desc,
        )


def test_hand_1f1b_forward_only(eight_devices):
    from apex_tpu.transformer.pipeline_parallel import (
        forward_backward_pipelining_1f1b,
    )

    pp = 4
    mesh = ps.initialize_model_parallel(1, pp)
    stacked = make_stages(pp)
    inputs, targets = make_batch()

    def run(stacked_local, inputs, targets):
        params = jax.tree_util.tree_map(lambda v: v[0], stacked_local)
        losses, grads = forward_backward_pipelining_1f1b(
            stage_fn, loss_fn, params, (inputs, targets),
            num_microbatches=NM, forward_only=True,
        )
        assert grads is None
        return losses

    losses = jax.jit(
        jax.shard_map(
            run, mesh=mesh, in_specs=(P("pp"), P(), P()), out_specs=P(),
            check_vma=False,
        )
    )(stacked, inputs, targets)
    ref_losses, _ = sequential_reference(stacked, inputs, targets, pp)
    np.testing.assert_allclose(
        np.asarray(losses), np.asarray(ref_losses), rtol=1e-4, atol=1e-6
    )


# ---------------------------------------------------------------------------
# hand-scheduled interleaved 1F1B (chunk stash ring, three lockstep phases)
# ---------------------------------------------------------------------------


def _run_hand_interleaved(mesh, pp, vpp, stacked, inputs, targets, nm, **kw):
    from apex_tpu.transformer.pipeline_parallel import (
        forward_backward_pipelining_interleaved_1f1b,
    )

    regrouped = jax.tree_util.tree_map(
        lambda v: v.reshape(vpp, pp, *v.shape[1:]), stacked
    )

    def run(local, inputs, targets):
        params = jax.tree_util.tree_map(lambda v: v[:, 0], local)
        losses, grads = forward_backward_pipelining_interleaved_1f1b(
            stage_fn, loss_fn, params, (inputs, targets),
            num_microbatches=nm, num_model_chunks=vpp, **kw,
        )
        grads = jax.tree_util.tree_map(lambda v: v[:, None], grads)
        return losses, grads

    return jax.jit(
        jax.shard_map(
            run, mesh=mesh, in_specs=(P(None, "pp"), P(), P()),
            out_specs=(P(), P(None, "pp")), check_vma=False,
        )
    )(regrouped, inputs, targets)


@pytest.mark.parametrize("stash", ["residuals", "input"])
def test_hand_interleaved_matches_sequential(eight_devices, stash):
    """The hand interleaved schedule (chunk-granular stash ring, three
    lockstep phases, grads computed with no autodiff over the tick
    loop) reproduces the sequential golden for both stash modes."""
    pp, vpp, nm = 2, 2, 6
    n_virtual = pp * vpp
    mesh = ps.initialize_model_parallel(1, pp)
    stacked = make_stages(n_virtual)
    inputs, targets = make_batch()
    losses, grads = _run_hand_interleaved(
        mesh, pp, vpp, stacked, inputs, targets, nm, stash=stash
    )
    ref_losses, ref_grads = sequential_reference(
        stacked, inputs, targets, n_virtual
    )
    np.testing.assert_allclose(
        np.asarray(losses), np.asarray(ref_losses), rtol=1e-4, atol=1e-6
    )
    for k in ("w", "b"):
        got = np.asarray(grads[k]).reshape(n_virtual, *stacked[k].shape[1:])
        np.testing.assert_allclose(
            got, np.asarray(ref_grads[k]), rtol=1e-4, atol=1e-5
        )


@pytest.mark.slow
def test_hand_interleaved_deep_virtual_pipe(eight_devices):
    """pp=4, vpp=2 (8 virtual stages): warmup/cooldown span V-1=7 chunk
    ticks and the ring wraps its full 2V-1 window."""
    pp, vpp, nm = 4, 2, 8
    n_virtual = pp * vpp
    mesh = ps.initialize_model_parallel(1, pp)
    stacked = make_stages(n_virtual, seed=11)
    rng = np.random.RandomState(12)
    inputs = jnp.asarray(rng.randn(nm, MB, D), jnp.float32)
    targets = jnp.asarray(rng.randn(nm, MB, D), jnp.float32)
    losses, grads = _run_hand_interleaved(
        mesh, pp, vpp, stacked, inputs, targets, nm, stash="residuals"
    )
    ref_losses, ref_grads = sequential_reference(
        stacked, inputs, targets, n_virtual
    )
    np.testing.assert_allclose(
        np.asarray(losses), np.asarray(ref_losses), rtol=1e-4, atol=1e-6
    )
    for k in ("w", "b"):
        got = np.asarray(grads[k]).reshape(n_virtual, *stacked[k].shape[1:])
        np.testing.assert_allclose(
            got, np.asarray(ref_grads[k]), rtol=1e-4, atol=1e-5
        )


def test_hand_interleaved_loss_takes_params(eight_devices):
    """Megatron post-process head: loss-side grads land on the LAST
    model chunk (index vpp-1) of the last rank via the scatter lane."""
    from apex_tpu.transformer.pipeline_parallel import (
        forward_backward_pipelining_interleaved_1f1b,
    )

    pp, vpp, nm = 2, 2, 4
    n_virtual = pp * vpp
    mesh = ps.initialize_model_parallel(1, pp)
    stacked = make_stages(n_virtual)
    rng = np.random.RandomState(2)
    inputs = jnp.asarray(rng.randn(nm, MB, D), jnp.float32)
    targets = jnp.asarray(rng.randn(nm, MB, D), jnp.float32)
    regrouped = jax.tree_util.tree_map(
        lambda v: v.reshape(vpp, pp, *v.shape[1:]), stacked
    )

    def head_loss(p, y, t):
        return jnp.mean((y + p["b"] - t) ** 2)

    def run(local, inputs, targets):
        params = jax.tree_util.tree_map(lambda v: v[:, 0], local)
        losses, grads = forward_backward_pipelining_interleaved_1f1b(
            stage_fn, head_loss, params, (inputs, targets),
            num_microbatches=nm, num_model_chunks=vpp,
            loss_takes_params=True,
        )
        return losses, jax.tree_util.tree_map(lambda v: v[:, None], grads)

    losses, grads = jax.jit(
        jax.shard_map(
            run, mesh=mesh, in_specs=(P(None, "pp"), P(), P()),
            out_specs=(P(), P(None, "pp")), check_vma=False,
        )
    )(regrouped, inputs, targets)

    def seq_loss(stacked):
        def one(x, t):
            for s in range(n_virtual):
                p_s = jax.tree_util.tree_map(lambda v: v[s], stacked)
                x = stage_fn(p_s, x)
            p_last = jax.tree_util.tree_map(
                lambda v: v[n_virtual - 1], stacked
            )
            return head_loss(p_last, x, t)

        losses = jax.vmap(one)(inputs, targets)
        return jnp.mean(losses), losses

    (_, ref_losses), ref_grads = jax.value_and_grad(
        seq_loss, has_aux=True
    )(stacked)
    np.testing.assert_allclose(
        np.asarray(losses), np.asarray(ref_losses), rtol=1e-4, atol=1e-6
    )
    for k in ("w", "b"):
        got = np.asarray(grads[k]).reshape(n_virtual, *stacked[k].shape[1:])
        np.testing.assert_allclose(
            got, np.asarray(ref_grads[k]), rtol=1e-4, atol=1e-5
        )
    # head grads reached the last VIRTUAL stage's b
    assert not np.allclose(got[-1], 0.0)


@pytest.mark.slow
@pytest.mark.parametrize("seed", range(4))
def test_hand_interleaved_config_fuzz(eight_devices, seed):
    """Seeded (pp, vpp, nm, stash, remat, head) draws — hand interleaved
    vs the lockstep interleaved golden on identical params/inputs
    (losses AND grads).  Includes nm=pp (minimal steady phase) and
    vpp=1 (reduces to plain 1F1B with three phases)."""
    from apex_tpu.transformer.pipeline_parallel import (
        forward_backward_pipelining_interleaved_1f1b,
    )

    rng = np.random.RandomState(97 + seed)
    if seed == 0:
        pp, vpp, nm = 2, 3, 2       # nm == pp: minimal steady phase
    elif seed == 1:
        pp, vpp, nm = 4, 1, 8       # vpp=1 degenerate
    else:
        pp = int(rng.choice([2, 4]))
        vpp = int(rng.choice([2, 3, 4]))
        nm = pp * int(rng.randint(1, 4))
    stash = str(rng.choice(["residuals", "input"]))
    remat = bool(rng.randint(0, 2)) and stash == "residuals"
    takes_params = bool(rng.randint(0, 2))
    desc = (
        f"pp={pp} vpp={vpp} nm={nm} stash={stash} remat={remat} "
        f"head={takes_params}"
    )
    n_virtual = pp * vpp
    mesh = ps.initialize_model_parallel(1, pp)
    stacked = make_stages(n_virtual, seed=seed)
    inputs = jnp.asarray(rng.randn(nm, MB, D), jnp.float32)
    targets = jnp.asarray(rng.randn(nm, MB, D), jnp.float32)
    regrouped = jax.tree_util.tree_map(
        lambda v: v.reshape(vpp, pp, *v.shape[1:]), stacked
    )

    if takes_params:
        def lfn(p, y, t):
            return jnp.mean((y + p["b"] - t) ** 2)
    else:
        lfn = loss_fn

    def run(schedule, **kw):
        def body(local, inputs, targets):
            params = jax.tree_util.tree_map(lambda v: v[:, 0], local)
            losses, grads = schedule(
                stage_fn, lfn, params, (inputs, targets),
                num_microbatches=nm, num_model_chunks=vpp,
                loss_takes_params=takes_params, **kw,
            )
            return losses, jax.tree_util.tree_map(
                lambda v: v[:, None], grads
            )

        return jax.jit(
            jax.shard_map(
                body, mesh=mesh, in_specs=(P(None, "pp"), P(), P()),
                out_specs=(P(), P(None, "pp")), check_vma=False,
            )
        )(regrouped, inputs, targets)

    losses, grads = run(
        forward_backward_pipelining_interleaved_1f1b, stash=stash,
        remat=remat, remat_policy="dots" if remat else None,
    )
    ref_losses, ref_grads = run(
        forward_backward_pipelining_with_interleaving, remat=False
    )
    np.testing.assert_allclose(
        np.asarray(losses), np.asarray(ref_losses),
        rtol=1e-5, atol=1e-7, err_msg=desc,
    )
    for k in ("w", "b"):
        np.testing.assert_allclose(
            np.asarray(grads[k]), np.asarray(ref_grads[k]),
            rtol=1e-4, atol=1e-6, err_msg=desc,
        )


def test_hand_interleaved_forward_only(eight_devices):
    from apex_tpu.transformer.pipeline_parallel import (
        forward_backward_pipelining_interleaved_1f1b,
    )

    pp, vpp, nm = 2, 2, 6
    n_virtual = pp * vpp
    mesh = ps.initialize_model_parallel(1, pp)
    stacked = make_stages(n_virtual)
    inputs, targets = make_batch()
    regrouped = jax.tree_util.tree_map(
        lambda v: v.reshape(vpp, pp, *v.shape[1:]), stacked
    )

    def run(local, inputs, targets):
        params = jax.tree_util.tree_map(lambda v: v[:, 0], local)
        losses, grads = forward_backward_pipelining_interleaved_1f1b(
            stage_fn, loss_fn, params, (inputs, targets),
            num_microbatches=nm, num_model_chunks=vpp, forward_only=True,
        )
        assert grads is None
        return losses

    losses = jax.jit(
        jax.shard_map(
            run, mesh=mesh, in_specs=(P(None, "pp"), P(), P()),
            out_specs=P(), check_vma=False,
        )
    )(regrouped, inputs, targets)
    ref_losses, _ = sequential_reference(
        stacked, inputs, targets, n_virtual
    )
    np.testing.assert_allclose(
        np.asarray(losses), np.asarray(ref_losses), rtol=1e-4, atol=1e-6
    )


def test_hand_interleaved_rejects_indivisible_microbatches(eight_devices):
    from apex_tpu.transformer.pipeline_parallel import (
        forward_backward_pipelining_interleaved_1f1b,
    )

    pp, vpp = 2, 2
    mesh = ps.initialize_model_parallel(1, pp)
    stacked = make_stages(pp * vpp)
    rng = np.random.RandomState(3)
    inputs = jnp.asarray(rng.randn(3, MB, D), jnp.float32)
    targets = jnp.asarray(rng.randn(3, MB, D), jnp.float32)
    regrouped = jax.tree_util.tree_map(
        lambda v: v.reshape(vpp, pp, *v.shape[1:]), stacked
    )

    def run(local, inputs, targets):
        params = jax.tree_util.tree_map(lambda v: v[:, 0], local)
        losses, _ = forward_backward_pipelining_interleaved_1f1b(
            stage_fn, loss_fn, params, (inputs, targets),
            num_microbatches=3, num_model_chunks=vpp,
        )
        return losses

    with pytest.raises(ValueError, match="multiple of pipeline"):
        jax.jit(
            jax.shard_map(
                run, mesh=mesh,
                in_specs=(P(None, "pp"), P(), P()), out_specs=P(),
                check_vma=False,
            )
        )(regrouped, inputs, targets)


@pytest.mark.parametrize("carry_chunk", [2, 5, 100])
def test_interleaved_carry_chunk_matches_sequential(
    eight_devices, carry_chunk
):
    """Chunked tick scan on the interleaved schedule: numerics identical
    for dividing, non-dividing, and oversized chunk sizes."""
    pp, vpp, nm = 2, 2, 4
    n_virtual = pp * vpp
    mesh = ps.initialize_model_parallel(1, pp)
    stacked = make_stages(n_virtual, seed=9)
    rng = np.random.RandomState(4)
    inputs = jnp.asarray(rng.randn(nm, MB, D), jnp.float32)
    targets = jnp.asarray(rng.randn(nm, MB, D), jnp.float32)
    regrouped = jax.tree_util.tree_map(
        lambda v: v.reshape(vpp, pp, *v.shape[1:]), stacked
    )

    def run(local, inputs, targets):
        params = jax.tree_util.tree_map(lambda v: v[:, 0], local)
        losses, grads = forward_backward_pipelining_with_interleaving(
            stage_fn, loss_fn, params, (inputs, targets),
            num_microbatches=nm, num_model_chunks=vpp,
            carry_chunk=carry_chunk,
        )
        grads = jax.tree_util.tree_map(lambda v: v[:, None], grads)
        return losses, grads

    losses, grads = jax.jit(
        jax.shard_map(
            run, mesh=mesh,
            in_specs=(P(None, "pp"), P(), P()),
            out_specs=(P(), P(None, "pp")),
            check_vma=False,
        )
    )(regrouped, inputs, targets)
    ref_losses, ref_grads = sequential_reference(
        stacked, inputs, targets, n_virtual
    )
    np.testing.assert_allclose(
        np.asarray(losses), np.asarray(ref_losses), rtol=1e-4, atol=1e-6
    )
    for k in ("w", "b"):
        got = np.asarray(grads[k]).reshape(n_virtual, *stacked[k].shape[1:])
        np.testing.assert_allclose(
            got, np.asarray(ref_grads[k]), rtol=1e-4, atol=1e-5
        )


def test_interleaved_rejects_indivisible_microbatches(eight_devices):
    pp, vpp = 2, 2
    mesh = ps.initialize_model_parallel(1, pp)
    stacked = make_stages(pp * vpp)
    rng = np.random.RandomState(3)
    inputs = jnp.asarray(rng.randn(3, MB, D), jnp.float32)  # 3 % pp != 0
    targets = jnp.asarray(rng.randn(3, MB, D), jnp.float32)
    regrouped = jax.tree_util.tree_map(
        lambda v: v.reshape(vpp, pp, *v.shape[1:]), stacked
    )

    def run(local, inputs, targets):
        params = jax.tree_util.tree_map(lambda v: v[:, 0], local)
        losses, _ = forward_backward_pipelining_with_interleaving(
            stage_fn, loss_fn, params, (inputs, targets),
            num_microbatches=3, num_model_chunks=vpp,
        )
        return losses

    with pytest.raises(ValueError, match="multiple of pipeline"):
        jax.jit(
            jax.shard_map(
                run, mesh=mesh,
                in_specs=(P(None, "pp"), P(), P()), out_specs=P(),
                check_vma=False,
            )
        )(regrouped, inputs, targets)


def test_get_forward_backward_func(eight_devices):
    ps.initialize_model_parallel(1, 1)
    assert get_forward_backward_func() is forward_backward_no_pipelining
    ps.destroy_model_parallel()
    ps.initialize_model_parallel(1, 2)
    assert (
        get_forward_backward_func()
        is forward_backward_pipelining_without_interleaving
    )
    ps.destroy_model_parallel()
    ps.initialize_model_parallel(1, 2, virtual_pipeline_model_parallel_size=2)
    f = get_forward_backward_func()
    assert f.func is forward_backward_pipelining_with_interleaving
    ps.destroy_model_parallel()
    ps.initialize_model_parallel(1, 2)
    from apex_tpu.transformer.pipeline_parallel import (
        forward_backward_pipelining_1f1b,
        forward_backward_pipelining_interleaved_1f1b,
    )
    assert (
        get_forward_backward_func(hand_scheduled=True)
        is forward_backward_pipelining_1f1b
    )
    ps.destroy_model_parallel()
    ps.initialize_model_parallel(1, 2, virtual_pipeline_model_parallel_size=2)
    f = get_forward_backward_func(hand_scheduled=True)
    assert f.func is forward_backward_pipelining_interleaved_1f1b
    assert f.keywords["num_model_chunks"] == 2


# ---------------------------------------------------------------------------
# microbatch calculators
# ---------------------------------------------------------------------------


def test_constant_microbatches():
    c = ConstantNumMicroBatches(64, 4, 2)
    assert c.get() == 8
    with pytest.raises(ValueError):
        ConstantNumMicroBatches(65, 4, 2)


def test_rampup_microbatches():
    r = RampupBatchsizeNumMicroBatches(
        start_batch_size=8,
        batch_size_increment=8,
        ramup_samples=100,
        global_batch_size=32,
        micro_batch_size=4,
        data_parallel_size=1,
    )
    assert r.get_current_global_batch_size() == 8
    r.update(60)
    assert r.get_current_global_batch_size() == 16
    r.update(200)
    assert r.get_current_global_batch_size() == 32
    assert r.get() == 8


def test_split_batch_into_microbatches():
    b = {"x": jnp.zeros((12, 3))}
    out = split_batch_into_microbatches(b, 4)
    assert out["x"].shape == (4, 3, 3)
    with pytest.raises(ValueError):
        split_batch_into_microbatches(b, 5)
