"""Contrib long-tail tests — ≙ apex/contrib/test/<feature>/test_*.py:
golden is the equivalent unfused composition (or a brute-force reference
for the transducer DP)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from apex_tpu import parallel_state as ps


class TestGroupNorm:
    @pytest.mark.parametrize("act", [None, "silu"])
    def test_vs_manual(self, act):
        from apex_tpu.contrib.group_norm import GroupNorm

        m = GroupNorm(num_groups=4, num_channels=16, act=act)
        x = jax.random.normal(jax.random.PRNGKey(0), (2, 8, 8, 16))
        params = m.init(jax.random.PRNGKey(1), x)
        y = m.apply(params, x)

        xf = np.asarray(x).reshape(2, -1, 4, 4)
        mean = xf.mean(axis=(1, 3), keepdims=True)
        var = xf.var(axis=(1, 3), keepdims=True)
        ref = ((xf - mean) / np.sqrt(var + 1e-5)).reshape(x.shape)
        if act == "silu":
            ref = np.asarray(jax.nn.silu(jnp.asarray(ref)))
        np.testing.assert_allclose(np.asarray(y), ref, atol=1e-5, rtol=1e-5)

    def test_channel_divisibility(self):
        from apex_tpu.contrib.group_norm import group_norm

        with pytest.raises(ValueError):
            group_norm(jnp.ones((1, 4, 4, 10)), num_groups=4)


class TestGroupBn:
    def test_matches_plain_bn_math(self):
        from apex_tpu.contrib.groupbn import BatchNorm2d_NHWC

        m = BatchNorm2d_NHWC(8, fuse_relu=True)
        x = jax.random.normal(jax.random.PRNGKey(0), (4, 6, 6, 8))
        z = jax.random.normal(jax.random.PRNGKey(1), (4, 6, 6, 8))
        variables = m.init(jax.random.PRNGKey(2), x, use_running_average=False)
        y, _ = m.apply(
            variables, x, z, use_running_average=False,
            mutable=["batch_stats"],
        )
        xf = np.asarray(x)
        mean = xf.mean(axis=(0, 1, 2))
        var = xf.var(axis=(0, 1, 2))
        ref = (xf - mean) / np.sqrt(var + 1e-5) + np.asarray(z)
        ref = np.maximum(ref, 0.0)
        np.testing.assert_allclose(np.asarray(y), ref, atol=1e-4, rtol=1e-4)

    def test_bn_group_psum(self, eight_devices):
        """bn_group=8: stats over the full dp-wide batch must match
        single-device BN on the gathered batch."""
        from apex_tpu.contrib.groupbn import BatchNorm2d_NHWC

        mesh = ps.initialize_model_parallel()
        m = BatchNorm2d_NHWC(4, bn_group=8)
        x = jax.random.normal(jax.random.PRNGKey(0), (16, 4, 4, 4))

        def f(key, x):
            variables = m.init(key, x, use_running_average=False)
            y, _ = m.apply(
                variables, x, use_running_average=False,
                mutable=["batch_stats"],
            )
            return y

        y = jax.jit(
            jax.shard_map(
                f, mesh=mesh, in_specs=(P(), P("dp")), out_specs=P("dp"),
                check_vma=False,
            )
        )(jax.random.PRNGKey(1), x)
        xf = np.asarray(x)
        mean = xf.mean(axis=(0, 1, 2))
        var = xf.var(axis=(0, 1, 2))
        ref = (xf - mean) / np.sqrt(var + 1e-5)
        np.testing.assert_allclose(np.asarray(y), ref, atol=1e-4, rtol=1e-4)


class TestHaloExchange:
    def test_halo_matches_neighbor_rows(self, eight_devices):
        from apex_tpu.contrib.peer_memory import halo_exchange_1d

        mesh = ps.initialize_model_parallel()  # dp=8
        x = jnp.arange(8.0 * 4).reshape(8, 4, 1, 1)  # H=4 rows per rank

        def f(x):
            return halo_exchange_1d(x, 1, axis=1, axis_name="dp")[None]

        out = jax.jit(
            jax.shard_map(
                f, mesh=mesh, in_specs=(P("dp"),), out_specs=P("dp"),
                check_vma=False,
            )
        )(x.reshape(8, 4, 1, 1))
        out = np.asarray(out).reshape(8, 6)
        full = np.arange(32.0).reshape(8, 4)
        for r in range(8):
            np.testing.assert_allclose(out[r, 1:5], full[r])
            if r > 0:
                np.testing.assert_allclose(out[r, 0], full[r - 1, -1])
            else:
                assert out[r, 0] == 0.0
            if r < 7:
                np.testing.assert_allclose(out[r, 5], full[r + 1, 0])
            else:
                assert out[r, 5] == 0.0

    def test_left_right_exchange(self, eight_devices):
        from apex_tpu.contrib.nccl_p2p import left_right_halo_exchange

        mesh = ps.initialize_model_parallel()
        left = jnp.arange(8.0)  # rank r's left halo = r
        right = jnp.arange(8.0) + 100  # rank r's right halo = 100 + r

        def f(l, r):
            li, ri = left_right_halo_exchange(l[0], r[0], "dp")
            return li[None], ri[None]

        li, ri = jax.jit(
            jax.shard_map(
                f, mesh=mesh, in_specs=(P("dp"), P("dp")),
                out_specs=(P("dp"), P("dp")), check_vma=False,
            )
        )(left, right)
        li, ri = np.asarray(li), np.asarray(ri)
        # left_input[r] = right halo of r-1; right_input[r] = left halo of r+1
        for r in range(8):
            assert li[r] == (0.0 if r == 0 else 100.0 + r - 1)
            assert ri[r] == (0.0 if r == 7 else r + 1.0)


class TestSpatialBottleneck:
    def test_spatial_matches_full(self, eight_devices):
        """H-sharded SpatialBottleneck == unsharded Bottleneck."""
        from apex_tpu.contrib.bottleneck import Bottleneck, SpatialBottleneck

        mesh = ps.initialize_model_parallel()  # dp=8 as the spatial axis
        n, hh, w, c = 2, 16, 8, 8
        x = jax.random.normal(jax.random.PRNGKey(0), (n, hh, w, c))
        full = Bottleneck(c, 4, c, spatial_axis_name=None, dtype=jnp.float32)
        # NOTE eval mode (train=False) so BN uses running stats — batch
        # stats differ per H-shard in train mode by design (like the
        # reference, which syncs BN separately via bn_group).
        variables = full.init(jax.random.PRNGKey(1), x, train=False)
        ref = full.apply(variables, x, train=False)

        spatial = SpatialBottleneck(c, 4, c, dtype=jnp.float32)

        def f(variables, x):
            return spatial.apply(variables, x, train=False)

        out = jax.jit(
            jax.shard_map(
                f, mesh=mesh,
                in_specs=(P(), P(None, "dp")),
                out_specs=P(None, "dp"), check_vma=False,
            )
        )(variables, x)
        np.testing.assert_allclose(
            np.asarray(out), np.asarray(ref), atol=1e-4, rtol=1e-4
        )

    def test_stride_rejected(self):
        from apex_tpu.contrib.bottleneck import SpatialBottleneck

        m = SpatialBottleneck(8, 4, 8, stride=2)
        with pytest.raises(ValueError, match="stride"):
            m.init(jax.random.PRNGKey(0), jnp.ones((1, 8, 8, 8)), train=False)


class TestFocalLoss:
    def test_vs_manual(self):
        from apex_tpu.contrib.focal_loss import focal_loss

        logits = jax.random.normal(jax.random.PRNGKey(0), (32, 4))
        targets = jnp.asarray(np.random.RandomState(0).randint(-1, 5, (32,)))
        out = focal_loss(logits, targets, num_positives_sum=jnp.asarray(7.0))

        lf = np.asarray(logits)
        t = np.asarray(targets)
        one_hot = np.zeros((32, 4), np.float32)
        for i, ti in enumerate(t):
            if ti >= 1:
                one_hot[i, ti - 1] = 1.0
        p = 1.0 / (1.0 + np.exp(-lf))
        ce = np.maximum(lf, 0) - lf * one_hot + np.log1p(np.exp(-np.abs(lf)))
        pt = p * one_hot + (1 - p) * (1 - one_hot)
        at = 0.25 * one_hot + 0.75 * (1 - one_hot)
        per = at * (1 - pt) ** 2.0 * ce
        per[t < 0] = 0.0
        np.testing.assert_allclose(
            float(out), per.sum() / 7.0, rtol=1e-5, atol=1e-6
        )


class TestIndexMul2d:
    def test_fwd_and_scatter_grad(self):
        from apex_tpu.contrib.index_mul_2d import index_mul_2d

        in1 = jax.random.normal(jax.random.PRNGKey(0), (5, 3))
        in2 = jax.random.normal(jax.random.PRNGKey(1), (4, 3))
        idx = jnp.asarray([0, 2, 2, 4])
        out = index_mul_2d(in1, in2, idx)
        np.testing.assert_allclose(
            np.asarray(out), np.asarray(in1)[np.asarray(idx)] * np.asarray(in2),
            rtol=1e-6,
        )
        # repeated index 2 must accumulate grads (scatter-add semantics)
        g = jax.grad(lambda a: jnp.sum(index_mul_2d(a, in2, idx)))(in1)
        expect_row2 = np.asarray(in2)[1] + np.asarray(in2)[2]
        np.testing.assert_allclose(np.asarray(g)[2], expect_row2, rtol=1e-6)


def _brute_force_rnnt(log_probs, labels, T, U, blank):
    """O(T·U) DP in numpy, one batch element."""
    alpha = np.full((T, U + 1), -np.inf)
    alpha[0, 0] = 0.0
    for t in range(T):
        for u in range(U + 1):
            cands = []
            if t > 0:
                cands.append(alpha[t - 1, u] + log_probs[t - 1, u, blank])
            if u > 0:
                cands.append(alpha[t, u - 1] + log_probs[t, u - 1, labels[u - 1]])
            if cands:
                alpha[t, u] = np.logaddexp.reduce(cands)
    return -(alpha[T - 1, U] + log_probs[T - 1, U, blank])


class TestTransducer:
    def test_joint(self):
        from apex_tpu.contrib.transducer import transducer_joint

        f = jax.random.normal(jax.random.PRNGKey(0), (2, 5, 8))
        g = jax.random.normal(jax.random.PRNGKey(1), (2, 3, 8))
        out = transducer_joint(f, g, relu=True)
        ref = np.maximum(
            np.asarray(f)[:, :, None, :] + np.asarray(g)[:, None, :, :], 0.0
        )
        np.testing.assert_allclose(np.asarray(out), ref, rtol=1e-6)

    def test_loss_vs_brute_force(self):
        from apex_tpu.contrib.transducer import transducer_loss

        rng = np.random.RandomState(0)
        B, T, U, V = 3, 6, 4, 7
        x = rng.randn(B, T, U + 1, V).astype(np.float32)
        lp = np.asarray(jax.nn.log_softmax(jnp.asarray(x), axis=-1))
        labels = rng.randint(1, V, (B, U))
        f_len = np.asarray([6, 4, 5])
        y_len = np.asarray([4, 2, 3])
        out = transducer_loss(
            jnp.asarray(lp), jnp.asarray(labels), jnp.asarray(f_len),
            jnp.asarray(y_len), blank_idx=0,
        )
        for b in range(B):
            ref = _brute_force_rnnt(lp[b], labels[b], f_len[b], y_len[b], 0)
            np.testing.assert_allclose(float(out[b]), ref, rtol=1e-5, atol=1e-5)

    def test_loss_grad_finite(self):
        from apex_tpu.contrib.transducer import TransducerLoss

        loss_fn = TransducerLoss()
        x = jax.random.normal(jax.random.PRNGKey(0), (2, 5, 4, 6))
        labels = jnp.asarray([[1, 2, 3], [2, 1, 4]])
        g = jax.grad(
            lambda x: jnp.sum(
                loss_fn(x, labels, jnp.asarray([5, 4]), jnp.asarray([3, 2]))
            )
        )(x)
        assert bool(jnp.all(jnp.isfinite(g)))


class TestSparsity:
    def test_mask_is_2of4(self):
        from apex_tpu.contrib.sparsity import create_mask

        w = jax.random.normal(jax.random.PRNGKey(0), (8, 16))
        mask = np.asarray(create_mask(w))
        grouped = mask.reshape(8, 4, 4)
        assert (grouped.sum(axis=-1) == 2).all()
        # kept entries are the two largest magnitudes per group
        mag = np.abs(np.asarray(w)).reshape(8, 4, 4)
        for i in range(8):
            for gidx in range(4):
                kept = set(np.where(grouped[i, gidx])[0])
                top2 = set(np.argsort(mag[i, gidx])[-2:])
                assert kept == top2

    def test_asp_workflow(self):
        from apex_tpu.contrib.sparsity import ASP

        params = {
            "dense": {"kernel": jax.random.normal(jax.random.PRNGKey(0), (64, 32)),
                      "bias": jnp.ones((32,))},
        }
        pruned, masks = ASP.prune_trained_model(params)
        # flax kernels are (in, out): 2:4 must hold along the INPUT dim
        # (axis -2) — groups of 4 consecutive rows within each column
        k = np.asarray(pruned["dense"]["kernel"]).T.reshape(32, 16, 4)
        assert (np.count_nonzero(k, axis=-1) <= 2).all()
        # bias untouched, and its mask is the scalar sentinel (no memory)
        np.testing.assert_allclose(np.asarray(pruned["dense"]["bias"]), 1.0)
        assert masks["dense"]["bias"].ndim == 0
        # masked grads keep sparsity through an update
        grads = jax.tree_util.tree_map(jnp.ones_like, params)
        mg = ASP.apply_masks(grads, masks)
        mk = np.asarray(mg["dense"]["kernel"]).T.reshape(32, 16, 4)
        assert (mk.sum(-1) == 2).all()

    def test_torch_layout_prunes_last_axis(self):
        from apex_tpu.contrib.sparsity import ASP

        params = {"weight": jax.random.normal(jax.random.PRNGKey(1), (32, 64))}
        pruned, _ = ASP.prune_trained_model(params)
        w = np.asarray(pruned["weight"]).reshape(32, 16, 4)
        assert (np.count_nonzero(w, axis=-1) <= 2).all()

    def test_permutation_search_improves_retained_magnitude(self):
        """≙ permutation_lib: the greedy channel-permutation must retain
        MORE magnitude under the 2:4 mask than identity on a random
        matrix (VERDICT r2 item 9's done-criterion), and the permuted
        mask must stay a valid 2:4 pattern."""
        from apex_tpu.contrib.sparsity import (
            create_mask,
            permutation_retained_magnitude,
            search_channel_permutation,
        )

        w = jax.random.normal(jax.random.PRNGKey(2), (64, 64))
        perm, before, after = search_channel_permutation(w, axis=-1)
        assert sorted(perm.tolist()) == list(range(64))  # a permutation
        assert after > before  # random matrices essentially always improve
        # reported values match the independent evaluator
        ident = permutation_retained_magnitude(w, np.arange(64), axis=-1)
        np.testing.assert_allclose(before, ident, rtol=1e-6)
        np.testing.assert_allclose(
            after, permutation_retained_magnitude(w, perm, axis=-1),
            rtol=1e-6,
        )
        # retained magnitude of the actual masked permuted weight agrees
        wp = np.asarray(w)[:, perm]
        mask = np.asarray(create_mask(jnp.asarray(wp), axis=-1))
        np.testing.assert_allclose(
            float(np.abs(wp * mask).sum()), after, rtol=1e-5
        )

    def test_permutation_search_flax_layout_and_tree(self):
        """compute_permutations walks the tree, prunes axis -2 for flax
        kernels, skips biases; apply/invert round-trips."""
        from apex_tpu.contrib.sparsity import (
            ASP,
            apply_permutation,
            invert_permutation,
        )

        params = {
            "dense": {
                "kernel": jax.random.normal(jax.random.PRNGKey(3), (32, 24)),
                "bias": jnp.ones((24,)),
            }
        }
        perms = ASP.compute_permutations(params)
        entry = perms["dense"]["kernel"]
        assert perms["dense"]["bias"] is None
        assert entry["axis"] == -2
        assert entry["after"] >= entry["before"]
        k = params["dense"]["kernel"]
        kp = apply_permutation(k, entry["perm"], axis=-2)
        back = apply_permutation(kp, invert_permutation(entry["perm"]), axis=-2)
        np.testing.assert_allclose(np.asarray(back), np.asarray(k))


class TestConvBiasRelu:
    def test_vs_compose(self):
        from apex_tpu.contrib.conv_bias_relu import ConvBiasMaskReLU, ConvBiasReLU

        x = jax.random.normal(jax.random.PRNGKey(0), (2, 8, 8, 3))
        w = jax.random.normal(jax.random.PRNGKey(1), (3, 3, 3, 5)) * 0.1
        b = jnp.ones((5,)) * 0.05
        out = ConvBiasReLU(x, w, b)
        ref = jax.nn.relu(
            jax.lax.conv_general_dilated(
                x, w, (1, 1), ((1, 1), (1, 1)),
                dimension_numbers=("NHWC", "HWIO", "NHWC"),
            ) + b
        )
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=1e-5)
        mask = jnp.asarray(np.random.RandomState(0).rand(2, 8, 8, 5) > 0.5)
        out2 = ConvBiasMaskReLU(x, w, b, mask)
        assert out2.shape == ref.shape


class TestNaStubs:
    def test_nccl_allocator_noop(self):
        from apex_tpu.contrib import nccl_allocator

        nccl_allocator.init()
        with nccl_allocator.nccl_mem():
            pass

    def test_gds_raises_with_pointer(self):
        from apex_tpu.contrib import gpu_direct_storage

        with pytest.raises(NotImplementedError, match="orbax"):
            gpu_direct_storage.load_data("/tmp/x")

    def test_openfold_dap_roundtrip(self, eight_devices):
        from apex_tpu.contrib.openfold import (
            scatter_cols_gather_rows,
            scatter_rows_gather_cols,
        )

        mesh = ps.initialize_model_parallel()  # dp=8
        x = jax.random.normal(jax.random.PRNGKey(0), (8, 16, 4))

        def f(x):
            y = scatter_rows_gather_cols(x, "dp", row_axis=0, col_axis=1)
            z = scatter_cols_gather_rows(y, "dp", row_axis=0, col_axis=1)
            return z

        out = jax.jit(
            jax.shard_map(
                f, mesh=mesh, in_specs=(P("dp"),), out_specs=P("dp"),
                check_vma=False,
            )
        )(x)
        np.testing.assert_allclose(np.asarray(out), np.asarray(x), rtol=1e-6)

    def test_openfold_scatter_gather(self, eight_devices):
        from apex_tpu.contrib.openfold import gather, scatter

        mesh = ps.initialize_model_parallel()  # dp=8
        x = jax.random.normal(jax.random.PRNGKey(1), (16, 8, 4))

        def f(x):
            local = scatter(x, "dp", dim=0)  # enter DAP: rows sharded
            assert local.shape == (2, 8, 4)
            return gather(local, "dp", dim=0)

        out = jax.jit(
            jax.shard_map(
                f, mesh=mesh, in_specs=(P(),), out_specs=P(),
                check_vma=False,
            )
        )(x)
        np.testing.assert_allclose(np.asarray(out), np.asarray(x), rtol=1e-6)

    def test_openfold_axial_pair_stack_sharded_matches_unsharded(
        self, eight_devices
    ):
        """A 2-block DAP axial pair stack (row-attn on row-sharded layout,
        row_to_col, col-attn on col-sharded layout, col_to_row, MLP) on a
        4-device mesh must equal the same stack run unsharded — the
        reference dap.py's equivalence contract (VERDICT r2 item 10)."""
        from apex_tpu.contrib.openfold import DAPAxialBlock

        R, C, D, H, dap = 8, 12, 16, 4, 4
        x = jax.random.normal(jax.random.PRNGKey(2), (R, C, D))
        key = jax.random.PRNGKey(3)

        # golden: unsharded, axis_name=None (no transitions)
        blocks_ref = [
            DAPAxialBlock(dim=D, heads=H, axis_name=None, name=f"b{i}")
            for i in range(2)
        ]
        y_ref = x
        params_ref = []
        for i, blk in enumerate(blocks_ref):
            p = blk.init(jax.random.fold_in(key, i), y_ref)
            params_ref.append(p)
            y_ref = blk.apply(p, y_ref)

        mesh = ps.initialize_model_parallel(
            devices=jax.devices()[:dap]
        )  # dp=4 used as the dap axis

        def f(x):
            y = x  # enters row-sharded: (R/dap, C, D)
            for i in range(2):
                blk = DAPAxialBlock(
                    dim=D, heads=H, axis_name="dp", name=f"b{i}"
                )
                # same init key as golden; params are R-independent
                # (Dense/LN over D) so both inits are identical
                p = blk.init(jax.random.fold_in(key, i), y)
                y = blk.apply(p, y)
            return y

        y_sh = jax.jit(
            jax.shard_map(
                f, mesh=mesh, in_specs=(P("dp"),), out_specs=P("dp"),
                check_vma=False,
            )
        )(x)
        np.testing.assert_allclose(
            np.asarray(y_sh), np.asarray(y_ref), rtol=2e-5, atol=2e-5
        )
