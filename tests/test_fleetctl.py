"""Fleet control plane — router, replicas, autoscaler, Fleet loop.

Covers the ISSUE 16 acceptance surface: least-loaded routing with
queue-headroom gating, prompt-only re-routing that preserves the
SHARED retry budget and original ``submitted_at``, crash evacuation
with provably-empty pools, graceful preemption drains that migrate
work, zero-loss rolling updates through the supervised rebuild path,
hung-replica ejection + rejoin, burn-rate autoscaling decisions
(out/in/cooldown), per-replica ops export aggregation, and the
fleet-level SLO rule key pins.  The full storm (crash + preempt +
spike + deploy in one seeded run) lives in ``tools/fleet_drill.py``
behind the FLEET CI gate.
"""

import types

import jax
import jax.numpy as jnp
import pytest

from apex_tpu.fleetctl import (
    DEAD,
    DRAINING,
    EJECTED,
    LIVE,
    Autoscaler,
    AutoscalerConfig,
    EngineReplica,
    Fleet,
    Router,
    aggregate_expositions,
)
from apex_tpu.models.gpt import GptConfig, GptModel
from apex_tpu.observability import MetricRegistry
from apex_tpu.observability.ometrics import Histogram
from apex_tpu.observability.slo import (
    FLEET_TERMINAL_SHED_KEYS,
    fleet_slo_rules,
)
from apex_tpu.observability.spans import SpanRecorder
from apex_tpu.serve import (
    InferenceEngine,
    Request,
    SHED_REASONS,
    SHED_REROUTED,
    ServeConfig,
)


class VClock:
    def __init__(self, tick_s=0.005):
        self.t = 0.0
        self.tick_s = tick_s

    def __call__(self):
        return self.t

    def advance(self):
        self.t += self.tick_s


@pytest.fixture(scope="module")
def gpt():
    cfg = GptConfig(
        vocab_size=64, hidden_size=32, num_layers=2, num_heads=2,
        intermediate_size=64, max_seq_len=128, dtype=jnp.float32,
    )
    model = GptModel(cfg)
    params = model.init(
        jax.random.PRNGKey(0), jnp.zeros((8, 1), jnp.int32)
    )
    return cfg, model, params


def make_replica(gpt, name, clock, *, spans=None, spec=None, **sched_kw):
    cfg, _, params = gpt
    registry = MetricRegistry(fetch_every=1)
    engine = InferenceEngine(
        cfg, params,
        ServeConfig(page_size=8, num_pages=32, max_batch=2,
                    max_pages_per_seq=8, verify=False),
        registry=registry, spec=spec,
    ).build()
    return EngineReplica(name, engine, clock=clock, spans=spans,
                         **sched_kw)


def make_fleet(gpt, clock, *, n=2, spans=None, autoscaler=None,
               hung_ticks=200, spec=None, **sched_kw):
    def factory(name):
        return make_replica(gpt, name, clock, spans=spans, spec=spec,
                            **sched_kw)

    return Fleet(factory, replicas=n, clock=clock, spans=spans,
                 autoscaler=autoscaler, hung_ticks=hung_ticks)


def pump(fleet, clock, reqs, *, max_ticks=3000):
    """Step the fleet until every request in ``reqs`` is terminal."""
    for _ in range(max_ticks):
        if all(r.status in ("done", "shed") for r in reqs):
            return
        fleet.step()
        clock.advance()
    raise AssertionError(
        f"requests not terminal after {max_ticks} ticks: "
        f"{[(r.rid, r.status) for r in reqs if r.status not in ('done', 'shed')]}"
    )


def req(n_prompt=4, n_out=4):
    return Request(prompt=list(range(1, 1 + n_prompt)),
                   max_new_tokens=n_out)


# ---------------------------------------------------------------------------
# router
# ---------------------------------------------------------------------------


class TestRouter:
    def test_pick_least_loaded_live_with_headroom(self, gpt):
        clock = VClock()
        a = make_replica(gpt, "a", clock, max_queue_depth=2)
        b = make_replica(gpt, "b", clock, max_queue_depth=2)
        # equal load: name breaks the tie
        assert Router.pick([a, b]) is a
        a.sched.submit(req())
        assert Router.pick([a, b]) is b
        # a full admission queue disqualifies a replica even if it is
        # otherwise least-loaded — force-feeding it would shed
        b.sched.submit(req())
        b.sched.submit(req())
        assert len(b.sched.queue) == 2
        assert Router.pick([a, b]) is a
        a.sched.submit(req())
        assert Router.pick([a, b]) is None  # everyone saturated
        b.state = DEAD
        a.state = EJECTED
        assert Router.pick([a, b]) is None

    def test_reroute_resets_to_prompt_and_preserves_budget(self):
        clock = VClock()
        router = Router(clock=clock)
        r = req()
        r.submitted_at = 1.25
        r.retries = 2
        r.queue_blocked_s = 0.5
        r.tokens = [7, 8]
        r.ctx_len = 6
        r.status = "running"
        r.first_token_at = 2.0
        assert router.reroute(r)
        assert list(router.door) == [r]
        assert r.tokens == [] and r.ctx_len == 0
        assert r.status == "queued" and r.first_token_at is None
        # the identity that makes fleet TTFT and the shared retry
        # budget honest across hops:
        assert r.submitted_at == 1.25
        assert r.retries == 2
        assert r.queue_blocked_s == 0.5

    def test_reroute_rejects_page_holders(self):
        router = Router(clock=VClock())
        r = req()
        r.pages = [3]
        with pytest.raises(AssertionError):
            router.reroute(r)

    def test_dispatch_routes_and_records_span(self, gpt):
        clock = VClock()
        spans = SpanRecorder(capacity=256)
        counts = {}

        def count(name, n=1):
            counts[name] = counts.get(name, 0) + n

        router = Router(clock=clock, spans=spans, count=count)
        a = make_replica(gpt, "a", clock, spans=spans)
        r = router.submit(req())
        assert counts == {"fleet/submitted": 1}
        assert router.dispatch([a], tick=0) == 1
        assert not router.door and len(a.sched.queue) == 1
        assert counts["fleet/routed"] == 1
        # the routed span opened with the destination replica and was
        # closed by the target's own queued event
        routed = [s for s in spans.snapshot()
                  if s.get("name") == "req/routed"]
        assert len(routed) == 1
        assert routed[0]["args"]["replica"] == "a"

    def test_router_chaos_holds_the_door(self, gpt):
        from apex_tpu.resilience import chaos

        clock = VClock()
        counts = {}
        router = Router(
            clock=clock,
            count=lambda k, n=1: counts.__setitem__(
                k, counts.get(k, 0) + n
            ),
        )
        a = make_replica(gpt, "a", clock)
        router.submit(req())
        fault, = chaos.parse_spec("fleet.router:raise:x1@0")[0]
        with chaos.inject(fault, seed=0):
            assert router.dispatch([a], tick=0) == 0
            assert len(router.door) == 1  # retained, not lost
            assert counts["fleet/router_faults"] == 1
            assert router.dispatch([a], tick=1) == 1


# ---------------------------------------------------------------------------
# fleet: failure handling
# ---------------------------------------------------------------------------


def running_replica(fleet, r):
    """The replica whose slots currently hold request ``r``."""
    for rep in fleet.replicas:
        if any(s is r for s in rep.sched.slots):
            return rep
    return None


class TestFleetFailures:
    def test_shared_retry_budget_across_replicas(self, gpt):
        """Satellite 3: a request that faults on replica A and again
        on replica B consumes ONE shared ``max_retries`` budget and
        ends as a terminal ``retries_exhausted`` — not an infinite
        route loop."""
        clock = VClock()
        fleet = make_fleet(gpt, clock, n=2, max_retries=1)
        r = fleet.submit(req(n_out=24))
        crashed = 0
        for _ in range(2000):
            rep = running_replica(fleet, r)
            if rep is not None and crashed < 2:
                fleet.crash(rep)
                crashed += 1
            if r.status in ("done", "shed"):
                break
            fleet.step()
            clock.advance()
        assert crashed == 2
        assert r.status == "shed"
        assert r.shed_reason == "retries_exhausted"
        assert r.retries == 1  # the budget, spent once, fleet-wide
        # exactly one fleet-wide terminal: the shed happened on the
        # SECOND crash's replica; no replica also completed it
        assert fleet.completed_count() == 0
        assert fleet.shed_count("retries_exhausted") == 1
        assert all(v == 0 for v in fleet.leak_check().values())

    def test_crash_evacuates_and_work_finishes_elsewhere(self, gpt):
        clock = VClock()
        fleet = make_fleet(gpt, clock, n=2, max_retries=3)
        reqs = [fleet.submit(req(n_out=8)) for _ in range(4)]
        for _ in range(3):  # route + admit somewhere
            fleet.step()
            clock.advance()
        victim = next(
            rep for rep in fleet.replicas if rep.sched.pending
        )
        fleet.crash(victim)
        assert victim.state == DEAD
        assert victim.sched.pool.in_use == 0  # evacuated, provably
        pump(fleet, clock, reqs)
        assert all(r.status == "done" for r in reqs)
        assert fleet.completed_count() == 4
        fr = fleet.registry.fetch()
        assert fr["fleet/replica_crashes"] == 1
        assert all(v == 0 for v in fleet.leak_check().values())

    def test_preempt_drains_gracefully_and_migrates(self, gpt):
        clock = VClock()
        fleet = make_fleet(gpt, clock, n=2)
        reqs = [fleet.submit(req(n_out=6)) for _ in range(4)]
        for _ in range(3):
            fleet.step()
            clock.advance()
        victim = next(
            rep for rep in fleet.replicas if rep.sched.pending
        )
        fleet.preempt(victim)
        assert victim.state == DRAINING
        pump(fleet, clock, reqs)
        assert victim.state == DEAD  # drained out, then left
        assert all(r.status == "done" for r in reqs)
        # ZERO terminal draining sheds: the drain re-routed instead
        assert fleet.shed_count("draining") == 0
        assert victim.drain_reports and (
            victim.drain_reports[0]["reason"] == "preempt"
        )

    def test_eject_and_rejoin(self, gpt):
        clock = VClock()
        fleet = make_fleet(gpt, clock, n=2)
        rep = fleet.replicas[0]
        fleet.eject(rep, "burn_rate:9.0x")
        assert rep.state == EJECTED
        assert rep.end_cause == "burn_rate:9.0x"
        with pytest.raises(RuntimeError):
            fleet.rejoin(fleet.replicas[1])  # LIVE cannot "rejoin"
        fleet.rejoin(rep)
        assert rep.state == LIVE and rep.end_cause is None
        fleet.step()  # counters publish on the tick cadence
        fr = fleet.registry.fetch()
        assert fr["fleet/ejections"] == 1 and fr["fleet/rejoins"] == 1
        rules = [e.rule for e in fleet.health_events]
        assert rules == ["fleet_eject", "fleet_rejoin"]

    def test_hung_replica_is_ejected(self, gpt):
        clock = VClock()
        fleet = make_fleet(gpt, clock, n=1, hung_ticks=3)
        rep = fleet.replicas[0]
        r = fleet.submit(req(n_out=8))
        fleet.step()  # routed + admitted
        clock.advance()
        rep.step = lambda: None  # wedge the iteration loop
        for _ in range(8):
            fleet.step()
            clock.advance()
        assert rep.state == EJECTED
        assert rep.end_cause == "hung"
        assert rep.sched.pool.in_use == 0
        # the request was evacuated back to the fleet door (no live
        # replica to take it yet)
        assert r in fleet.router.door


# ---------------------------------------------------------------------------
# fleet: rolling update
# ---------------------------------------------------------------------------


class TestRollingUpdate:
    def test_zero_loss_rolling_update_under_load(self, gpt):
        cfg, model, _ = gpt
        params2 = model.init(
            jax.random.PRNGKey(42), jnp.zeros((8, 1), jnp.int32)
        )
        clock = VClock()
        fleet = make_fleet(gpt, clock, n=2)
        names = [rep.name for rep in fleet.replicas]
        reqs = [fleet.submit(req(n_out=6)) for _ in range(4)]
        for _ in range(2):
            fleet.step()
            clock.advance()
        fleet.start_rolling_update(params2)
        with pytest.raises(RuntimeError):
            fleet.start_rolling_update(params2)  # one at a time
        reqs += [fleet.submit(req(n_out=4)) for _ in range(3)]
        pump(fleet, clock, reqs)
        for _ in range(50):  # let the deploy seal
            if fleet.deploy is None:
                break
            fleet.step()
            clock.advance()
        assert fleet.deploy is None
        d, = fleet.deploy_history
        assert sorted(d["updated"]) == sorted(names)
        assert d["lost_requests"] == 0  # the tentpole number
        assert all(r.status == "done" for r in reqs)
        for rep in fleet.replicas:
            assert rep.state == LIVE
            assert rep.engine.params is params2
            assert rep.engine.rebuilds >= 1  # supervised rebuild path
        fr = fleet.registry.fetch()
        assert fr["fleet/deploys"] == 1
        assert fleet.shed_count("draining") == 0

    def test_last_live_replica_swap_waits_for_idle(self, gpt):
        cfg, model, _ = gpt
        params2 = model.init(
            jax.random.PRNGKey(43), jnp.zeros((8, 1), jnp.int32)
        )
        clock = VClock()
        fleet = make_fleet(gpt, clock, n=1)
        rep = fleet.replicas[0]
        r = fleet.submit(req(n_out=6))
        fleet.step()
        clock.advance()
        fleet.start_rolling_update(params2)
        fleet.step()  # must NOT drain the only replica under traffic
        assert rep.state == LIVE and fleet.deploy is not None
        pump(fleet, clock, [r])
        for _ in range(50):
            if fleet.deploy is None:
                break
            fleet.step()
            clock.advance()
        # idle now: the instant swap ran, zero requests lost
        assert fleet.deploy is None
        assert rep.engine.params is params2 and rep.state == LIVE
        assert r.status == "done"
        assert fleet.deploy_history[0]["lost_requests"] == 0


# ---------------------------------------------------------------------------
# fleet: speculative decoding
# ---------------------------------------------------------------------------


class TestSpeculativeFleet:
    def test_draft_weights_ride_rolling_update(self, gpt):
        """A speculative fleet keeps speculating across a rolling
        deploy: self-draft replicas re-alias the NEW target weights at
        redeploy (a draft frozen on old weights would bleed acceptance
        silently), and the router-level acceptance aggregate keeps
        moving afterwards."""
        from apex_tpu.serve import SpecConfig

        cfg, model, _ = gpt
        params2 = model.init(
            jax.random.PRNGKey(44), jnp.zeros((8, 1), jnp.int32)
        )
        clock = VClock()
        fleet = make_fleet(
            gpt, clock, n=2, spec=SpecConfig(draft_params=None, k=2),
        )
        reqs = [fleet.submit(req(n_out=6)) for _ in range(4)]
        pump(fleet, clock, reqs)
        acc = fleet.spec_acceptance()
        # self-draft + greedy: every proposal matches the target argmax
        assert acc["drafted"] > 0 and acc["rate"] == 1.0
        fleet.start_rolling_update(params2)
        for _ in range(60):
            if fleet.deploy is None:
                break
            fleet.step()
            clock.advance()
        assert fleet.deploy is None
        assert fleet.deploy_history[0]["lost_requests"] == 0
        for rep in fleet.replicas:
            assert rep.state == LIVE
            assert rep.engine.params is params2
            assert rep.engine.draft_params is params2
        reqs2 = [fleet.submit(req(n_out=4)) for _ in range(2)]
        pump(fleet, clock, reqs2)
        acc2 = fleet.spec_acceptance()
        assert acc2["drafted"] > acc["drafted"] and acc2["rate"] == 1.0


# ---------------------------------------------------------------------------
# autoscaler
# ---------------------------------------------------------------------------


def fake_replica(depth, ttfts=(), threshold=100.0):
    hist = Histogram("serve/ttft", (25.0, 50.0, 100.0, 200.0),
                     unit="ms")
    for v in ttfts:
        hist.observe(v)
    return types.SimpleNamespace(
        depth=depth, sched=types.SimpleNamespace(ttft_hist=hist)
    )


class TestAutoscaler:
    CFG = dict(min_replicas=1, max_replicas=4, queue_high=8.0,
               queue_low=1.0, headroom_evals=2, cooldown_ticks=8,
               eval_every=1, short_window_s=1.0, long_window_s=4.0,
               out_factor=3.0, ttft_threshold_ms=100.0)

    def test_scale_out_on_queue_pressure_and_cooldown(self):
        scaler = Autoscaler(AutoscalerConfig(**self.CFG))
        reps = [fake_replica(10), fake_replica(10)]
        e = scaler.evaluate(reps, tick=0)
        assert e is not None and e.rule == "fleet_scale_out"
        assert "queue depth" in e.message
        # cooldown mutes the actuator even under sustained pressure
        assert scaler.evaluate(reps, tick=4) is None
        assert scaler.evaluate(reps, tick=9) is not None

    def test_scale_out_on_fast_burn(self):
        scaler = Autoscaler(AutoscalerConfig(**self.CFG))
        reps = [fake_replica(2.0)]  # below queue_high: burn must act
        for i in range(6):
            # every TTFT blows the 100ms threshold: error rate 1.0
            # against a 0.1 budget = 10x burn >= the 3x page factor
            reps[0].sched.ttft_hist.observe(150.0)
            e = scaler.evaluate(reps, tick=i)
            if e is not None:
                assert e.rule == "fleet_scale_out"
                assert "burn" in e.message
                return
        raise AssertionError("fast burn never paged a scale-out")

    def test_scale_in_needs_sustained_headroom(self):
        scaler = Autoscaler(AutoscalerConfig(**self.CFG))
        reps = [fake_replica(0.0), fake_replica(0.0)]
        assert scaler.evaluate(reps, tick=0) is None  # 1st headroom
        e = scaler.evaluate(reps, tick=1)  # 2nd consecutive
        assert e is not None and e.rule == "fleet_scale_in"
        # at min_replicas the decision is never emitted
        solo = [fake_replica(0.0)]
        scaler2 = Autoscaler(AutoscalerConfig(**self.CFG))
        for i in range(6):
            assert scaler2.evaluate(solo, tick=i) is None

    def test_headroom_resets_on_pressure(self):
        scaler = Autoscaler(AutoscalerConfig(**self.CFG))
        reps = [fake_replica(0.0), fake_replica(0.0)]
        assert scaler.evaluate(reps, tick=0) is None
        busy = [fake_replica(5.0), fake_replica(5.0)]
        assert scaler.evaluate(busy, tick=1) is None  # mid pressure
        assert scaler.evaluate(reps, tick=2) is None  # count restarts
        assert scaler.evaluate(reps, tick=3) is not None


# ---------------------------------------------------------------------------
# ops aggregation + fleet SLO rules
# ---------------------------------------------------------------------------


class TestFleetObservability:
    def test_aggregate_expositions_sums_counters(self):
        h = Histogram("serve/ttft", (50.0,), unit="ms")
        texts = []
        for completed in (3.0, 4.0):
            reg = MetricRegistry(fetch_every=1)
            reg.counter("serve/completed")
            reg.gauge("serve/queue_depth")
            st = reg.update(reg.init(), {
                "serve/completed": completed,
                "serve/queue_depth": completed,
            })
            reg.observe(0, st)
            reg.fetch()
            from apex_tpu.observability.ometrics import render

            texts.append(render([reg], [h], None))
        agg = aggregate_expositions(texts)
        assert agg["sources"] == 2
        completed = [v for k, v in agg["counters"].items()
                     if "completed" in k]
        assert completed == [7.0]  # counters SUM across replicas
        depth = [v for k, v in agg["gauges"].items()
                 if "queue_depth" in k]
        assert depth == [[3.0, 4.0]]  # gauges stay per-source

    def test_replica_ops_servers_get_distinct_ports(self, gpt):
        clock = VClock()
        a = make_replica(gpt, "a", clock)
        b = make_replica(gpt, "b", clock)
        try:
            sa, sb = a.start_ops(), b.start_ops()
            assert sa.bound_port and sb.bound_port
            assert sa.bound_port != sb.bound_port
            agg = aggregate_expositions([sa.scrape(), sb.scrape()])
            assert agg["sources"] == 2
        finally:
            a.stop_ops()
            b.stop_ops()

    def test_terminal_shed_keys_pin(self):
        """A new shed reason must be classified: terminal (extend
        FLEET_TERMINAL_SHED_KEYS) or a hop (extend the exclusion
        below, with the reasoning rerouted has)."""
        derived = tuple(
            f"serve/shed_{r}" for r in SHED_REASONS
            if r != SHED_REROUTED
        )
        assert derived == FLEET_TERMINAL_SHED_KEYS

    def test_fleet_goodput_ignores_reroutes(self):
        values = {"serve/completed": 90.0, "serve/shed": 40.0,
                  "serve/shed_rerouted": 30.0,
                  "serve/shed_draining": 10.0}
        rules = fleet_slo_rules(values_fn=lambda: values)
        by_name = {r.slo.name: r.slo for r in rules}
        good, total = by_name["fleet_goodput"].counts(values)
        # 30 re-routed hops are NOT failures: 90/(90+10), not 90/130
        assert (good, total) == (90.0, 100.0)
        good, total = by_name["fleet_deploy_loss"].counts(values)
        assert (good, total) == (90.0, 100.0)  # draining IS a loss


# ---------------------------------------------------------------------------
# prefix-affinity routing + cache-armed failure handling
# (docs/serving.md "Prefix caching & chunked prefill")
# ---------------------------------------------------------------------------


class TestPrefixAffinity:
    def test_pick_prefers_deepest_cache_hit(self, gpt):
        clock = VClock()
        a = make_replica(gpt, "a", clock, prefix_cache=True)
        b = make_replica(gpt, "b", clock, prefix_cache=True)
        prompt = list(range(1, 17))  # 2 full pages at page_size=8
        warm = Request(prompt=list(prompt), max_new_tokens=2)
        b.sched.submit(warm)
        b.sched.run()
        # no prompt (or no hit anywhere): the legacy (depth, name)
        # tie-break is untouched
        assert Router.pick([a, b]) is a
        assert Router.pick([a, b], prompt=[60, 61, 62]) is a
        # affinity: the replica already holding the prefix wins the tie
        assert Router.pick([a, b], prompt=prompt) is b
        assert Router.peek_cached(b, prompt) == 16
        assert Router.peek_cached(a, prompt) == 0
        # deepest hit wins: warm `a` with only the first page
        a.sched.submit(Request(prompt=list(prompt[:8]), max_new_tokens=2))
        a.sched.run()
        assert Router.peek_cached(a, prompt) == 8
        assert Router.pick([a, b], prompt=prompt) is b  # 16 > 8

    def test_peek_cached_is_zero_without_cache(self, gpt):
        clock = VClock()
        a = make_replica(gpt, "a", clock)  # cacheless replica
        assert Router.peek_cached(a, [1, 2, 3]) == 0
        assert Router.pick([a], prompt=[1, 2, 3]) is a

    def test_dispatch_counts_affinity_hits(self, gpt):
        clock = VClock()
        counts = {}
        router = Router(
            clock=clock,
            count=lambda k, n=1: counts.__setitem__(
                k, counts.get(k, 0) + n
            ),
        )
        a = make_replica(gpt, "a", clock, prefix_cache=True)
        b = make_replica(gpt, "b", clock, prefix_cache=True)
        prompt = list(range(1, 17))
        b.sched.submit(Request(prompt=list(prompt), max_new_tokens=2))
        b.sched.run()
        router.submit(Request(prompt=list(prompt), max_new_tokens=2))
        router.submit(Request(prompt=[60, 61, 62, 63], max_new_tokens=2))
        assert router.dispatch([a, b], tick=0) == 2
        # exactly the shared-prompt request rode affinity, onto b
        assert counts["fleet/prefix_affinity_hits"] == 1
        assert len(b.sched.queue) == 1 and len(a.sched.queue) == 1
        a.sched.run()
        b.sched.run()
        assert a.sched.pool.in_use - len(
            a.sched.prefix.cached_pages()
        ) == 0

    def test_crash_evacuates_leak_clean_with_cache_armed(self, gpt):
        """A crash mid-traffic with the prefix cache holding pages:
        evacuation flushes the cache, the pool is provably empty, and
        the evacuated requests finish elsewhere — the fleet-wide
        ledger stays exact."""
        clock = VClock()
        fleet = make_fleet(gpt, clock, n=2, max_retries=3,
                           prefix_cache=True)
        shared = list(range(1, 20))  # partial-tail prompt
        reqs = [
            fleet.submit(Request(prompt=list(shared), max_new_tokens=12))
            for _ in range(4)
        ]
        for _ in range(3):  # route + admit somewhere
            fleet.step()
            clock.advance()
        victim = next(
            rep for rep in fleet.replicas if rep.sched.pending
        )
        fleet.crash(victim)
        assert victim.state == DEAD
        assert victim.sched.pool.in_use == 0  # cache flushed + evacuated
        pump(fleet, clock, reqs)
        assert all(r.status == "done" for r in reqs)
        assert fleet.completed_count() == 4
        # the exact-ledger re-proof passes with caches armed: a live
        # replica's residual pages are exactly its cached runs, the
        # dead one's pool is exactly empty
        held = fleet.leak_check()
        for rep in fleet.replicas:
            cached = (len(rep.sched.prefix.cached_pages())
                      if rep.sched.prefix is not None else 0)
            assert held[rep.name] == cached
        assert held[victim.name] == 0

    def test_preempt_drain_flushes_cache_and_migrates(self, gpt):
        clock = VClock()
        fleet = make_fleet(gpt, clock, n=2, prefix_cache=True)
        shared = list(range(1, 17))
        reqs = [
            fleet.submit(Request(prompt=list(shared), max_new_tokens=12))
            for _ in range(4)
        ]
        for _ in range(3):
            fleet.step()
            clock.advance()
        victim = next(
            rep for rep in fleet.replicas if rep.sched.pending
        )
        fleet.preempt(victim)
        pump(fleet, clock, reqs)
        assert victim.state == DEAD
        assert victim.sched.pool.in_use == 0  # drain sealed cache-clean
        assert all(r.status == "done" for r in reqs)
        held = fleet.leak_check()
        assert held[victim.name] == 0
