"""≙ tests/L0/run_optimizers/test_fused_optimizer.py + test_lamb.py.

Golden for Adam/AdamW/SGD/Adagrad = torch.optim on CPU (the reference
compares its fused CUDA optimizers against torch.optim the same way);
golden for LAMB = a pure-numpy reference implementing the documented
stage1/stage2 semantics (the reference tests against a python RefLAMB).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
import torch

from apex_tpu import optimizers as opt


def make_params(seed=0, shapes=((7, 9), (33,), (4, 5, 6))):
    rng = np.random.RandomState(seed)
    return [rng.randn(*s).astype(np.float32) for s in shapes]


def run_jax(tx, params_np, grads_seq):
    params = [jnp.asarray(p) for p in params_np]
    state = tx.init(params)

    @jax.jit
    def step(params, state, grads):
        updates, state = tx.update(grads, state, params)
        new_params = jax.tree_util.tree_map(lambda p, u: p + u, params, updates)
        return new_params, state

    for g in grads_seq:
        params, state = step(params, state, [jnp.asarray(x) for x in g])
    return [np.asarray(p) for p in params]


def run_torch(opt_cls, params_np, grads_seq, **kw):
    params = [torch.tensor(p, requires_grad=True) for p in params_np]
    o = opt_cls(params, **kw)
    for g in grads_seq:
        for p, gi in zip(params, g):
            p.grad = torch.tensor(gi)
        o.step()
    return [p.detach().numpy() for p in params]


def grad_seq(n_steps, shapes=((7, 9), (33,), (4, 5, 6)), seed=100):
    rng = np.random.RandomState(seed)
    return [
        [rng.randn(*s).astype(np.float32) for s in shapes]
        for _ in range(n_steps)
    ]


@pytest.mark.parametrize("wd", [0.0, 0.1])
def test_adam_l2_mode_vs_torch(wd):
    p0, gs = make_params(), grad_seq(5)
    got = run_jax(
        opt.fused_adam(1e-2, weight_decay=wd, adam_w_mode=False), p0, gs
    )
    ref = run_torch(torch.optim.Adam, p0, gs, lr=1e-2, weight_decay=wd)
    for a, r in zip(got, ref):
        np.testing.assert_allclose(a, r, rtol=1e-5, atol=1e-6)


@pytest.mark.parametrize("wd", [0.0, 0.1])
def test_adamw_mode_vs_torch(wd):
    p0, gs = make_params(), grad_seq(5)
    got = run_jax(
        opt.fused_adam(1e-2, weight_decay=wd, adam_w_mode=True), p0, gs
    )
    ref = run_torch(torch.optim.AdamW, p0, gs, lr=1e-2, weight_decay=wd)
    for a, r in zip(got, ref):
        np.testing.assert_allclose(a, r, rtol=1e-5, atol=1e-6)


@pytest.mark.parametrize(
    "momentum,nesterov,wd,dampening",
    [(0.0, False, 0.0, 0.0), (0.9, False, 0.0, 0.0), (0.9, True, 0.01, 0.0),
     (0.9, False, 0.1, 0.1)],
)
def test_sgd_vs_torch(momentum, nesterov, wd, dampening):
    p0, gs = make_params(), grad_seq(6)
    got = run_jax(
        opt.fused_sgd(
            1e-2,
            momentum=momentum,
            nesterov=nesterov,
            weight_decay=wd,
            dampening=dampening,
        ),
        p0,
        gs,
    )
    ref = run_torch(
        torch.optim.SGD,
        p0,
        gs,
        lr=1e-2,
        momentum=momentum,
        nesterov=nesterov,
        weight_decay=wd,
        dampening=dampening,
    )
    for a, r in zip(got, ref):
        np.testing.assert_allclose(a, r, rtol=1e-5, atol=1e-6)


@pytest.mark.parametrize("wd", [0.0, 0.1])
def test_adagrad_vs_torch(wd):
    p0, gs = make_params(), grad_seq(5)
    got = run_jax(opt.fused_adagrad(1e-2, weight_decay=wd), p0, gs)
    ref = run_torch(torch.optim.Adagrad, p0, gs, lr=1e-2, weight_decay=wd)
    for a, r in zip(got, ref):
        np.testing.assert_allclose(a, r, rtol=1e-5, atol=1e-6)


# ---------------------------------------------------------------------------
# LAMB vs pure-numpy reference (≙ test_lamb.py's RefLAMB)
# ---------------------------------------------------------------------------


def ref_lamb_steps(
    params,
    grads_seq,
    lr,
    betas=(0.9, 0.999),
    eps=1e-6,
    wd=0.01,
    max_grad_norm=1.0,
    use_nvlamb=False,
    grad_averaging=True,
    bias_correction=True,
):
    b1, b2 = betas
    params = [p.copy().astype(np.float64) for p in params]
    m = [np.zeros_like(p) for p in params]
    v = [np.zeros_like(p) for p in params]
    beta3 = (1 - b1) if grad_averaging else 1.0
    for t, grads in enumerate(grads_seq, start=1):
        gnorm = np.sqrt(sum(np.sum(np.square(g.astype(np.float64))) for g in grads))
        clip = gnorm / max_grad_norm if (max_grad_norm > 0 and gnorm > max_grad_norm) else 1.0
        bc1 = 1 - b1**t if bias_correction else 1.0
        bc2 = 1 - b2**t if bias_correction else 1.0
        for i, g in enumerate(grads):
            gf = g.astype(np.float64) / clip
            m[i] = b1 * m[i] + beta3 * gf
            v[i] = b2 * v[i] + (1 - b2) * gf * gf
            u = (m[i] / bc1) / (np.sqrt(v[i] / bc2) + eps)
            if wd != 0:
                u = u + wd * params[i]
            wn = np.sqrt(np.sum(params[i] ** 2))
            un = np.sqrt(np.sum(u**2))
            ratio = wn / un if (wn > 0 and un > 0) else 1.0
            if not use_nvlamb and wd == 0:
                ratio = 1.0
            params[i] = params[i] - lr * ratio * u
    return [p.astype(np.float32) for p in params]


@pytest.mark.parametrize("wd,use_nvlamb", [(0.01, False), (0.0, False), (0.0, True)])
def test_lamb_vs_numpy_reference(wd, use_nvlamb):
    p0, gs = make_params(), grad_seq(5)
    got = run_jax(
        opt.fused_lamb(1e-2, weight_decay=wd, use_nvlamb=use_nvlamb), p0, gs
    )
    ref = ref_lamb_steps(p0, gs, 1e-2, wd=wd, use_nvlamb=use_nvlamb)
    for a, r in zip(got, ref):
        np.testing.assert_allclose(a, r, rtol=1e-4, atol=1e-5)


def test_lamb_grad_clipping_engages():
    p0 = make_params()
    big = [[g * 100 for g in gs] for gs in grad_seq(2)]
    got = run_jax(opt.fused_lamb(1e-2), p0, big)
    ref = ref_lamb_steps(p0, big, 1e-2)
    for a, r in zip(got, ref):
        np.testing.assert_allclose(a, r, rtol=1e-4, atol=1e-5)


# ---------------------------------------------------------------------------
# NovoGrad: formula check (first-step v init) + convergence
# ---------------------------------------------------------------------------


def test_novograd_first_step_matches_formula():
    p0 = [np.ones((4,), np.float32)]
    g0 = [np.full((4,), 2.0, np.float32)]
    tx = opt.fused_novograd(0.1, weight_decay=0.0, grad_averaging=False)
    state = tx.init([jnp.asarray(p) for p in p0])
    updates, state = tx.update(
        [jnp.asarray(g) for g in g0], state, [jnp.asarray(p) for p in p0]
    )
    # v_1 = ||g||^2 = 16; m_1 = g/(sqrt(16)+eps) = 0.5; p -= 0.1*0.5
    np.testing.assert_allclose(np.asarray(updates[0]), -0.05, rtol=1e-5)


@pytest.mark.parametrize(
    "factory,steps",
    [
        (lambda: opt.fused_adam(0.05), 60),
        # LAMB/NovoGrad take (near-)unit-norm steps regardless of grad
        # magnitude, so they need more iterations on a quadratic bowl.
        (lambda: opt.fused_lamb(0.1, weight_decay=0.01), 400),
        (lambda: opt.fused_sgd(0.05, momentum=0.9), 60),
        (lambda: opt.fused_novograd(0.05, beta1=0.9, beta2=0.99), 400),
        (lambda: opt.fused_adagrad(0.5), 60),
    ],
    ids=["adam", "lamb", "sgd", "novograd", "adagrad"],
)
def test_quadratic_convergence(factory, steps):
    tx = factory()
    target = jnp.asarray(np.random.RandomState(0).randn(16).astype(np.float32))
    params = {"w": jnp.zeros(16)}
    state = tx.init(params)

    @jax.jit
    def step(params, state):
        loss, grads = jax.value_and_grad(
            lambda p: jnp.sum((p["w"] - target) ** 2)
        )(params)
        updates, state = tx.update(grads, state, params)
        params = jax.tree_util.tree_map(lambda p, u: p + u, params, updates)
        return params, state, loss

    losses = []
    for _ in range(steps):
        params, state, loss = step(params, state)
        losses.append(float(loss))
    assert losses[-1] < 0.1 * losses[0]


# ---------------------------------------------------------------------------
# LARC / clip_grad / multi_tensor
# ---------------------------------------------------------------------------


def test_larc_scales_gradients():
    lr, tc = 0.1, 0.02
    p = [jnp.full((10,), 2.0)]
    g = [jnp.full((10,), 1.0)]
    tx = opt.larc(learning_rate=lr, trust_coefficient=tc, clip=False)
    state = tx.init(p)
    scaled, _ = tx.update(g, state, p)
    p_norm = np.sqrt(10 * 4.0)
    g_norm = np.sqrt(10.0)
    expect = tc * p_norm / (g_norm + 1e-8)
    np.testing.assert_allclose(np.asarray(scaled[0]), expect, rtol=1e-5)

    # clip mode caps the multiplier at local_lr/lr but never amplifies past 1
    tx2 = opt.larc(learning_rate=lr, trust_coefficient=tc, clip=True)
    scaled2, _ = tx2.update(g, tx2.init(p), p)
    expect2 = min(expect / lr, 1.0)
    np.testing.assert_allclose(np.asarray(scaled2[0]), expect2, rtol=1e-5)


def test_larc_zero_param_passthrough():
    p = [jnp.zeros((5,))]
    g = [jnp.ones((5,))]
    tx = opt.larc(learning_rate=0.1)
    scaled, _ = tx.update(g, tx.init(p), p)
    np.testing.assert_allclose(np.asarray(scaled[0]), 1.0)


def test_clip_grad_norm():
    g = {"a": jnp.full((3,), 4.0), "b": jnp.full((4,), 3.0)}
    total = float(np.sqrt(3 * 16 + 4 * 9))
    clipped, norm = opt.clip_grad_norm(g, max_norm=1.0)
    np.testing.assert_allclose(float(norm), total, rtol=1e-5)
    cn = opt.global_norm(clipped)
    np.testing.assert_allclose(float(cn), 1.0, rtol=1e-4)
    # under the limit: untouched
    same, _ = opt.clip_grad_norm(g, max_norm=100.0)
    np.testing.assert_allclose(np.asarray(same["a"]), 4.0, rtol=1e-5)


def test_scale_with_overflow_check():
    ok = {"a": jnp.ones((4,)), "b": jnp.full((2,), 2.0)}
    scaled, flag = opt.scale_with_overflow_check(ok, 0.5)
    assert float(flag) == 0.0
    np.testing.assert_allclose(np.asarray(scaled["a"]), 0.5)
    bad = {"a": jnp.array([1.0, jnp.inf]), "b": jnp.ones((2,))}
    _, flag = opt.scale_with_overflow_check(bad, 0.5)
    assert float(flag) == 1.0
    nan = {"a": jnp.array([1.0, jnp.nan]), "b": jnp.ones((2,))}
    _, flag = opt.scale_with_overflow_check(nan, 0.5)
    assert float(flag) == 1.0


def test_per_tensor_norm():
    t = {"x": jnp.full((4,), 2.0), "y": jnp.full((9,), 1.0)}
    norms = opt.per_tensor_norm(t)
    np.testing.assert_allclose(float(norms["x"]), 4.0, rtol=1e-6)
    np.testing.assert_allclose(float(norms["y"]), 3.0, rtol=1e-6)


def test_schedule_is_zero_based():
    # first update must see lr(0), matching optax's schedule convention
    seen = []

    def sched(count):
        seen.append(1)
        return jnp.where(count == 0, 1.0, 0.0)

    tx = opt.fused_sgd(learning_rate=sched)
    p = [jnp.zeros((2,))]
    g = [jnp.ones((2,))]
    state = tx.init(p)
    updates, state = tx.update(g, state, p)
    np.testing.assert_allclose(np.asarray(updates[0]), -1.0)  # lr(0) == 1
    updates, state = tx.update(g, state, p)
    np.testing.assert_allclose(np.asarray(updates[0]), 0.0)  # lr(1) == 0


def test_sgd_updates_carry_param_dtype():
    # bf16 grads must not truncate fp32 master-weight updates
    p = [jnp.ones((4,), jnp.float32)]
    g = [jnp.full((4,), 1e-3, jnp.bfloat16)]
    tx = opt.fused_sgd(1e-3, momentum=0.9)
    updates, _ = tx.update(g, tx.init(p), p)
    assert updates[0].dtype == jnp.float32


def test_larc_zero_grad_passthrough_with_wd():
    # frozen param (zero grad) must not receive a weight-decay pseudo-grad
    p = [jnp.full((5,), 2.0)]
    g = [jnp.zeros((5,))]
    tx = opt.larc(learning_rate=0.1, weight_decay=0.01)
    scaled, _ = tx.update(g, tx.init(p), p)
    np.testing.assert_allclose(np.asarray(scaled[0]), 0.0)


def test_class_wrappers():
    params = [jnp.ones((8,))]
    grads = [jnp.full((8,), 0.5)]
    for cls in (opt.FusedAdam, opt.FusedLAMB, opt.FusedSGD, opt.FusedNovoGrad,
                opt.FusedAdagrad):
        o = cls(params, lr=0.01)
        new_params = o.step(grads, params)
        assert not np.allclose(np.asarray(new_params[0]), np.asarray(params[0]))
        # second step uses advanced state
        newer = o.step(grads, new_params)
        assert int(o.state.count) == 2
        assert newer[0].shape == (8,)
