"""Example smoke tests (VERDICT r1 weak item 7 / next-round item 9).

The reference runs its ImageNet example as the L1 test harness
(SURVEY §4.2); the analog here: every ``examples/`` script must complete a
couple of synthetic-data steps on the CPU mesh.  Each runs in a
subprocess (own backend, own argv) so example-level breakage — imports,
argparse, train-loop wiring — fails THIS suite instead of rotting.
"""

import os
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _run_example(relpath, argv, n_devices=2, timeout=420):
    code = (
        "import sys\n"
        f"sys.argv = {['x'] + argv!r}\n"
        "import jax\n"
        "jax.config.update('jax_platforms', 'cpu')\n"
        "import runpy\n"
        f"runpy.run_path({os.path.join(REPO, relpath)!r}, "
        "run_name='__main__')\n"
    )
    env = dict(os.environ)
    env["XLA_FLAGS"] = (
        env.get("XLA_FLAGS", "")
        + f" --xla_force_host_platform_device_count={n_devices}"
    )
    env.pop("JAX_PLATFORMS", None)
    proc = subprocess.run(
        [sys.executable, "-c", code],
        env=env,
        cwd=REPO,
        stdout=subprocess.PIPE,
        stderr=subprocess.STDOUT,
        text=True,
        timeout=timeout,
    )
    assert proc.returncode == 0, (
        f"{relpath} {argv} failed:\n{proc.stdout[-3000:]}"
    )
    return proc.stdout


@pytest.mark.parametrize("opt_level", ["O1", "O2"])
def test_imagenet_amp_smoke(opt_level):
    out = _run_example(
        "examples/imagenet/main_amp.py",
        [
            "--opt-level", opt_level, "--steps", "2", "--batch-size", "8",
            "--image-size", "32", "--num-classes", "10",
        ],
    )
    assert "loss" in out.lower() or "img/s" in out.lower(), out[-500:]


def test_imagenet_amp_syncbn_smoke():
    _run_example(
        "examples/imagenet/main_amp.py",
        [
            "--opt-level", "O0", "--steps", "2", "--batch-size", "8",
            "--image-size", "32", "--num-classes", "10", "--sync-bn",
        ],
    )


def test_dcgan_amp_smoke():
    _run_example(
        "examples/dcgan/main_amp.py",
        ["--steps", "2", "--batch", "4", "--zdim", "8"],
    )


def test_simple_ddp_smoke():
    out = _run_example(
        "examples/simple/distributed/distributed_data_parallel.py", []
    )
    assert "devices: 2" in out, out[-500:]


def test_simple_resilient_accum_smoke(tmp_path):
    """Resilient loop + DDP gradient accumulation (no_sync boundary
    sync, int8 wire) over a 2-device dp mesh."""
    out = _run_example(
        "examples/simple/resilient/train_resilient.py",
        ["--steps", "8", "--accum", "2", "--wire", "int8",
         "--save-every", "4", "--dir", str(tmp_path / "demo")],
        n_devices=2,
    )
    assert "dp=2, accum=2, wire=int8" in out, out[-500:]
    assert "final loss" in out, out[-500:]


def test_bert_pretrain_tiny_smoke():
    # default path: packed masked-position MLM head (the recipe input)
    _run_example("examples/bert/pretrain_bert.py", ["--tiny"])


def test_bert_pretrain_dense_head_smoke():
    # --max-predictions-per-seq 0 keeps the dense-label MLM head
    _run_example(
        "examples/bert/pretrain_bert.py",
        ["--tiny", "--max-predictions-per-seq", "0"],
    )


def test_gpt_train_tiny_smoke():
    out = _run_example(
        "examples/gpt/train_gpt.py",
        ["--tiny", "--steps", "4", "--batch", "4", "--seq-len", "64"],
    )
    assert "chunk 0: loss" in out, out[-500:]


def test_gpt_train_pp_smoke():
    """Pipeline-parallel LM example: 1F1B, loss finite and printed."""
    out = _run_example(
        "examples/gpt/train_gpt_pp.py",
        ["--pp", "2", "--steps", "3", "--layers", "2", "--seq", "16",
         "--hidden", "32", "--vocab", "64"],
        n_devices=2,
    )
    assert "pipeline LM: pp=2 (1F1B)" in out, out[-500:]
    assert "step   2" in out, out[-500:]


def test_gpt_train_pp_interleaved_smoke():
    """Interleaved virtual-stage LM example (vpp=2)."""
    out = _run_example(
        "examples/gpt/train_gpt_pp.py",
        ["--pp", "2", "--vpp", "2", "--steps", "3", "--layers", "4",
         "--seq", "16", "--hidden", "32", "--vocab", "64"],
        n_devices=2,
    )
    assert "interleaved vpp=2" in out, out[-500:]
    assert "step   2" in out, out[-500:]


def test_gpt_train_pp_hand_1f1b_smoke():
    """Hand-scheduled 1F1B (stash ring) LM example end-to-end."""
    out = _run_example(
        "examples/gpt/train_gpt_pp.py",
        ["--pp", "2", "--hand-1f1b", "--steps", "3", "--layers", "2",
         "--seq", "16", "--hidden", "32", "--vocab", "64"],
        n_devices=2,
    )
    assert "hand-1F1B stash=residuals" in out, out[-500:]
    assert "step   2" in out, out[-500:]


def test_gpt_train_pp_hand_interleaved_smoke():
    """Hand-scheduled INTERLEAVED 1F1B (chunk stash ring, --vpp composed
    with --hand-1f1b) LM example end-to-end."""
    out = _run_example(
        "examples/gpt/train_gpt_pp.py",
        ["--pp", "2", "--vpp", "2", "--hand-1f1b", "--steps", "3",
         "--layers", "4", "--seq", "16", "--hidden", "32",
         "--vocab", "64", "--nm", "4"],
        n_devices=2,
    )
    assert "hand-interleaved-1F1B vpp=2 stash=residuals" in out, out[-500:]
    assert "step   2" in out, out[-500:]


def test_gpt_train_cp_ring_smoke():
    """Context-parallel ring attention end-to-end in the example."""
    out = _run_example(
        "examples/gpt/train_gpt.py",
        [
            "--tiny", "--steps", "4", "--batch", "2", "--seq-len", "64",
            "--context-parallel", "ring", "--cp", "2",
        ],
        n_devices=4,
    )
    assert "cp=2(ring)" in out, out[-500:]


def test_gpt_train_cp_zigzag_smoke():
    """The causal-load-balanced zigzag layout end-to-end in the example
    (layout-aware input sharding + zigzag RoPE + zigzag loss shift)."""
    out = _run_example(
        "examples/gpt/train_gpt.py",
        [
            "--tiny", "--steps", "4", "--batch", "2", "--seq-len", "64",
            "--context-parallel", "ring_zigzag", "--cp", "2",
        ],
        n_devices=4,
    )
    assert "cp=2(ring_zigzag)" in out, out[-500:]


def test_gpt_train_tp_sp_moe_smoke():
    out = _run_example(
        "examples/gpt/train_gpt.py",
        [
            "--tiny", "--steps", "4", "--batch", "2", "--seq-len", "64",
            "--tp", "2", "--sequence-parallel", "--num-experts", "4",
        ],
        n_devices=4,
    )
    assert "sp=True experts=4" in out, out[-500:]


def test_bert_pretrain_checkpoint_resume(tmp_path):
    """Train 8 steps with checkpointing, resume to 16, and compare with
    an uninterrupted 16-step run: the resumed run must pick up at step 8
    AND produce the same remaining loss trajectory (bit-exact params from
    the checkpoint + fast-forwarded deterministic data stream)."""

    def losses(out):
        return [
            line.split("loss ", 1)[1]
            for line in out.splitlines()
            if line.startswith("chunk ")
        ]

    d = str(tmp_path / "ck")
    args = ["--tiny", "--ckpt-dir", d, "--save-every", "4", "--chunk", "4"]
    _run_example(
        "examples/bert/pretrain_bert.py", args + ["--steps", "8"]
    )
    out_resumed = _run_example(
        "examples/bert/pretrain_bert.py",
        args + ["--steps", "16", "--resume"],
    )
    assert "resumed from step 8" in out_resumed, out_resumed[-800:]
    out_full = _run_example(
        "examples/bert/pretrain_bert.py",
        ["--tiny", "--chunk", "4", "--steps", "16"],
    )
    # resumed chunks 0..1 == uninterrupted chunks 2..3 (steps 8..16)
    assert losses(out_resumed) == losses(out_full)[2:], (
        out_resumed[-600:],
        out_full[-600:],
    )


def test_serve_gpt_smoke(tmp_path):
    """Train -> checkpoint -> restore (bit-exact assert inside the
    example) -> serve through the AOT engine + paged cache + scheduler;
    the JSONL must carry the serving TTFT/throughput gauges."""
    import json

    d = str(tmp_path / "serve_demo")
    out = _run_example(
        "examples/simple/serve/serve_gpt.py",
        ["--dir", d, "--train-steps", "6", "--requests", "3",
         "--metrics-out", os.path.join(d, "serve.jsonl")],
        n_devices=1,
    )
    assert "round-trips: restored == trained" in out, out[-800:]
    assert "served 3 requests (0 shed)" in out, out[-800:]
    recs = [
        json.loads(l)
        for l in open(os.path.join(d, "serve.jsonl"))
        if l.strip()
    ]
    metrics = {r["metric"] for r in recs}
    assert {"serve/ttft_ms", "serve/tokens_per_s"} <= metrics, metrics
