"""The shared gradient-sync engine (parallel/comm.py): wire formats,
chunking, HLO verification hooks, and its two consumers (DDP and the
ZeRO optimizers) on the 8-device CPU mesh.

Acceptance pins (ISSUE 2): the chunked int8 sync emits a FIXED
collective count independent of tree size; its ring wire bytes are
<= ~30% of the f32 path; optimizer numerics stay within the
INT8WIRE_SENSITIVITY.json envelope of the exact-psum path.
"""

import json
import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from apex_tpu import parallel_state as ps
from apex_tpu.parallel import (
    DistributedDataParallel,
    DistributedFusedAdam,
    DistributedFusedLAMB,
    all_reduce_gradients,
    comm,
)

DP = 8
REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _run(fn, tree):
    """tree leaves have a leading (DP,) axis of per-rank values."""
    mesh = ps.initialize_model_parallel(devices=jax.devices()[:DP])

    def f(tree):
        local = jax.tree_util.tree_map(lambda x: x[0], tree)
        out = fn(local)
        return jax.tree_util.tree_map(lambda x: x[None], out)

    out = jax.jit(
        jax.shard_map(
            f, mesh=mesh, in_specs=(P("dp"),), out_specs=P("dp"),
            check_vma=False,
        )
    )(tree)
    ps.destroy_model_parallel()
    return out


def _lower_sync(tree, **kwargs):
    """Compiled-HLO collective summary of a sync_gradients call (AOT —
    compiles, never executes)."""
    mesh = ps.initialize_model_parallel(devices=jax.devices()[:DP])
    fn = jax.jit(
        jax.shard_map(
            lambda t: comm.sync_gradients(t, **kwargs),
            mesh=mesh, in_specs=(P(),), out_specs=P(), check_vma=False,
        )
    )
    summary = comm.compiled_collectives(fn, tree)
    ps.destroy_model_parallel()
    return summary


# ---------------------------------------------------------------------------
# pure-python units: chunk heuristic + HLO parser
# ---------------------------------------------------------------------------


def test_resolve_chunks_heuristic_and_overrides(monkeypatch):
    monkeypatch.delenv(comm.ENV_CHUNKS, raising=False)
    # bandwidth heuristic: ~4 MiB per chunk, capped at 16
    assert comm.resolve_chunks(1) == 1
    assert comm.resolve_chunks(comm.TARGET_CHUNK_BYTES) == 1
    assert comm.resolve_chunks(2 * comm.TARGET_CHUNK_BYTES + 1) == 3
    assert comm.resolve_chunks(1 << 40) == 16
    # explicit beats heuristic; hard-capped at 64
    assert comm.resolve_chunks(1 << 40, chunks=2) == 2
    assert comm.resolve_chunks(1, chunks=100) == 64
    assert comm.resolve_chunks(1, chunks=0) == 1
    # env beats both
    monkeypatch.setenv(comm.ENV_CHUNKS, "7")
    assert comm.resolve_chunks(1, chunks=2) == 7
    assert comm.chunks_requested(None)
    monkeypatch.delenv(comm.ENV_CHUNKS)
    assert not comm.chunks_requested(None)
    assert comm.chunks_requested(3)


def test_chunk_bounds_alignment_and_raggedness():
    assert comm._chunk_bounds(10, 1) == [(0, 10)]
    assert comm._chunk_bounds(10, 3) == [(0, 3), (3, 6), (6, 10)]
    # aligned interior edges; final chunk carries the ragged tail
    assert comm._chunk_bounds(663, 4, align=256) == [
        (0, 256), (256, 512), (512, 663)
    ]
    # buffer smaller than one aligned chunk collapses to a single span
    assert comm._chunk_bounds(100, 4, align=256) == [(0, 100)]
    # spans tile [0, n) exactly
    for n, k, a in ((1000, 7, 1), (4096, 3, 256), (5, 9, 1)):
        b = comm._chunk_bounds(n, k, a)
        assert b[0][0] == 0 and b[-1][1] == n
        assert all(x[1] == y[0] for x, y in zip(b, b[1:]))


def test_collective_summary_and_ring_bytes():
    hlo = """
ENTRY %main {
  %p0 = f32[1024]{0} parameter(0)
  %rs = f32[128]{0} reduce-scatter(f32[1024]{0} %p0), dimensions={0}
  %q = s8[1040]{0} fusion(%rs), kind=kLoop, calls=%fc
  %ag = s8[8,1040]{1,0} all-gather(s8[1040]{0} %q), dimensions={0}
}
"""
    s = comm.collective_summary(hlo)
    assert s["reduce-scatter"] == {"count": 1, "bytes": 128 * 4}
    assert s["all-gather"] == {"count": 1, "bytes": 8 * 1040}
    # notation-normalized ring traffic: RS prints the SHARD, AG the FULL
    t = comm.ring_wire_bytes(s, world=8)
    assert t == pytest.approx(128 * 4 * 7 + 8 * 1040 * 7 / 8)


def test_wire_bytes_per_element():
    assert comm.wire_bytes_per_element("f32") == 4.0
    assert comm.wire_bytes_per_element("bf16") == 2.0
    assert comm.wire_bytes_per_element("int8", block=256) == pytest.approx(
        1.015625
    )
    with pytest.raises(ValueError):
        comm.wire_bytes_per_element("fp4")


# ---------------------------------------------------------------------------
# numerics on the mesh
# ---------------------------------------------------------------------------


def test_int8_chunked_sync_within_artifact_envelope(eight_devices):
    """Chunked int8 sync vs the exact psum, judged against the
    INT8WIRE_SENSITIVITY.json operating envelope (block=256 rows): the
    per-sync mean relative error must sit inside what the recorded
    block x model-scale sweep already showed to be training-safe."""
    rows = []
    with open(os.path.join(REPO, "INT8WIRE_SENSITIVITY.json")) as f:
        for line in f:
            rec = json.loads(line)
            if rec.get("block") == 256:
                rows.append(rec["rel_err_mean_worst_leaf"])
    assert rows, "artifact missing block=256 rows"
    envelope = max(rows)

    g = {
        "w": jax.random.normal(jax.random.PRNGKey(0), (DP, 96, 128)),
        "b": jax.random.normal(jax.random.PRNGKey(1), (DP, 8192)),
    }
    got = _run(
        lambda t: comm.sync_gradients(t, wire="int8", chunks=3, min_size=1),
        g,
    )
    want = _run(all_reduce_gradients, g)
    for k in g:
        a, b = np.asarray(got[k][0]), np.asarray(want[k][0])
        # replicated output: every rank row identical
        for r in range(1, DP):
            np.testing.assert_array_equal(np.asarray(got[k][r]), a)
        # hard bound: ~2 half-ulps of the pre-reduction block max
        gmax = np.abs(np.asarray(g[k])).max()
        assert np.abs(a - b).max() <= 2.0 / 127.0 * gmax
        # envelope: mean rel err within the recorded operating envelope
        rel = np.abs(a - b).mean() / (np.abs(b).mean() + 1e-12)
        assert rel <= envelope, (k, rel, envelope)


def test_bf16_wire_bounded_and_f32_chunked_exact(eight_devices):
    g = {"w": jax.random.normal(jax.random.PRNGKey(2), (DP, 64, 96))}
    want = _run(all_reduce_gradients, g)
    got16 = _run(
        lambda t: comm.sync_gradients(t, wire="bf16", chunks=2, min_size=1),
        g,
    )
    gmax = np.abs(np.asarray(g["w"])).max()
    # bf16 wire: one rounding per rank contribution + one on the gather;
    # 2^-8 relative-to-magnitude covers both with slack
    assert (
        np.abs(np.asarray(got16["w"][0]) - np.asarray(want["w"][0])).max()
        <= 2.0 ** -8 * gmax * 2
    )
    # f32 wire, chunked: the reduce is still exact per element
    got32 = _run(
        lambda t: comm.sync_gradients(t, wire="f32", chunks=3, min_size=1),
        g,
    )
    np.testing.assert_array_equal(
        np.asarray(got32["w"]), np.asarray(want["w"])
    )


# ---------------------------------------------------------------------------
# HLO regression: fixed collective count, bounded wire bytes
# ---------------------------------------------------------------------------


def _big_tree(n_leaves):
    # ~0.5M elements however many leaves carry them, so chunk counts
    # and byte ratios are structure- not size-limited
    per = 524288 // n_leaves
    return {f"p{i}": jnp.ones((per,), jnp.float32) for i in range(n_leaves)}


def test_chunked_int8_collective_count_independent_of_tree_size(
    eight_devices,
):
    """K-chunk int8 sync = K all-to-alls + K all-gathers, whether the
    bucket holds 2 leaves or 16 — the latency property that makes the
    bucket safe on DCN."""
    for n_leaves in (2, 16):
        s = _lower_sync(
            _big_tree(n_leaves), wire="int8", chunks=4, min_size=1
        )
        assert s["all-to-all"]["count"] == 4, (n_leaves, s)
        assert s["all-gather"]["count"] == 4, (n_leaves, s)
        assert "all-reduce" not in s, s  # no per-leaf psums leaked


def test_int8_wire_bytes_at_most_30pct_of_f32(eight_devices):
    """The acceptance bound: ring wire traffic of the chunked int8 sync
    <= 30% of the f32 path on the same tree (analytically ~25.4% =
    (1 + 4/256) / 4, plus <=1 padded tail block per chunk)."""
    tree = _big_tree(4)
    s8 = _lower_sync(tree, wire="int8", chunks=4, min_size=1)
    s32 = _lower_sync(tree, wire="f32", chunks=4, min_size=1)
    b8 = comm.ring_wire_bytes(s8, DP)
    b32 = comm.ring_wire_bytes(s32, DP)
    assert b8 > 0 and b32 > 0
    assert b8 / b32 <= 0.30, (b8, b32, b8 / b32)


def test_env_chunk_override(eight_devices, monkeypatch):
    monkeypatch.setenv(comm.ENV_CHUNKS, "5")
    s = _lower_sync(_big_tree(2), wire="int8", chunks=2, min_size=1)
    assert s["all-to-all"]["count"] == 5, s
    assert s["all-gather"]["count"] == 5, s


# ---------------------------------------------------------------------------
# ZeRO optimizers through the engine
# ---------------------------------------------------------------------------


def _toy(n=64):
    rng = np.random.RandomState(0)
    params = {
        "w1": jnp.asarray(rng.randn(8, 16) * 0.3, jnp.float32),
        "w2": jnp.asarray(rng.randn(16, 4) * 0.3, jnp.float32),
    }
    batch = {
        "x": jnp.asarray(rng.randn(n, 8), jnp.float32),
        "y": jnp.asarray(rng.randn(n, 4), jnp.float32),
    }

    def loss(p, b):
        pred = jnp.tanh(b["x"] @ p["w1"]) @ p["w2"]
        return jnp.mean((pred - b["y"]) ** 2)

    return params, batch, loss


def _train_dist(make_opt, steps=4):
    mesh = ps.initialize_model_parallel()
    params, batch, loss = _toy()
    dist = make_opt()
    state = dist.init(params, world=DP)
    step = dist.make_train_step(loss, mesh)
    losses = []
    for _ in range(steps):
        params, state, l = step(params, state, batch)
        losses.append(float(l))
    ps.destroy_model_parallel()
    return params, losses


@pytest.mark.parametrize("opt_cls", [DistributedFusedAdam,
                                     DistributedFusedLAMB])
def test_zero_quantized_wire_tracks_f32(eight_devices, opt_cls):
    """wire="int8" grads + bf16 param gather: the recommended
    aggressive setting stays within a few percent of the f32-wire run
    and still optimizes."""
    kw = dict(lr=1e-2, weight_decay=0.01)
    p_ref, l_ref = _train_dist(lambda: opt_cls(**kw))
    p_q, l_q = _train_dist(
        lambda: opt_cls(**kw, wire="int8", param_wire="bf16", chunks=2)
    )
    for a, r in zip(
        jax.tree_util.tree_leaves(p_q), jax.tree_util.tree_leaves(p_ref)
    ):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(r), rtol=0.05, atol=5e-3
        )
    assert l_q[-1] < l_q[0]
    assert abs(l_q[-1] - l_ref[-1]) < 0.05 * max(l_ref[0], 1e-6)


def test_zero_master_weights_survive_lossy_param_wire(eight_devices):
    """lr far below the params' bf16 ulp: updates must accumulate in the
    f32 master shard (state.master) instead of being re-rounded away by
    the bf16 param gather every step — the classic ZeRO master-weights
    property.  The replicated working copy may only ever be one wire
    rounding away from the masters."""
    mesh = ps.initialize_model_parallel()
    params, batch, loss = _toy()
    dist = DistributedFusedAdam(lr=1e-5, param_wire="bf16")
    state = dist.init(params, world=DP)
    flat0 = np.asarray(state.master)
    step = dist.make_train_step(loss, mesh)
    p, s = params, state
    for _ in range(10):
        p, s, _ = step(p, s, batch)
    # masters accumulated ~10 adam updates of ~lr each; re-rounding
    # against a bf16 grid (ulp ~1e-3 at |w|~0.3) would leave ~0
    drift = np.abs(np.asarray(s.master) - flat0).max()
    assert drift >= 5e-5, drift
    # working copy == masters up to ONE bf16 rounding
    gathered = np.concatenate(
        [np.asarray(l).ravel() for l in jax.tree_util.tree_leaves(p)]
    )
    masters = np.asarray(s.master)[: gathered.size]
    np.testing.assert_allclose(gathered, masters, rtol=2.0 ** -8)
    ps.destroy_model_parallel()


def test_zero_hlo_chunked_counts(eight_devices):
    """The full ZeRO step at wire="int8", chunks=3: grad reduce-scatter
    = 3 all-to-alls, param all-gather = 3 all-gathers, independent of
    how many leaves the flat buffer packs."""
    mesh = ps.initialize_model_parallel()

    def build(n_leaves):
        rng = np.random.RandomState(1)
        per = 32768 // n_leaves
        params = {
            f"w{i}": jnp.asarray(rng.randn(per) * 0.1, jnp.float32)
            for i in range(n_leaves)
        }
        batch = jnp.asarray(rng.randn(DP * 4, per), jnp.float32)

        def loss(p, b):
            s = sum(b @ p[k] for k in p)
            return jnp.mean(s**2)

        dist = DistributedFusedAdam(lr=1e-3, wire="int8", chunks=3)
        dist.init(params, world=DP)
        step = dist.make_train_step(loss, mesh)
        state = dist.init(params, world=DP)
        return comm.compiled_collectives(step, params, state, batch)

    for n_leaves in (1, 8):
        s = build(n_leaves)
        assert s["all-to-all"]["count"] == 3, (n_leaves, s)
        assert s["all-gather"]["count"] == 3, (n_leaves, s)
    ps.destroy_model_parallel()


# ---------------------------------------------------------------------------
# DDP: no_sync + gradient accumulation through the same engine
# ---------------------------------------------------------------------------


def _ddp_toy():
    rng = np.random.RandomState(0)
    params = {
        "w1": jnp.asarray(rng.randn(8, 16) * 0.3, jnp.float32),
        "w2": jnp.asarray(rng.randn(16, 4) * 0.3, jnp.float32),
    }
    batch = {
        "x": jnp.asarray(rng.randn(64, 8), jnp.float32),
        "y": jnp.asarray(rng.randn(64, 4), jnp.float32),
    }

    def loss(p, b):
        pred = jnp.tanh(b["x"] @ p["w1"]) @ p["w2"]
        return jnp.mean((pred - b["y"]) ** 2)

    return params, batch, loss


def test_no_sync_returns_local_grads_then_engine_syncs(eight_devices):
    mesh = ps.initialize_model_parallel()
    params, batch, loss = _ddp_toy()
    ddp = DistributedDataParallel(loss, gradient_average=False)

    def f(p, b):
        with ddp.no_sync():
            _, g_local = ddp.value_and_grad(p, b)
        # local grads differ per shard; the engine sync (SUM semantics
        # here) must equal a manual psum of the same locals
        g_engine = ddp.all_reduce_gradients(g_local)
        g_manual = jax.tree_util.tree_map(
            lambda x: jax.lax.psum(x, "dp"), g_local
        )
        spread = sum(
            jnp.max(jnp.abs(x - jax.lax.pmean(x, "dp")))
            for x in jax.tree_util.tree_leaves(g_local)
        )
        return g_engine, g_manual, spread

    g_engine, g_manual, spread = jax.jit(
        jax.shard_map(
            f, mesh=mesh, in_specs=(P(), P("dp")),
            out_specs=(P(), P(), P()),
        )
    )(params, batch)
    assert float(spread) > 1e-6  # grads really were local
    for a, b in zip(
        jax.tree_util.tree_leaves(g_engine),
        jax.tree_util.tree_leaves(g_manual),
    ):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=1e-6, atol=1e-7
        )


def test_accum_step_matches_single_big_batch(eight_devices):
    """make_step(accum_steps=4) over (4, 16, ...) microbatches ==
    make_step over the 64-row batch: mean-of-means equals the full mean
    for equal microbatches, so grads, losses, and params all agree."""
    from apex_tpu.optimizers import fused_adam

    mesh = ps.initialize_model_parallel()
    params, batch, loss = _ddp_toy()
    micro = jax.tree_util.tree_map(
        lambda x: x.reshape(4, 16, *x.shape[1:]), batch
    )
    tx = fused_adam(5e-2)

    ddp = DistributedDataParallel(loss)
    step1 = ddp.make_step(tx, mesh)
    step4 = ddp.make_step(tx, mesh, accum_steps=4)

    p1, o1 = params, tx.init(params)
    p4, o4 = params, tx.init(params)
    for _ in range(3):
        p1, o1, l1 = step1(p1, o1, batch)
        p4, o4, l4 = step4(p4, o4, micro)
    np.testing.assert_allclose(float(l1), float(l4), rtol=1e-5)
    for a, b in zip(
        jax.tree_util.tree_leaves(p1), jax.tree_util.tree_leaves(p4)
    ):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=1e-5, atol=1e-6
        )


def test_accum_with_quantized_boundary_sync_trains(eight_devices):
    """Accumulation + int8 boundary sync: the combination the satellite
    wires into the resilient example — K local microbatches, ONE
    quantized wire payment — still trains the toy to a lower loss."""
    from apex_tpu.optimizers import fused_adam

    mesh = ps.initialize_model_parallel()
    params, batch, loss = _ddp_toy()
    micro = jax.tree_util.tree_map(
        lambda x: x.reshape(4, 16, *x.shape[1:]), batch
    )
    tx = fused_adam(5e-2)
    ddp = DistributedDataParallel(loss, wire="int8", min_size=1)
    step = ddp.make_step(tx, mesh, accum_steps=4)
    p, o = params, tx.init(params)
    losses = []
    for _ in range(15):
        p, o, l = step(p, o, micro)
        losses.append(float(l))
    assert losses[-1] < 0.6 * losses[0], losses


def test_make_step_rejects_bad_accum(eight_devices):
    mesh = ps.initialize_model_parallel()
    params, batch, loss = _ddp_toy()
    ddp = DistributedDataParallel(loss)
    from apex_tpu.optimizers import fused_adam

    with pytest.raises(ValueError):
        ddp.make_step(fused_adam(1e-3), mesh, accum_steps=0)


def test_ddp_rejects_unknown_wire():
    with pytest.raises(ValueError):
        DistributedDataParallel(lambda p, b: 0.0, wire="fp4")
    with pytest.raises(ValueError):
        DistributedFusedAdam(wire="int4")
