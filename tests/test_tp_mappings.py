"""≙ tests/L0/run_transformer/test_mapping.py — the collective octet."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from apex_tpu import parallel_state as ps
from apex_tpu.transformer import tensor_parallel as tp


def tp_mesh():
    return ps.initialize_model_parallel(tensor_model_parallel_size=8)


def run_tp(fn, *args, in_specs, out_specs):
    mesh = ps.get_mesh()
    return jax.jit(
        jax.shard_map(
            fn, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
            check_vma=False,
        )
    )(*args)


def test_copy_identity_fwd_allreduce_bwd(eight_devices):
    tp_mesh()
    x = jnp.arange(8.0)

    def f(x):
        y = tp.copy_to_tensor_model_parallel_region(x)
        # per-rank loss varying over tp: grad of sum over ranks == psum
        rank = jax.lax.axis_index("tp").astype(jnp.float32)
        return jnp.sum(y) * (rank + 1.0)

    def g(x):
        return jax.grad(f)(x)

    out = run_tp(g, x, in_specs=(P(),), out_specs=P())
    # sum of (rank+1) over 8 ranks = 36
    np.testing.assert_allclose(np.asarray(out), 36.0)


def test_reduce_fwd(eight_devices):
    tp_mesh()
    x = jnp.ones((4,))
    out = run_tp(
        lambda x: tp.reduce_from_tensor_model_parallel_region(x),
        x,
        in_specs=(P(),),
        out_specs=P(),
    )
    np.testing.assert_allclose(np.asarray(out), 8.0)


def test_scatter_gather_last_dim_roundtrip(eight_devices):
    tp_mesh()
    x = jnp.arange(16.0).reshape(2, 8)

    def f(x):
        s = tp.scatter_to_tensor_model_parallel_region(x)
        assert s.shape == (2, 1)
        return tp.gather_from_tensor_model_parallel_region(s)

    out = run_tp(f, x, in_specs=(P(),), out_specs=P())
    np.testing.assert_allclose(np.asarray(out), np.asarray(x))


def test_sequence_parallel_roundtrip(eight_devices):
    tp_mesh()
    x = jnp.arange(32.0).reshape(16, 2)

    def f(x):
        s = tp.scatter_to_sequence_parallel_region(x)
        assert s.shape == (2, 2)
        return tp.gather_from_sequence_parallel_region(s)

    out = run_tp(f, x, in_specs=(P(),), out_specs=P())
    np.testing.assert_allclose(np.asarray(out), np.asarray(x))


def test_reduce_scatter_fwd(eight_devices):
    tp_mesh()
    x = jnp.ones((16, 2))

    def f(x):
        rs = tp.reduce_scatter_to_sequence_parallel_region(x)
        assert rs.shape == (2, 2)
        return tp.gather_from_sequence_parallel_region(rs)

    out = run_tp(f, x, in_specs=(P(),), out_specs=P())
    np.testing.assert_allclose(np.asarray(out), 8.0)


def test_gather_bwd_is_reduce_scatter(eight_devices):
    tp_mesh()
    x = jnp.ones((2, 2))  # per-rank seq shard

    def f(x):
        full = tp.gather_from_sequence_parallel_region(x)  # (16, 2)
        rank = jax.lax.axis_index("tp").astype(jnp.float32)
        return jnp.sum(full) * (rank + 1.0)

    def g(x):
        return jax.grad(f)(x)[None]

    out = run_tp(g, x, in_specs=(P(),), out_specs=P("tp"))
    # d/dx_local = sum over ranks of (rank+1) for my seq slice = 36
    np.testing.assert_allclose(np.asarray(out), 36.0)


def test_split_utils():
    x = jnp.arange(12.0).reshape(3, 4)
    parts = tp.split_tensor_along_last_dim(x, 2)
    assert len(parts) == 2 and parts[0].shape == (3, 2)
    with pytest.raises(ValueError):
        tp.split_tensor_along_last_dim(x, 3)
    assert tp.VocabUtility.vocab_range_from_global_vocab_size(100, 2, 4) == (
        50,
        75,
    )
