"""Benchmark harness — BASELINE config #3 (north star).

BERT-Large phase-1 pretraining step (seq 128) with FusedLAMB + fused
LayerNorm + flash attention on the available TPU chip(s).  Prints ONE JSON
line: {"metric", "value", "unit", "vs_baseline"}.

MFU accounting per BASELINE.md: FLOPs/step = 6·N·T (N = param count,
T = tokens/step), peak = per-chip bf16 peak × chips.  Timing discipline:
K train steps inside one jitted ``lax.scan`` (donated params — no
host↔device churn; the idiomatic TPU train loop), a device→host transfer
of the final loss as the synchronization point, median over repeated
chunks.  (Per-step ``block_until_ready`` is unreliable over the remote
tunnel this environment routes the chip through, and per-call dispatch
would dominate at ~150 ms; the scan chunk measures the device.)
vs_baseline = MFU / 0.50 (the BASELINE.json target of ≥50% MFU).
"""

from __future__ import annotations

import argparse
import contextlib
import functools
import json
import time

import jax
import jax.numpy as jnp

# per-chip dense bf16 peak FLOP/s by device kind (public specs)
_PEAK = {
    "TPU v5 lite": 197e12,  # v5e
    "TPU v5e": 197e12,
    "TPU v5p": 459e12,
    "TPU v5": 459e12,
    "TPU v4": 275e12,
    "TPU v6 lite": 918e12,  # v6e (Trillium)
}


def _chip_peak(device) -> float:
    kind = getattr(device, "device_kind", "")
    for key, val in _PEAK.items():
        if kind.startswith(key):
            return val
    return 197e12  # conservative default


def main(trace_dir: str | None = None):
    import apex_tpu.utils
    from apex_tpu.models import (
        BertForPreTraining,
        bert_large_config,
        bert_pretrain_loss,
    )
    from apex_tpu.optimizers import fused_lamb

    seq_len, batch = 128, 128
    chunk, trials = 6, 3

    cfg = bert_large_config(remat=True)
    model = BertForPreTraining(cfg)
    tx = fused_lamb(learning_rate=1e-3, weight_decay=0.01)

    key = jax.random.PRNGKey(0)
    ids = jax.random.randint(key, (seq_len, batch), 0, cfg.vocab_size)
    batch_data = {
        "input_ids": ids,
        "token_type_ids": jnp.zeros_like(ids),
        "attention_mask": jnp.ones((batch, seq_len), jnp.int32),
        "mlm_labels": jnp.where(ids % 7 == 0, ids, -1),
        "nsp_labels": jnp.zeros((batch,), jnp.int32),
    }

    params = model.init(jax.random.PRNGKey(1), ids)
    opt_state = tx.init(params)
    n_params = sum(p.size for p in jax.tree_util.tree_leaves(params))

    @functools.partial(jax.jit, donate_argnums=(0, 1))
    def train_chunk(params, opt_state, batch_data):
        def body(carry, _):
            params, opt_state = carry
            loss, grads = jax.value_and_grad(
                lambda p: bert_pretrain_loss(p, model, batch_data)
            )(params)
            updates, opt_state = tx.update(grads, opt_state, params)
            params = jax.tree_util.tree_map(jnp.add, params, updates)
            return (params, opt_state), loss

        (params, opt_state), losses = jax.lax.scan(
            body, (params, opt_state), None, length=chunk
        )
        return params, opt_state, losses

    # warmup (compile + one chunk)
    params, opt_state, losses = train_chunk(params, opt_state, batch_data)
    loss = float(losses[-1])

    # optional profile of the steady-state window (VERDICT r1 item 5:
    # ≙ the reference's nvtx bracketing; view in TensorBoard/Perfetto)
    profile = (
        apex_tpu.utils.trace(trace_dir)
        if trace_dir
        else contextlib.nullcontext()
    )
    times = []
    with profile:
        for _ in range(trials):
            t0 = time.perf_counter()
            params, opt_state, losses = train_chunk(
                params, opt_state, batch_data
            )
            loss = float(losses[-1])  # device->host: the sync point
            times.append((time.perf_counter() - t0) / chunk)
    times.sort()
    step_time = times[len(times) // 2]  # median

    tokens = seq_len * batch
    flops = 6.0 * n_params * tokens
    peak = sum(_chip_peak(d) for d in jax.devices())
    mfu = flops / (step_time * peak)

    print(
        json.dumps(
            {
                "metric": "bert_large_lamb_mfu",
                "value": round(mfu, 4),
                "unit": "MFU (step_time_ms=%.1f, batch=%d, params=%dM, loss=%.3f)"
                % (step_time * 1e3, batch, n_params // 1_000_000, loss),
                "vs_baseline": round(mfu / 0.50, 4),
            }
        )
    )


if __name__ == "__main__":
    ap = argparse.ArgumentParser()
    ap.add_argument(
        "--trace",
        metavar="DIR",
        default=None,
        help="collect a jax.profiler trace of the timed window into DIR",
    )
    main(trace_dir=ap.parse_args().trace)
