"""Benchmark harness — the five BASELINE parity configs.

Default (no args) runs BASELINE config #3, the north star: BERT-Large
phase-1 pretraining step (seq 128) with FusedLAMB + fused LayerNorm + flash
attention, and prints ONE JSON line {"metric", "value", "unit",
"vs_baseline"} — the driver contract.  ``--config all`` (or a config name)
additionally runs the other BASELINE.md table rows:

  #1 resnet50     ResNet-50 synthetic-ImageNet train step, single device
                  (≙ examples/imagenet/main_amp.py)                [img/s]
  #3 bert_lamb    BERT-Large + FusedLAMB (north star)          [MFU, step]
  #4 mha          fused self-attention vs unfused composition
                  (≙ apex/contrib/multihead_attn plots)          [speedup]
     train3d      the composable trainer (apex_tpu.train) at dp=2, tp=2,
                  and dp=2 x tp=2 — REPLACES the old degenerate
                  ddp_syncbn (dp=1) / tp_gpt (tp=1) proxies in the
                  multi-device slot: its rows are honest only when the
                  mesh is real (dp/tp >= 2), and bench_diff
                  --check-schema refuses degenerate train3d rows
                  outright                                     [step time]

The old ddp_syncbn (#2) and tp_gpt (#5) configs remain invocable by name
for single-config comparisons against historical BENCH_all rounds:

  #2 ddp_syncbn   ResNet-50 + DDP + SyncBatchNorm over a dp mesh of all
                  available devices (≙ apex/parallel/*)            [img/s]
  #5 tp_gpt       GPT block train step over a tp mesh of all available
                  devices (≙ tensor_parallel/layers.py)       [step time]

vs_baseline: #3 = MFU / 0.50 (the BASELINE.json ≥50%-MFU target); #4 =
speedup over the unfused composition (its own reference baseline, as in the
reference's README plots); #1/#2/#5 = null — the reference publishes no
absolute numbers for these (BASELINE.md "published: {}"), so the honest
record is the measurement itself with its basis in the unit string.

MFU accounting per BASELINE.md: FLOPs/step = 6·N·T, peak = per-chip bf16
peak × chips.  Timing discipline: K steps inside one jitted ``lax.scan``
(donated carry — the idiomatic TPU train loop), a device→host transfer of
the final loss as the sync point, median over repeated chunks.  (Per-step
``block_until_ready`` is unreliable over the remote tunnel this environment
routes the chip through, and per-call dispatch would dominate at ~150 ms;
the scan chunk measures the device.)
"""

from __future__ import annotations

import argparse
import contextlib
import functools
import json
import os
import shutil
import sys
import threading
import time

import jax
import jax.numpy as jnp


# single source of truth for metric names: used by every bench's _emit
# and by the watchdog's NOT-MEASURED line, so they cannot drift
_METRIC_NAMES = {
    "resnet50": "resnet50_imgs_per_sec",
    "ddp_syncbn": "ddp_syncbn_resnet50_imgs_per_sec",
    "bert_lamb": "bert_large_lamb_mfu",
    "mha": "mha_fused_speedup",
    "tp_gpt": "tp_gpt_block_step_ms",
    "train3d": "train3d_dp2tp2_step_ms",
    "long_attn": "long_context_flash_attn_tflops",
    "zero": "zero_lamb_int8_wire_speedup",
    "serve": "serve_decode_tokens_per_s",
    "fleet": "fleet_chaos_goodput_pct",
    "all": "bert_large_lamb_mfu",  # the headline stands in for the batch
}


def _backend_watchdog(seconds: float, metric: str = _METRIC_NAMES["bert_lamb"]):
    """Fail fast if backend init hangs (the axon tunnel has been observed
    to wedge for hours — a bench that hangs is worse for the driver than
    one that exits nonzero with a diagnostic).  Disarmed once the first
    device call returns; APEX_TPU_BENCH_WATCHDOG_S=0 disables."""
    done = threading.Event()

    def watch():
        if not done.wait(seconds):
            print(
                f"bench.py: backend initialization exceeded {seconds:.0f}s "
                "(TPU tunnel unresponsive?) — aborting", file=sys.stderr,
            )
            # one honest JSON line so the driver records the outage as an
            # explicit non-measurement instead of silence (value null —
            # never a stale number).  Point at the newest mid-round
            # on-chip capture of THIS metric so the null line still
            # carries the round's real evidence.
            last = "; see BENCH_all artifacts for the last measured round"
            # Nothing below may take the watchdog down with it: a dead
            # watchdog thread means no null line, no os._exit, and a
            # driver recording silence — the exact failure this thread
            # exists to prevent.
            try:
                import glob as _glob
                import re as _re
                # Artifacts live at the repo root (tools/bench_all.py
                # anchors there), not in the driver's cwd.  Order by the
                # round number in the name, newest first (git checkouts
                # scramble mtimes; mtime only breaks ties like
                # BENCH_all_r05.json vs its r05a pre-refresh backup),
                # falling through to older files if the newest lacks
                # this metric (e.g. a partial mid-outage write).
                root = os.path.dirname(os.path.abspath(__file__))
                def _round_key(p):
                    m = _re.search(r"_r(\d+)", os.path.basename(p))
                    return (int(m.group(1)) if m else -1,
                            os.path.getmtime(p))
                paths = sorted(
                    _glob.glob(os.path.join(root, "BENCH_all_r*.json")),
                    key=_round_key, reverse=True,
                )
                for path in paths:
                    if "last on-chip" in last:
                        break
                    with open(path) as f:
                        for line in f:
                            try:
                                rec = json.loads(line)
                            except ValueError:
                                continue
                            if isinstance(rec, dict) and (
                                rec.get("metric") == metric
                            ) and rec.get("value") is not None:
                                last = (
                                    f"; last on-chip: {rec['value']} "
                                    f"({os.path.basename(path)})"
                                )
                                break
            except Exception:
                pass
            _emit(
                metric, None,
                "NOT MEASURED: TPU tunnel unresponsive "
                f"(backend init > {seconds:.0f}s)" + last, None,
            )
            os._exit(3)

    t = threading.Thread(target=watch, daemon=True)
    t.start()
    return done


_WATCHDOG_S = float(os.environ.get("APEX_TPU_BENCH_WATCHDOG_S", "900"))
# Headline remat policy (dots | sums | full) — one read shared by the
# main() fail-fast guard and bench_bert_lamb's default config.
_BENCH_POLICY = os.environ.get("APEX_TPU_BENCH_POLICY", "dots")
# --lint: run the apex_tpu.analysis passes (docs/analysis.md) over the
# headline step's jaxpr + compiled HLO and emit the finding counts as a
# metric line.  Env var so `--config all` subprocess wrappers inherit it.
_BENCH_LINT = os.environ.get("APEX_TPU_BENCH_LINT", "") == "1"

# Per-chip dense bf16 peak FLOP/s — ONE model shared with live
# telemetry (apex_tpu.observability.meter), so bench artifacts and a
# run's --metrics-out JSONL can never disagree on the MFU denominator.
from apex_tpu.observability.meter import (  # noqa: E402
    chip_peak_flops as _chip_peak,
    transformer_train_flops as _train_flops,
)

# Optional JSONL sink mirroring every _emit line (--metrics-out): the
# stdout contract for the driver stays byte-identical, the file gets
# the same records for trajectory diffing.
_METRICS_SINK = None

# Optional flight recorder (--flight / APEX_TPU_FLIGHT): every emitted
# metric line lands in its event log, and an unhandled exception dumps
# the black box — the crash forensics for a bench that dies over a
# flaky tunnel mid-config (docs/observability.md).
_FLIGHT = None

# Every emitted record, in-memory — what --gate hands tools/bench_diff.py
# after the configs finish (degenerate rows ride along; the gate excludes
# them itself, so the exclusion rule lives in ONE place).
_GATE_RECORDS = []


def _emit(metric, value, unit, vs_baseline, degenerate=False):
    """``degenerate=True`` marks a multi-device config that ran with only
    one device visible (dp=1/tp=1): the number is a valid single-chip
    measurement but does NOT exercise the config's collective path."""
    rec = {
        "metric": metric,
        "value": value,
        "unit": unit,
        "vs_baseline": vs_baseline,
    }
    if degenerate:
        rec["degenerate"] = True
    print(json.dumps(rec), flush=True)
    _GATE_RECORDS.append(rec)
    if _METRICS_SINK is not None:
        _METRICS_SINK.write(rec)
    if _FLIGHT is not None:
        _FLIGHT.note("bench_metric", **rec)


def _time_chunks(fn, carry, chunk, trials, profile=None, reduce="median"):
    """Per-step time of ``fn`` (a jitted scan chunk on ``carry``).

    Warmup (compile + one chunk) runs BEFORE the optional ``profile``
    context is entered, so a collected trace covers only steady state.
    Returns ``(step_time, carry, last_sync)`` — last_sync is the final
    synced scalar (the loss for the train benches: the cheap end-to-end
    sanity signal recorded in the unit string).
    """
    carry, sync = fn(*carry)  # warmup/compile — outside the profile window
    last = float(jnp.sum(sync))
    times = []
    with profile if profile is not None else contextlib.nullcontext():
        for _ in range(trials):
            t0 = time.perf_counter()
            carry, sync = fn(*carry)
            last = float(jnp.sum(sync))  # device->host: the sync point
            times.append((time.perf_counter() - t0) / chunk)
    times.sort()
    t = times[0] if reduce == "min" else times[len(times) // 2]
    return t, carry, last


# ---------------------------------------------------------------------------
# #3 BERT-Large + FusedLAMB (north star, the default headline)
# ---------------------------------------------------------------------------


def bench_bert_lamb(trace_dir=None, batch=128, chunk=6, trials=3,
                    cfg_kwargs=None, mlm_loss_chunks="auto",
                    max_predictions_per_seq=20, emit=True):
    """Returns (mfu, step_time, loss, mfu_exec) — mfu is the 6·N·T
    recipe-parity headline, mfu_exec the executed-FLOPs utilization
    (equal for the dense head).  ``cfg_kwargs`` overrides the tuned
    model config (tools/mfu_sweep.py reuses this function for its variants,
    so sweep numbers and the headline stay comparable).

    ``max_predictions_per_seq``: fixed-K masked-position MLM head (the
    reference recipe's masked_lm_positions input; 20 is its phase-1 value
    at seq 128).  The r2 headline scored the MLM head on all 128 positions
    — ~3.1 TFLOP/step of vocab matmul where the recipe does ~0.5;
    None restores that dense-label variant.  ``mlm_loss_chunks="auto"``
    resolves to unchunked for the packed head and the measured-best 16
    for dense; an explicit None always means unchunked."""
    import apex_tpu.utils
    from apex_tpu.models import (
        BertForPreTraining,
        bert_large_config,
        bert_pretrain_loss,
    )
    from apex_tpu.optimizers import fused_lamb

    seq_len = 128
    # Measured on the v5e chip (tools/mfu_sweep.py): scan-over-layers spends
    # ~1/3 of the step copying remat saves into (L, ...) stacked buffers
    # (0.41 MFU); unrolling removes it (0.45); recomputing the attention
    # core (drops the f32 (B,H,S,S) saves) + chunking the MLM loss (the
    # 2 GB f32 logits never exist) reaches 0.53.
    if cfg_kwargs is None:
        # remat_prevent_cse=False on the unrolled path is deliberate: XLA
        # keeps whichever forward activations fit HBM instead of honoring
        # the full recompute (same values; 316 ms vs 371 ms measured) —
        # the right trade on one chip at batch 128.
        # _BENCH_POLICY lets the on-chip queue flip the headline remat
        # policy (dots vs the staged "sums" epilogue-fusion bet,
        # docs/mfu.md lever #1) without editing code mid-window.
        cfg_kwargs = dict(
            remat=True, remat_policy=_BENCH_POLICY, scan_layers=False,
            remat_attention=True, remat_prevent_cse=False,
        )
    cfg = bert_large_config(**cfg_kwargs)
    model = BertForPreTraining(cfg)
    tx = fused_lamb(learning_rate=1e-3, weight_decay=0.01)

    key = jax.random.PRNGKey(0)
    ids = jax.random.randint(key, (seq_len, batch), 0, cfg.vocab_size)
    labels = jnp.where(ids % 7 == 0, ids, -1)
    batch_data = {
        "input_ids": ids,
        "token_type_ids": jnp.zeros_like(ids),
        "attention_mask": jnp.ones((batch, seq_len), jnp.int32),
        "mlm_labels": labels,
        "nsp_labels": jnp.zeros((batch,), jnp.int32),
    }
    if max_predictions_per_seq:
        from apex_tpu.data import pack_mlm_predictions

        pos, pids, w = pack_mlm_predictions(
            labels, max_predictions_per_seq
        )
        batch_data.update(
            mlm_positions=jnp.asarray(pos),
            mlm_label_ids=jnp.asarray(pids),
            mlm_weights=jnp.asarray(w),
        )
    if mlm_loss_chunks == "auto":
        # packed head: the (K·B, V) logits are small — unchunked.  Dense
        # fallback: never materialize the full (S·B, V) f32 logits (~2 GB
        # at batch 128); 16 is the measured-best chunking.  An explicit
        # None always means unchunked.
        mlm_loss_chunks = None if max_predictions_per_seq else 16

    params = model.init(jax.random.PRNGKey(1), ids)
    opt_state = tx.init(params)
    n_params = sum(p.size for p in jax.tree_util.tree_leaves(params))

    @functools.partial(jax.jit, donate_argnums=(0, 1))
    def train_chunk(params, opt_state):
        def body(carry, _):
            params, opt_state = carry
            loss, grads = jax.value_and_grad(
                lambda p: bert_pretrain_loss(
                    p, model, batch_data, mlm_loss_chunks=mlm_loss_chunks
                )
            )(params)
            updates, opt_state = tx.update(grads, opt_state, params)
            params = jax.tree_util.tree_map(jnp.add, params, updates)
            return (params, opt_state), loss

        (params, opt_state), losses = jax.lax.scan(
            body, (params, opt_state), None, length=chunk
        )
        return (params, opt_state), losses[-1]

    timed_fn = train_chunk
    hlo_out = os.environ.get("APEX_TPU_BENCH_HLO_OUT")
    if hlo_out or _BENCH_LINT:
        # Compiled-HLO text of the headline step, for the trace↔source
        # join (tools/trace_summary.py TRACE --hlo FILE — the docs/mfu.md
        # lever-#2 copies attribution).  AOT lower().compile() does NOT
        # land in the jit dispatch cache (ADVICE r5), so dispatching
        # train_chunk afterwards would pay a SECOND full compile inside
        # a scarce tunnel window — time the compiled executable itself
        # instead (same program, donation semantics preserved).  --lint
        # rides the same single compile: the analysis passes read the
        # executable's text rather than paying their own.
        compiled = train_chunk.lower(params, opt_state).compile()
        module_text = compiled.as_text()  # one render serves both uses
        if hlo_out:
            with open(hlo_out, "w") as f:
                f.write(module_text)
        timed_fn = compiled
    if _BENCH_LINT:
        from apex_tpu import analysis

        donated = sum(
            len(jax.tree_util.tree_leaves(a)) for a in (params, opt_state)
        )
        lint_hlo_text = module_text
        # APEX_TPU_BENCH_HBM_BUDGET (bytes) arms the static peak-HBM
        # gate on the headline step; unset leaves the memory pass
        # reporting-only (the peak still rides the unit string below)
        hbm_budget = os.environ.get("APEX_TPU_BENCH_HBM_BUDGET")
        report = analysis.lint_hlo(
            lint_hlo_text, donated=donated,
            hbm_budget=int(hbm_budget) if hbm_budget else None,
            name="bert_lamb/train_chunk",
        )
        report.extend(analysis.lint_jaxpr(
            jax.make_jaxpr(train_chunk)(params, opt_state),
            name="bert_lamb/train_chunk",
        ).findings)
        analysis.publish_report(report)
        print(report.render(), file=sys.stderr)
        _emit(
            "graph_lint_errors",
            float(len(report.errors())),
            "ERROR findings (bert_lamb step; warnings=%d, rules=%s; "
            "docs/analysis.md)" % (
                len(report.warnings()), ",".join(report.rule_ids()) or "-"
            ),
            None,
        )
        # the sharding/memory half of the linter (ISSUE 9): ERROR count
        # scoped to the sharding-conformance/reshard/budget rules, plus
        # the static peak-HBM estimate of the same compiled module —
        # the record rides the standard bench-line schema that
        # tools/bench_diff.py --check-schema enforces
        _SHARD_RULES = (
            "sharding-replicated", "sharding-mismatch",
            "reshard-unplanned", "reshard-plan", "memory-budget",
        )
        shard_errors = sum(
            1 for f in report.errors() if f.rule in _SHARD_RULES
        )
        est = analysis.memory.estimate_peak(lint_hlo_text)
        analysis.memory.publish_peak(est)
        _emit(
            "graph_lint_shard_errors",
            float(shard_errors),
            "sharding/reshard/memory ERROR findings (bert_lamb step; "
            "peak_hbm=%.1fMiB; budget %s; docs/analysis.md)" % (
                est["peak_bytes"] / (1 << 20),
                ("%s bytes" % hbm_budget) if hbm_budget
                else "unarmed (APEX_TPU_BENCH_HBM_BUDGET)",
            ),
            None,
        )
        # the kernel half of the linter (ISSUE 10): the three shipped
        # Pallas kernels at their default configs, judged compile-free
        # (VMEM/tiling/coverage/dead-tiles — docs/analysis.md "Kernel
        # passes"); ERROR count rides the bench_diff schema so a
        # kernel-config regression gates like shard errors do
        krep = analysis.kernels.analyze_default_kernels()
        analysis.kernels.publish_kernel_report(krep)
        kernel_waste = max(
            [
                (e.get("dead_tiles") or {}).get("waste_fraction", 0.0)
                for e in krep.sections["kernels"]
            ] or [0.0]
        )
        _emit(
            "graph_lint_kernel_errors",
            float(len(krep.errors())),
            "kernel-pass ERROR findings (flash/layer_norm/decode "
            "defaults; warnings=%d; causal dead-tile waste=%.3f; "
            "docs/analysis.md)" % (len(krep.warnings()), kernel_waste),
            None,
        )
        # the host-side half of the linter (PR 19): lock discipline
        # over every threaded class + replay purity over the
        # replay-critical modules (docs/analysis.md "Concurrency &
        # replay-purity passes") — golden-pinned at zero so a new race
        # or impurity gates like a graph regression does
        conc_report = analysis.lint_package()
        _emit(
            "concurrency_lint_errors",
            float(len(conc_report.errors())),
            "concurrency/replay-purity ERROR findings (apex_tpu "
            "package; warnings=%d, files=%d; docs/analysis.md)" % (
                len(conc_report.warnings()),
                conc_report.sections.get("files_scanned", 0),
            ),
            None,
        )

    profile = apex_tpu.utils.trace(trace_dir) if trace_dir else None
    step_time, carry, loss = _time_chunks(
        timed_fn, (params, opt_state), chunk, trials, profile=profile
    )
    del carry

    tokens = seq_len * batch
    # Headline numerator: the BASELINE.md contract formula 6·N·T — the
    # same accounting the reference recipe's A100 numbers use, and that
    # recipe also gathers masked positions (max_predictions_per_seq), so
    # packed-head step times are the apples-to-apples comparison.
    flops = _train_flops(n_params, tokens)
    peak = sum(_chip_peak(d) for d in jax.devices())
    mfu = flops / (step_time * peak)
    # Honesty sidecar: the packed head EXECUTES fewer decoder FLOPs than
    # 6·N·T credits (K·B rows instead of T through the tied V×H decoder).
    # mfu_exec charges only executed work — the utilization number, vs
    # the recipe-parity headline above.  Dense head: identical.
    mfu_exec = mfu
    if max_predictions_per_seq:
        dec = cfg.vocab_size * cfg.hidden_size
        kb = max_predictions_per_seq * batch
        flops_exec = flops - 6.0 * (tokens - kb) * dec
        mfu_exec = flops_exec / (step_time * peak)
    if emit:
        extra = ""
        if max_predictions_per_seq:
            extra = ", mfu_exec=%.4f, mpps=%d" % (
                mfu_exec, max_predictions_per_seq
            )
        # record the remat policy that actually ran so artifacts from
        # different APEX_TPU_BENCH_POLICY settings stay distinguishable
        extra += ", policy=%s" % cfg.remat_policy
        _emit(
            _METRIC_NAMES["bert_lamb"],
            round(mfu, 4),
            "MFU (step_time_ms=%.1f, batch=%d, params=%dM, loss=%.3f%s)"
            % (step_time * 1e3, batch, n_params // 1_000_000, loss, extra),
            round(mfu / 0.50, 4),
        )
    return mfu, step_time, loss, mfu_exec


# ---------------------------------------------------------------------------
# #1 / #2 ResNet-50 (single device / DDP + SyncBN over dp)
# ---------------------------------------------------------------------------


def _resnet_step_fns(use_syncbn, batch, tx):
    from apex_tpu.models.resnet import resnet50

    model = resnet50(use_syncbn=use_syncbn)
    key = jax.random.PRNGKey(0)
    x = jax.random.normal(key, (batch, 224, 224, 3), jnp.bfloat16)
    y = jax.random.randint(key, (batch,), 0, 1000)
    variables = model.init(jax.random.PRNGKey(1), x, train=False)
    params, batch_stats = variables["params"], variables["batch_stats"]
    opt_state = tx.init(params)

    def loss_fn(p, bs):
        logits, updates = model.apply(
            {"params": p, "batch_stats": bs}, x, train=True,
            mutable=["batch_stats"],
        )
        logp = jax.nn.log_softmax(logits.astype(jnp.float32))
        loss = -jnp.mean(jnp.take_along_axis(logp, y[:, None], axis=-1))
        return loss, updates["batch_stats"]

    return loss_fn, params, batch_stats, opt_state, model


def bench_resnet50(trace_dir=None, batch=256, chunk=4, trials=3):
    """BASELINE #1: single-device synthetic-ImageNet train step."""
    import apex_tpu.utils
    from apex_tpu.optimizers import fused_sgd

    tx = fused_sgd(learning_rate=0.1, momentum=0.9)
    loss_fn, params, batch_stats, opt_state, _ = _resnet_step_fns(
        False, batch, tx
    )

    @functools.partial(jax.jit, donate_argnums=(0, 1, 2))
    def train_chunk(params, batch_stats, opt_state):
        def body(carry, _):
            params, batch_stats, opt_state = carry
            (loss, batch_stats), grads = jax.value_and_grad(
                loss_fn, has_aux=True
            )(params, batch_stats)
            updates, opt_state = tx.update(grads, opt_state, params)
            params = jax.tree_util.tree_map(jnp.add, params, updates)
            return (params, batch_stats, opt_state), loss

        carry, losses = jax.lax.scan(
            body, (params, batch_stats, opt_state), None, length=chunk
        )
        return carry, losses[-1]

    step_time, _, loss = _time_chunks(
        train_chunk, (params, batch_stats, opt_state), chunk, trials,
        profile=apex_tpu.utils.trace(trace_dir) if trace_dir else None,
    )
    _emit(
        _METRIC_NAMES["resnet50"],
        round(batch / step_time, 1),
        "img/s (step_time_ms=%.1f, batch=%d, loss=%.3f, single device; "
        "reference publishes no absolute number)"
        % (step_time * 1e3, batch, loss),
        None,
    )


def bench_ddp_syncbn(trace_dir=None, batch_per_replica=128, chunk=4, trials=3):
    """BASELINE #2: DDP ResNet-50 + SyncBatchNorm over every device."""
    from jax.sharding import Mesh, PartitionSpec as P

    import apex_tpu.utils
    from apex_tpu import parallel_state as ps
    from apex_tpu.optimizers import fused_sgd
    from apex_tpu.parallel.distributed import all_reduce_gradients

    devices = jax.devices()
    dp = len(devices)
    ps.destroy_model_parallel()
    ps.initialize_model_parallel(devices=devices)
    global_batch = batch_per_replica * dp

    tx = fused_sgd(learning_rate=0.1, momentum=0.9)
    loss_fn, params, batch_stats, opt_state, _ = _resnet_step_fns(
        True, batch_per_replica, tx
    )

    mesh = Mesh(devices, ("dp",))

    def one_step(params, batch_stats, opt_state):
        (loss, batch_stats), grads = jax.value_and_grad(
            loss_fn, has_aux=True
        )(params, batch_stats)
        grads = all_reduce_gradients(grads)
        loss = jax.lax.pmean(loss, "dp")
        updates, opt_state = tx.update(grads, opt_state, params)
        params = jax.tree_util.tree_map(jnp.add, params, updates)
        return params, batch_stats, opt_state, loss

    @functools.partial(jax.jit, donate_argnums=(0, 1, 2))
    def train_chunk(params, batch_stats, opt_state):
        def body(carry, _):
            p, bs, os_ = carry
            p, bs, os_, loss = one_step(p, bs, os_)
            return (p, bs, os_), loss

        def sharded(p, bs, os_):
            carry, losses = jax.lax.scan(
                body, (p, bs, os_), None, length=chunk
            )
            return carry, losses[-1]

        return jax.shard_map(
            sharded, mesh=mesh, in_specs=(P(), P(), P()),
            out_specs=(P(), P()), check_vma=False,
        )(params, batch_stats, opt_state)

    step_time, _, loss = _time_chunks(
        train_chunk, (params, batch_stats, opt_state), chunk, trials,
        profile=apex_tpu.utils.trace(trace_dir) if trace_dir else None,
    )
    ps.destroy_model_parallel()
    _emit(
        _METRIC_NAMES["ddp_syncbn"],
        round(global_batch / step_time, 1),
        "img/s (step_time_ms=%.1f, dp=%d, global_batch=%d, loss=%.3f, "
        "SyncBN; reference publishes no absolute number)"
        % (step_time * 1e3, dp, global_batch, loss),
        None,
        degenerate=dp == 1,
    )


# ---------------------------------------------------------------------------
# #4 fused multihead attention vs unfused composition
# ---------------------------------------------------------------------------


def bench_mha(trace_dir=None, batch=8, seq=2048, heads=16, head_dim=64,
              chunk=8, trials=3):
    """BASELINE #4: fused attention core vs the unfused composition, fwd+bwd
    (≙ the reference's multihead_attn speedup-vs-torch.nn plots)."""
    import apex_tpu.utils
    from apex_tpu.ops.attention import flash_attention, mha_reference

    key = jax.random.PRNGKey(0)
    shape = (batch, heads, seq, head_dim)
    q, k, v = (
        jax.random.normal(kk, shape, jnp.bfloat16)
        for kk in jax.random.split(key, 3)
    )

    def timed(fn):
        @jax.jit
        def chunk_fn(q, k, v):
            def body(carry, _):
                qq, kk, vv = carry
                def loss(qq, kk, vv):
                    return jnp.sum(
                        fn(qq, kk, vv, causal=True).astype(jnp.float32) ** 2
                    )
                dq, dk, dv = jax.grad(loss, argnums=(0, 1, 2))(qq, kk, vv)
                # feed grads back so scan iterations are not DCE'd
                return (dq, dk, dv), jnp.float32(0)

            carry, _ = jax.lax.scan(body, (q, k, v), None, length=chunk)
            return carry, carry[0][0, 0, 0]

        t, _, _ = _time_chunks(
            lambda *c: chunk_fn(*c), (q, k, v), chunk, trials,
            profile=apex_tpu.utils.trace(trace_dir) if trace_dir else None,
        )
        return t

    t_fused = timed(flash_attention)
    trace_dir = None  # one trace (the fused pass) is enough
    t_unfused = timed(mha_reference)
    speedup = t_unfused / t_fused
    _emit(
        _METRIC_NAMES["mha"],
        round(speedup, 3),
        "x vs unfused (fused_ms=%.2f, unfused_ms=%.2f, b=%d h=%d s=%d d=%d, "
        "fwd+bwd)" % (t_fused * 1e3, t_unfused * 1e3, *((batch, heads, seq,
                                                         head_dim))),
        round(speedup, 3),
    )


# ---------------------------------------------------------------------------
# #5 tensor-parallel GPT block
# ---------------------------------------------------------------------------


def bench_tp_gpt(trace_dir=None, batch=8, seq=1024, chunk=4, trials=3):
    """BASELINE #5: GPT block train step over a tp mesh of all devices."""
    from jax.sharding import Mesh, PartitionSpec as P

    import apex_tpu.utils
    from apex_tpu import parallel_state as ps
    from apex_tpu.models.gpt import GptBlock, GptConfig
    from apex_tpu.optimizers import fused_adam

    devices = jax.devices()
    tp = len(devices)
    ps.destroy_model_parallel()
    ps.initialize_model_parallel(
        tensor_model_parallel_size=tp, devices=devices
    )
    mesh = Mesh(devices, (ps.TENSOR_PARALLEL_AXIS,))

    cfg = GptConfig(
        hidden_size=1024, num_heads=16, intermediate_size=4096,
        sequence_parallel=tp > 1, dtype=jnp.bfloat16,
    )
    block = GptBlock(cfg)
    tx = fused_adam(learning_rate=1e-4)
    x = jax.random.normal(
        jax.random.PRNGKey(0), (seq, batch, cfg.hidden_size), jnp.bfloat16
    )

    def build(x):
        xl = x
        if tp > 1:
            rank = jax.lax.axis_index(ps.TENSOR_PARALLEL_AXIS)
            sp = seq // tp
            xl = jax.lax.dynamic_slice_in_dim(x, rank * sp, sp, 0)
        params = block.init(jax.random.PRNGKey(1), xl)
        return params, tx.init(params), xl

    def sharded_chunk(length, x):
        # params live only inside shard_map (per-rank tp shards have no
        # convenient global representation), so init runs inside the jit;
        # the two-length timing below subtracts it out of the step time.
        params, opt_state, xl = build(x)

        def body(carry, _):
            params, opt_state = carry

            def loss_fn(p):
                y = block.apply(p, xl)
                return jnp.sum(y.astype(jnp.float32) ** 2)

            loss, grads = jax.value_and_grad(loss_fn)(params)
            updates, opt_state = tx.update(grads, opt_state, params)
            params = jax.tree_util.tree_map(jnp.add, params, updates)
            return (params, opt_state), loss

        (params, opt_state), losses = jax.lax.scan(
            body, (params, opt_state), None, length=length,
        )
        return losses[-1]

    def timed(length, profile=None):
        fn = jax.jit(
            jax.shard_map(
                functools.partial(sharded_chunk, length),
                mesh=mesh, in_specs=(P(),), out_specs=P(),
                check_vma=False,
            )
        )

        def wrapped(x):
            return (x,), fn(x)

        # total (init + length steps) time; per-step division happens in
        # the subtraction below, so pass chunk=1 here.  min (not median)
        # over trials: the subtraction needs the noise floor of each.
        total, _, _ = _time_chunks(
            wrapped, (x,), 1, trials, profile=profile, reduce="min"
        )
        return total

    t_long = timed(2 * chunk)
    t_short = timed(chunk)
    if trace_dir:
        # dedicated traced run — its time is NOT used, so profiler
        # overhead cannot bias the init-cancelling subtraction below
        timed(2 * chunk, profile=apex_tpu.utils.trace(trace_dir))
    ps.destroy_model_parallel()
    if t_long <= t_short:
        # timing noise swamped the subtraction: report the conservative
        # upper bound (init amortized over 2*chunk steps) and say so
        step_time = t_long / (2 * chunk)
        basis = "upper bound incl. per-call init: noisy subtraction"
    else:
        step_time = (t_long - t_short) / chunk
        basis = "init-cancelled two-length measurement"
    _emit(
        _METRIC_NAMES["tp_gpt"],
        round(step_time * 1e3, 2),
        "ms/step (tp=%d, seq=%d, batch=%d, h=%d, SP=%s, %s; reference "
        "publishes no absolute number)"
        % (tp, seq, batch, cfg.hidden_size, tp > 1, basis),
        None,
        degenerate=tp == 1,
    )


# ---------------------------------------------------------------------------
# ZeRO gradient sync: BERT-Large + DistributedFusedLAMB, wire f32 vs int8
# ---------------------------------------------------------------------------


def bench_zero(trace_dir=None, batch_per_replica=32, chunk=3, trials=3,
               cfg_kwargs=None):
    """BERT-Large + DistributedFusedLAMB (cross-replica weight-update
    sharding) over a dp mesh of all devices, A/B'd over the comm layer's
    wire format: f32 vs int8 grads with bf16 param gather (the
    recommended aggressive setting, docs/comm.md).  Value = f32/int8
    step-time speedup — the wall-clock effect of cutting DP sync bytes
    ~4x; both step times ride in the unit string.  dp=1 runs are marked
    degenerate (no wire to cut: the engine skips collectives entirely,
    so the honest expectation there is ~1.0x).  ``cfg_kwargs`` overrides
    the BERT-Large shape (CPU smoke drives use a tiny model).
    """
    from jax.sharding import Mesh, PartitionSpec as P

    import apex_tpu.utils
    from apex_tpu import parallel_state as ps
    from apex_tpu.models import (
        BertForPreTraining,
        bert_large_config,
        bert_pretrain_loss,
    )
    from apex_tpu.parallel import DistributedFusedLAMB

    devices = jax.devices()
    dp = len(devices)
    seq_len = 128
    global_batch = batch_per_replica * dp
    if cfg_kwargs is None:
        cfg_kwargs = dict(
            remat=True, remat_policy=_BENCH_POLICY, scan_layers=False,
            remat_attention=True, remat_prevent_cse=False,
        )
    cfg = bert_large_config(**cfg_kwargs)
    model = BertForPreTraining(cfg)

    key = jax.random.PRNGKey(0)
    ids = jax.random.randint(key, (seq_len, global_batch), 0, cfg.vocab_size)
    labels = jnp.where(ids % 7 == 0, ids, -1)
    batch_data = {
        "input_ids": ids,
        "token_type_ids": jnp.zeros_like(ids),
        "attention_mask": jnp.ones((global_batch, seq_len), jnp.int32),
        "mlm_labels": labels,
        "nsp_labels": jnp.zeros((global_batch,), jnp.int32),
    }
    # dense-label MLM head: every leaf's batch axis is explicit below, so
    # per-rank slicing inside shard_map stays a one-liner
    _BATCH_AXIS = {
        "input_ids": 1, "token_type_ids": 1, "attention_mask": 0,
        "mlm_labels": 1, "nsp_labels": 0,
    }
    params = model.init(jax.random.PRNGKey(1), ids[:, :batch_per_replica])
    n_params = sum(p.size for p in jax.tree_util.tree_leaves(params))

    mesh = Mesh(devices, (ps.DATA_PARALLEL_AXIS,))
    ps.destroy_model_parallel()
    ps.initialize_model_parallel(devices=devices)

    def run(wire, param_wire, profile=None):
        # fresh param copy per A/B arm: the step donates its carry, so
        # sharing one tree would hand arm 2 deleted buffers
        arm_params = jax.tree_util.tree_map(jnp.copy, params)
        dist = DistributedFusedLAMB(
            lr=1e-3, weight_decay=0.01, wire=wire, param_wire=param_wire,
        )
        state = dist.init(arm_params, world=dp)
        state_spec = jax.tree_util.tree_map(
            lambda x: P("dp") if getattr(x, "ndim", 0) == 1 else P(),
            state,
        )

        def sharded_chunk(params, state, batch):
            rank = jax.lax.axis_index(ps.DATA_PARALLEL_AXIS)
            local = {
                k: jax.lax.dynamic_slice_in_dim(
                    v, rank * batch_per_replica, batch_per_replica,
                    _BATCH_AXIS[k],
                )
                for k, v in batch.items()
            }

            def body(carry, _):
                params, state = carry
                loss, grads = jax.value_and_grad(
                    lambda p: bert_pretrain_loss(
                        p, model, local, mlm_loss_chunks=16
                    )
                )(params)
                loss = jax.lax.pmean(loss, ps.DATA_PARALLEL_AXIS)
                params, state = dist.update_inside_shard_map(
                    grads, state, params
                )
                return (params, state), loss

            (params, state), losses = jax.lax.scan(
                body, (params, state), None, length=chunk
            )
            return params, state, losses[-1]

        fn = jax.jit(
            jax.shard_map(
                sharded_chunk, mesh=mesh,
                in_specs=(P(), state_spec, P()),
                out_specs=(P(), state_spec, P()),
                check_vma=False,
            ),
            donate_argnums=(0, 1),
        )

        def wrapped(p, s):
            p, s, loss = fn(p, s, batch_data)
            return (p, s), loss

        t, carry, loss = _time_chunks(
            wrapped, (arm_params, state), chunk, trials, profile=profile
        )
        del carry
        return t, loss

    t_f32, loss = run("f32", None)
    t_int8, _ = run(
        "int8", "bf16",
        profile=apex_tpu.utils.trace(trace_dir) if trace_dir else None,
    )
    ps.destroy_model_parallel()
    speedup = t_f32 / t_int8
    _emit(
        _METRIC_NAMES["zero"],
        round(speedup, 3),
        "x vs f32 wire (f32_ms=%.1f, int8_ms=%.1f, dp=%d, "
        "global_batch=%d, params=%dM, loss=%.3f, ZeRO LAMB, "
        "param_wire=bf16; reference publishes no absolute number)"
        % (t_f32 * 1e3, t_int8 * 1e3, dp, global_batch,
           n_params // 1_000_000, loss),
        None,
        degenerate=dp == 1,
    )


# ---------------------------------------------------------------------------
# long-context attention (beyond-reference capability demo)
# ---------------------------------------------------------------------------


def bench_long_attn(trace_dir=None, batch=1, heads=8, seq=16384,
                    head_dim=128, chunk=4, trials=3):
    """Causal flash attention fwd+bwd at long sequence — the regime the
    reference cannot reach (its fmha kernels cap at seq 512, its fused
    softmax at ~2k; an unfused composition would materialize a
    (S, S) = 17 GB f32 score tensor here).  Reports achieved TFLOP/s and
    fraction of chip peak; vs_baseline is null (no reference number
    exists at this length by construction)."""
    import apex_tpu.utils
    from apex_tpu.ops.attention import flash_attention

    key = jax.random.PRNGKey(0)
    shape = (batch, heads, seq, head_dim)
    q, k, v = (
        jax.random.normal(kk, shape, jnp.bfloat16)
        for kk in jax.random.split(key, 3)
    )

    @jax.jit
    def chunk_fn(q, k, v):
        def body(carry, _):
            qq, kk, vv = carry

            def loss(qq, kk, vv):
                o = flash_attention(qq, kk, vv, causal=True)
                return jnp.sum(o.astype(jnp.float32) ** 2)

            dq, dk, dv = jax.grad(loss, argnums=(0, 1, 2))(qq, kk, vv)
            return (dq, dk, dv), jnp.float32(0)

        carry, _ = jax.lax.scan(body, (q, k, v), None, length=chunk)
        return carry, carry[0][0, 0, 0]

    t, _, _ = _time_chunks(
        lambda *c: chunk_fn(*c), (q, k, v), chunk, trials,
        profile=apex_tpu.utils.trace(trace_dir) if trace_dir else None,
    )
    # causal fwd ≈ 2·B·H·S²·D MACs = 4·B·H·S²·D/2 FLOPs; bwd ≈ 2.5× fwd
    flops = 3.5 * 4 * batch * heads * seq * seq * head_dim / 2
    peak = _chip_peak(jax.devices()[0])
    tf = flops / t / 1e12
    _emit(
        _METRIC_NAMES["long_attn"],
        round(tf, 1),
        "TFLOP/s (%.0f%% of peak, step_ms=%.1f, b=%d h=%d s=%d d=%d, "
        "causal fwd+bwd, O(S) memory; reference caps at seq 512)"
        % (100 * flops / t / peak, t * 1e3, batch, heads, seq, head_dim),
        None,
    )


# ---------------------------------------------------------------------------
# Serving smoke config (seconds on CPU — the verify_tier1.sh PERF pass;
# docs/serving.md)
# ---------------------------------------------------------------------------


def bench_serve(trace_dir=None, prompt_len=48, decode_steps=24, trials=3):
    """Paged-inference smoke rows: prefill tokens/s, continuous-batch
    decode tokens/s, and TTFT through the real scheduler path — a tiny
    GPT so the rows land in seconds on CPU.  Like ``bench_smoke``
    these are SCHEMA/PRESENCE rows, not performance claims: they pin
    the serving metric names into the golden/gate stream
    (``tools/bench_golden_cpu.jsonl``) so serving perf can never go
    flat silently; real serving load curves come from
    ``tools/serve_bench.py``."""
    import numpy as np

    from apex_tpu.models.gpt import GptConfig, GptModel
    from apex_tpu.serve import (
        ContinuousBatchingScheduler,
        InferenceEngine,
        Request,
        ServeConfig,
    )

    cfg = GptConfig(
        vocab_size=128, hidden_size=64, num_layers=2, num_heads=4,
        intermediate_size=128, max_seq_len=256, dtype=jnp.float32,
    )
    serve_cfg = ServeConfig(
        page_size=16, num_pages=64, max_batch=4, max_pages_per_seq=8,
        verify=False,
    )
    model = GptModel(cfg)
    ids = jax.random.randint(
        jax.random.PRNGKey(0), (prompt_len, 1), 0, cfg.vocab_size
    )
    params = model.init(jax.random.PRNGKey(1), ids)
    engine = InferenceEngine(cfg, params, serve_cfg)
    rs = np.random.RandomState(0)

    def prompt(n):
        return list(rs.randint(0, cfg.vocab_size, size=n))

    # -- prefill tokens/s (direct engine path, batch-of-1 buckets) ------
    pages = engine.pool.alloc(engine.pool.pages_for(prompt_len))
    engine.prefill(prompt(prompt_len), pages)  # warmup/compile
    times = []
    for _ in range(trials):
        t0 = time.perf_counter()
        engine.prefill(prompt(prompt_len), pages)
        times.append(time.perf_counter() - t0)
    times.sort()
    t_prefill = times[len(times) // 2]
    _emit(
        "serve_prefill_tokens_per_s",
        round(prompt_len / t_prefill, 1),
        "tokens/s (prompt=%d, bucket=%d, page=%d, h=%d L=%d; CI "
        "serving smoke on CPU, not a perf claim)"
        % (prompt_len, engine.bucket_for(prompt_len),
           serve_cfg.page_size, cfg.hidden_size, cfg.num_layers),
        None,
    )
    engine.pool.free(pages)

    # -- decode tokens/s at a full continuous batch ---------------------
    b = serve_cfg.max_batch
    reqs = []
    tables = np.zeros((b, serve_cfg.max_pages_per_seq), np.int32)
    for i in range(b):
        p = engine.pool.alloc(engine.pool.pages_for(prompt_len))
        _, tok = engine.prefill(prompt(prompt_len), p)
        reqs.append({"pages": p, "tok": tok, "ctx": prompt_len})
    lengths = np.zeros((b,), np.int32)
    tokens = np.zeros((b,), np.int32)

    def decode_once():
        for i, r in enumerate(reqs):
            if r["ctx"] // serve_cfg.page_size >= len(r["pages"]):
                got = engine.pool.alloc(1)
                if got is None:
                    raise RuntimeError(
                        "bench serve: page pool exhausted — raise "
                        "num_pages or lower decode_steps/prompt_len"
                    )
                r["pages"] += got
            tables[i, : len(r["pages"])] = r["pages"]
            tokens[i] = r["tok"]
            lengths[i] = r["ctx"] + 1
        _, nxt = engine.decode(tokens, lengths, tables)
        for i, r in enumerate(reqs):
            r["ctx"] += 1
            r["tok"] = int(nxt[i])

    decode_once()  # warmup/compile
    t0 = time.perf_counter()
    for _ in range(decode_steps):
        decode_once()
    t_decode = (time.perf_counter() - t0) / decode_steps
    _emit(
        "serve_decode_tokens_per_s",
        round(b / t_decode, 1),
        "tokens/s (batch=%d, ctx~%d, page=%d, paged KV; CI serving "
        "smoke on CPU, not a perf claim)"
        % (b, prompt_len + decode_steps, serve_cfg.page_size),
        None,
    )
    for r in reqs:
        engine.pool.free(r["pages"])

    # -- TTFT through the scheduler (queue -> admit -> prefill) ---------
    # spans ON: this row doubles as the span-recording overhead gate —
    # the golden tolerance on serve_ttft_ms binds the scheduler path
    # WITH per-request span chains being recorded
    from apex_tpu.observability.spans import SpanRecorder

    ttfts = []
    for _ in range(trials):
        # each scheduler takes the engine over with its own recorder
        sched = ContinuousBatchingScheduler(
            engine, spans=SpanRecorder(capacity=1024)
        )
        sched.submit(Request(prompt=prompt(prompt_len), max_new_tokens=2))
        sched.run()
        ttfts.append(sched.completed[-1].ttft_ms)
    ttfts.sort()
    engine.spans = None
    _emit(
        "serve_ttft_ms",
        round(ttfts[len(ttfts) // 2], 3),
        "ms (prompt=%d via ContinuousBatchingScheduler, queue->first "
        "token, span recording ON; CI serving smoke on CPU, not a perf "
        "claim)" % prompt_len,
        None,
    )

    # -- live ops plane rows (docs/observability.md "Live ops plane") ---
    # ops_scrape_ms: a REAL HTTP GET against the OpenMetrics endpoint
    # serving the last scheduler's TTFT histogram + the board — the
    # exporter's cost rides the bench_diff golden stream so scrape
    # overhead can never regress silently
    import urllib.request

    from apex_tpu.observability import ometrics, slo as slo_lib

    srv = ometrics.OpsServer(
        histograms=[sched.ttft_hist], port=0
    ).start()
    scrape_ms = []
    body = b""
    for _ in range(3):
        t0 = time.perf_counter()
        with urllib.request.urlopen(srv.url, timeout=10) as resp:
            body = resp.read()
        scrape_ms.append(1e3 * (time.perf_counter() - t0))
    srv.stop()
    scrape_ms.sort()
    _emit(
        "ops_scrape_ms",
        round(scrape_ms[len(scrape_ms) // 2], 3),
        "ms (HTTP GET /metrics, median of 3, %d bytes exposition; CI "
        "ops smoke on CPU, not a perf claim)" % len(body),
        None,
    )
    # slo_alerts_fired: the deterministic burn-rate drill (a 5x burn
    # against a 90% objective judged by one (60s, 240s, 2x) window
    # fires exactly once) — pins the multi-window alert math into the
    # golden stream
    _emit(
        "slo_alerts_fired",
        float(slo_lib.burn_rate_drill()),
        "alerts (canonical burn-rate drill: 50% errors vs a 90% "
        "objective, one 60s/240s window at factor 2 — must fire "
        "exactly once)",
        None,
    )

    # -- prefix-cache rows (docs/serving.md "Prefix caching") ----------
    # serve_prefix_hit_ttft_ms: TTFT of a fully-cached prompt through
    # the real scheduler path — the hit borrows every committed page
    # and chunked prefill re-runs only the final grain-aligned chunk.
    # serve_prefill_flops_saved_pct: analytic prefill FLOPs the hit
    # skipped vs a cold run of the same prompt (deterministic — a
    # function of the grain-floored resume point, not the clock).
    # Together they pin the prefix-cache fast path into the golden
    # stream (_ms lower-better / _pct higher-better per bench_diff's
    # suffix rules); the workload-level proof lives in verify_tier1.sh's
    # prefix gate over tools/serve_bench.py.
    psched = ContinuousBatchingScheduler(
        engine,
        spans=SpanRecorder(capacity=1024),
        prefix_cache=True,
        prefill_chunk_tokens=serve_cfg.page_size,
    )
    shared = prompt(prompt_len)
    # cold run: compiles the chunk/fork programs and commits the prefix
    psched.submit(Request(prompt=list(shared), max_new_tokens=2))
    psched.run()
    hit_ttfts = []
    for _ in range(trials):
        psched.submit(Request(prompt=list(shared), max_new_tokens=2))
        psched.run()
        hit_ttfts.append(psched.completed[-1].ttft_ms)
    hit_req = psched.completed[-1]
    assert hit_req.cache_hit_tokens > 0, "prefix cache never hit"
    hit_ttfts.sort()
    engine.spans = None
    _emit(
        "serve_prefix_hit_ttft_ms",
        round(hit_ttfts[len(hit_ttfts) // 2], 3),
        "ms (fully-cached prompt=%d, page=%d, chunk=%d; queue->first "
        "token on a warm prefix cache; CI serving smoke on CPU, not a "
        "perf claim)"
        % (prompt_len, serve_cfg.page_size, serve_cfg.page_size),
        None,
    )
    grain = serve_cfg.page_size
    start = (min(hit_req.cache_hit_tokens, prompt_len - 1) // grain) * grain
    h, ff, L = cfg.hidden_size, cfg.intermediate_size, cfg.num_layers

    def _pf_flops(n, skip=0):
        linear = (4 * h * h + 2 * h * ff) * (n - skip)
        attn = 2 * h * (n * (n + 1) - skip * (skip + 1)) // 2
        return L * (linear + attn)

    _emit(
        "serve_prefill_flops_saved_pct",
        round(
            100.0 * (1.0 - _pf_flops(prompt_len, start)
                     / _pf_flops(prompt_len)), 3),
        "%% prefill FLOPs skipped by a full prefix hit (prompt=%d, "
        "resume at token %d of %d; analytic model, deterministic)"
        % (prompt_len, start, prompt_len),
        None,
    )
    # hand every cached page back and prove the pool drained clean —
    # the smoke row must not leak pages into the chaos section below
    psched.prefix.flush()
    psched.leak_check()
    assert engine.pool.in_use == 0, engine.pool.in_use

    # -- speculative-decode rows (docs/serving.md "Speculative decoding")
    # serve_spec_accept_rate / serve_spec_tokens_per_step: a friendly
    # (self-draft) k=4 speculative run through the real scheduler path.
    # Greedy self-draft acceptance is exact by construction, so the
    # accept-rate row pins 1.0 and the tokens/step row pins the
    # k+1-wide emission — deterministic SCHEMA rows like the rest of
    # this config (the workload-level proof, including the chaos storm
    # and the plain-decode replay, lives in verify_tier1.sh's spec gate
    # over tools/serve_bench.py).
    from apex_tpu.observability import MetricRegistry
    from apex_tpu.serve import SpecConfig

    sreg = MetricRegistry(fetch_every=1)
    sengine = InferenceEngine(
        cfg, params, serve_cfg, registry=sreg,
        spec=SpecConfig(draft_params=None, k=4),
    ).build()
    ssched = ContinuousBatchingScheduler(sengine, registry=sreg)
    for _ in range(2):
        ssched.submit(Request(prompt=prompt(16), max_new_tokens=12))
    ssched.run()
    ssched.leak_check()
    assert sengine.pool.in_use == 0, sengine.pool.in_use
    sreg.fetch()
    svals = sreg.values()
    assert svals.get("serve/spec_rounds", 0.0) > 0, svals
    _emit(
        "serve_spec_accept_rate",
        round(svals["serve/spec_accept_rate"], 3),
        "draft tokens accepted / drafted (self-draft k=4, greedy: "
        "exact by construction, MUST be 1.0; CI serving smoke on CPU)",
        None,
    )
    _emit(
        "serve_spec_tokens_per_step",
        round(svals["serve/spec_tokens_per_step"], 3),
        "tokens emitted per decode step (self-draft k=4 over %d "
        "requests; plain decode is 1.0 by definition; CI serving "
        "smoke on CPU, not a perf claim)" % len(ssched.completed),
        None,
    )

    # -- serving resilience rows (docs/serving.md "Failure semantics") --
    # reuses tools/serve_chaos_drill.py (the SERVE-CHAOS gate's exact
    # machinery: fault-free Poisson reference + an APEX_TPU_CHAOS storm
    # at all four serve sites + overload-ladder probe + drain) and
    # emits the two headline rows: request goodput under the storm and
    # the p99 TTFT inflation vs the fault-free reference.  The gate's
    # evidence artifact is reused via APEX_TPU_SERVE_CHAOS_ARTIFACT
    # (verify_tier1.sh runs SERVE-CHAOS before PERF and hands it over)
    # so CI pays for ONE storm, not two.
    import importlib.util as _ilu

    root = os.path.dirname(os.path.abspath(__file__))
    spec = _ilu.spec_from_file_location(
        "serve_chaos_drill",
        os.path.join(root, "tools", "serve_chaos_drill.py"),
    )
    scd = _ilu.module_from_spec(spec)
    spec.loader.exec_module(scd)
    defaults = scd.build_parser().parse_args([])
    art = None
    reuse = os.environ.get("APEX_TPU_SERVE_CHAOS_ARTIFACT")
    if reuse and os.path.exists(reuse):
        try:
            with open(reuse) as f:
                cand = json.load(f)
            # accept only an artifact of the SAME storm: a stale file
            # from a different spec/geometry must not publish rows
            # describing a drill the current code never ran.  Every
            # key the artifact's config section records must equal the
            # drill's defaults, plus the chaos spec itself.
            cfg_sec = cand.get("config", {})
            if (cand.get("chaos_spec") == defaults.chaos
                    and cfg_sec
                    and all(getattr(defaults, k, None) == v
                            for k, v in cfg_sec.items())):
                art = cand
        except (OSError, ValueError):
            art = None
    if art is None:
        art = scd.run_drill(defaults)
    storm_req = art["storm"]
    chaos_desc = (
        "storm %s; rebuilds=%d retries=%d; sheds %s"
        % (art["chaos_spec"], art["engine"]["rebuilds"],
           art["registry"].get("serve/retries", 0),
           dict(sorted(storm_req["shed_reasons"].items())))
    )
    _emit(
        "serve_chaos_goodput_pct",
        round(100.0 * storm_req["completed"] / storm_req["offered"], 3)
        if storm_req["offered"] else 0.0,
        "%% requests completed under the serve chaos storm (%s)"
        % chaos_desc,
        None,
    )
    _emit(
        "serve_chaos_p99_inflation",
        round(art["p99_ttft_inflation"], 3),
        "x storm p99 TTFT over the fault-free reference (bound 2.0 — "
        "graceful degradation, not collapse; %s)" % chaos_desc,
        None,
    )


# ---------------------------------------------------------------------------
# train3d: the composable trainer at dp=2 / tp=2 / dp=2 x tp=2
# ---------------------------------------------------------------------------


def bench_train3d(trace_dir=None, steps=8, trials=3):
    """The ``apex_tpu.train`` trainer's honest multi-device rows — the
    replacement for the degenerate ddp_syncbn (dp=1) / tp_gpt (tp=1)
    proxies (ISSUE 12).  Three arms — dp=2, tp=2, dp=2 x tp=2 — each a
    REAL mesh when enough devices are visible (CI mocks 8 CPU devices
    via ``--xla_force_host_platform_device_count=8``; an on-chip window
    uses real chips).  Every arm's trainer build SELF-VERIFIES
    (``TrainConfig(verify="error")``): the compiled step's sharding,
    collective schedule, and memory must equal the config-derived plan
    or the bench dies loudly — so a row here is a verified shape, not
    just a number.  With too few devices the arm falls back to a
    single-device build marked ``degenerate`` — and ``bench_diff
    --check-schema`` REFUSES degenerate train3d rows, so the fallback
    can never pass a gate.

    With ``--lint`` a ``train3d_lint_errors`` line carries the total
    ERROR findings across the three builds (0 by construction: a build
    with errors raises).
    """
    from apex_tpu.train import build_demo

    arms = (("dp2", 2, 1), ("tp2", 1, 2), ("dp2tp2", 2, 2))
    navail = len(jax.devices())
    lint_errors = 0
    modes = []
    for name, dp, tp in arms:
        degenerate = navail < dp * tp
        bdp, btp = (1, 1) if degenerate else (dp, tp)
        step = build_demo(bdp, btp, verify="error")
        if step.report is not None:
            lint_errors += len(step.report.errors())
        state, batch = step.state, step.example_batch
        st, aux = step(state, batch)  # warmup/compile
        float(aux["loss"])
        times = []
        loss = 0.0
        for _ in range(trials):
            t0 = time.perf_counter()
            for _ in range(steps):
                st, aux = step(st, batch)
            loss = float(aux["loss"])  # device->host: the sync point
            times.append((time.perf_counter() - t0) / steps)
        times.sort()
        step_ms = times[len(times) // 2] * 1e3
        modes.append(f"{name}:{step.mode}")
        _emit(
            f"train3d_{name}_step_ms",
            round(step_ms, 3),
            "ms/step (dp=%d, tp=%d, rows=%d, dim=%d, mode=%s, wire=%s, "
            "loss=%.4f, %d devices, build self-verified; "
            "apex_tpu.train demo config)"
            % (bdp, btp, step.tokens_per_step(),
               step.example_batch[0].shape[1], step.mode,
               step.config.wire, loss, navail),
            None,
            degenerate=degenerate,
        )
    if _BENCH_LINT:
        _emit(
            "train3d_lint_errors",
            float(lint_errors),
            "ERROR findings across the three self-verified trainer "
            "builds (%s; a failing build raises, so nonzero here means "
            "a verify='warn' escape; docs/training.md)"
            % ", ".join(modes),
            None,
        )
        # host-side concurrency + replay-purity lint (PR 19), riding
        # the same --lint invocation so the golden stream pins the
        # package race/impurity ERROR count at zero
        from apex_tpu import analysis

        conc_report = analysis.lint_package()
        _emit(
            "concurrency_lint_errors",
            float(len(conc_report.errors())),
            "concurrency/replay-purity ERROR findings (apex_tpu "
            "package; warnings=%d, files=%d; docs/analysis.md)" % (
                len(conc_report.warnings()),
                conc_report.sections.get("files_scanned", 0),
            ),
            None,
        )


# ---------------------------------------------------------------------------
# CI smoke config (seconds on CPU — the verify_tier1.sh PERF pass)
# ---------------------------------------------------------------------------


def bench_smoke(trace_dir=None, dim=128, batch=64, chunk=4, trials=2):
    """Tiny MLP train step, single-device AND under a dp shard_map over
    every visible device — NOT a performance claim, a schema driver:
    it exercises the real ``_time_chunks``/``_emit`` path (including
    the degenerate-marking contract on the dp row) in seconds on CPU,
    so ``tools/bench_diff.py --check-schema`` can gate contract drift
    in CI without a TPU (``tools/bench_golden_cpu.jsonl`` is the
    committed golden line)."""
    from jax.sharding import Mesh, PartitionSpec as P

    key = jax.random.PRNGKey(0)
    w1 = jax.random.normal(key, (dim, dim), jnp.float32) * 0.1
    w2 = jax.random.normal(key, (dim, dim), jnp.float32) * 0.1
    x = jax.random.normal(key, (batch, dim), jnp.float32)
    y = jnp.ones((batch, dim), jnp.float32)

    def loss_fn(params, x, y):
        h = jnp.tanh(x @ params["w1"])
        return jnp.mean((h @ params["w2"] - y) ** 2)

    def body(carry, _):
        params = carry
        loss, grads = jax.value_and_grad(loss_fn)(params, x, y)
        params = jax.tree_util.tree_map(
            lambda p, g: p - 1e-2 * g, params, grads
        )
        return params, loss

    @functools.partial(jax.jit, donate_argnums=(0,))
    def train_chunk(params):
        params, losses = jax.lax.scan(body, params, None, length=chunk)
        return (params,), losses[-1]

    # each arm gets its own copy: the chunks donate their carry, and
    # the dp arm below needs live source buffers
    params = {"w1": jnp.copy(w1), "w2": jnp.copy(w2)}
    t, _, loss = _time_chunks(
        lambda p: train_chunk(p), (params,), chunk, trials
    )
    _emit(
        "smoke_mlp_step_ms",
        round(t * 1e3, 3),
        "ms/step (dim=%d, batch=%d, loss=%.4f, single device; CI "
        "schema smoke, not a perf claim)" % (dim, batch, loss),
        None,
    )

    devices = jax.devices()
    dp = len(devices)
    mesh = Mesh(devices, ("dp",))

    def dp_body(carry, _):
        params = carry
        loss, grads = jax.value_and_grad(loss_fn)(params, x, y)
        grads = jax.tree_util.tree_map(
            lambda g: jax.lax.pmean(g, "dp"), grads
        )
        params = jax.tree_util.tree_map(
            lambda p, g: p - 1e-2 * g, params, grads
        )
        return params, loss

    @functools.partial(jax.jit, donate_argnums=(0,))
    def dp_chunk(params):
        def sharded(params):
            params, losses = jax.lax.scan(
                dp_body, params, None, length=chunk
            )
            return params, losses[-1]

        params, loss = jax.shard_map(
            sharded, mesh=mesh, in_specs=(P(),), out_specs=(P(), P()),
            check_vma=False,
        )(params)
        return (params,), loss

    params = {"w1": jnp.copy(w1), "w2": jnp.copy(w2)}
    t_dp, _, loss = _time_chunks(
        lambda p: dp_chunk(p), (params,), chunk, trials
    )
    _emit(
        "smoke_dp_mlp_step_ms",
        round(t_dp * 1e3, 3),
        "ms/step (dp=%d, dim=%d, batch=%d, loss=%.4f, psum grad sync; "
        "CI schema smoke, not a perf claim)" % (dp, dim, batch, loss),
        None,
        degenerate=dp == 1,
    )


def bench_goodput(trace_dir=None, steps=60, preempt_every=12):
    """The preemptible-fleet I/O plane (docs/goodput.md), measured:
    reuses ``tools/goodput_drill.py``'s storm (the GOODPUT gate's
    exact machinery — uninterrupted reference + APEX_TPU_CHAOS
    preemption storm over the resilient example's real programs, fed
    by the resumable stream, saved by the async engine) and emits the
    headline rows: storm goodput %, the step path's zero-stall
    percentage, checkpoint enqueue/finalize stall ms, input-stall
    fraction, and the resumed-loss drift (which must be 0.0 — a
    nonzero value here means determinism broke, not that a knob needs
    tuning).  CI-grade numbers on CPU; not TPU perf claims."""
    import importlib.util
    import tempfile

    # APEX_TPU_GOODPUT_ARTIFACT: reuse an evidence artifact a previous
    # drill wrote (verify_tier1.sh runs the GOODPUT gate first and
    # hands its --json here) instead of paying a second full
    # reference+storm+resume drill for the same numbers.  Ignored
    # unless the artifact matches the requested storm geometry.
    art = None
    reuse = os.environ.get("APEX_TPU_GOODPUT_ARTIFACT")
    if reuse and os.path.exists(reuse):
        try:
            with open(reuse) as f:
                cand = json.load(f)
            if (cand.get("steps") == steps
                    and cand.get("preempt_every") == preempt_every):
                art = cand
        except (OSError, ValueError):
            art = None
    if art is None:
        root = os.path.dirname(os.path.abspath(__file__))
        spec = importlib.util.spec_from_file_location(
            "goodput_drill",
            os.path.join(root, "tools", "goodput_drill.py"),
        )
        gd = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(gd)
        workdir = tempfile.mkdtemp(prefix="apex_tpu_bench_goodput_")
        try:
            art = gd.run_drill(
                steps=steps, preempt_every=preempt_every,
                workdir=workdir,
            )
        finally:
            # CI runs this config every PERF pass: don't leave a
            # corpus + three checkpoint trees in /tmp per invocation
            shutil.rmtree(workdir, ignore_errors=True)

    def med(xs):
        # 0.0 on empty, never NaN: on fast storage every write can
        # settle before a drain point, leaving no finalize events —
        # and a NaN row would sail through every bench_diff
        # comparison (all NaN compares are False) instead of gating
        return sorted(xs)[len(xs) // 2] if xs else 0.0

    a = art["accountant"]
    storm = (
        "preempt every %d of %d steps + 1 healed save fault; accepted=%d "
        "skipped=%d discarded=%d resumes=%d; async ckpt engine + "
        "resumable stream; docs/goodput.md"
        % (preempt_every, steps, a["accepted"], a["skipped"],
           a["discarded"], a["resumes"])
    )
    _emit(
        "goodput_storm_pct", round(art["goodput"] * 100, 3),
        "%% productive/executed steps under the chaos storm (%s)" % storm,
        None,
    )
    _emit(
        "goodput_zero_stall_pct",
        round((1.0 - art["ckpt"]["stall_frac"]) * 100, 3),
        "%% of run wall time NOT stalled on checkpointing (snapshot+"
        "enqueue over wall on the full-length reference run, "
        "background writes excluded — the <1%% overhead bound "
        "inverted; %d saves)" % int(art["ckpt"]["saves"]),
        None,
    )
    _emit(
        "goodput_ckpt_enqueue_ms",
        round(med(art["ckpt"]["snapshot_ms"]), 3),
        "ms median host-snapshot+enqueue per save — the ONLY "
        "checkpoint cost on the step path (write runs behind)",
        None,
    )
    _emit(
        "goodput_ckpt_finalize_ms",
        round(med(art["ckpt"]["finalize_ms"]), 3),
        "ms median finalize barrier (rollback anchor / preemption / "
        "shutdown drains — off the step path by design)",
        None,
    )
    _emit(
        "goodput_input_stall_frac",
        round(art["input_stall_fraction"], 5),
        "fraction of wall time the consumer blocked on the prefetch "
        "queue (DevicePrefetcher depth=2 over the token loader)",
        None,
    )
    _emit(
        "goodput_resume_loss_drift",
        art["loss_trajectory"]["max_abs_drift"],
        "max |stormed - uninterrupted| per-step loss over %d steps "
        "(MUST be 0.0: resume is bit-exact by contract)"
        % art["loss_trajectory"]["ref_steps"],
        None,
    )


def bench_fleet(trace_dir=None):
    """The fleet control plane (docs/serving.md "Fleet operations"),
    measured: reuses ``tools/fleet_drill.py``'s seeded storm (the FLEET
    gate's exact machinery — crash + preemption + arrival spike +
    mid-load rolling deploy over an autoscaled multi-replica fleet on a
    virtual clock, vs a fault-free fixed-size reference) and emits the
    three headline rows: request goodput under the combined storm, the
    number of accepted requests LOST by the rolling deploy (0 by
    contract — a nonzero value means the zero-downtime guarantee broke,
    not that a knob needs tuning), and the storm's p99 TTFT inflation
    over the fault-free reference (bound 2.0 in the drill itself).
    Plus the canary-gate rows from ``tools/canary_drill.py``: the
    detection latency of a planted bad deploy
    (``fleet_canary_detect_ticks``) and the clean-deploy false-verdict
    count (``fleet_canary_false_positive``, pinned 0.0).
    CI-grade numbers on CPU virtual time; not TPU perf claims.

    The FLEET gate's evidence artifact is reused via
    APEX_TPU_FLEET_ARTIFACT (verify_tier1.sh runs FLEET before PERF and
    hands its --json here) so CI pays for ONE storm, not two — accepted
    only when the artifact's recorded config and chaos spec equal the
    drill's defaults, exactly like the serve-chaos reuse above."""
    import importlib.util as _ilu

    root = os.path.dirname(os.path.abspath(__file__))
    spec = _ilu.spec_from_file_location(
        "fleet_drill", os.path.join(root, "tools", "fleet_drill.py"),
    )
    fd = _ilu.module_from_spec(spec)
    spec.loader.exec_module(fd)
    defaults = fd.build_parser().parse_args([])
    art = None
    reuse = os.environ.get("APEX_TPU_FLEET_ARTIFACT")
    if reuse and os.path.exists(reuse):
        try:
            with open(reuse) as f:
                cand = json.load(f)
            cfg_sec = cand.get("config", {})
            if (cand.get("chaos_spec") == defaults.chaos
                    and cfg_sec
                    and all(getattr(defaults, k, None) == v
                            for k, v in cfg_sec.items())):
                art = cand
        except (OSError, ValueError):
            art = None
    if art is None:
        art = fd.run_drill(defaults)
    storm = art["storm"]
    fr = art["fleet_registry"]
    lost = sum(d["lost_requests"] for d in art["deploys"])
    desc = (
        "storm %s; crashes=%d preempts=%d router_faults=%d rerouted=%d "
        "scale_out=%d scale_in=%d deploys=%d replicas=%d"
        % (art["chaos_spec"],
           fr.get("fleet/replica_crashes", 0),
           fr.get("fleet/preempts", 0),
           fr.get("fleet/router_faults", 0),
           fr.get("fleet/rerouted", 0),
           fr.get("fleet/scale_out", 0),
           fr.get("fleet/scale_in", 0),
           fr.get("fleet/deploys", 0),
           len(art["replicas"]))
    )
    _emit(
        "fleet_chaos_goodput_pct",
        round(100.0 * storm["completed"] / storm["offered"], 3)
        if storm["offered"] else 0.0,
        "%% requests completed under the fleet storm (%s)" % desc,
        None,
    )
    _emit(
        "fleet_deploy_lost_requests",
        float(lost),
        "accepted requests lost across %d rolling deploy(s) under the "
        "storm (MUST be 0: drain+handoff re-routes, never sheds; %s)"
        % (len(art["deploys"]), desc),
        None,
    )
    inflation = art["p99_ttft_inflation"]
    _emit(
        "fleet_p99_inflation",
        round(inflation, 3) if inflation == inflation else 0.0,
        "x storm p99 TTFT over the fault-free fixed-size reference "
        "(drill bound 2.0x; <1.0 means the autoscaled storm fleet "
        "beat the reference; %s)" % desc,
        None,
    )

    # -- canary-gate rows (tools/canary_drill.py) --------------------------
    # same reuse contract as the storm above: the CANARY gate runs the
    # drill before PERF and hands its --json via APEX_TPU_CANARY_ARTIFACT,
    # accepted only when the artifact's recorded config equals the
    # drill's defaults; otherwise the drill runs here.
    cspec = _ilu.spec_from_file_location(
        "canary_drill", os.path.join(root, "tools", "canary_drill.py"),
    )
    cd = _ilu.module_from_spec(cspec)
    cspec.loader.exec_module(cd)
    cdefaults = cd.build_parser().parse_args([])
    cart = None
    creuse = os.environ.get("APEX_TPU_CANARY_ARTIFACT")
    if creuse and os.path.exists(creuse):
        try:
            with open(creuse) as f:
                cand = json.load(f)
            cfg_sec = cand.get("config", {})
            if cfg_sec and all(
                getattr(cdefaults, k, None) == v
                for k, v in cfg_sec.items()
            ):
                cart = cand
        except (OSError, ValueError):
            cart = None
    if cart is None:
        cart = cd.run_drill(cdefaults)
    cdesc = (
        "planted NaN-poisoned weights + %dx-throttled decode behind a "
        "frac=%.2f canary hold, %d replicas, soak=%d window=%d ticks"
        % (cdefaults.slow_factor, cdefaults.canary_frac,
           cdefaults.replicas, cdefaults.soak_ticks,
           cdefaults.max_window_ticks)
    )
    detect = cart.get("detect_ticks")
    _emit(
        "fleet_canary_detect_ticks",
        float(detect) if detect is not None else float("nan"),
        "virtual ticks from canary window open to the FAIL verdict + "
        "auto-rollback on the planted regression (%s; lower is faster "
        "detection, bounded by the drill's soak floor)" % cdesc,
        None,
    )
    _emit(
        "fleet_canary_false_positive",
        float(cart.get("false_positives", -1)),
        "canary FAIL verdicts across %d clean deploys of re-seeded "
        "same-architecture weights (MUST stay 0.0: the one-sided "
        "tests + min-sample honesty floor admit no verdict from the "
        "hold's own load skew)" % len(cart.get("clean_runs", [])),
        None,
    )


_CONFIGS = {
    "resnet50": bench_resnet50,
    "ddp_syncbn": bench_ddp_syncbn,
    "bert_lamb": bench_bert_lamb,
    "mha": bench_mha,
    "tp_gpt": bench_tp_gpt,
    "train3d": bench_train3d,
    "zero": bench_zero,
    "long_attn": bench_long_attn,
    "smoke": bench_smoke,
    "serve": bench_serve,
    "goodput": bench_goodput,
    "fleet": bench_fleet,
}

#: configs `--config all` skips: smoke/serve/goodput/fleet are CI
#: schema/acceptance drivers, and ddp_syncbn/tp_gpt are the
#: degenerate-prone proxies train3d REPLACES in the batch (still
#: invocable by name for historical comparisons)
_ALL_EXCLUDED = (
    "smoke", "serve", "goodput", "fleet", "ddp_syncbn", "tp_gpt"
)


def main(config="bert_lamb", trace_dir=None):
    # Fail a typo'd APEX_TPU_BENCH_POLICY BEFORE any backend touch:
    # under --config all the bert config would otherwise raise only
    # after earlier benches burned scarce tunnel time.  The guard and
    # the consumer share ONE module-level read (_BENCH_POLICY) and the
    # validation delegates to the models' own resolution, so a policy
    # added there is automatically accepted here.
    from apex_tpu.transformer.pipeline_parallel.schedules import (
        resolve_remat_policy,
    )

    try:
        resolve_remat_policy(_BENCH_POLICY)
    except ValueError as e:
        raise SystemExit(f"APEX_TPU_BENCH_POLICY: {e}")
    if _WATCHDOG_S > 0:
        armed = _backend_watchdog(
            _WATCHDOG_S, _METRIC_NAMES.get(config, config)
        )
        jax.devices()  # first backend touch happens under the watchdog
        armed.set()
    if config == "all":
        for name, fn in _CONFIGS.items():
            if name in _ALL_EXCLUDED:
                continue
            # one trace (the headline config) per invocation
            fn(trace_dir if name == "bert_lamb" else None)
        return
    _CONFIGS[config](trace_dir)


def _run_gate(baseline_path=None):
    """bench.py --gate: judge THIS invocation's emitted lines against
    the last committed round with tools/bench_diff.py (regression gate
    on every measured metric + the flatline gate on the flash-attention
    line when it was measured).  Returns the number of failures; emits
    a ``bench_gate_failures`` metric line so the gate verdict rides the
    same artifact stream it judges."""
    import importlib.util

    root = os.path.dirname(os.path.abspath(__file__))
    spec = importlib.util.spec_from_file_location(
        "bench_diff", os.path.join(root, "tools", "bench_diff.py")
    )
    bd = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(bd)

    baseline_path = baseline_path or bd.default_baseline(root)
    if baseline_path is None:
        print("bench gate: no baseline round found — nothing to gate",
              file=sys.stderr)
        return 0
    current = bd.collapse(list(_GATE_RECORDS))
    baseline = bd.collapse(bd.load_records(baseline_path))
    # judge only what this invocation measured: --config bert_lamb must
    # not "fail" for not re-running the other rows
    baseline = {m: s for m, s in baseline.items() if m in current}
    rows = bd.compare(current, baseline)
    print(f"bench gate vs {os.path.basename(baseline_path)}:",
          file=sys.stderr)
    print(bd.render(rows), file=sys.stderr)
    failures = [
        f"regression: {r['metric']} {r['baseline']} -> {r['current']}"
        for r in rows if r["status"] == "regressed"
    ]
    flash = next(
        (r for r in rows if r["metric"] == bd.FLAT_DEFAULT), None
    )
    if flash is not None and flash["status"] == "flat":
        failures.append(
            f"flatline: {bd.FLAT_DEFAULT} stuck at {flash['current']}"
        )
    for f_ in failures:
        print(f"bench gate FAIL {f_}", file=sys.stderr)
    _emit(
        "bench_gate_failures",
        float(len(failures)),
        "regressions+flatlines vs %s (tools/bench_diff.py; "
        "docs/observability.md)" % os.path.basename(baseline_path),
        None,
    )
    return len(failures)


if __name__ == "__main__":
    ap = argparse.ArgumentParser()
    ap.add_argument(
        "--config",
        default="bert_lamb",
        choices=sorted(_CONFIGS) + ["all"],
        help="BASELINE parity config to run (default: the #3 north star)",
    )
    ap.add_argument(
        "--trace",
        metavar="DIR",
        default=None,
        help="collect a jax.profiler trace of the timed window into DIR",
    )
    ap.add_argument(
        "--hlo-out",
        metavar="FILE",
        default=None,
        help="write the compiled headline step's optimized-HLO text to "
        "FILE (bert_lamb config; feeds tools/trace_summary.py --hlo). "
        "Equivalent to APEX_TPU_BENCH_HLO_OUT, the programmatic channel.",
    )
    ap.add_argument(
        "--metrics-out",
        metavar="FILE",
        default=None,
        help="also append every emitted metric line to FILE as JSONL "
        "(the observability sink schema, docs/observability.md) — "
        "stdout output is unchanged",
    )
    ap.add_argument(
        "--flight",
        metavar="N[:DIR]",
        default=None,
        help="arm a flight recorder: keep the last N emitted metric "
        "lines and dump flight_<ts>.json on an unhandled exception "
        "(crash forensics, docs/observability.md).  Equivalent to "
        "APEX_TPU_FLIGHT=N[:DIR].",
    )
    ap.add_argument(
        "--lint",
        action="store_true",
        help="run the apex_tpu.analysis graph-lint passes over the "
        "headline step (transfer/donation via compiled HLO, callback "
        "scan via jaxpr) and emit a graph_lint_errors metric line "
        "(docs/analysis.md).  Equivalent to APEX_TPU_BENCH_LINT=1.",
    )
    ap.add_argument(
        "--gate",
        action="store_true",
        help="after the configs run, judge this invocation's metric "
        "lines against the last committed BENCH round with "
        "tools/bench_diff.py (regression + flash-attention flatline "
        "gates); exit 4 on failure so the trajectory cannot go flat "
        "silently again (ROADMAP item 2)",
    )
    ap.add_argument(
        "--gate-baseline",
        metavar="FILE",
        default=None,
        help="baseline round for --gate (default: the newest "
        "BENCH_all_r*.json at the repo root)",
    )
    args = ap.parse_args()
    if args.hlo_out:
        os.environ["APEX_TPU_BENCH_HLO_OUT"] = args.hlo_out
    if args.lint:
        os.environ["APEX_TPU_BENCH_LINT"] = "1"
        _BENCH_LINT = True
    if args.metrics_out:
        from apex_tpu.observability.export import JSONLSink

        _METRICS_SINK = JSONLSink(args.metrics_out)
    from apex_tpu.observability.flight import FlightRecorder

    _FLIGHT = FlightRecorder.from_env(
        args.flight, run={"bench": args.config}
    ) if args.flight else FlightRecorder.from_env(
        run={"bench": args.config}
    )
    try:
        main(config=args.config, trace_dir=args.trace)
        if args.gate and _run_gate(args.gate_baseline):
            sys.exit(4)
    except BaseException as e:
        if _FLIGHT is not None and not isinstance(e, SystemExit):
            from apex_tpu.resilience.runner import _safe_dump

            # guarded: a failing dump (full disk, bad dir) must not
            # demote the crash being debugged to "During handling..."
            _safe_dump(_FLIGHT, f"{type(e).__name__}: {e}")
        raise
    finally:
        if _METRICS_SINK is not None:
            _METRICS_SINK.close()
