"""Repo lint — source-level complement of the graph linter.

The graph linter (``apex_tpu/analysis/``, ``tools/graph_lint.py``)
proves properties of TRACED programs; some defects are cheaper to
catch at the source line, before anything traces:

- ``time.time()`` / ``datetime.now`` in jitted-path packages: inside a
  traced step these freeze at trace time (a constant baked into the
  program), the classic "why does my timestamp never change" bug.
  Host-side subsystems (observability, resilience, data, tools) are
  exempt — wall clocks are their job.
- ``float64`` literals in jitted paths: with x64 enabled they drag a
  subgraph into emulated-f64 on TPU; with it disabled they lie about
  precision.  (The graph linter's ``promotion-f64`` rule catches the
  traced consequence; this catches the source.)
- bare ``jax.device_get`` outside observability/export: a forced
  device→host sync that serializes dispatch — telemetry must go
  through the MetricRegistry's async fetch instead.
- sharding hygiene at ``pjit``/``shard_map`` call sites (the source
  half of the graph linter's sharding passes, docs/analysis.md
  "Sharding & memory passes"): ``in_shardings=None`` is implicit full
  replication (rule ``sharding-implicit-replication``), and a call
  site in a file that contracts big tensors (einsum/dot/matmul) but
  never pins an intermediate with ``with_sharding_constraint`` leaves
  GSPMD guessing activation layouts (rule
  ``sharding-missing-constraint``).  Severities and fix hints come
  from the shared ``apex_tpu.analysis.findings.RULES`` catalog — one
  rulebook for the source scan and the graph passes.
- literal kernel tile sizes at call sites (rule
  ``kernel-hardcoded-block``, the source half of the kernel passes in
  docs/analysis.md "Kernel passes"): ``block_q=128`` baked into a
  jitted-path call bypasses the tuned-tile lookup
  (``APEX_TPU_TUNE_CACHE`` → ``_TUNED_TILES`` → heuristic), so the
  number is right on one chip/shape and silently wrong everywhere
  else.  The kernel entry points' ``block_q=None`` defaults and
  variable-valued plumbing never match — only literal digits do.

- wall clocks in HOST-SIDE replay-critical modules: deterministic
  replay (resilience runner, serve engine, fleetctl) re-executes a
  recorded step sequence, and ``time.time()`` there makes the replay
  diverge from the recording.  The module list is NOT duplicated here
  — it delegates to ``apex_tpu.analysis.purity.REPLAY_CRITICAL`` (the
  AST pass's single source of truth, docs/analysis.md "Concurrency &
  replay-purity passes"); the pass's in-line waiver
  ``# lint: allow(replay-wall-clock): <reason>`` is honored here too.

A line carrying ``repo-lint: allow`` is waived (use sparingly, with a
reason in the adjacent comment).  Run from anywhere::

    python tools/repo_lint.py          # exit 1 on any violation

Wired into tools/verify_tier1.sh (the analysis pass).
"""

from __future__ import annotations

import os
import re
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
PKG = os.path.join(REPO, "apex_tpu")

#: packages whose code runs (at least partly) inside traced steps —
#: wall clocks and f64 literals are banned here.  Host-side subsystems
#: (observability, resilience, checkpoint, data, _native, analysis,
#: utils) are deliberately absent.
JITTED_PATHS = (
    "ops", "models", "optimizers", "parallel", "transformer", "amp",
    "contrib", "mlp", "fused_dense", "RNN", "multi_tensor_apply",
    "reparameterization", "fp16_utils", "normalization",
)

#: (regex, why, fix) applied only under JITTED_PATHS
JITTED_RULES = (
    (re.compile(r"\btime\.time\(\)"),
     "wall clock in a jitted path freezes at trace time",
     "hoist to the host loop or observability.MetricRegistry.timing"),
    (re.compile(r"\bdatetime\.now\b"),
     "wall clock in a jitted path freezes at trace time",
     "hoist to the host loop"),
    (re.compile(r"\bfloat64\b|\bjnp\.f64\b|\bnp\.f64\b"),
     "f64 literal in a jitted path (emulated on TPU; see "
     "analysis rule promotion-f64)",
     "use float32 or the amp policy's compute dtype"),
)

#: (regex, why, fix, allowed path fragments) applied everywhere
GLOBAL_RULES = (
    (re.compile(r"\bjax\.device_get\b|\bjax\.device_get\("),
     "bare jax.device_get forces a blocking device->host sync",
     "fetch through observability.MetricRegistry (async, on a cadence)",
     ("observability" + os.sep, "checkpoint" + os.sep)),
)

WAIVER = "repo-lint: allow"


_CATALOG = None
_PURITY = None


def _purity_mod():
    """``apex_tpu.analysis.purity`` loaded STANDALONE (stdlib-only at
    module level) — the one place the replay-critical module list and
    the wall-clock patterns live.  The AST pass judges semantics; this
    linter reuses its constants for the cheap source scan."""
    global _PURITY
    if _PURITY is None:
        import importlib.util

        path = os.path.join(REPO, "apex_tpu", "analysis", "purity.py")
        spec = importlib.util.spec_from_file_location(
            "_repo_lint_purity", path
        )
        mod = importlib.util.module_from_spec(spec)
        sys.modules[spec.name] = mod
        try:
            spec.loader.exec_module(mod)
        finally:
            sys.modules.pop(spec.name, None)
        _PURITY = mod
    return _PURITY


def _catalog_rules():
    """The shared rule catalog, loaded STANDALONE from
    apex_tpu/analysis/findings.py (stdlib-only module) so this linter
    stays importable without jax — the catalog is the single source of
    severities and fix hints for the sharding source rules."""
    global _CATALOG
    if _CATALOG is None:
        import importlib.util

        path = os.path.join(REPO, "apex_tpu", "analysis", "findings.py")
        spec = importlib.util.spec_from_file_location(
            "_repo_lint_rules", path
        )
        mod = importlib.util.module_from_spec(spec)
        sys.modules[spec.name] = mod  # dataclasses needs it registered
        try:
            spec.loader.exec_module(mod)
        finally:
            sys.modules.pop(spec.name, None)
        _CATALOG = mod.RULES
    return _CATALOG


#: pjit/shard_map CALL sites (not defs/imports)
_SHARD_CALL_RE = re.compile(
    r"(?<!def )\b(?:pjit|shard_map)\s*\("
)
_IMPLICIT_REPL_RE = re.compile(r"\bin_shardings\s*=\s*None\b")
#: big-contraction fingerprints: a file doing these wants its
#: activations pinned
_CONTRACTION_RE = re.compile(
    r"jnp\.einsum|jnp\.matmul|jnp\.dot\b|lax\.dot_general|\s@\s"
)
_CONSTRAINT_TOKEN = "with_sharding_constraint"


#: literal tile sizes at kernel call sites: block_q=128 / block_k=512 /
#: block_q_dq=... with a DIGIT on the right-hand side (the entry
#: points' block_q=None defaults and variable plumbing never match)
_HARDCODED_BLOCK_RE = re.compile(r"\bblock_[qk](?:_dq)?\s*=\s*\d")


def _kernel_violations(rel: str, lines, jitted: bool):
    """Source-level kernel rules over one file's lines (rule
    ``kernel-hardcoded-block``); the graph-side kernel passes judge
    the resulting configs, this catches the bypass at the call site."""
    if not jitted:
        return []
    catalog = _catalog_rules()
    out = []
    for lineno, line in enumerate(lines, 1):
        if WAIVER in line or line.lstrip().startswith("#"):
            continue
        if _HARDCODED_BLOCK_RE.search(line):
            _sev, why, fix = catalog["kernel-hardcoded-block"]
            out.append((rel, lineno, line.strip(), why, fix))
    return out


def _sharding_violations(rel: str, lines, jitted: bool):
    """Source-level sharding rules over one file's lines; the graph
    passes prove the compiled result, this catches the call-site
    defect before anything traces."""
    catalog = _catalog_rules()
    out = []
    has_contraction = any(
        _CONTRACTION_RE.search(ln) for ln in lines
        if WAIVER not in ln and not ln.lstrip().startswith("#")
    )
    has_constraint = any(_CONSTRAINT_TOKEN in ln for ln in lines)
    for lineno, line in enumerate(lines, 1):
        if WAIVER in line or line.lstrip().startswith("#"):
            continue
        if jitted and _IMPLICIT_REPL_RE.search(line):
            _sev, why, fix = catalog["sharding-implicit-replication"]
            out.append((rel, lineno, line.strip(), why, fix))
            continue
        if (
            jitted
            and _SHARD_CALL_RE.search(line)
            and "import" not in line
            and has_contraction
            and not has_constraint
        ):
            _sev, why, fix = catalog["sharding-missing-constraint"]
            out.append((rel, lineno, line.strip(), why, fix))
    return out


def _replay_clock_violations(rel: str, lines):
    """Wall clocks in host-side replay-critical modules (rule
    ``replay-wall-clock``).  Which modules are replay-critical and
    what counts as a wall clock both come from the purity pass —
    one list, two enforcement layers."""
    purity = _purity_mod()
    if not purity.is_replay_critical(rel.replace(os.sep, "/")):
        return []
    catalog = _catalog_rules()
    patterns = [re.compile(p) for p in purity.WALL_CLOCK_PATTERNS]
    out = []
    for lineno, line in enumerate(lines, 1):
        if WAIVER in line or line.lstrip().startswith("#"):
            continue
        m = purity.WAIVER_RE.search(line)
        if m is not None and m.group(1) == "replay-wall-clock":
            continue
        if any(rx.search(line) for rx in patterns):
            _sev, why, fix = catalog["replay-wall-clock"]
            out.append((rel, lineno, line.strip(), why, fix))
    return out


def _iter_sources():
    for root, dirs, files in os.walk(PKG):
        dirs[:] = [d for d in dirs if d != "__pycache__"]
        for fn in files:
            if fn.endswith(".py"):
                yield os.path.join(root, fn)


def lint() -> list:
    violations = []
    for path in _iter_sources():
        rel = os.path.relpath(path, PKG)
        top = rel.split(os.sep, 1)[0]
        jitted = top in JITTED_PATHS
        with open(path, encoding="utf-8") as f:
            lines = f.read().splitlines()
        for lineno, line in enumerate(lines, 1):
            if WAIVER in line:
                continue
            if jitted:
                for rx, why, fix in JITTED_RULES:
                    if rx.search(line):
                        violations.append(
                            (rel, lineno, line.strip(), why, fix)
                        )
            for rx, why, fix, allowed in GLOBAL_RULES:
                if any(a in rel for a in allowed):
                    continue
                if rx.search(line):
                    violations.append(
                        (rel, lineno, line.strip(), why, fix)
                    )
        violations.extend(_sharding_violations(rel, lines, jitted))
        violations.extend(_kernel_violations(rel, lines, jitted))
        violations.extend(_replay_clock_violations(rel, lines))
    return violations


def main() -> int:
    violations = lint()
    if not violations:
        print(f"repo lint: apex_tpu/ clean "
              f"({len(list(_iter_sources()))} files)")
        return 0
    print(f"repo lint: {len(violations)} violation(s)")
    for rel, lineno, text, why, fix in violations:
        print(f"  apex_tpu/{rel}:{lineno}: {why}\n"
              f"    {text}\n"
              f"    fix: {fix}")
    return 1


if __name__ == "__main__":
    sys.exit(main())
