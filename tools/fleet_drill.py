"""Fleet control-plane drill — the FLEET acceptance gate's engine.

Proves the fleet control plane (docs/serving.md "Fleet operations")
end to end on a deterministic virtual clock: one seeded storm run
combines a **replica crash**, a **preemption**, a **traffic spike**,
router faults, and a **rolling deploy** against an autoscaled
multi-replica :class:`~apex_tpu.fleetctl.Fleet`, next to a fault-free
same-size reference — and the drill asserts the headline guarantees:

1. **zero lost accepted requests** — every submitted request reaches
   exactly ONE fleet-wide terminal (completed, or a terminal shed on
   whichever replica it truly ended on; re-routes are hops, not
   outcomes), no span chain is left open, and the rolling deploy's
   ``lost_requests`` (terminal ``shed(draining)`` over the deploy
   window) is exactly 0 — drains re-route through the fleet door;
2. **zero leaked pages, per replica** — ``PagePool.leak_check`` is
   re-proven on EVERY replica at the end, including crashed, ejected
   and scaled-in ones (an evacuated pool must be exactly empty);
3. **every fleet chaos site fired and was ledgered** — the
   ``fleet.replica_crash`` / ``fleet.preempt`` / ``fleet.router``
   injections show up 1:1 on the fleet counters
   (``fleet/replica_crashes``, ``fleet/preempts``,
   ``fleet/router_faults``);
4. **the autoscaler actually scaled** — at least one scale-OUT (the
   spike/crash pressure) and one scale-IN (the post-storm headroom)
   executed, on the counters AND as ``health/fleet_scale_*`` instants
   on the shared span timeline;
5. **bounded degradation** — fleet p99 end-to-end TTFT (original
   ``submitted_at`` preserved across every re-route) within
   ``--max-p99-inflation`` of the fault-free reference fleet under
   the SAME traffic (spike included).

The storm replicas share one :class:`SpanRecorder` (request ids are
globally unique), so ``tools/timeline.py --json`` re-proves chain
completeness across replica hops (``routed`` phases) from the dump.
A final ops check starts each live replica's port-0
:class:`OpsServer`, verifies the OS assigned distinct ports, and
folds the per-replica scrapes through
:func:`~apex_tpu.fleetctl.aggregate_expositions`.

``--json`` writes the evidence artifact (``bench.py --config fleet``
reuses it via ``APEX_TPU_FLEET_ARTIFACT`` for its ``fleet_*`` golden
rows); ``--spans`` records every storm request's span chain for the
timeline gate.

Usage::

    python tools/fleet_drill.py --json /tmp/fleet.json \
        --spans /tmp/fleet_spans.json
"""

from __future__ import annotations

import argparse
import json
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

os.environ.setdefault("JAX_PLATFORMS", "cpu")

#: the default storm: every fleet chaos site fires at least once.
#: Indices are FLEET TICKS (the control plane's own call counter —
#: one ``Fleet.step`` per tick), so the storm shape is readable
#: straight off the spec: router blips at ticks 20/21, a replica
#: crash at 120 (mid-load), a preemption notice at 260 (mid-SPIKE —
#: capacity leaves exactly when demand peaks).
DEFAULT_CHAOS_SPEC = (
    "fleet.router:raise:x2@20,21;"
    "fleet.replica_crash:kill:x1@120;"
    "fleet.preempt:notice:x1@260"
)

#: injected fault counts per fleet ledger counter the artifact must
#: show — derived from DEFAULT_CHAOS_SPEC (a custom --chaos skips)
DEFAULT_EXPECTED = {
    "fleet/router_faults": 2,
    "fleet/replica_crashes": 1,
    "fleet/preempts": 1,
}


def model_configs(args):
    import jax.numpy as jnp

    from apex_tpu.models.gpt import GptConfig
    from apex_tpu.serve import ServeConfig

    cfg = GptConfig(
        vocab_size=args.vocab, hidden_size=args.hidden,
        num_layers=args.layers, num_heads=args.heads,
        intermediate_size=2 * args.hidden, max_seq_len=256,
        dtype=jnp.float32,
    )
    serve_cfg = ServeConfig(
        page_size=args.page_size, num_pages=args.pages,
        max_batch=args.batch, max_pages_per_seq=args.pages_per_seq,
        verify=args.verify,
    )
    return cfg, serve_cfg


def make_params(args, key: int):
    import jax

    from apex_tpu.models.gpt import GptModel

    cfg, _ = model_configs(args)
    model = GptModel(cfg)
    return model.init(
        jax.random.PRNGKey(key),
        jax.random.randint(jax.random.PRNGKey(0), (16, 1), 0,
                           cfg.vocab_size),
    )


class VirtualClock:
    """One fixed tick per fleet step — same rationale as
    serve_chaos_drill's: chaos is seeded and exact, the latency
    verdict must be too.  TTFT measures fleet SCHEDULING delay (door
    wait, queue wait, re-route round-trips, drain grace) in tick
    units, bit-for-bit reproducible per seed."""

    def __init__(self, tick_s: float = 0.005):
        self.t = 0.0
        self.tick_s = tick_s

    def __call__(self) -> float:
        return self.t

    def advance(self) -> None:
        self.t += self.tick_s


def build_fleet(args, clock, params, *, recorder=None, scaled=False):
    from apex_tpu.fleetctl import (
        Autoscaler,
        AutoscalerConfig,
        EngineReplica,
        Fleet,
    )
    from apex_tpu.observability import MetricRegistry
    from apex_tpu.serve import InferenceEngine

    cfg, serve_cfg = model_configs(args)

    def factory(name: str) -> EngineReplica:
        registry = MetricRegistry(fetch_every=1)
        engine = InferenceEngine(
            cfg, params, serve_cfg, registry=registry,
        ).build()
        return EngineReplica(
            name, engine, clock=clock, spans=recorder,
            max_queue_depth=args.max_queue_depth,
            clamp_max_new_tokens=args.clamp_max_new_tokens,
            clamp_occupancy=args.clamp_occupancy,
            max_retries=args.max_retries,
        )

    autoscaler = None
    if scaled:
        autoscaler = Autoscaler(AutoscalerConfig(
            min_replicas=1, max_replicas=args.max_replicas,
            ttft_threshold_ms=args.ttft_threshold_ms,
            short_window_s=50 * clock.tick_s,
            long_window_s=400 * clock.tick_s,
            out_factor=args.out_factor,
            queue_high=args.queue_high, queue_low=args.queue_low,
            headroom_evals=3, cooldown_ticks=args.cooldown_ticks,
            eval_every=4,
        ), clock=clock)
    return Fleet(
        factory, replicas=args.replicas, clock=clock, spans=recorder,
        autoscaler=autoscaler,
    )


def gen_arrivals(args, rs):
    """Time-varying Poisson arrivals: the base rate with a
    ``spike_factor`` burst over [spike_start, spike_end) virtual
    seconds — the traffic spike the autoscaler must absorb."""
    arrivals = []
    t = 0.0
    for _ in range(args.requests):
        rate = args.rate * (
            args.spike_factor
            if args.spike_start <= t < args.spike_end else 1.0
        )
        t += rs.exponential(1.0 / rate)
        arrivals.append(t)
    return arrivals


def run_fleet_load(fleet, clock, args, *, label, deploy_params=None,
                   tail_ticks=1):
    """Drive one seeded load through a fleet on the virtual clock:
    submissions at the door, one ``Fleet.step`` per tick, a rolling
    update started at ``--deploy-tick`` when ``deploy_params`` is
    given, then ``tail_ticks`` idle ticks (the post-storm headroom a
    scale-in needs to prove itself)."""
    import numpy as np

    from apex_tpu.observability.meter import percentile
    from apex_tpu.serve import Request

    rs = np.random.RandomState(args.seed)
    arrivals = gen_arrivals(args, rs)
    prompt_lens = rs.choice(args.prompt_mix, size=args.requests)
    out_lens = rs.choice(args.output_mix, size=args.requests)

    submitted = 0
    reqs = []
    deployed = False
    idle = 0
    for _ in range(args.max_ticks):
        now = clock()
        while submitted < args.requests and arrivals[submitted] <= now:
            reqs.append(fleet.submit(Request(
                prompt=list(rs.randint(0, args.vocab,
                                       size=prompt_lens[submitted])),
                max_new_tokens=int(out_lens[submitted]),
            )))
            submitted += 1
        if (
            deploy_params is not None and not deployed
            and fleet.tick >= args.deploy_tick
        ):
            fleet.start_rolling_update(deploy_params)
            deployed = True
        fleet.step()
        clock.advance()
        if submitted >= args.requests and not fleet.pending:
            idle += 1
            if idle >= tail_ticks:
                break
        else:
            idle = 0
    else:
        raise RuntimeError(
            f"{label}: fleet did not settle within {args.max_ticks} "
            f"ticks (door={fleet.door_depth}, deploy={fleet.deploy})"
        )

    done = [r for r in reqs if r.status == "done"]
    shed = [r for r in reqs if r.status == "shed"]
    ttfts = sorted(r.ttft_ms for r in done if r.ttft_ms is not None)
    shed_reasons = {}
    for r in shed:
        key = r.shed_reason or "?"
        shed_reasons[key] = shed_reasons.get(key, 0) + 1
    return {
        "label": label,
        "offered": len(reqs),
        "completed": len(done),
        "shed": len(shed),
        "shed_reasons": shed_reasons,
        "unterminated": [
            r.rid for r in reqs if r.status not in ("done", "shed")
        ],
        "retries_total": sum(r.retries for r in reqs),
        "ttft_ms": {
            "p50": percentile(ttfts, 0.50),
            "p99": percentile(ttfts, 0.99),
            "samples": len(ttfts),
        },
        "ticks": fleet.tick,
        "wall_s": clock(),
        "deployed": deployed,
    }


def ops_check(fleet) -> dict:
    """Satellite proof: N replicas in one process each export
    ``/metrics`` on an OS-assigned port (no collision), and the
    router-side aggregation folds their scrapes into one fleet view.
    EVERY replica that ever served exports — dead ones still hold
    their ledger, and the fleet totals are only honest with all of
    them in the fold."""
    started = [rep.start_ops() for rep in fleet.replicas]
    try:
        ports = [srv.bound_port for srv in started]
        agg = fleet.aggregate_scrapes()
    finally:
        for rep in fleet.replicas:
            rep.stop_ops()
    return {
        "servers": len(started),
        "ports": ports,
        "distinct_ports": len(set(ports)) == len(ports),
        "all_bound": all(p and p > 0 for p in ports),
        "aggregated_sources": agg["sources"],
        "aggregated_completed": agg["counters"].get(
            "apex_serve_completed_count_total",
            agg["counters"].get("serve/completed"),
        ),
        "counter_families": len(agg["counters"]),
    }


def run_drill(args) -> dict:
    from apex_tpu.observability.spans import SpanRecorder, wall_clock_anchor
    from apex_tpu.resilience import chaos

    faults, seed = chaos.parse_spec(args.chaos)
    sites = sorted({f.site for f in faults})
    params = make_params(args, key=1)

    # -- 1. fault-free N-replica reference (same traffic, spike and
    # all; no chaos, no autoscaler, no deploy) -----------------------------
    ref_clock = VirtualClock()
    ref_fleet = build_fleet(args, ref_clock, params)
    reference = run_fleet_load(ref_fleet, ref_clock, args,
                               label="reference")
    ref_leaks = ref_fleet.leak_check()

    # -- 2. the storm: crash + preemption + spike + rolling deploy ---------
    recorder = SpanRecorder(capacity=args.span_capacity)
    storm_clock = VirtualClock()
    storm_fleet = build_fleet(args, storm_clock, params,
                              recorder=recorder, scaled=True)
    deploy_params = make_params(args, key=2)
    with chaos.inject(*faults, seed=seed):
        storm = run_fleet_load(
            storm_fleet, storm_clock, args, label="storm",
            deploy_params=deploy_params, tail_ticks=args.tail_ticks,
        )
    storm_leaks = storm_fleet.leak_check()

    ops = ops_check(storm_fleet)

    if args.spans:
        recorder.dump(reason="fleet_drill", path=args.spans)

    freg = {
        k: v for k, v in storm_fleet.registry.fetch().items()
        if k.startswith("fleet/")
    }
    agg_serve = storm_fleet.aggregate_values()

    ref_p99 = reference["ttft_ms"]["p99"]
    storm_p99 = storm["ttft_ms"]["p99"]
    inflation = (
        storm_p99 / ref_p99
        if ref_p99 and ref_p99 == ref_p99 and storm_p99 == storm_p99
        else float("nan")
    )
    health_rules = [e.rule for e in storm_fleet.health_events]

    return {
        "anchor": wall_clock_anchor(),
        "config": {
            k: getattr(args, k) for k in (
                "requests", "rate", "spike_factor", "spike_start",
                "spike_end", "prompt_mix", "output_mix", "seed",
                "replicas", "max_replicas", "batch", "page_size",
                "pages", "pages_per_seq", "max_queue_depth",
                "max_retries", "deploy_tick", "tail_ticks",
            )
        },
        "chaos_spec": args.chaos,
        "chaos_sites": sites,
        "reference": reference,
        "storm": storm,
        "p99_ttft_inflation": inflation,
        "process_deaths": 0,  # reaching this line IS the evidence
        "goodput": storm_fleet.goodput(),
        "terminals": {
            "offered": storm["offered"],
            "completed": storm["completed"],
            "shed": storm["shed"],
            "accounted": (
                storm["completed"] + storm["shed"] == storm["offered"]
            ),
            "open_spans": len(recorder.open_requests),
            "span_drops": recorder.dropped,
        },
        "pages": {
            "per_replica_in_use": storm_leaks,
            "reference_in_use": ref_leaks,
        },
        "fleet_registry": freg,
        "aggregated_serve": agg_serve,
        "replicas": storm_fleet.summary()["replicas"],
        "deploys": storm_fleet.deploy_history,
        "autoscaler": {
            "decisions": [
                e.rule for e in storm_fleet.autoscaler.decisions
            ],
            "health_events": health_rules,
            "scale_out_events": health_rules.count("fleet_scale_out"),
            "scale_in_events": health_rules.count("fleet_scale_in"),
        },
        "ops": ops,
        "spans_file": args.spans,
    }


def build_parser() -> argparse.ArgumentParser:
    ap = argparse.ArgumentParser(
        description='fleet control-plane drill (docs/serving.md '
        '"Fleet operations")',
    )
    ap.add_argument("--requests", type=int, default=140)
    ap.add_argument("--rate", type=float, default=30.0,
                    help="base Poisson arrival rate, requests/s "
                    "(virtual time)")
    ap.add_argument("--spike-factor", type=float, default=5.0,
                    dest="spike_factor",
                    help="arrival-rate multiplier during the spike")
    ap.add_argument("--spike-start", type=float, default=0.9,
                    dest="spike_start", help="spike window start (s)")
    ap.add_argument("--spike-end", type=float, default=1.5,
                    dest="spike_end", help="spike window end (s)")
    ap.add_argument("--prompt-mix", type=int, nargs="+",
                    default=[8, 16, 24], dest="prompt_mix")
    ap.add_argument("--output-mix", type=int, nargs="+",
                    default=[8, 16, 24], dest="output_mix")
    ap.add_argument("--vocab", type=int, default=64)
    ap.add_argument("--hidden", type=int, default=32)
    ap.add_argument("--layers", type=int, default=2)
    ap.add_argument("--heads", type=int, default=2)
    ap.add_argument("--batch", type=int, default=4)
    ap.add_argument("--page-size", type=int, default=8)
    ap.add_argument("--pages", type=int, default=64)
    ap.add_argument("--pages-per-seq", type=int, default=8)
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--verify", action="store_true",
                    help="run analysis verification at every (re)build "
                    "— slower; redeploys re-verify too")
    ap.add_argument("--chaos", default=DEFAULT_CHAOS_SPEC,
                    help="APEX_TPU_CHAOS-grammar storm spec; fleet.* "
                    "site indices are FLEET TICKS (default fires all "
                    "three fleet sites)")
    ap.add_argument("--replicas", type=int, default=2,
                    help="initial fleet size (and the reference size)")
    ap.add_argument("--max-replicas", type=int, default=4)
    ap.add_argument("--max-queue-depth", type=int, default=16)
    ap.add_argument("--max-retries", type=int, default=2)
    ap.add_argument("--clamp-max-new-tokens", type=int, default=12)
    ap.add_argument("--clamp-occupancy", type=float, default=0.85)
    ap.add_argument("--ttft-threshold-ms", type=float, default=100.0,
                    dest="ttft_threshold_ms")
    ap.add_argument("--out-factor", type=float, default=3.0,
                    dest="out_factor")
    ap.add_argument("--queue-high", type=float, default=8.0)
    ap.add_argument("--queue-low", type=float, default=1.0)
    ap.add_argument("--cooldown-ticks", type=int, default=32)
    ap.add_argument("--deploy-tick", type=int, default=320,
                    help="fleet tick to start the rolling update at "
                    "(default lands mid-load, right after the spike: "
                    "a TRUE rolling deploy across serving replicas, "
                    "not an idle-fleet swap)")
    ap.add_argument("--tail-ticks", type=int, default=400,
                    help="idle ticks after the load settles (the "
                    "scale-in headroom window)")
    ap.add_argument("--max-ticks", type=int, default=20000)
    ap.add_argument("--max-p99-inflation", type=float, default=2.0)
    ap.add_argument("--json", default=None, metavar="OUT")
    ap.add_argument("--spans", default=None, metavar="OUT")
    ap.add_argument("--span-capacity", type=int, default=65536)
    return ap


def main(argv=None) -> int:
    args = build_parser().parse_args(argv)

    art = run_drill(args)
    if args.json:
        from apex_tpu.observability.flight import json_safe

        with open(args.json, "w") as f:
            json.dump(json_safe(art), f, indent=1, allow_nan=False)
            f.write("\n")

    ref, storm = art["reference"], art["storm"]
    print(
        "fleet drill: storm %d/%d completed (%d shed: %s) across "
        "%d replicas; reference %d/%d on %d"
        % (storm["completed"], storm["offered"], storm["shed"],
           ", ".join(f"{k}={v}"
                     for k, v in sorted(storm["shed_reasons"].items()))
           or "none",
           len(art["replicas"]), ref["completed"], ref["offered"],
           art["config"]["replicas"])
    )
    print(
        "  p99 TTFT: storm %.2fms vs reference %.2fms (inflation "
        "%.2fx, bound %.1fx)"
        % (storm["ttft_ms"]["p99"], ref["ttft_ms"]["p99"],
           art["p99_ttft_inflation"], args.max_p99_inflation)
    )
    fr = art["fleet_registry"]
    print(
        "  churn: crashes=%d preempts=%d router_faults=%d "
        "rerouted=%d scale_out=%d scale_in=%d deploys=%d"
        % (fr.get("fleet/replica_crashes", 0),
           fr.get("fleet/preempts", 0),
           fr.get("fleet/router_faults", 0),
           fr.get("fleet/rerouted", 0),
           fr.get("fleet/scale_out", 0),
           fr.get("fleet/scale_in", 0),
           fr.get("fleet/deploys", 0))
    )
    for d in art["deploys"]:
        print(
            "  deploy: ticks %d..%d updated=%s lost_requests=%d"
            % (d["started_tick"], d["finished_tick"],
               ",".join(d["updated"]), d["lost_requests"])
        )
    print(
        "  ops: %d servers on ports %s, %d counter families aggregated"
        % (art["ops"]["servers"], art["ops"]["ports"],
           art["ops"]["counter_families"])
    )

    failures = []
    t = art["terminals"]
    if not t["accounted"]:
        failures.append(
            f"unaccounted terminals: {t['completed']}+{t['shed']} != "
            f"{t['offered']}"
        )
    if storm["unterminated"]:
        failures.append(f"unterminated requests: {storm['unterminated']}")
    if t["open_spans"]:
        failures.append(f"{t['open_spans']} request span chains left open")
    leaked = {k: v for k, v in art["pages"]["per_replica_in_use"].items()
              if v != 0}
    if leaked:
        failures.append(f"leaked pages on replicas: {leaked}")
    infl = art["p99_ttft_inflation"]
    if not (infl == infl and infl <= args.max_p99_inflation):
        failures.append(
            f"p99 TTFT inflation {infl:.2f}x over the "
            f"{args.max_p99_inflation:.1f}x bound"
        )
    if args.chaos == DEFAULT_CHAOS_SPEC:
        for key, want in DEFAULT_EXPECTED.items():
            if fr.get(key, 0) != want:
                failures.append(
                    f"{key}={fr.get(key, 0)} != injected {want} — a "
                    "fleet fault fired without its ledger entry (or "
                    "never fired at all)"
                )
    if fr.get("fleet/scale_out", 0) < 1:
        failures.append("autoscaler never scaled out under the storm")
    if fr.get("fleet/scale_in", 0) < 1:
        failures.append("autoscaler never scaled in after the storm")
    if art["autoscaler"]["scale_out_events"] < 1:
        failures.append("no fleet_scale_out health event on the timeline")
    if art["autoscaler"]["scale_in_events"] < 1:
        failures.append("no fleet_scale_in health event on the timeline")
    if not storm["deployed"] or not art["deploys"]:
        failures.append("the rolling update never ran to completion")
    for d in art["deploys"]:
        if d["lost_requests"] != 0:
            failures.append(
                f"rolling deploy lost {d['lost_requests']} accepted "
                f"requests to shed(draining)"
            )
        if not d["updated"]:
            failures.append("rolling deploy updated zero replicas")
    agg = art["aggregated_serve"]
    if agg.get("serve/shed_rerouted", 0) != fr.get("fleet/rerouted", 0):
        failures.append(
            f"re-route ledger split-brain: per-replica "
            f"serve/shed_rerouted sums to "
            f"{agg.get('serve/shed_rerouted', 0)} but the fleet "
            f"counted {fr.get('fleet/rerouted', 0)} re-admissions"
        )
    ops = art["ops"]
    if not ops["all_bound"] or not ops["distinct_ports"]:
        failures.append(
            f"ops servers not cleanly bound: ports={ops['ports']}"
        )
    if ops["aggregated_sources"] != ops["servers"]:
        failures.append(
            f"scrape aggregation saw {ops['aggregated_sources']} "
            f"sources for {ops['servers']} servers"
        )

    for msg in failures:
        print(f"FLEET DRILL FAIL: {msg}", file=sys.stderr)
    if not failures:
        print("fleet drill: PASS")
    return 1 if failures else 0


if __name__ == "__main__":
    sys.exit(main())
