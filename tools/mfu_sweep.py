"""MFU experiment sweep for BASELINE #3 (BERT-Large + LAMB).

A thin wrapper over ``bench.bench_bert_lamb`` (the headline harness) that
varies {batch, remat, remat_policy, scan_layers, remat_attention,
mlm_loss_chunks} — reusing the bench's batch construction and timing loop so
sweep numbers stay comparable to the headline.

Usage: python tools/mfu_sweep.py --only 256,True,dots,F,T,8 [--trace DIR]
(fields: batch,remat,policy,scan,rattn,mlmc; trailing fields optional)
"""

from __future__ import annotations

import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import bench


def run(batch, remat, remat_policy, scan_layers=True, remat_attention=False,
        mlm_loss_chunks=None, prevent_cse=None, mpps=None, trace_dir=None):
    cfg_kwargs = dict(
        remat=remat, remat_policy=remat_policy, scan_layers=scan_layers,
        remat_attention=remat_attention, remat_prevent_cse=prevent_cse,
    )
    label = (
        f"batch={batch:4d} remat={remat!s:5} policy={remat_policy:5} "
        f"scan={scan_layers!s:5} rattn={remat_attention!s:5} "
        f"mlmc={mlm_loss_chunks} pcse={prevent_cse} mpps={mpps}"
    )
    try:
        mfu, t, _loss, mfu_exec = bench.bench_bert_lamb(
            trace_dir=trace_dir, batch=batch, cfg_kwargs=cfg_kwargs,
            mlm_loss_chunks=mlm_loss_chunks,
            max_predictions_per_seq=mpps, emit=False,
        )
        # mfu_exec rides every row so packed (mpps) rows can't be misread
        # as like-for-like with dense rows: levers that don't change
        # executed FLOPs must move mfu_exec/step-time, not just the 6NT
        # headline (VERDICT r3 #3).
        print(
            f"{label} step={t * 1e3:7.1f}ms MFU={mfu:.4f} "
            f"mfu_exec={mfu_exec:.4f}",
            flush=True,
        )
    except Exception as e:  # OOM / compile failure etc.
        print(
            f"{label} FAILED: {type(e).__name__}: {str(e)[:200]}", flush=True
        )


# NOTE on comparability: rows run the DENSE MLM head (mpps=None) unless
# the mpps field is set; packed-head (mpps=20) numbers execute ~84% less
# decoder work and are only comparable to other packed rows (bench.py
# emits the executed-FLOPs mfu_exec alongside the 6NT headline for this
# reason).
# The r3 exploration grid (VERDICT r2 item 5: push 0.53 -> >=0.58).
# Each entry: (batch, remat, policy, scan, rattn, mlmc, pcse).  Rationale
# per row in the comment; ~2-4 min each on the chip (compile + 3 trials).
R3_GRID = [
    # headline reference point (r2 tuned config)
    (128, True, "dots", False, True, 8, False),
    # bigger batch amortizes fixed per-step cost (LAMB, LN, loss tail)
    (256, True, "dots", False, True, 8, False),
    (192, True, "dots", False, True, 8, False),
    # no remat at all: if HBM fits, removes the recompute premium
    (128, False, "dots", False, False, 8, None),
    (192, False, "dots", False, False, 8, None),
    # MLM loss chunking sweep (chunk overhead vs logits memory)
    (128, True, "dots", False, True, 4, False),
    (128, True, "dots", False, True, 16, False),
    # attention recompute off (keep the f32 score saves at S=128)
    (128, True, "dots", False, False, 8, False),
]

# Staged for the next chip window (run with --grid2): the r3 "sums"
# remat policy (same saved bytes as "dots", raw matmul outputs freed for
# epilogue fusion — docs/mfu.md lever #1) on the packed-head headline,
# vs the dots packed baseline.  Entries gain an mpps field.
R3_GRID2 = [
    (128, True, "dots", False, True, 0, False, 20),  # packed baseline
    (128, True, "sums", False, True, 0, False, 20),  # epilogue-fusion bet
    (128, True, "sums", False, False, 0, False, 20),  # sums w/o attn rematerialization
    (128, True, "sums", False, True, 16, False, None),  # dense-head control
]


if __name__ == "__main__":
    ap = argparse.ArgumentParser()
    ap.add_argument("--trace", default=None)
    ap.add_argument(
        "--only", default=None,
        help="batch,remat,policy,scan,rattn,mlmc[,pcse[,mpps]] "
             "e.g. 256,True,dots,F,T,8,F,20 (mpps=0 → dense labels)",
    )
    ap.add_argument(
        "--grid", action="store_true",
        help="run the r3 exploration grid (one line per config)",
    )
    ap.add_argument(
        "--grid2", action="store_true",
        help="run the staged 'sums'-policy grid (packed head)",
    )
    args = ap.parse_args()
    if args.grid:
        for batch, remat, policy, scan, rattn, mlmc, pcse in R3_GRID:
            run(
                batch, remat, policy, scan_layers=scan,
                remat_attention=rattn, mlm_loss_chunks=mlmc,
                prevent_cse=pcse,
            )
    elif args.grid2:
        for batch, remat, policy, scan, rattn, mlmc, pcse, mpps in R3_GRID2:
            run(
                batch, remat, policy, scan_layers=scan,
                remat_attention=rattn, mlm_loss_chunks=mlmc or None,
                prevent_cse=pcse, mpps=mpps,
            )
    elif args.only:
        f = args.only.split(",")
        run(
            int(f[0]), f[1][0] in "Tt", f[2], trace_dir=args.trace,
            scan_layers=f[3][0] in "Tt" if len(f) > 3 else True,
            remat_attention=f[4][0] in "Tt" if len(f) > 4 else False,
            mlm_loss_chunks=int(f[5]) if len(f) > 5 and f[5] != "0" else None,
            prevent_cse=(f[6][0] in "Tt") if len(f) > 6 else None,
            mpps=int(f[7]) if len(f) > 7 and f[7] != "0" else None,
        )
    else:
        # no args = exactly the headline: cfg_kwargs=None takes bench.py's
        # tuned default config, so the numbers are directly comparable
        mfu, t, _, mfu_exec = bench.bench_bert_lamb(
            trace_dir=args.trace, emit=False
        )
        print(
            f"headline step={t * 1e3:7.1f}ms MFU={mfu:.4f} "
            f"mfu_exec={mfu_exec:.4f}",
            flush=True,
        )
