"""On-chip flash-attention block-size tuner.

≙ the reference's hand-tuned per-shape kernel traits (fmha's fixed-seqlen
kernels / multihead_attn's launch configs).  The Pallas kernels take
``block_q``/``block_k``; ``_auto_block`` picks 512/256 heuristically.
This sweeps (block_q, block_k) on the real chip for the two bench-critical
shapes (BASELINE #4 mha and the long-context config) plus fwd-only and
fwd+bwd, prints TFLOP/s per cell, and flags where the heuristic loses.

``--prune`` runs the compile-free kernel analyzer
(``apex_tpu.analysis.kernels``) over every cell FIRST: infeasible
configs (VMEM overflow, tile misalignment, non-dividing blocks) and
cells the cost model predicts ``--prune-ratio``x slower than the best
predicted cell are dropped before paying their compile; the survivors
are ranked by predicted TFLOP/s.  ``--prune --dry-run`` prints the
KEEP/PRUNE table and exits without touching a device (the
verify_tier1.sh smoke).  The model's ranking is validated against the
recorded v5e sweeps (tests/data/attn_sweep_r05.json): every recorded
cell within 5% of the measured best survives pruning.

``--cache-out FILE`` persists each sweep's measured winner into the
on-disk tuning cache (``apex_tpu.ops.pallas.tune_cache`` schema) —
point ``APEX_TPU_TUNE_CACHE`` at the file and ``_tuned_tile`` consults
it at dispatch, no source edit needed.  Combined with ``--prune
--dry-run`` it instead persists the cost model's best PREDICTED cell
per sweep flavor — a device-free ranking artifact
(``tools/tune_cache_v5e.json`` is committed from exactly this) so the
next on-chip window starts one command from the model's pick; a real
measured sweep overwrites the predictions through the same merge
path.

Run (on a TPU host):  python tools/attn_tune.py [--shapes mha,long]
"""

from __future__ import annotations

import argparse
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp

from apex_tpu.ops.pallas import flash_attention as fa

SHAPES = {
    # name: (batch, heads, sq, d, causal)
    "mha": (8, 16, 2048, 64, True),      # BASELINE #4 microbench shape
    "long": (1, 8, 16384, 128, True),    # bench.py --config long_attn
    "bert": (128, 16, 128, 64, False),   # headline phase-1 shape
    "tiny": (1, 2, 256, 64, True),       # CPU interpret-mode smoke
}
BLOCKS = [128, 256, 512, 1024]

# Any cell whose implied rate beats the chip's peak plus margin is a
# mis-timed cell, not a fast one — see the under-wait caveat below.
# Default assumes v5e (~197 TFLOP/s bf16) with ~1.27x margin for
# FLOP-count conventions; on other chips pass --peak-tflops (e.g. 459
# for v5p), matching tools/comm_structure.py's knob.
_PEAK_TFLOPS_BOUND = 250.0

# r5a measured: every kernel at the long shape wants the LARGEST swept
# tile (1024, 1024) — the optimum may sit beyond the default grid.
# --blocks 512,1024,2048 probes past it (the divisibility filter
# already drops tiles the seq doesn't divide; VMEM is the real bound:
# a (1024, 2048) f32 score tile is 8 MB).
#
# Known caveat: the COMBINED fwd+bwd sweep mis-times at the mha shape
# (d=64) on the real chip — 0.01 ms cells, i.e. block_until_ready
# returned without waiting (onchip_r05.attn_tune.log); the long shape
# (d=128) times sanely, and fwd-only and --bwd-only are sane at BOTH
# shapes (attn_bwd_r05.log).  Ruled out: trace-level DCE — the traced
# combined step's jaxpr carries all 3 pallas_calls (fwd, dkdv, dq) at
# the exact mha shape, so this is a runtime synchronization artifact
# of the remote backend, not a program bug.  Until it is understood,
# trust fwd-only + --bwd-only for mha-shape decisions.  Two gates keep
# mis-timed cells out of the winners: the absolute peak-TFLOP/s bound
# below, and the fwd-floor cross-check (a combined fwd+bwd cell must be
# STRICTLY slower than the same tile's fwd-only cell — ADVICE r5).


def _flops(b, h, sq, d, causal, bwd):
    # scores + PV matmuls, causal halves the live area; bwd ~2x fwd
    f = 2 * 2 * b * h * sq * sq * d * (0.5 if causal else 1.0)
    return f * (3.0 if bwd else 1.0)


def _time_scan(step, q, k, v, iters=8, trials=3):
    """Median per-iteration time with on-device serialization.

    Same discipline as ln_tune._time_scan / bench.py: independent
    dispatches mis-time over the remote device tunnel (the host clock
    sees dispatch, not execution), so each scan iteration's q is
    data-dependent on the previous output — execution serializes on
    device and chunk_time/iters is honest.  ``step(q, k, v)`` must
    return a q-shaped tensor (o for fwd, dq for fwd+bwd).

    Sync discipline: each timed chunk ends with a device->host VALUE
    pull (float(sum)), not bare block_until_ready — the remote runtime
    has been observed returning early from block_until_ready for some
    program shapes (the r5 "0.01 ms cells", see module caveat), while
    fetching a value cannot complete before the producing execution
    has.  bench.py times the same way (its `last_sync` scalar).
    """

    @jax.jit
    def chunk(q):
        def body(carry, _):
            out = step(carry, k, v)
            return carry + out * jnp.asarray(1e-8, carry.dtype), None

        carry, _ = jax.lax.scan(body, q, None, length=iters)
        # f32 scalar alongside the carry: the value the host pulls to
        # prove the chunk executed (negligible: one pass over carry)
        return carry, jnp.sum(carry.astype(jnp.float32))

    carry, sync = chunk(q)
    float(sync)  # warmup/compile, synced
    times = []
    for _ in range(trials):
        t0 = time.perf_counter()
        carry, sync = chunk(carry)
        float(sync)  # device->host: the sync point
        times.append((time.perf_counter() - t0) / iters)
    times.sort()
    return times[len(times) // 2]


#: sweep flavor -> the kernel_specs modes whose predicted times the
#: prune model sums (what each sweep actually dispatches per cell)
_PRUNE_MODES = {
    "fwd": ("fwd",),
    "fwd+bwd": ("fwd", "dkdv", "dq"),
    "bwd-only": ("dkdv", "dq"),
    # the bwd-only PHASE-2 sweep varies the dq call's tiles alone
    # (dkdv pinned at its winner), so its prune must price the dq
    # kernel alone — a cell whose dkdv is slow can still hold the
    # best dq tile (the committed mha entry is exactly that shape)
    "dq-only": ("dq",),
}


def _prune_verdicts(name, sweep_mode, blocks, ratio, device_kind):
    """Model verdict per (bq, bk) cell: ("KEEP"|"PRUNE", prediction,
    reason).  ``sweep_mode`` keys :data:`_PRUNE_MODES` so the model
    prices exactly the kernels that sweep flavor times (a bwd-only
    sweep must not prune on a fwd prediction it never measures).
    Infeasible = any ERROR finding from the kernel passes;
    model-dominated = predicted time beyond ``ratio``x the best
    feasible cell's."""
    from apex_tpu.analysis import kernels as ka

    b, h, sq, d, causal = SHAPES[name]
    dk = fa.padded_head_dim(d)
    modes = _PRUNE_MODES[sweep_mode]
    preds = {}
    for bq in blocks:
        if bq > sq or sq % bq:
            continue
        for bk in blocks:
            if bk > sq or sq % bk:
                continue
            specs = fa.kernel_specs(
                b * h, sq, sq, dk, causal=causal, block_q=bq,
                block_k=bk, modes=modes,
            )
            preds[(bq, bk)] = ka.predict_config(
                specs, device_kind=device_kind
            )
    feasible = [p["time_s"] for p in preds.values() if p["feasible"]]
    best = min(feasible) if feasible else None
    verdicts = {}
    for cell, p in preds.items():
        if not p["feasible"]:
            verdicts[cell] = (
                "PRUNE", p,
                "infeasible: " + ",".join(p["report"].rule_ids()),
            )
        elif best is not None and p["time_s"] > ratio * best:
            verdicts[cell] = (
                "PRUNE", p,
                f"model-dominated ({p['time_s'] / best:.2f}x best "
                f"predicted)",
            )
        else:
            verdicts[cell] = ("KEEP", p, "")
    return verdicts


def _print_verdicts(name, mode, verdicts, ratio):
    kept = sum(1 for v, _, _ in verdicts.values() if v == "KEEP")
    print(f"\n== {name} {SHAPES[name]} {mode} — model prune "
          f"(ratio {ratio}x): keep {kept}/{len(verdicts)} ==")
    print(f"{'':>5} {'bq':>5} {'bk':>5} {'pred ms':>9} {'pred TF/s':>9}"
          "  reason")
    by_time = sorted(
        verdicts.items(), key=lambda kv: kv[1][1]["time_s"]
    )
    for (bq, bk), (verdict, p, reason) in by_time:
        print(f"{verdict:>5} {bq:5d} {bk:5d} {p['time_s'] * 1e3:9.2f} "
              f"{p['tflops']:9.1f}  {reason}")


def _grid_sweep(
    name, mode, make_step, flops, sq, d, q, k, v, floor=None, keep=None
):
    """Shared (bq, bk) grid driver: divisibility filter, timing,
    FAILED formatting, best tracking, auto-heuristic footer.
    ``make_step(bq, bk)`` returns a q-shaped-output step for
    :func:`_time_scan`.

    ``floor`` is the under-wait cross-check invariant (ADVICE r5):
    ``{(bq, bk): seconds}`` of a STRICTLY-CHEAPER sweep of the same
    shape (fwd-only vs this combined fwd+bwd).  A cell timing at or
    under its floor is physically impossible — it means the remote
    runtime under-waited at a *plausible* sub-peak rate the absolute
    gate cannot catch — so it is flagged and excluded from winners.

    ``keep`` (from :func:`_prune_verdicts`) restricts the sweep to the
    model-approved cells — pruned cells print and skip, paying neither
    compile nor device time.

    Returns ``(best, times)`` where ``times`` maps every successfully
    timed cell (flagged ones included) to its seconds, so a fwd sweep's
    result can serve as the next sweep's floor.
    """
    print(f"\n== {name} {SHAPES[name]} {mode} ==")
    print(f"{'bq':>5} {'bk':>5} {'ms':>9} {'TFLOP/s':>9}")
    best = (None, 0.0)
    times = {}
    for bq in BLOCKS:
        if bq > sq or sq % bq:
            continue
        for bk in BLOCKS:
            if bk > sq or sq % bk:
                continue
            if keep is not None and (bq, bk) not in keep:
                print(f"{bq:5d} {bk:5d}   PRUNED  (model; --prune)")
                continue
            try:
                t = _time_scan(make_step(bq, bk), q, k, v)
            except Exception as e:
                print(f"{bq:5d} {bk:5d}   FAILED  {type(e).__name__}:"
                      f" {str(e)[:60]}")
                continue
            times[(bq, bk)] = t
            tflops = flops / t / 1e12
            # Plausibility gate for the remote runtime's under-wait
            # artifact (see module caveat): no real cell can beat the
            # chip's peak; an "impossible" rate means block_until_ready
            # returned early and the cell must not become a winner.
            if tflops > _PEAK_TFLOPS_BOUND:
                print(f"{bq:5d} {bk:5d} {t * 1e3:9.2f} {tflops:9.1f}"
                      "  IMPLAUSIBLE (under-wait; excluded)")
                continue
            if floor is not None and (bq, bk) in floor and t <= floor[(bq, bk)]:
                print(f"{bq:5d} {bk:5d} {t * 1e3:9.2f} {tflops:9.1f}"
                      f"  UNDER-WAIT (<= fwd-only {floor[(bq, bk)] * 1e3:.2f}"
                      " ms at this tile; excluded)")
                continue
            mark = ""
            if tflops > best[1]:
                best = ((bq, bk), tflops)
                mark = "  <-- best"
            print(f"{bq:5d} {bk:5d} {t * 1e3:9.2f} {tflops:9.1f}{mark}")
    auto = fa._auto_block(sq, d)
    print(f"auto heuristic picks ({auto}, {auto}); best {best[0]} "
          f"at {best[1]:.1f} TFLOP/s")
    return best, times


def _qkv(name):
    b, h, sq, d, causal = SHAPES[name]
    key = jax.random.PRNGKey(0)
    kq, kk, kv = jax.random.split(key, 3)
    q = jax.random.normal(kq, (b * h, sq, d), jnp.bfloat16)
    k = jax.random.normal(kk, (b * h, sq, d), jnp.bfloat16)
    v = jax.random.normal(kv, (b * h, sq, d), jnp.bfloat16)
    return b, h, q, k, v, sq, d, causal, d ** -0.5


def sweep(name, bwd, floor=None, keep=None):
    b, h, q, k, v, sq, d, causal, scale = _qkv(name)
    flops = _flops(b, h, sq, d, causal, bwd)

    def make_step(bq, bk):
        if bwd:
            # fwd + the recomputation backward, kernels called directly
            # (the public custom_vjp sits a layer up).  ALL outputs are
            # folded into the q-shaped carry — returning dq alone lets
            # XLA DCE the entire dkdv pallas_call (two independent
            # side-effect-free calls) and the sweep would time only dq.
            def step(q, k, v):
                o, lse = fa.flash_fwd(
                    q, k, v, None, scale=scale, causal=causal,
                    block_q=bq, block_k=bk,
                )
                dq, dk, dv = fa.flash_bwd(
                    q, k, v, o, lse, 2.0 * o, None, scale=scale,
                    causal=causal, block_q=bq, block_k=bk,
                )
                return dq + (dk + dv) * jnp.asarray(1e-8, dq.dtype)
        else:
            def step(q, k, v):
                o, _ = fa.flash_fwd(
                    q, k, v, None, scale=scale, causal=causal,
                    block_q=bq, block_k=bk,
                )
                return o
        return step

    mode = "fwd+bwd" if bwd else "fwd"
    return _grid_sweep(
        name, mode, make_step, flops, sq, d, q, k, v, floor=floor,
        keep=keep,
    )


def sweep_bwd_only(name, keep=None, keep_dq=None):
    """Isolate the backward kernels (dkdv + dq pallas_calls, ~2/3 of a
    train step's attention time): time ``flash_bwd`` alone against
    constant precomputed (o, lse, do).  Values are garbage after the
    first carry feedback — timing-only, same shapes/FLOPs — but this
    splits the fwd+bwd sweep's confound: a (bq, bk) that wins fwd+bwd
    may be carrying a fwd win over a bwd loss."""
    b, h, q, k, v, sq, d, causal, scale = _qkv(name)
    o, lse = jax.jit(
        lambda q, k, v: fa.flash_fwd(
            q, k, v, None, scale=scale, causal=causal
        )
    )(q, k, v)
    o, lse = jax.block_until_ready((o, lse))
    flops = _flops(b, h, sq, d, causal, bwd=True) * 2.0 / 3.0  # bwd share

    def make_step(bq, bk):
        def step(q, k, v):
            dq, dk, dv = fa.flash_bwd(
                q, k, v, o, lse, 2.0 * o, None, scale=scale,
                causal=causal, block_q=bq, block_k=bk,
            )
            # fold dk/dv in: dq alone would DCE the dkdv pallas_call
            return dq + (dk + dv) * jnp.asarray(1e-8, dq.dtype)
        return step

    best, _ = _grid_sweep(
        name, "bwd-only", make_step, flops, sq, d, q, k, v, keep=keep
    )

    # Explicit config dict on EVERY path so consumers can't misread
    # which pair is which: apply as flash_bwd(block_q=.., block_k=..,
    # block_q_dq=.., block_k_dq=..).
    if best[0] is None:
        return {"dkdv": None, "dq": None, "tflops": 0.0}
    dkdv_bq, dkdv_bk = best[0]

    # phase 2: pin the dkdv tiles at the winner, sweep the dq call's
    # independent tiles (block_q_dq/block_k_dq) — the two kernels walk
    # the grid transposed, so their optima can differ
    def make_step_dq(bq, bk):
        def step(q, k, v):
            dq, dk, dv = fa.flash_bwd(
                q, k, v, o, lse, 2.0 * o, None, scale=scale,
                causal=causal, block_q=dkdv_bq, block_k=dkdv_bk,
                block_q_dq=bq, block_k_dq=bk,
            )
            return dq + (dk + dv) * jnp.asarray(1e-8, dq.dtype)
        return step

    best_dq, _ = _grid_sweep(
        name, f"bwd-only dq-tiles (dkdv pinned {dkdv_bq},{dkdv_bk})",
        make_step_dq, flops, sq, d, q, k, v,
        keep=keep_dq if keep_dq is not None else keep,
    )
    if best_dq[0] is None:
        # every phase-2 cell failed: the shared-tile phase-1 winner is
        # still a valid measured config — don't discard it
        return {"dkdv": best[0], "dq": best[0], "tflops": best[1]}
    return {"dkdv": best[0], "dq": best_dq[0], "tflops": best_dq[1]}


#: dry-run sweep flavor -> tuning-cache tile mode.  The combined
#: fwd+bwd (or bwd-only phase-1) sweep decides the shared bwd tile
#: pair; the dq-only phase decides the dq call's independent pair.
#: Only one of fwd+bwd / bwd-only appears per invocation, so the
#: shared "bwd" target never collides.
_CACHE_MODE = {
    "fwd": "fwd", "fwd+bwd": "bwd", "bwd-only": "bwd",
    "dq-only": "bwd_dq",
}


def _persist_predicted(cache_out, name, verdicts_by_mode, device_kind):
    """``--prune --dry-run --cache-out``: persist the cost model's best
    PREDICTED KEEP cell per sweep flavor.  No device was touched, so
    these are ranking artifacts, not measurements — but they make the
    next on-chip session one command (point ``APEX_TPU_TUNE_CACHE`` at
    the file) instead of a cold heuristic start, and a later measured
    sweep overwrites them through the same merge-write."""
    from apex_tpu.ops.pallas import tune_cache

    b, h, sq, d, causal = SHAPES[name]
    tiles = {}
    for sweep_mode, verdicts in verdicts_by_mode.items():
        kept = {
            cell: p for cell, (vd, p, _) in verdicts.items()
            if vd == "KEEP"
        }
        if kept:
            best = min(kept.items(), key=lambda cp: cp[1]["time_s"])
            tiles[_CACHE_MODE[sweep_mode]] = best[0]
    if not tiles:
        return
    tune_cache.update_flash(
        cache_out, sq=sq, d=fa.padded_head_dim(d), causal=causal,
        tiles=tiles, dtype="bfloat16", backend=device_kind,
    )
    print(f"[attn_tune] cached {name} PREDICTED winners {tiles} "
          f"-> {cache_out}")


def _persist_winner(cache_out, name, tiles):
    """Write a sweep's measured winner(s) into the on-disk tuning
    cache — the artifact ``_tuned_tile`` consults at dispatch."""
    from apex_tpu.ops.pallas import tune_cache

    b, h, sq, d, causal = SHAPES[name]
    tiles = {m: p for m, p in tiles.items() if p}
    if not tiles:
        return
    try:
        backend = jax.devices()[0].device_kind
    except Exception:
        backend = None
    tune_cache.update_flash(
        cache_out, sq=sq, d=fa.padded_head_dim(d), causal=causal,
        tiles=tiles, dtype="bfloat16", backend=backend,
    )
    print(f"[attn_tune] cached {name} winners {tiles} -> {cache_out}")


if __name__ == "__main__":
    ap = argparse.ArgumentParser()
    ap.add_argument("--shapes", default="mha,long")
    ap.add_argument("--fwd-only", action="store_true")
    ap.add_argument("--bwd-only", action="store_true",
                    help="sweep flash_bwd alone (constant o/lse/do) to "
                         "decouple the backward tile choice from fwd")
    ap.add_argument("--blocks", default=None,
                    help="comma-separated tile grid override, e.g. "
                         "512,1024,2048 (default: 128,256,512,1024)")
    ap.add_argument("--peak-tflops", type=float, default=197.0,
                    help="chip peak bf16 TFLOP/s for the under-wait "
                         "plausibility gate (default v5e 197; v5p 459)")
    ap.add_argument("--prune", action="store_true",
                    help="drop infeasible/model-dominated cells via the "
                         "compile-free kernel analyzer before sweeping")
    ap.add_argument("--dry-run", action="store_true",
                    help="with --prune: print the KEEP/PRUNE table and "
                         "exit without touching a device")
    ap.add_argument("--prune-ratio", type=float, default=1.5,
                    help="prune cells predicted this many times slower "
                         "than the best predicted cell (default 1.5)")
    ap.add_argument("--device-kind", default="TPU v5 lite",
                    help="device-kind string for the prune model's "
                         "peak/VMEM tables (default v5e; the sweep "
                         "itself always times the local chip)")
    ap.add_argument("--cache-out", default=None, metavar="FILE",
                    help="persist measured winners into this tuning-"
                         "cache JSON (APEX_TPU_TUNE_CACHE schema)")
    args = ap.parse_args()
    if args.blocks:
        BLOCKS = [int(x) for x in args.blocks.split(",")]
    if args.dry_run and not args.prune:
        ap.error("--dry-run requires --prune")
    _PEAK_TFLOPS_BOUND = 1.27 * args.peak_tflops
    for name in args.shapes.split(","):
        keeps = {}
        verdicts_by_mode = {}
        if args.prune:
            if args.bwd_only:
                prune_sweeps = ["bwd-only", "dq-only"]
            elif args.fwd_only:
                prune_sweeps = ["fwd"]
            else:
                prune_sweeps = ["fwd", "fwd+bwd"]
            for sweep_mode in prune_sweeps:
                v = _prune_verdicts(
                    name, sweep_mode, BLOCKS, args.prune_ratio,
                    args.device_kind,
                )
                _print_verdicts(name, sweep_mode, v, args.prune_ratio)
                verdicts_by_mode[sweep_mode] = v
                keeps[sweep_mode] = {
                    c for c, (verdict, _, _) in v.items()
                    if verdict == "KEEP"
                }
        keep_fwd = keeps.get("fwd")
        keep_bwd = keeps.get("fwd+bwd") or keeps.get("bwd-only")
        if args.dry_run:
            if args.cache_out:
                _persist_predicted(
                    args.cache_out, name, verdicts_by_mode,
                    args.device_kind,
                )
            continue
        if args.bwd_only:
            result = sweep_bwd_only(
                name, keep=keep_bwd, keep_dq=keeps.get("dq-only")
            )
            if args.cache_out and result.get("dkdv"):
                _persist_winner(args.cache_out, name, {
                    "bwd": result["dkdv"], "bwd_dq": result["dq"],
                })
            continue
        best_fwd, fwd_times = sweep(name, bwd=False, keep=keep_fwd)
        if args.cache_out and best_fwd[0]:
            _persist_winner(args.cache_out, name, {"fwd": best_fwd[0]})
        if not args.fwd_only:
            # the fwd-only cells are the combined sweep's floor: a
            # fwd+bwd cell at most as slow as fwd alone is an under-wait
            best_bwd, _ = sweep(
                name, bwd=True, floor=fwd_times, keep=keep_bwd
            )
            if args.cache_out and best_bwd[0]:
                _persist_winner(
                    args.cache_out, name, {"bwd": best_bwd[0]}
                )
