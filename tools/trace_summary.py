"""Summarize a jax.profiler trace directory: top device ops by total time.

Usage: python tools/trace_summary.py /tmp/trace_dir [-n 30]

Parses the Perfetto ``*.trace.json.gz`` the profiler writes and aggregates
wall time per event name on the device tracks, so the 0.4x-MFU question
("where do the milliseconds go?") has a terminal-native answer — no
TensorBoard needed in this environment.

``--flight flight_<ts>.json`` cross-references a flight-recorder dump
(``tools/flight_view.py``, ``docs/observability.md``) against the
scheduled-trace windows under the dir: it prints which windows overlap
the incident's step span and summarizes the latest overlapping one —
"was anything profiling when it died, and what did the chip do?".

``--attribution`` additionally runs the step-time attribution layer
(``apex_tpu.observability.attribution``, docs/observability.md
"Attribution & roofline") over the chosen window: bucket fractions
(matmul/attention/norm-elementwise/collective/other), the
compute/collective/host-stall split, and — with ``--hlo`` — cost-model
exact bucketing of every fused op.  ``tools/step_profile.py`` is the
full workflow (profile + roofline + watchdog); this flag answers the
same question for a trace that already exists.
"""

from __future__ import annotations

import argparse
import collections
import glob
import gzip
import json
import os


def list_windows(log_dir: str):
    """``[(start, end, path)]`` of the scheduled-trace windows under
    ``log_dir`` (the ``steps_<start>_<end>/`` TraceScheduler layout),
    numerically sorted."""
    import re

    windows = []
    if os.path.isdir(log_dir):
        for name in sorted(os.listdir(log_dir)):
            m = re.match(r"steps_(\d+)_(\d+)$", name)
            if m:
                windows.append(
                    (int(m.group(1)), int(m.group(2)),
                     os.path.join(log_dir, name))
                )
    windows.sort()
    return windows


def flight_step_range(path: str) -> tuple[int, int]:
    """The incident's step span from a flight-recorder dump: min..max
    over the ring frames (replay passes rewind steps, so min can sit
    well below the crash step — that is the span worth profiling)."""
    with open(path) as f:
        data = json.load(f)
    steps = [f["step"] for f in data.get("frames", ())
             if isinstance(f.get("step"), int)]
    final = data.get("final") or {}
    if isinstance(final.get("fetched_step"), int):
        steps.append(final["fetched_step"])
    if not steps:
        raise SystemExit(f"{path}: flight dump has no step frames")
    return min(steps), max(steps)


def cross_reference_flight(log_dir: str, flight_path: str) -> str | None:
    """Print which trace windows overlap the flight dump's incident
    span; returns the latest overlapping window's path (None when no
    window overlaps)."""
    lo, hi = flight_step_range(flight_path)
    windows = list_windows(log_dir)
    print(f"flight incident span: steps {lo}..{hi} ({flight_path})")
    if not windows:
        print(f"no steps_*_* trace windows under {log_dir}")
        return None
    hit = None
    for s, e, path in windows:
        overlap = s <= hi and e >= lo
        mark = "OVERLAPS incident" if overlap else "outside"
        print(f"  window {s}..{e}: {mark}")
        if overlap:
            hit = path
    if hit is None:
        print("no trace window overlaps the incident — nothing was "
              "profiling when it happened (arm APEX_TPU_TRACE_STEPS or "
              "a health-escalation window next run)")
    return hit


def resolve_window(log_dir: str, step: int | None = None) -> str:
    """Resolve a scheduled-trace base dir to one capture window.

    ``apex_tpu.observability.trace.TraceScheduler`` writes each armed
    window to ``<base>/steps_<start>_<end>/``; given the base dir this
    lists the windows and picks the one containing ``--step`` (default:
    the latest).  A dir without window children passes through
    unchanged, so plain ``bench.py --trace`` dirs keep working.
    """
    # numeric order (via list_windows) — lexicographic listdir order
    # lies once step numbers outgrow the %06d padding
    # (steps_1200000 < steps_999000)
    windows = list_windows(log_dir)
    if not windows:
        if step is not None:
            raise SystemExit(
                f"--step given but {log_dir} has no steps_*_* windows"
            )
        return log_dir
    print(
        "trace windows: "
        + ", ".join(f"{s}..{e}" for s, e, _ in windows)
    )
    if step is None:
        return windows[-1][2]
    for s, e, path in windows:
        if s <= step <= e:
            return path
    raise SystemExit(
        f"no trace window contains step {step} under {log_dir}"
    )


def load_trace(log_dir: str) -> dict:
    paths = glob.glob(
        os.path.join(log_dir, "**", "*.trace.json.gz"), recursive=True
    )
    if not paths:
        raise SystemExit(f"no *.trace.json.gz under {log_dir}")
    path = max(paths, key=os.path.getmtime)
    with gzip.open(path, "rt") as f:
        return json.load(f)


def load_hlo_metadata(path: str) -> dict:
    """op name → \"op_name (source_file:line)\" from an HLO text dump.

    Join key: XLA's op names in profiler traces ("fusion.9461",
    "add_add_fusion.78") are the HLO instruction names, so a compiled
    ``jit_fn.lower(...).compile().as_text()`` dump attributes every trace
    row to the model source that produced it — the manual step of the
    r2/r3 MFU loops, automated.
    """
    import re

    meta = {}
    pat = re.compile(
        r"%?([\w.-]+) = .*metadata=\{[^}]*?op_name=\"([^\"]+)\""
        r"(?:[^}]*?source_file=\"([^\"]+)\")?"
        r"(?:[^}]*?source_line=(\d+))?"
    )
    with open(path) as f:
        for line in f:
            m = pat.search(line)
            if not m:
                continue
            name, op, src, ln = m.groups()
            where = ""
            if src:
                base = src.rsplit("/", 1)[-1]
                where = f" ({base}:{ln})" if ln else f" ({base})"
            meta[name] = f"{op}{where}"
    return meta


def summarize(trace: dict, top: int, like: str | None, hlo_meta=None):
    events = trace.get("traceEvents", [])
    # pid -> process name; device tracks are named "/device:TPU:0" etc.
    # One device pid carries several threads (XLA Modules spanning whole
    # steps, XLA Ops with the individual kernels, …) — summing across all
    # of them double-counts nested time, so keep only the op-level threads.
    pnames = {}
    tnames = {}
    for e in events:
        if e.get("ph") == "M" and e.get("name") == "process_name":
            pnames[e["pid"]] = e["args"].get("name", "")
        if e.get("ph") == "M" and e.get("name") == "thread_name":
            tnames[(e["pid"], e.get("tid"))] = e["args"].get("name", "")
    device_pids = {
        pid
        for pid, name in pnames.items()
        if "TPU" in name or "device" in name.lower() or "GPU" in name
    }
    op_tids = {
        key
        for key, name in tnames.items()
        if key[0] in device_pids and "Ops" in name
    }
    per_op = collections.Counter()
    per_op_n = collections.Counter()
    total = 0.0
    tmin, tmax = float("inf"), 0.0
    for e in events:
        if e.get("ph") != "X" or e.get("pid") not in device_pids:
            continue
        if op_tids and (e.get("pid"), e.get("tid")) not in op_tids:
            continue
        # span covers ALL device op events (not just --like matches), so
        # util stays meaningful under filtering
        ts = e.get("ts", 0)
        tmin = min(tmin, ts)
        tmax = max(tmax, ts + e.get("dur", 0))
        name = e.get("name", "?")
        if like and like not in name:
            continue
        # control-flow wrappers (the scan While, the jit entry) span their
        # whole contents — counting them double-counts every child op
        if name.startswith(("while", "jit_", "body", "condition")) or (
            name.isdigit()
        ):
            continue
        dur = e.get("dur", 0) / 1e3  # us -> ms
        per_op[name] += dur
        per_op_n[name] += 1
        total += dur
    span = (tmax - tmin) / 1e3 if tmax > tmin else 0.0
    # busy is summed across every device op-thread; normalize the span by
    # the thread count so util is per-device average, not >100%
    n_tracks = max(1, len(op_tids) if op_tids else len(device_pids))
    print(f"device tracks: {sorted(pnames[p] for p in device_pids)}")
    print(
        f"busy={total:.1f}ms span={span:.1f}ms x{n_tracks} tracks "
        f"util={100 * total / (span * n_tracks) if span else 0:.1f}%\n"
    )
    print(f"{'total_ms':>9} {'n':>6} {'avg_us':>8}  name")
    for name, dur in per_op.most_common(top):
        n = per_op_n[name]
        attr = ""
        if hlo_meta is not None:
            attr = "  <- " + hlo_meta.get(name, "?")
        print(f"{dur:9.2f} {n:6d} {dur / n * 1e3:8.1f}  {name[:110]}{attr[:160]}")


def print_attribution(trace: dict, hlo_path: str | None) -> None:
    """Bucket fractions of one loaded trace (the --attribution block)."""
    import sys

    sys.path.insert(
        0, os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    )
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    from apex_tpu.observability import attribution as A

    hlo_map = None
    cost_weights = None
    if hlo_path and os.path.exists(hlo_path):
        with open(hlo_path) as f:
            text = f.read()
        hlo_map = A.hlo_bucket_map(text)
        cost_weights = A.attribute_cost_model(text).bucket_fractions()
    meas = A.attribute_trace(
        trace, hlo_map=hlo_map, cost_weights=cost_weights
    )
    fr = meas.fractions()
    print(
        "attribution (%s, %d op events): compute=%.3f collective=%.3f "
        "host_stall=%.3f"
        % (meas.source, meas.events, fr["compute"], fr["collective"],
           fr["host_stall"])
    )
    for bucket, share in sorted(
        meas.bucket_fractions().items(), key=lambda kv: -kv[1]
    ):
        if share > 0:
            print(f"  {bucket:<18} {100 * share:5.1f}% of busy "
                  f"({meas.bucket_ms[bucket]:.2f} ms)")
    print(f"  span={meas.span_ms:.1f}ms busy={meas.busy_ms:.1f}ms "
          f"stall={meas.stall_ms:.1f}ms "
          "(tools/step_profile.py adds the roofline)\n")


if __name__ == "__main__":
    ap = argparse.ArgumentParser()
    ap.add_argument("log_dir")
    ap.add_argument("-n", type=int, default=30)
    ap.add_argument("--like", default=None, help="substring filter")
    ap.add_argument(
        "--attribution", action="store_true",
        help="print step-time attribution bucket fractions for the "
        "chosen window (docs/observability.md 'Attribution & "
        "roofline'); --hlo upgrades the bucketing to the cost model's "
        "exact per-op join",
    )
    ap.add_argument(
        "--step", type=int, default=None,
        help="pick the scheduled-trace window (steps_<start>_<end>/ "
        "subdir, APEX_TPU_TRACE_STEPS layout) containing this step; "
        "default: the latest window, or the dir itself if plain",
    )
    ap.add_argument(
        "--hlo", default=None,
        help="optimized-HLO text dump (jit_fn.lower().compile().as_text())"
        " of the traced program; attributes each op row to its op_name +"
        " source line",
    )
    ap.add_argument(
        "--flight", default=None, metavar="FILE",
        help="a flight-recorder dump (flight_<ts>.json): print which "
        "trace windows overlap the incident's step span and summarize "
        "the latest overlapping one (--step overrides the choice)",
    )
    args = ap.parse_args()
    if args.flight:
        hit = cross_reference_flight(args.log_dir, args.flight)
        if args.step is None:
            if hit is None:
                raise SystemExit(1)
            args.log_dir = hit
        else:
            args.log_dir = resolve_window(args.log_dir, args.step)
    else:
        args.log_dir = resolve_window(args.log_dir, args.step)
    meta = None
    if args.hlo:
        # Degrade, don't die: in a staged queue the HLO-dump step can be
        # skipped by a tunnel drop while an older trace still exists —
        # an un-attributed summary beats no summary.
        if os.path.exists(args.hlo):
            meta = load_hlo_metadata(args.hlo)
        else:
            print(f"[trace_summary] --hlo {args.hlo} not found; "
                  "printing un-attributed summary")
    trace = load_trace(args.log_dir)
    if args.attribution:
        print_attribution(trace, args.hlo)
    summarize(trace, args.n, args.like, hlo_meta=meta)
