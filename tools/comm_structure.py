"""Collective structure of the multi-device BASELINE configs (#2, #5).

One real chip cannot time dp>1 / tp>1 (VERDICT r2 "what's weak" #2: the
TP comm fraction that BASELINE config #5 exists to measure has never been
recorded).  What CAN be recorded honestly without a pod is the *compiled
collective schedule*: build the real train step on the 8-device CPU mesh
(identical shardings/program to the TPU run — GSPMD doesn't care about
the backend), compile it, and read every collective out of the optimized
HLO with its operand shape.  From bytes moved + an explicit ICI bandwidth
model this yields an analytic comm fraction; the artifact records the
structure (op kinds, counts, bytes) so the model's inputs are auditable.

Writes one JSON line per config to COMM_STRUCTURE_r{N}.json at the repo
root:  python tools/comm_structure.py --round 3

Bandwidth/peak model (overridable): v5e ICI = 45 GB/s per link per
direction x 4 links/chip (2D torus, public "How to Scale Your Model"
figures), bf16 peak 197 TFLOP/s.  Collectives here ride one mesh axis, so
the per-chip effective bandwidth used is one link pair (ring algorithms
stream over two directed links): 90 GB/s.
"""

from __future__ import annotations

import argparse

import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

os.environ.setdefault("XLA_FLAGS", "--xla_force_host_platform_device_count=8")

import jax

jax.config.update("jax_platforms", "cpu")

import jax.numpy as jnp  # noqa: E402
from jax.sharding import Mesh, PartitionSpec as P  # noqa: E402

# The HLO parsers live with the static-analysis subsystem
# (apex_tpu/analysis/hlo.py) so the library's regression tests, the
# analysis passes, and this artifact generator read compiled HLO with
# ONE implementation; `collect` and `overlap_collect` keep their
# names/contracts here (per-kind {count, bytes} with async pairs
# counted once at -start; schedule-overlap windows per VERDICT r4 #6).
from apex_tpu.analysis.hlo import (  # noqa: E402
    collective_summary as collect,
    overlap_collect,
)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def ring_traffic_bytes(kinds: dict, world: int) -> float:
    """Per-chip ICI traffic (bytes sent) under ring algorithms."""
    t = 0.0
    for kind, rec in kinds.items():
        b = rec["bytes"]
        if kind == "all-reduce":
            t += 2.0 * b * (world - 1) / world
        elif kind in ("all-gather", "reduce-scatter"):
            # operand is the local shard for AG / full buffer for RS; the
            # shapes recorded are op RESULTS for AG (full) and shards for
            # RS in XLA's notation — both stream (world-1)/world of the
            # full buffer; b is whichever the HLO printed, so this is a
            # lower bound for RS and exact for AG results.
            t += b * (world - 1) / world
        elif kind == "collective-permute":
            t += b  # one hop
        elif kind == "all-to-all":
            t += b * (world - 1) / world
    return t


def emit(rec, fh):
    line = json.dumps(rec)
    print(line, flush=True)
    fh.write(line + "\n")


def tp_gpt_structure(world: int, hidden=1024, heads=16, inter=4096,
                     seq=1024, batch=8):
    """BASELINE #5: the GPT block train step at tp=world (+SP).

    The default (h=1024) shape is the bench.py #5 toy and is
    comm-DOMINATED by construction — its analytic fraction measures the
    shape, not the design.  main() also records a GPT-Large-class shape
    (h=4096) where compute/comm overlap is the actual question (VERDICT
    r3 #7); this only compiles (never executes), so the big shape is
    cheap on the CPU mesh."""
    from apex_tpu import parallel_state as ps
    from apex_tpu.transformer.tensor_parallel.mappings import (
        allreduce_sequence_parallel_gradients,
    )
    from apex_tpu.models.gpt import GptBlock, GptConfig
    from apex_tpu.optimizers import fused_adam

    devices = jax.devices()[:world]
    ps.destroy_model_parallel()
    ps.initialize_model_parallel(
        tensor_model_parallel_size=world, devices=devices
    )
    mesh = Mesh(devices, (ps.TENSOR_PARALLEL_AXIS,))
    cfg = GptConfig(
        hidden_size=hidden, num_heads=heads, intermediate_size=inter,
        sequence_parallel=True, dtype=jnp.bfloat16,
    )
    block = GptBlock(cfg)
    tx = fused_adam(learning_rate=1e-4)
    x = jax.random.normal(
        jax.random.PRNGKey(0), (seq, batch, cfg.hidden_size), jnp.bfloat16
    )

    def step(x):
        rank = jax.lax.axis_index(ps.TENSOR_PARALLEL_AXIS)
        xl = jax.lax.dynamic_slice_in_dim(
            x, rank * (seq // world), seq // world, 0
        )
        params = block.init(jax.random.PRNGKey(1), xl)
        opt_state = tx.init(params)

        def loss_fn(p):
            y = block.apply(p, xl)
            return jnp.sum(y.astype(jnp.float32) ** 2)

        loss, grads = jax.value_and_grad(loss_fn)(params)
        grads = allreduce_sequence_parallel_gradients(grads)
        updates, _ = tx.update(grads, opt_state, params)
        # fold every update leaf into the output so the whole backward +
        # optimizer graph (incl. its collectives) survives DCE
        return loss + sum(
            jnp.sum(u).astype(jnp.float32)
            for u in jax.tree_util.tree_leaves(updates)
        )

    fn = jax.jit(
        jax.shard_map(
            step, mesh=mesh, in_specs=(P(),), out_specs=P(),
            check_vma=False,
        )
    )
    hlo = fn.lower(x).compile().as_text()
    ps.destroy_model_parallel()
    kinds = collect(hlo)
    # fwd+bwd GEMM FLOPs of the block per chip: qkv/out/mlp-in/mlp-out
    h, i = cfg.hidden_size, cfg.intermediate_size
    gemm = 2 * seq * batch * (h * 3 * h + h * h + h * i + i * h)
    flops_chip = 3 * gemm / world
    return kinds, flops_chip, hlo


def ddp_syncbn_structure(world: int, quantized: bool = False):
    """BASELINE #2: ResNet-50 + DDP + SyncBatchNorm at dp=world.

    Small images (64x64): conv compute shrinks but the collective
    structure (grad psums + per-BN Welford psums) and grad BYTES are
    image-size-invariant; the recorded flops_chip reflects the small
    images and is marked as such.

    ``quantized=True`` swaps the gradient sync for
    ``parallel.quantized.quantized_all_reduce_gradients`` — the recorded
    collective bytes then demonstrate the int8-wire reduction from the
    actual compiled HLO (all_to_all + all_gather of int8 payloads
    replacing the f32 grad psums; SyncBN Welford psums stay exact).
    """
    from apex_tpu.models.resnet import resnet50
    from apex_tpu.optimizers import fused_sgd
    from apex_tpu.parallel import distributed as dist
    from apex_tpu.parallel import quantized_all_reduce_gradients
    from apex_tpu import parallel_state as ps

    devices = jax.devices()[:world]
    ps.destroy_model_parallel()
    ps.initialize_model_parallel(devices=devices)  # pure dp mesh
    mesh = Mesh(devices, (ps.DATA_PARALLEL_AXIS,))
    batch = 2  # per replica
    model = resnet50(use_syncbn=True)
    tx = fused_sgd(learning_rate=0.1, momentum=0.9)
    x = jax.random.normal(
        jax.random.PRNGKey(0), (world * batch, 64, 64, 3), jnp.bfloat16
    )
    y = jax.random.randint(jax.random.PRNGKey(1), (world * batch,), 0, 1000)

    def step(x, y):
        rank = jax.lax.axis_index(ps.DATA_PARALLEL_AXIS)
        xl = jax.lax.dynamic_slice_in_dim(x, rank * batch, batch, 0)
        yl = jax.lax.dynamic_slice_in_dim(y, rank * batch, batch, 0)
        variables = model.init(jax.random.PRNGKey(2), xl, train=False)
        params, bstats = variables["params"], variables["batch_stats"]
        opt_state = tx.init(params)

        def loss_fn(p):
            logits, upd = model.apply(
                {"params": p, "batch_stats": bstats}, xl, train=True,
                mutable=["batch_stats"],
            )
            logp = jax.nn.log_softmax(logits.astype(jnp.float32))
            return -jnp.mean(
                jnp.take_along_axis(logp, yl[:, None], axis=-1)
            )

        loss, grads = jax.value_and_grad(loss_fn)(params)
        if quantized:
            grads = quantized_all_reduce_gradients(
                grads, axis_name=ps.DATA_PARALLEL_AXIS
            )
        else:
            grads = dist.all_reduce_gradients(
                grads, axis_name=ps.DATA_PARALLEL_AXIS
            )
        updates, _ = tx.update(grads, opt_state, params)
        return loss + sum(
            jnp.sum(u).astype(jnp.float32)
            for u in jax.tree_util.tree_leaves(updates)
        )

    fn = jax.jit(
        jax.shard_map(
            step, mesh=mesh, in_specs=(P(), P()), out_specs=P(),
            check_vma=False,
        )
    )
    hlo = fn.lower(x, y).compile().as_text()
    ps.destroy_model_parallel()
    return collect(hlo), None, hlo


def cp_ring_balance_model(cp: int):
    """Analytic per-rank causal ring work, contiguous vs zigzag
    (VERDICT r4 #4/#5).  Unit: one FULL attention block at zigzag
    granularity — a (S/2cp × S/2cp) q×k tile; a diagonal (self) tile is
    a triangle = 0.5.  Work(r, h) sums the tiles rank ``r`` computes at
    hop ``h`` (kv arrives from rank ``(r-h) mod cp``; causal-future
    tiles are SKIPPED by the ring's ``lax.switch``, not masked).  The
    lockstep wall per hop is the MAX over ranks (the ring's ppermute
    resynchronizes every hop), so imbalance is pure idle time."""

    def tile(qc, kc):
        return 1.0 if qc > kc else (0.5 if qc == kc else 0.0)

    def work(chunks_of, r, h):
        j = (r - h) % cp
        return sum(
            tile(qc, kc)
            for qc in chunks_of(r) for kc in chunks_of(j)
        )

    layouts = {
        "contiguous": lambda r: (2 * r, 2 * r + 1),
        "zigzag": lambda r: (r, 2 * cp - 1 - r),
    }
    out = {}
    for name, chunks_of in layouts.items():
        per_hop_max = [
            max(work(chunks_of, r, h) for r in range(cp))
            for h in range(cp)
        ]
        total_useful = sum(
            work(chunks_of, r, h)
            for r in range(cp) for h in range(cp)
        )
        wall = sum(per_hop_max)
        out[name] = {
            "per_hop_max_tiles": per_hop_max,
            "lockstep_wall_tiles": wall,
            "useful_tiles_total": total_useful,
            "utilization": round(total_useful / (cp * wall), 4),
        }
    out["wall_ratio_contiguous_over_zigzag"] = round(
        out["contiguous"]["lockstep_wall_tiles"]
        / out["zigzag"]["lockstep_wall_tiles"], 4
    )
    return out


def cp_ring_wall_ab(cp: int = 4, seq_local: int = 256, heads: int = 4,
                    head_dim: int = 64, batch: int = 2, reps: int = 3):
    """CPU-mesh wall A/B: causal ring attention, contiguous vs zigzag
    layout, same global problem.  HONEST FRAMING: this container has one
    physical core, so the virtual ranks serialize and wall measures the
    SUM of per-rank work — which the model above proves is equal across
    layouts (2·cp² tiles).  Near-equal walls here validate the work
    accounting (zigzag adds no overhead); the 2−1/cp lockstep wall win
    is the per-hop MAX row of the analytic model and needs parallel
    ranks to show up in wall-clock."""
    import time

    from apex_tpu import parallel_state as ps
    from apex_tpu.transformer.context_parallel import ring_attention

    devices = jax.devices()[:cp]
    ps.destroy_model_parallel()
    ps.initialize_model_parallel(
        context_parallel_size=cp, devices=devices
    )
    mesh = Mesh(devices, (ps.CONTEXT_PARALLEL_AXIS,))
    kq = jax.random.PRNGKey(0)
    shape = (cp, batch, heads, seq_local, head_dim)
    q = jax.random.normal(kq, shape, jnp.float32)
    k = jax.random.normal(jax.random.fold_in(kq, 1), shape, jnp.float32)
    v = jax.random.normal(jax.random.fold_in(kq, 2), shape, jnp.float32)

    walls = {}
    for layout in ("contiguous", "zigzag"):
        def run(q, k, v):
            o = ring_attention(
                q[0], k[0], v[0], causal=True, layout=layout
            )
            return jnp.sum(o.astype(jnp.float32))[None]

        fn = jax.jit(
            jax.shard_map(
                run, mesh=mesh, in_specs=(P("cp"),) * 3,
                out_specs=P("cp"), check_vma=False,
            )
        )
        jax.block_until_ready(fn(q, k, v))  # compile+warm
        ts = []
        for _ in range(reps):
            t0 = time.perf_counter()
            jax.block_until_ready(fn(q, k, v))
            ts.append(time.perf_counter() - t0)
        walls[layout] = round(min(ts) * 1e3, 2)
    ps.destroy_model_parallel()
    return walls


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--round", type=int, default=3)
    ap.add_argument("--world", type=int, default=8)
    ap.add_argument("--ici-gbps", type=float, default=90.0,
                    help="per-chip usable ICI GB/s for one mesh axis "
                    "(v5e: one bidirectional link pair)")
    ap.add_argument("--peak-tflops", type=float, default=197.0)
    args = ap.parse_args()

    out_path = os.path.join(
        REPO, f"COMM_STRUCTURE_r{args.round:02d}.json"
    )
    with open(out_path, "w") as fh:
        for name, fn in (
            ("tp_gpt_block", tp_gpt_structure),
            # GPT-Large-class shape: h=4096 puts the GEMMs where a real
            # tp deployment sits, so the analytic fraction is a design
            # signal rather than a toy-shape artifact (VERDICT r3 #7)
            ("tp_gpt_block_h4096",
             lambda w: tp_gpt_structure(w, hidden=4096, heads=32,
                                        inter=16384)),
            ("ddp_resnet50_syncbn", ddp_syncbn_structure),
            # same model/step with the int8-wire grad sync: the bytes
            # delta vs the row above is the quantization win, measured
            # from compiled HLO rather than claimed
            ("ddp_resnet50_syncbn_int8wire",
             lambda w: ddp_syncbn_structure(w, quantized=True)),
        ):
            kinds, flops_chip, hlo = fn(args.world)
            traffic = ring_traffic_bytes(kinds, args.world)
            comm_s = traffic / (args.ici_gbps * 1e9)
            rec = {
                "config": name,
                "world": args.world,
                "collectives": kinds,
                "per_chip_traffic_bytes": int(traffic),
                "ici_model_gbps": args.ici_gbps,
                "analytic_comm_ms": round(comm_s * 1e3, 4),
            }
            # overlap-aware column (VERDICT r4 #6): which part of the
            # serial-bytes upper bound the compiled schedule actually
            # overlaps with compute
            ov = overlap_collect(hlo)
            all_b = ov["async_bytes"] + ov["sync_bytes"]
            ov_frac = (
                ov["overlapped_bytes"] / all_b if all_b else 0.0
            )
            rec["overlap"] = dict(ov, overlapped_byte_fraction=round(
                ov_frac, 4
            ))
            comm_serial_s = comm_s * (1.0 - ov_frac)
            rec["analytic_comm_ms_nonoverlapped"] = round(
                comm_serial_s * 1e3, 4
            )
            if flops_chip:
                comp_s = flops_chip / (args.peak_tflops * 1e12)
                rec["per_chip_gemm_flops"] = int(flops_chip)
                rec["analytic_compute_ms_at_peak"] = round(comp_s * 1e3, 4)
                # (a) serial-bytes fraction — every collective blocks
                rec["analytic_comm_fraction"] = round(
                    comm_s / (comm_s + comp_s), 4
                )
                # (b) overlap-aware — only collectives with no compute
                # in their async window count against the wall
                rec["analytic_comm_fraction_overlap_aware"] = round(
                    comm_serial_s / (comm_serial_s + comp_s), 4
                )
            emit(rec, fh)

        # zigzag causal-balance model + CPU-mesh wall A/B (VERDICT r4 #4)
        rec = {
            "config": "cp_ring_causal_balance",
            "model_unit": "one (S/2cp)^2 attention tile; diagonal = 0.5",
            "model": {
                str(cp): cp_ring_balance_model(cp) for cp in (4, 8)
            },
            "wall_ab_cpu_mesh": cp_ring_wall_ab(cp=4),
            "wall_ab_note": (
                "1-core container: ranks serialize, wall ~ SUM of work "
                "(equal across layouts by the model) — validates the "
                "accounting; the 2-1/cp win is the lockstep per-hop MAX "
                "row and needs parallel ranks"
            ),
        }
        emit(rec, fh)
    print(f"[comm_structure] wrote {out_path}", file=sys.stderr)


if __name__ == "__main__":
    main()
