"""Merge span records, flight dumps, and profiler windows into one
Perfetto timeline — and gate CI on span accounting.

Usage::

    python tools/timeline.py --spans spans.json [--spans more.json ...]
        [--flight flight_<ts>.json ...] [--trace-dir DIR]
        [--out trace.json] [--json] [--ttft-tol-ms 1.0]

Inputs:

- ``--spans``: :class:`apex_tpu.observability.spans.SpanRecorder`
  dumps (``tools/serve_bench.py --spans``, ``APEX_TPU_SPANS`` runs).
  Each file carries its own **wall-clock anchor** (monotonic→epoch
  offset captured once per process), so records from different
  hosts/processes land on one epoch-aligned timeline.
- ``--flight``: :class:`~apex_tpu.observability.flight.FlightRecorder`
  dumps — frames become ``train/step`` spans, the event log becomes
  instants, per-frame metrics become counter tracks.  Crash
  postmortems and live traces open in the same viewer.
- ``--trace-dir``: a :class:`~apex_tpu.observability.trace.
  TraceScheduler` base dir — each ``steps_<a>_<b>/`` profiler window
  becomes a marker locating the on-chip profile on the timeline.

``--out FILE`` writes Chrome-trace-event JSON (open at
``ui.perfetto.dev`` or ``chrome://tracing``), one track per source,
one process group per input host.

``--json`` prints the **span-accounting summary** the
``verify_tier1.sh`` SERVE gate consumes, and makes the exit status
enforce the invariants: every admitted request's span chain must be
complete (``queued → prefill → [decode] → exactly one terminal``),
every attributed TTFT must equal the sum of its
queue-wait/prefill/contention components within ``--ttft-tol-ms``, and
a record carrying request chains must not have dropped ring entries (a
truncated record cannot prove completeness; a wrapped train-only
record claims nothing about chains and stays clean).  Canary deploy
windows get their own accounting (:func:`account_canary`): the
``canary``-annotated routing hops between each
``fleet/deploy_window_open``/``_close`` pair re-prove the
``canary_frac`` exposure bound from the span dump alone, independent
of the fleet's own counters.  Exit status: 0
clean (always, for a plain ``--out`` merge — violations are printed
but only ``--json`` gates on them), 1 accounting violated under
``--json``, 2 unreadable input.
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

TERMINALS = ("req/done", "req/shed")
WINDOW_OPEN = "fleet/deploy_window_open"
WINDOW_CLOSE = "fleet/deploy_window_close"


def load_spans_dump(path: str) -> dict:
    with open(path) as f:
        data = json.load(f)
    if data.get("kind") != "apex_tpu_spans" or "spans" not in data:
        raise ValueError(f"not a span dump (kind/spans keys): {path}")
    return data


def load_flight_dump(path: str) -> dict:
    with open(path) as f:
        data = json.load(f)
    for key in ("version", "reason", "frames", "events"):
        if key not in data:
            raise ValueError(f"not a flight dump: missing {key!r}: {path}")
    return data


def trace_window_entries(trace_dir: str):
    """Marker instants for each discovered profiler window dir, stamped
    with the newest artifact mtime inside (epoch seconds)."""
    entries = []
    for d in sorted(glob.glob(os.path.join(trace_dir, "steps_*_*"))):
        if not os.path.isdir(d):
            continue
        mtimes = [
            os.path.getmtime(os.path.join(root, fn))
            for root, _, files in os.walk(d) for fn in files
        ]
        if not mtimes:
            continue
        entries.append({
            "name": "trace/window", "track": "trace", "t": max(mtimes),
            "args": {"log_dir": d},
        })
    return entries


def account_requests(spans, dropped, ttft_tol_ms: float) -> dict:
    """The span-accounting invariants over the serve/requests track.

    Chains key on ``(_src, lane)``: request ids restart at 0 per
    process, so a multi-dump merge must scope each dump's rids to its
    source (``main`` tags entries with ``_src`` per input file) — two
    hosts' rid-0 chains are two requests, not one corrupt one.

    ``dropped`` is per-source too (``{src: count}``, or an int for a
    single source): only a source whose OWN ring wrapped *and* whose
    record carries request chains is unaccountable — a wrapped
    train-only dump merged beside a complete serve dump must not fail
    the serve dump's accounting.
    """
    dropped_by_src = (
        dict(dropped) if isinstance(dropped, dict)
        else {0: int(dropped or 0)}
    )
    by_rid: dict = {}
    for e in spans:
        if e.get("track") != "serve/requests":
            continue
        rid = (e.get("_src", 0), e.get("lane"))
        rec = by_rid.setdefault(
            rid, {"spans": [], "instants": [], "terminals": []}
        )
        if "t0" in e:
            rec["spans"].append(e)
        else:
            rec["instants"].append(e)
            if e.get("name") in TERMINALS:
                rec["terminals"].append(e)

    total = len(by_rid)
    admitted = complete = 0
    shed_reasons: dict = {}
    violations = []
    ttft_checked = 0
    ttft_max_err = 0.0
    for (src, lane), rec in sorted(by_rid.items(), key=lambda kv: str(kv[0])):
        rid = f"{lane}" if src == 0 else f"{lane} (dump {src})"
        names = [s["name"] for s in rec["spans"]]
        n_term = len(rec["terminals"])
        was_admitted = "req/prefill" in names
        if was_admitted:
            admitted += 1
        if n_term != 1:
            violations.append(
                f"rid={rid}: {n_term} terminal events (want exactly 1)"
            )
            continue
        term = rec["terminals"][0]
        if term["name"] == "req/shed":
            reason = (term.get("args") or {}).get("reason", "?")
            shed_reasons[reason] = shed_reasons.get(reason, 0) + 1
        if "req/queued" not in names:
            violations.append(f"rid={rid}: no req/queued span")
            continue
        if was_admitted and term["name"] == "req/done":
            # every completed request's prefill span must carry its
            # TTFT attribution, and the components must sum to the
            # measured TTFT
            args = {}
            for s in rec["spans"]:
                if s["name"] == "req/prefill":
                    args = s.get("args") or {}
            comps = [args.get(k) for k in (
                "ttft_ms", "queue_wait_ms", "prefill_ms", "contention_ms",
            )]
            if any(not isinstance(c, (int, float)) for c in comps):
                violations.append(
                    f"rid={rid}: req/prefill span missing TTFT "
                    f"attribution args (have {sorted(args)})"
                )
                continue
            ttft, qw, pf, ct = comps
            # prefix-cache component (0.0 pre-cache records, which
            # predate the key — the 3-component sum is unchanged then)
            cp = args.get("cached_prefill_ms", 0.0)
            if not isinstance(cp, (int, float)):
                violations.append(
                    f"rid={rid}: non-numeric cached_prefill_ms {cp!r}"
                )
                continue
            err = abs(ttft - (qw + cp + pf + ct))
            ttft_checked += 1
            ttft_max_err = max(ttft_max_err, err)
            if err > ttft_tol_ms:
                violations.append(
                    f"rid={rid}: TTFT components sum off by {err:.3f}ms "
                    f"(ttft={ttft:.3f}, qw={qw:.3f}, pf={pf:.3f}, "
                    f"ct={ct:.3f}; tol {ttft_tol_ms}ms)"
                )
                continue
        complete += 1
    # a wrapped ring cannot prove REQUEST-CHAIN completeness (a whole
    # chain may have been evicted) — the violation fires for any
    # source that wrapped AND shows serve activity on ANY serve/*
    # track: surviving engine spans with zero chains means the chains
    # themselves were evicted, which is exactly the truncation the
    # gate exists to catch.  A wrapped train-only record (the
    # recorder's designed steady state over a long run) claims nothing
    # about chains, so it stays clean.
    serve_srcs = {
        e.get("_src", 0) for e in spans
        if str(e.get("track", "")).startswith("serve/")
    }
    for src in sorted(serve_srcs):
        n = dropped_by_src.get(src, 0)
        if n:
            violations.append(
                f"dump {src}: ring dropped {n} entries — its request "
                "record cannot prove chain completeness (raise the "
                "recorder capacity)"
            )
    total_dropped = sum(dropped_by_src.values())
    return {
        "requests": {
            "total": total,
            "admitted": admitted,
            "complete": complete,
        },
        "shed_reasons": shed_reasons,
        "ttft_accounting": {
            "checked": ttft_checked,
            "max_error_ms": ttft_max_err,
            "tol_ms": ttft_tol_ms,
        },
        "dropped": total_dropped,
        "violations": violations,
        "ok": not violations,
    }


def account_canary(spans) -> dict:
    """Re-prove the canary exposure bound from the span dump ALONE.

    The fleet's own counters claim ``canary_routed <= frac * routed +
    1`` during a deploy window; this accounting re-derives it from the
    validated ``canary`` annotations on ``req/routed`` spans, with no
    trust in the fleet's arithmetic.  Windows pair
    ``fleet/deploy_window_open``/``_close`` instants per source, and
    membership uses the recorder's append order (``seq``) rather than
    timestamps: on a virtual clock every event in a tick shares one
    timestamp, but append order preserves the tick's phase order
    (dispatch before the window opens in the same tick is genuinely
    outside the window).

    Invariants, each a violation when broken:

    - windows nest/pair correctly (no nested open, no orphan close;
      an unclosed window extends to the end of the record);
    - INSIDE a window, a routed hop targets the canary replica iff it
      carries the ``canary`` annotation (both directions);
    - per window, annotated hops ``<= frac * routed + 1``;
    - every ``canary``-annotated hop falls inside some window (the
      recorder enforces this at write time; re-proven from the dump).
    """
    by_src: dict = {}
    for e in spans:
        by_src.setdefault(e.get("_src", 0), []).append(e)
    windows = []
    violations = []
    canary_hops = 0
    for src in sorted(by_src):
        entries = sorted(by_src[src], key=lambda e: e.get("seq", 0))
        open_evt = None
        wins = []
        for e in entries:
            if e.get("track") != "health":
                continue
            name = e.get("name")
            if name == WINDOW_OPEN:
                if open_evt is not None:
                    violations.append(
                        f"dump {src}: nested {WINDOW_OPEN} at "
                        f"seq {e.get('seq')}"
                    )
                open_evt = e
            elif name == WINDOW_CLOSE:
                if open_evt is None:
                    violations.append(
                        f"dump {src}: {WINDOW_CLOSE} without an open "
                        f"window at seq {e.get('seq')}"
                    )
                    continue
                wins.append((open_evt, e))
                open_evt = None
        if open_evt is not None:
            wins.append((open_evt, None))
        routed = [
            e for e in entries
            if e.get("track") == "serve/requests"
            and e.get("name") == "req/routed"
        ]

        def _inside(e, o, c):
            lo = o.get("seq", 0)
            hi = c.get("seq") if c is not None else float("inf")
            return lo < e.get("seq", 0) < hi

        for o, c in wins:
            oargs = o.get("args") or {}
            cname = oargs.get("canary")
            frac = oargs.get("frac")
            n_routed = n_canary = 0
            for e in routed:
                if not _inside(e, o, c):
                    continue
                args = e.get("args") or {}
                n_routed += 1
                annotated = bool(args.get("canary"))
                to_canary = args.get("replica") == cname
                if annotated:
                    n_canary += 1
                if annotated != to_canary:
                    violations.append(
                        f"dump {src}: routed span seq {e.get('seq')} "
                        f"to {args.get('replica')!r} inside the "
                        f"{cname!r} window has canary={annotated} "
                        f"(want {to_canary})"
                    )
            if not isinstance(frac, (int, float)) or not cname:
                violations.append(
                    f"dump {src}: {WINDOW_OPEN} at seq "
                    f"{o.get('seq')} missing canary/frac args "
                    f"(have {sorted(oargs)})"
                )
            elif n_canary > frac * n_routed + 1:
                violations.append(
                    f"dump {src}: window {cname!r} routed {n_canary} "
                    f"canary hops of {n_routed} — breaks the "
                    f"frac={frac} exposure bound "
                    f"(max {frac * n_routed + 1:.1f})"
                )
            windows.append({
                "src": src,
                "canary": cname,
                "frac": frac,
                "verdict": ((c.get("args") or {}).get("verdict")
                            if c is not None else None),
                "closed": c is not None,
                "routed": n_routed,
                "canary_routed": n_canary,
                "exposure_frac": (
                    n_canary / n_routed if n_routed else 0.0
                ),
            })
        for e in routed:
            if not (e.get("args") or {}).get("canary"):
                continue
            canary_hops += 1
            if not any(_inside(e, o, c) for o, c in wins):
                violations.append(
                    f"dump {src}: canary-annotated routed span seq "
                    f"{e.get('seq')} falls outside every deploy window"
                )
    return {
        "windows": windows,
        "canary_hops": canary_hops,
        "violations": violations,
        "ok": not violations,
    }


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="merge spans/flight/profiler artifacts into one "
        "Perfetto timeline (docs/observability.md)"
    )
    ap.add_argument("--spans", action="extend", nargs="+", default=[],
                    metavar="FILE",
                    help="SpanRecorder dump(s) — repeatable, and each "
                    "flag takes several files (shell globs work)")
    ap.add_argument("--flight", action="extend", nargs="+", default=[],
                    metavar="FILE",
                    help="FlightRecorder dump(s) — repeatable/globbable")
    ap.add_argument("--trace-dir", default=None,
                    help="TraceScheduler base dir (profiler windows)")
    ap.add_argument("--out", default=None, metavar="FILE",
                    help="write Chrome-trace-event JSON here")
    ap.add_argument("--json", action="store_true",
                    help="print the span-accounting summary (the CI "
                    "artifact); exit 1 on violations")
    ap.add_argument("--ttft-tol-ms", type=float, default=1.0)
    args = ap.parse_args(argv)
    if not args.spans and not args.flight and not args.trace_dir:
        ap.error("nothing to merge: give --spans, --flight or --trace-dir")

    span_dumps = []
    flight_dumps = []
    try:
        for path in args.spans:
            span_dumps.append((path, load_spans_dump(path)))
        for path in args.flight:
            flight_dumps.append((path, load_flight_dump(path)))
    except (OSError, ValueError, json.JSONDecodeError) as e:
        print(f"timeline: cannot read input: {e}", file=sys.stderr)
        return 2

    all_spans = []
    dropped_by_src = {}
    for i, (_, dump) in enumerate(span_dumps):
        # tag each entry with its source file: request ids restart at 0
        # per process (and ring wrap is per recorder), so accounting
        # scopes both chains and dropped counts to the dump
        all_spans.extend(
            dict(e, _src=i) for e in dump.get("spans", [])
        )
        dropped_by_src[i] = int(dump.get("dropped", 0) or 0)

    if args.out:
        from apex_tpu.observability.export import (
            TimelineSink,
            flight_counters,
            flight_entries,
        )

        with TimelineSink(
            args.out,
            other_data={
                "sources": {
                    "spans": [p for p, _ in span_dumps],
                    "flight": [p for p, _ in flight_dumps],
                    "trace_dir": args.trace_dir,
                },
            },
        ) as sink:
            n = 0
            for i, (path, dump) in enumerate(span_dumps):
                host = (dump.get("host") or {}).get("id", 0)
                pid = 1 + i
                n += sink.add_spans(
                    dump.get("spans", []),
                    anchor=dump.get("anchor"),
                    pid=pid,
                    process_name=(
                        f"host{host} spans ({os.path.basename(path)})"
                    ),
                )
            for j, (path, dump) in enumerate(flight_dumps):
                host = (dump.get("host") or {}).get("id", 0)
                pid = 101 + j
                n += sink.add_spans(
                    flight_entries(dump),
                    anchor=None,  # flight timestamps are epoch already
                    pid=pid,
                    process_name=(
                        f"host{host} flight ({os.path.basename(path)})"
                    ),
                )
                for name, t, v in flight_counters(dump):
                    sink.counter(name, t, v, pid=pid)
                    n += 1
            if args.trace_dir:
                n += sink.add_spans(
                    trace_window_entries(args.trace_dir),
                    anchor=None, pid=201, process_name="profiler windows",
                )
        print(f"[timeline] wrote {args.out} ({n} events)", file=sys.stderr)

    summary = account_requests(
        all_spans, dropped_by_src, args.ttft_tol_ms
    )
    canary = account_canary(all_spans)
    summary["canary"] = canary
    summary["violations"].extend(canary["violations"])
    summary["ok"] = summary["ok"] and canary["ok"]
    summary["sources"] = {
        "spans": len(span_dumps),
        "flight": len(flight_dumps),
        "span_entries": len(all_spans),
    }
    if args.json:
        print(json.dumps(summary))
    else:
        req = summary["requests"]
        print(
            f"span accounting: {req['complete']}/{req['total']} request "
            f"chains complete ({req['admitted']} admitted), "
            f"TTFT checked on {summary['ttft_accounting']['checked']} "
            f"(max err "
            f"{summary['ttft_accounting']['max_error_ms']:.4f}ms), "
            f"shed by reason: {summary['shed_reasons'] or '{}'}"
        )
        for w in canary["windows"]:
            print(
                f"  canary window {w['canary']!r}"
                f" (dump {w['src']}): {w['canary_routed']}/"
                f"{w['routed']} hops (frac {w['exposure_frac']:.3f}"
                f" <= {w['frac']}), verdict={w['verdict']}"
            )
        for v in summary["violations"]:
            print(f"  VIOLATION: {v}")
    # the exit status is the CI gate, and the gate is --json mode: a
    # plain merge (--out) succeeds as long as the trace was written,
    # violations or not — they are printed either way
    if args.json and not summary["ok"]:
        return 1
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
