"""Canary-gated deploy drill — the CANARY acceptance gate's engine.

Proves canary analysis (docs/serving.md "Canary deploys") end to end
on a deterministic virtual clock, three scenarios in one seeded run:

1. **fingerprint identity** — a golden-probe fingerprint survives
   ``engine.rebuild(full=True)`` bit-exactly, a SINGLE flipped sign
   bit on the highest-magnitude weight flips the digest, and
   restoring the weights restores the digest;
2. **clean deploys, zero false verdicts** — across ``--clean-seeds``
   independent seeded loads, a canary-gated deploy of behaviorally
   equivalent re-initialized weights PASSES every time: no fail
   verdict, no rollback, zero lost requests, every live replica on
   the new weights, and router exposure within ``canary_frac``;
3. **planted regression detected + rolled back** — the deploy ships
   NaN-poisoned weights on a replica whose decode is additionally
   chaos-throttled (a drill-local replica subclass skips 2 of every 3
   scheduler steps while it runs the regressed weights — the
   "slow decode on the new replica only" in virtual time).  The drift
   verdict FAILS inside the window, the deploy halts, the canary
   rebuilds back to the incumbent weights (rollback fingerprint
   bit-exact vs the pre-deploy digest), ``fleet/deploys_rolled_back``
   bumps, zero requests are lost, and bad-weight exposure — routed
   requests AND served tokens — stays ≤ the canary fraction.

Scenario 2's first run and scenario 3 share ONE span recorder and one
monotonically advancing clock, so the dump holds BOTH deploy windows
(a pass and a fail) and ``tools/timeline.py --json`` re-proves the
exposure bound per-request from the validated ``canary`` routing
annotations alone.

``--json`` writes the evidence artifact (``bench.py --config fleet``
reuses it via ``APEX_TPU_CANARY_ARTIFACT`` for the
``fleet_canary_detect_ticks`` / ``fleet_canary_false_positive``
golden rows); ``--spans`` records the two-window span dump for the
timeline gate.

Usage::

    python tools/canary_drill.py --json /tmp/canary.json \
        --spans /tmp/canary_spans.json
"""

from __future__ import annotations

import argparse
import importlib.util
import json
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

os.environ.setdefault("JAX_PLATFORMS", "cpu")

_spec = importlib.util.spec_from_file_location(
    "fleet_drill",
    os.path.join(os.path.dirname(os.path.abspath(__file__)),
                 "fleet_drill.py"),
)
fleet_drill = importlib.util.module_from_spec(_spec)
_spec.loader.exec_module(fleet_drill)

VirtualClock = fleet_drill.VirtualClock
model_configs = fleet_drill.model_configs
make_params = fleet_drill.make_params


def corrupt_one_bit(params):
    """Flip the SIGN bit of the single highest-magnitude weight — one
    bit, chosen where it provably participates in every forward pass
    (a flipped bit in e.g. an unused embedding row is behaviorally
    invisible and no black-box fingerprint could — or should — see
    it)."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    leaves, treedef = jax.tree_util.tree_flatten(params)
    mags = [float(np.abs(np.asarray(leaf)).max()) for leaf in leaves]
    i = int(np.argmax(mags))
    flat = np.asarray(leaves[i]).copy()
    j = int(np.abs(flat).argmax())
    flat.view(np.uint32).flat[j] ^= np.uint32(0x80000000)
    leaves = list(leaves)
    leaves[i] = jnp.asarray(flat)
    return jax.tree_util.tree_unflatten(treedef, leaves)


def nan_poison(params):
    """The planted regression: every weight tree leaf set to NaN —
    the corrupted-checkpoint deploy the canary gate must catch."""
    import jax
    import jax.numpy as jnp

    return jax.tree_util.tree_map(
        lambda a: a.at[...].set(jnp.nan) if a.ndim else a, params
    )


class ThrottledReplica:
    """Factory wrapper is below — this class subclasses EngineReplica
    lazily (imports live in functions, fleet_drill style)."""


def _throttled_replica_cls():
    from apex_tpu.fleetctl import EngineReplica

    class _Throttled(EngineReplica):
        """A replica whose scheduler runs 1 of every ``slow_factor``
        fleet ticks WHILE it serves the regressed weight tree — the
        deterministic stand-in for chaos-slowed decode on the new
        replica only (behavioral weight changes alone cannot move
        virtual-clock latency; the throttle is how "the new build is
        slow" exists in drill time)."""

        def __init__(self, *a, regressed=None, slow_factor=3, **kw):
            super().__init__(*a, **kw)
            self._regressed = regressed
            self._slow = int(slow_factor)
            self._throttled = False
            self._phase = 0

        def redeploy(self, params, draft_params=None):
            super().redeploy(params, draft_params)
            self._throttled = params is self._regressed
            self._phase = 0

        def step(self):
            if self._throttled:
                self._phase = (self._phase + 1) % self._slow
                if self._phase != 0:
                    return
            super().step()

    return _Throttled


def build_canary_fleet(args, clock, params, *, recorder=None,
                       regressed=None):
    """A fixed-size fleet (no autoscaler — the canary hold's routing
    arithmetic is the subject under test, keep the replica set
    stable) whose replicas throttle themselves iff handed the
    ``regressed`` tree."""
    from apex_tpu.fleetctl import Fleet
    from apex_tpu.observability import MetricRegistry
    from apex_tpu.serve import InferenceEngine

    cfg, serve_cfg = model_configs(args)
    cls = _throttled_replica_cls()

    def factory(name: str):
        registry = MetricRegistry(fetch_every=1)
        engine = InferenceEngine(
            cfg, params, serve_cfg, registry=registry,
        ).build()
        return cls(
            name, engine, clock=clock, spans=recorder,
            regressed=regressed, slow_factor=args.slow_factor,
            max_queue_depth=args.max_queue_depth,
            max_retries=args.max_retries,
        )

    return Fleet(factory, replicas=args.replicas, clock=clock,
                 spans=recorder)


def canary_config(args, probes):
    from apex_tpu.observability.canary import CanaryConfig

    return CanaryConfig(
        frac=args.canary_frac, probes=probes,
        min_samples=args.min_samples, alpha=args.alpha,
        min_events=args.min_events,
        min_event_total=args.min_event_total,
        soak_ticks=args.soak_ticks,
        max_window_ticks=args.max_window_ticks,
    )


def run_canary_load(fleet, clock, args, *, label, deploy_params,
                    canary_cfg, seed):
    """One seeded Poisson load with a canary-gated deploy at
    ``--deploy-tick``; runs until every request is terminal AND the
    deploy machinery is idle."""
    import numpy as np

    from apex_tpu.observability.meter import percentile
    from apex_tpu.serve import Request

    rs = np.random.RandomState(seed)
    t0 = clock()
    arrivals = [t0 + a for a in fleet_drill.gen_arrivals(args, rs)]
    prompt_lens = rs.choice(args.prompt_mix, size=args.requests)
    out_lens = rs.choice(args.output_mix, size=args.requests)

    start_tick = fleet.tick
    submitted = 0
    reqs = []
    deployed = False
    idle = 0
    for _ in range(args.max_ticks):
        now = clock()
        while submitted < args.requests and arrivals[submitted] <= now:
            reqs.append(fleet.submit(Request(
                prompt=list(rs.randint(0, args.vocab,
                                       size=prompt_lens[submitted])),
                max_new_tokens=int(out_lens[submitted]),
            )))
            submitted += 1
        if (
            not deployed
            and fleet.tick - start_tick >= args.deploy_tick
        ):
            fleet.start_rolling_update(deploy_params, canary=canary_cfg)
            deployed = True
        fleet.step()
        clock.advance()
        if submitted >= args.requests and deployed and not fleet.pending:
            idle += 1
            if idle >= args.tail_ticks:
                break
        else:
            idle = 0
    else:
        raise RuntimeError(
            f"{label}: fleet did not settle within {args.max_ticks} "
            f"ticks (door={fleet.door_depth}, deploy={fleet.deploy})"
        )

    done = [r for r in reqs if r.status == "done"]
    shed = [r for r in reqs if r.status == "shed"]
    ttfts = sorted(r.ttft_ms for r in done if r.ttft_ms is not None)
    shed_reasons = {}
    for r in shed:
        key = r.shed_reason or "?"
        shed_reasons[key] = shed_reasons.get(key, 0) + 1
    freg = {
        k: v for k, v in fleet.registry.fetch().items()
        if k.startswith("fleet/")
    }
    return {
        "label": label,
        "seed": seed,
        "offered": len(reqs),
        "completed": len(done),
        "shed": len(shed),
        "shed_reasons": shed_reasons,
        "unterminated": [
            r.rid for r in reqs if r.status not in ("done", "shed")
        ],
        "ttft_p99_ms": percentile(ttfts, 0.99) if ttfts else None,
        "ticks": fleet.tick - start_tick,
        "deploys": fleet.deploy_history,
        "rolled_back": freg.get("fleet/deploys_rolled_back", 0.0),
        "verdict_pass": freg.get("fleet/canary/verdict_pass", 0.0),
        "verdict_fail": freg.get("fleet/canary/verdict_fail", 0.0),
        "probes": freg.get("fleet/canary/probes", 0.0),
        "fleet_registry": freg,
        "leaks": fleet.leak_check(),
        "health_rules": [e.rule for e in fleet.health_events],
    }


def fingerprint_scenario(args) -> dict:
    """Scenario 1: rebuild bit-exactness, single-bit sensitivity,
    restore symmetry — on one quiet engine."""
    from apex_tpu.observability import MetricRegistry
    from apex_tpu.observability.canary import (
        GoldenProbeSet,
        fingerprint_distance,
        model_fingerprint,
    )
    from apex_tpu.serve import InferenceEngine

    cfg, serve_cfg = model_configs(args)
    params = make_params(args, key=1)
    engine = InferenceEngine(
        cfg, params, serve_cfg, registry=MetricRegistry(fetch_every=1),
    ).build()
    probes = GoldenProbeSet.generate(
        args.vocab, n_probes=args.n_probes,
        prompt_len=args.probe_prompt_len,
        max_new_tokens=args.probe_new_tokens, seed=args.probe_seed,
    )
    fp_a = model_fingerprint(engine, probes)
    engine.rebuild(full=True)
    fp_b = model_fingerprint(engine, probes)
    engine.params = corrupt_one_bit(params)
    engine.rebuild(full=True)
    fp_bit = model_fingerprint(engine, probes)
    engine.params = params
    engine.rebuild(full=True)
    fp_back = model_fingerprint(engine, probes)
    pool_clean = engine.pool.in_use == 0
    return {
        "digest": fp_a["digest"],
        "rebuild_bit_exact": fp_a["digest"] == fp_b["digest"],
        "single_bit_flips_digest": fp_a["digest"] != fp_bit["digest"],
        "single_bit_distance": fingerprint_distance(fp_a, fp_bit),
        "restore_matches": fp_back["digest"] == fp_a["digest"],
        "probe_pool_clean": pool_clean,
        "probe_tokens": fp_a["tokens"],
    }


def run_drill(args) -> dict:
    from apex_tpu.observability.canary import GoldenProbeSet
    from apex_tpu.observability.spans import (
        SpanRecorder,
        wall_clock_anchor,
    )

    probes = GoldenProbeSet.generate(
        args.vocab, n_probes=args.n_probes,
        prompt_len=args.probe_prompt_len,
        max_new_tokens=args.probe_new_tokens, seed=args.probe_seed,
    )
    fingerprints = fingerprint_scenario(args)

    # one clock + one recorder across the recorded runs: time advances
    # monotonically through BOTH deploy windows, so the dump's windows
    # never overlap and the timeline re-proof is unambiguous
    clock = VirtualClock()
    recorder = SpanRecorder(capacity=args.span_capacity, clock=clock)
    params = make_params(args, key=1)

    # -- scenario 2: clean deploys across seeds ----------------------------
    clean_runs = []
    for i in range(args.clean_seeds):
        rec = recorder if i == 0 else None
        run_clock = clock if i == 0 else VirtualClock()
        fleet = build_canary_fleet(args, run_clock, params, recorder=rec)
        new_params = make_params(args, key=10 + i)
        clean_runs.append(run_canary_load(
            fleet, run_clock, args, label=f"clean[{i}]",
            deploy_params=new_params,
            canary_cfg=canary_config(args, probes),
            seed=args.seed + i,
        ))

    # -- scenario 3: the planted regression --------------------------------
    regressed = nan_poison(make_params(args, key=2))
    fleet = build_canary_fleet(args, clock, params, recorder=recorder,
                               regressed=regressed)
    incumbent_fp = fleet.replicas[0].probe(probes)
    regression = run_canary_load(
        fleet, clock, args, label="regression",
        deploy_params=regressed,
        canary_cfg=canary_config(args, probes),
        seed=args.seed + 100,
    )
    regression["incumbent_digest"] = incumbent_fp["digest"]
    # post-rollback: every live replica must hold weights that
    # fingerprint identical to the incumbent digest
    post_digests = {}
    for rep in fleet.replicas:
        if rep.state == "live":
            rep.engine.reset_cache()
            post_digests[rep.name] = rep.probe(probes)["digest"]
    regression["post_rollback_digests"] = post_digests

    if args.spans:
        recorder.dump(reason="canary_drill", path=args.spans)

    false_positives = sum(int(r["verdict_fail"]) for r in clean_runs)
    reg_deploy = regression["deploys"][-1] if regression["deploys"] \
        else {}
    reg_canary = reg_deploy.get("canary", {})
    detect_ticks = reg_canary.get("detect_ticks")

    return {
        "anchor": wall_clock_anchor(),
        "config": {
            k: getattr(args, k) for k in (
                "requests", "rate", "prompt_mix", "output_mix", "seed",
                "replicas", "batch", "page_size", "pages",
                "pages_per_seq", "max_queue_depth", "max_retries",
                "deploy_tick", "tail_ticks", "clean_seeds",
                "canary_frac", "min_samples", "alpha", "min_events",
                "min_event_total", "soak_ticks", "max_window_ticks",
                "slow_factor", "n_probes", "probe_prompt_len",
                "probe_new_tokens", "probe_seed",
            )
        },
        "fingerprints": fingerprints,
        "clean_runs": clean_runs,
        "regression": regression,
        "false_positives": false_positives,
        "detect_ticks": detect_ticks,
        "open_spans": len(recorder.open_requests),
        "span_drops": recorder.dropped,
        "spans_file": args.spans,
    }


def check(args, art) -> list:
    """The drill's own verdict: every acceptance claim as an explicit
    failure string (the CANARY gate re-asserts the same from the
    artifact + span dump)."""
    failures = []
    fp = art["fingerprints"]
    if not fp["rebuild_bit_exact"]:
        failures.append("fingerprint changed across a same-weights "
                        "rebuild — bit-exactness broken")
    if not fp["single_bit_flips_digest"]:
        failures.append("a single-bit weight corruption did NOT flip "
                        "the fingerprint digest")
    if not fp["restore_matches"]:
        failures.append("restoring the weights did not restore the "
                        "fingerprint")
    if not fp["probe_pool_clean"]:
        failures.append("probing leaked pages")

    if art["false_positives"]:
        failures.append(
            f"{art['false_positives']} FALSE canary fail verdicts "
            f"across {len(art['clean_runs'])} clean deploys"
        )
    for run in art["clean_runs"]:
        label = run["label"]
        deploys = run["deploys"]
        if not deploys or deploys[-1].get("rolled_back"):
            failures.append(f"{label}: clean deploy did not complete")
            continue
        d = deploys[-1]
        if d["canary"].get("verdict") != "pass":
            failures.append(
                f"{label}: clean verdict "
                f"{d['canary'].get('verdict')!r} != 'pass'"
            )
        if d["lost_requests"] != 0:
            failures.append(
                f"{label}: lost {d['lost_requests']} requests"
            )
        if run["unterminated"]:
            failures.append(
                f"{label}: unterminated {run['unterminated']}"
            )
        exposure = d["canary"].get("exposure_frac", 1.0)
        routed = d["canary"].get("routed", 0)
        if routed and d["canary"]["canary_routed"] > \
                args.canary_frac * routed + 1:
            failures.append(
                f"{label}: routed exposure {exposure:.3f} broke the "
                f"{args.canary_frac} canary fraction bound"
            )
        if any(v != 0 for v in run["leaks"].values()):
            failures.append(f"{label}: leaked pages {run['leaks']}")

    reg = art["regression"]
    deploys = reg["deploys"]
    if not deploys or not deploys[-1].get("rolled_back"):
        failures.append("planted regression was NOT rolled back")
        return failures
    d = deploys[-1]
    c = d["canary"]
    if c.get("verdict") != "fail":
        failures.append(
            f"regression verdict {c.get('verdict')!r} != 'fail'"
        )
    if reg["rolled_back"] != 1:
        failures.append(
            f"fleet/deploys_rolled_back={reg['rolled_back']} != 1"
        )
    if art["detect_ticks"] is None:
        failures.append("no detect_ticks recorded for the regression")
    if d["lost_requests"] != 0:
        failures.append(
            f"regression rollback lost {d['lost_requests']} requests"
        )
    if reg["unterminated"]:
        failures.append(
            f"regression: unterminated {reg['unterminated']}"
        )
    if c.get("fingerprint", {}).get("new_finite", True):
        failures.append(
            "NaN-poisoned weights fingerprinted as finite"
        )
    if c.get("rollback_digest") != reg["incumbent_digest"]:
        failures.append(
            "rollback fingerprint does not match the incumbent "
            "digest — the rollback is not bit-exact"
        )
    for name, digest in reg["post_rollback_digests"].items():
        if digest != reg["incumbent_digest"]:
            failures.append(
                f"replica {name} fingerprints {digest[:12]} != "
                f"incumbent after the rollback"
            )
    routed = c.get("routed", 0)
    if routed and c.get("canary_routed", 0) > \
            args.canary_frac * routed + 1:
        failures.append(
            f"regression routed exposure {c.get('exposure_frac')}"
            f" broke the {args.canary_frac} bound"
        )
    tok_total = c.get("tokens_total", 0)
    if tok_total and c.get("tokens_canary", 0) > \
            args.canary_frac * tok_total + args.batch * 4:
        failures.append(
            f"bad-weight TOKEN exposure {c.get('tokens_canary')}/"
            f"{tok_total} broke the {args.canary_frac} bound"
        )
    if any(v != 0 for v in reg["leaks"].values()):
        failures.append(f"regression: leaked pages {reg['leaks']}")
    if art["open_spans"]:
        failures.append(
            f"{art['open_spans']} request span chains left open"
        )
    return failures


def build_parser() -> argparse.ArgumentParser:
    ap = argparse.ArgumentParser(
        description='canary-gated deploy drill (docs/serving.md '
        '"Canary deploys")',
    )
    ap.add_argument("--requests", type=int, default=220)
    ap.add_argument("--rate", type=float, default=40.0,
                    help="Poisson arrival rate, requests/s (virtual)")
    ap.add_argument("--spike-factor", type=float, default=1.0,
                    dest="spike_factor")
    ap.add_argument("--spike-start", type=float, default=0.0,
                    dest="spike_start")
    ap.add_argument("--spike-end", type=float, default=0.0,
                    dest="spike_end")
    ap.add_argument("--prompt-mix", type=int, nargs="+",
                    default=[8, 16, 24], dest="prompt_mix")
    ap.add_argument("--output-mix", type=int, nargs="+",
                    default=[8, 16], dest="output_mix")
    ap.add_argument("--vocab", type=int, default=64)
    ap.add_argument("--hidden", type=int, default=32)
    ap.add_argument("--layers", type=int, default=2)
    ap.add_argument("--heads", type=int, default=2)
    ap.add_argument("--batch", type=int, default=4)
    ap.add_argument("--page-size", type=int, default=8)
    ap.add_argument("--pages", type=int, default=64)
    ap.add_argument("--pages-per-seq", type=int, default=8)
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--verify", action="store_true")
    ap.add_argument("--replicas", type=int, default=3)
    ap.add_argument("--max-queue-depth", type=int, default=16)
    ap.add_argument("--max-retries", type=int, default=2)
    ap.add_argument("--deploy-tick", type=int, default=120)
    ap.add_argument("--tail-ticks", type=int, default=20)
    ap.add_argument("--max-ticks", type=int, default=30000)
    ap.add_argument("--clean-seeds", type=int, default=3,
                    dest="clean_seeds",
                    help="independent clean-deploy loads (the false-"
                    "positive pin)")
    ap.add_argument("--canary-frac", type=float, default=0.25,
                    dest="canary_frac")
    ap.add_argument("--min-samples", type=int, default=12,
                    dest="min_samples")
    ap.add_argument("--alpha", type=float, default=1e-3)
    ap.add_argument("--min-events", type=int, default=4,
                    dest="min_events")
    ap.add_argument("--min-event-total", type=int, default=8,
                    dest="min_event_total")
    ap.add_argument("--soak-ticks", type=int, default=250,
                    dest="soak_ticks")
    ap.add_argument("--max-window-ticks", type=int, default=900,
                    dest="max_window_ticks")
    ap.add_argument("--slow-factor", type=int, default=3,
                    dest="slow_factor",
                    help="regressed replica runs 1 of N fleet ticks")
    ap.add_argument("--n-probes", type=int, default=3,
                    dest="n_probes")
    ap.add_argument("--probe-prompt-len", type=int, default=8,
                    dest="probe_prompt_len")
    ap.add_argument("--probe-new-tokens", type=int, default=6,
                    dest="probe_new_tokens")
    ap.add_argument("--probe-seed", type=int, default=0xCA9A,
                    dest="probe_seed")
    ap.add_argument("--json", default=None, metavar="OUT")
    ap.add_argument("--spans", default=None, metavar="OUT")
    ap.add_argument("--span-capacity", type=int, default=131072)
    return ap


def main(argv=None) -> int:
    args = build_parser().parse_args(argv)
    art = run_drill(args)
    if args.json:
        from apex_tpu.observability.flight import json_safe

        with open(args.json, "w") as f:
            json.dump(json_safe(art), f, indent=1, allow_nan=False)
            f.write("\n")

    fp = art["fingerprints"]
    print(
        "canary drill: fingerprint %s rebuild_exact=%s "
        "single_bit_flips=%s restore=%s"
        % (fp["digest"][:12], fp["rebuild_bit_exact"],
           fp["single_bit_flips_digest"], fp["restore_matches"])
    )
    for run in art["clean_runs"]:
        d = run["deploys"][-1] if run["deploys"] else {}
        c = d.get("canary", {})
        print(
            "  %s: %d/%d completed, verdict=%s exposure=%.3f "
            "lost=%s"
            % (run["label"], run["completed"], run["offered"],
               c.get("verdict"), c.get("exposure_frac", float("nan")),
               d.get("lost_requests"))
        )
    reg = art["regression"]
    d = reg["deploys"][-1] if reg["deploys"] else {}
    c = d.get("canary", {})
    print(
        "  regression: %d/%d completed (%s), verdict=%s "
        "detect_ticks=%s rolled_back=%d"
        % (reg["completed"], reg["offered"],
           ", ".join(f"{k}={v}" for k, v in
                     sorted(reg["shed_reasons"].items())) or "no shed",
           c.get("verdict"), art["detect_ticks"],
           int(reg["rolled_back"]))
    )
    print(
        "  exposure: routed %s/%s (frac %.3f <= %.2f), tokens %s/%s"
        % (c.get("canary_routed"), c.get("routed"),
           c.get("exposure_frac", float("nan")), args.canary_frac,
           c.get("tokens_canary"), c.get("tokens_total"))
    )

    failures = check(args, art)
    for msg in failures:
        print(f"CANARY DRILL FAIL: {msg}", file=sys.stderr)
    if not failures:
        print("canary drill: PASS")
    return 1 if failures else 0


if __name__ == "__main__":
    sys.exit(main())
