"""int8-wire gradient-sync sensitivity sweep (VERDICT r4 #7).

``parallel.quantized.quantized_all_reduce_gradients`` trades exactness
for ~4x wire-byte reduction; its convergence test pins ONE operating
point.  This sweep maps the envelope: block size x model scale ->

- one-sync relative gradient error vs the exact psum (mean + max over
  elements, worst leaf), and
- the N-step training-loss delta vs exact sync from the same init
  (the number that actually matters),

on the dp=8 CPU mesh.  Results + the when-NOT-to-use-it guidance live in
docs/parallel.md next to the module's contract.

Run:  python tools/int8wire_sensitivity.py
"""

import os
import sys
import json

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

os.environ["XLA_FLAGS"] = (
    os.environ.get("XLA_FLAGS", "")
    + " --xla_force_host_platform_device_count=8"
)

import jax

jax.config.update("jax_platforms", "cpu")

import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec as P

from apex_tpu import parallel_state as ps
from apex_tpu.parallel import (
    all_reduce_gradients,
    quantized_all_reduce_gradients,
)
from apex_tpu.optimizers import fused_sgd

DP = 8
N_STEPS = 30

# model scales: (hidden, depth, lr) of a tanh MLP regression net.
# "small" has mixed tiny/large leaves in one bucket; "large" spans many
# blocks per leaf so per-block scaling is exercised both within and
# across leaves.  lr is tuned per scale so the EXACT baseline converges
# (momentum-SGD at lr=0.05 diverges at hidden=512 regardless of sync —
# a divergent baseline measures nothing about quantization).
SCALES = {
    "small (9.5k params)": (48, 2, 0.05),
    "medium (54k params)": (128, 3, 0.05),
    "large (528k params)": (512, 2, 0.005),
}
BLOCKS = (256, 1024, 4096)


def _mlp_init(key, d_in, hidden, depth):
    params = []
    dims = [d_in] + [hidden] * depth + [1]
    for i, (a, b) in enumerate(zip(dims[:-1], dims[1:])):
        k = jax.random.fold_in(key, i)
        params.append({
            "w": jax.random.normal(k, (a, b), jnp.float32) / np.sqrt(a),
            "b": jnp.zeros((b,), jnp.float32),
        })
    return params


def _mlp_apply(params, x):
    for i, layer in enumerate(params):
        x = x @ layer["w"] + layer["b"]
        if i < len(params) - 1:
            x = jnp.tanh(x)
    return x


def measure(hidden, depth, lr, block):
    """(worst-leaf mean rel err, worst-leaf max rel err, loss_delta)."""
    d_in = 16
    key = jax.random.PRNGKey(7)
    xs = jax.random.normal(jax.random.fold_in(key, 1), (DP, 64, d_in))
    w_true = jax.random.normal(jax.random.fold_in(key, 2), (d_in, 1))
    ys = jnp.einsum("rbd,do->rbo", xs, w_true) + 0.01 * jax.random.normal(
        jax.random.fold_in(key, 3), (DP, 64, 1)
    )
    tx = fused_sgd(learning_rate=lr, momentum=0.9)

    def one_sync_err(x, y):
        x, y = x[0], y[0]
        params = _mlp_init(key, d_in, hidden, depth)
        grads = jax.grad(
            lambda p: jnp.mean((_mlp_apply(p, x) - y) ** 2)
        )(params)
        exact = all_reduce_gradients(grads)
        quant = quantized_all_reduce_gradients(
            grads, min_size=1, block=block
        )
        errs = []
        for e, q in zip(
            jax.tree_util.tree_leaves(exact),
            jax.tree_util.tree_leaves(quant),
        ):
            denom = jnp.mean(jnp.abs(e)) + 1e-12
            errs.append(
                (jnp.mean(jnp.abs(q - e)) / denom,
                 jnp.max(jnp.abs(q - e)) / denom)
            )
        mean_rel = jnp.max(jnp.stack([a for a, _ in errs]))
        max_rel = jnp.max(jnp.stack([b for _, b in errs]))
        return mean_rel[None], max_rel[None]

    def train_hist(x, y, sync):
        x, y = x[0], y[0]
        params = _mlp_init(key, d_in, hidden, depth)
        opt = tx.init(params)

        def step(carry, _):
            params, opt = carry
            loss, grads = jax.value_and_grad(
                lambda p: jnp.mean((_mlp_apply(p, x) - y) ** 2)
            )(params)
            grads = sync(grads)
            upd, opt = tx.update(grads, opt, params)
            params = jax.tree_util.tree_map(jnp.add, params, upd)
            return (params, opt), loss

        _, hist = jax.lax.scan(step, (params, opt), None, length=N_STEPS)
        return jax.lax.pmean(hist, ps.DATA_PARALLEL_AXIS)[None]

    mesh = ps.initialize_model_parallel(devices=jax.devices()[:DP])

    def run(f, *args):
        return jax.jit(
            jax.shard_map(
                f, mesh=mesh, in_specs=(P("dp"),) * len(args),
                out_specs=P("dp"), check_vma=False,
            )
        )(*args)

    mean_rel, max_rel = run(one_sync_err, xs, ys)
    h_exact = np.asarray(
        run(lambda x, y: train_hist(x, y, all_reduce_gradients), xs, ys)
    )[0]
    h_quant = np.asarray(
        run(
            lambda x, y: train_hist(
                x, y,
                lambda g: quantized_all_reduce_gradients(
                    g, min_size=1, block=block
                ),
            ),
            xs, ys,
        )
    )[0]
    ps.destroy_model_parallel()
    loss_delta = float(h_quant[-1] - h_exact[-1]) / float(h_exact[0])
    return (
        float(np.asarray(mean_rel)[0]),
        float(np.asarray(max_rel)[0]),
        float(h_exact[-1]),
        float(h_quant[-1]),
        loss_delta,
    )


def main():
    print(
        f"{'model':<22}{'block':>7}{'rel_err_mean':>14}{'rel_err_max':>13}"
        f"{'exact_loss':>12}{'quant_loss':>12}{'loss_delta':>12}",
        flush=True,
    )
    rows = []
    for name, (hidden, depth, lr) in SCALES.items():
        for block in BLOCKS:
            m, mx, le, lq, dl = measure(hidden, depth, lr, block)
            rows.append({
                "model": name, "block": block,
                "rel_err_mean_worst_leaf": round(m, 5),
                "rel_err_max_worst_leaf": round(mx, 5),
                "exact_final_loss": round(le, 6),
                "quant_final_loss": round(lq, 6),
                "loss_delta_frac_of_init": round(dl, 6),
            })
            print(
                f"{name:<22}{block:>7}{m:>14.5f}{mx:>13.5f}"
                f"{le:>12.6f}{lq:>12.6f}{dl:>12.6f}",
                flush=True,
            )
    out = os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        "INT8WIRE_SENSITIVITY.json",
    )
    with open(out, "w") as fh:
        for r in rows:
            fh.write(json.dumps(r) + "\n")
    print(f"[int8wire_sensitivity] wrote {out}", file=sys.stderr)


if __name__ == "__main__":
    main()
