#!/bin/sh
# Round-5 tunnel watcher: probe the axon TPU tunnel on a loop; the moment a
# probe succeeds, fire the staged on-chip queue (tools/onchip_queue.sh, or
# the QUEUE script passed as $3) and exit. Bounded by MAX_SECONDS so it
# never outlives the round.
#
#   sh tools/tunnel_watch.sh [ROUND] [MAX_SECONDS] [QUEUE_SCRIPT]
#
# Writes a heartbeat to tunnel_watch_r{N}.log so progress is inspectable.
set -u
ROUND="${1:-5}"
MAX="${2:-39600}"   # 11h default
QUEUE="${3:-}"
REPO="$(cd "$(dirname "$0")/.." && pwd)"
cd "$REPO" || exit 1
# Fail a bad queue path NOW, not after an hours-long tunnel wait: the
# path is resolved relative to the repo root just cd'd into (matching
# how the fire step invokes it).
if [ -n "$QUEUE" ] && [ ! -f "$QUEUE" ]; then
    echo "tunnel_watch: queue script not found: $QUEUE" >&2
    exit 2
fi
LOG="tunnel_watch_r$(printf %02d "$ROUND").log"
START=$(date +%s)
echo "watch start $(date -u)" >>"$LOG"
while :; do
    NOW=$(date +%s)
    ELAPSED=$((NOW - START))
    if [ "$ELAPSED" -ge "$MAX" ]; then
        echo "watch giving up after ${ELAPSED}s $(date -u)" >>"$LOG"
        exit 3
    fi
    if sh tools/tpu_probe.sh 90; then
        echo "tunnel OPEN at $(date -u) (elapsed ${ELAPSED}s) - firing queue" >>"$LOG"
        if [ -n "$QUEUE" ]; then
            sh "$QUEUE" >>"$LOG" 2>&1
        else
            sh tools/onchip_queue.sh "$ROUND" >>"$LOG" 2>&1
        fi
        echo "queue done rc=$? $(date -u)" >>"$LOG"
        exit 0
    fi
    echo "probe down $(date -u) (elapsed ${ELAPSED}s)" >>"$LOG"
    sleep 420
done
