"""Kernel lint CLI — run the Pallas kernel static analyzer
(``apex_tpu.analysis.kernels``, docs/analysis.md "Kernel passes") over
the three shipped kernels at their default configs, and emit findings
as text + a JSON artifact.

Nothing traces or compiles: the kernel modules export their call plans
(``kernel_specs()``) and the passes judge VMEM footprint, tile
alignment, grid coverage/races, causal dead-tile waste, and the
compile-free roofline against one peak table
(``observability.meter``).  This is the ``verify_tier1.sh`` LINT
gate's kernel half: any ERROR finding exits 1, and ``--max-dead-tile``
turns the causal flash default's wasted-FLOP fraction into a pinned
bound (the bound that keeps a naive-causal tile choice from silently
landing).

Usage::

    python tools/kernel_lint.py                      # defaults, text
    python tools/kernel_lint.py --json out.json      # machine artifact
    python tools/kernel_lint.py --max-dead-tile 0.15 # CI bound
    python tools/kernel_lint.py --device-kind "TPU v5p"

Exit code: 0 clean, 1 ERROR findings or dead-tile bound exceeded,
2 usage error.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

os.environ.setdefault("JAX_PLATFORMS", "cpu")


def main():
    ap = argparse.ArgumentParser(
        description="static lint + cost model over the shipped Pallas "
        "kernels (rule catalog: docs/analysis.md)"
    )
    ap.add_argument("--device-kind", default="TPU v5 lite",
                    help="device-kind string for the peak/VMEM tables "
                    "(default v5e)")
    ap.add_argument("--vmem-budget", type=int, default=None,
                    metavar="BYTES",
                    help="override the per-core VMEM budget")
    ap.add_argument("--max-dead-tile", type=float, default=None,
                    metavar="FRACTION",
                    help="fail (exit 1) if any causal kernel's wasted-"
                    "FLOP fraction exceeds this bound")
    ap.add_argument("--json", metavar="FILE", default=None,
                    help="write the report as one JSON object")
    ap.add_argument("--fail-on", choices=["error", "warning"],
                    default="error")
    args = ap.parse_args()

    from apex_tpu.analysis import kernels as ka

    report = ka.analyze_default_kernels(
        device_kind=args.device_kind, vmem_budget=args.vmem_budget,
    )
    ka.publish_kernel_report(report)

    print(f"kernel lint ({args.device_kind}):")
    print(f"  {'config':<17} {'kernel':<17} {'grid':<14} {'VMEM MiB':>8} "
          f"{'AI':>7} {'ceil TF/s':>9} {'pred TF/s':>9} {'bound':>7} "
          f"{'waste':>6}")
    worst_waste = 0.0
    for e in report.sections["kernels"]:
        r = e["roofline"]
        waste = (e.get("dead_tiles") or {}).get("waste_fraction")
        worst_waste = max(worst_waste, waste or 0.0)
        print(f"  {e['config']:<17} {e['name']:<17} "
              f"{'x'.join(str(g) for g in e['grid']):<14} "
              f"{e['vmem']['total_bytes'] / (1 << 20):8.1f} "
              f"{r['arithmetic_intensity']:7.1f} "
              f"{r['ceiling_tflops']:9.1f} {r['predicted_tflops']:9.1f} "
              f"{r['bound']:>7} "
              f"{'-' if waste is None else f'{waste:.3f}':>6}")
    print(report.render())

    if args.json:
        with open(args.json, "w") as f:
            json.dump(report.to_json(), f, indent=2)
            f.write("\n")
        print(f"[kernel_lint] wrote {args.json}", file=sys.stderr)

    rc = 0 if report.ok(fail_on=args.fail_on) else 1
    if args.max_dead_tile is not None and worst_waste > args.max_dead_tile:
        print(f"kernel lint: dead-tile waste {worst_waste:.3f} exceeds "
              f"the {args.max_dead_tile} bound")
        rc = 1
    return rc


if __name__ == "__main__":
    sys.exit(main())
