"""Pipeline-schedule cost measurement (VERDICT r1 item 8).

Measures, on the virtual CPU mesh, for pp in {2, 4}:

- wall time per full fwd+bwd step of the lockstep pipeline
  (``forward_backward_pipelining_without_interleaving``) with remat on
  (the default) and off,
- the same work under ``forward_backward_no_pipelining`` on one rank
  (the whole L-layer model, nm microbatches) — the scaling baseline,
- XLA's compile-time memory analysis (argument + temp bytes) for each,

and prints a table plus derived efficiency vs the ideal-bubble model.
Results + the schedule decision are recorded in
``docs/pipeline-schedules.md``.

Run:  XLA_FLAGS=--xla_force_host_platform_device_count=8 \
      python tools/pipeline_cost.py
"""

import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

os.environ["XLA_FLAGS"] = (
    os.environ.get("XLA_FLAGS", "") + " --xla_force_host_platform_device_count=8"
)

import jax

jax.config.update("jax_platforms", "cpu")

import functools

import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from apex_tpu import parallel_state as ps
from apex_tpu.transformer.pipeline_parallel import (
    forward_backward_no_pipelining,
    forward_backward_pipelining_1f1b,
    forward_backward_pipelining_interleaved_1f1b,
    forward_backward_pipelining_with_interleaving,
    forward_backward_pipelining_without_interleaving,
)

HIDDEN = 512
LAYERS = 8  # total; each pp stage runs LAYERS/pp of these
NM = 8
MB = 4  # microbatch rows
SEQ = 128


def make_stage_fn(n_layers):
    """n_layers of (dense 4H + gelu + dense H) — a transformer-MLP-shaped
    stage with enough FLOPs for timing to mean something."""

    def stage_fn(params, x):
        for i in range(n_layers):
            w1, w2 = params[i]
            h = jax.nn.gelu(x @ w1)
            x = x + h @ w2
        return x

    return stage_fn


def make_params(key, n_layers):
    ks = jax.random.split(key, 2 * n_layers)
    scale = 1.0 / (HIDDEN**0.5)
    return [
        (
            jax.random.normal(ks[2 * i], (HIDDEN, 4 * HIDDEN), jnp.float32) * scale,
            jax.random.normal(ks[2 * i + 1], (4 * HIDDEN, HIDDEN), jnp.float32) * scale,
        )
        for i in range(n_layers)
    ]


def loss_fn(y, t):
    return jnp.mean((y - t) ** 2)


def timed(fn, args, reps=3):
    out = jax.block_until_ready(fn(*args))  # compile+warm
    ts = []
    for _ in range(reps):
        t0 = time.perf_counter()
        out = jax.block_until_ready(fn(*args))
        ts.append(time.perf_counter() - t0)
    return min(ts), out


def mem_analysis(fn, args):
    try:
        c = jax.jit(fn).lower(*args).compile()
        m = c.memory_analysis()
        return (m.temp_size_in_bytes + m.output_size_in_bytes) / 1e6
    except Exception:
        return float("nan")


def run_no_pipelining():
    key = jax.random.PRNGKey(0)
    params = make_params(key, LAYERS)
    stage = make_stage_fn(LAYERS)
    x = jax.random.normal(key, (NM, MB, SEQ, HIDDEN), jnp.float32)
    t = jax.random.normal(jax.random.PRNGKey(1), x.shape, jnp.float32)

    def step(params, x, t):
        losses, grads = forward_backward_no_pipelining(
            stage, loss_fn, params, (x, t), num_microbatches=NM, remat=False
        )
        return jnp.sum(losses), sum(
            jnp.sum(jnp.abs(g)) for g in jax.tree_util.tree_leaves(grads)
        )

    f = jax.jit(step)
    wall, _ = timed(f, (params, x, t))
    mem = mem_analysis(step, (params, x, t))
    return wall, mem


def run_lockstep(pp, remat):
    devices = jax.devices()[:pp]
    ps.destroy_model_parallel()
    ps.initialize_model_parallel(
        pipeline_model_parallel_size=pp, devices=devices
    )
    mesh = Mesh(devices, (ps.PIPELINE_PARALLEL_AXIS,))
    stage = make_stage_fn(LAYERS // pp)
    key = jax.random.PRNGKey(0)
    x = jax.random.normal(key, (NM, MB, SEQ, HIDDEN), jnp.float32)
    t = jax.random.normal(jax.random.PRNGKey(1), x.shape, jnp.float32)

    def sharded_step(x, t):
        rank = jax.lax.axis_index(ps.PIPELINE_PARALLEL_AXIS)
        params = make_params(jax.random.fold_in(key, rank), LAYERS // pp)
        losses, grads = forward_backward_pipelining_without_interleaving(
            stage, loss_fn, params, (x, t), num_microbatches=NM, remat=remat
        )
        return jnp.sum(losses), sum(
            jnp.sum(jnp.abs(g)) for g in jax.tree_util.tree_leaves(grads)
        )

    step = jax.shard_map(
        sharded_step, mesh=mesh, in_specs=(P(), P()), out_specs=(P(), P()),
        check_vma=False,
    )
    f = jax.jit(step)
    wall, _ = timed(f, (x, t))
    mem = mem_analysis(step, (x, t))
    ps.destroy_model_parallel()
    return wall, mem


def run_interleaved(pp, vpp, remat, nm=NM):
    devices = jax.devices()[:pp]
    ps.destroy_model_parallel()
    ps.initialize_model_parallel(
        pipeline_model_parallel_size=pp, devices=devices
    )
    mesh = Mesh(devices, (ps.PIPELINE_PARALLEL_AXIS,))
    per_chunk = LAYERS // (pp * vpp)
    stage = make_stage_fn(per_chunk)
    key = jax.random.PRNGKey(0)
    x = jax.random.normal(key, (nm, MB, SEQ, HIDDEN), jnp.float32)
    t = jax.random.normal(jax.random.PRNGKey(1), x.shape, jnp.float32)

    def sharded_step(x, t):
        rank = jax.lax.axis_index(ps.PIPELINE_PARALLEL_AXIS)
        chunks = [
            make_params(jax.random.fold_in(key, rank + pp * k), per_chunk)
            for k in range(vpp)
        ]
        params = jax.tree_util.tree_map(
            lambda *xs: jnp.stack(xs, axis=0), *chunks
        )
        losses, grads = forward_backward_pipelining_with_interleaving(
            stage, loss_fn, params, (x, t),
            num_microbatches=nm, num_model_chunks=vpp, remat=remat,
        )
        return jnp.sum(losses), sum(
            jnp.sum(jnp.abs(g)) for g in jax.tree_util.tree_leaves(grads)
        )

    step = jax.shard_map(
        sharded_step, mesh=mesh, in_specs=(P(), P()), out_specs=(P(), P()),
        check_vma=False,
    )
    f = jax.jit(step)
    wall, _ = timed(f, (x, t))
    mem = mem_analysis(step, (x, t))
    ps.destroy_model_parallel()
    return wall, mem


def run_lockstep_nm(pp, nm, remat=True):
    """Lockstep memory at large grad-accumulation nm (VERDICT r2 item 7)."""
    devices = jax.devices()[:pp]
    ps.destroy_model_parallel()
    ps.initialize_model_parallel(
        pipeline_model_parallel_size=pp, devices=devices
    )
    mesh = Mesh(devices, (ps.PIPELINE_PARALLEL_AXIS,))
    stage = make_stage_fn(LAYERS // pp)
    key = jax.random.PRNGKey(0)
    x = jax.random.normal(key, (nm, MB, SEQ, HIDDEN), jnp.float32)
    t = jax.random.normal(jax.random.PRNGKey(1), x.shape, jnp.float32)

    def sharded_step(x, t):
        rank = jax.lax.axis_index(ps.PIPELINE_PARALLEL_AXIS)
        params = make_params(jax.random.fold_in(key, rank), LAYERS // pp)
        losses, grads = forward_backward_pipelining_without_interleaving(
            stage, loss_fn, params, (x, t), num_microbatches=nm, remat=remat
        )
        return jnp.sum(losses), sum(
            jnp.sum(jnp.abs(g)) for g in jax.tree_util.tree_leaves(grads)
        )

    step = jax.shard_map(
        sharded_step, mesh=mesh, in_specs=(P(), P()), out_specs=(P(), P()),
        check_vma=False,
    )
    mem = mem_analysis(step, (x, t))
    ps.destroy_model_parallel()
    return mem


FRONTIER_HIDDEN = 256  # 1/4 the compute of HIDDEN=512; same memory SHAPE


def run_schedule(pp, nm, schedule, vpp=None, **kw):
    """Wall + compile-time memory for one schedule at (pp, nm) — the
    frontier measurement (VERDICT r3 #5, r4 #2): lockstep variants vs
    the hand-scheduled 1F1B family at grad-accumulation scale.  With
    ``vpp`` the rank's params are ``vpp`` stacked chunks and
    ``num_model_chunks`` is passed through (the interleaved frontier).
    One compile serves both the memory analysis and the (single-rep:
    1-core container, the memory column is the trustworthy one) wall
    timing."""
    n_chunks = vpp or 1
    if LAYERS % (pp * n_chunks):
        # a silent clamp here would compare different model sizes across
        # rows — refuse instead
        raise ValueError(
            f"LAYERS={LAYERS} not divisible by pp*vpp={pp * n_chunks}"
        )
    per_chunk = LAYERS // (pp * n_chunks)
    devices = jax.devices()[:pp]
    ps.destroy_model_parallel()
    ps.initialize_model_parallel(
        pipeline_model_parallel_size=pp, devices=devices
    )
    mesh = Mesh(devices, (ps.PIPELINE_PARALLEL_AXIS,))
    stage = make_stage_fn(per_chunk)
    key = jax.random.PRNGKey(0)
    h = FRONTIER_HIDDEN
    scale = 1.0 / (h ** 0.5)
    x = jax.random.normal(key, (nm, MB, SEQ, h), jnp.float32)
    t = jax.random.normal(jax.random.PRNGKey(1), x.shape, jnp.float32)

    def chunk_params(k):
        ks = jax.random.split(k, 2 * per_chunk)
        return [
            (
                jax.random.normal(ks[2 * i], (h, 4 * h), jnp.float32)
                * scale,
                jax.random.normal(ks[2 * i + 1], (4 * h, h), jnp.float32)
                * scale,
            )
            for i in range(per_chunk)
        ]

    def sharded_step(x, t):
        rank = jax.lax.axis_index(ps.PIPELINE_PARALLEL_AXIS)
        if vpp:
            chunks = [
                chunk_params(jax.random.fold_in(key, rank + pp * k))
                for k in range(vpp)
            ]
            params = jax.tree_util.tree_map(
                lambda *xs: jnp.stack(xs, axis=0), *chunks
            )
            extra = dict(num_model_chunks=vpp)
        else:
            params = chunk_params(jax.random.fold_in(key, rank))
            extra = {}
        losses, grads = schedule(
            stage, loss_fn, params, (x, t), num_microbatches=nm,
            **extra, **kw
        )
        return jnp.sum(losses), sum(
            jnp.sum(jnp.abs(g)) for g in jax.tree_util.tree_leaves(grads)
        )

    step = jax.shard_map(
        sharded_step, mesh=mesh, in_specs=(P(), P()), out_specs=(P(), P()),
        check_vma=False,
    )
    c = jax.jit(step).lower(x, t).compile()
    m = c.memory_analysis()
    mem = (m.temp_size_in_bytes + m.output_size_in_bytes) / 1e6
    jax.block_until_ready(c(x, t))  # warm (allocation etc.)
    t0 = time.perf_counter()
    jax.block_until_ready(c(x, t))
    wall = time.perf_counter() - t0
    ps.destroy_model_parallel()
    return wall, mem


FRONTIER_POINTS = [
    # (label, schedule, kwargs) — every bounded-memory point on offer
    ("lockstep remat",
     forward_backward_pipelining_without_interleaving,
     dict(remat=True)),
    ("lockstep no-remat",
     forward_backward_pipelining_without_interleaving,
     dict(remat=False)),
    ("lockstep carry_chunk",
     forward_backward_pipelining_without_interleaving,
     dict(remat=True, carry_chunk="sqrt")),
    ("hand 1f1b residuals",
     forward_backward_pipelining_1f1b,
     dict(stash="residuals")),
    ("hand 1f1b input",
     forward_backward_pipelining_1f1b,
     dict(stash="input")),
]


VPP_FRONTIER_POINTS = [
    ("interleaved remat",
     forward_backward_pipelining_with_interleaving,
     dict(remat=True)),
    ("interleaved carry_chunk",
     forward_backward_pipelining_with_interleaving,
     dict(remat=True, carry_chunk="sqrt")),
    ("hand intlv residuals",
     forward_backward_pipelining_interleaved_1f1b,
     dict(stash="residuals")),
    ("hand intlv input",
     forward_backward_pipelining_interleaved_1f1b,
     dict(stash="input")),
]


def run_frontier_vpp():
    """The virtual-stage frontier: (pp, vpp) in {(2,2), (2,4), (4,2)}
    (every grid point keeps LAYERS/(pp·vpp) whole so rows stay
    like-for-like), nm in {32, 64} — the hand interleaved schedule's
    memory must be flat in nm (explicit chunk-stash ring) where the
    lockstep family's autodiff carries grow O(nm·vpp).  Decision
    recorded in docs/pipeline-schedules.md."""
    print(
        f"{'schedule':<26}{'pp':>4}{'vpp':>5}{'nm':>5}{'wall ms':>10}"
        f"{'mem MB':>9}",
        flush=True,
    )
    for pp, vpp in ((2, 2), (2, 4), (4, 2)):
        for nm in (32, 64):
            for label, schedule, kw in VPP_FRONTIER_POINTS:
                kw = dict(kw)
                if kw.get("carry_chunk") == "sqrt":
                    kw["carry_chunk"] = max(
                        2, int(round((nm * vpp + pp - 1) ** 0.5))
                    )
                try:
                    wall, mem = run_schedule(pp, nm, schedule, vpp=vpp, **kw)
                except Exception as e:
                    print(f"{label:<26}{pp:>4}{vpp:>5}{nm:>5}  FAILED: {e}")
                    continue
                print(
                    f"{label:<26}{pp:>4}{vpp:>5}{nm:>5}{wall*1e3:>10.1f}"
                    f"{mem:>9.1f}",
                    flush=True,
                )


def run_frontier():
    """The memory/compute frontier at grad-accumulation scale:
    nm in {32, 64} x pp in {4, 8}, wall + compiled memory for each
    schedule.  Decision recorded in docs/pipeline-schedules.md."""
    print(
        f"{'schedule':<24}{'pp':>4}{'nm':>5}{'wall ms':>10}{'mem MB':>9}",
        flush=True,
    )
    for pp in (4, 8):
        for nm in (32, 64):
            for label, schedule, kw in FRONTIER_POINTS:
                kw = dict(kw)
                if kw.get("carry_chunk") == "sqrt":
                    kw["carry_chunk"] = max(
                        2, int(round((nm + pp - 1) ** 0.5))
                    )
                try:
                    wall, mem = run_schedule(pp, nm, schedule, **kw)
                except Exception as e:
                    print(f"{label:<24}{pp:>4}{nm:>5}  FAILED: {e}")
                    continue
                print(
                    f"{label:<24}{pp:>4}{nm:>5}{wall*1e3:>10.1f}"
                    f"{mem:>9.1f}",
                    flush=True,
                )


def main():
    mode = sys.argv[1] if len(sys.argv) > 1 else "all"
    header = (
        f"{'schedule':<28}{'pp':>4}{'vpp':>4}{'remat':>7}{'wall ms':>10}"
        f"{'mem MB':>9}{'speedup':>9}{'ideal':>7}{'eff':>7}"
    )

    if mode in ("all", "schedules", "lockstep", "interleaved"):
        base_wall, base_mem = run_no_pipelining()
        print(
            f"no_pipelining  (1 rank, L={LAYERS}, nm={NM}):"
            f"  wall={base_wall*1e3:8.1f} ms  mem={base_mem:8.1f} MB",
            flush=True,
        )
        print(header, flush=True)

    if mode in ("all", "schedules", "lockstep"):
        for pp in (2, 4):
            for remat in (True, False):
                wall, mem = run_lockstep(pp, remat)
                speed = base_wall / wall
                # ideal bubble-limited speedup for pipelining nm microbatches
                # over pp stages: pp * nm / (nm + pp - 1)
                ideal = pp * NM / (NM + pp - 1)
                print(
                    f"{'lockstep_1f1b':<28}{pp:>4}{'-':>4}{str(remat):>7}"
                    f"{wall*1e3:>10.1f}{mem:>9.1f}{speed:>9.2f}{ideal:>7.2f}"
                    f"{speed/ideal:>7.2f}",
                    flush=True,
                )

    if mode in ("all", "schedules", "interleaved"):
        for pp, vpp in ((2, 2), (2, 4), (4, 2)):
            for remat in (True, False):
                wall, mem = run_interleaved(pp, vpp, remat)
                speed = base_wall / wall
                # ticks = nm*vpp + pp - 1 of duration 1/vpp stage:
                # ideal speedup = pp*vpp*nm / (nm*vpp + pp - 1)
                ideal = pp * vpp * NM / (NM * vpp + pp - 1)
                print(
                    f"{'interleaved':<28}{pp:>4}{vpp:>4}{str(remat):>7}"
                    f"{wall*1e3:>10.1f}{mem:>9.1f}{speed:>9.2f}{ideal:>7.2f}"
                    f"{speed/ideal:>7.2f}",
                    flush=True,
                )

    if mode in ("all", "frontier"):
        print()
        print("memory/compute frontier at grad-accumulation scale:",
              flush=True)
        run_frontier()

    if mode in ("all", "frontier-vpp"):
        print()
        print("virtual-stage (interleaved) frontier:", flush=True)
        run_frontier_vpp()

    if mode in ("all", "nm-sweep"):
        print()
        print("lockstep memory vs num_microbatches (remat=True):", flush=True)
        print(f"{'pp':>4}{'nm':>6}{'mem MB':>10}{'mem/nm MB':>12}", flush=True)
        for pp in (2, 4):
            for nm in (8, 16, 32, 64):
                mem = run_lockstep_nm(pp, nm)
                print(f"{pp:>4}{nm:>6}{mem:>10.1f}{mem/nm:>12.2f}", flush=True)


if __name__ == "__main__":
    main()
