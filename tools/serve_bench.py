"""Closed-loop serving load generator — latency distribution, goodput,
and the serving acceptance artifact.

Drives a real :class:`apex_tpu.serve.InferenceEngine` +
:class:`ContinuousBatchingScheduler` with **Poisson arrivals** and a
configurable prompt/output length mix, then reports what a production
operator would page on:

- the TTFT and per-output-token latency distributions (p50/p95/p99,
  rendered as a text histogram);
- goodput under shedding: completed / offered requests and tokens, with
  the shed count broken out (graceful degradation is only graceful if
  it is measured);
- the continuous-batching proof: mean/peak batch-fill gauge vs the
  single-request baseline (a scheduler that never admits mid-stream
  would sit at the baseline);
- the numerics proof: paged **int8-KV** decode logits vs the unpaged
  f32 reference forward (``GptModel.apply``) within the pinned
  tolerance, same check at f32;
- the static proof: ``analysis.check`` ERROR counts on the AOT prefill
  and decode step programs (zero required).

``--json FILE`` writes everything as one artifact — the ISSUE 7
acceptance surface, consumed by CI — including the per-reason shed
breakdown, the TTFT queue-wait/prefill/contention attribution
percentiles, and the process wall-clock anchor.  ``--spans FILE``
additionally records every request's span chain
(``queued → admitted → prefill → decode[i] → done|shed``) through a
:class:`~apex_tpu.observability.spans.SpanRecorder`; feed the dump to
``tools/timeline.py`` for the Perfetto timeline and the
span-accounting CI gate (``docs/observability.md``).

The live ops plane (``docs/observability.md`` "Live ops plane"):

- ``--ops-port PORT`` (or ``APEX_TPU_OPS_PORT``; 0 = OS-assigned)
  serves OpenMetrics at ``/metrics`` while the load runs — scheduler
  gauges/counters, the TTFT histogram, and the board.  One scrape is
  taken over real HTTP mid-run and one after the final registry drain;
  both land in the ``--json`` artifact (the end-of-run one parsed and
  value-cross-checked against the registry section by the
  ``verify_tier1.sh`` OPS gate).
- with ``--slo-ttft-ms`` set, a health :class:`Watchdog` evaluates the
  serving SLO set (TTFT latency, goodput, deadline-shed rate) with
  multi-window burn-rate alerting on every scheduler iteration; fired
  alerts land in the artifact AND — with ``--spans`` — on the span
  timeline next to the requests that blew the budget.  The window pair
  is scaled by ``--slo-burn-short/--slo-burn-long`` (seconds) so a CI
  storm fires in-process; production deployments use the SRE-workbook
  defaults in :mod:`apex_tpu.observability.slo`.
- live device-memory watermarks are sampled every iteration
  (``device.memory_stats()`` on TPU; a fake provider seeded from the
  engine's OWN static peak-HBM predictions on CPU — scale it with
  ``--memstats-fake-scale`` to plant drift) and cross-checked against
  the static analyzer at the end: drift beyond
  ``--memstats-tolerance`` is reported in the artifact naming the
  program, never silently.

With ``--speculate K`` the run decodes speculatively (optionally with a
``--draft-layers N`` truncated draft) and the artifact grows a ``spec``
section — acceptance rate, tokens/decode-step, per-request
decode-steps-saved percentiles, and the bit-identity replay against a
plain-decode reference (``docs/serving.md`` "Speculative decoding").

Usage::

    python tools/serve_bench.py                  # small CPU run
    python tools/serve_bench.py --requests 32 --rate 50 --json out.json
    python tools/serve_bench.py --speculate 4 --json out.json
    python tools/serve_bench.py --spans spans.json --json out.json
    python tools/serve_bench.py --ops-port 9400 --slo-ttft-ms 250
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

os.environ.setdefault("JAX_PLATFORMS", "cpu")

#: pinned acceptance tolerances on last-position logits vs the unpaged
#: f32 reference (tests/test_serve.py pins the same numbers)
TOL_F32 = 2e-4
TOL_INT8_KV = 5e-2


# the ONE nearest-rank implementation the scheduler gauges use too
from apex_tpu.observability.meter import percentile as _percentile  # noqa: E402


def _histogram(vals, width=40, bins=10):
    if not vals:
        return "  (no samples)"
    lo, hi = min(vals), max(vals)
    span = (hi - lo) or 1.0
    counts = [0] * bins
    for v in vals:
        counts[min(bins - 1, int((v - lo) / span * bins))] += 1
    peak = max(counts)
    lines = []
    for i, c in enumerate(counts):
        b0 = lo + span * i / bins
        b1 = lo + span * (i + 1) / bins
        bar = "#" * int(width * c / peak)
        lines.append(f"  {b0:9.2f}-{b1:9.2f} ms |{bar:<{width}}| {c}")
    return "\n".join(lines)


def build_engine(args):
    import jax
    import jax.numpy as jnp

    from apex_tpu.models.gpt import GptConfig, GptModel
    from apex_tpu.serve import InferenceEngine, ServeConfig
    from apex_tpu.observability import MetricRegistry

    cfg = GptConfig(
        vocab_size=args.vocab, hidden_size=args.hidden,
        num_layers=args.layers, num_heads=args.heads,
        intermediate_size=2 * args.hidden, max_seq_len=1024,
        dtype=jnp.float32,
    )
    serve_cfg = ServeConfig(
        page_size=args.page_size, num_pages=args.pages,
        max_batch=args.batch, max_pages_per_seq=args.pages_per_seq,
        kv_wire=args.kv_wire, weight_wire=args.weight_wire,
        verify=True,
    )
    model = GptModel(cfg)
    ids = jax.random.randint(jax.random.PRNGKey(0), (32, 1), 0, cfg.vocab_size)
    params = model.init(jax.random.PRNGKey(1), ids)
    registry = MetricRegistry(fetch_every=1)
    spec = None
    if args.speculate:
        import dataclasses

        from apex_tpu.serve import SpecConfig, draft_from_params

        if args.draft_layers:
            # truncated draft: the target's first N layers (embeddings
            # and final norm shared) — cheap to propose, aligned enough
            # to accept
            spec = SpecConfig(
                draft_params=draft_from_params(params, args.draft_layers),
                k=args.speculate,
                draft_cfg=dataclasses.replace(
                    cfg, num_layers=args.draft_layers
                ),
            )
        else:
            # self-draft: the target proposes for itself — 100% greedy
            # acceptance, the upper bound the gate pins tokens/step on
            spec = SpecConfig(draft_params=None, k=args.speculate)
    # build() compiles AND analysis-verifies every bucket + the decode
    # step up front, so engine.reports is the acceptance evidence; the
    # chunk-prefill/fork programs warm too when the run will use them
    # (a lazy compile inside the first cache hit would poison its TTFT)
    engine = InferenceEngine(
        cfg, params, serve_cfg, spec=spec, registry=registry
    ).build(chunked=bool(args.prefix_cache or args.chunk_tokens))
    return cfg, model, params, engine, registry


def numerics_check(cfg, model, params, args):
    """Paged decode logits (f32 cache AND int8-KV cache) vs the unpaged
    f32 reference forward, on one greedy continuation."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from apex_tpu.models.gpt import _tied_vocab_logits
    from apex_tpu.serve import InferenceEngine, ServeConfig

    rs = np.random.RandomState(7)
    prompt = list(rs.randint(0, cfg.vocab_size, size=24))
    steps = 6
    out = {}
    for wire, tol in (("f32", TOL_F32), ("int8", TOL_INT8_KV)):
        eng = InferenceEngine(
            cfg, params,
            ServeConfig(
                page_size=args.page_size, num_pages=args.pages,
                max_batch=2, max_pages_per_seq=args.pages_per_seq,
                kv_wire=wire, verify=False,
            ),
        )
        pages = eng.pool.alloc(eng.pool.pages_for(len(prompt)))
        _, tok = eng.prefill(prompt, pages)
        cur = list(prompt)
        ctx = len(prompt)
        worst = 0.0
        table = np.zeros((2, args.pages_per_seq), np.int32)
        for _ in range(steps):
            if ctx // args.page_size >= len(pages):
                got = eng.pool.alloc(1)
                if got is None:
                    raise RuntimeError(
                        "numerics check: page pool exhausted — raise "
                        "--pages"
                    )
                pages += got
            table[0, : len(pages)] = pages
            logits, nxt = eng.decode(
                np.array([tok, 0]), np.array([ctx + 1, 0]), table
            )
            cur.append(tok)
            ref_ids = jnp.asarray(np.array(cur)[:, None], jnp.int32)
            h = model.apply(params, ref_ids)
            ref = _tied_vocab_logits(params, model, h, sp_gathered=False)
            worst = max(
                worst,
                float(np.abs(logits[0] - np.asarray(ref[-1, 0])).max()),
            )
            ctx += 1
            tok = int(nxt[0])
        out[wire] = {
            "max_abs_logit_diff": worst,
            "tolerance": tol,
            "ok": worst <= tol,
        }
    return out


def http_scrape(url, timeout=5.0):
    """One HTTP GET of the ops endpoint: ``{ok, ms, bytes, status}``
    (+ ``text`` on success, ``error`` on failure)."""
    import urllib.error
    import urllib.request

    t0 = time.perf_counter()
    try:
        with urllib.request.urlopen(url, timeout=timeout) as resp:
            body = resp.read().decode("utf-8")
            return {
                "ok": True,
                "status": resp.status,
                "ms": 1e3 * (time.perf_counter() - t0),
                "bytes": len(body),
                "content_type": resp.headers.get("Content-Type", ""),
                "text": body,
            }
    except (urllib.error.URLError, OSError) as e:
        return {
            "ok": False,
            "ms": 1e3 * (time.perf_counter() - t0),
            "error": f"{type(e).__name__}: {e}",
        }


def run_load(sched, args, *, watchdog=None, monitor=None, ops=None):
    import numpy as np

    from apex_tpu.serve import Request

    rs = np.random.RandomState(args.seed)

    # Poisson arrivals: exponential inter-arrival gaps at --rate req/s,
    # pre-drawn so the run is deterministic under --seed
    gaps = rs.exponential(1.0 / args.rate, size=args.requests)
    arrivals = np.cumsum(gaps)
    prompt_lens = rs.choice(args.prompt_mix, size=args.requests)
    out_lens = rs.choice(args.output_mix, size=args.requests)
    # shared-prefix workload (the prefix-cache proof): --shared-frac of
    # the requests open with the SAME --shared-prefix-tokens system
    # prompt and differ only in their tail — the draws come AFTER the
    # base workload's so plain runs keep their exact historical stream
    shared_prefix = None
    shared_mask = np.zeros(args.requests, bool)
    if args.shared_prefix_tokens:
        shared_prefix = list(rs.randint(
            0, args.vocab, size=args.shared_prefix_tokens
        ))
        shared_mask = rs.rand(args.requests) < args.shared_frac
        prompt_lens = np.maximum(
            prompt_lens, args.shared_prefix_tokens + 1
        )

    def make_prompt(i):
        n = int(prompt_lens[i])
        if shared_prefix is not None and shared_mask[i]:
            tail = list(rs.randint(0, args.vocab,
                                   size=n - len(shared_prefix)))
            return list(shared_prefix) + tail
        return list(rs.randint(0, args.vocab, size=n))

    submitted_reqs = []
    t0 = time.monotonic()
    submitted = 0
    iteration = 0
    fills = []
    occupancy = []
    mid_scrape = None
    while submitted < args.requests or sched.pending:
        now = time.monotonic() - t0
        while submitted < args.requests and arrivals[submitted] <= now:
            req = sched.submit(Request(
                prompt=make_prompt(submitted),
                max_new_tokens=int(out_lens[submitted]),
                slo_ttft_ms=args.slo_ttft_ms,
            ))
            submitted_reqs.append(req)
            submitted += 1
        if sched.pending:
            sched.step()
            iteration += 1
            fills.append(sched.batch_fill())
            occupancy.append(sched.pool.occupancy())
            if monitor is not None:
                monitor.sample(iteration)
            if watchdog is not None:
                watchdog.on_step(iteration)
            if (
                ops is not None
                and mid_scrape is None
                and submitted * 2 >= args.requests
            ):
                # the scrape-under-load proof: a real HTTP GET against
                # the endpoint WHILE the scheduler is mid-traffic
                mid_scrape = http_scrape(ops.url)
                mid_scrape.pop("text", None)  # the end-of-run one is kept
        elif submitted < args.requests:
            time.sleep(min(0.002, arrivals[submitted] - now))
    wall = time.monotonic() - t0

    done = sched.completed
    shed = sched.shed
    ttfts = sorted(r.ttft_ms for r in done if r.ttft_ms is not None)
    per_tok = []
    for r in done:
        n_decode = len(r.tokens) - 1
        if n_decode > 0 and r.done_at and r.first_token_at:
            per_tok.append(
                1e3 * (r.done_at - r.first_token_at) / n_decode
            )
    per_tok.sort()
    tokens_done = sum(len(r.tokens) for r in done)
    # offered output tokens across ALL submitted requests (shed
    # included): the token-level goodput denominator
    tokens_offered = int(sum(int(n) for n in out_lens[:submitted]))
    offered = len(done) + len(shed)

    # per-reason shed breakdown (the split serve/shed counters carry
    # the same numbers through the registry)
    shed_reasons = {}
    for r in shed:
        key = r.shed_reason or "?"
        shed_reasons[key] = shed_reasons.get(key, 0) + 1
    # TTFT attribution: per-component percentiles over every completed
    # request — the same queue-wait/prefill/contention decomposition
    # the scheduler publishes as serve/ttft_*_ms_p* gauges
    from apex_tpu.serve import ttft_attribution

    comps = [c for c in (r.ttft_components() for r in done)
             if c is not None]
    # the scheduler's own aggregation: the artifact and the
    # serve/ttft_* registry gauges come from ONE implementation
    ttft_attr = ttft_attribution(comps)
    return {
        "requests": {
            "offered": offered,
            "completed": len(done),
            "shed": len(shed),
            "shed_reasons": shed_reasons,
            "goodput": len(done) / offered if offered else 0.0,
        },
        "ttft_attribution": ttft_attr,
        "tokens": {
            "completed": tokens_done,
            "offered": tokens_offered,
            "goodput": (
                tokens_done / tokens_offered if tokens_offered else 0.0
            ),
            "throughput_per_s": tokens_done / wall if wall > 0 else 0.0,
        },
        "ttft_ms": {
            "p50": _percentile(ttfts, 0.50),
            "p95": _percentile(ttfts, 0.95),
            "p99": _percentile(ttfts, 0.99),
            "samples": len(ttfts),
        },
        "per_token_ms": {
            "p50": _percentile(per_tok, 0.50),
            "p95": _percentile(per_tok, 0.95),
            "p99": _percentile(per_tok, 0.99),
            "samples": len(per_tok),
        },
        "batch_fill": {
            "mean": sum(fills) / len(fills) if fills else 0.0,
            "peak": max(fills) if fills else 0.0,
        },
        "page_occupancy_peak": max(occupancy) if occupancy else 0.0,
        "wall_s": wall,
        "_ttft_samples": ttfts,
        "_per_tok_samples": per_tok,
        "_mid_scrape": mid_scrape,
        "_requests": submitted_reqs,
    }


def _prefill_flops(cfg, n, start):
    """Analytic prefill FLOPs for positions ``[start, n)`` of an
    ``n``-token prompt: per-token linear work (qkv + attention output
    + MLP matmuls) plus causal attention ``QK^T``/``AV`` work, which
    for position ``i`` scans a context of ``i + 1`` — the quadratic
    term the prefix cache's skipped positions save twice over."""
    h = cfg.hidden_size
    linear = 4 * h * h + 2 * h * cfg.intermediate_size
    pairs = (n * (n + 1) - start * (start + 1)) / 2.0
    return cfg.num_layers * (linear * (n - start) + 2.0 * h * pairs)


def prefix_report(sched, cfg, args, load):
    """The prefix-cache acceptance section: hit-vs-miss TTFT (classified
    by each completed request's actual ``cache_hit_tokens``), the
    analytic prefill-FLOPs saving over the whole completed set, the
    cache ledger, and the pool-accounting proof."""
    done = [r for r in sched.completed if r.ttft_ms is not None]
    hit = [r for r in done if r.cache_hit_tokens > 0]
    miss = [r for r in done if r.cache_hit_tokens == 0]
    grain = args.chunk_tokens or args.page_size
    flops_cold = flops_cached = 0.0
    for r in done:
        n = len(r.prompt)
        start = (min(r.cache_hit_tokens, n - 1) // grain) * grain
        flops_cold += _prefill_flops(cfg, n, 0)
        flops_cached += _prefill_flops(cfg, n, start)
    saved_pct = (
        100.0 * (1.0 - flops_cached / flops_cold) if flops_cold else 0.0
    )
    sched.leak_check()  # must not raise — the final accounting proof
    prefix = sched.prefix
    return {
        "shared_prefix_tokens": args.shared_prefix_tokens,
        "shared_frac": args.shared_frac,
        "chunk_tokens": args.chunk_tokens,
        "hit_requests": len(hit),
        "miss_requests": len(miss),
        "hit_ttft_ms": {
            "p50": _percentile(sorted(r.ttft_ms for r in hit), 0.50),
            "samples": len(hit),
        },
        "miss_ttft_ms": {
            "p50": _percentile(sorted(r.ttft_ms for r in miss), 0.50),
            "samples": len(miss),
        },
        "prefill_flops_saved_pct": saved_pct,
        "cache": {
            "hits": prefix.hits,
            "misses": prefix.misses,
            "hit_tokens": prefix.hit_tokens,
            "commits": prefix.commits,
            "evictions": prefix.evictions,
            "cached_pages": len(prefix.cached_pages()),
        },
        "leak_checks_run": sched.leak_checks_run,
    }


def prefix_replay_check(cfg, params, args, completed):
    """Bit-identity proof: replay every completed request, one at a
    time, through a cache-DISABLED scheduler with the same chunk
    config — the cached run's full token stream must match exactly
    (greedy sampling; the hit re-runs the same final chunk over
    bit-identical committed pages, so any divergence means a borrowed
    page was corrupted)."""
    from apex_tpu.serve import (
        ContinuousBatchingScheduler,
        InferenceEngine,
        Request,
        ServeConfig,
    )

    eng = InferenceEngine(cfg, params, ServeConfig(
        page_size=args.page_size, num_pages=args.pages,
        max_batch=2, max_pages_per_seq=args.pages_per_seq,
        kv_wire=args.kv_wire, weight_wire=args.weight_wire,
        verify=False,
    ))
    sched = ContinuousBatchingScheduler(
        eng, registry=None, prefix_cache=False,
        prefill_chunk_tokens=args.chunk_tokens,
    )
    mismatches = []
    for r in completed:
        ref = sched.submit(Request(
            prompt=list(r.prompt), max_new_tokens=r.max_new_tokens,
        ))
        sched.run()
        if ref.tokens != r.tokens:
            mismatches.append(r.rid)
    return {
        "replayed": len(completed),
        "mismatched_rids": mismatches,
        "bit_identical": not mismatches,
    }


def spec_report(sched, registry, args):
    """The speculative-decoding acceptance section: windowed acceptance
    rate and tokens/decode-step from the scheduler's own gauges, the
    draft/accept/rollback ledger, and per-request decode-steps-saved
    percentiles (each completed request's actual engine iterations vs
    the one-token-per-step count plain decode would have needed)."""
    registry.fetch()
    vals = registry.values()
    saved = []
    for r in sched.completed:
        n_decode = len(r.tokens) - 1
        if (
            n_decode > 0
            and r.first_decode_iter is not None
            and r.last_decode_iter is not None
        ):
            steps = r.last_decode_iter - r.first_decode_iter + 1
            saved.append(100.0 * (1.0 - steps / n_decode))
    saved.sort()
    sched.leak_check()  # draft pages ledgered exactly, proven here
    return {
        "k": args.speculate,
        "draft_layers": args.draft_layers,
        "rounds": vals.get("serve/spec_rounds", 0.0),
        "drafted": vals.get("serve/spec_drafted", 0.0),
        "accepted": vals.get("serve/spec_accepted", 0.0),
        "rollbacks": vals.get("serve/spec_rollbacks", 0.0),
        "fallbacks": vals.get("serve/spec_fallbacks", 0.0),
        "draft_faults": vals.get("serve/draft_faults", 0.0),
        "accept_rate": vals.get("serve/spec_accept_rate", 0.0),
        "tokens_per_step": vals.get("serve/spec_tokens_per_step", 0.0),
        "decode_steps_saved_pct": {
            "p50": _percentile(saved, 0.50),
            "p95": _percentile(saved, 0.95),
            "p99": _percentile(saved, 0.99),
            "samples": len(saved),
        },
        "leak_checks_run": sched.leak_checks_run,
    }


def single_request_baseline(engine, args):
    """Batch-fill a lone request sustains — the bar the continuous
    batcher must beat (one request on max_batch slots)."""
    import numpy as np

    from apex_tpu.serve import ContinuousBatchingScheduler, Request

    rs = np.random.RandomState(1)
    sched = ContinuousBatchingScheduler(engine, registry=None)
    sched.submit(Request(
        prompt=list(rs.randint(0, args.vocab, size=int(args.prompt_mix[0]))),
        max_new_tokens=int(args.output_mix[0]),
    ))
    fills = []
    while sched.pending:
        sched.step()
        fills.append(sched.batch_fill())
    return sum(fills) / len(fills) if fills else 0.0


def main():
    ap = argparse.ArgumentParser(
        description="closed-loop serving load generator (docs/serving.md)"
    )
    ap.add_argument("--requests", type=int, default=12)
    ap.add_argument("--rate", type=float, default=100.0,
                    help="Poisson arrival rate, requests/s")
    ap.add_argument("--prompt-mix", type=int, nargs="+",
                    default=[16, 32, 48], dest="prompt_mix")
    ap.add_argument("--output-mix", type=int, nargs="+",
                    default=[4, 8, 16], dest="output_mix")
    ap.add_argument("--slo-ttft-ms", type=float, default=None,
                    help="per-request TTFT SLO (None = best effort)")
    ap.add_argument("--vocab", type=int, default=128)
    ap.add_argument("--hidden", type=int, default=64)
    ap.add_argument("--layers", type=int, default=2)
    ap.add_argument("--heads", type=int, default=4)
    ap.add_argument("--batch", type=int, default=4)
    ap.add_argument("--page-size", type=int, default=16)
    ap.add_argument("--pages", type=int, default=96)
    ap.add_argument("--pages-per-seq", type=int, default=8)
    ap.add_argument("--kv-wire", default="f32", choices=["f32", "int8"])
    ap.add_argument("--weight-wire", default="f32", choices=["f32", "int8"])
    ap.add_argument("--prefix-cache", action="store_true",
                    help="arm the cross-request prefix cache "
                    "(docs/serving.md 'Prefix caching')")
    ap.add_argument("--shared-prefix-tokens", type=int, default=0,
                    metavar="N", dest="shared_prefix_tokens",
                    help="length of the shared system prompt opening "
                    "--shared-frac of the requests (0 = off)")
    ap.add_argument("--shared-frac", type=float, default=0.8,
                    dest="shared_frac",
                    help="fraction of requests drawing the shared prefix")
    ap.add_argument("--chunk-tokens", type=int, default=None,
                    metavar="N", dest="chunk_tokens",
                    help="prefill chunk size (page multiple): slices "
                    "prefill between decode iterations; also the "
                    "re-run grain a cache hit's bit-identity rides on")
    ap.add_argument("--speculate", type=int, default=0, metavar="K",
                    help="speculative decoding: draft K tokens per "
                    "round, one target verify step scores them all "
                    "(0 = off; docs/serving.md 'Speculative decoding')")
    ap.add_argument("--draft-layers", type=int, default=None,
                    metavar="N", dest="draft_layers",
                    help="draft = the target's first N layers "
                    "(embeddings shared); default self-draft — the "
                    "target proposes for itself (100%% greedy accept)")
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--json", metavar="FILE", default=None)
    ap.add_argument("--spans", metavar="FILE", default=None,
                    help="record per-request span chains and dump them "
                    "here (feed to tools/timeline.py)")
    ap.add_argument("--span-capacity", type=int, default=65536)
    ap.add_argument("--ops-port", type=int, default=None,
                    metavar="PORT",
                    help="serve OpenMetrics at /metrics during the run "
                    "(0 = OS-assigned; APEX_TPU_OPS_PORT is the default)")
    ap.add_argument("--slo-objective", type=float, default=0.9,
                    help="TTFT SLO objective (fraction of requests "
                    "under --slo-ttft-ms)")
    ap.add_argument("--slo-burn-short", type=float, default=0.25,
                    metavar="S",
                    help="short burn-rate window, seconds (scaled for "
                    "in-process runs; production uses slo.DEFAULT_WINDOWS)")
    ap.add_argument("--slo-burn-long", type=float, default=1.0,
                    metavar="S", help="long burn-rate window, seconds")
    ap.add_argument("--slo-burn-factor", type=float, default=2.0,
                    help="burn-rate page factor over BOTH windows")
    ap.add_argument("--memstats-fake-scale", type=float, default=1.0,
                    help="scale of the fake provider's live watermark "
                    "vs the static peak (CPU only; 2.0 plants the "
                    "drift the CI gate must flag)")
    ap.add_argument("--memstats-tolerance", type=float, default=0.25,
                    help="static-vs-live reconciliation tolerance")
    args = ap.parse_args()
    if args.ops_port is None:
        from apex_tpu.observability.ometrics import ops_port_from_env

        args.ops_port = ops_port_from_env()

    cfg, model, params, engine, registry = build_engine(args)
    lint_errors = {
        name: len(rep.errors()) for name, rep in engine.reports.items()
    }

    from apex_tpu.observability import memstats as memstats_lib

    # the engine build (verify=True) just published its per-program
    # static peak-HBM predictions — the reconciliation baseline
    static_peaks = memstats_lib.static_peaks_from_board()
    provider = memstats_lib.default_provider()
    if provider is None:  # CPU tier: fake seeded from the static peaks
        provider = memstats_lib.FakeMemoryProvider.from_static(
            static_peaks or {"unverified": 0.0},
            scale=args.memstats_fake_scale,
        )
    monitor = memstats_lib.MemStatsMonitor(provider)

    recorder = None
    if args.spans:
        from apex_tpu.observability.spans import SpanRecorder

        recorder = SpanRecorder(capacity=args.span_capacity)

    baseline_fill = single_request_baseline(engine, args)

    from apex_tpu.serve import ContinuousBatchingScheduler

    sched = ContinuousBatchingScheduler(
        engine, registry=registry, spans=recorder,
        prefix_cache=args.prefix_cache,
        prefill_chunk_tokens=args.chunk_tokens,
    )

    ops = None
    if args.ops_port is not None:
        from apex_tpu.observability.ometrics import OpsServer

        ops = OpsServer(
            registries=[registry], histograms=[sched.ttft_hist],
            collect=monitor.sample, port=args.ops_port,
        ).start()
        print(f"[serve_bench] ops endpoint live at {ops.url}")

    watchdog = None
    if args.slo_ttft_ms is not None:
        from apex_tpu.observability import slo as slo_lib
        from apex_tpu.observability.health import Watchdog

        windows = (slo_lib.Window(
            args.slo_burn_short, args.slo_burn_long,
            args.slo_burn_factor, "critical",
        ),)
        watchdog = Watchdog(
            rules=slo_lib.serve_slo_rules(
                ttft_histogram=sched.ttft_hist,
                ttft_threshold_ms=args.slo_ttft_ms,
                ttft_objective=args.slo_objective,
                windows=windows,
            ),
            registry=registry, spans=recorder, check_every=1,
        )

    load = run_load(
        sched, args, watchdog=watchdog, monitor=monitor, ops=ops
    )
    numerics = numerics_check(cfg, model, params, args)

    if recorder is not None:
        spans_path = recorder.dump(reason="serve_bench", path=args.spans)
        print(f"[serve_bench] wrote {spans_path} "
              f"({len(recorder.snapshot())} span entries, "
              f"{recorder.dropped} dropped)")

    ttft_samples = load.pop("_ttft_samples")
    per_tok_samples = load.pop("_per_tok_samples")
    mid_scrape = load.pop("_mid_scrape")
    load.pop("_requests")
    if args.prefix_cache:
        load["prefix"] = prefix_report(sched, cfg, args, load)
        load["prefix"]["replay"] = prefix_replay_check(
            cfg, params, args, sched.completed
        )
    if args.speculate:
        load["spec"] = spec_report(sched, registry, args)
        # bit-identity proof: prefix_replay_check's reference engine is
        # ALSO speculation-free, so the same replay serves both gates
        load["spec"]["replay"] = prefix_replay_check(
            cfg, params, args, sched.completed
        )
    registry.fetch()

    # the end-of-run scrape happens AFTER the registry drain, so its
    # gauge/counter samples must EQUAL the artifact's registry section
    # — the OPS gate's cross-check
    final_scrape = http_scrape(ops.url) if ops is not None else None
    memstats_findings = monitor.crosscheck(
        static_peaks, tolerance=args.memstats_tolerance
    )

    print(f"== serve_bench: {args.requests} requests, Poisson "
          f"{args.rate}/s, kv_wire={args.kv_wire}, "
          f"weight_wire={args.weight_wire} ==")
    r = load["requests"]
    tk = load["tokens"]
    shed_desc = (
        " (" + ", ".join(
            f"{k}={v}" for k, v in sorted(r["shed_reasons"].items())
        ) + ")" if r["shed_reasons"] else ""
    )
    print(f"goodput: {r['completed']}/{r['offered']} requests "
          f"({100 * r['goodput']:.1f}%), {r['shed']} shed{shed_desc}; "
          f"{tk['completed']}/{tk['offered']} tokens "
          f"({100 * tk['goodput']:.1f}%)")
    print(f"throughput: {load['tokens']['throughput_per_s']:.1f} tokens/s "
          f"({load['tokens']['completed']} tokens in "
          f"{load['wall_s']:.2f}s)")
    t = load["ttft_ms"]
    print(f"TTFT ms: p50={t['p50']:.2f} p95={t['p95']:.2f} "
          f"p99={t['p99']:.2f} (n={t['samples']})")
    from apex_tpu.serve import TTFT_COMPONENTS

    ta = load["ttft_attribution"]
    print("TTFT attribution (p50/p95/p99 ms): " + "  ".join(
        f"{comp}={ta[f'{comp}_ms']['p50']:.2f}/"
        f"{ta[f'{comp}_ms']['p95']:.2f}/{ta[f'{comp}_ms']['p99']:.2f}"
        for comp in TTFT_COMPONENTS
    ) + f"  queue-wait fraction={ta['queue_wait_fraction']:.3f}")
    print(_histogram(ttft_samples))
    p = load["per_token_ms"]
    print(f"per-token ms: p50={p['p50']:.2f} p95={p['p95']:.2f} "
          f"p99={p['p99']:.2f} (n={p['samples']})")
    print(_histogram(per_tok_samples))
    bf = load["batch_fill"]
    print(f"batch fill: mean={bf['mean']:.3f} peak={bf['peak']:.3f} "
          f"(single-request baseline {baseline_fill:.3f}); page "
          f"occupancy peak {load['page_occupancy_peak']:.3f}")
    for wire, rec in numerics.items():
        print(f"numerics [{wire} KV vs unpaged f32]: max|dlogit|="
              f"{rec['max_abs_logit_diff']:.2e} tol={rec['tolerance']} "
              f"{'OK' if rec['ok'] else 'FAIL'}")
    if args.prefix_cache:
        px = load["prefix"]
        hp = px["hit_ttft_ms"]["p50"]
        mp = px["miss_ttft_ms"]["p50"]
        ratio = (hp / mp) if (hp == hp and mp and mp == mp) else float("nan")
        print(f"prefix cache: {px['hit_requests']} hit / "
              f"{px['miss_requests']} miss; hit p50 TTFT {hp:.2f}ms vs "
              f"miss {mp:.2f}ms (ratio {ratio:.3f}); prefill FLOPs "
              f"saved {px['prefill_flops_saved_pct']:.1f}%; "
              f"evictions={px['cache']['evictions']} "
              f"commits={px['cache']['commits']} "
              f"leak_checks={px['leak_checks_run']}")
        rp = px["replay"]
        print(f"prefix replay: {rp['replayed']} requests vs uncached "
              f"reference — "
              f"{'BIT-IDENTICAL' if rp['bit_identical'] else 'MISMATCH'}")
    if args.speculate:
        sx = load["spec"]
        ds = sx["decode_steps_saved_pct"]
        print(f"speculative decode (k={sx['k']}, draft_layers="
              f"{sx['draft_layers'] or 'self'}): accept rate "
              f"{100 * sx['accept_rate']:.1f}%, "
              f"{sx['tokens_per_step']:.2f} tokens/step over "
              f"{sx['rounds']:.0f} rounds; decode steps saved "
              f"p50={ds['p50']:.1f}% p95={ds['p95']:.1f}% "
              f"(rollbacks={sx['rollbacks']:.0f} "
              f"fallbacks={sx['fallbacks']:.0f} "
              f"draft_faults={sx['draft_faults']:.0f})")
        srp = sx["replay"]
        print(f"spec replay: {srp['replayed']} requests vs plain-decode "
              f"reference — "
              f"{'BIT-IDENTICAL' if srp['bit_identical'] else 'MISMATCH'}")
    print(f"graph lint ERRORs: {lint_errors}")

    slo_events = list(watchdog.events) if watchdog is not None else []
    if watchdog is not None:
        print(f"SLO burn-rate alerts fired: {len(slo_events)}")
        for ev in slo_events[:5]:
            print(f"  [{ev.severity}] {ev.rule}: {ev.message}")
    live_peaks = monitor.live_peaks()
    print(
        f"memstats [{provider.kind}]: live peak "
        f"{max(live_peaks.values(), default=0.0) / (1 << 20):.2f} MiB "
        f"vs static {max(static_peaks.values(), default=0.0) / (1 << 20):.2f}"
        f" MiB over {len(static_peaks)} program(s); "
        f"{len(memstats_findings)} drift finding(s)"
    )
    for f in memstats_findings:
        print(f"  DRIFT: {f['message']}")
    if ops is not None and final_scrape is not None:
        print(
            f"ops scrape: {final_scrape.get('bytes', 0)} bytes in "
            f"{final_scrape['ms']:.2f}ms "
            f"(mid-run: {'OK' if mid_scrape and mid_scrape.get('ok') else 'MISSED'})"
        )

    failures = []
    if bf["mean"] <= baseline_fill:
        failures.append(
            f"continuous batching not engaged: mean fill {bf['mean']:.3f} "
            f"<= single-request baseline {baseline_fill:.3f}"
        )
    for wire, rec in numerics.items():
        if not rec["ok"]:
            failures.append(
                f"{wire}-KV decode drifted {rec['max_abs_logit_diff']:.3e} "
                f"> {rec['tolerance']} from the unpaged f32 reference"
            )
    if "decode" not in lint_errors or not any(
        k.startswith("prefill") for k in lint_errors
    ):
        failures.append(
            f"analysis.check did not cover both steps: {sorted(lint_errors)}"
        )
    if any(lint_errors.values()):
        failures.append(f"graph lint ERRORs on serve steps: {lint_errors}")
    if args.prefix_cache:
        rp = load["prefix"]["replay"]
        if not rp["bit_identical"]:
            failures.append(
                f"prefix cache broke decode bit-identity: rids "
                f"{rp['mismatched_rids']} diverged from the uncached "
                f"reference"
            )
    if args.speculate:
        srp = load["spec"]["replay"]
        if not srp["bit_identical"]:
            failures.append(
                f"speculative decoding broke bit-identity: rids "
                f"{srp['mismatched_rids']} diverged from the "
                f"plain-decode reference"
            )

    if args.json:
        from apex_tpu.observability.spans import wall_clock_anchor

        artifact = {
            # the per-process monotonic→epoch anchor: lets this
            # artifact line up against span/flight records from the
            # same run when merged by tools/timeline.py
            "anchor": wall_clock_anchor(),
            "config": {
                k: getattr(args, k) for k in (
                    "requests", "rate", "prompt_mix", "output_mix",
                    "slo_ttft_ms", "batch", "page_size", "pages",
                    "pages_per_seq", "kv_wire", "weight_wire", "seed",
                    "prefix_cache", "shared_prefix_tokens",
                    "shared_frac", "chunk_tokens", "speculate",
                    "draft_layers",
                )
            },
            "load": load,
            "batch_fill_single_request_baseline": baseline_fill,
            "numerics_vs_unpaged_f32": numerics,
            "graph_lint_errors": lint_errors,
            "registry": {
                k: v for k, v in registry.values().items()
                if k.startswith("serve/")
            },
            "ttft_histogram": sched.ttft_hist.snapshot(),
            "ops": None if ops is None else {
                "port": ops.port,
                "url": ops.url,
                "mid_scrape": mid_scrape,
                "scrape": final_scrape,
            },
            "slo": None if watchdog is None else {
                "alerts_fired": len(slo_events),
                "windows": {
                    "short_s": args.slo_burn_short,
                    "long_s": args.slo_burn_long,
                    "factor": args.slo_burn_factor,
                },
                "events": [ev._asdict() for ev in slo_events],
            },
            "memstats": {
                "provider": provider.kind,
                "fake_scale": (
                    args.memstats_fake_scale
                    if provider.kind == "fake" else None
                ),
                "tolerance": args.memstats_tolerance,
                "live_peaks": live_peaks,
                "static_peaks": static_peaks,
                "watermark_samples": monitor.samples,
                "findings": memstats_findings,
            },
            "spans_file": args.spans,
            "failures": failures,
        }
        # strict JSON: an all-shed run yields NaN percentiles ("no
        # measurement"); encode them the flight-dump way instead of
        # emitting bare NaN tokens jq/JS parsers reject
        from apex_tpu.observability.flight import json_safe

        with open(args.json, "w") as f:
            json.dump(json_safe(artifact), f, indent=2, allow_nan=False)
            f.write("\n")
        print(f"[serve_bench] wrote {args.json}")

    if ops is not None:
        ops.stop()
    for msg in failures:
        print(f"FAIL {msg}", file=sys.stderr)
    return 1 if failures else 0


if __name__ == "__main__":
    sys.exit(main())
