"""On-chip LayerNorm block-size tuner — fills _TUNED_BLOCK_ROWS.

The reference's FastLayerNorm (apex/contrib/csrc/layer_norm/
ln_kernel_traits.h) hardcodes tuned kernel traits per hidden size; the TPU
analog is the row-block size of the Pallas LN kernels.  This sweeps
block_rows per hidden size on the real chip (fwd and fwd+bwd), prints a
table, and emits the dict literal to paste into
apex_tpu/ops/pallas/layer_norm.py::_TUNED_BLOCK_ROWS.

Run (on a TPU host):  python tools/ln_tune.py [--rows 16384]
"""

from __future__ import annotations

import argparse
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp

from apex_tpu.ops.pallas import layer_norm as ln

HIDDENS = [768, 1024, 1536, 2048, 3072, 4096, 5120, 6144, 8192]
BLOCKS = [8, 16, 32, 64, 128, 256]


def _time_scan(step, x, args, iters=24, trials=3):
    """Per-iteration time of ``step`` under a data-dependent lax.scan.

    Independent repeated calls mis-time over this environment's remote
    device tunnel (the host clock sees dispatch, not execution); a scan
    whose carry feeds each iteration's input from the previous one forces
    serialized device execution, so chunk_time/iters is honest.
    """

    @jax.jit
    def chunk(x):
        def body(carry, _):
            out = step(carry, *args)
            return out[0], out[1]
        carry, last = jax.lax.scan(body, x, None, length=iters)
        # f32 scalar the host pulls to prove the chunk executed: the
        # remote runtime has been observed returning early from bare
        # block_until_ready (attn_tune's r5 under-wait caveat), while a
        # value fetch cannot complete before the producing execution.
        return carry, jnp.sum(last.astype(jnp.float32))

    carry, sync = chunk(x)
    float(sync)  # warmup/compile, synced
    times = []
    for _ in range(trials):
        t0 = time.perf_counter()
        carry, sync = chunk(carry)
        float(sync)  # device->host: the sync point
        times.append((time.perf_counter() - t0) / iters)
    times.sort()
    return times[len(times) // 2]


def tune(rows, dtype=jnp.bfloat16):
    best = {}
    print(f"rows={rows} dtype={dtype.__name__} backend={jax.default_backend()}")
    print(f"{'hidden':>7} " + " ".join(f"br={b:<4d}" for b in BLOCKS)
          + "  best (fwd+bwd us)")
    for hidden in HIDDENS:
        key = jax.random.PRNGKey(0)
        x = jax.random.normal(key, (rows, hidden), dtype)
        w = jnp.ones((hidden,), dtype)
        b = jnp.zeros((hidden,), dtype)
        times = []
        for br in BLOCKS:
            if br * hidden * 4 > 8_000_000:  # > ~8MB per VMEM buffer: skip
                times.append(float("inf"))
                continue
            try:
                g = jnp.ones_like(x)

                def step(x, w, b, g, _br=br):
                    """fwd+bwd; returns (dx, scalar) — dx feeds the next
                    scan iteration so device work serializes."""
                    y, mu, rstd = ln.layer_norm_fwd(
                        x, w, b, eps=1e-5, rms=False, block_rows=_br
                    )
                    dx, dw, db = ln.layer_norm_bwd(
                        x, w, b, mu, rstd, g, rms=False,
                        x_is_output=False, block_rows=_br,
                    )
                    # mix y in so neither pass can be DCE'd
                    return dx + y * 1e-6, jnp.sum(dw)

                t = _time_scan(step, x, (w, b, g))
                times.append(t)
            except Exception as e:
                print(f"  hidden={hidden} br={br} failed: {str(e)[:80]}")
                times.append(float("inf"))
        ibest = min(range(len(BLOCKS)), key=lambda i: times[i])
        best[hidden] = BLOCKS[ibest]
        cells = " ".join(
            f"{t * 1e6:7.0f}" if t != float("inf") else "      -"
            for t in times
        )
        print(f"{hidden:>7} {cells}  -> br={BLOCKS[ibest]}"
              f" ({times[ibest] * 1e6:.0f}us)")
    print("\n_TUNED_BLOCK_ROWS = {")
    for h, b in best.items():
        print(f"    {h}: {b},")
    print("}")
    return best


if __name__ == "__main__":
    ap = argparse.ArgumentParser()
    ap.add_argument("--rows", type=int, default=16384)
    args = ap.parse_args()
    tune(args.rows)
