#!/bin/sh
# Probe the axon TPU tunnel: exit 0 iff a tiny jit compile+execute completes.
# The tunnel's observed failure mode is accepting metadata calls
# (jax.devices()) while hanging on compile/execute, so the probe must run
# a real computation, under a hard timeout.
timeout "${1:-90}" python -c "
import jax, jax.numpy as jnp
x = jnp.ones((128, 128), jnp.bfloat16)
print(jax.jit(lambda a: (a @ a).sum())(x))
" >/dev/null 2>&1
