#!/usr/bin/env bash
# Tier-1 verification — the exact ROADMAP.md command, wrapped for CI.
#
# Runs the quick test tier on CPU, prints DOTS_PASSED (count of passing
# tests parsed from pytest's progress dots, the same metric the roadmap
# tracks), and exits non-zero on any failure.
#
# A second stage re-runs the comm-layer tests (tests/test_comm.py,
# tests/test_quantized_allreduce.py) with the 8-device CPU mesh forced
# at the SHELL level (JAX_PLATFORMS=cpu +
# --xla_force_host_platform_device_count=8) — the conftest sets the same
# env today, but the gradient-sync acceptance pins (fixed collective
# count, <=30% wire bytes, psum-tolerance numerics; see docs/comm.md)
# must not silently start skipping on their eight_devices fixture if
# that ever changes, and must run even when extra pytest args (e.g.
# `-m chaos`) filter them out of the main pass.
#
# A third stage drives the observability pipe end to end: the resilient
# example runs under injected chaos with --metrics-out, and the JSONL is
# asserted to parse, carry the bench-line schema with step/MFU/goodput
# keys, and reflect the injected skip count EXACTLY (docs/observability.md).
# Like the comm pass it hard-fails rather than silently skipping.
#
# A FLIGHT stage drives the crash-forensics path end to end
# (docs/observability.md): the resilient example runs under a
# persistent chaos NaN burst until the skip budget exhausts
# max_rollbacks (a RuntimeError by contract), and the stage asserts a
# flight dump exists, tools/flight_view.py parses it, and the dump's
# recorded skip/rollback counts EXACTLY match the JSONL goodput line.
#
# A fourth stage is the static-analysis gate (docs/analysis.md):
# tools/repo_lint.py greps apex_tpu/ for banned source patterns in
# jitted paths (incl. the sharding source rules: in_shardings=None,
# unpinned shard_map contractions), and tools/graph_lint.py builds the
# resilient example's ACTUAL compiled step and runs the
# apex_tpu.analysis passes over its jaxpr + optimized HLO — any
# ERROR-severity finding (host transfer, dropped donation, f64,
# collective mismatch) hard-fails.  A sharding/memory gate (ISSUE 9)
# then runs tools/shard_report.py against the same example on a MOCKED
# 8-device mesh (--xla_force_host_platform_device_count=8): the
# declared dp plan must prove out (params/scaler replicated, batch
# sharded over dp, only the declared gradient sync compiled) with zero
# ERRORs, and the static peak-HBM estimate must sit inside the 8 MiB
# budget without drifting to zero — both directions of drift fail.
# A kernel gate (ISSUE 10) then runs tools/kernel_lint.py over the
# three shipped Pallas kernels at their default configs (zero ERRORs,
# causal dead-tile waste < 0.15) and an attn_tune --prune --dry-run
# smoke: the compile-free cost model must keep the measured-best
# (1024, 1024) long-shape tile while eliminating >=30% of the sweep
# grid.
#
# A TRAIN stage proves the composable trainer (ISSUE 12,
# docs/training.md): tools/shard_report.py --target train builds the
# apex_tpu.train demo config at dp=2, tp=2, and dp=2 x tp=2 on the
# MOCKED 8-device mesh and must report zero ERRORs against the
# trainer's OWN derived rule table + collective plan (the compiled
# collective schedule EQUALS the declaration or the reshard pass
# fails), with a non-degenerate static peak inside the 64 MiB budget —
# drift in either direction (peak 0 = the estimator went blind; over
# budget = the build lied about memory) hard-fails.  The dp>=2 arms
# must come out mode=zero (the update-sharding heuristic genuinely
# chose ZeRO) with the flat optimizer state compiled SHARDED.
#
# A PERF stage guards the perf-observability contract
# (docs/observability.md "Attribution & roofline"):
#   1. the committed r03→r05 flash-attention flatline MUST be caught by
#      tools/bench_diff.py --fail-on-flat (and the same rounds must
#      pass the plain regression gate — no false positive);
#   2. short CPU bench configs (bench.py --config smoke / serve, plus
#      --config train3d --lint on the mocked 8-device mesh) run end to
#      end and their lines pass the schema gate against the committed
#      golden (key order, degenerate honesty vs the unit's dp=/tp=,
#      and the train3d rows' REQUIRED dp/tp >= 2 shapes);
#   3. tools/step_profile.py --target resilient emits
#      compute/collective/host-stall fractions summing to 1 +- 0.02
#      with roofline-vs-StepMeter MFU agreement within 5% (the ISSUE 6
#      acceptance line).
#
# A SERVE stage drives the inference path end to end
# (docs/serving.md): the serve example trains a tiny GPT with the
# resilient runner, restores the checkpoint from disk (asserting the
# restored tree is bit-exact — the train->serve handoff), and serves it
# through the AOT engine + paged KV cache + continuous-batching
# scheduler.  The stage asserts the emitted JSONL carries TTFT and
# tokens-per-s serving metrics, and that tools/graph_lint.py --target
# serve reports ZERO ERRORs on the compiled prefill/decode steps.
# A span-accounting gate (ISSUE 8) then runs tools/serve_bench.py with
# --spans and feeds the dump through tools/timeline.py --json: every
# admitted request must have a complete span chain with exactly one
# terminal event, per-request TTFT components must sum to the measured
# TTFT within 1ms, the per-reason shed counters must sum to the total
# on both the artifact and the registry, and the merged Perfetto trace
# must carry real events.
# A prefix-cache gate (ISSUE 17) then replays an 85%-shared Poisson
# workload with the content-addressed prefix cache + chunked prefill
# armed and asserts the headline win AND its correctness escort:
# cache-hit p50 TTFT <= 0.3x cold-miss p50 at equal load, >= 50% of
# prefill FLOPs saved, every completed request's token stream
# bit-identical to a cache-disabled replay, and every
# PagePool.leak_check clean with the cache holding pages.
#
# An OPS stage drives the live ops plane end to end
# (docs/observability.md "Live ops plane", ISSUE 11): serve_bench runs
# a Poisson load with --ops-port 0 --spans under a PLANTED deadline
# storm (--slo-ttft-ms 1: every admission blows the TTFT objective).
# The gate asserts (1) the artifact's end-of-run HTTP scrape is
# OpenMetrics-valid (ometrics.parse_exposition) and carries
# TTFT/queue/goodput/watermark families whose values EQUAL the
# artifact's registry section (the scrape ran after the final drain);
# (2) the fast-burn multi-window SLO alert fired as a critical
# HealthEvent AND landed as a health/slo_ttft instant in the span dump
# and the merged Perfetto trace; (3) the fake-provider memstats
# cross-check reconciles cleanly on the honest run, and a second run
# with --memstats-fake-scale 2.0 (a planted static-vs-live drift) is
# FLAGGED with a finding naming the governing program.
#
# A SERVE-CHAOS stage proves the serving resilience layer end to end
# (docs/serving.md "Failure semantics & degradation ladder", ISSUE 14):
# tools/serve_chaos_drill.py runs a fault-free Poisson reference, then
# the same load under an APEX_TPU_CHAOS-grammar storm firing all four
# serving chaos sites (serve.prefill raise, serve.decode raise+nan,
# serve.admission raise, serve.kv_alloc fail), then a deterministic
# overload-ladder probe (queue-cap fast-reject + max-new-tokens clamp)
# and a graceful drain.  The drill hard-fails unless: zero process
# deaths (it finishing IS the proof), PagePool.leak_check clean after
# every fault with the pool exactly empty at the end, every request in
# exactly one accounted terminal state, p99 TTFT <= 2x the fault-free
# reference, every injected fault visible on its ledger counter
# (engine_faults/rebuilds, shed_poisoned, admission/kv_alloc faults),
# the ladder rejecting exactly the over-cap burst excess, and the
# drain report clean.  The gate then re-proves chain completeness from
# the span dump via tools/timeline.py --json and re-asserts the
# headline numbers from the artifact.  The artifact is handed to the
# PERF stage (APEX_TPU_SERVE_CHAOS_ARTIFACT) so bench.py --config
# serve emits its serve_chaos_* golden rows from the SAME storm
# instead of paying a second one — which is why SERVE-CHAOS runs
# before PERF.
#
# A GOODPUT stage proves the preemptible-fleet I/O plane end to end
# (ISSUE 13, docs/goodput.md): tools/goodput_drill.py runs the
# resilient example's real programs through an APEX_TPU_CHAOS-style
# preemption storm — resumable-stream-fed, async-engine-checkpointed —
# and the gate asserts goodput >= 99%, a bit-identical resumed loss
# trajectory, checkpoint stall < 1% of wall time, intact-previous-
# checkpoint after a planted mid-write kill (tmp debris + markerless
# half-written step dir), ckpt/* spans on the timeline, and zero
# goodput_rules watchdog pages.  The same drill's numbers land as
# gated bench rows (bench.py --config goodput in the PERF stage reuses
# the GOODPUT stage's evidence artifact — which is why GOODPUT runs
# first — against the committed golden) so they can never go flat
# silently.
#
# A FLEET stage proves the multi-replica control plane end to end
# (docs/serving.md "Fleet operations", ISSUE 16): tools/fleet_drill.py
# runs a fault-free fixed-size fleet reference, then the same seeded
# Poisson load — with a 5x arrival spike — through an autoscaled fleet
# under an APEX_TPU_CHAOS-grammar storm firing all three fleet sites
# (fleet.router raise, fleet.replica_crash kill, fleet.preempt notice)
# plus a mid-load zero-downtime rolling deploy.  The drill hard-fails
# unless: every request reaches exactly one fleet-wide terminal, zero
# open spans, per-replica PagePool leak_check clean, p99 TTFT <= 2x
# the reference, every injected fault pinned on its fleet/* ledger
# counter, the re-route ledger agrees across router and replicas,
# >= 1 autoscaler scale-out AND scale-in on the health timeline, the
# rolling deploy updates every replica with ZERO accepted requests
# lost, and every replica's ops server binds a distinct port whose
# scrapes aggregate.  The gate then re-proves chain completeness from
# the span dump via tools/timeline.py --json, and hands the artifact
# to the PERF stage (APEX_TPU_FLEET_ARTIFACT) so bench.py --config
# fleet emits its fleet_* golden rows from the SAME storm — which is
# why FLEET runs before PERF.
#
# A CANARY stage proves canary-gated deploys end to end
# (docs/serving.md "Canary deploys", ISSUE 20): tools/canary_drill.py
# asserts golden-probe fingerprints are bit-exact across a
# same-weights rebuild yet flip on a SINGLE corrupted weight bit,
# runs clean canary deploys across independent seeds (ZERO false
# fail verdicts by contract — the one-sided drift tests + min-sample
# honesty floor must not page on the canary hold's own load skew),
# then plants a NaN-poisoned + decode-throttled deploy and asserts
# the drift verdict FAILS inside the window, the deploy halts and
# rolls the canary back to the incumbent weights (rollback
# fingerprint bit-exact), fleet/deploys_rolled_back bumps, ZERO
# requests are lost, and bad-weight exposure stays within the canary
# fraction.  The gate re-proves the exposure bound from the span dump
# alone via tools/timeline.py --json (account_canary over the
# validated `canary` routing annotations), and hands the artifact to
# the PERF stage (APEX_TPU_CANARY_ARTIFACT) so bench.py --config
# fleet emits the fleet_canary_* golden rows from the SAME drill —
# which is why CANARY runs before PERF.
#
# Usage:
#   tools/verify_tier1.sh              # quick tier + comm + obs + flight + lint + train + goodput + serve-chaos + fleet + canary + perf + serve + ops
#   tools/verify_tier1.sh -m chaos     # extra pytest args are passed through
#
# Env:
#   T1_LOG      log path        (default /tmp/_t1.log)
#   T1_TIMEOUT  seconds         (default 870)
#   T1_SKIP_COMM=1              skip the dedicated comm pass
#   T1_SKIP_OBS=1               skip the observability pass
#   T1_SKIP_FLIGHT=1            skip the flight-recorder pass
#   T1_SKIP_LINT=1              skip the static-analysis pass
#   T1_SKIP_TRAIN=1             skip the composable-trainer pass
#   T1_SKIP_PERF=1              skip the perf-gate pass
#   T1_SKIP_SERVE=1             skip the serving pass
#   T1_SKIP_OPS=1               skip the live-ops-plane pass
#   T1_SKIP_GOODPUT=1           skip the goodput storm-drill pass
#   T1_SKIP_SERVECHAOS=1        skip the serving chaos-drill pass
#   T1_SKIP_FLEET=1             skip the fleet control-plane drill pass
#   T1_SKIP_CANARY=1            skip the canary-deploy drill pass

set -o pipefail

REPO_ROOT="$(cd "$(dirname "$0")/.." && pwd)"
LOG="${T1_LOG:-/tmp/_t1.log}"
TIMEOUT="${T1_TIMEOUT:-870}"

cd "$REPO_ROOT" || exit 2
rm -f "$LOG"

timeout -k 10 "$TIMEOUT" env JAX_PLATFORMS=cpu \
    python -m pytest tests/ -q -m 'not slow' \
    --continue-on-collection-errors \
    -p no:cacheprovider -p no:xdist -p no:randomly \
    "$@" 2>&1 | tee "$LOG"
rc=${PIPESTATUS[0]}

dots=$(grep -aE '^[.FEsx]+( *\[ *[0-9]+%\])?$' "$LOG" | tr -cd . | wc -c)
echo "DOTS_PASSED=$dots"

comm_rc=0
if [ "${T1_SKIP_COMM:-0}" != "1" ]; then
    timeout -k 10 "$TIMEOUT" env JAX_PLATFORMS=cpu \
        XLA_FLAGS="--xla_force_host_platform_device_count=8" \
        python -m pytest tests/test_comm.py tests/test_quantized_allreduce.py \
        -q -m 'not slow' \
        -p no:cacheprovider -p no:xdist -p no:randomly \
        2>&1 | tee -a "$LOG"
    comm_rc=${PIPESTATUS[0]}
    # the acceptance pins may not pass by skipping: fail on any skips
    # (match the skipped count anywhere in the summary — an all-skipped
    # run prints "N skipped in ..." with no "passed" token at all)
    if tail -n 3 "$LOG" | grep -aqE '(^|[ ,])[0-9]+ skipped'; then
        echo "TIER1-COMM: FAIL (comm tests skipped — 8-device mesh missing?)"
        comm_rc=1
    elif [ "$comm_rc" -eq 0 ]; then
        echo "TIER1-COMM: PASS"
    else
        echo "TIER1-COMM: FAIL (pytest rc=$comm_rc)"
    fi
fi

obs_rc=0
if [ "${T1_SKIP_OBS:-0}" != "1" ]; then
    OBS_OUT="$(mktemp /tmp/_t1_obs.XXXXXX.jsonl)"
    OBS_DIR="$(mktemp -d /tmp/_t1_obs_ckpt.XXXXXX)"
    # grads:nan@7,8 -> exactly 2 skipped steps, 0 rollbacks; the JSONL
    # goodput line must reproduce those counts (ISSUE 3 acceptance)
    timeout -k 10 300 env JAX_PLATFORMS=cpu \
        APEX_TPU_CHAOS="grads:nan@7,8" \
        python examples/simple/resilient/train_resilient.py \
        --steps 20 --save-every 5 --dir "$OBS_DIR" \
        --metrics-out "$OBS_OUT" 2>&1 | tail -n 4 | tee -a "$LOG"
    obs_rc=${PIPESTATUS[0]}
    if [ "$obs_rc" -eq 0 ]; then
        python - "$OBS_OUT" <<'PYEOF' 2>&1 | tee -a "$LOG"
import json, sys
recs = [json.loads(l) for l in open(sys.argv[1]) if l.strip()]
assert recs, "metrics JSONL is empty"
for r in recs:
    assert list(r)[:4] == ["metric", "value", "unit", "vs_baseline"], r
    assert "step" in r, f"telemetry line without step key: {r}"
metrics = {r["metric"] for r in recs}
for need in ("train/step_time_ms", "train/mfu", "train/goodput",
             "train/loss", "amp/loss_scale", "guard/skipped"):
    assert need in metrics, f"missing metric {need}; have {sorted(metrics)}"
final = [r for r in recs if r["metric"] == "train/goodput" and "skipped" in r]
assert final, "no consolidated goodput line with event counts"
g = final[-1]
assert g["skipped"] == 2, f"goodput line skipped={g['skipped']}, chaos injected 2"
assert g["rollbacks"] == 0, f"goodput line rollbacks={g['rollbacks']}, expected 0"
assert g["value"] == (g["accepted"] - g["discarded"]) / (g["accepted"] + g["skipped"])
print(f"observability JSONL OK: {len(recs)} records, goodput={g['value']:.3f} "
      f"(skipped={g['skipped']}, rollbacks={g['rollbacks']})")
PYEOF
        obs_rc=${PIPESTATUS[0]}
    fi
    rm -rf "$OBS_DIR"
    if [ "$obs_rc" -eq 0 ]; then
        rm -f "$OBS_OUT"
        echo "TIER1-OBS: PASS"
    else
        # keep the JSONL that failed the assertions — it IS the evidence
        echo "TIER1-OBS: FAIL (rc=$obs_rc; metrics kept at $OBS_OUT)"
    fi
fi

flight_rc=0
if [ "${T1_SKIP_FLIGHT:-0}" != "1" ]; then
    FL_OUT="$(mktemp /tmp/_t1_flight.XXXXXX.jsonl)"
    FL_DIR="$(mktemp -d /tmp/_t1_flight_ckpt.XXXXXX)"
    # 5 consecutive NaN steps x (1 + max_rollbacks=3 replays) -> the
    # skip budget (rollback_after=5) exhausts and run_resilient raises;
    # the example must STILL leave a parseable black box.  Expected
    # ledger: skipped=20, rollbacks=3, in BOTH artifacts.
    timeout -k 10 300 env JAX_PLATFORMS=cpu \
        APEX_TPU_CHAOS="grads:nan@10,11,12,13,14" \
        python examples/simple/resilient/train_resilient.py \
        --steps 30 --save-every 5 --dir "$FL_DIR" \
        --metrics-out "$FL_OUT" 2>&1 | tail -n 3 | tee -a "$LOG"
    example_rc=${PIPESTATUS[0]}
    if [ "$example_rc" -eq 0 ]; then
        echo "TIER1-FLIGHT: example was expected to DIE (skip budget)" \
            | tee -a "$LOG"
        flight_rc=1
    else
        DUMP=$(ls "$FL_DIR"/flight/flight_*.json 2>/dev/null | tail -n 1)
        if [ -z "$DUMP" ]; then
            echo "TIER1-FLIGHT: no flight dump under $FL_DIR/flight" \
                | tee -a "$LOG"
            flight_rc=1
        else
            python tools/flight_view.py "$DUMP" --json 2>&1 | tee -a "$LOG"
            flight_rc=${PIPESTATUS[0]}
        fi
    fi
    if [ "$flight_rc" -eq 0 ]; then
        python - "$DUMP" "$FL_OUT" <<'PYEOF' 2>&1 | tee -a "$LOG"
import json, sys
dump = json.load(open(sys.argv[1]))
recs = [json.loads(l) for l in open(sys.argv[2]) if l.strip()]
final = [r for r in recs if r["metric"] == "train/goodput" and "skipped" in r]
assert final, "no consolidated goodput line in the JSONL"
g = final[-1]
fg = dump.get("goodput") or {}
assert "skip budget exhausted" in dump["reason"], dump["reason"]
# the black box and the telemetry stream must tell ONE story
for key in ("accepted", "skipped", "discarded", "rollbacks", "retries"):
    assert fg.get(key) == g[key], (
        f"flight {key}={fg.get(key)} vs goodput line {g[key]}")
assert g["skipped"] == 20 and g["rollbacks"] == 3, g
frames = dump["frames"]
assert frames, "flight dump has no frames"
tail = frames[-5:]
assert all(f["skipped"] for f in tail), "last frames must be the fatal streak"
fm = dump["final"]["metrics"]
assert fm.get("guard/consecutive_skips") == 5.0, fm
assert fm.get("guard/found_inf") == 1.0, fm
print(f"flight dump OK: reason={dump['reason'][:40]!r}... "
      f"skipped={fg['skipped']} rollbacks={fg['rollbacks']} "
      f"(== JSONL goodput line)")
PYEOF
        flight_rc=${PIPESTATUS[0]}
    fi
    if [ "$flight_rc" -eq 0 ]; then
        rm -rf "$FL_DIR"
        rm -f "$FL_OUT"
        echo "TIER1-FLIGHT: PASS"
    else
        # keep the artifacts that failed the assertions — the evidence
        echo "TIER1-FLIGHT: FAIL (rc=$flight_rc; metrics at $FL_OUT," \
            "dump dir $FL_DIR)"
    fi
fi

lint_rc=0
if [ "${T1_SKIP_LINT:-0}" != "1" ]; then
    # source-level lint: banned patterns in jitted paths (fast, no jax)
    python tools/repo_lint.py 2>&1 | tee -a "$LOG"
    lint_rc=${PIPESTATUS[0]}
    if [ "$lint_rc" -eq 0 ]; then
        # concurrency + replay-purity lint: lock discipline over every
        # threaded class and purity over the replay-critical modules —
        # any ERROR finding exits 1 (also jax-free)
        CLINT_JSON="${T1_CLINT_JSON:-/tmp/_t1_concurrency_lint.json}"
        python tools/concurrency_lint.py --json "$CLINT_JSON" \
            2>&1 | tee -a "$LOG"
        lint_rc=${PIPESTATUS[0]}
    fi
    if [ "$lint_rc" -eq 0 ]; then
        # graph lint: the resilient example's compiled step must carry
        # zero ERROR findings (exit 1 otherwise — the acceptance gate)
        LINT_JSON="${T1_LINT_JSON:-/tmp/_t1_graph_lint.json}"
        timeout -k 10 300 env JAX_PLATFORMS=cpu \
            python tools/graph_lint.py --target resilient \
            --json "$LINT_JSON" 2>&1 | tee -a "$LOG"
        lint_rc=${PIPESTATUS[0]}
    fi
    if [ "$lint_rc" -eq 0 ]; then
        # sharding & memory gate (ISSUE 9): prove the declared dp plan
        # on a mocked 8-device mesh — zero ERRORs, budget headroom, and
        # a non-degenerate estimate (peak 0 would mean the estimator
        # silently stopped seeing buffers: drift in EITHER direction
        # fails)
        SHARD_JSON="${T1_SHARD_JSON:-/tmp/_t1_shard_report.json}"
        SHARD_BUDGET=$((8 * 1024 * 1024))
        timeout -k 10 300 env JAX_PLATFORMS=cpu \
            XLA_FLAGS="--xla_force_host_platform_device_count=8" \
            python tools/shard_report.py --target resilient \
            --budget "$SHARD_BUDGET" --json "$SHARD_JSON" \
            2>&1 | tail -n 6 | tee -a "$LOG"
        lint_rc=${PIPESTATUS[0]}
        if [ "$lint_rc" -eq 0 ]; then
            python - "$SHARD_JSON" "$SHARD_BUDGET" <<'PYEOF' 2>&1 | tee -a "$LOG"
import json, sys
d = json.load(open(sys.argv[1]))
budget = int(sys.argv[2])
assert d["errors"] == 0, f"shard report carries {d['errors']} ERROR(s)"
peak = d["peak_hbm_bytes"]
assert 0 < peak <= budget, f"peak {peak} outside (0, {budget}] — estimator drift"
rows = {(r["program"], r["name"]): r for r in d["shard_plan"]}
w = rows[("resilient/compute_grads", "params/w")]
assert w["verdict"] == "ok" and w["sharding"] == "replicated", w
b0 = rows[("resilient/compute_grads", "batch/0")]
assert b0["verdict"] == "ok" and "devices=" in b0["sharding"], b0
for name in ("sharding", "reshard", "memory"):
    assert name in d["pass_timings"], d["pass_timings"]
print(f"shard report OK: peak_hbm={peak} bytes (budget {budget}), "
      f"{len(d['shard_plan'])} plan rows, dp plan proven on the 8-device mesh")
PYEOF
            lint_rc=${PIPESTATUS[0]}
        fi
    fi
    if [ "$lint_rc" -eq 0 ]; then
        # kernel gate (ISSUE 10, docs/analysis.md "Kernel passes"):
        # the three shipped Pallas kernels at their default configs
        # must carry zero ERROR findings (VMEM/tiling/coverage) and
        # the causal flash default must waste <15% of its live-tile
        # FLOPs on masked elements
        KLINT_JSON="${T1_KLINT_JSON:-/tmp/_t1_kernel_lint.json}"
        timeout -k 10 300 env JAX_PLATFORMS=cpu \
            python tools/kernel_lint.py --json "$KLINT_JSON" \
            --max-dead-tile 0.15 2>&1 | tail -n 10 | tee -a "$LOG"
        lint_rc=${PIPESTATUS[0]}
    fi
    if [ "$lint_rc" -eq 0 ]; then
        # attn_tune prune smoke: the compile-free cost model must keep
        # the measured-best (1024, 1024) long-shape tile while
        # eliminating >=30% of the default sweep grid — all without
        # touching a device
        PRUNE_OUT="$(mktemp /tmp/_t1_prune.XXXXXX.log)"
        timeout -k 10 300 env JAX_PLATFORMS=cpu \
            python tools/attn_tune.py --prune --dry-run --shapes long \
            > "$PRUNE_OUT" 2>&1
        lint_rc=$?
        if [ "$lint_rc" -eq 0 ]; then
            python - "$PRUNE_OUT" <<'PYEOF' 2>&1 | tee -a "$LOG"
import re, sys
text = open(sys.argv[1]).read()
sweeps = [(int(k), int(t)) for k, t in re.findall(r"keep (\d+)/(\d+)", text)]
assert sweeps, "no prune summary in attn_tune --dry-run output"
for kept, total in sweeps:
    assert total - kept >= 0.3 * total, (
        f"prune eliminated only {total - kept}/{total} cells (<30%)")
assert re.search(r"^ *KEEP +1024 +1024", text, re.M), (
    "prune dropped the known-good (1024, 1024) long-shape config")
print(f"attn_tune prune smoke OK: kept {sweeps} of the default grid, "
      "(1024, 1024) survives")
PYEOF
            lint_rc=${PIPESTATUS[0]}
        fi
        if [ "$lint_rc" -eq 0 ]; then
            rm -f "$PRUNE_OUT"
        else
            echo "TIER1-LINT: attn_tune prune smoke failed (output at" \
                "$PRUNE_OUT)" | tee -a "$LOG"
        fi
    fi
    if [ "$lint_rc" -eq 0 ]; then
        echo "TIER1-LINT: PASS"
    else
        echo "TIER1-LINT: FAIL (rc=$lint_rc; findings in ${LINT_JSON:-repo_lint output} / ${CLINT_JSON:-concurrency_lint} / ${SHARD_JSON:-shard_report})"
    fi
fi

train_rc=0
if [ "${T1_SKIP_TRAIN:-0}" != "1" ]; then
    TRAIN_BUDGET=$((64 * 1024 * 1024))
    for spec in "2 1 zero" "1 2 ddp" "2 2 zero"; do
        set -- $spec
        TDP=$1; TTP=$2; TMODE=$3
        [ "$train_rc" -ne 0 ] && break
        TRAIN_JSON="$(mktemp /tmp/_t1_train_${TDP}x${TTP}.XXXXXX.json)"
        timeout -k 10 300 env JAX_PLATFORMS=cpu \
            XLA_FLAGS="--xla_force_host_platform_device_count=8" \
            python tools/shard_report.py --target train \
            --dp "$TDP" --tp "$TTP" --budget "$TRAIN_BUDGET" \
            --json "$TRAIN_JSON" 2>&1 | tail -n 4 | tee -a "$LOG"
        train_rc=${PIPESTATUS[0]}
        if [ "$train_rc" -eq 0 ]; then
            python - "$TRAIN_JSON" "$TRAIN_BUDGET" "$TDP" "$TTP" "$TMODE" \
                <<'PYEOF' 2>&1 | tee -a "$LOG"
import json, sys
d = json.load(open(sys.argv[1]))
budget, dp, tp, mode = (int(sys.argv[2]), int(sys.argv[3]),
                        int(sys.argv[4]), sys.argv[5])
assert d["errors"] == 0, f"trainer report carries {d['errors']} ERROR(s)"
assert d["target"].endswith(f"dp{dp}tp{tp}/{mode}"), d["target"]
peak = d["peak_hbm_bytes"]
assert 0 < peak <= budget, f"peak {peak} outside (0, {budget}] — drift"
for name in ("sharding", "reshard", "memory"):
    assert name in d["pass_timings"], d["pass_timings"]
rows = {r["name"]: r for r in d["shard_plan"]}
assert all(r["verdict"] == "ok" for r in rows.values()), rows
if mode == "zero":
    # the heuristic chose ZeRO and the flat optimizer state COMPILED
    # sharded — the headline feature, proven from the artifact
    m = rows["state/opt/master"]
    assert "devices=" in m["sharding"], m
if tp > 1:
    assert "devices=" in rows["state/params/w1"]["sharding"], rows
print(f"train dp={dp} tp={tp} OK: mode={mode}, peak_hbm={peak} bytes, "
      f"{len(rows)} plan rows all conformant, schedule == declaration")
PYEOF
            train_rc=${PIPESTATUS[0]}
        fi
        if [ "$train_rc" -eq 0 ]; then
            rm -f "$TRAIN_JSON"
        else
            echo "TIER1-TRAIN: dp=$TDP tp=$TTP failed (report at" \
                "$TRAIN_JSON)" | tee -a "$LOG"
        fi
    done
    if [ "$train_rc" -eq 0 ]; then
        echo "TIER1-TRAIN: PASS"
    else
        echo "TIER1-TRAIN: FAIL (rc=$train_rc)"
    fi
fi

goodput_rc=0
if [ "${T1_SKIP_GOODPUT:-0}" != "1" ]; then
    # GOODPUT gate (ISSUE 13, docs/goodput.md): an APEX_TPU_CHAOS-style
    # preemption storm through the resilient example's REAL programs,
    # fed by the resumable stream, saved by the async engine.  The
    # drill itself hard-fails unless goodput >= 99%, the resumed loss
    # trajectory is bit-identical to the uninterrupted reference,
    # checkpoint stall < 1% of wall time, the planted mid-write kill
    # (orbax tmp debris + a markerless half-written step dir) leaves
    # the previous checkpoint as the resume anchor, ckpt spans land on
    # the timeline, and the goodput_rules watchdog stays quiet.  The
    # artifact assertions below re-prove the verdict from the evidence.
    GP_JSON="$(mktemp /tmp/_t1_goodput.XXXXXX.json)"
    GP_DIR="$(mktemp -d /tmp/_t1_goodput_drill.XXXXXX)"
    # APEX_TPU_LOCKSAN=1 arms the runtime lock-order sanitizer for the
    # whole storm: the artifact's "locksan" section must come back
    # armed, with acquisitions recorded and ZERO cycles
    timeout -k 10 420 env JAX_PLATFORMS=cpu XLA_FLAGS="" \
        APEX_TPU_LOCKSAN=1 \
        python tools/goodput_drill.py --steps 60 --preempt-every 12 \
        --dir "$GP_DIR" --json "$GP_JSON" 2>&1 | tail -n 5 | tee -a "$LOG"
    goodput_rc=${PIPESTATUS[0]}
    if [ "$goodput_rc" -eq 0 ]; then
        python - "$GP_JSON" <<'PYEOF' 2>&1 | tee -a "$LOG"
import json, sys
a = json.load(open(sys.argv[1]))
assert a["goodput"] >= 0.99, f"goodput {a['goodput']} under the 99% floor"
lt = a["loss_trajectory"]
assert lt["bit_exact"] and lt["max_abs_drift"] == 0.0, lt
assert lt["storm_steps"] == lt["ref_steps"] == a["steps"], lt
assert a["ckpt"]["stall_frac"] < 0.01, a["ckpt"]
assert a["accountant"]["resumes"] >= 3, a["accountant"]  # the storm ran
assert a["accountant"]["retries"] >= 1, a["accountant"]  # fault healed
pm = a["planted_midwrite"]
assert pm["previous_intact"] and pm["resume_ok"], pm
sc = a["stream_cursor"]
assert sc["restored_next_batch"] == sc["expected"], sc
assert a["spans"]["ckpt_write"] > 0 and a["spans"]["ckpt_snapshot"] > 0
assert a["watchdog_pages"] == [], a["watchdog_pages"]
ls = a["locksan"]
assert ls["armed"], "LOCKSAN was not armed for the drill"
assert ls["cycles"] == [], f"lock-order cycles: {ls['cycles']}"
assert ls["locks"], "sanitizer saw no TrackedLock acquisitions"
print(f"GOODPUT artifact OK: goodput={a['goodput']:.4f} over "
      f"{a['invocations']} invocations ({a['accountant']['resumes']} "
      f"preemption resumes), stall={a['ckpt']['stall_frac']:.4%}, "
      f"loss drift {lt['max_abs_drift']} over {lt['ref_steps']} steps, "
      f"mid-write plant ignored (anchor step {pm['latest_before']})")
PYEOF
        goodput_rc=${PIPESTATUS[0]}
    fi
    if [ "$goodput_rc" -eq 0 ]; then
        # keep the artifact: the PERF stage's `bench.py --config
        # goodput` reuses it (APEX_TPU_GOODPUT_ARTIFACT) instead of
        # paying a second full storm drill for the same numbers
        rm -rf "$GP_DIR"
        echo "TIER1-GOODPUT: PASS"
    else
        echo "TIER1-GOODPUT: FAIL (rc=$goodput_rc; artifact at $GP_JSON," \
            "drill dir $GP_DIR)"
    fi
fi

servechaos_rc=0
if [ "${T1_SKIP_SERVECHAOS:-0}" != "1" ]; then
    SC_JSON="$(mktemp /tmp/_t1_servechaos.XXXXXX.json)"
    SC_SPANS="$(mktemp /tmp/_t1_servechaos_spans.XXXXXX.json)"
    SC_TRACE="$(mktemp /tmp/_t1_servechaos_trace.XXXXXX.json)"
    # the drill hard-fails on its own acceptance set (deaths, leaks,
    # terminals, p99 bound, ledger pins, ladder, drain) — see the
    # header comment
    timeout -k 10 420 env JAX_PLATFORMS=cpu XLA_FLAGS="" \
        python tools/serve_chaos_drill.py \
        --json "$SC_JSON" --spans "$SC_SPANS" \
        2>&1 | tail -n 7 | tee -a "$LOG"
    servechaos_rc=${PIPESTATUS[0]}
    if [ "$servechaos_rc" -eq 0 ]; then
        # chain completeness re-proven from the span dump: every storm
        # + probe + drain request walked
        # queued -> ... [retrying ...] -> exactly one terminal
        timeout -k 10 120 env JAX_PLATFORMS=cpu \
            python tools/timeline.py --spans "$SC_SPANS" \
            --out "$SC_TRACE" --json 2>&1 | tee -a "$LOG"
        servechaos_rc=${PIPESTATUS[0]}
    fi
    if [ "$servechaos_rc" -eq 0 ]; then
        python - "$SC_JSON" "$SC_SPANS" <<'PYEOF' 2>&1 | tee -a "$LOG"
import json, sys
a = json.load(open(sys.argv[1]))
spans = json.load(open(sys.argv[2]))
assert a["process_deaths"] == 0
assert len(a["chaos_sites"]) == 4, a["chaos_sites"]  # all four serve sites
t = a["terminals"]
assert t["accounted"] and t["completed"] + t["shed"] == t["offered"], t
assert t["open_spans"] == 0, t
p = a["pages"]
assert p["pool_in_use_end"] == 0, p
assert p["leak_checks_run"] > 0, p
infl = a["p99_ttft_inflation"]
assert infl == infl and infl <= 2.0, f"p99 inflation {infl}"
assert a["engine"]["rebuilds"] >= 1, a["engine"]
reg = a["registry"]
assert reg.get("serve/shed_poisoned", 0) >= 1, "quarantine never fired"
assert reg.get("serve/retries", 0) >= 1, "no re-admission retries"
probe = a["overload_probe"]
assert probe["queue_full"] == probe["burst"] - probe["queue_cap"], probe
assert probe["clamped"] >= 2, probe
d = a["drain"]
assert d["drained"] and d["pool_in_use"] == 0 and d["shed_draining"] >= 1, d
# the retrying recovery phase is ON the span record, not just counted
names = {e["name"] for e in spans["spans"]}
assert "req/retrying" in names, sorted(names)
assert "req/clamped" in names, sorted(names)
print(f"SERVE-CHAOS artifact OK: {t['completed']}/{t['offered']} "
      f"terminal-accounted, p99 inflation {infl:.2f}x (<=2x), "
      f"{a['engine']['rebuilds']} rebuild(s), "
      f"{reg.get('serve/shed_poisoned', 0):.0f} quarantined, "
      f"{p['leak_checks_run']} leak checks clean")
PYEOF
        servechaos_rc=${PIPESTATUS[0]}
    fi
    if [ "$servechaos_rc" -eq 0 ]; then
        # keep SC_JSON: the PERF stage's bench --config serve reuses it
        # (APEX_TPU_SERVE_CHAOS_ARTIFACT) instead of a second storm
        rm -f "$SC_SPANS" "$SC_TRACE"
        echo "TIER1-SERVECHAOS: PASS"
    else
        echo "TIER1-SERVECHAOS: FAIL (rc=$servechaos_rc; artifacts at" \
            "$SC_JSON $SC_SPANS $SC_TRACE)"
    fi
fi

fleet_rc=0
if [ "${T1_SKIP_FLEET:-0}" != "1" ]; then
    FL_JSON="$(mktemp /tmp/_t1_fleet.XXXXXX.json)"
    FL_SPANS="$(mktemp /tmp/_t1_fleet_spans.XXXXXX.json)"
    # the drill hard-fails on its own acceptance set (terminals, leaks,
    # ledger pins, scale-out+in, zero-loss deploy, p99 bound, ops
    # aggregation) — see the header comment
    timeout -k 10 600 env JAX_PLATFORMS=cpu XLA_FLAGS="" \
        python tools/fleet_drill.py \
        --json "$FL_JSON" --spans "$FL_SPANS" \
        2>&1 | tail -n 8 | tee -a "$LOG"
    fleet_rc=${PIPESTATUS[0]}
    if [ "$fleet_rc" -eq 0 ]; then
        # chain completeness re-proven from the span dump: every storm
        # request walked queued -> [routed/retrying hops] -> exactly
        # one fleet-wide terminal, across every replica it visited
        timeout -k 10 120 env JAX_PLATFORMS=cpu \
            python tools/timeline.py --spans "$FL_SPANS" --json \
            2>&1 | tail -n 3 | tee -a "$LOG"
        fleet_rc=${PIPESTATUS[0]}
    fi
    if [ "$fleet_rc" -eq 0 ]; then
        python - "$FL_JSON" "$FL_SPANS" <<'PYEOF' 2>&1 | tee -a "$LOG"
import json, sys
a = json.load(open(sys.argv[1]))
spans = json.load(open(sys.argv[2]))
assert a["process_deaths"] == 0
assert len(a["chaos_sites"]) == 3, a["chaos_sites"]  # all three fleet sites
t = a["terminals"]
assert t["accounted"] and t["completed"] + t["shed"] == t["offered"], t
assert t["open_spans"] == 0 and t["span_drops"] == 0, t
assert all(v == 0 for v in a["pages"]["per_replica_in_use"].values()), \
    a["pages"]
infl = a["p99_ttft_inflation"]
assert infl == infl and infl <= 2.0, f"p99 inflation {infl}"
fr = a["fleet_registry"]
assert fr.get("fleet/replica_crashes", 0) >= 1, fr
assert fr.get("fleet/preempts", 0) >= 1, fr
assert fr.get("fleet/router_faults", 0) >= 1, fr
assert fr.get("fleet/scale_out", 0) >= 1, fr
assert fr.get("fleet/scale_in", 0) >= 1, fr
sc = a["autoscaler"]
assert sc["scale_out_events"] >= 1 and sc["scale_in_events"] >= 1, sc
assert a["deploys"] and all(
    d["lost_requests"] == 0 and d["updated"] for d in a["deploys"]
), a["deploys"]
# the re-route ledger agrees fleet-wide: router hops == replica sheds
assert a["aggregated_serve"].get("serve/shed_rerouted", 0) \
    == fr.get("fleet/rerouted", 0), (a["aggregated_serve"], fr)
ops = a["ops"]
assert ops["all_bound"] and ops["distinct_ports"], ops
assert ops["aggregated_sources"] == ops["servers"], ops
# the routed hop phase is ON the span record, not just counted
names = {e["name"] for e in spans["spans"]}
assert "req/routed" in names, sorted(names)
print(f"FLEET artifact OK: {t['completed']}/{t['offered']} "
      f"terminal-accounted across {len(a['replicas'])} replicas, "
      f"p99 inflation {infl:.2f}x (<=2x), crashes="
      f"{fr.get('fleet/replica_crashes', 0):.0f} preempts="
      f"{fr.get('fleet/preempts', 0):.0f} rerouted="
      f"{fr.get('fleet/rerouted', 0):.0f}, scale out/in="
      f"{sc['scale_out_events']}/{sc['scale_in_events']}, "
      f"{len(a['deploys'])} deploy(s) lost 0")
PYEOF
        fleet_rc=${PIPESTATUS[0]}
    fi
    if [ "$fleet_rc" -eq 0 ]; then
        # keep FL_JSON: the PERF stage's bench --config fleet reuses it
        # (APEX_TPU_FLEET_ARTIFACT) instead of a second storm
        rm -f "$FL_SPANS"
        echo "TIER1-FLEET: PASS"
    else
        echo "TIER1-FLEET: FAIL (rc=$fleet_rc; artifacts at" \
            "$FL_JSON $FL_SPANS)"
    fi
fi

canary_rc=0
if [ "${T1_SKIP_CANARY:-0}" != "1" ]; then
    CN_JSON="$(mktemp /tmp/_t1_canary.XXXXXX.json)"
    CN_SPANS="$(mktemp /tmp/_t1_canary_spans.XXXXXX.json)"
    # the drill hard-fails on its own acceptance set (fingerprint
    # bit-exactness + single-bit sensitivity, zero false verdicts on
    # clean deploys, planted-regression detection + bit-exact
    # rollback, zero lost requests, exposure bound) — see its header
    timeout -k 10 600 env JAX_PLATFORMS=cpu XLA_FLAGS="" \
        python tools/canary_drill.py \
        --json "$CN_JSON" --spans "$CN_SPANS" \
        2>&1 | tail -n 10 | tee -a "$LOG"
    canary_rc=${PIPESTATUS[0]}
    if [ "$canary_rc" -eq 0 ]; then
        # the exposure bound re-proven from the span dump alone: every
        # canary-annotated routing hop falls inside a deploy window,
        # and per window canary hops <= frac * routed + 1
        timeout -k 10 120 env JAX_PLATFORMS=cpu \
            python tools/timeline.py --spans "$CN_SPANS" --json \
            > /tmp/_t1_canary_timeline.json 2>>"$LOG"
        canary_rc=$?
    fi
    if [ "$canary_rc" -eq 0 ]; then
        python - "$CN_JSON" /tmp/_t1_canary_timeline.json \
            <<'PYEOF' 2>&1 | tee -a "$LOG"
import json, sys
a = json.load(open(sys.argv[1]))
tl = json.load(open(sys.argv[2]))
fp = a["fingerprints"]
assert fp["rebuild_bit_exact"], fp
assert fp["single_bit_flips_digest"], fp
assert fp["restore_matches"], fp
assert a["false_positives"] == 0, a["false_positives"]
frac = a["config"]["canary_frac"]
for run in a["clean_runs"]:
    d = run["deploys"][-1]
    assert d["canary"]["verdict"] == "pass", (run["label"], d)
    assert d["lost_requests"] == 0, (run["label"], d)
reg = a["regression"]
d = reg["deploys"][-1]
c = d["canary"]
assert d["rolled_back"] and c["verdict"] == "fail", d
assert reg["rolled_back"] == 1, reg["rolled_back"]
assert d["lost_requests"] == 0, d
assert c["rollback_digest"] == reg["incumbent_digest"], c
assert a["detect_ticks"] is not None and a["detect_ticks"] > 0
# the timeline's independent re-derivation: one pass + one fail
# window, both within the canary fraction
assert tl["ok"], tl["violations"]
wins = tl["canary"]["windows"]
verdicts = sorted(w["verdict"] for w in wins)
assert verdicts == ["fail", "pass"], wins
for w in wins:
    assert w["closed"], w
    assert w["canary_routed"] <= w["frac"] * w["routed"] + 1, w
    assert w["frac"] == frac, (w, frac)
print(f"CANARY artifact OK: fingerprint bit-exact + single-bit "
      f"sensitive, {len(a['clean_runs'])} clean deploys 0 false "
      f"verdicts, regression detected in {a['detect_ticks']} ticks "
      f"and rolled back bit-exact, exposure "
      f"{max(w['exposure_frac'] for w in wins):.3f} <= {frac} "
      f"re-proven from {len(wins)} span-dump windows")
PYEOF
        canary_rc=${PIPESTATUS[0]}
    fi
    if [ "$canary_rc" -eq 0 ]; then
        # keep CN_JSON: the PERF stage's bench --config fleet reuses it
        # (APEX_TPU_CANARY_ARTIFACT) instead of a second drill
        rm -f "$CN_SPANS" /tmp/_t1_canary_timeline.json
        echo "TIER1-CANARY: PASS"
    else
        echo "TIER1-CANARY: FAIL (rc=$canary_rc; artifacts at" \
            "$CN_JSON $CN_SPANS)"
    fi
fi

perf_rc=0
if [ "${T1_SKIP_PERF:-0}" != "1" ]; then
    # 1a. the flatline catch: r03 vs r05 sat at 43 TFLOP/s — the gate
    #     MUST exit non-zero on these committed artifacts
    if python tools/bench_diff.py BENCH_all_r05.json \
        --baseline BENCH_all_r03.json --fail-on-flat \
        >/dev/null 2>>"$LOG"; then
        echo "TIER1-PERF: bench_diff failed to catch the committed" \
            "r03->r05 flash flatline" | tee -a "$LOG"
        perf_rc=1
    fi
    # 1b. ...and no false positive from the plain regression gate
    if [ "$perf_rc" -eq 0 ]; then
        python tools/bench_diff.py BENCH_all_r05.json \
            --baseline BENCH_all_r03.json --fail-on-regression \
            2>&1 | tail -n 2 | tee -a "$LOG"
        perf_rc=${PIPESTATUS[0]}
    fi
    # 2. short CPU bench configs + schema gate vs the committed golden
    #    (smoke + serve append into ONE file: the golden carries both
    #    metric sets, so --require-same-metrics needs both runs)
    if [ "$perf_rc" -eq 0 ]; then
        PERF_OUT="$(mktemp /tmp/_t1_perf.XXXXXX.jsonl)"
        timeout -k 10 300 env JAX_PLATFORMS=cpu XLA_FLAGS="" \
            APEX_TPU_BENCH_WATCHDOG_S=0 \
            python bench.py --config smoke --metrics-out "$PERF_OUT" \
            2>&1 | tail -n 2 | tee -a "$LOG"
        perf_rc=${PIPESTATUS[0]}
        if [ "$perf_rc" -eq 0 ]; then
            # the serve config's serve_chaos_* rows reuse the
            # SERVE-CHAOS stage's evidence artifact (one storm per CI
            # pass); with the stage skipped or failed the bench runs
            # its own drill
            SC_REUSE=""
            if [ "${T1_SKIP_SERVECHAOS:-0}" != "1" ] \
                && [ "$servechaos_rc" -eq 0 ] && [ -s "${SC_JSON:-}" ]; then
                SC_REUSE="$SC_JSON"
            fi
            timeout -k 10 300 env JAX_PLATFORMS=cpu XLA_FLAGS="" \
                APEX_TPU_BENCH_WATCHDOG_S=0 \
                APEX_TPU_SERVE_CHAOS_ARTIFACT="$SC_REUSE" \
                python bench.py --config serve --metrics-out "$PERF_OUT" \
                2>&1 | tail -n 2 | tee -a "$LOG"
            perf_rc=${PIPESTATUS[0]}
            [ -n "$SC_REUSE" ] && rm -f "$SC_REUSE"
        fi
        # the trainer's honest multi-device rows (ISSUE 12): built on
        # the MOCKED 8-device mesh with --lint, so the golden stream
        # carries dp/tp >= 2 shapes the schema gate REQUIRES (a
        # degenerate train3d row is a schema failure, not an exclusion)
        if [ "$perf_rc" -eq 0 ]; then
            timeout -k 10 300 env JAX_PLATFORMS=cpu \
                XLA_FLAGS="--xla_force_host_platform_device_count=8" \
                APEX_TPU_BENCH_WATCHDOG_S=0 \
                python bench.py --config train3d --lint \
                --metrics-out "$PERF_OUT" \
                2>&1 | tail -n 2 | tee -a "$LOG"
            perf_rc=${PIPESTATUS[0]}
        fi
        # the goodput acceptance rows (ISSUE 13): the chaos-storm
        # drill's numbers ride the same golden/schema stream, so storm
        # goodput / zero-stall / bit-exact-resume can never go flat or
        # vanish silently.  The GOODPUT stage (which runs first) hands
        # its evidence artifact over so this pass emits rows from the
        # ONE drill already run; with the stage skipped or failed the
        # bench falls back to running the drill itself.
        if [ "$perf_rc" -eq 0 ]; then
            GP_REUSE=""
            if [ "${T1_SKIP_GOODPUT:-0}" != "1" ] \
                && [ "$goodput_rc" -eq 0 ] && [ -s "${GP_JSON:-}" ]; then
                GP_REUSE="$GP_JSON"
            fi
            timeout -k 10 420 env JAX_PLATFORMS=cpu XLA_FLAGS="" \
                APEX_TPU_BENCH_WATCHDOG_S=0 \
                APEX_TPU_GOODPUT_ARTIFACT="$GP_REUSE" \
                python bench.py --config goodput --metrics-out "$PERF_OUT" \
                2>&1 | tail -n 2 | tee -a "$LOG"
            perf_rc=${PIPESTATUS[0]}
            [ -n "$GP_REUSE" ] && rm -f "$GP_REUSE"
        fi
        # the fleet acceptance rows (ISSUE 16): the control-plane
        # storm's numbers ride the same golden/schema stream, so fleet
        # goodput / zero-loss deploys / p99 inflation can never go
        # flat or vanish silently.  The FLEET stage (which runs first)
        # hands its evidence artifact over so this pass emits rows
        # from the ONE storm already run; with the stage skipped or
        # failed the bench falls back to running the drill itself.
        if [ "$perf_rc" -eq 0 ]; then
            FL_REUSE=""
            if [ "${T1_SKIP_FLEET:-0}" != "1" ] \
                && [ "$fleet_rc" -eq 0 ] && [ -s "${FL_JSON:-}" ]; then
                FL_REUSE="$FL_JSON"
            fi
            # ...and the CANARY stage's artifact rides the same config
            # (fleet_canary_detect_ticks / fleet_canary_false_positive)
            CN_REUSE=""
            if [ "${T1_SKIP_CANARY:-0}" != "1" ] \
                && [ "$canary_rc" -eq 0 ] && [ -s "${CN_JSON:-}" ]; then
                CN_REUSE="$CN_JSON"
            fi
            timeout -k 10 600 env JAX_PLATFORMS=cpu XLA_FLAGS="" \
                APEX_TPU_BENCH_WATCHDOG_S=0 \
                APEX_TPU_FLEET_ARTIFACT="$FL_REUSE" \
                APEX_TPU_CANARY_ARTIFACT="$CN_REUSE" \
                python bench.py --config fleet --metrics-out "$PERF_OUT" \
                2>&1 | tail -n 3 | tee -a "$LOG"
            perf_rc=${PIPESTATUS[0]}
            [ -n "$FL_REUSE" ] && rm -f "$FL_REUSE"
            [ -n "$CN_REUSE" ] && rm -f "$CN_REUSE"
        fi
        if [ "$perf_rc" -eq 0 ]; then
            python tools/bench_diff.py "$PERF_OUT" \
                --baseline tools/bench_golden_cpu.jsonl \
                --check-schema --require-same-metrics \
                2>&1 | tail -n 2 | tee -a "$LOG"
            perf_rc=${PIPESTATUS[0]}
        fi
        if [ "$perf_rc" -eq 0 ]; then
            rm -f "$PERF_OUT"
        else
            echo "TIER1-PERF: smoke/schema gate failed (lines kept at" \
                "$PERF_OUT)" | tee -a "$LOG"
        fi
    fi
    # 3. the ISSUE 6 acceptance line: attribution fractions + MFU
    if [ "$perf_rc" -eq 0 ]; then
        SP_JSON="$(mktemp /tmp/_t1_stepprof.XXXXXX.json)"
        timeout -k 10 420 env JAX_PLATFORMS=cpu XLA_FLAGS="" \
            python tools/step_profile.py --target resilient --steps 5 \
            --json "$SP_JSON" 2>&1 | tail -n 4 | tee -a "$LOG"
        perf_rc=${PIPESTATUS[0]}
        if [ "$perf_rc" -eq 0 ]; then
            python - "$SP_JSON" <<'PYEOF' 2>&1 | tee -a "$LOG"
import json, sys
p = json.load(open(sys.argv[1]))
assert abs(p["fraction_sum"] - 1.0) <= 0.02, p["fraction_sum"]
assert set(p["fractions"]) == {"compute", "collective", "host_stall"}
assert p["mfu"]["agreement"] <= 0.05, p["mfu"]
assert p["roofline"][-1]["bucket"] == "total"
print(f"step_profile OK: fractions sum={p['fraction_sum']:.3f} "
      f"(source={p['source']}), mfu agreement="
      f"{p['mfu']['agreement']:.4f}")
PYEOF
            perf_rc=${PIPESTATUS[0]}
        fi
        if [ "$perf_rc" -eq 0 ]; then
            rm -f "$SP_JSON"
        else
            echo "TIER1-PERF: step_profile acceptance failed (json at" \
                "$SP_JSON)" | tee -a "$LOG"
        fi
    fi
    if [ "$perf_rc" -eq 0 ]; then
        echo "TIER1-PERF: PASS"
    else
        echo "TIER1-PERF: FAIL (rc=$perf_rc)"
    fi
fi

serve_rc=0
if [ "${T1_SKIP_SERVE:-0}" != "1" ]; then
    SV_OUT="$(mktemp /tmp/_t1_serve.XXXXXX.jsonl)"
    SV_DIR="$(mktemp -d /tmp/_t1_serve_demo.XXXXXX)"
    # train -> checkpoint -> restore (bit-exact assert inside) -> serve
    timeout -k 10 420 env JAX_PLATFORMS=cpu XLA_FLAGS="" \
        python examples/simple/serve/serve_gpt.py \
        --dir "$SV_DIR" --train-steps 8 --requests 5 \
        --metrics-out "$SV_OUT" 2>&1 | tail -n 5 | tee -a "$LOG"
    serve_rc=${PIPESTATUS[0]}
    if [ "$serve_rc" -eq 0 ]; then
        python - "$SV_OUT" <<'PYEOF' 2>&1 | tee -a "$LOG"
import json, sys
recs = [json.loads(l) for l in open(sys.argv[1]) if l.strip()]
assert recs, "serving metrics JSONL is empty"
metrics = {r["metric"] for r in recs}
for need in ("serve/ttft_ms", "serve/tokens_per_s", "serve/queue_depth",
             "serve/batch_fill", "serve/page_occupancy"):
    assert need in metrics, f"missing metric {need}; have {sorted(metrics)}"
def last(name):
    return [r for r in recs if r["metric"] == name][-1]["value"]
ttft = last("serve/ttft_ms")
tps = last("serve/tokens_per_s")
assert isinstance(ttft, (int, float)) and ttft > 0, f"ttft={ttft!r}"
assert isinstance(tps, (int, float)) and tps > 0, f"tokens/s={tps!r}"
assert last("serve/completed") == 5, last("serve/completed")
print(f"serving JSONL OK: {len(recs)} records, ttft={ttft:.2f}ms "
      f"tokens/s={tps:.1f}, 5/5 completed")
PYEOF
        serve_rc=${PIPESTATUS[0]}
    fi
    if [ "$serve_rc" -eq 0 ]; then
        # the decode/prefill AOT programs must lint clean (exit 1 on
        # any ERROR — the ISSUE 7 acceptance gate)
        SERVE_LINT_JSON="${T1_SERVE_LINT_JSON:-/tmp/_t1_serve_lint.json}"
        timeout -k 10 300 env JAX_PLATFORMS=cpu \
            python tools/graph_lint.py --target serve \
            --json "$SERVE_LINT_JSON" 2>&1 | tail -n 2 | tee -a "$LOG"
        serve_rc=${PIPESTATUS[0]}
    fi
    # span-accounting gate (ISSUE 8): a closed-loop serve_bench run
    # records every request's span chain; tools/timeline.py must prove
    # the record complete (one terminal per admitted request, TTFT
    # components summing to the measured TTFT within 1ms, zero ring
    # drops) and emit a Perfetto-loadable trace.
    if [ "$serve_rc" -eq 0 ]; then
        SB_JSON="$(mktemp /tmp/_t1_servebench.XXXXXX.json)"
        SB_SPANS="$(mktemp /tmp/_t1_spans.XXXXXX.json)"
        SB_TRACE="$(mktemp /tmp/_t1_trace.XXXXXX.json)"
        timeout -k 10 420 env JAX_PLATFORMS=cpu XLA_FLAGS="" \
            python tools/serve_bench.py --requests 8 \
            --json "$SB_JSON" --spans "$SB_SPANS" \
            2>&1 | tail -n 4 | tee -a "$LOG"
        serve_rc=${PIPESTATUS[0]}
        if [ "$serve_rc" -eq 0 ]; then
            timeout -k 10 120 env JAX_PLATFORMS=cpu \
                python tools/timeline.py --spans "$SB_SPANS" \
                --out "$SB_TRACE" --json 2>&1 | tee -a "$LOG"
            serve_rc=${PIPESTATUS[0]}
        fi
        if [ "$serve_rc" -eq 0 ]; then
            python - "$SB_JSON" "$SB_SPANS" "$SB_TRACE" <<'PYEOF' 2>&1 | tee -a "$LOG"
import json, sys
art = json.load(open(sys.argv[1]))
spans = json.load(open(sys.argv[2]))
trace = json.load(open(sys.argv[3]))
# the wall-clock anchor satellite: every artifact from the process
# carries the same monotonic->epoch offset
for name, d in (("serve_bench", art), ("spans", spans)):
    a = d.get("anchor") or {}
    assert {"monotonic", "epoch"} <= set(a), f"{name} missing anchor: {a}"
assert art["anchor"]["epoch"] == spans["anchor"]["epoch"], "anchor drift"
# TTFT attribution p95s appear BOTH in the artifact and on the registry
ta = art["load"]["ttft_attribution"]
for comp in ("queue_wait", "cached_prefill", "prefill", "contention"):
    assert "p95" in ta[f"{comp}_ms"], ta
    key = f"serve/ttft_{comp}_ms_p95"
    assert key in art["registry"], f"missing {key} on the registry board"
# per-reason shed breakdown sums to the shed total, both surfaces
req = art["load"]["requests"]
assert sum(req["shed_reasons"].values()) == req["shed"], req
reg = art["registry"]
assert sum(
    v for k, v in reg.items()
    if k.startswith("serve/shed_")
) == reg["serve/shed"], reg
# the merged trace is Chrome-trace-event JSON with real events
assert trace["traceEvents"], "empty Perfetto trace"
assert any(e.get("ph") == "X" for e in trace["traceEvents"])
print(f"span gate OK: {req['completed']}/{req['offered']} requests, "
      f"{len(trace['traceEvents'])} trace events, queue-wait p95="
      f"{ta['queue_wait_ms']['p95']:.2f}ms")
PYEOF
            serve_rc=${PIPESTATUS[0]}
        fi
        if [ "$serve_rc" -eq 0 ]; then
            rm -f "$SB_JSON" "$SB_SPANS" "$SB_TRACE"
        else
            echo "TIER1-SERVE: span-accounting gate failed (artifacts" \
                "at $SB_JSON $SB_SPANS $SB_TRACE)" | tee -a "$LOG"
        fi
    fi
    # prefix-cache gate (ISSUE 17): an 85%-shared Poisson workload with
    # the content-addressed prefix cache armed must prove the headline
    # win — cache-hit p50 TTFT <= 0.3x cold-miss p50 at equal load,
    # >= 50% of prefill FLOPs saved — AND prove it did not buy speed
    # with correctness: the replay harness re-decodes every completed
    # request on a cache-disabled scheduler and demands bit-identical
    # token streams, and every leak_check (one per drained step plus
    # final drain) must have passed with the cache holding pages.
    if [ "$serve_rc" -eq 0 ]; then
        PFX_JSON="$(mktemp /tmp/_t1_prefix.XXXXXX.json)"
        timeout -k 10 420 env JAX_PLATFORMS=cpu XLA_FLAGS="" \
            python tools/serve_bench.py --requests 20 --rate 40 \
            --prompt-mix 72 80 --output-mix 4 8 --pages 120 \
            --prefix-cache --shared-prefix-tokens 64 --shared-frac 0.85 \
            --chunk-tokens 16 --json "$PFX_JSON" \
            2>&1 | tail -n 4 | tee -a "$LOG"
        serve_rc=${PIPESTATUS[0]}
        if [ "$serve_rc" -eq 0 ]; then
            python - "$PFX_JSON" <<'PYEOF' 2>&1 | tee -a "$LOG"
import json, sys
art = json.load(open(sys.argv[1]))
pfx = art["load"]["prefix"]
assert pfx["hit_requests"] > 0, pfx
assert pfx["miss_requests"] > 0, pfx
hit = pfx["hit_ttft_ms"]["p50"]
miss = pfx["miss_ttft_ms"]["p50"]
ratio = hit / miss
assert ratio <= 0.3, (
    f"hit p50 {hit:.2f}ms vs miss p50 {miss:.2f}ms -> ratio "
    f"{ratio:.3f} > 0.3: prefix cache is not paying for itself")
saved = pfx["prefill_flops_saved_pct"]
assert saved >= 50.0, f"prefill FLOPs saved {saved:.1f}% < 50%"
rp = pfx["replay"]
assert rp["bit_identical"], (
    f"cached decode diverged from uncached reference: {rp}")
assert pfx["leak_checks_run"] > 0, pfx
assert pfx["cache"]["commits"] > 0, pfx
print(f"prefix gate OK: {pfx['hit_requests']} hit / "
      f"{pfx['miss_requests']} miss, hit p50 {hit:.2f}ms vs miss "
      f"{miss:.2f}ms (ratio {ratio:.3f}), FLOPs saved {saved:.1f}%, "
      f"replay bit-identical over {rp['replayed']} requests, "
      f"{pfx['leak_checks_run']} leak checks clean")
PYEOF
            serve_rc=${PIPESTATUS[0]}
        fi
        if [ "$serve_rc" -eq 0 ]; then
            rm -f "$PFX_JSON"
        else
            echo "TIER1-SERVE: prefix-cache gate failed (artifact at" \
                "$PFX_JSON)" | tee -a "$LOG"
        fi
    fi
    # speculative-decode gate (ISSUE 18): a friendly (self-draft)
    # speculative run at k=4 must prove the headline — >= 1.5 emitted
    # tokens per decode step — WITHOUT buying speed with correctness:
    # the replay harness re-decodes every completed request on a
    # speculation-free engine and demands bit-identical streams, and a
    # planted serve.draft fault storm (raise at two draft rounds) must
    # leave every stream intact and the pool leak-clean with
    # draft-namespace pages in flight.
    if [ "$serve_rc" -eq 0 ]; then
        SPEC_JSON="$(mktemp /tmp/_t1_spec.XXXXXX.json)"
        timeout -k 10 420 env JAX_PLATFORMS=cpu XLA_FLAGS="" \
            APEX_TPU_CHAOS="serve.draft:raise@1,3" \
            python tools/serve_bench.py --requests 10 \
            --output-mix 8 12 --speculate 4 --json "$SPEC_JSON" \
            2>&1 | tail -n 5 | tee -a "$LOG"
        serve_rc=${PIPESTATUS[0]}
        if [ "$serve_rc" -eq 0 ]; then
            python - "$SPEC_JSON" <<'PYEOF' 2>&1 | tee -a "$LOG"
import json, sys
art = json.load(open(sys.argv[1]))
sp = art["load"]["spec"]
assert sp["k"] == 4 and sp["rounds"] > 0, sp
rp = sp["replay"]
assert rp["bit_identical"], (
    f"speculative decode diverged from plain reference: {rp}")
# self-draft greedy acceptance is exact except in the wake of the
# planted faults (a plain-fallback round leaves the draft KV one
# token behind until the next round's first column heals it)
assert sp["accept_rate"] >= 0.8, (
    f"self-draft greedy acceptance {sp['accept_rate']} < 0.8")
tps = sp["tokens_per_step"]
assert tps >= 1.5, f"spec tokens/decode-step {tps:.2f} < 1.5 at k=4"
assert sp["draft_faults"] >= 1, (
    f"planted serve.draft storm never landed: {sp}")
assert sp["leak_checks_run"] > 0, sp
print(f"spec gate OK: {sp['rounds']:.0f} rounds, accept rate "
      f"{100 * sp['accept_rate']:.1f}%, {tps:.2f} tokens/step, "
      f"{sp['draft_faults']:.0f} draft faults absorbed, replay "
      f"bit-identical over {rp['replayed']} requests, "
      f"{sp['leak_checks_run']} leak checks clean")
PYEOF
            serve_rc=${PIPESTATUS[0]}
        fi
        if [ "$serve_rc" -eq 0 ]; then
            rm -f "$SPEC_JSON"
        else
            echo "TIER1-SERVE: speculative-decode gate failed (artifact" \
                "at $SPEC_JSON)" | tee -a "$LOG"
        fi
    fi
    if [ "$serve_rc" -eq 0 ]; then
        rm -rf "$SV_DIR"
        rm -f "$SV_OUT"
        echo "TIER1-SERVE: PASS"
    else
        echo "TIER1-SERVE: FAIL (rc=$serve_rc; metrics at $SV_OUT," \
            "demo dir $SV_DIR)"
    fi
fi

ops_rc=0
if [ "${T1_SKIP_OPS:-0}" != "1" ]; then
    OPS_JSON="$(mktemp /tmp/_t1_ops.XXXXXX.json)"
    OPS_SPANS="$(mktemp /tmp/_t1_ops_spans.XXXXXX.json)"
    OPS_TRACE="$(mktemp /tmp/_t1_ops_trace.XXXXXX.json)"
    # the planted deadline storm: a 1ms TTFT objective every admission
    # blows, judged by an in-process-scaled (0.1s, 0.4s, 2x) window
    # pair — the fast-burn alert must fire DURING the run and land on
    # the span timeline beside the requests that blew the budget.  The
    # run must SPAN the long window's min_coverage (half of it) or the
    # tracker honestly reports no-evidence and nothing fires: 32
    # requests keep the run comfortably past 0.2s on a fast box.
    timeout -k 10 420 env JAX_PLATFORMS=cpu XLA_FLAGS="" \
        python tools/serve_bench.py --requests 32 --rate 300 \
        --output-mix 8 16 24 \
        --slo-ttft-ms 1 --slo-burn-short 0.1 --slo-burn-long 0.4 \
        --ops-port 0 --spans "$OPS_SPANS" --json "$OPS_JSON" \
        2>&1 | tail -n 6 | tee -a "$LOG"
    ops_rc=${PIPESTATUS[0]}
    if [ "$ops_rc" -eq 0 ]; then
        timeout -k 10 120 env JAX_PLATFORMS=cpu \
            python tools/timeline.py --spans "$OPS_SPANS" \
            --out "$OPS_TRACE" 2>&1 | tee -a "$LOG"
        ops_rc=${PIPESTATUS[0]}
    fi
    if [ "$ops_rc" -eq 0 ]; then
        python - "$OPS_JSON" "$OPS_SPANS" "$OPS_TRACE" <<'PYEOF' 2>&1 | tee -a "$LOG"
import json, sys
sys.path.insert(0, ".")
from apex_tpu.observability.ometrics import parse_exposition
art = json.load(open(sys.argv[1]))
spans = json.load(open(sys.argv[2]))
trace = json.load(open(sys.argv[3]))
# 1. the endpoint served OpenMetrics-valid text, live under load AND
#    after the final registry drain
ops = art["ops"]
assert ops["mid_scrape"] and ops["mid_scrape"]["ok"], ops["mid_scrape"]
assert ops["scrape"]["content_type"].startswith(
    "application/openmetrics-text"), ops["scrape"]["content_type"]
fams = parse_exposition(ops["scrape"]["text"])  # raises on violations
for need in ("apex_tpu_serve_ttft_ms", "apex_tpu_serve_ttft_hist_ms",
             "apex_tpu_serve_queue_depth", "apex_tpu_serve_completed",
             "apex_tpu_memstats_device0_peak_bytes_in_use"):
    assert need in fams, f"scrape missing {need}; have {len(fams)} families"
# the scrape's values EQUAL the artifact registry section (the scrape
# ran after the drain — zero-cadence staleness)
reg = art["registry"]
for key, fam in (("serve/completed", "apex_tpu_serve_completed"),
                 ("serve/shed", "apex_tpu_serve_shed"),
                 ("serve/queue_depth", "apex_tpu_serve_queue_depth"),
                 ("serve/ttft_ms", "apex_tpu_serve_ttft_ms")):
    assert fams[fam]["value"] == reg[key], (key, fams[fam]["value"], reg[key])
# 2. the storm fired the fast-burn SLO alert, critically, and it is ON
#    the timeline with the request spans
slo = art["slo"]
assert slo["alerts_fired"] >= 1, slo
ttft_alerts = [e for e in slo["events"] if e["rule"] == "slo_ttft"]
assert ttft_alerts and ttft_alerts[0]["severity"] == "critical", slo["events"]
health = [e for e in spans["spans"]
          if e.get("track") == "health" and e["name"] == "health/slo_ttft"]
assert health, "SLO alert missing from the span dump's health track"
assert any(e.get("name") == "health/slo_ttft"
           for e in trace["traceEvents"]), "alert not in the merged trace"
# 3. the honest fake-provider memstats run reconciles cleanly
mem = art["memstats"]
assert mem["provider"] == "fake", mem["provider"]  # CPU tier
assert mem["findings"] == [], mem["findings"]
assert mem["watermark_samples"] > 0
assert len(mem["static_peaks"]) >= 2, mem["static_peaks"]
print(f"OPS gate OK: {len(fams)} families served, "
      f"{slo['alerts_fired']} SLO alert(s) on the timeline, memstats "
      f"reconciled over {len(mem['static_peaks'])} static programs")
PYEOF
        ops_rc=${PIPESTATUS[0]}
    fi
    if [ "$ops_rc" -eq 0 ]; then
        # the planted static-vs-live drift: a fake watermark at 2x the
        # static peak MUST come back as a finding naming the program
        OPS_DRIFT="$(mktemp /tmp/_t1_ops_drift.XXXXXX.json)"
        timeout -k 10 300 env JAX_PLATFORMS=cpu XLA_FLAGS="" \
            python tools/serve_bench.py --requests 3 \
            --memstats-fake-scale 2.0 --json "$OPS_DRIFT" \
            2>&1 | tail -n 2 | tee -a "$LOG"
        ops_rc=${PIPESTATUS[0]}
        if [ "$ops_rc" -eq 0 ]; then
            python - "$OPS_DRIFT" <<'PYEOF' 2>&1 | tee -a "$LOG"
import json, sys
mem = json.load(open(sys.argv[1]))["memstats"]
assert mem["findings"], "planted 2x drift was NOT flagged"
f = mem["findings"][0]
assert f["direction"] == "static-under-predicts", f
assert f["program"], f
assert abs(f["ratio"] - 2.0) < 0.05, f
print(f"planted drift flagged OK: {f['program']} at {f['ratio']:.2f}x")
PYEOF
            ops_rc=${PIPESTATUS[0]}
        fi
        if [ "$ops_rc" -eq 0 ]; then
            rm -f "$OPS_DRIFT"
        else
            echo "TIER1-OPS: planted-drift check failed (artifact at" \
                "$OPS_DRIFT)" | tee -a "$LOG"
        fi
    fi
    if [ "$ops_rc" -eq 0 ]; then
        rm -f "$OPS_JSON" "$OPS_SPANS" "$OPS_TRACE"
        echo "TIER1-OPS: PASS"
    else
        echo "TIER1-OPS: FAIL (rc=$ops_rc; artifacts at $OPS_JSON" \
            "$OPS_SPANS $OPS_TRACE)"
    fi
fi

if [ "$rc" -eq 0 ] && [ "$comm_rc" -eq 0 ] && [ "$obs_rc" -eq 0 ] \
    && [ "$flight_rc" -eq 0 ] && [ "$lint_rc" -eq 0 ] \
    && [ "$train_rc" -eq 0 ] && [ "$perf_rc" -eq 0 ] \
    && [ "$serve_rc" -eq 0 ] && [ "$ops_rc" -eq 0 ] \
    && [ "$goodput_rc" -eq 0 ] && [ "$servechaos_rc" -eq 0 ] \
    && [ "$fleet_rc" -eq 0 ] && [ "$canary_rc" -eq 0 ]; then
    echo "TIER1: PASS"
else
    echo "TIER1: FAIL (pytest rc=$rc, comm rc=$comm_rc, obs rc=$obs_rc, flight rc=$flight_rc, lint rc=$lint_rc, train rc=$train_rc, perf rc=$perf_rc, serve rc=$serve_rc, ops rc=$ops_rc, goodput rc=$goodput_rc, serve-chaos rc=$servechaos_rc, fleet rc=$fleet_rc, canary rc=$canary_rc)"
fi
[ "$rc" -ne 0 ] && exit "$rc"
[ "$comm_rc" -ne 0 ] && exit "$comm_rc"
[ "$obs_rc" -ne 0 ] && exit "$obs_rc"
[ "$flight_rc" -ne 0 ] && exit "$flight_rc"
[ "$lint_rc" -ne 0 ] && exit "$lint_rc"
[ "$train_rc" -ne 0 ] && exit "$train_rc"
[ "$perf_rc" -ne 0 ] && exit "$perf_rc"
[ "$serve_rc" -ne 0 ] && exit "$serve_rc"
[ "$ops_rc" -ne 0 ] && exit "$ops_rc"
[ "$goodput_rc" -ne 0 ] && exit "$goodput_rc"
[ "$servechaos_rc" -ne 0 ] && exit "$servechaos_rc"
[ "$fleet_rc" -ne 0 ] && exit "$fleet_rc"
exit "$canary_rc"
