#!/usr/bin/env bash
# Tier-1 verification — the exact ROADMAP.md command, wrapped for CI.
#
# Runs the quick test tier on CPU, prints DOTS_PASSED (count of passing
# tests parsed from pytest's progress dots, the same metric the roadmap
# tracks), and exits non-zero on any failure.
#
# Usage:
#   tools/verify_tier1.sh              # full quick tier
#   tools/verify_tier1.sh -m chaos     # extra pytest args are passed through
#
# Env:
#   T1_LOG      log path        (default /tmp/_t1.log)
#   T1_TIMEOUT  seconds         (default 870)

set -o pipefail

REPO_ROOT="$(cd "$(dirname "$0")/.." && pwd)"
LOG="${T1_LOG:-/tmp/_t1.log}"
TIMEOUT="${T1_TIMEOUT:-870}"

cd "$REPO_ROOT" || exit 2
rm -f "$LOG"

timeout -k 10 "$TIMEOUT" env JAX_PLATFORMS=cpu \
    python -m pytest tests/ -q -m 'not slow' \
    --continue-on-collection-errors \
    -p no:cacheprovider -p no:xdist -p no:randomly \
    "$@" 2>&1 | tee "$LOG"
rc=${PIPESTATUS[0]}

dots=$(grep -aE '^[.FEsx]+( *\[ *[0-9]+%\])?$' "$LOG" | tr -cd . | wc -c)
echo "DOTS_PASSED=$dots"
if [ "$rc" -eq 0 ]; then
    echo "TIER1: PASS"
else
    echo "TIER1: FAIL (pytest rc=$rc)"
fi
exit "$rc"
