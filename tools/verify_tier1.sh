#!/usr/bin/env bash
# Tier-1 verification — the exact ROADMAP.md command, wrapped for CI.
#
# Runs the quick test tier on CPU, prints DOTS_PASSED (count of passing
# tests parsed from pytest's progress dots, the same metric the roadmap
# tracks), and exits non-zero on any failure.
#
# A second stage re-runs the comm-layer tests (tests/test_comm.py,
# tests/test_quantized_allreduce.py) with the 8-device CPU mesh forced
# at the SHELL level (JAX_PLATFORMS=cpu +
# --xla_force_host_platform_device_count=8) — the conftest sets the same
# env today, but the gradient-sync acceptance pins (fixed collective
# count, <=30% wire bytes, psum-tolerance numerics; see docs/comm.md)
# must not silently start skipping on their eight_devices fixture if
# that ever changes, and must run even when extra pytest args (e.g.
# `-m chaos`) filter them out of the main pass.
#
# Usage:
#   tools/verify_tier1.sh              # full quick tier + comm pass
#   tools/verify_tier1.sh -m chaos     # extra pytest args are passed through
#
# Env:
#   T1_LOG      log path        (default /tmp/_t1.log)
#   T1_TIMEOUT  seconds         (default 870)
#   T1_SKIP_COMM=1              skip the dedicated comm pass

set -o pipefail

REPO_ROOT="$(cd "$(dirname "$0")/.." && pwd)"
LOG="${T1_LOG:-/tmp/_t1.log}"
TIMEOUT="${T1_TIMEOUT:-870}"

cd "$REPO_ROOT" || exit 2
rm -f "$LOG"

timeout -k 10 "$TIMEOUT" env JAX_PLATFORMS=cpu \
    python -m pytest tests/ -q -m 'not slow' \
    --continue-on-collection-errors \
    -p no:cacheprovider -p no:xdist -p no:randomly \
    "$@" 2>&1 | tee "$LOG"
rc=${PIPESTATUS[0]}

dots=$(grep -aE '^[.FEsx]+( *\[ *[0-9]+%\])?$' "$LOG" | tr -cd . | wc -c)
echo "DOTS_PASSED=$dots"

comm_rc=0
if [ "${T1_SKIP_COMM:-0}" != "1" ]; then
    timeout -k 10 "$TIMEOUT" env JAX_PLATFORMS=cpu \
        XLA_FLAGS="--xla_force_host_platform_device_count=8" \
        python -m pytest tests/test_comm.py tests/test_quantized_allreduce.py \
        -q -m 'not slow' \
        -p no:cacheprovider -p no:xdist -p no:randomly \
        2>&1 | tee -a "$LOG"
    comm_rc=${PIPESTATUS[0]}
    # the acceptance pins may not pass by skipping: fail on any skips
    # (match the skipped count anywhere in the summary — an all-skipped
    # run prints "N skipped in ..." with no "passed" token at all)
    if tail -n 3 "$LOG" | grep -aqE '(^|[ ,])[0-9]+ skipped'; then
        echo "TIER1-COMM: FAIL (comm tests skipped — 8-device mesh missing?)"
        comm_rc=1
    elif [ "$comm_rc" -eq 0 ]; then
        echo "TIER1-COMM: PASS"
    else
        echo "TIER1-COMM: FAIL (pytest rc=$comm_rc)"
    fi
fi

if [ "$rc" -eq 0 ] && [ "$comm_rc" -eq 0 ]; then
    echo "TIER1: PASS"
else
    echo "TIER1: FAIL (pytest rc=$rc, comm rc=$comm_rc)"
fi
[ "$rc" -ne 0 ] && exit "$rc"
exit "$comm_rc"
