#!/bin/sh
# The staged on-chip queue (VERDICT r3 #2): run everything that needs the
# real TPU chip, in value order, with per-step logging — so a short
# tunnel window is never wasted deciding what to run.
#
#   sh tools/onchip_queue.sh [ROUND]
#
# Steps (each guarded by a fresh probe so a mid-queue outage skips the
# rest instead of hanging):
#   1. tests_tpu           — on-chip parity suite (incl. sums remat +
#                            compiled-dropout keep-mask cases)
#   2. mfu_sweep --grid2   — sums-policy A/B on the packed headline
#   3. attn_tune           — flash-attention (block_q, block_k) sweep
#   4. bench_all --round N — refresh BENCH_all_r{N}.json artifacts
# Logs land in onchip_r{N}.*.log at the repo root.
#
# If the grid2 A/B shows "sums" beating "dots" on step time / mfu_exec,
# re-run step 4 with the headline flipped — no code edit needed:
#   APEX_TPU_BENCH_POLICY=sums sh tools/onchip_queue.sh N   (or just
#   APEX_TPU_BENCH_POLICY=sums python tools/bench_all.py --round N)

set -u
ROUND="${1:-4}"
REPO="$(cd "$(dirname "$0")/.." && pwd)"
cd "$REPO" || exit 1

probe() {
    sh tools/tpu_probe.sh 120
}

step() {
    name="$1"; shift
    log="onchip_r$(printf %02d "$ROUND").$name.log"
    if ! probe; then
        echo "[$name] SKIPPED: probe failed (tunnel down)" | tee -a "$log"
        return 1
    fi
    echo "[$name] start $(date -u +%H:%M:%S)" | tee -a "$log"
    # 45 min cap per step: nothing in the queue legitimately needs more
    timeout 2700 "$@" >>"$log" 2>&1
    rc=$?
    echo "[$name] done rc=$rc $(date -u +%H:%M:%S)" | tee -a "$log"
    return $rc
}

step tests_tpu python -m pytest tests_tpu/ -q -p no:cacheprovider
step mfu_sweep python tools/mfu_sweep.py --grid2
step attn_tune python tools/attn_tune.py
step bench_all python tools/bench_all.py --round "$ROUND"
echo "queue finished $(date -u)"
