"""Serving chaos drill — the SERVE-CHAOS acceptance gate's engine.

Proves the serving resilience layer end to end (docs/serving.md
"Failure semantics & degradation ladder"): a Poisson load runs twice
through the REAL engine + continuous-batching scheduler — once
fault-free (the reference), once under an ``APEX_TPU_CHAOS``-style
storm injecting faults at all four serving chaos sites
(``serve.prefill``, ``serve.decode``, ``serve.admission``,
``serve.kv_alloc``) — and the drill asserts the four headline
guarantees:

1. **zero process deaths** — every fault is absorbed by the recovery
   machinery (bounded re-admission retries, poisoned-request
   quarantine, supervised background engine rebuild); the storm run
   completing IS the proof;
2. **zero leaked pages** — ``PagePool.leak_check`` runs after every
   shed/free path (``leak_checks=True``) and the pool is exactly empty
   once every request is terminal;
3. **every request exactly one accounted terminal** — completed + shed
   equals offered, no request span chain is left open, and (with
   ``--spans``) ``tools/timeline.py --json`` re-proves chain
   completeness from the dump;
4. **bounded p99 TTFT inflation** — storm p99 TTFT within
   ``--max-p99-inflation`` (default 2x) of the fault-free reference:
   graceful degradation, not collapse.  Both loads run on a
   deterministic virtual clock (one tick per scheduler iteration), so
   TTFT measures SCHEDULING delay — queue wait, retry round-trips,
   fault recovery — reproducibly per seed, immune to CI-runner
   weather; and the supervised rebuild is deferred off the traffic
   path precisely so a recompile never lands in anyone's TTFT.

An **overload probe** then walks the degradation ladder
deterministically (no timing dependence — a synchronous burst of
``3 x max_queue_depth`` submissions against a small queue cap):
rung 1 backpressure must fast-reject exactly the over-cap excess as
``shed(queue_full)``, rung 2 must clamp admissions to
``clamp_max_new_tokens`` (``serve/clamped``), and every probe request
still reaches exactly one terminal.

A final **drain phase** exercises the rolling-restart path on the
still-chaos-scarred scheduler: new work is submitted, admission is
stopped mid-flight (``drain()``), running decodes finish, the
never-admitted queue sheds loudly as ``shed(draining)``, and the pool
is re-proven empty.

``--json`` writes the evidence artifact (``bench.py --config serve``
reuses it via ``APEX_TPU_SERVE_CHAOS_ARTIFACT`` for its
``serve_chaos_*`` golden rows); ``--spans`` records every storm/drain
request's span chain for the timeline gate.

Usage::

    python tools/serve_chaos_drill.py --json /tmp/serve_chaos.json \
        --spans /tmp/serve_chaos_spans.json
"""

from __future__ import annotations

import argparse
import json
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

os.environ.setdefault("JAX_PLATFORMS", "cpu")

#: the default storm: every serving chaos site fires at least once,
#: through the SAME spec grammar / parser / hit accounting an
#: ``APEX_TPU_CHAOS`` env drill uses.  Indices are 0-based call
#: counters per site (prefill calls, decode iterations, admission
#: attempts, pool allocations).
#: stall-mode faults are deliberately absent: a 50ms injected hang is
#: bigger than the whole fault-free p99, so it belongs to the
#: deterministic unit tier (tests/test_serve.py pins the per-request
#: decode-timeout rung under a chaos stall), not to a drill whose
#: acceptance is a p99 bound.
DEFAULT_CHAOS_SPEC = (
    "serve.prefill:raise:x1@2;"
    "serve.decode:raise:x1@6;"
    "serve.decode:nan:x2@10,16;"
    "serve.admission:raise:x2@4,5;"
    "serve.kv_alloc:fail:x2@9,12"
)

#: injected fault counts per ledger counter the artifact must show —
#: derived from DEFAULT_CHAOS_SPEC (a custom --chaos skips the pins)
DEFAULT_EXPECTED = {
    "engine_faults": 2,      # 1 prefill raise + 1 decode raise
    "engine_rebuilds": 1,    # decode raise -> supervised rebuild
    "poisoned": 2,           # 2 nan decode iterations, 1 slot each
    "admission_faults": 2,
    "kv_alloc_faults": 2,
}


def build_engine(args, *, registry=None):
    import jax
    import jax.numpy as jnp

    from apex_tpu.models.gpt import GptConfig, GptModel
    from apex_tpu.serve import InferenceEngine, ServeConfig

    cfg = GptConfig(
        vocab_size=args.vocab, hidden_size=args.hidden,
        num_layers=args.layers, num_heads=args.heads,
        intermediate_size=2 * args.hidden, max_seq_len=256,
        dtype=jnp.float32,
    )
    serve_cfg = ServeConfig(
        page_size=args.page_size, num_pages=args.pages,
        max_batch=args.batch, max_pages_per_seq=args.pages_per_seq,
        verify=args.verify,
    )
    model = GptModel(cfg)
    params = model.init(
        jax.random.PRNGKey(1),
        jax.random.randint(jax.random.PRNGKey(0), (16, 1), 0,
                           cfg.vocab_size),
    )
    return InferenceEngine(cfg, params, serve_cfg,
                           registry=registry).build()


class VirtualClock:
    """A deterministic scheduler clock: one fixed tick per drill loop
    iteration.  Chaos injection is seeded and exact (``chaos.py``'s
    whole design); the drill's latency verdict must be too — measured
    on wall time, the p99-inflation ratio of two short runs is a coin
    flip against CI-runner hiccups an order of magnitude larger than a
    decode iteration.  On the virtual clock, TTFT measures SCHEDULING
    delay in iteration units (queue wait, retry round-trips, fault
    recovery) — exactly what the resilience layer controls — and the
    drill's numbers reproduce bit-for-bit per seed."""

    def __init__(self, tick_s: float = 0.005):
        self.t = 0.0
        self.tick_s = tick_s

    def __call__(self) -> float:
        return self.t

    def advance(self) -> None:
        self.t += self.tick_s


def run_load(sched, clock, args, *, label):
    """One closed-loop Poisson load (same shape as serve_bench's) on
    the drill's virtual clock: deterministic arrival/length draws
    under --seed.  (The per-request decode-timeout rung needs real
    elapsed time to fire and is pinned by the deterministic unit tier
    instead — tests/test_serve.py.)"""
    import numpy as np

    from apex_tpu.serve import Request

    rs = np.random.RandomState(args.seed)
    gaps = rs.exponential(1.0 / args.rate, size=args.requests)
    arrivals = np.cumsum(gaps)
    prompt_lens = rs.choice(args.prompt_mix, size=args.requests)
    out_lens = rs.choice(args.output_mix, size=args.requests)

    submitted = 0
    reqs = []
    while submitted < args.requests or sched.pending:
        now = clock()
        while submitted < args.requests and arrivals[submitted] <= now:
            reqs.append(sched.submit(Request(
                prompt=list(rs.randint(0, args.vocab,
                                       size=prompt_lens[submitted])),
                max_new_tokens=int(out_lens[submitted]),
            )))
            submitted += 1
        if sched.pending:
            sched.step()
        clock.advance()
    wall = clock()

    from apex_tpu.observability.meter import percentile

    done = [r for r in reqs if r.status == "done"]
    shed = [r for r in reqs if r.status == "shed"]
    ttfts = sorted(r.ttft_ms for r in done if r.ttft_ms is not None)
    shed_reasons = {}
    for r in shed:
        key = r.shed_reason or "?"
        shed_reasons[key] = shed_reasons.get(key, 0) + 1
    unterminated = [r.rid for r in reqs if r.status not in ("done", "shed")]
    return {
        "label": label,
        "offered": len(reqs),
        "completed": len(done),
        "shed": len(shed),
        "shed_reasons": shed_reasons,
        "unterminated": unterminated,
        "retries_total": sum(r.retries for r in reqs),
        "clamped": sum(1 for r in reqs if r.clamped_from is not None),
        "ttft_ms": {
            "p50": percentile(ttfts, 0.50),
            "p99": percentile(ttfts, 0.99),
            "samples": len(ttfts),
        },
        "wall_s": wall,
    }


def run_drill(args) -> dict:
    import numpy as np

    from apex_tpu.observability import MetricRegistry
    from apex_tpu.observability.spans import SpanRecorder, wall_clock_anchor
    from apex_tpu.resilience import chaos
    from apex_tpu.serve import ContinuousBatchingScheduler, Request

    faults, seed = chaos.parse_spec(args.chaos)
    sites = sorted({f.site for f in faults})

    # -- 1. fault-free reference ------------------------------------------
    ref_engine = build_engine(args)
    ref_clock = VirtualClock()
    ref_sched = ContinuousBatchingScheduler(
        ref_engine, registry=None, clock=ref_clock,
        max_queue_depth=args.max_queue_depth,
        clamp_max_new_tokens=args.clamp_max_new_tokens,
        clamp_occupancy=args.clamp_occupancy,
    )
    reference = run_load(ref_sched, ref_clock, args, label="reference")
    ref_sched.leak_check()

    # -- 2. the chaos storm ------------------------------------------------
    recorder = None
    if args.spans:
        recorder = SpanRecorder(capacity=args.span_capacity)
    registry = MetricRegistry(fetch_every=1)
    storm_engine = build_engine(args, registry=registry)
    storm_clock = VirtualClock()
    storm_sched = ContinuousBatchingScheduler(
        storm_engine, registry=registry, spans=recorder,
        clock=storm_clock,
        max_queue_depth=args.max_queue_depth,
        clamp_max_new_tokens=args.clamp_max_new_tokens,
        clamp_occupancy=args.clamp_occupancy,
    )
    with chaos.inject(*faults, seed=seed):
        storm = run_load(storm_sched, storm_clock, args, label="storm")
    storm_sched.leak_check()

    # -- 3. deterministic overload probe: the degradation ladder -----------
    # a synchronous burst against a small queue cap — no Poisson, no
    # clock dependence: exactly (burst - cap) submissions MUST
    # fast-reject at rung 1, and admissions under the backed-up queue
    # MUST clamp at rung 2.  Shares the storm's engine/registry/
    # recorder so the rung counters land on the same board and span
    # record the gate audits.
    probe_cap = 4
    probe_clamp = 4
    probe_sched = ContinuousBatchingScheduler(
        storm_engine, registry=registry, spans=recorder,
        clock=storm_clock,
        max_queue_depth=probe_cap,
        clamp_max_new_tokens=probe_clamp,
        clamp_queue_depth=2,
    )
    rs = np.random.RandomState(args.seed + 7)
    burst = [
        probe_sched.submit(Request(
            prompt=list(rs.randint(0, args.vocab, size=args.prompt_mix[0])),
            max_new_tokens=16,
        ))
        for _ in range(3 * probe_cap)
    ]
    probe_sched.run()
    probe = {
        "burst": len(burst),
        "queue_cap": probe_cap,
        "queue_full": sum(
            1 for r in burst if r.shed_reason == "queue_full"
        ),
        "clamped": sum(1 for r in burst if r.clamped_from is not None),
        "completed": sum(1 for r in burst if r.status == "done"),
        "unterminated": [
            r.rid for r in burst if r.status not in ("done", "shed")
        ],
    }
    probe_sched.leak_check()

    # -- 4. graceful drain on the storm-scarred scheduler ------------------
    rs = np.random.RandomState(args.seed + 1)
    drain_reqs = [
        storm_sched.submit(Request(
            prompt=list(rs.randint(0, args.vocab, size=args.prompt_mix[0])),
            max_new_tokens=8,
        ))
        for _ in range(args.drain_requests)
    ]
    storm_sched.step()
    drain_report = storm_sched.drain()
    drain_statuses = {}
    for r in drain_reqs:
        drain_statuses[r.status] = drain_statuses.get(r.status, 0) + 1
    drain_shed_draining = sum(
        1 for r in drain_reqs if r.shed_reason == "draining"
    )

    if recorder is not None:
        recorder.dump(reason="serve_chaos_drill", path=args.spans)

    registry.fetch()
    reg = {
        k: v for k, v in registry.values().items()
        if k.startswith("serve/")
    }

    ref_p99 = reference["ttft_ms"]["p99"]
    storm_p99 = storm["ttft_ms"]["p99"]
    inflation = (
        storm_p99 / ref_p99
        if ref_p99 and ref_p99 == ref_p99 and storm_p99 == storm_p99
        else float("nan")
    )
    offered_total = storm["offered"] + probe["burst"] + len(drain_reqs)
    done_total = len(storm_sched.completed) + len(probe_sched.completed)
    shed_total = len(storm_sched.shed) + len(probe_sched.shed)

    return {
        "anchor": wall_clock_anchor(),
        "config": {
            k: getattr(args, k) for k in (
                "requests", "rate", "prompt_mix", "output_mix", "seed",
                "batch", "page_size", "pages", "pages_per_seq",
                "max_queue_depth", "clamp_max_new_tokens",
                "drain_requests",
            )
        },
        "chaos_spec": args.chaos,
        "chaos_sites": sites,
        "reference": reference,
        "storm": storm,
        "overload_probe": probe,
        "p99_ttft_inflation": inflation,
        "process_deaths": 0,  # reaching this line IS the evidence
        "terminals": {
            "offered": offered_total,
            "completed": done_total,
            "shed": shed_total,
            "accounted": done_total + shed_total == offered_total,
            "open_spans": (
                len(recorder.open_requests) if recorder is not None else None
            ),
        },
        "pages": {
            "pool_in_use_end": storm_sched.pool.in_use,
            "leak_checks_run": storm_sched.leak_checks_run,
        },
        "engine": {
            "rebuilds": storm_engine.rebuilds,
            "compile_counts": dict(storm_engine.compile_counts),
        },
        "registry": reg,
        "drain": {
            **drain_report,
            "statuses": drain_statuses,
            "shed_draining": drain_shed_draining,
        },
        "spans_file": args.spans,
    }


def build_parser() -> argparse.ArgumentParser:
    ap = argparse.ArgumentParser(
        description="serving chaos drill (docs/serving.md "
        '"Failure semantics & degradation ladder")',
    )
    ap.add_argument("--requests", type=int, default=100)
    ap.add_argument("--rate", type=float, default=30.0,
                    help="Poisson arrival rate, requests/s (virtual "
                    "time; ~50%% decode-capacity utilization)")
    ap.add_argument("--prompt-mix", type=int, nargs="+",
                    default=[8, 16, 24], dest="prompt_mix")
    ap.add_argument("--output-mix", type=int, nargs="+",
                    default=[8, 16, 24], dest="output_mix")
    ap.add_argument("--vocab", type=int, default=64)
    ap.add_argument("--hidden", type=int, default=32)
    ap.add_argument("--layers", type=int, default=2)
    ap.add_argument("--heads", type=int, default=2)
    ap.add_argument("--batch", type=int, default=4)
    ap.add_argument("--page-size", type=int, default=8)
    ap.add_argument("--pages", type=int, default=64)
    ap.add_argument("--pages-per-seq", type=int, default=8)
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--verify", action="store_true",
                    help="run analysis verification at (re)build — "
                    "slower; the SERVE gate lints the same programs")
    ap.add_argument("--chaos", default=DEFAULT_CHAOS_SPEC,
                    help="APEX_TPU_CHAOS-grammar storm spec (default "
                    "fires all four serve sites)")
    ap.add_argument("--max-queue-depth", type=int, default=12)
    ap.add_argument("--clamp-max-new-tokens", type=int, default=12)
    ap.add_argument("--clamp-occupancy", type=float, default=0.6)
    ap.add_argument("--drain-requests", type=int, default=6)
    ap.add_argument("--max-p99-inflation", type=float, default=2.0)
    ap.add_argument("--json", default=None, metavar="OUT")
    ap.add_argument("--spans", default=None, metavar="OUT")
    ap.add_argument("--span-capacity", type=int, default=65536)
    return ap


def main(argv=None) -> int:
    args = build_parser().parse_args(argv)

    art = run_drill(args)
    if args.json:
        from apex_tpu.observability.flight import json_safe

        with open(args.json, "w") as f:
            json.dump(json_safe(art), f, indent=1, allow_nan=False)
            f.write("\n")

    ref, storm = art["reference"], art["storm"]
    print(
        "serve chaos drill: storm %d/%d completed (%d shed: %s), "
        "reference %d/%d"
        % (storm["completed"], storm["offered"], storm["shed"],
           ", ".join(f"{k}={v}"
                     for k, v in sorted(storm["shed_reasons"].items()))
           or "none",
           ref["completed"], ref["offered"])
    )
    print(
        "  p99 TTFT: storm %.2fms vs reference %.2fms (inflation "
        "%.2fx, bound %.1fx)"
        % (storm["ttft_ms"]["p99"], ref["ttft_ms"]["p99"],
           art["p99_ttft_inflation"], args.max_p99_inflation)
    )
    print(
        "  recovery: rebuilds=%d retries=%d readmitted=%d timeouts=%d "
        "clamped=%d; pages: in_use=%d leak_checks=%d"
        % (art["engine"]["rebuilds"],
           art["registry"].get("serve/retries", 0),
           art["registry"].get("serve/readmitted", 0),
           art["registry"].get("serve/decode_timeouts", 0),
           art["registry"].get("serve/clamped", 0),
           art["pages"]["pool_in_use_end"],
           art["pages"]["leak_checks_run"])
    )
    probe = art["overload_probe"]
    print(
        "  ladder probe: burst=%d cap=%d -> queue_full=%d clamped=%d "
        "completed=%d"
        % (probe["burst"], probe["queue_cap"], probe["queue_full"],
           probe["clamped"], probe["completed"])
    )
    print(
        "  drain: %s (shed_draining=%d)"
        % (art["drain"]["statuses"], art["drain"]["shed_draining"])
    )

    failures = []
    t = art["terminals"]
    if not t["accounted"]:
        failures.append(
            f"unaccounted terminals: {t['completed']}+{t['shed']} != "
            f"{t['offered']}"
        )
    if t["open_spans"]:
        failures.append(f"{t['open_spans']} request span chains left open")
    if storm["unterminated"]:
        failures.append(f"unterminated requests: {storm['unterminated']}")
    if art["pages"]["pool_in_use_end"] != 0:
        failures.append(
            f"leaked pages: pool in_use={art['pages']['pool_in_use_end']}"
        )
    infl = art["p99_ttft_inflation"]
    if not (infl == infl and infl <= args.max_p99_inflation):
        failures.append(
            f"p99 TTFT inflation {infl:.2f}x over the "
            f"{args.max_p99_inflation:.1f}x bound"
        )
    if args.chaos == DEFAULT_CHAOS_SPEC:
        reg = art["registry"]
        pins = {
            "serve/engine_faults": DEFAULT_EXPECTED["engine_faults"],
            "serve/engine_rebuilds": DEFAULT_EXPECTED["engine_rebuilds"],
            "serve/shed_poisoned": DEFAULT_EXPECTED["poisoned"],
            "serve/admission_faults": DEFAULT_EXPECTED["admission_faults"],
            "serve/kv_alloc_faults": DEFAULT_EXPECTED["kv_alloc_faults"],
        }
        for key, want in pins.items():
            if reg.get(key, 0) != want:
                failures.append(
                    f"{key}={reg.get(key, 0)} != injected {want} — a "
                    "fault fired without its ledger entry (or never "
                    "fired at all)"
                )
        if art["registry"].get("serve/retries", 0) < 1:
            failures.append("no re-admission retries under the storm")
    want_rejects = probe["burst"] - probe["queue_cap"]
    if probe["queue_full"] != want_rejects:
        failures.append(
            f"backpressure rung: {probe['queue_full']} queue_full "
            f"rejects != the over-cap excess {want_rejects}"
        )
    if probe["clamped"] < 2:
        failures.append(
            f"clamp rung: only {probe['clamped']} admissions clamped "
            "under a backed-up queue"
        )
    if probe["unterminated"]:
        failures.append(
            f"overload probe left unterminated requests: "
            f"{probe['unterminated']}"
        )
    if not art["drain"]["drained"] or art["drain"]["pool_in_use"] != 0:
        failures.append(f"drain not clean: {art['drain']}")
    if (
        art["config"]["drain_requests"] > art["config"]["batch"]
        and art["drain"]["shed_draining"] == 0
    ):
        failures.append("drain shed no queued request as 'draining'")

    for msg in failures:
        print(f"SERVE CHAOS DRILL FAIL: {msg}", file=sys.stderr)
    if not failures:
        print("serve chaos drill: PASS")
    return 1 if failures else 0


if __name__ == "__main__":
    sys.exit(main())
