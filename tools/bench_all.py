"""Run every BASELINE parity config and commit-ready artifact the results.

VERDICT r2 item 2: the numbers for all five BASELINE configs (plus the
long-context attention bench) existed each round but only the headline
made it into a committed artifact.  This wrapper runs ``bench.py
--config all`` and writes one JSON line per emitted metric to
``BENCH_all_r{N}.json`` at the repo root (N from --round, default 3),
leaving bench.py's own stdout contract (one JSON line per config run)
untouched for the driver.

Run on the real chip:  python tools/bench_all.py --round 3
"""

import argparse
import json
import os
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--round", type=int, default=3)
    ap.add_argument(
        "--configs", default="all",
        help="comma list of bench.py configs, or 'all'",
    )
    args = ap.parse_args()

    cmd = [sys.executable, os.path.join(REPO, "bench.py")]
    names = (
        ["all"] if args.configs == "all" else args.configs.split(",")
    )
    lines = []
    failed = False
    for name in names:
        proc = subprocess.run(
            cmd + ["--config", name],
            capture_output=True, text=True, cwd=REPO,
        )
        sys.stderr.write(proc.stderr)
        for ln in proc.stdout.splitlines():
            ln = ln.strip()
            if not ln.startswith("{"):
                continue
            try:
                rec = json.loads(ln)
            except json.JSONDecodeError:
                continue
            print(ln, flush=True)
            lines.append(rec)
        if proc.returncode != 0:
            failed = True
            print(
                f"[bench_all] config {name!r} exited "
                f"{proc.returncode}", file=sys.stderr,
            )

    out = os.path.join(REPO, f"BENCH_all_r{args.round:02d}.json")
    with open(out, "w") as f:
        for rec in lines:
            f.write(json.dumps(rec) + "\n")
    print(f"[bench_all] wrote {len(lines)} metric lines to {out}",
          file=sys.stderr)
    if failed or not lines:
        # a partial artifact must not read as a successful round
        sys.exit(1)


if __name__ == "__main__":
    main()
