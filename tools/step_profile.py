"""Step-time attribution + roofline for a real training step.

The "where do the milliseconds go" tool (docs/observability.md,
"Attribution & roofline"): builds a target's ACTUAL compiled step,
profiles a few steady-state executions, and decomposes the step two
ways that must agree —

- the compiled cost model (exact FLOPs/bytes per fused op, bucketed
  matmul / attention / norm-elementwise / collective / other through
  ``analysis/hlo.py``), and
- the measured profiler trace (exact time per op + the host-stall no
  kernel accounts for),

then prints compute/collective/host-stall fractions (summing to 1), a
per-bucket roofline (achieved FLOP/s vs the ``meter.py`` peak table,
arithmetic intensity, compute- vs bandwidth-bound verdict), the MFU
consistency pin against a live :class:`StepMeter` on the same run
(one denominator by design — the pin fails only if a second peak/FLOP
model sneaks in), and the trace-vs-host clock skew diagnostic.
The fractions land on the observability board, where the watchdog's
``CollectiveFractionRule`` / ``HostStallRule`` judge them — the tool
runs that judgment and prints any events.

Usage::

    python tools/step_profile.py --target resilient            # the CI target
    python tools/step_profile.py --target resilient --steps 12 \
        --json profile.json --metrics-out attr.jsonl
    python tools/step_profile.py --hlo bert_step.hlo           # cost model only
                                                               # (bench --hlo-out)

Exit code 0; the machine-readable artifact (``--json``) carries the
fractions, bucket shares, roofline rows, and the MFU agreement — what
the verify_tier1.sh PERF pass asserts on.
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import sys
import tempfile

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

os.environ.setdefault("JAX_PLATFORMS", "cpu")

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _load_resilient_module():
    """Import the example script as a module (same loader as
    tools/graph_lint.py — the example lives outside the package tree
    on purpose)."""
    import importlib.util

    path = os.path.join(
        REPO, "examples", "simple", "resilient", "train_resilient.py"
    )
    spec = importlib.util.spec_from_file_location("train_resilient", path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def profile_resilient(args):
    """Build the resilient example's real step, profile ``--steps``
    steady-state executions, and attribute them from both sources."""
    import jax

    from apex_tpu import observability as obs
    from apex_tpu.observability import attribution as A

    mod = _load_resilient_module()
    t = mod.build_training(accum=args.accum, wire=args.wire)
    state, batch_fn = t["state"], t["batch_fn"]
    compute_grads, apply_update = t["compute_grads"], t["apply_update"]

    # -- source (a): the compiled cost model (AOT texts of BOTH
    # programs the step dispatches) --------------------------------------
    batch = batch_fn(0)
    grads_args = (state["params"], state["scaler"], batch)
    hlo_grads = compute_grads.lower(*grads_args).compile().as_text()
    loss, scaled = compute_grads(*grads_args)
    hlo_update = apply_update.lower(
        scaled, state, loss
    ).compile().as_text()
    cost = A.attribute_cost_model([hlo_grads, hlo_update])
    if args.hlo_out:
        with open(args.hlo_out, "w") as f:
            f.write(hlo_grads)
            f.write("\n")
            f.write(hlo_update)

    # -- measured run: warmup outside the trace, then K metered steps ----
    # ONE peak/FLOP numerator (the cost model counts one device's
    # program; each chip executes it) but TWO independent clocks: the
    # meter times steps with host perf_counter ticks, the roofline
    # divides by the profiler window's span — MFU agreement is then a
    # real cross-check that the trace covers the same milliseconds the
    # wall clock paid, not an algebraic identity.
    meter = obs.StepMeter(
        tokens_per_step=t["rows"], flops_per_step=cost.total_flops,
        peak_flops=cost.peak_flops,
    )
    state, _ = apply_update(scaled, state, loss)  # warmup apply too

    def one_step(state, step):
        loss, scaled = compute_grads(
            state["params"], state["scaler"], batch_fn(step)
        )
        new_state, verdict = apply_update(scaled, state, loss)
        float(loss)  # device->host sync: the honest step boundary
        return new_state

    trace_dir = args.trace_dir or tempfile.mkdtemp(prefix="step_profile_")
    meter.tick()  # arm the clock
    with jax.profiler.trace(trace_dir):
        for step in range(args.steps):
            state = one_step(state, step)
            meter.tick()

    trace = A.load_trace_dir(trace_dir)
    measured = A.attribute_trace(
        trace, hlo_map=cost.bucket_map(),
        cost_weights=cost.bucket_fractions(),
    )
    # the trace's own per-step clock (median same-op period): the
    # independent measurement the MFU cross-check compares against the
    # meter's host perf_counter ticks
    trace_step_s = A.trace_step_period(trace, hlo_map=cost.bucket_map())
    return cost, measured, meter, trace_dir, trace_step_s


def profile_hlo(args):
    """Cost-model-only attribution of an optimized-HLO dump (e.g.
    ``bench.py --hlo-out``): exact FLOPs/bytes and estimated shares,
    no measured time and no host view."""
    from apex_tpu.observability import attribution as A

    texts = []
    for path in args.hlo:
        with open(path) as f:
            texts.append(f.read())
    return A.attribute_cost_model(texts), None, None, None


def main():
    ap = argparse.ArgumentParser(
        description="step-time attribution + roofline "
        "(docs/observability.md)"
    )
    ap.add_argument("--target", choices=["resilient"], default=None)
    ap.add_argument("--hlo", nargs="+", metavar="FILE", default=None,
                    help="attribute optimized-HLO dump(s) instead of "
                    "profiling a target (cost model only)")
    ap.add_argument("--steps", type=int, default=8,
                    help="steady-state steps to profile (default 8)")
    ap.add_argument("--accum", type=int, default=1)
    ap.add_argument("--wire", default="f32",
                    choices=["f32", "bf16", "int8"])
    ap.add_argument("--trace-dir", default=None,
                    help="keep the profile here (default: a temp dir)")
    ap.add_argument("--hlo-out", metavar="FILE", default=None,
                    help="also write the compiled step's HLO text")
    ap.add_argument("--json", metavar="FILE", default=None,
                    help="write the full report as one JSON object")
    ap.add_argument("--metrics-out", metavar="FILE", default=None,
                    help="append the attribution fractions as "
                    "bench-schema JSONL (the observability sink)")
    args = ap.parse_args()
    if bool(args.target) == bool(args.hlo):
        ap.error("exactly one of --target / --hlo is required")

    from apex_tpu import observability as obs
    from apex_tpu.observability import attribution as A

    if args.target:
        cost, measured, meter, trace_dir, trace_step_s = \
            profile_resilient(args)
    else:
        cost, measured, meter, trace_dir = profile_hlo(args)
        trace_step_s = 0.0

    src = measured if measured is not None else cost
    fractions = src.fractions()
    frac_sum = sum(fractions.values())
    print(
        "step fractions (%s): compute=%.3f collective=%.3f "
        "host_stall=%.3f  (sum=%.3f)"
        % (
            measured.source if measured is not None else "cost model",
            fractions["compute"], fractions["collective"],
            fractions["host_stall"], frac_sum,
        )
    )
    cost_fr = cost.fractions()
    if measured is not None:
        print(
            "cost-model cross-check: collective=%.3f (measured %.3f); "
            "host stall is invisible to the compiled program"
            % (cost_fr["collective"], fractions["collective"])
        )

    # roofline step time = the meter's: ONE denominator by design (the
    # satellite contract — StepMeter MFU, bench headlines, and the
    # roofline must never tell contradictory utilization stories), so
    # the MFU agreement below is a consistency PIN: it fails only if a
    # second denominator sneaks back in (a diverging peak table, a
    # different FLOP model), which is exactly the drift it guards.
    step_time = meter.step_time if meter is not None else cost.est_step_time
    rows = A.roofline_report(
        cost, step_time_s=step_time, measured=measured
    )
    print()
    print(A.render_roofline(rows))
    roofline_mfu = rows[-1].pct_peak
    meter_mfu = meter.mfu if meter is not None else roofline_mfu
    agreement = (
        abs(roofline_mfu - meter_mfu) / meter_mfu if meter_mfu > 0 else 0.0
    )
    print(
        "\nMFU: roofline=%.4f meter=%.4f (delta %.2f%%; one "
        "denominator by design: observability.meter)"
        % (roofline_mfu, meter_mfu, 100 * agreement)
    )
    # the genuinely independent comparison, as a diagnostic: the
    # trace's own per-step clock (median same-op period) vs the host
    # ticks.  Large skew is NOT an error — an async runtime batching
    # executions behind a host-bound loop produces exactly this, and
    # the host_stall fraction above already quantifies it.
    if trace_step_s > 0 and meter is not None and meter.step_time > 0:
        skew = abs(trace_step_s - meter.step_time) / meter.step_time
        print(
            "clock skew: trace step %.3f ms vs host step %.3f ms "
            "(%.1f%% — execution pacing vs dispatch pacing)"
            % (trace_step_s * 1e3, meter.step_time * 1e3, 100 * skew)
        )

    # publish -> board (the watchdog rules' source) + optional JSONL
    reporter = None
    if args.metrics_out:
        reporter = obs.Reporter([obs.JSONLSink(args.metrics_out)])
    A.publish_attribution(src, reporter=reporter, step=0)
    if reporter is not None:
        reporter.close()

    # judge the fractions the way a live run would
    wd = obs.Watchdog(
        rules=[obs.CollectiveFractionRule(), obs.HostStallRule()],
        attribution=src, check_every=1,
    )
    events = wd.check(0)
    for ev in events:
        print(f"[health/{ev.severity}] {ev.rule}: {ev.message}")
    if not events:
        print("watchdog: collective/host-stall fractions within floors")

    if args.json:
        payload = {
            "target": args.target or "hlo",
            "source": measured.source if measured is not None else "cost-model",
            "fractions": fractions,
            "fraction_sum": frac_sum,
            "cost_fractions": cost_fr,
            "bucket_fractions": src.bucket_fractions(),
            "cost_buckets": cost.buckets,
            "step_time_ms": step_time * 1e3,
            "trace_step_ms": trace_step_s * 1e3,
            "roofline": [r._asdict() for r in rows],
            "mfu": {"roofline": roofline_mfu, "meter": meter_mfu,
                    "agreement": agreement},
            "health_events": [ev._asdict() for ev in events],
            "trace_dir": trace_dir,
        }
        with open(args.json, "w") as f:
            json.dump(payload, f, indent=2)
            f.write("\n")
        print(f"[step_profile] wrote {args.json}", file=sys.stderr)
    return 0


if __name__ == "__main__":
    sys.exit(main())
