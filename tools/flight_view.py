"""Render a flight-recorder dump into a human postmortem timeline.

Usage: python tools/flight_view.py flight_<ts>.json [--json] [-n 16]

A ``FlightRecorder`` dump (``apex_tpu.observability.flight``,
``docs/observability.md``) holds the last N steps' telemetry frames,
the event log (rollbacks, resumes, retries, preemption, health
events), the final drained metric values, and the goodput ledger.
This tool turns that JSON into the first five minutes of an incident
review:

- the header: what killed the run, when, on which host;
- the merged timeline: frames and events interleaved by ``seq``, skips
  and replay passes marked;
- the last frame's metric table next to the FINAL drained values — the
  guard/scaler state at death;
- the goodput ledger (exact skip/rollback/retry counts).

``--json`` prints a one-line machine summary instead (reason + frame/
event/skip/rollback counts) — what ``tools/verify_tier1.sh``'s FLIGHT
pass consumes.  ``--timeline OUT`` emits the dump as Chrome-trace-event
JSON (frames as ``train/step`` spans, events as instants, frame metrics
as counter tracks) so a crash postmortem opens in the SAME Perfetto
viewer as live span traces (``tools/timeline.py``,
``docs/observability.md``).  Exit status: 0 on a parseable dump, 2
otherwise.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def _num(value):
    """Undo the dump's non-finite encoding ("NaN"/"Infinity"/...)."""
    if value == "NaN":
        return float("nan")
    if value == "Infinity":
        return float("inf")
    if value == "-Infinity":
        return float("-inf")
    return value


def _fmt(value) -> str:
    value = _num(value)
    if isinstance(value, bool):
        return str(value)
    if isinstance(value, float):
        return f"{value:.6g}"
    return str(value)


def load_dump(path: str) -> dict:
    with open(path) as f:
        data = json.load(f)
    for key in ("version", "reason", "frames", "events"):
        if key not in data:
            raise ValueError(f"not a flight dump: missing {key!r} key")
    return data


def summarize(data: dict) -> dict:
    """Machine summary: the counts the CI gate cross-checks against the
    JSONL goodput line."""
    frames = data["frames"]
    events = data["events"]
    out = {
        "reason": data["reason"],
        "frames": len(frames),
        "events": len(events),
        "frame_skips": sum(1 for f in frames if f.get("skipped")),
        "rollbacks": sum(1 for e in events if e["kind"] == "rollback"),
        "retries": sum(1 for e in events if e["kind"] == "retry"),
        "health_events": sum(1 for e in events if e["kind"] == "health"),
        "preempted": any(e["kind"] == "preempt" for e in events),
    }
    goodput = data.get("goodput")
    if goodput:
        out["goodput"] = goodput
    return out


def render(data: dict, last_frames: int = 16) -> None:
    host = data.get("host", {})
    when = data.get("wall_time")
    when_s = (
        time.strftime("%Y-%m-%d %H:%M:%S", time.localtime(when))
        if isinstance(when, (int, float)) else "?"
    )
    print(f"flight recorder postmortem — {when_s}")
    print(f"  reason : {data['reason']}")
    print(f"  host   : {host.get('id', '?')}/{host.get('count', '?')}"
          f"  capacity: {data.get('capacity', '?')}")
    run = data.get("run") or {}
    if run:
        print("  run    : " + ", ".join(f"{k}={v}" for k, v in run.items()))

    goodput = data.get("goodput")
    if goodput:
        print(
            "  goodput: {goodput:.3f} (accepted={accepted} "
            "skipped={skipped} discarded={discarded} "
            "rollbacks={rollbacks} retries={retries} "
            "resumes={resumes}{p})".format(
                p=", PREEMPTED" if goodput.get("preempted") else "",
                **{k: goodput.get(k, 0) for k in (
                    "goodput", "accepted", "skipped", "discarded",
                    "rollbacks", "retries", "resumes")},
            )
        )

    # merged timeline, frames + events ordered by seq
    frames = [dict(f, _what="frame") for f in data["frames"]]
    events = [dict(e, _what="event") for e in data["events"]]
    timeline = sorted(frames + events, key=lambda r: r.get("seq", 0))
    if last_frames and len(timeline) > last_frames:
        dropped = len(timeline) - last_frames
        timeline = timeline[-last_frames:]
        print(f"\ntimeline (last {last_frames}; {dropped} earlier "
              "entries in the dump):")
    else:
        print("\ntimeline:")
    t0 = timeline[0].get("t") if timeline else None
    for row in timeline:
        dt = ""
        if isinstance(row.get("t"), (int, float)) and isinstance(
            t0, (int, float)
        ):
            dt = f"+{row['t'] - t0:7.2f}s"
        if row["_what"] == "frame":
            marks = []
            if row.get("skipped"):
                marks.append("SKIPPED")
            if row.get("replay"):
                marks.append("replay")
            extra = f"  [{', '.join(marks)}]" if marks else ""
            stale = ""
            if row.get("fetched_step") is not None:
                stale = f"  (metrics@{row['fetched_step']})"
            print(f"  {dt:>10}  step {row.get('step', '?'):>6}"
                  f"{extra}{stale}")
        else:
            desc = ", ".join(
                f"{k}={_fmt(v)}" for k, v in row.items()
                if k not in ("_what", "seq", "t", "kind") and v is not None
            )
            print(f"  {dt:>10}  ** {row['kind'].upper()}  {desc}")

    # the state at death: last frame's (possibly stale) metrics next to
    # the final drained values
    final = data.get("final") or {}
    last_metrics = {}
    for f in reversed(data["frames"]):
        if f.get("metrics"):
            last_metrics = f["metrics"]
            break
    final_metrics = final.get("metrics") or {}
    names = sorted(set(last_metrics) | set(final_metrics))
    if names:
        print(f"\nstate at death (final = drained at dump; "
              f"last-frame fetch@{final.get('fetched_step', '?')}):")
        width = max(len(n) for n in names)
        print(f"  {'metric':<{width}}  {'last frame':>14}  {'final':>14}")
        for name in names:
            lv = _fmt(last_metrics.get(name, ""))
            fv = _fmt(final_metrics.get(name, ""))
            flag = "  <-- " if lv != fv else ""
            print(f"  {name:<{width}}  {lv:>14}  {fv:>14}{flag}")
    meter = final.get("meter")
    if meter:
        print("\nmeter at death: " + "  ".join(
            f"{k.split('/')[-1]}={_fmt(v)}" for k, v in meter.items()
        ))
    board = data.get("board") or {}
    health_keys = {k: v for k, v in board.items()
                   if k.startswith(("health/", "fleet/"))}
    if health_keys:
        print("\nhealth/fleet board:")
        for k in sorted(health_keys):
            print(f"  {k} = {_fmt(health_keys[k])}")


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="render a FlightRecorder dump as a postmortem"
    )
    ap.add_argument("dump", help="flight_<ts>.json path")
    ap.add_argument("-n", type=int, default=16,
                    help="timeline entries to show (default 16)")
    ap.add_argument("--json", action="store_true",
                    help="print a one-line machine summary instead")
    ap.add_argument("--timeline", metavar="OUT", default=None,
                    help="write the dump as Chrome-trace-event JSON "
                    "(Perfetto-viewable, same format as tools/timeline.py)")
    args = ap.parse_args(argv)
    try:
        data = load_dump(args.dump)
    except (OSError, ValueError, json.JSONDecodeError) as e:
        print(f"flight_view: cannot read {args.dump}: {e}",
              file=sys.stderr)
        return 2
    if args.timeline:
        from apex_tpu.observability.export import (
            TimelineSink,
            flight_counters,
            flight_entries,
        )

        host = (data.get("host") or {}).get("id", 0)
        with TimelineSink(
            args.timeline,
            process_name=f"host{host} flight ({args.dump})",
            other_data={"reason": data.get("reason"),
                        "anchor": data.get("anchor")},
        ) as sink:
            n = sink.add_spans(flight_entries(data), anchor=None)
            for name, t, v in flight_counters(data):
                sink.counter(name, t, v)
                n += 1
        print(f"[flight_view] wrote {args.timeline} ({n} events)",
              file=sys.stderr)
    if args.json:
        print(json.dumps(summarize(data)))
    else:
        render(data, last_frames=args.n)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
