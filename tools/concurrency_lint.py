"""Concurrency & replay-purity lint CLI — the host-side static half of
the concurrency story (docs/analysis.md "Concurrency & replay-purity
passes").

Runs two AST passes over the ``apex_tpu`` package source — no jax, no
imports of the code under analysis:

- ``apex_tpu.analysis.concurrency`` — lock-discipline lint: per-class
  maps of attributes mutated under ``with self._lock`` vs. outside,
  thread entrypoints (``threading.Thread(target=...)``, ``http.server``
  handler classes) + a lightweight call graph; an attribute reachable
  from both a thread body and the main path and written without the
  lock is ``race-unlocked-shared-state`` (or
  ``race-nonatomic-counter`` when every site is a read-modify-write);
  a lock held across a bounded-queue ``put``/``join``/``result()``
  whose consumer thread needs the same lock is
  ``race-lock-across-blocking``.
- ``apex_tpu.analysis.purity`` — replay-purity lint over the declared
  replay-critical modules (``purity.REPLAY_CRITICAL``): wall-clock
  reads, unseeded RNG, iteration over sets feeding scheduling, env
  reads outside construction (``replay-*`` rules).

Waiver syntax (same line as the finding, reason REQUIRED by review)::

    t = time.time()  # lint: allow(replay-wall-clock): display only

This is the ``verify_tier1.sh`` LINT gate's concurrency half, and
``bench.py --lint`` pins its ERROR count at 0 in the golden file.

Usage::

    python tools/concurrency_lint.py                 # table
    python tools/concurrency_lint.py --json out.json # machine artifact
    python tools/concurrency_lint.py --root PKG_DIR  # lint another tree

Exit code: 0 clean, 1 findings at/above ``--fail-on`` (default:
error), 2 usage error.

The passes and the rule catalog (``findings.py``) are stdlib-only;
this tool loads them standalone under their real dotted names so the
lint runs on a box with no jax installed (CI lint stage, pre-commit).
"""

from __future__ import annotations

import argparse
import importlib.util
import json
import os
import sys
import types

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
_ANALYSIS = os.path.join(_REPO, "apex_tpu", "analysis")


def _load_analysis_modules():
    """The analysis trio (findings → purity → concurrency) under their
    full dotted names WITHOUT importing ``apex_tpu`` (whose __init__
    pulls jax).  Stub package modules hold the namespace; the leaf
    modules are the real files, so the lazy
    ``from apex_tpu.analysis.findings import make_finding`` inside the
    passes resolves against exactly what we loaded."""
    if "apex_tpu" not in sys.modules:
        for pkg in ("apex_tpu", "apex_tpu.analysis"):
            mod = types.ModuleType(pkg)
            mod.__path__ = []  # mark as package
            sys.modules[pkg] = mod
    loaded = {}
    for name in ("findings", "purity", "concurrency"):
        dotted = f"apex_tpu.analysis.{name}"
        if dotted in sys.modules:
            loaded[name] = sys.modules[dotted]
            continue
        spec = importlib.util.spec_from_file_location(
            dotted, os.path.join(_ANALYSIS, f"{name}.py")
        )
        mod = importlib.util.module_from_spec(spec)
        sys.modules[dotted] = mod
        spec.loader.exec_module(mod)
        setattr(sys.modules["apex_tpu.analysis"], name, mod)
        loaded[name] = mod
    return loaded["findings"], loaded["purity"], loaded["concurrency"]


def main():
    ap = argparse.ArgumentParser(
        description="host-side concurrency + replay-purity static lint "
        "(rule catalog: docs/analysis.md)"
    )
    ap.add_argument("--root", default=None, metavar="DIR",
                    help="package directory to lint (default: the "
                    "repo's apex_tpu/)")
    ap.add_argument("--json", metavar="FILE", default=None,
                    help="write the report as one JSON object")
    ap.add_argument("--fail-on", choices=["error", "warning"],
                    default="error")
    args = ap.parse_args()

    findings_mod, purity, concurrency = _load_analysis_modules()

    root = args.root or os.path.join(_REPO, "apex_tpu")
    sources = purity.collect_sources(root)
    found = []
    found.extend(concurrency.lint_sources(sources))
    found.extend(purity.lint_sources(sources))
    found.sort(key=lambda f: (f.path, f.rule))

    report = findings_mod.Report(
        target=os.path.basename(os.path.normpath(root)),
        findings=found,
        rules_run=("concurrency", "purity"),
    )
    report.sections["files_scanned"] = len(sources)

    print(report.render())
    if args.json:
        with open(args.json, "w") as f:
            json.dump(report.to_json(), f, indent=2, sort_keys=True)
            f.write("\n")
        print(f"report: {args.json}")

    return 0 if report.ok(fail_on=args.fail_on) else 1


if __name__ == "__main__":
    sys.exit(main())
